"""Deadline-aware verify lanes + speculative quorum commit (ISSUE 12).

The lane split and the speculative route reorder attack commit p50, and
both are only admissible if they change WHEN work happens, never what is
committed:

1. randomized parity: the threaded lane-split engine with
   ``speculative_commit`` ON produces byte-identical PER-TX commit
   certificates, the same committed set, the same application state and
   the same residual vote-set stakes as the scalar ``try_add_vote``
   golden path — across linger flushes, partial priority buckets and a
   mid-stream validator-power restage. Only the cross-tx commit ORDER
   may differ (that is the optimization), so app.digest is NOT compared;
2. speculative spans drain: every ``spec_commit`` span opened at the
   quorum decision is closed by the end of its route pass — zero open
   spans after stop();
3. unit coverage for the new moving parts: the expired-deadline
   wait_budget fix, priority-lane bucket targets, the
   AdaptiveLingerController steering loop and its engine wiring, the
   per-lane pool pending estimates, critical-path lane/spec
   attribution, the latency-bank supersede contract, and the
   lane-linger latency model in tools/sim_device.py.
"""

import hashlib
import time

import pytest

from test_pipeline import (
    _mixed_stream,
    make_engine,
    make_pvs,
    sign_vote,
)
from txflow_tpu.engine.adaptive import AdaptiveLingerController
from txflow_tpu.engine.txflow import _BatchCoalescer
from txflow_tpu.pool.mempool import LANE_BULK, LANE_PRIORITY
from txflow_tpu.trace import Tracer
from txflow_tpu.trace.report import (
    critical_path,
    format_line,
    merge_critical_paths,
)
from txflow_tpu.trace.tracer import SPAN_E2E
from txflow_tpu.types import Validator, ValidatorSet
from txflow_tpu.utils.config import TraceConfig
from txflow_tpu.utils.metrics import Registry
from txflow_tpu.verifier import ScalarVoteVerifier


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def _key(tx: bytes) -> bytes:
    return hashlib.sha256(tx).digest()


def _hash(tx: bytes) -> str:
    return hashlib.sha256(tx).hexdigest().upper()


def _wait_quiescent_lanes(flow, votepool, timeout=30.0):
    """Lane-aware quiescence: BOTH drain cursors caught up, no retries
    on either lane, commit queue drained — stable across checks."""
    deadline = time.monotonic() + timeout
    stable = 0
    while time.monotonic() < deadline:
        idle = (
            flow._drain_cursor >= votepool.seq()
            and flow._prio_drain_cursor >= votepool.prio_seq()
            and not flow._retry
            and not flow._retry_prio
            and flow.commits_drained()
        )
        stable = stable + 1 if idle else 0
        if stable >= 3:
            return True
        time.sleep(0.02)
    return False


# ---- parity: lanes + speculation never change commit content ----------


@pytest.mark.parametrize("seed", [7, 31])
def test_lane_split_speculative_matches_scalar_golden(seed):
    """Per-tx certificates from the lane-split speculative engine are
    BYTE-identical to the scalar reference; committed set, app state and
    residual stakes match. Commit ORDER may differ (priority txs jump
    the queue), so app.digest is deliberately not compared."""
    pvs, vals = make_pvs(7)  # total 70, quorum 47 -> 5 votes needed
    txs = [b"lane%d-%d=%d" % (seed, i, i) for i in range(16)]
    prio_keys = {_key(tx) for tx in txs[::3]}
    stream = _mixed_stream(pvs, txs, seed)
    half = len(stream) // 2
    # same membership, re-weighted powers: a mid-stream epoch restage
    vals2 = ValidatorSet(
        [
            Validator.from_pub_key(pv.get_pub_key(), 10 + (i % 3))
            for i, pv in enumerate(pvs)
        ]
    )

    # scalar golden path: one vote at a time, restage at the half mark
    flow_s, mem_s, _, store_s, app_s = make_engine(vals, use_device=False)
    for tx in txs:
        mem_s.check_tx(tx)
    for v in stream[:half]:
        flow_s.try_add_vote(v.copy())
    flow_s.update_state(flow_s.height, vals2)
    for v in stream[half:]:
        flow_s.try_add_vote(v.copy())

    # lane-split speculative engine: same stream via the pool, threaded,
    # small buckets so the priority lane flushes partials on its linger
    verifier = ScalarVoteVerifier(vals)
    verifier.buckets = (8, 32)  # coalescer + lane activate off these
    flow_p, mem_p, pool_p, store_p, app_p = make_engine(
        vals,
        use_device=False,
        verifier=verifier,
        max_batch=32,
        min_batch=1,
        pipeline_depth=3,
        coalesce=True,
        coalesce_linger=0.02,
        lane_split=True,
        priority_linger=0.002,
        priority_bucket_cap=8,
        speculative_commit=True,
    )
    pool_p.lane_of_vote = (
        lambda v: LANE_PRIORITY if v.tx_key in prio_keys else LANE_BULK
    )
    flow_p.tracer = Tracer(TraceConfig(sample_rate=1))
    for tx in txs:
        mem_p.check_tx(tx)
    flow_p.start()
    try:
        for v in stream[:half]:
            try:
                pool_p.check_tx(v)
            except Exception:
                pass  # cache dup etc. — the scalar path saw the vote anyway
        assert _wait_quiescent_lanes(flow_p, pool_p), "first half never drained"
        flow_p.update_state(flow_p.height, vals2)
        for v in stream[half:]:
            try:
                pool_p.check_tx(v)
            except Exception:
                pass
        assert _wait_quiescent_lanes(flow_p, pool_p), "second half never drained"
    finally:
        flow_p.stop()

    assert app_p.tx_count == app_s.tx_count
    assert app_p.state == app_s.state
    for tx in txs:
        cs = store_s.load_tx_commit(_hash(tx))
        cp = store_p.load_tx_commit(_hash(tx))
        assert (cs is None) == (cp is None)
        if cs is not None:
            # byte-identical certificates: same validators, same
            # signatures, same within-tx order
            assert [
                (c.validator_address, c.signature) for c in cs.commits
            ] == [(c.validator_address, c.signature) for c in cp.commits]
    # residual stakes: the scalar path creates a vote_set even when the
    # only vote then fails verification (stake 0), the batched path only
    # for verified votes — so golden is a superset; every set holding
    # stake must exist on both sides with the same stake
    assert set(flow_p.vote_sets) <= set(flow_s.vote_sets)
    for tx_hash, vs in flow_s.vote_sets.items():
        if vs.stake() > 0:
            assert flow_p.vote_sets[tx_hash].stake() == vs.stake()
    for tx_hash, vs in flow_p.vote_sets.items():
        assert vs.stake() == flow_s.vote_sets[tx_hash].stake()

    stats = flow_p.pipeline_stats()
    assert stats["lanes"]["enabled"] is True
    assert stats["lanes"]["prio_batches"] > 0
    assert stats["lanes"]["prio_votes"] > 0
    assert stats["spec"]["enabled"] is True
    assert stats["spec"]["saved_s"] >= 0.0
    # drain-on-stop: every begun span (device AND spec_commit) closed
    assert flow_p.tracer.open_count() == 0


def test_speculative_reorder_counts_and_spans_close():
    """A batch holding one quorate tx and one sub-quorum tx triggers the
    speculative first pass deterministically: the quorate tx commits in
    the spec half, the spec counter advances, the spec_commit span is
    recorded and closed, and the certificate matches the scalar path."""
    pvs, vals = make_pvs(4)  # total 40, quorum 27 -> 3 votes needed
    tx_a, tx_b = b"spec-a=1", b"spec-b=1"
    votes = [
        sign_vote(pvs[0], tx_a),
        sign_vote(pvs[1], tx_b),  # interleaved: reorder is observable
        sign_vote(pvs[1], tx_a),
        sign_vote(pvs[2], tx_a),
    ]

    flow_s, mem_s, _, store_s, _ = make_engine(vals, use_device=False)
    for tx in (tx_a, tx_b):
        mem_s.check_tx(tx)
    for v in votes:
        flow_s.try_add_vote(v.copy())

    flow, mem, pool, store, app = make_engine(
        vals,
        use_device=False,
        min_batch=1,
        max_batch=8,
        coalesce=False,
        speculative_commit=True,
    )
    flow.tracer = Tracer(TraceConfig(sample_rate=1), registry=Registry())
    for tx in (tx_a, tx_b):
        mem.check_tx(tx)
    for v in votes:
        pool.check_tx(v)
    flow.step()

    assert app.tx_count == 1  # tx_a quorate, tx_b one vote short
    assert flow._spec_commits == 1
    stats = flow.pipeline_stats()
    assert stats["spec"] == {
        "enabled": True,
        "commits": 1,
        "saved_s": stats["spec"]["saved_s"],
    }
    assert stats["spec"]["saved_s"] >= 0.0
    cert_s = store_s.load_tx_commit(_hash(tx_a))
    cert_p = store.load_tx_commit(_hash(tx_a))
    assert [(c.validator_address, c.signature) for c in cert_s.commits] == [
        (c.validator_address, c.signature) for c in cert_p.commits
    ]
    # the decision-to-route-end window was traced and fully closed
    fams = flow.tracer.digest()["latency_ms"]
    assert "spec_commit" in fams
    assert flow.tracer.open_count() == 0


# ---- unit: coalescer wait budget + priority-lane construction ---------


def test_wait_budget_expired_deadline_is_zero():
    """An expired linger deadline means the flush is due NOW: the wait
    budget must be 0.0, not the old 0.5 ms floor that held every late
    flush for one extra poll."""
    clk = FakeClock()
    co = _BatchCoalescer((8,), cap=64, min_batch=1, linger=0.5, clock=clk)
    assert co.decide(3) == 0  # arms the deadline at t+0.5
    assert 0.0 < co.wait_budget(0.2, 0.0) <= 0.2
    clk.t += 0.6  # deadline passed
    assert co.wait_budget(0.2, 0.0) == 0.0
    assert co.wait_budget(0.2, 0.05) == 0.0
    # un-armed coalescer: the full poll budget survives
    co2 = _BatchCoalescer((8,), cap=64, min_batch=1, linger=0.5, clock=clk)
    assert co2.wait_budget(0.2, 0.05) == 0.2


def test_prio_lane_targets_capped_and_shard_divisible():
    """The priority lane keeps only bucket targets within its cap, with
    min_batch pinned at 1 so a single urgent vote can dispatch, and is
    built even for a plain scalar verifier (no ladder: cap-sized
    degrade) — the lane is about preemption, not shapes."""
    pvs, vals = make_pvs(4)
    verifier = ScalarVoteVerifier(vals)
    verifier.buckets = (8, 32, 128)
    flow, *_ = make_engine(
        vals,
        use_device=False,
        verifier=verifier,
        coalesce=True,
        lane_split=True,
        priority_bucket_cap=16,
        priority_linger=0.003,
    )
    flow.start()
    try:
        pl = flow._prio_lane
        assert pl is not None
        assert pl.targets == [8]  # 32/128 exceed the 16-vote cap
        assert pl.linger == 0.003
        assert flow._coalescer is not None  # bulk lane rides the ladder
        stats = flow.pipeline_stats()
        assert stats["lanes"]["enabled"] is True
        assert stats["lanes"]["prio_linger_ms"] == 3.0
    finally:
        flow.stop()

    # no bucket ladder: the bulk coalescer stays off, the lane persists
    flow2, *_ = make_engine(
        vals, use_device=False, coalesce=True, lane_split=True,
        priority_bucket_cap=16,
    )
    flow2.start()
    try:
        assert flow2._coalescer is None
        assert flow2._prio_lane is not None
        assert flow2._prio_lane.targets == [16]  # cap-sized degrade
    finally:
        flow2.stop()


# ---- unit: adaptive linger controller + engine wiring -----------------


def test_adaptive_linger_controller_steering():
    c = AdaptiveLingerController(
        slo_budget_ms=50.0,
        prio_linger=0.002,
        bulk_linger=0.008,
        min_linger=0.0005,
    )
    # over budget: priority halves, bulk shrinks softer ((0.5+1)/2)
    assert c.observe(80.0) is True
    assert c.prio_linger == pytest.approx(0.001)
    assert c.bulk_linger == pytest.approx(0.006)
    # sustained pressure floors at min_linger, then stops changing
    for _ in range(12):
        c.observe(80.0)
    assert c.prio_linger == pytest.approx(0.0005)
    assert c.bulk_linger >= 0.0005
    assert c.observe(80.0) is False  # floored on both lanes: no change
    # headroom (p50 under half budget): relax back to targets, never past
    for _ in range(50):
        c.observe(10.0)
    assert c.prio_linger == pytest.approx(0.002)
    assert c.bulk_linger == pytest.approx(0.008)
    # dead zone between budget/2 and budget: hold
    assert c.observe(30.0) is False


def test_adaptive_linger_cadence_gate_and_no_data_hold():
    c = AdaptiveLingerController(interval=0.25)
    # no sampled commits yet: hold (but the cadence window is consumed)
    assert c.maybe_observe(lambda: {"latency_ms": {}}, now=100.0) is False
    calls = []

    def dig():
        calls.append(1)
        return {"latency_ms": {"e2e": {"p50": 500.0}}}

    # inside the interval: gated, the digest is not even pulled
    assert c.maybe_observe(dig, now=100.1) is False
    assert not calls
    # due: pulls once and steers (500 ms >> 50 ms default budget)
    assert c.maybe_observe(dig, now=100.4) is True
    assert len(calls) == 1
    st = c.stats()
    assert st["adjustments"] == 1
    assert st["last_p50_ms"] == 500.0
    # a digest fault holds rather than raising into the engine loop
    def boom():
        raise RuntimeError("digest fault")

    assert c.maybe_observe(boom, now=101.0) is False


def test_adaptive_linger_engine_pushes_into_live_lane():
    """The serial run loop steers the LIVE lane coalescers from the
    trace digest: an over-budget e2e p50 shrinks the priority linger in
    the running engine."""
    pvs, vals = make_pvs(4)
    flow, *_ = make_engine(
        vals,
        use_device=False,
        coalesce=False,
        pipeline_depth=1,  # serial loop steers every iteration
        lane_split=True,
        adaptive_linger=True,
        slo_budget_ms=10.0,
        priority_linger=0.004,
    )
    flow.tracer = Tracer(TraceConfig(sample_rate=1), registry=Registry())
    # synthetic 50 ms commit: 5x over the 10 ms budget
    flow.tracer.span(_hash(b"slow-tx"), SPAN_E2E, 100.0, 100.05)
    flow.start()
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if flow._linger_ctrl.adjustments >= 1:
                break
            time.sleep(0.01)
    finally:
        flow.stop()
    ctrl = flow._linger_ctrl
    assert ctrl is not None and ctrl.adjustments >= 1
    assert flow._prio_lane.linger == ctrl.prio_linger < 0.004
    stats = flow.pipeline_stats()
    assert stats["adaptive_linger"]["adjustments"] >= 1
    assert stats["lanes"]["prio_linger_ms"] < 4.0


# ---- unit: per-lane pending estimates + lane-targeted step ------------


def test_lane_pending_estimates_and_lane_step():
    pvs, vals = make_pvs(4)
    flow, mem, pool, store, app = make_engine(vals, use_device=False)
    tx_p, tx_b = b"lane-p=1", b"lane-b=1"
    prio_key = _key(tx_p)
    pool.lane_of_vote = (
        lambda v: LANE_PRIORITY if v.tx_key == prio_key else LANE_BULK
    )
    mem.check_tx(tx_p)
    mem.check_tx(tx_b)
    for pv in pvs[:2]:  # 20 stake: one vote short of quorum (27)
        pool.check_tx(sign_vote(pv, tx_p))
    for pv in pvs[:3]:  # quorate
        pool.check_tx(sign_vote(pv, tx_b))
    assert flow._prio_pending() == 2
    # _bulk_pending subtracts the priority backlog only in lane-split
    # mode (the lane coalescer exists); mimic a started lane engine
    flow._prio_lane = _BatchCoalescer((8,), cap=8, min_batch=1, linger=0.001)
    assert flow._bulk_pending() == 3

    # draining the priority lane empties the priority estimate; the bulk
    # estimate transiently OVER-counts (the main-log walk has not passed
    # the drained priority entries yet) — safe for a coalescer, and it
    # self-corrects as the bulk cursor advances below
    got = flow.step(limit=8, lane="prio")
    assert got == 2
    assert flow._prio_pending() == 0
    assert flow._bulk_pending() == 5
    stats = flow.pipeline_stats()
    assert stats["lanes"]["prio_batches"] == 1
    assert stats["lanes"]["prio_votes"] == 2

    # the bulk walk skips the priority entries it would double-deliver
    got = flow.step(limit=8, lane="bulk")
    assert got == 3
    assert flow._bulk_pending() == 0
    assert app.tx_count == 1
    assert store.load_tx_commit(_hash(tx_b)) is not None
    assert store.load_tx_commit(_hash(tx_p)) is None
    assert flow.vote_sets[_hash(tx_p)].stake() == 20


# ---- unit: critical-path lane/spec attribution ------------------------


def test_critical_path_lane_and_spec_attribution():
    stats = {
        "prep_s": 2.0,
        "route_s": 1.0,
        "dispatch_wait_s": 3.0,
        "lock_wait_s": 0.5,
        "spec": {"enabled": True, "commits": 4, "saved_s": 0.25},
    }
    digest = {
        "latency_ms": {
            "linger_prio": {"sum_ms": 200.0, "p50": 1.0},
            "linger_bulk": {"sum_ms": 800.0, "p50": 4.0},
            "e2e": {"p50": 40.0},
        }
    }
    cp = critical_path(stats, digest)
    assert cp["linger_s"] == pytest.approx(1.0)  # per-lane families sum
    assert cp["linger_prio_s"] == pytest.approx(0.2)
    assert cp["linger_bulk_s"] == pytest.approx(0.8)
    assert cp["spec_saved_s"] == pytest.approx(0.25)
    assert cp["spec_commits"] == 4
    assert cp["bound"] == "device"  # 3.0 > host 2.5 > linger 1.0
    # e2e 40 minus the per-lane linger p50s (1 + 4): residual 35
    assert cp["network_residual_ms"] == pytest.approx(35.0)

    merged = merge_critical_paths([cp, cp])
    assert merged["linger_prio_s"] == pytest.approx(0.4)
    assert merged["linger_bulk_s"] == pytest.approx(1.6)
    assert merged["spec_saved_s"] == pytest.approx(0.5)
    assert merged["spec_commits"] == 8
    # busy fractions come from the four main components only: the
    # per-lane split must not double-count linger in the denominator
    assert sum(merged["fractions"].values()) == pytest.approx(1.0, abs=0.01)
    line = format_line(merged)
    assert "linger[prio=" in line
    assert "spec_saved=" in line

    # a pre-lane digest (merged "linger" family only) still attributes
    cp_legacy = critical_path(
        {"prep_s": 1.0, "route_s": 0.0, "dispatch_wait_s": 0.0},
        {"latency_ms": {"linger": {"sum_ms": 1500.0}}},
    )
    assert cp_legacy["linger_s"] == pytest.approx(1.5)
    assert "linger_prio_s" not in cp_legacy
    assert "spec_saved_s" not in cp_legacy


# ---- unit: latency-bank supersede contract ----------------------------


def test_latency_bank_supersede_contract(tmp_path, monkeypatch):
    import bench

    monkeypatch.setattr(bench, "_ARTIFACT_DIR", str(tmp_path))
    monkeypatch.setattr(
        bench, "_LATENCY_LATEST", str(tmp_path / "latency_latest.json")
    )
    clean = {
        "priority_p50_ms": 12.0,
        "priority_p99_ms": 30.0,
        "slo_breach": False,
    }
    dirty_breach = dict(clean, slo_breach=True)
    dirty_error = {"error": "timeout", "priority_p50_ms": 1.0,
                   "priority_p99_ms": 2.0}
    missing_lane = {"priority_p50_ms": 5.0}  # p99 absent
    assert bench._latency_clean(clean)
    assert not bench._latency_clean(dirty_breach)
    assert not bench._latency_clean(dirty_error)
    assert not bench._latency_clean(missing_lane)

    # a dirty run banks when nothing is banked yet (some data > none)
    bench._bank_latency_result(dirty_error)
    assert bench._load_banked_latency()["error"] == "timeout"
    # clean overwrites dirty, and is stamped
    bench._bank_latency_result(clean)
    banked = bench._load_banked_latency()
    assert banked["priority_p50_ms"] == 12.0
    assert "measured_at_unix" in banked
    # dirty never displaces clean — a regression cannot silently
    # replace the reference it regressed from
    bench._bank_latency_result(dirty_breach)
    assert bench._load_banked_latency()["priority_p50_ms"] == 12.0
    bench._bank_latency_result(dirty_error)
    assert bench._load_banked_latency()["priority_p50_ms"] == 12.0
    # a newer clean run supersedes the older clean one
    bench._bank_latency_result(dict(clean, priority_p50_ms=8.0))
    assert bench._load_banked_latency()["priority_p50_ms"] == 8.0


# ---- unit: lane-linger latency model (tools/sim_device.py) ------------


def _sim_device():
    import importlib.util
    import pathlib

    root = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "sim_device_for_tests", root / "tools" / "sim_device.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_lane_latency_model_monotonic_and_capped():
    m = _sim_device().lane_latency_model
    lingers = (0.00025, 0.001, 0.004, 0.016)
    rows = [m(800.0, l, 0.008, 27.6e-6, bucket_cap=512) for l in lingers]
    p50s = [r["p50_ms"] for r in rows]
    batches = [r["batch"] for r in rows]
    # at fixed arrival, a longer hold only adds latency (p50 strictly
    # rises) while buying batch occupancy (batch non-decreasing, capped)
    assert p50s == sorted(p50s) and p50s[0] < p50s[-1]
    assert batches == sorted(batches)
    assert all(r["batch"] <= 512 for r in rows)
    assert all(r["p99_ms"] >= r["p50_ms"] for r in rows)
    # saturation: once linger exceeds cap/arrival the hold stops growing
    sat_a = m(800.0, 10.0, 0.008, 27.6e-6, bucket_cap=512)
    sat_b = m(800.0, 20.0, 0.008, 27.6e-6, bucket_cap=512)
    sat_a.pop("linger_ms"), sat_b.pop("linger_ms")
    assert sat_a == sat_b
    # a mesh divides the per-slot bill: same linger, lower p50
    assert (
        m(800.0, 0.004, 0.008, 27.6e-6, mesh=4)["p50_ms"]
        < m(800.0, 0.004, 0.008, 27.6e-6, mesh=1)["p50_ms"]
    )
