"""VoteVerifier parity: device (and sharded-device) vs the scalar golden model.

Mirrors the reference's quorum tests (types/vote_set_test.go) at the batch
level, plus BASELINE config 4's adversarial mix: honest votes, corrupted
signatures, wrong-key signatures, off-range validator indices, and padding.
Commit decisions must be bit-identical across all three implementations.
"""

import hashlib

import numpy as np
import pytest

from txflow_tpu.crypto import ed25519 as host_ed
from txflow_tpu.parallel import make_mesh
from txflow_tpu.types import TxVote, Validator, ValidatorSet, canonical_sign_bytes
from txflow_tpu.verifier import (
    DeviceVoteVerifier,
    ScalarVoteVerifier,
    bucket_size,
)

CHAIN_ID = "txflow-test"


def make_valset(n, power=10):
    seeds = [hashlib.sha256(b"val%d" % i).digest() for i in range(n)]
    pubs = [host_ed.public_key_from_seed(s) for s in seeds]
    vals = ValidatorSet([Validator.from_pub_key(p, power) for p in pubs])
    # map validator order back to seeds (ValidatorSet sorts by address)
    seed_by_pub = dict(zip(pubs, seeds))
    return vals, [seed_by_pub[v.pub_key] for v in vals]


def make_batch(vals, seeds, n_txs, corrupt=()):
    """One vote per (tx, validator); corrupt[i] flavors in arrival order."""
    msgs, sigs, vidx, slot = [], [], [], []
    k = 0
    for t in range(n_txs):
        tx_hash = hashlib.sha256(b"tx%d" % t).hexdigest().upper()
        tx_key = hashlib.sha256(b"key%d" % t).digest()
        for vi in range(len(seeds)):
            msg = canonical_sign_bytes(CHAIN_ID, 1, tx_hash, 1700000000_000000000 + t)
            sig = host_ed.sign(seeds[vi], msg)
            mode = corrupt[k % len(corrupt)] if corrupt else "ok"
            if mode == "flip":
                sig = sig[:10] + bytes([sig[10] ^ 1]) + sig[11:]
            elif mode == "wrongkey":
                sig = host_ed.sign(seeds[(vi + 1) % len(seeds)], msg)
            elif mode == "badidx":
                vidx.append(len(seeds) + 5)
                msgs.append(msg), sigs.append(sig), slot.append(t)
                k += 1
                continue
            msgs.append(msg), sigs.append(sig), vidx.append(vi), slot.append(t)
            k += 1
    return msgs, sigs, np.array(vidx), np.array(slot)


@pytest.fixture(scope="module")
def valset4():
    return make_valset(4)


def assert_parity(vals, msgs, sigs, vidx, slot, n_slots, prior=None):
    scalar = ScalarVoteVerifier(vals)
    device = DeviceVoteVerifier(vals)
    r_s = scalar.verify_and_tally(msgs, sigs, vidx, slot, n_slots, prior)
    r_d = device.verify_and_tally(msgs, sigs, vidx, slot, n_slots, prior)
    np.testing.assert_array_equal(r_s.valid, r_d.valid)
    np.testing.assert_array_equal(r_s.stake, r_d.stake.astype(np.int64))
    np.testing.assert_array_equal(r_s.maj23, r_d.maj23)
    np.testing.assert_array_equal(r_s.dropped, r_d.dropped)
    return r_s


def test_all_honest_quorum(valset4):
    vals, seeds = valset4
    msgs, sigs, vidx, slot = make_batch(vals, seeds, n_txs=3)
    r = assert_parity(vals, msgs, sigs, vidx, slot, n_slots=3)
    assert r.valid.all()
    assert r.maj23.all()
    assert (r.stake == vals.total_voting_power()).all()


def test_adversarial_mix(valset4):
    vals, seeds = valset4
    msgs, sigs, vidx, slot = make_batch(
        vals, seeds, n_txs=4, corrupt=("ok", "flip", "wrongkey", "badidx")
    )
    r = assert_parity(vals, msgs, sigs, vidx, slot, n_slots=4)
    assert not r.valid.all() and r.valid.any()
    # with only 1-2 of 4 honest votes per tx, no quorum anywhere
    assert not r.maj23.any()


def test_prior_stake_latches_quorum(valset4):
    """Quorum accumulates across batches exactly like the incremental reference."""
    vals, seeds = valset4
    msgs, sigs, vidx, slot = make_batch(vals, seeds, n_txs=1)
    # batch 1: two honest votes -> 20/40 stake, below quorum (27)
    r1 = assert_parity(vals, msgs[:2], sigs[:2], vidx[:2], slot[:2], 1)
    assert not r1.maj23[0] and r1.stake[0] == 20
    # batch 2: one more vote on top of prior -> 30 >= 27
    r2 = assert_parity(vals, msgs[2:3], sigs[2:3], vidx[2:3], slot[2:3], 1, prior=r1.stake)
    assert r2.maj23[0] and r2.stake[0] == 30


@pytest.mark.slow  # 8-way mesh compile: ~80s on the 1-core CPU CI box
def test_sharded_matches_single_device(valset4):
    vals, seeds = valset4
    mesh = make_mesh(8)
    msgs, sigs, vidx, slot = make_batch(
        vals, seeds, n_txs=5, corrupt=("ok", "ok", "flip")
    )
    sharded = DeviceVoteVerifier(vals, mesh=mesh)
    single = DeviceVoteVerifier(vals)
    r_m = sharded.verify_and_tally(msgs, sigs, vidx, slot, 5)
    r_1 = single.verify_and_tally(msgs, sigs, vidx, slot, 5)
    np.testing.assert_array_equal(r_m.valid, r_1.valid)
    np.testing.assert_array_equal(r_m.stake, r_1.stake)
    np.testing.assert_array_equal(r_m.maj23, r_1.maj23)


def test_replayed_vote_not_double_counted(valset4):
    """A (tx, validator) pair repeated in one batch contributes power once.

    The reference can never double-count one validator's stake
    (first-signature-wins, types/vote_set.go:109-131); an adversary
    replaying one honest vote must not be able to fake a quorum.
    """
    vals, seeds = valset4
    msgs, sigs, vidx, slot = make_batch(vals, seeds, n_txs=1)
    # one honest vote replayed 3x + one fresh honest vote = 2 real voters
    m = [msgs[0]] * 3 + [msgs[1]]
    s = [sigs[0]] * 3 + [sigs[1]]
    vi = np.array([vidx[0]] * 3 + [vidx[1]])
    sl = np.array([0, 0, 0, 0])
    r = assert_parity(vals, m, s, vi, sl, n_slots=1)
    assert r.stake[0] == 20 and not r.maj23[0]
    np.testing.assert_array_equal(r.dropped, [False, True, True, False])
    assert r.valid.tolist() == [True, False, False, True]


def test_bucket_size():
    assert bucket_size(1) == 64
    assert bucket_size(64) == 64
    assert bucket_size(65) == 256
    assert bucket_size(70000, multiple=8) == 70000
    assert bucket_size(70001, multiple=8) == 70008


def test_verifier_mux_matches_direct_calls():
    """Concurrent verify calls through the mux must return bit-identical
    results to direct per-caller calls (votes merged, slot ranges shifted,
    results split)."""
    import threading

    from txflow_tpu.verifier import VerifierMux

    vals, seeds = make_valset(4)
    direct = ScalarVoteVerifier(vals)
    mux = VerifierMux(ScalarVoteVerifier(vals), gather_wait=0.05)
    mux.start()
    try:
        reqs = []
        for t in range(3):  # three "engines" with different batch shapes
            msgs, sigs, vidx, slot = make_batch(
                vals, seeds, n_txs=2 + t, corrupt=("ok", "flip") if t == 1 else ()
            )
            reqs.append((msgs, sigs, vidx, slot, 2 + t))
        want = [
            direct.verify_and_tally(m, s, v, sl, ns) for m, s, v, sl, ns in reqs
        ]
        got = [None] * len(reqs)
        errs = []

        def call(i):
            m, s, v, sl, ns = reqs[i]
            try:
                got[i] = mux.verify_and_tally(m, s, v, sl, ns)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=call, args=(i,)) for i in range(len(reqs))]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=30)
        assert not errs, errs
        for w, g in zip(want, got):
            np.testing.assert_array_equal(w.valid, g.valid)
            np.testing.assert_array_equal(w.stake, g.stake)
            np.testing.assert_array_equal(w.maj23, g.maj23)
            np.testing.assert_array_equal(w.dropped, g.dropped)

        # quorum overrides are not mergeable
        m, s, v, sl, ns = reqs[0]
        with pytest.raises(ValueError):
            mux.verify_and_tally(m, s, v, sl, ns, quorum=1)
    finally:
        mux.stop()


def test_verifier_mux_prior_stake_isolated():
    """Each caller's prior_stake must only affect its own slots."""
    from txflow_tpu.verifier import VerifierMux

    vals, seeds = make_valset(4)
    mux = VerifierMux(ScalarVoteVerifier(vals), gather_wait=0.05)
    mux.start()
    try:
        import threading

        msgs, sigs, vidx, slot = make_batch(vals, seeds, n_txs=2)
        # caller A: one vote shy of quorum already (prior 20 of 30 needed);
        # caller B: zero prior — same votes, different quorum outcomes
        prior_a = np.array([20, 0], np.int64)
        out = {}

        def call(name, prior):
            out[name] = mux.verify_and_tally(
                msgs[:4], sigs[:4], vidx[:4], slot[:4], 2, prior_stake=prior
            )

        ta = threading.Thread(target=call, args=("a", prior_a))
        tb = threading.Thread(target=call, args=("b", None))
        ta.start(); tb.start(); ta.join(30); tb.join(30)
        # first 4 votes are tx0's full validator quorum (4 x power 10)
        assert out["a"].stake[0] == 20 + 40 and bool(out["a"].maj23[0])
        assert out["b"].stake[0] == 40 and bool(out["b"].maj23[0])
        assert out["a"].stake[1] == 0 and out["b"].stake[1] == 0
    finally:
        mux.stop()


@pytest.mark.slow  # two 8-way mesh compiles: ~60s on the 1-core CPU CI box
def test_ring_tally_matches_psum_step():
    """The explicit ppermute ring all-reduce must produce bit-identical
    tallies to the psum formulation over the virtual mesh."""
    import numpy as _np

    from txflow_tpu.ops import ed25519_batch
    from txflow_tpu.parallel import make_mesh
    from txflow_tpu.parallel.mesh import sharded_compact_step, sharded_ring_step

    vals, seeds = make_valset(4)
    epoch = ed25519_batch.EpochTables([v.pub_key for v in vals])
    msgs, sigs, vidx, slot = make_batch(
        vals, seeds, n_txs=4, corrupt=("ok", "ok", "flip")
    )
    batch = ed25519_batch.prepare_compact(msgs, sigs, vidx, epoch)
    n = batch.size
    pad = (-n) % 8
    import numpy as np

    def p(a):
        return np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])

    args = (
        p(batch.s_nibbles), p(batch.h_nibbles), p(batch.val_idx),
        p(batch.r_y), p(batch.r_sign), p(batch.pre_ok),
        np.concatenate([np.asarray(slot, np.int32), np.full(pad, -1, np.int32)]),
        epoch.tables, vals.powers_array().astype(np.int32),
        np.zeros(4, np.int32), np.int32(vals.quorum_power()),
    )
    mesh = make_mesh(8)
    a = sharded_compact_step(mesh)(*args)
    b = sharded_ring_step(mesh)(*args)
    _np.testing.assert_array_equal(_np.asarray(a[0]), _np.asarray(b[0]))
    # ring outputs are per-shard copies of the global: every shard's slice
    # must equal the psum-replicated global
    stake = _np.asarray(b[1]).reshape(8, -1)
    maj = _np.asarray(b[2]).reshape(8, -1)
    for sh in range(8):
        _np.testing.assert_array_equal(stake[sh], _np.asarray(a[1]))
        _np.testing.assert_array_equal(maj[sh], _np.asarray(a[2]))


def test_verifier_mux_error_propagates_to_all_waiters():
    """An inner-verifier failure must surface to every merged caller and
    leave the mux serviceable for the next call."""
    import threading

    from txflow_tpu.verifier import VerifierMux

    vals, seeds = make_valset(4)

    class Flaky:
        def __init__(self, inner):
            self.inner = inner
            self.val_set = inner.val_set
            self.fail = True

        def verify_and_tally(self, *a, **k):
            if self.fail:
                raise RuntimeError("device fell over")
            return self.inner.verify_and_tally(*a, **k)

    flaky = Flaky(ScalarVoteVerifier(vals))
    mux = VerifierMux(flaky, gather_wait=0.05)
    mux.start()
    try:
        msgs, sigs, vidx, slot = make_batch(vals, seeds, n_txs=2)
        errs, oks = [], []

        def call():
            try:
                oks.append(mux.verify_and_tally(msgs, sigs, vidx, slot, 2))
            except RuntimeError as e:
                errs.append(e)

        ts = [threading.Thread(target=call) for _ in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert len(errs) == 3 and not oks

        flaky.fail = False  # mux must still serve after the failure
        r = mux.verify_and_tally(msgs, sigs, vidx, slot, 2)
        assert r.valid.all()
    finally:
        mux.stop()


def test_verifier_mux_stop_strands_no_callers():
    """stop() must release every in-flight caller: queued requests (even
    ones enqueued concurrently with shutdown) either get served inline on
    the inner verifier or fail with RuntimeError — no thread may block in
    done.wait() forever (r3 advisor low)."""
    import threading
    import time

    from txflow_tpu.verifier import VerifierMux

    vals, seeds = make_valset(4)
    mux = VerifierMux(ScalarVoteVerifier(vals), gather_wait=0.05)
    mux.start()
    results = []

    def caller():
        msgs, sigs, vidx, slot = make_batch(vals, seeds, n_txs=1)
        try:
            r = mux.verify_and_tally(msgs, sigs, vidx, slot, 1)
            results.append(("ok", bool(r.valid.all())))
        except RuntimeError as e:
            results.append(("stopped", str(e)))

    threads = [threading.Thread(target=caller) for _ in range(8)]
    for t in threads:
        t.start()
    time.sleep(0.01)
    mux.stop()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive(), "caller stranded in done.wait() after stop()"
    assert len(results) == 8
    # served results must be correct; failures must be the shutdown error
    for kind, val in results:
        assert (kind == "ok" and val is True) or kind == "stopped", results


def test_verify_cache_parity_and_sharing():
    """Cached verifiers must make bit-identical decisions to the plain
    scalar golden model, while co-located engines sharing one cache skip
    re-verifying votes the first engine already resolved (r4: the 4-node
    bench ran 4x redundant kernel work without this)."""
    from txflow_tpu.verifier import VerifyCache

    vals, seeds = make_valset(4)
    golden = ScalarVoteVerifier(vals)
    cache = VerifyCache()
    eng_a = ScalarVoteVerifier(vals, shared_cache=cache)
    eng_b = ScalarVoteVerifier(vals, shared_cache=cache)

    msgs, sigs, vidx, slot = make_batch(
        vals, seeds, n_txs=6,
        corrupt=("ok", "flip", "ok", "wrongkey", "badidx", "ok"),
    )
    n_slots = 6
    want = golden.verify_and_tally(msgs, sigs, vidx, slot, n_slots)
    got_a = eng_a.verify_and_tally(msgs, sigs, vidx, slot, n_slots)
    np.testing.assert_array_equal(want.valid, got_a.valid)
    np.testing.assert_array_equal(want.stake, got_a.stake)
    np.testing.assert_array_equal(want.maj23, got_a.maj23)
    np.testing.assert_array_equal(want.dropped, got_a.dropped)

    # second engine, same gossip: all cacheable rows must hit
    before_misses = cache.misses
    got_b = eng_b.verify_and_tally(msgs, sigs, vidx, slot, n_slots)
    np.testing.assert_array_equal(want.valid, got_b.valid)
    np.testing.assert_array_equal(want.maj23, got_b.maj23)
    assert cache.misses == before_misses, "engine B re-verified cached votes"
    assert cache.hits > 0

    # key binds the message: replaying a cached-valid signature on a
    # DIFFERENT payload must NOT alias to the cached verdict
    forged_msgs = [m + b"X" for m in msgs]
    got_forged = eng_b.verify_and_tally(forged_msgs, sigs, vidx, slot, n_slots)
    assert not got_forged.valid.any()


def test_device_verifier_cached_parity(device_verifier_factory=None):
    """Device verifier with the cache on: decisions identical to both the
    plain device kernel and the scalar golden model; second call all-hits."""
    vals, seeds = make_valset(4)
    golden = ScalarVoteVerifier(vals)
    dev = DeviceVoteVerifier(vals, shared_cache=True)
    msgs, sigs, vidx, slot = make_batch(
        vals, seeds, n_txs=5, corrupt=("ok", "flip", "ok", "wrongkey")
    )
    n_slots = 5
    want = golden.verify_and_tally(msgs, sigs, vidx, slot, n_slots)
    got = dev.verify_and_tally(msgs, sigs, vidx, slot, n_slots)
    np.testing.assert_array_equal(want.valid, got.valid)
    np.testing.assert_array_equal(want.stake, got.stake)
    np.testing.assert_array_equal(want.maj23, got.maj23)
    np.testing.assert_array_equal(want.dropped, got.dropped)
    before = dev.cache.misses
    got2 = dev.verify_and_tally(msgs, sigs, vidx, slot, n_slots)
    np.testing.assert_array_equal(want.valid, got2.valid)
    assert dev.cache.misses == before

    # prior stake must latch through the cached host tally as well
    prior = np.array([vals.quorum_power() - 10] + [0] * (n_slots - 1), np.int64)
    got3 = dev.verify_and_tally(msgs, sigs, vidx, slot, n_slots, prior_stake=prior)
    want3 = golden.verify_and_tally(msgs, sigs, vidx, slot, n_slots, prior_stake=prior)
    np.testing.assert_array_equal(want3.stake, got3.stake)
    np.testing.assert_array_equal(want3.maj23, got3.maj23)


def test_verify_cache_binds_pubkey_not_index():
    """A shared cache outliving a validator-set change must never replay a
    'valid' verdict for a signature that was checked against a DIFFERENT
    key now living at the same index (r4 advisor: keys previously bound
    the index). Two sets are built so a seed-A validator sits at index 0
    in set A and a different key sits at index 0 in set B."""
    from txflow_tpu.verifier import VerifyCache

    seed_a = hashlib.sha256(b"epoch-a-val").digest()
    seed_b = hashlib.sha256(b"epoch-b-val").digest()
    pub_a = host_ed.public_key_from_seed(seed_a)
    pub_b = host_ed.public_key_from_seed(seed_b)
    set_a = ValidatorSet([Validator.from_pub_key(pub_a, 10)])
    set_b = ValidatorSet([Validator.from_pub_key(pub_b, 10)])

    msg = canonical_sign_bytes(CHAIN_ID, 1, "AA" * 32, 1700000000_000000000)
    sig = host_ed.sign(seed_a, msg)  # valid under pub_a only

    cache = VerifyCache()
    v_a = ScalarVoteVerifier(set_a, shared_cache=cache)
    v_b = ScalarVoteVerifier(set_b, shared_cache=cache)

    r_a = v_a.verify_and_tally([msg], [sig], np.array([0]), np.array([0]), 1)
    assert r_a.valid[0]  # genuinely valid under set A, now cached
    r_b = v_b.verify_and_tally([msg], [sig], np.array([0]), np.array([0]), 1)
    assert not r_b.valid[0]  # same index, different key: MUST miss + fail

    # and the key is split-unambiguous: shifting a boundary byte between
    # msg and sig yields a different cache key
    k1 = VerifyCache.key(msg, sig, pub_a)
    k2 = VerifyCache.key(msg + sig[:1], sig[1:], pub_a)
    assert k1 != k2


@pytest.mark.parametrize("nv", [16, 64])
def test_large_validator_set_parity(nv):
    """Device/scalar parity at BASELINE configs 2-3 validator counts (the
    [V,16,4,NLIMB] epoch-table gather at V=16/64 — the shapes the TPU
    bench sweeps; adversarial mix included)."""
    vals, seeds = make_valset(nv)
    msgs, sigs, vidx, slot = make_batch(
        vals, seeds, n_txs=3, corrupt=("ok", "flip", "ok", "wrongkey")
    )
    assert_parity(vals, msgs, sigs, vidx, slot, 3)


def test_verify_cache_claims_dedupe_inflight():
    """Claim semantics (r5: co-located engines racing on the same misses
    each paid a full padded device call — 580 votes/s on TPU vs 12k
    uncached): the first asker owns a miss; concurrent askers are told
    it is pending and must defer; store resolves it for everyone;
    release hands an abandoned claim to the next asker."""
    from txflow_tpu.verifier import VerifyCache

    cache = VerifyCache()
    k = VerifyCache.key(b"m", b"s" * 64, b"p" * 32)
    vals, pending = cache.lookup_or_claim_many([k])
    assert vals == [None] and not pending[0]  # this caller owns the claim
    vals2, pending2 = cache.lookup_or_claim_many([k])
    assert vals2 == [None] and pending2[0]  # concurrent asker defers
    cache.store_many([(k, True)])
    vals3, pending3 = cache.lookup_or_claim_many([k])
    assert vals3 == [True] and not pending3[0]  # resolved for everyone

    # release without a verdict: next asker becomes the owner
    k2 = VerifyCache.key(b"m2", b"s" * 64, b"p" * 32)
    cache.lookup_or_claim_many([k2])
    cache.release_many([k2])
    v, p = cache.lookup_or_claim_many([k2])
    assert v == [None] and not p[0]

    # None keys are never claimed or pending
    v, p = cache.lookup_or_claim_many([None])
    assert v == [None] and not p[0]


def test_verify_cache_claim_ttl_reclaims_abandoned():
    """A claim whose owner died mid-verify must not stall waiters
    forever: past claim_ttl the next asker takes ownership."""
    import time as _time

    from txflow_tpu.verifier import VerifyCache

    cache = VerifyCache(claim_ttl=0.02)
    k = VerifyCache.key(b"m", b"s" * 64, b"p" * 32)
    cache.lookup_or_claim_many([k])
    _, p = cache.lookup_or_claim_many([k])
    assert p[0]  # fresh claim: still owned elsewhere
    _time.sleep(0.03)
    v, p = cache.lookup_or_claim_many([k])
    assert v == [None] and not p[0]  # stale claim handed over


def test_verify_cache_claim_keepalive_outlives_ttl():
    """A device call slower than claim_ttl (a cold-shape compile runs
    minutes) must NOT leak its claims mid-flight: the keepalive heartbeat
    re-stamps them, so concurrent engines keep deferring instead of
    re-verifying the same votes; once the owner exits, claims age out
    normally."""
    import time as _time

    from txflow_tpu.verifier import VerifyCache

    cache = VerifyCache(claim_ttl=0.05)
    keys = [VerifyCache.key(b"m%d" % i, b"s" * 64, b"p" * 32) for i in range(3)]
    _, pending = cache.lookup_or_claim_many(keys)
    assert not any(pending)  # we own all three
    with cache.claim_keepalive(keys):
        _time.sleep(0.2)  # several TTLs inside the "device call"
        _, p = cache.lookup_or_claim_many(keys)
        assert all(p), "heartbeat must keep in-flight claims owned"
    # owner exited without storing (the call failed): claims expire and
    # the next asker takes over after the TTL
    _time.sleep(0.08)
    v, p = cache.lookup_or_claim_many(keys)
    assert v == [None] * 3 and not any(p)
    # keepalive over an empty claim list is a no-op context
    with cache.claim_keepalive([]):
        pass


def test_shared_cache_pending_defers_instead_of_failing():
    """An engine that meets another engine's in-flight verifies must
    report those votes as dropped (deferred for retry) — never as
    invalid — and must resolve them to the correct verdicts once the
    owner stores. Deferred votes also must not contribute stake."""
    from txflow_tpu.verifier import VerifyCache

    vals, seeds = make_valset(4)
    cache = VerifyCache()
    golden = ScalarVoteVerifier(vals)
    eng_b = ScalarVoteVerifier(vals, shared_cache=cache)

    msgs, sigs, vidx, slot = make_batch(vals, seeds, n_txs=3)
    n_slots = 3
    keys = [
        VerifyCache.key(msgs[i], sigs[i], eng_b._pub_keys[int(vidx[i])])
        for i in range(len(msgs))
    ]
    # simulate engine A holding claims on every vote (mid-device-call)
    _, pend = cache.lookup_or_claim_many(keys)
    assert not pend.any()

    got = eng_b.verify_and_tally(msgs, sigs, vidx, slot, n_slots)
    assert got.dropped.all(), "pending votes must come back deferred"
    assert not got.valid.any()
    assert (got.stake == 0).all() and not got.maj23.any()

    # engine A finishes: stores the true verdicts; B's retry is all hits
    want = golden.verify_and_tally(msgs, sigs, vidx, slot, n_slots)
    cache.store_many([(keys[i], bool(want.valid[i])) for i in range(len(keys))])
    before = cache.misses
    got2 = eng_b.verify_and_tally(msgs, sigs, vidx, slot, n_slots)
    np.testing.assert_array_equal(want.valid, got2.valid)
    np.testing.assert_array_equal(want.stake, got2.stake)
    np.testing.assert_array_equal(want.maj23, got2.maj23)
    np.testing.assert_array_equal(want.dropped, got2.dropped)
    assert cache.misses == before, "retry after store must be all hits"


def test_device_cached_pending_defers(valset4):
    """Device cached path: same deferral contract as the scalar one."""
    from txflow_tpu.verifier import VerifyCache

    vals, seeds = valset4
    cache = VerifyCache()
    dev = DeviceVoteVerifier(vals, shared_cache=cache)
    golden = ScalarVoteVerifier(vals)
    msgs, sigs, vidx, slot = make_batch(vals, seeds, n_txs=2)
    keys = [
        VerifyCache_key_for(dev, msgs[i], sigs[i], int(vidx[i]))
        for i in range(len(msgs))
    ]
    cache.lookup_or_claim_many(keys)  # another engine owns everything
    got = dev.verify_and_tally(msgs, sigs, vidx, slot, 2)
    assert got.dropped.all() and not got.valid.any()
    cache.release_many(keys)  # owner aborted: dev may now verify
    got2 = dev.verify_and_tally(msgs, sigs, vidx, slot, 2)
    want = golden.verify_and_tally(msgs, sigs, vidx, slot, 2)
    np.testing.assert_array_equal(want.valid, got2.valid)
    np.testing.assert_array_equal(want.maj23, got2.maj23)


def VerifyCache_key_for(verifier, msg, sig, vi):
    from txflow_tpu.verifier import VerifyCache

    return VerifyCache.key(msg, sig, verifier._pub_keys[vi])


def test_warmup_full_compiles_every_reachable_shape(valset4):
    """warmup(full=True) must exercise _verify_only at EVERY miss bucket
    (cached path) — a shape left cold compiles mid-measurement on the
    first batch that hits it (r5: a 169 s throughput phase was ~160 s of
    one such compile)."""
    from txflow_tpu.verifier import VerifyCache

    vals, _seeds = valset4
    dev = DeviceVoteVerifier(vals, buckets=(64, 256), shared_cache=VerifyCache())
    seen: list[int] = []
    orig = dev._verify_only

    def spy(msgs, sigs, val_idx):
        seen.append(len(msgs))
        return orig(msgs, sigs, val_idx)

    dev._verify_only = spy
    dev.warmup(full=True)
    assert set(seen) >= set(dev.miss_buckets), (seen, dev.miss_buckets)

    # default warmup(n) keeps its contract: every shape an n-vote batch
    # can hit is warm — all miss buckets up to n's coarse bucket
    dev2 = DeviceVoteVerifier(vals, buckets=(64, 256), shared_cache=VerifyCache())
    seen2: list[int] = []
    orig2 = dev2._verify_only

    def spy2(msgs, sigs, val_idx):
        seen2.append(len(msgs))
        return orig2(msgs, sigs, val_idx)

    dev2._verify_only = spy2
    dev2.warmup(256)
    want = {b for b in dev2.miss_buckets if b <= 256}
    assert set(seen2) >= want, (seen2, want)


def test_replay_flood_costs_zero_repeat_dispatches(valset4):
    """Replay-flood regression (accountable gossip): re-submitting a
    batch the verifier has already judged must cost ZERO device
    dispatches — the verdict cache replays every verdict, including the
    False ones, so an identical-vote flood can never re-buy device time
    with signatures that already failed."""
    from txflow_tpu.verifier import VerifyCache

    vals, seeds = valset4
    dev = DeviceVoteVerifier(vals, shared_cache=VerifyCache())
    dispatches: list[int] = []
    orig = dev._dispatch_verify_only

    def spy(msgs, sigs, val_idx, **kw):
        dispatches.append(len(msgs))
        return orig(msgs, sigs, val_idx, **kw)

    dev._dispatch_verify_only = spy
    msgs, sigs, vidx, slot = make_batch(vals, seeds, n_txs=3, corrupt=("ok", "flip"))
    r1 = dev.verify_and_tally(msgs, sigs, vidx, slot, 3)
    assert len(dispatches) == 1 and dispatches[0] == len(msgs)
    assert r1.valid.any() and not r1.valid.all()  # mixed verdicts cached

    r2 = dev.verify_and_tally(msgs, sigs, vidx, slot, 3)
    assert len(dispatches) == 1, "an identical replay must not reach the device"
    np.testing.assert_array_equal(r1.valid, r2.valid)
    np.testing.assert_array_equal(r1.stake, r2.stake)
    np.testing.assert_array_equal(r1.maj23, r2.maj23)
