"""Batched device verifier vs the scalar golden model — incl. adversarial cases."""

import random

import numpy as np

from txflow_tpu.crypto import ed25519 as host_ed
from txflow_tpu.ops import ed25519_batch as eb

rng = random.Random(0xED)


def make_keys(n):
    seeds = [bytes([rng.randrange(256) for _ in range(32)]) for _ in range(n)]
    pubs = [host_ed.public_key_from_seed(s) for s in seeds]
    return seeds, pubs


def test_verify_valid_and_corrupted():
    seeds, pubs = make_keys(4)
    epoch = eb.EpochTables(pubs)
    msgs, sigs, vidx, want = [], [], [], []

    # valid signatures
    for i in range(4):
        m = bytes([rng.randrange(256) for _ in range(rng.randrange(1, 120))])
        msgs.append(m)
        sigs.append(host_ed.sign(seeds[i], m))
        vidx.append(i)
        want.append(True)

    # corrupted signature byte (R part)
    m = b"corrupt-r"
    s = bytearray(host_ed.sign(seeds[0], m))
    s[5] ^= 1
    msgs.append(m)
    sigs.append(bytes(s))
    vidx.append(0)
    want.append(False)

    # corrupted S part
    s = bytearray(host_ed.sign(seeds[1], m))
    s[40] ^= 1
    msgs.append(m)
    sigs.append(bytes(s))
    vidx.append(1)
    want.append(False)

    # wrong message
    msgs.append(b"other message")
    sigs.append(host_ed.sign(seeds[2], b"original message"))
    vidx.append(2)
    want.append(False)

    # wrong validator (signature by 0, claimed by 3)
    msgs.append(m)
    sigs.append(host_ed.sign(seeds[0], m))
    vidx.append(3)
    want.append(False)

    # S >= L (malleability): forge sig with S + L
    good = host_ed.sign(seeds[0], m)
    s_val = int.from_bytes(good[32:], "little") + host_ed.L
    msgs.append(m)
    sigs.append(good[:32] + s_val.to_bytes(32, "little"))
    vidx.append(0)
    want.append(False)

    # wrong signature length
    msgs.append(m)
    sigs.append(good[:50])
    vidx.append(0)
    want.append(False)

    batch = eb.prepare_batch(msgs, sigs, np.array(vidx), epoch)
    got = eb.verify_batch(batch)
    assert got.tolist() == want
    # agreement with both host paths, case by case
    for i, (m, s, vi) in enumerate(zip(msgs, sigs, vidx)):
        assert bool(got[i]) == host_ed.verify(pubs[vi], m, s)
        assert bool(got[i]) == host_ed.verify_pure(pubs[vi], m, s)


def test_off_curve_pubkey_rejected():
    # y = 2 is not on the curve (2^2-1 / (d*4+1) is a non-residue for this y)
    bad_pub = (2).to_bytes(32, "little")
    assert host_ed.point_decompress(bad_pub) is None
    epoch = eb.EpochTables([bad_pub])
    m = b"msg"
    sig = bytes(64)
    batch = eb.prepare_batch([m], [sig], np.array([0]), epoch)
    assert eb.verify_batch(batch).tolist() == [False]
    assert not epoch.key_ok[0]


def test_noncanonical_r_rejected():
    """The kernel accepts only the exact canonical R encoding.

    Every non-canonical 255-bit y encoding is y' = y + p for some y < 19, so
    no forgeable signature can reach that branch end-to-end (R = sB - hA
    would need to land on one of ~19 points); what must hold is (a) the
    frozen-limb comparison distinguishes y from y + p, and (b) a flipped
    x-sign bit on an otherwise-valid R is rejected end-to-end.
    """
    from txflow_tpu.ops import fe
    import jax.numpy as jnp

    # (a) direct: canonical limbs of y vs the non-canonical y + p encoding
    for y_small in (0, 1, 5, 18):
        canon = jnp.asarray(fe.int_to_limbs(y_small))[None]
        noncanon = jnp.asarray(fe.int_to_limbs(y_small + host_ed.P))[None]
        assert not bool(fe.fe_is_equal_frozen(canon, noncanon)[0])
        assert bool(fe.fe_is_equal_frozen(canon, canon)[0])

    # (b) end-to-end: same point, flipped canonical sign bit -> reject
    seeds, pubs = make_keys(1)
    epoch = eb.EpochTables(pubs)
    m = b"canonical"
    good = host_ed.sign(seeds[0], m)
    r_int = int.from_bytes(good[:32], "little")
    sig = (r_int ^ (1 << 255)).to_bytes(32, "little") + good[32:]
    batch = eb.prepare_batch([m], [sig], np.array([0]), epoch)
    assert eb.verify_batch(batch).tolist() == [False]
    assert not host_ed.verify(pubs[0], m, sig)


def test_random_cross_check_mixed():
    # Mixed batch: random valid/invalid, compare elementwise vs golden model.
    n_val = 6
    seeds, pubs = make_keys(n_val)
    epoch = eb.EpochTables(pubs)
    msgs, sigs, vidx = [], [], []
    B = 24
    for i in range(B):
        vi = rng.randrange(n_val)
        m = bytes([rng.randrange(256) for _ in range(40)])
        sig = bytearray(host_ed.sign(seeds[vi], m))
        kind = i % 4
        if kind == 1:
            sig[rng.randrange(64)] ^= 1 << rng.randrange(8)
        elif kind == 2:
            m = m + b"!"
        elif kind == 3:
            vi = (vi + 1) % n_val
        msgs.append(m)
        sigs.append(bytes(sig))
        vidx.append(vi)
    got = eb.verify_batch(eb.prepare_batch(msgs, sigs, np.array(vidx), epoch))
    for i in range(B):
        assert bool(got[i]) == host_ed.verify_pure(pubs[vidx[i]], msgs[i], sigs[i]), i
