"""Mesh-sharded verify in the serving engine + the multi-worker host path.

Three contracts from the mesh/host-pool work:

1. a DeviceVoteVerifier over an N-way mesh (pow2 AND non-pow2, full and
   partial buckets) makes decisions byte-identical to the single-device
   and scalar golden paths — certificates included;
2. a mid-run epoch restage on a mesh verifier stays inside the prewarmed
   shape set (zero in-run compiles: restaging swaps tables/powers, never
   program shapes);
3. the host-prep pool (engine sign-bytes assembly and compact-batch prep)
   is a pure parallelization — outputs equal the serial path bit for bit.
"""

import hashlib

import numpy as np
import pytest

from test_engine import make_engine, make_pvs, sign_vote
from test_pipeline import _mixed_stream, _wait_quiescent
from test_pipeline import make_engine as make_threaded_engine
from test_verifier import make_batch, make_valset
from txflow_tpu.crypto import ed25519 as host_ed
from txflow_tpu.engine.hostprep import HostPrepPool
from txflow_tpu.engine.shapes import ShapeWarmRegistry
from txflow_tpu.engine.txflow import _BatchCoalescer
from txflow_tpu.ops import ed25519_batch
from txflow_tpu.parallel import make_mesh
from txflow_tpu.types import Validator, ValidatorSet
from txflow_tpu.verifier import (
    DeviceVoteVerifier,
    ScalarVoteVerifier,
    bucket_size,
)

BUCKETS = (32, 128)  # small ladder: CPU-sized compiles across mesh variants


# ---- verifier-level mesh parity ---------------------------------------


# tier-1 keeps the 4-way mesh (the acceptance device count) — the mesh
# case also checks the scalar and single-device paths, so [1] adds no
# coverage it lacks; every other cardinality compiles its own shapes
# (~45s each on the 1-core CI box) and rides the slow lane
@pytest.mark.parametrize(
    "n_shards",
    [
        pytest.param(1, marks=pytest.mark.slow),
        pytest.param(2, marks=pytest.mark.slow),
        pytest.param(3, marks=pytest.mark.slow),
        4,
        pytest.param(8, marks=pytest.mark.slow),
    ],
)
def test_mesh_parity_randomized(n_shards):
    """Mesh vs single-device vs scalar on an adversarial batch whose size
    is NOT shard-divisible (partial bucket: padding differs per mesh)."""
    vals, seeds = make_valset(4)
    msgs, sigs, vidx, slot = make_batch(
        vals, seeds, n_txs=7, corrupt=("ok", "flip", "ok", "wrongkey", "badidx")
    )
    # 7 txs x 4 validators = 28 votes: partial on every rung of BUCKETS
    n_slots = 7
    prior = np.array([0, 25, 0, 0, 10, 0, 0], dtype=np.int64)

    scalar = ScalarVoteVerifier(vals)
    single = DeviceVoteVerifier(vals, buckets=BUCKETS)
    mesh = make_mesh(n_shards) if n_shards > 1 else None
    sharded = DeviceVoteVerifier(vals, buckets=BUCKETS, mesh=mesh)
    assert sharded._n_shards == n_shards

    r_s = scalar.verify_and_tally(msgs, sigs, vidx, slot, n_slots, prior)
    r_1 = single.verify_and_tally(msgs, sigs, vidx, slot, n_slots, prior)
    r_n = sharded.verify_and_tally(msgs, sigs, vidx, slot, n_slots, prior)
    for r in (r_1, r_n):
        np.testing.assert_array_equal(r_s.valid, r.valid)
        np.testing.assert_array_equal(r_s.stake, r.stake.astype(np.int64))
        np.testing.assert_array_equal(r_s.maj23, r.maj23)
        np.testing.assert_array_equal(r_s.dropped, r.dropped)


def test_bucket_size_rounds_before_selecting():
    """Round-then-select: a drain sized exactly at a shard-rounded rung
    pads zero instead of spilling to the next rung (a 258-vote drain on 3
    shards is the rounded 256 bucket, not 1026)."""
    assert bucket_size(258, (256, 1024), multiple=3) == 258
    assert bucket_size(256, (256, 1024), multiple=3) == 258
    assert bucket_size(259, (256, 1024), multiple=3) == 1026
    # multiple=1 unchanged
    assert bucket_size(256, (256, 1024)) == 256
    assert bucket_size(257, (256, 1024)) == 1024
    # above the ladder: round the count itself
    assert bucket_size(1027, (256, 1024), multiple=4) == 1028


def test_coalescer_targets_round_to_shard_multiple():
    co = _BatchCoalescer((256, 1024), cap=2048, min_batch=1, linger=0.01,
                         multiple=3)
    assert co.targets == [258, 1026]
    co1 = _BatchCoalescer((256, 1024), cap=2048, min_batch=1, linger=0.01)
    assert co1.targets == [256, 1024]


# ---- engine-level certificate parity ----------------------------------


def _drain(flow):
    while flow.step():
        pass


@pytest.mark.slow
def test_mesh_engine_certificates_byte_identical():
    """Same adversarial stream through a single-device engine and a
    4-way-mesh engine (host pool on): byte-identical certificates, app
    state, and commit order."""
    import random

    rng = random.Random(7)
    pvs, vals = make_pvs(7)
    txs = [b"mesh%d=%d" % (i, i) for i in range(10)]
    stream = []
    for tx in txs:
        for vi in rng.sample(range(7), rng.randint(3, 7)):
            vote = sign_vote(pvs[vi], tx)
            if rng.random() < 0.15:
                vote.signature = bytes(64)
            stream.append(vote)
    rng.shuffle(stream)

    def run(verifier):
        flow, mem, _, pool, store, app, _ = make_engine(
            vals, verifier=verifier, max_batch=17
        )
        for tx in txs:
            mem.check_tx(tx)
        for v in stream:
            try:
                pool.check_tx(v.copy())
            except Exception:
                pass
        _drain(flow)
        return flow, store, app

    flow_1, store_1, app_1 = run(DeviceVoteVerifier(vals, buckets=BUCKETS))
    flow_m, store_m, app_m = run(
        DeviceVoteVerifier(
            vals, buckets=BUCKETS, mesh=make_mesh(4), host_prep_workers=3
        )
    )

    assert app_m.tx_count == app_1.tx_count
    assert app_m.state == app_1.state
    assert app_m.digest == app_1.digest  # commit ORDER identical
    committed = 0
    for tx in txs:
        tx_hash = hashlib.sha256(tx).hexdigest().upper()
        c1 = store_1.load_tx_commit(tx_hash)
        cm = store_m.load_tx_commit(tx_hash)
        assert (c1 is None) == (cm is None)
        if c1 is not None:
            committed += 1
            assert [
                (c.validator_address, c.signature, c.timestamp_ns)
                for c in c1.commits
            ] == [
                (c.validator_address, c.signature, c.timestamp_ns)
                for c in cm.commits
            ]
    assert committed > 0, "stream never formed a quorum — test is vacuous"
    for tx_hash, vs in flow_1.vote_sets.items():
        assert flow_m.vote_sets[tx_hash].stake() == vs.stake()


@pytest.mark.slow
def test_mesh_engine_linger_flush_parity():
    """Threaded coalescing engine on a 3-way mesh (non-pow2): a sub-bucket
    tail leaves via the linger deadline, and every decision still matches
    the scalar golden path."""
    import time

    pvs, vals = make_pvs(7)  # quorum 47 -> 5 votes needed
    txs = [b"ml%d=%d" % (i, i) for i in range(8)]
    stream = _mixed_stream(pvs, txs, seed=13)
    tail_tx = b"ml-tail=1"
    tail = [sign_vote(pv, tail_tx) for pv in pvs[:3]]  # stake 30 < 47

    flow_s, mem_s, _, store_s, app_s = make_threaded_engine(
        vals, use_device=False
    )
    for tx in txs + [tail_tx]:
        mem_s.check_tx(tx)
    for v in stream + tail:
        flow_s.try_add_vote(v.copy())

    verifier = DeviceVoteVerifier(vals, buckets=(8, 32), mesh=make_mesh(3))
    verifier.warmup(full=True)  # compile OUTSIDE the drain-wait windows
    flow_m, mem_m, pool_m, store_m, app_m = make_threaded_engine(
        vals,
        verifier=verifier,
        max_batch=32,
        min_batch=4,
        pipeline_depth=2,
        coalesce=True,
        coalesce_linger=0.02,
        mesh_devices=3,
    )
    for tx in txs + [tail_tx]:
        mem_m.check_tx(tx)
    flow_m.start()
    try:
        co = flow_m._coalescer
        assert co is not None and co.targets == [9, 33]  # shard-rounded
        for v in stream:
            try:
                pool_m.check_tx(v)
            except Exception:
                pass
        assert _wait_quiescent(flow_m, pool_m, timeout=90.0), (
            "mesh engine never drained"
        )
        for v in tail:
            pool_m.check_tx(v)
        assert _wait_quiescent(flow_m, pool_m, timeout=90.0), (
            "tail never flushed"
        )
        assert co.linger_flushes > 0, "tail left without a linger flush"
    finally:
        flow_m.stop()

    assert app_m.tx_count == app_s.tx_count
    assert app_m.state == app_s.state
    assert app_m.digest == app_s.digest
    for tx in txs + [tail_tx]:
        tx_hash = hashlib.sha256(tx).hexdigest().upper()
        cs = store_s.load_tx_commit(tx_hash)
        cm = store_m.load_tx_commit(tx_hash)
        assert (cs is None) == (cm is None)
        if cs is not None:
            assert [
                (c.validator_address, c.signature) for c in cs.commits
            ] == [(c.validator_address, c.signature) for c in cm.commits]


# ---- epoch restage: zero in-run compiles ------------------------------


def test_mesh_epoch_restage_zero_recompile():
    """Prewarm a mesh verifier, verify, rotate the validator set mid-run
    (same cardinality: an epoch rotation), verify again — every dispatch
    stays inside the prewarmed shape set."""
    vals, seeds = make_valset(4)
    # single-rung ladder: full prewarm is ONE mesh-4 shape — ("fused",
    # 32, 32), the same shape test_mesh_parity_randomized[4] compiles, so
    # in-suite this test rides that jit cache instead of paying 3 compiles
    verifier = DeviceVoteVerifier(vals, buckets=(32,), mesh=make_mesh(4))
    registry = ShapeWarmRegistry(verifier)
    registry.prewarm(full=True)

    msgs, sigs, vidx, slot = make_batch(vals, seeds, n_txs=6)
    r1 = verifier.verify_and_tally(msgs, sigs, vidx, slot, 6)
    assert r1.valid.any()

    # rotation: 4 NEW keys, same set size -> same table/power shapes
    new_seeds = [hashlib.sha256(b"rot%d" % i).digest() for i in range(4)]
    new_pubs = [host_ed.public_key_from_seed(s) for s in new_seeds]
    new_vals = ValidatorSet(
        [Validator.from_pub_key(p, 10) for p in new_pubs]
    )
    seed_by_pub = dict(zip(new_pubs, new_seeds))
    new_seeds = [seed_by_pub[v.pub_key] for v in new_vals.validators]
    assert verifier.restage(new_vals)

    msgs2, sigs2, vidx2, slot2 = make_batch(new_vals, new_seeds, n_txs=5)
    r2 = verifier.verify_and_tally(msgs2, sigs2, vidx2, slot2, 5)
    scalar = ScalarVoteVerifier(new_vals)
    r2_s = scalar.verify_and_tally(msgs2, sigs2, vidx2, slot2, 5)
    np.testing.assert_array_equal(r2_s.valid, r2.valid)
    np.testing.assert_array_equal(r2_s.stake, r2.stake.astype(np.int64))

    assert registry.cold_shapes() == [], (
        "epoch restage compiled a new shape mid-run"
    )


# ---- host-prep pool parity --------------------------------------------


def test_host_pool_compact_prep_parity():
    """Pooled prepare_compact == serial prepare_compact, field for field,
    at a size above the pool threshold and with adversarial rows."""
    vals, seeds = make_valset(4)
    n = 600  # > _POOL_MIN_ROWS, not worker-divisible
    msgs, sigs, vidx, _ = make_batch(
        vals, seeds, n_txs=150, corrupt=("ok", "flip", "wrongkey", "badidx")
    )
    msgs, sigs, vidx = msgs[:n], sigs[:n], vidx[:n]
    epoch = ed25519_batch.EpochTables([v.pub_key for v in vals.validators])

    serial = ed25519_batch.prepare_compact(msgs, sigs, vidx, epoch)
    pool = HostPrepPool(4, name="hostprep-test")
    try:
        pooled = ed25519_batch.prepare_compact(
            msgs, sigs, vidx, epoch, pool=pool
        )
        stats = pool.stats()
        assert stats["jobs_total"] > 0, "pool never ran a shard"
    finally:
        pool.close()
    for field in ("s_nibbles", "h_nibbles", "val_idx", "r_y", "r_sign",
                  "pre_ok"):
        np.testing.assert_array_equal(
            getattr(serial, field), getattr(pooled, field), err_msg=field
        )


def test_engine_pooled_sign_assembly_parity():
    """A >=256-vote drain through an engine with host_prep_workers set
    takes the pooled sign-bytes assembly and still matches the scalar
    golden path."""
    pvs, vals = make_pvs(4)
    txs = [b"hp%d=%d" % (i, i) for i in range(80)]  # 80*4 = 320 votes
    stream = [sign_vote(pv, tx) for tx in txs for pv in pvs]

    flow_s, mem_s, _, store_s, app_s = make_threaded_engine(
        vals, use_device=False
    )
    for tx in txs:
        mem_s.check_tx(tx)
    for v in stream:
        flow_s.try_add_vote(v.copy())

    flow_p, mem_p, pool_p, store_p, app_p = make_threaded_engine(
        vals, use_device=False, host_prep_workers=4, max_batch=1024
    )
    for tx in txs:
        mem_p.check_tx(tx)
    for v in stream:  # queue the whole corpus BEFORE start: one big drain
        pool_p.check_tx(v)
    flow_p.start()
    try:
        assert _wait_quiescent(flow_p, pool_p), "pooled engine never drained"
        # capture BEFORE stop(): an engine-owned pool is closed and nulled
        # on stop (bench/profile_host read pipeline_stats pre-stop too)
        stats = flow_p.pipeline_stats()
        assert flow_p._host_pool is not None
        pool_stats = flow_p._host_pool.stats()
    finally:
        flow_p.stop()

    assert stats["host_prep_workers"] == 4
    assert pool_stats["jobs_total"] > 0, (
        "drain never took the pooled assembly path"
    )
    assert app_p.tx_count == app_s.tx_count
    assert app_p.state == app_s.state
    assert app_p.digest == app_s.digest
    for tx in txs:
        tx_hash = hashlib.sha256(tx).hexdigest().upper()
        cs = store_s.load_tx_commit(tx_hash)
        cp = store_p.load_tx_commit(tx_hash)
        assert cs is not None and cp is not None
        assert [
            (c.validator_address, c.signature) for c in cs.commits
        ] == [(c.validator_address, c.signature) for c in cp.commits]
