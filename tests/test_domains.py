"""PRNG domain registry (utils.domains): byte-identity pins.

The registry centralizes every domain-separation tag; migrating the use
sites onto it was required to be a ZERO behavior change — the same
(seed, inputs) must derive the exact streams shipped before the
migration. These tests pin each derivation against digests computed
inline from the HISTORICAL byte layouts, so a registry edit (or a
refactor of a use site's suffix packing) that would fork a seeded
schedule fails here, not months later as a quorum mismatch.
"""

import hashlib
import random

import pytest

from txflow_tpu.utils.domains import (
    COMMITTEE_V1,
    FAULTPLAN_LINK,
    NETEM_LINK,
    SCENARIO_AXIS,
    _register,
    registered_domains,
)

# ---------------------------------------------------------------------------
# registry mechanics
# ---------------------------------------------------------------------------


def test_tag_bytes_are_the_historical_literals():
    # the exact bytes that prefixed each stream BEFORE the registry
    # existed; changing any of these forks every schedule it seeds
    assert COMMITTEE_V1 == b"txflow/committee/v1"
    assert SCENARIO_AXIS == b"scenario"
    assert FAULTPLAN_LINK == b"faultplan"
    assert NETEM_LINK == b"netem"


def test_registered_domains_snapshot():
    doms = registered_domains()
    assert doms["committee-sampler"] == COMMITTEE_V1
    assert doms["scenario-axis"] == SCENARIO_AXIS
    assert doms["faultplan-link"] == FAULTPLAN_LINK
    assert doms["netem-link"] == NETEM_LINK
    assert len(set(doms.values())) == len(doms), "tags must be pairwise distinct"
    # a snapshot, not the live table
    doms["committee-sampler"] = b"mutated"
    assert registered_domains()["committee-sampler"] == COMMITTEE_V1


def test_register_rejects_duplicate_name_and_tag():
    with pytest.raises(ValueError, match="duplicate domain name"):
        _register("committee-sampler", b"totally-new-tag")
    with pytest.raises(ValueError, match="already registered"):
        _register("totally-new-name", b"scenario")
    # neither failed attempt leaked into the table
    assert "totally-new-name" not in registered_domains()
    assert registered_domains()["committee-sampler"] == COMMITTEE_V1


# ---------------------------------------------------------------------------
# use-site byte identity (the zero-behavior-change acceptance)
# ---------------------------------------------------------------------------


def test_committee_seed_unchanged():
    from txflow_tpu.committee.sampler import SEED_DOMAIN, committee_seed

    assert SEED_DOMAIN is COMMITTEE_V1  # re-export intact
    h = hashlib.sha256()
    h.update(b"txflow/committee/v1")
    h.update(b"|")
    h.update(b"chain-x")
    h.update(b"|")
    h.update((5).to_bytes(8, "big"))
    assert committee_seed("chain-x", 5) == h.digest()


def test_axis_seed_unchanged():
    from txflow_tpu.scenario.spec import axis_rng, axis_seed

    digest = hashlib.sha256(b"scenario|3|weather|wan").digest()
    want = int.from_bytes(digest[:8], "little")
    assert axis_seed(3, "weather", "wan") == want
    assert axis_rng(3, "weather", "wan").random() == random.Random(want).random()


def test_faultplan_link_stream_unchanged():
    from txflow_tpu.faults.plan import FaultPlan, FaultSpec

    plan = FaultPlan(FaultSpec(seed=7))
    digest = hashlib.sha256(b"faultplan|7|n0|n1").digest()
    want = random.Random(int.from_bytes(digest[:8], "little"))
    got = plan._link_rng("n0", "n1")
    assert [got.random() for _ in range(4)] == [want.random() for _ in range(4)]
    # per-link cache: same stream object on re-lookup
    assert plan._link_rng("n0", "n1") is got


def test_netem_link_stream_unchanged_and_disjoint_from_faultplan():
    from txflow_tpu.netem.shaper import LinkShaper

    shaper = LinkShaper(profile="lan", seed=7)
    digest = hashlib.sha256(b"netem|7|n0|n1").digest()
    # netem historically packed its seed int big-endian (faultplan is
    # little-endian) — part of the layout the migration must not touch
    want = random.Random(int.from_bytes(digest[:8], "big"))
    got = shaper._link_rng("n0", "n1")
    assert [got.random() for _ in range(4)] == [want.random() for _ in range(4)]
    # same (seed, link) under the OTHER domain is a different stream:
    # the shaper never consumes or perturbs chaos draws
    fp = hashlib.sha256(b"faultplan|7|n0|n1").digest()
    assert digest[:8] != fp[:8]
