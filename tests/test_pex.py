"""PEX / addrbook tests: address persistence and network-wide peer
discovery from a single seed address (reference PEX + addrbook,
node/node.go:507-552).
"""

import conftest  # noqa: F401

import hashlib
import time

from txflow_tpu.node.node import Node, NodeConfig
from txflow_tpu.p2p.pex import AddressBook, PEXReactor
from txflow_tpu.types.priv_validator import MockPV
from txflow_tpu.types.validator import Validator, ValidatorSet
from txflow_tpu.utils.config import test_config as make_test_config

CHAIN_ID = "test-pex"


def wait_until(pred, timeout=30.0, poll=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return False


def test_address_book_persistence(tmp_path):
    path = str(tmp_path / "addrbook.json")
    book = AddressBook(path)
    assert book.add("n1", "127.0.0.1", 1234)
    assert not book.add("n1", "127.0.0.1", 1234)  # no-op
    assert book.add("n1", "127.0.0.1", 4321)  # update
    assert book.add("n2", "10.0.0.2", 999)
    book2 = AddressBook(path)  # reload from disk
    assert book2.get("n1") == ("127.0.0.1", 4321)
    assert book2.size() == 2


def build_node(i, pvs, vs):
    return Node(
        node_id=f"pex-node{i}",
        chain_id=CHAIN_ID,
        val_set=vs,
        app=__import__(
            "txflow_tpu.abci.kvstore", fromlist=["KVStoreApplication"]
        ).KVStoreApplication(),
        priv_val=pvs[i],
        node_config=NodeConfig(
            config=make_test_config(), use_device_verifier=False,
            enable_consensus=False,
        ),
    )


def test_pex_discovers_full_mesh_from_one_seed():
    """4 nodes with TCP listeners; node0's address seeds the others'
    books; PEX advertisement + the ensure-peers loop converge the network
    to a full mesh, and a tx then commits everywhere."""
    pvs = [MockPV(hashlib.sha256(b"pex-%d" % i).digest()) for i in range(4)]
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs])
    by_addr = {pv.get_address(): pv for pv in pvs}
    pvs_sorted = [by_addr[v.address] for v in vs]
    nodes = [build_node(i, pvs_sorted, vs) for i in range(4)]
    books = []
    try:
        for n in nodes:
            book = AddressBook()
            books.append(book)
            n.switch.add_reactor("pex", PEXReactor(book))
            n.start()
            n.switch.listen_tcp("127.0.0.1", 0)
        seed_host, seed_port = nodes[0].switch.listen_addr
        for i in range(1, 4):
            books[i].add("pex-node0", seed_host, seed_port)

        # discovery: every node ends up connected to every other
        assert wait_until(
            lambda: all(n.switch.n_peers() == 3 for n in nodes), timeout=30
        ), f"peer counts: {[n.switch.n_peers() for n in nodes]}"
        # books learned everyone's listen address
        assert all(b.size() >= 3 for b in books)

        # the discovered mesh actually carries traffic
        tx = b"pex=v"
        nodes[1].broadcast_tx(tx)
        assert wait_until(lambda: all(n.is_committed(tx) for n in nodes))
    finally:
        for n in nodes:
            n.stop()


def test_pex_mesh_stable_with_secret_connections():
    """Authenticated transport: advertised node ids must equal verified-key
    addresses, or the ensure-peers loop redials known peers forever (r3
    review finding). Converge a 3-node keyed mesh, then assert the SAME
    peer objects stay connected across several ensure-loop ticks."""
    pvs = [MockPV(hashlib.sha256(b"spex-%d" % i).digest()) for i in range(3)]
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs])
    by_addr = {pv.get_address(): pv for pv in pvs}
    pvs_sorted = [by_addr[v.address] for v in vs]
    nodes = []
    books = []
    try:
        for i in range(3):
            cfg = make_test_config()
            n = Node(
                node_id=f"spex-node{i}",  # overridden by the key-derived id
                chain_id=CHAIN_ID,
                val_set=vs,
                app=__import__(
                    "txflow_tpu.abci.kvstore", fromlist=["KVStoreApplication"]
                ).KVStoreApplication(),
                priv_val=pvs_sorted[i],
                node_config=NodeConfig(
                    config=cfg,
                    use_device_verifier=False,
                    enable_consensus=False,
                    node_key_seed=hashlib.sha256(b"spex-key-%d" % i).digest(),
                    # this test wires its OWN book/reactor below; a keyed
                    # node would otherwise auto-register PEX (ch 0x00)
                    pex=False,
                ),
            )
            nodes.append(n)
            book = AddressBook()
            books.append(book)
            n.switch.add_reactor("pex", PEXReactor(book))
            n.start()
            n.switch.listen_tcp("127.0.0.1", 0)
        seed_host, seed_port = nodes[0].switch.listen_addr
        for i in range(1, 3):
            books[i].add(nodes[0].switch.node_id, seed_host, seed_port)

        assert wait_until(
            lambda: all(n.switch.n_peers() == 2 for n in nodes), timeout=30
        ), f"peer counts: {[n.switch.n_peers() for n in nodes]}"
        # stability: no churn across several ensure-loop ticks
        stable = [frozenset(id(p) for p in n.switch.peers()) for n in nodes]
        time.sleep(2.0)  # > 3 ensure intervals
        assert all(n.switch.n_peers() == 2 for n in nodes)
        assert [
            frozenset(id(p) for p in n.switch.peers()) for n in nodes
        ] == stable, "peer churn under authenticated PEX"

        tx = b"spex=v"
        nodes[1].broadcast_tx(tx)
        assert wait_until(lambda: all(n.is_committed(tx) for n in nodes))
    finally:
        for n in nodes:
            n.stop()
