"""TxVote sign bytes + wire encoding tests (mirrors reference types/vote_test.go,
with vectors regenerated for the actual CanonicalTxVote shape — the reference's
own vectors are stale copies of upstream Vote vectors, per SURVEY.md section 0)."""

import time

from txflow_tpu.codec import amino
from txflow_tpu.crypto import ed25519
from txflow_tpu.crypto.hash import address_hash, tx_hash, tx_key
from txflow_tpu.types import (
    MAX_VOTE_BYTES,
    MockPV,
    TxVote,
    canonical_sign_bytes,
    decode_tx_vote,
    encode_tx_vote,
)

# 2017-12-25T03:00:01.234Z, the reference's example timestamp.
STAMP_NS = 1514170801 * 1_000_000_000 + 234_000_000


def example_vote() -> TxVote:
    return TxVote(
        height=12345,
        tx_hash=tx_hash(b"tx_hash"),
        tx_key=tx_key(b"tx_hash"),
        timestamp_ns=STAMP_NS,
        validator_address=address_hash(b"validator_address"),
    )


def test_sign_bytes_structure():
    vote = example_vote()
    sb = vote.sign_bytes("test_chain_id")
    # Length-prefixed.
    total, pos = amino.read_uvarint(sb)
    assert pos + total == len(sb)
    r = amino.AminoReader(sb, pos)
    # Field 1: height fixed64.
    fnum, typ3 = r.read_field_key()
    assert (fnum, typ3) == (1, amino.TYP3_8BYTE)
    assert r.read_fixed64() == 12345
    # Field 2: tx hash string (64 hex chars).
    fnum, typ3 = r.read_field_key()
    assert (fnum, typ3) == (2, amino.TYP3_BYTELEN)
    assert r.read_bytes().decode() == tx_hash(b"tx_hash")
    # Field 3: TxKey — ALWAYS 32 zero bytes (canonicalization drops the key).
    fnum, typ3 = r.read_field_key()
    assert (fnum, typ3) == (3, amino.TYP3_BYTELEN)
    assert r.read_bytes() == bytes(32)
    # Field 4: timestamp.
    fnum, typ3 = r.read_field_key()
    assert (fnum, typ3) == (4, amino.TYP3_BYTELEN)
    assert amino.decode_time_body(r.read_bytes()) == STAMP_NS
    # Field 5: chain id.
    fnum, typ3 = r.read_field_key()
    assert (fnum, typ3) == (5, amino.TYP3_BYTELEN)
    assert r.read_bytes() == b"test_chain_id"
    assert r.eof()


def test_sign_bytes_empty_vote():
    # Height 0 and empty tx hash elided; TxKey + timestamp present.
    sb = canonical_sign_bytes("", 0, "", STAMP_NS)
    total, pos = amino.read_uvarint(sb)
    r = amino.AminoReader(sb, pos)
    fnum, typ3 = r.read_field_key()
    assert fnum == 3  # first non-elided field is TxKey
    r.read_bytes()
    fnum, _ = r.read_field_key()
    assert fnum == 4
    r.read_bytes()
    assert r.eof()


def test_sign_bytes_pinned_vector():
    # Pinned regression vector: any change to the canonical encoding breaks
    # every signature in the network.
    sb = canonical_sign_bytes("test_chain", 1, "AB", 1_000_000_000)
    want = bytes(
        [0x3F]  # total length 63: 9 (height) + 4 (hash) + 34 (key) + 4 (ts) + 12 (chain)
        + [0x09] + [1, 0, 0, 0, 0, 0, 0, 0]  # height fixed64 = 1
        + [0x12, 0x02] + list(b"AB")  # tx hash
        + [0x1A, 0x20] + [0] * 32  # zero TxKey
        + [0x22, 0x02, 0x08, 0x01]  # timestamp {seconds: 1}
        + [0x2A, 0x0A] + list(b"test_chain")
    )
    assert sb == want


def test_sign_and_verify():
    pv = MockPV()
    vote = example_vote()
    vote.validator_address = pv.get_address()
    pv.sign_tx_vote("test_chain_id", vote)
    assert vote.verify("test_chain_id", pv.get_pub_key()) is None
    # Wrong chain id fails.
    assert vote.verify("other_chain", pv.get_pub_key()) is not None
    # Wrong pubkey fails on address check.
    other = MockPV()
    assert vote.verify("test_chain_id", other.get_pub_key()) == (
        "invalid validator address"
    )


def test_broken_signer_rejected():
    pv = MockPV(break_tx_vote_signing=True)
    vote = example_vote()
    vote.validator_address = pv.get_address()
    pv.sign_tx_vote("test_chain_id", vote)
    assert vote.verify("test_chain_id", pv.get_pub_key()) == "invalid signature"


def test_wire_roundtrip():
    pv = MockPV()
    vote = example_vote()
    vote.validator_address = pv.get_address()
    pv.sign_tx_vote("test_chain_id", vote)
    enc = encode_tx_vote(vote)
    dec = decode_tx_vote(enc)
    assert dec == vote
    assert vote.size() == len(enc)


def test_max_vote_bytes():
    # A fully-populated vote must fit in the reference's 223-byte cap.
    pv = MockPV()
    vote = example_vote()
    vote.validator_address = pv.get_address()
    pv.sign_tx_vote("test_chain_id", vote)
    assert vote.size() <= MAX_VOTE_BYTES


def test_validate_basic():
    pv = MockPV()
    vote = example_vote()
    vote.validator_address = pv.get_address()
    pv.sign_tx_vote("test_chain_id", vote)
    assert vote.validate_basic() is None
    bad = vote.copy()
    bad.height = -1
    assert bad.validate_basic() is not None
    bad = vote.copy()
    bad.validator_address = b"\x00"
    assert bad.validate_basic() is not None
    bad = vote.copy()
    bad.signature = None
    assert bad.validate_basic() is not None
    bad = vote.copy()
    bad.signature = bytes(65)
    assert bad.validate_basic() is not None


def test_timestamp_now_default():
    before = time.time_ns()
    vote = TxVote(1, "AB", bytes(32))
    assert before <= vote.timestamp_ns <= time.time_ns()
