"""Self-healing liveness layer driven against live LocalNets.

The tentpole scenario: a seeded chaos partition heals MID-RUN and every
node reaches commit parity with the anti-entropy re-walk disabled — so
recovery is attributable to the health layer (quorum-stall watchdog
re-offers + peer-score-driven evict/reconnect cycles), with zero node
restarts. Satellites: consensus-channel chaos liveness, crash-under-chaos
exactly-once replay, and verifier-counter surfacing over RPC.
"""

import collections
import hashlib
import json
import time
import urllib.request

import pytest

from txflow_tpu.abci.kvstore import KVStoreApplication
from txflow_tpu.faults import ChaosRouter, FaultSpec, FlakyVerifier
from txflow_tpu.health import HealthConfig
from txflow_tpu.node.localnet import LocalNet
from txflow_tpu.types import MockPV, Validator, ValidatorSet
from txflow_tpu.utils.config import test_config as make_test_config
from txflow_tpu.verifier import ResilientVoteVerifier, ScalarVoteVerifier


def wait_until(pred, timeout=20.0, poll=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return False


def rpc_get(addr, path):
    host, port = addr
    with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=30) as r:
        return json.loads(r.read().decode())


# an aggressive profile so the drills resolve in seconds: fast ticks,
# quick staleness, shallow eviction floor, sub-second reconnect backoff
FAST_HEAL = HealthConfig(
    tick_interval=0.1,
    stall_timeout=0.8,
    stale_after=0.6,
    min_sends_for_stale=2,
    score_floor=-2.0,
    reconnect_base=0.2,
    reconnect_cap=1.0,
    seed=7,
)


# ------------------------------------------------ tentpole acceptance


def test_partition_heals_via_watchdog_and_reconnects():
    """2/2 partition starves quorum on both sides; after heal() the net
    reaches commit parity WITHOUT the reactors' anti-entropy re-walk
    (regossip effectively off) and without restarting any node: the
    stall watchdog re-offers votes+txs past sender suppression, and
    peer scoring evicts the black-holed links and re-dials them."""
    chaos = ChaosRouter(FaultSpec(seed=11))
    net = LocalNet(
        4,
        use_device_verifier=False,
        fault_plan=chaos,
        regossip_interval=60.0,  # longer than the test: health layer only
        health_config=FAST_HEAL,
    )
    net.start()
    try:
        pre = b"pre-partition=v"
        net.broadcast_tx(pre)
        assert net.wait_all_committed([pre], timeout=30)

        chaos.partition({"node0", "node1"})  # node2/node3: implicit group
        cut = [b"cut-%d=v" % i for i in range(5)]
        for tx in cut:
            net.broadcast_tx(tx, node_index=0)

        # both sides hold < 2/3 stake: the txs stall below quorum and the
        # watchdog + peer scorer must light up while the cut holds
        assert wait_until(
            lambda: net.nodes[0].health.snapshot()["watchdog"]["firings"] > 0
            and net.nodes[0].health.snapshot()["peers"]["evictions"] > 0,
            timeout=15,
        ), net.nodes[0].health.snapshot()
        # degradation is visible: stall onset age keeps growing past the
        # watchdog's own re-arm, flipping the liveness verdict
        assert wait_until(
            lambda: not net.nodes[0].health.snapshot()["healthy"], timeout=15
        )
        assert chaos.stats["partitioned"] > 0

        chaos.heal()
        assert net.wait_all_committed(cut, timeout=60), (
            "health layer must carry the backlog after heal",
            [n.health.snapshot() for n in net.nodes],
        )
        # acceptance: nonzero watchdog firings and score-driven reconnect
        # cycles, observed on a cut-side node, with no restarts
        snap = net.nodes[0].health.snapshot()
        assert snap["watchdog"]["firings"] > 0
        assert snap["peers"]["evictions"] > 0
        total_reconnects = sum(
            n.health.snapshot()["peers"]["reconnects"] for n in net.nodes
        )
        assert total_reconnects > 0
        assert all(n._started for n in net.nodes), "no node may restart"
        # the stalls resolved: verdict recovers on every node
        assert wait_until(
            lambda: all(n.health.snapshot()["healthy"] for n in net.nodes),
            timeout=15,
        )
    finally:
        net.stop()


# --------------------------------- satellite: consensus-channel chaos


def test_consensus_channel_chaos_block_liveness():
    """FaultSpec(channels=None) extends chaos over the consensus channel
    (0x20): dropped push-once state-machine messages are recovered by BFT
    round timeouts, so block production must stay live within the spec's
    own liveness_budget."""
    spec = FaultSpec(
        seed=23,
        drop=0.05,
        delay=0.10,
        delay_max=0.03,
        channels=None,  # every channel, consensus included
        liveness_budget=90.0,
    )
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(
        4,
        use_device_verifier=False,
        enable_consensus=True,
        config=cfg,
        fault_plan=spec,
        health_config=FAST_HEAL,
    )
    net.start()
    try:
        txs = [b"cons-chaos-%d=v" % i for i in range(4)]
        for tx in txs:
            net.broadcast_tx(tx)
        assert net.wait_all_committed(txs, timeout=spec.liveness_budget)
        for node in net.nodes:
            assert node.consensus.wait_for_height(2, timeout=spec.liveness_budget), (
                "block production must stay live under consensus-channel chaos"
            )
    finally:
        net.stop()


# ------------------------------------ satellite: crash under chaos


class CountingKVStore(KVStoreApplication):
    """kvstore recording every delivered tx (exactly-once oracle)."""

    def __init__(self):
        super().__init__()
        self.delivered = collections.Counter()

    def deliver_tx(self, tx):
        self.delivered[bytes(tx)] += 1
        return super().deliver_tx(tx)


def test_crash_and_revive_member_under_chaos(tmp_path):
    """CrashDrill-style kill/rebuild of a durable LocalNet member while a
    FaultPlan keeps dropping/delaying gossip: the revived node rebuilds a
    FRESH app by handshake replay + block catchup, delivering every tx
    exactly once, with the pre-crash commit order as a prefix."""
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(
        4,
        use_device_verifier=False,
        enable_consensus=True,
        config=cfg,
        app_factory=CountingKVStore,
        fault_plan=FaultSpec(seed=31, drop=0.05, delay=0.10, delay_max=0.02),
        health_config=FAST_HEAL,
    )
    net.make_durable(2, str(tmp_path))
    net.start()
    try:
        wave1 = [b"pre-crash-%d=v" % i for i in range(4)]
        for tx in wave1:
            net.broadcast_tx(tx)
        assert net.wait_all_committed(wave1, timeout=60)
        pre_order = net.nodes[2].tx_store.committed_hashes_in_order()
        assert len(pre_order) >= len(wave1)

        net.crash_node(2)
        # the survivors hold 3/4 stake: load continues through the outage
        wave2 = [b"mid-crash-%d=v" % i for i in range(4)]
        for tx in wave2:
            net.broadcast_tx(tx, node_index=0)
        survivors = [net.nodes[i] for i in (0, 1, 3)]
        deadline = time.monotonic() + 60
        for node in survivors:
            for tx in wave2:
                h = hashlib.sha256(tx).hexdigest().upper()
                while not node.tx_store.has_tx(h):
                    assert time.monotonic() < deadline, "survivors stalled"
                    time.sleep(0.01)

        # revive_node rebuilds with a FRESH app and start() immediately
        # handshake-replays the persisted blocks into it — so by the time
        # it returns, wave1 is already (re)delivered, exactly once
        revived = net.revive_node(2)
        assert revived.app is not net.nodes[2] and revived is net.nodes[2]
        assert net.wait_all_committed(wave1 + wave2, timeout=90), (
            "revived node must converge under active chaos"
        )
        # exactly-once: replay + catchup delivered every tx once
        for tx in wave1 + wave2:
            assert revived.app.delivered[tx] == 1, (tx, revived.app.delivered)
        assert not [t for t, c in revived.app.delivered.items() if c > 1]
        # commit-order convergence: what node2 had persisted before the
        # crash is a strict prefix of its post-revival order
        post_order = revived.tx_store.committed_hashes_in_order()
        assert post_order[: len(pre_order)] == pre_order
    finally:
        net.stop()


# --------------------------- satellite: verifier counters over RPC


def test_verifier_counters_surface_in_health_and_status():
    """A demoted ResilientVoteVerifier's counters flow through the
    degraded-mode registry into /health, /status and the metrics
    gauges."""
    pvs = [
        MockPV(hashlib.sha256(b"health-val%d" % i).digest()) for i in range(4)
    ]
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs])
    flaky = FlakyVerifier(ScalarVoteVerifier(vs))
    flaky.failing = True  # device down for the whole test
    resilient = ResilientVoteVerifier(
        flaky,
        fallback=ScalarVoteVerifier(vs),
        max_attempts=2,
        backoff_base=0.001,
        probe_interval=3600.0,  # stay demoted for the whole test
    )
    net = LocalNet(
        4,
        use_device_verifier=False,
        priv_vals=pvs,
        verifier=resilient,
        rpc=True,
        health_config=HealthConfig(tick_interval=0.05),
    )
    net.start()
    try:
        txs = [b"vrf-%d=v" % i for i in range(3)]
        for tx in txs:
            net.broadcast_tx(tx)
        assert net.wait_all_committed(txs, timeout=60), (
            "CPU fallback must keep commits flowing"
        )
        assert resilient.demotions >= 1

        def surfaced():
            h = rpc_get(net.nodes[0].rpc.addr, "/health")["result"]
            v = h.get("verifier") or {}
            return v.get("device_healthy") is False and v.get("demotions", 0) >= 1

        assert wait_until(surfaced, timeout=10)
        health = rpc_get(net.nodes[0].rpc.addr, "/health")["result"]
        v = health["verifier"]
        assert v["fallback_calls"] >= 1 and v["device_failures"] >= 1
        assert "injected device failure" in (v["last_error"] or "")
        status = rpc_get(net.nodes[0].rpc.addr, "/status")["result"]
        assert status["health"]["monitored"] is True
        assert status["health"]["verifier"]["demotions"] >= 1
        # and the Prometheus-side gauges agree
        m = net.nodes[0].health.registry.metrics
        assert m.verifier_demotions.value() >= 1
        assert m.verifier_device_healthy.value() == 0.0
    finally:
        net.stop()


# ------------------------------------------- health off-switch sanity


def test_health_disabled_node_runs_without_monitor():
    net = LocalNet(2, use_device_verifier=False, health=False)
    net.start()
    try:
        assert all(n.health is None for n in net.nodes)
        tx = b"nohealth=v"
        net.broadcast_tx(tx)
        assert net.wait_all_committed([tx], timeout=30)
    finally:
        net.stop()
