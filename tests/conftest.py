"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run against
XLA's host-platform device-count override, per the project testing contract.

Note: the environment's PJRT site hook may pre-register a TPU platform and
pin ``jax_platforms`` before this file runs, so setting the ``JAX_PLATFORMS``
env var is not sufficient — the config must be updated after jax import
(and XLA_FLAGS must be in place before the CPU client is first created).
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# Persistent XLA compile cache, shared with bench.py/__graft_entry__.py:
# many test files independently jit the same bucket-shaped programs, and
# each fresh function object misses the in-memory jit cache even when the
# HLO is identical — the disk cache turns those (and every compile of a
# rerun suite) into loads. On the 1-core CI box this is minutes of wall
# time per tier-1 run.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"),
)
# Runtime lock-order auditing is ON for the whole tier-1 suite (must be
# set before any txflow_tpu module constructs a lock). Opt out of the
# audit by exporting TXFLOW_LOCK_AUDIT=0 explicitly.
os.environ.setdefault("TXFLOW_LOCK_AUDIT", "1")
# Lockset race auditing (analysis/racegraph.py) rides on the lock audit:
# every declared shared field's accesses are checked Eraser-style across
# the whole suite, and the sessionfinish gate below fails the run on any
# race report. Opt out with TXFLOW_RACE_AUDIT=0.
os.environ.setdefault("TXFLOW_RACE_AUDIT", "1")

import jax

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, (
    "test contract requires an 8-device virtual CPU mesh, got "
    f"{jax.devices()}"
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running soak scenarios (tier-1 runs -m 'not slow')"
    )
    if os.environ.get("TXFLOW_LOCK_AUDIT") == "1":
        from txflow_tpu.analysis.lockgraph import install_probes

        install_probes()


# -- tier-1 time-budget audit -------------------------------------------
#
# Tier-1 runs ``-m 'not slow'`` under a hard wall-clock timeout, so a
# single unmarked test that balloons past the per-test budget silently
# eats the whole suite's headroom. The audit records call-phase durations
# and fails the RUN (without un-passing the tests) when an unmarked test
# exceeds TXFLOW_TIER1_TEST_BUDGET seconds — the fix is either to speed
# the test up or to mark it ``slow`` and move it out of tier-1.

_TIER1_BUDGET = float(os.environ.get("TXFLOW_TIER1_TEST_BUDGET", "120"))
_durations: dict = {}
_slow_marked: set = set()


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.get_closest_marker("slow") is not None:
            _slow_marked.add(item.nodeid)


def pytest_runtest_logreport(report):
    if report.when == "call":
        _durations[report.nodeid] = report.duration


def _lock_audit_gate(session):
    """Fail the RUN (without un-passing tests) if the runtime lock-order
    auditor observed a cycle in the acquisition graph or a lock held
    across a declared blocking call anywhere in the suite."""
    if os.environ.get("TXFLOW_LOCK_AUDIT") != "1":
        return
    from txflow_tpu.analysis.lockgraph import default_auditor

    report = default_auditor().report()
    cycles = report["cycles"]
    blocking = report["blocking_violations"]
    if not cycles and not blocking:
        return
    lines = ["runtime lock audit: violations observed during the suite:"]
    for cyc in cycles:
        lines.append(f"  lock-order cycle: {' -> '.join(cyc)}")
    for bv in blocking:
        lines.append(
            f"  blocking call {bv['desc']!r} while holding "
            f"{bv['held']} (thread {bv['thread']})"
        )
        if bv.get("stack"):
            lines.append(f"    at: {bv['stack']}")
    tr = session.config.pluginmanager.get_plugin("terminalreporter")
    if tr is not None:
        tr.section("runtime lock audit", sep="=")
        for line in lines:
            tr.write_line(line)
    else:
        print("\n".join(lines))
    if session.exitstatus == 0:
        session.exitstatus = 1


def _race_audit_gate(session):
    """Fail the RUN on any lockset race report, and dump the full field
    summary to .race_audit.json (repo root) for `tools/lint.py
    --race-report` — mirrors the lock-audit gate above."""
    if os.environ.get("TXFLOW_RACE_AUDIT") != "1":
        return
    if os.environ.get("TXFLOW_LOCK_AUDIT") != "1":
        return  # locksets come from the lock audit; nothing was recorded
    import json

    from txflow_tpu.analysis.racegraph import default_race_auditor

    report = default_race_auditor().report()
    out = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".race_audit.json",
    )
    try:
        with open(out, "w") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
    except OSError:
        pass
    races = report["races"]
    if not races:
        return
    lines = ["runtime race audit: lockset violations observed during the suite:"]
    for r in races:
        lines.append(
            f"  {r['field']}: unlocked {r['access']} at {r['site']} "
            f"(thread {r['thread']}) races {r['other_site']} "
            f"(thread {r['other_thread']})"
        )
        if r.get("stack"):
            lines.append(f"    at: {r['stack']}")
    tr = session.config.pluginmanager.get_plugin("terminalreporter")
    if tr is not None:
        tr.section("runtime race audit", sep="=")
        for line in lines:
            tr.write_line(line)
    else:
        print("\n".join(lines))
    if session.exitstatus == 0:
        session.exitstatus = 1


def pytest_sessionfinish(session, exitstatus):
    _lock_audit_gate(session)
    _race_audit_gate(session)
    offenders = sorted(
        (
            (dur, nodeid)
            for nodeid, dur in _durations.items()
            if dur > _TIER1_BUDGET and nodeid not in _slow_marked
        ),
        reverse=True,
    )
    if not offenders:
        return
    tr = session.config.pluginmanager.get_plugin("terminalreporter")
    lines = [
        "tier-1 marker audit: unmarked tests exceeded the "
        f"{_TIER1_BUDGET:g}s budget (mark them `slow` or speed them up):"
    ] + [f"  {dur:8.1f}s  {nodeid}" for dur, nodeid in offenders]
    if tr is not None:
        tr.section("tier-1 time budget", sep="=")
        for line in lines:
            tr.write_line(line)
    else:
        print("\n".join(lines))
    if session.exitstatus == 0:
        session.exitstatus = 1
