"""Test configuration: force an 8-device virtual CPU mesh before JAX import.

Multi-chip hardware is not available in CI; sharding tests run against
XLA's host-platform device-count override, per the project testing contract.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
