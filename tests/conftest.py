"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run against
XLA's host-platform device-count override, per the project testing contract.

Note: the environment's PJRT site hook may pre-register a TPU platform and
pin ``jax_platforms`` before this file runs, so setting the ``JAX_PLATFORMS``
env var is not sufficient — the config must be updated after jax import
(and XLA_FLAGS must be in place before the CPU client is first created).
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, (
    "test contract requires an 8-device virtual CPU mesh, got "
    f"{jax.devices()}"
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running soak scenarios (tier-1 runs -m 'not slow')"
    )
