"""Evidence capture tests: equivocation (conflicting signed votes) is
verified, pooled, surfaced via the event bus, and gossiped across a
LocalNet — the capability the reference leaves as a TODO for the fast
path (types/vote_set.go:123-125) and imports wholesale for the block path
(node/node.go:354-367).
"""

import conftest  # noqa: F401

import hashlib
import time

from txflow_tpu.node import LocalNet
from txflow_tpu.pool.evidence import EvidencePool
from txflow_tpu.types import TxVote
from txflow_tpu.types.block_vote import PREVOTE, BlockVote
from txflow_tpu.types.evidence import (
    DuplicateBlockVoteEvidence,
    decode_evidence,
    encode_evidence,
)
from txflow_tpu.types.priv_validator import MockPV
from txflow_tpu.types.validator import Validator, ValidatorSet
from txflow_tpu.utils.events import EventEvidence

CHAIN_ID = "test-evidence"


def make_valset(n=4):
    pvs = [MockPV(hashlib.sha256(b"ev-%d" % i).digest()) for i in range(n)]
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs])
    by_addr = {pv.get_address(): pv for pv in pvs}
    return vs, [by_addr[v.address] for v in vs]


def conflicting_tx_votes(pv, tx=b"dup=1"):
    key = hashlib.sha256(tx).digest()

    def vote(ts):
        v = TxVote(
            height=0,
            tx_hash=key.hex().upper(),
            tx_key=key,
            timestamp_ns=ts,
            validator_address=pv.get_address(),
        )
        pv.sign_tx_vote(CHAIN_ID, v)
        return v

    # different timestamps -> different sign bytes -> different signatures
    return vote(1_000), vote(2_000)


def conflicting_block_votes(pv, height=3, round_=0):
    out = []
    for block_id in (b"\x01" * 32, b"\x02" * 32):
        v = BlockVote(
            height=height,
            round=round_,
            type=PREVOTE,
            block_id=block_id,
            validator_address=pv.get_address(),
        )
        pv.sign_block_vote(CHAIN_ID, v)
        out.append(v)
    return out


def wait_until(pred, timeout=20.0, poll=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return False


def test_evidence_verify_and_wire_roundtrip():
    vs, pvs = make_valset()
    ba, bb = conflicting_block_votes(pvs[1])
    bev = DuplicateBlockVoteEvidence(ba, bb)
    assert bev.verify(CHAIN_ID, pvs[1].get_pub_key()) is None
    # hash is order-independent
    assert bev.hash() == DuplicateBlockVoteEvidence(bb, ba).hash()
    bev2 = decode_evidence(encode_evidence(bev))
    assert bev2.hash() == bev.hash()
    # same block twice is not conflicting
    same = DuplicateBlockVoteEvidence(ba, ba.copy())
    assert same.verify(CHAIN_ID, pvs[1].get_pub_key()) is not None
    # tampered signature breaks it
    bad = DuplicateBlockVoteEvidence(ba.copy(), bb.copy())
    bad.vote_b.signature = bytes(64)
    assert bad.verify(CHAIN_ID, pvs[1].get_pub_key()) is not None


def test_evidence_pool_admission_and_dedup():
    vs, pvs = make_valset()
    events = []
    from txflow_tpu.utils.events import EventBus

    bus = EventBus()
    bus.subscribe_callback(EventEvidence, events.append)
    pool = EvidencePool(CHAIN_ID, lambda: vs, event_bus=bus)

    a, b = conflicting_block_votes(pvs[0])
    ev = DuplicateBlockVoteEvidence(a, b)
    added, err = pool.add(ev)
    assert added and err is None
    assert pool.size() == 1 and pool.has(ev)
    assert len(events) == 1

    # dedup: same pair again (either order) is a no-op
    added, err = pool.add(DuplicateBlockVoteEvidence(b, a))
    assert not added and err is None
    assert pool.size() == 1

    # invalid evidence rejected with an error
    stranger = MockPV(hashlib.sha256(b"stranger").digest())
    sa, sb = conflicting_block_votes(stranger)
    added, err = pool.add(DuplicateBlockVoteEvidence(sa, sb))
    assert not added and err is not None  # unknown validator

    # committed evidence cannot re-enter
    pool.mark_committed([ev])
    assert pool.size() == 0
    added, err = pool.add(ev)
    assert not added and err is None


def test_byzantine_double_block_vote_captured_and_gossiped():
    """A validator signs two conflicting prevotes for the same height and
    round (block-path equivocation): the node that sees the pair captures
    evidence and gossip carries it to every node's pool."""
    from txflow_tpu.utils.config import test_config as make_test_config

    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(
        4, use_device_verifier=False, enable_consensus=True, config=cfg
    )
    net.start()
    try:
        byz_pv = net.priv_vals[0]
        cs = net.nodes[1].consensus

        def inject_conflicts():
            # heights churn (empty blocks): re-sign for the CURRENT round
            # until a pair lands in time to conflict
            rs = cs.round_state()
            for block_id in (b"\x0a" * 32, b"\x0b" * 32):
                v = BlockVote(
                    height=rs.height,
                    round=rs.round,
                    type=PREVOTE,
                    block_id=block_id,
                    validator_address=byz_pv.get_address(),
                )
                byz_pv.sign_block_vote(net.chain_id, v)
                cs.add_vote(v, peer_id="byz")
            return net.nodes[1].evidence_pool.size() >= 1

        assert wait_until(inject_conflicts, timeout=30, poll=0.05)
        assert wait_until(
            lambda: all(n.evidence_pool.size() >= 1 for n in net.nodes),
            timeout=30,
        ), "evidence must reach every node"
        ev = net.nodes[3].evidence_pool.pending()[0]
        assert ev.validator_address == byz_pv.get_address()
    finally:
        net.stop()
