"""Evidence capture tests: equivocation (conflicting signed votes) is
verified, pooled, surfaced via the event bus, and gossiped across a
LocalNet — the capability the reference leaves as a TODO for the fast
path (types/vote_set.go:123-125) and imports wholesale for the block path
(node/node.go:354-367).
"""

import conftest  # noqa: F401

import hashlib
import time

from txflow_tpu.node import LocalNet
from txflow_tpu.pool.evidence import EvidencePool
from txflow_tpu.types import TxVote
from txflow_tpu.types.block_vote import PREVOTE, BlockVote
from txflow_tpu.types.evidence import (
    DuplicateBlockVoteEvidence,
    decode_evidence,
    encode_evidence,
)
from txflow_tpu.types.priv_validator import MockPV
from txflow_tpu.types.validator import Validator, ValidatorSet
from txflow_tpu.utils.events import EventEvidence

CHAIN_ID = "test-evidence"


def make_valset(n=4):
    pvs = [MockPV(hashlib.sha256(b"ev-%d" % i).digest()) for i in range(n)]
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs])
    by_addr = {pv.get_address(): pv for pv in pvs}
    return vs, [by_addr[v.address] for v in vs]


def conflicting_tx_votes(pv, tx=b"dup=1"):
    key = hashlib.sha256(tx).digest()

    def vote(ts):
        v = TxVote(
            height=0,
            tx_hash=key.hex().upper(),
            tx_key=key,
            timestamp_ns=ts,
            validator_address=pv.get_address(),
        )
        pv.sign_tx_vote(CHAIN_ID, v)
        return v

    # different timestamps -> different sign bytes -> different signatures
    return vote(1_000), vote(2_000)


def conflicting_block_votes(pv, height=3, round_=0):
    out = []
    for block_id in (b"\x01" * 32, b"\x02" * 32):
        v = BlockVote(
            height=height,
            round=round_,
            type=PREVOTE,
            block_id=block_id,
            validator_address=pv.get_address(),
        )
        pv.sign_block_vote(CHAIN_ID, v)
        out.append(v)
    return out


def wait_until(pred, timeout=20.0, poll=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return False


def test_evidence_verify_and_wire_roundtrip():
    vs, pvs = make_valset()
    ba, bb = conflicting_block_votes(pvs[1])
    bev = DuplicateBlockVoteEvidence(ba, bb)
    assert bev.verify(CHAIN_ID, pvs[1].get_pub_key()) is None
    # hash is order-independent
    assert bev.hash() == DuplicateBlockVoteEvidence(bb, ba).hash()
    bev2 = decode_evidence(encode_evidence(bev))
    assert bev2.hash() == bev.hash()
    # same block twice is not conflicting
    same = DuplicateBlockVoteEvidence(ba, ba.copy())
    assert same.verify(CHAIN_ID, pvs[1].get_pub_key()) is not None
    # tampered signature breaks it
    bad = DuplicateBlockVoteEvidence(ba.copy(), bb.copy())
    bad.vote_b.signature = bytes(64)
    assert bad.verify(CHAIN_ID, pvs[1].get_pub_key()) is not None


def test_evidence_pool_admission_and_dedup():
    vs, pvs = make_valset()
    events = []
    from txflow_tpu.utils.events import EventBus

    bus = EventBus()
    bus.subscribe_callback(EventEvidence, events.append)
    pool = EvidencePool(CHAIN_ID, lambda: vs, event_bus=bus)

    a, b = conflicting_block_votes(pvs[0])
    ev = DuplicateBlockVoteEvidence(a, b)
    added, err = pool.add(ev)
    assert added and err is None
    assert pool.size() == 1 and pool.has(ev)
    assert len(events) == 1

    # dedup: same pair again (either order) is a no-op
    added, err = pool.add(DuplicateBlockVoteEvidence(b, a))
    assert not added and err is None
    assert pool.size() == 1

    # invalid evidence rejected with an error
    stranger = MockPV(hashlib.sha256(b"stranger").digest())
    sa, sb = conflicting_block_votes(stranger)
    added, err = pool.add(DuplicateBlockVoteEvidence(sa, sb))
    assert not added and err is not None  # unknown validator

    # committed evidence cannot re-enter
    pool.mark_committed([ev])
    assert pool.size() == 0
    added, err = pool.add(ev)
    assert not added and err is None


def test_byzantine_double_block_vote_captured_and_gossiped():
    """A validator signs two conflicting prevotes for the same height and
    round (block-path equivocation): the node that sees the pair captures
    evidence and gossip carries it to every node's pool."""
    from txflow_tpu.utils.config import test_config as make_test_config

    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(
        4, use_device_verifier=False, enable_consensus=True, config=cfg
    )
    net.start()
    try:
        byz_pv = net.priv_vals[0]
        cs = net.nodes[1].consensus

        def inject_conflicts():
            # heights churn (empty blocks): re-sign for the CURRENT round
            # until a pair lands in time to conflict
            rs = cs.round_state()
            for block_id in (b"\x0a" * 32, b"\x0b" * 32):
                v = BlockVote(
                    height=rs.height,
                    round=rs.round,
                    type=PREVOTE,
                    block_id=block_id,
                    validator_address=byz_pv.get_address(),
                )
                byz_pv.sign_block_vote(net.chain_id, v)
                cs.add_vote(v, peer_id="byz")
            return net.nodes[1].evidence_pool.size() >= 1

        assert wait_until(inject_conflicts, timeout=30, poll=0.05)
        assert wait_until(
            lambda: all(n.evidence_pool.size() >= 1 for n in net.nodes),
            timeout=30,
        ), "evidence must reach every node"
        ev = net.nodes[3].evidence_pool.pending()[0]
        assert ev.validator_address == byz_pv.get_address()
    finally:
        net.stop()


def test_evidence_commits_into_blocks_and_drains_pools():
    """The full loop the reference wires via the evidence pool + blocks
    (state/execution.go:103 reaps PendingEvidence; ApplyBlock marks it
    committed): captured equivocation is proposed inside a block, the
    block header commits to it (EvidenceHash), every node's app sees the
    byzantine validator in BeginBlock, and all pools stop gossiping it."""
    from txflow_tpu.utils.config import test_config as make_test_config

    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(
        4, use_device_verifier=False, enable_consensus=True, config=cfg
    )
    seen_byzantine = [set() for _ in net.nodes]
    for i, node in enumerate(net.nodes):
        orig = node.app.begin_block

        def hook(req, _orig=orig, _seen=seen_byzantine[i]):
            for addr, h in req.byzantine_validators:
                _seen.add(addr)
            return _orig(req)

        node.app.begin_block = hook
    net.start()
    try:
        byz_pv = net.priv_vals[0]
        cs = net.nodes[1].consensus

        def inject_conflicts():
            rs = cs.round_state()
            for block_id in (b"\x0c" * 32, b"\x0d" * 32):
                v = BlockVote(
                    height=rs.height,
                    round=rs.round,
                    type=PREVOTE,
                    block_id=block_id,
                    validator_address=byz_pv.get_address(),
                )
                byz_pv.sign_block_vote(net.chain_id, v)
                cs.add_vote(v, peer_id="byz")
            return net.nodes[1].evidence_pool.size() >= 1

        assert wait_until(inject_conflicts, timeout=30, poll=0.05)
        # a later block must carry the evidence and commit it everywhere
        assert wait_until(
            lambda: all(
                byz_pv.get_address() in seen for seen in seen_byzantine
            ),
            timeout=60,
        ), "every node's app must see the byzantine validator via BeginBlock"
        assert wait_until(
            lambda: all(n.evidence_pool.size() == 0 for n in net.nodes),
            timeout=30,
        ), "committed evidence must drain from every pool"
        # the stored block carries it, hash-committed
        store = net.nodes[2].block_store
        found = None
        for h in range(1, store.height() + 1):
            blk = store.load_block(h)
            if blk is not None and blk.evidence:
                found = blk
                break
        assert found is not None, "no stored block carries the evidence"
        from txflow_tpu.types.block import evidence_root

        assert found.header.evidence_hash == evidence_root(found.evidence)
        assert found.evidence[0].validator_address == byz_pv.get_address()
    finally:
        net.stop()


def test_proposal_filters_unusable_evidence_and_validation_rejects_recommit():
    """(a) Proposals exclude evidence a block could not validate (future
    height, validator no longer in the set) so a proposer can never wedge
    itself; (b) validation rejects evidence that already committed, so one
    offense cannot be punished twice (r3 review findings)."""
    from txflow_tpu.abci.kvstore import KVStoreApplication
    from txflow_tpu.abci.proxy import AppConns
    from txflow_tpu.pool.mempool import Mempool
    from txflow_tpu.state.execution import BlockExecutor
    from txflow_tpu.state.state import state_from_genesis
    from txflow_tpu.state.store import StateStore
    from txflow_tpu.store.db import MemDB
    from txflow_tpu.types.genesis import GenesisDoc, GenesisValidator
    from txflow_tpu.utils.config import test_config as make_test_config

    vs, pvs = make_valset(4)
    gen = GenesisDoc(
        chain_id=CHAIN_ID,
        validators=[GenesisValidator(v.pub_key, v.voting_power) for v in vs],
    )
    state = state_from_genesis(gen)
    proxy = AppConns(KVStoreApplication())
    pool = EvidencePool(CHAIN_ID, lambda: vs)
    exec_ = BlockExecutor(
        StateStore(MemDB()), proxy.consensus,
        Mempool(make_test_config().mempool, proxy_app_conn=proxy.mempool),
        Mempool(make_test_config().mempool),
        evidence_pool=pool,
    )

    def equivocation(pv, height):
        votes = []
        for bid in (b"\x0e" * 32, b"\x0f" * 32):
            v = BlockVote(height=height, round=0, type=PREVOTE, block_id=bid,
                          validator_address=pv.get_address())
            pv.sign_block_vote(CHAIN_ID, v)
            votes.append(v)
        return DuplicateBlockVoteEvidence(*votes)

    good = equivocation(pvs[0], 1)
    future = equivocation(pvs[1], 999)  # far beyond the next height
    outsider_pv = MockPV(hashlib.sha256(b"gone").digest())
    unknown = equivocation(outsider_pv, 1)
    assert pool.add(good)[0]
    assert pool.add(future)[0]
    pool._pending[unknown.hash()] = unknown  # bypass: "was valid, then left"

    block = exec_.create_proposal_block(1, state, None, vs.get_by_index(0).address)
    assert [ev.hash() for ev in block.evidence] == [good.hash()]
    assert not pool.has(unknown) or unknown.hash() not in pool._pending

    # the proposed block validates...
    assert exec_.validate_block(state, block) is None
    # ...but once its evidence is committed, re-proposing it is rejected
    pool.mark_committed([good])
    block2 = state.make_block(1, [], [], None, vs.get_by_index(0).address,
                              evidence=[good])
    err = exec_.validate_block(state, block2)
    assert err == "evidence already committed", err


def test_evidence_budget_and_durable_committed_markers():
    """(a) Proposals reap at most MAX_EVIDENCE_PER_BLOCK and validation
    rejects over-budget or stale evidence — a byzantine validator signing
    unlimited distinct equivocation pairs cannot flood blocks (r3 advisor
    medium; reference state/validation.go:135-148). (b) Committed-evidence
    markers persist in the shared db, so the already-committed rejection
    survives a restart (r3 advisor low; reference checks a persisted
    store, state/validation.go:148)."""
    from txflow_tpu.abci.kvstore import KVStoreApplication
    from txflow_tpu.abci.proxy import AppConns
    from txflow_tpu.pool.mempool import Mempool
    from txflow_tpu.state.execution import MAX_EVIDENCE_PER_BLOCK, BlockExecutor
    from txflow_tpu.state.state import state_from_genesis
    from txflow_tpu.state.store import StateStore
    from txflow_tpu.store.db import MemDB
    from txflow_tpu.types.genesis import GenesisDoc, GenesisValidator
    from txflow_tpu.utils.config import test_config as make_test_config

    vs, pvs = make_valset(4)
    gen = GenesisDoc(
        chain_id=CHAIN_ID,
        validators=[GenesisValidator(v.pub_key, v.voting_power) for v in vs],
    )
    state = state_from_genesis(gen)
    proxy = AppConns(KVStoreApplication())
    db = MemDB()
    pool = EvidencePool(CHAIN_ID, lambda: vs, db=db)
    exec_ = BlockExecutor(
        StateStore(MemDB()), proxy.consensus,
        Mempool(make_test_config().mempool, proxy_app_conn=proxy.mempool),
        Mempool(make_test_config().mempool),
        evidence_pool=pool,
    )

    def equivocation(pv, i):
        votes = []
        for bid in (b"\x01" * 32, hashlib.sha256(b"alt-%d" % i).digest()):
            v = BlockVote(height=1, round=0, type=PREVOTE, block_id=bid,
                          validator_address=pv.get_address())
            pv.sign_block_vote(CHAIN_ID, v)
            votes.append(v)
        return DuplicateBlockVoteEvidence(*votes)

    # one byzantine validator floods the pool past the per-block budget
    flood = [equivocation(pvs[1], i) for i in range(MAX_EVIDENCE_PER_BLOCK + 10)]
    for ev in flood:
        added, err = pool.add(ev)
        assert added, err

    block = exec_.create_proposal_block(1, state, None, vs.get_by_index(0).address)
    assert len(block.evidence) == MAX_EVIDENCE_PER_BLOCK
    assert exec_.validate_block(state, block) is None

    over = pool.pending()[: MAX_EVIDENCE_PER_BLOCK + 1]
    bad = state.make_block(1, [], [], None, vs.get_by_index(0).address,
                           evidence=over)
    err = exec_.validate_block(state, bad)
    assert err and "too much evidence" in err, err

    # durable markers: a restarted pool sharing the db still refuses
    committed = flood[0]
    pool.mark_committed([committed])
    reborn = EvidencePool(CHAIN_ID, lambda: vs, db=db)
    assert reborn.is_committed(committed)
    added, err = reborn.add(committed)
    assert not added and err is None
    recommit = state.make_block(1, [], [], None, vs.get_by_index(0).address,
                                evidence=[committed])
    exec_.evidence_pool = reborn
    err = exec_.validate_block(state, recommit)
    assert err == "evidence already committed", err
