"""Network weather (netem/) + adaptive peer transport tests.

Covers the ISSUE-11 surface: the real non-blocking TCP try_send, the
shaper/ChaosRouter PRNG stream discipline (domain-separated seeded
streams that survive reconnects), the bounded send queue + RTT/loss
estimator, weather-corrupted frames being caught (never committed) and
the link healing through the roster re-dial, a flapping reconnect drill
with bounded dial attempts, and the tier-1 gate over the real-socket
WAN scenario matrix (tools/soak.py --wan-matrix --smoke).
"""

import conftest  # noqa: F401

import os
import queue
import socket
import subprocess
import sys
import threading
import time

import pytest

from txflow_tpu.faults.plan import FaultPlan, FaultSpec
from txflow_tpu.netem import LinkShaper, NetProfile, PROFILES, get_profile
from txflow_tpu.node import LocalNet
from txflow_tpu.p2p.adaptive import (
    BoundedSendQueue,
    NetTransportConfig,
    PeerNetEstimator,
)
from txflow_tpu.p2p.transport import TCPConnection, tcp_connect, tcp_listen


def wait_until(pred, timeout=30.0, poll=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return False


# -- satellite 1: real non-blocking TCP try_send ---------------------------


def test_tcp_try_send_lock_busy_returns_false():
    srv = tcp_listen("127.0.0.1", 0)
    host, port = srv.getsockname()
    accepted = []
    t = threading.Thread(
        target=lambda: accepted.append(srv.accept()), daemon=True
    )
    t.start()
    client = tcp_connect(host, port)
    t.join(timeout=5)
    try:
        # a concurrent sender holds the write lock: try_send must bail
        # immediately instead of queueing behind it
        assert client._wlock.acquire(blocking=False)
        try:
            assert client.try_send(0x41, b"x") is False
        finally:
            client._wlock.release()
        # lock free again: the frame goes out whole
        assert client.try_send(0x41, b"hello") is True
        conn = TCPConnection(accepted[0][0])
        assert conn.recv(timeout=5) == (0x41, b"hello")
        conn.close()
    finally:
        client.close()
        srv.close()


def test_tcp_try_send_backpressure_and_framing():
    """With the kernel send buffer full, try_send refuses (False, nothing
    written) instead of blocking; frames that DID report True arrive
    intact and in order once the receiver drains — no torn frames."""
    srv = tcp_listen("127.0.0.1", 0)
    host, port = srv.getsockname()
    accepted = []
    t = threading.Thread(
        target=lambda: accepted.append(srv.accept()), daemon=True
    )
    t.start()
    raw = socket.create_connection((host, port))
    raw.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 16384)
    client = TCPConnection(raw)
    t.join(timeout=5)
    server_sock, _ = accepted[0]
    payload = os.urandom(65536)
    results = []

    def drain_later():
        time.sleep(1.0)
        conn = TCPConnection(server_sock)
        while True:
            try:
                chan, msg = conn.recv(timeout=2)
            except Exception:
                break
            got.append((chan, msg))

    got: list = []
    drainer = threading.Thread(target=drain_later, daemon=True)
    drainer.start()
    try:
        for _ in range(40):
            results.append(client.try_send(0x41, payload))
        assert True in results, "try_send never succeeded on a fresh socket"
        assert False in results, "try_send never refused on a full buffer"
        client.close()  # EOF lets the drainer finish
        drainer.join(timeout=15)
        assert len(got) == sum(1 for r in results if r)
        assert all(chan == 0x41 and msg == payload for chan, msg in got)
    finally:
        client.close()
        srv.close()


# -- satellite 2: PRNG stream discipline -----------------------------------


def test_shaper_does_not_perturb_chaos_streams():
    """FaultPlan decisions are identical whether or not a LinkShaper is
    drawing from its own stream on the same link names: the two PRNG
    domains (``faultplan|``/``netem|``) are disjoint by construction."""
    spec = FaultSpec(drop=0.1, duplicate=0.1, delay=0.2, seed=9)
    plan_a = FaultPlan(spec)
    seq_a = [plan_a.decide("n0", "n1", 0x30) for _ in range(200)]

    plan_b = FaultPlan(FaultSpec(drop=0.1, duplicate=0.1, delay=0.2, seed=9))
    shaper = LinkShaper("lossy-edge", seed=9)
    rng = shaper._link_rng("n0", "n1")
    seq_b = []
    for _ in range(200):
        rng.random()  # interleave shaper draws with chaos decisions
        seq_b.append(plan_b.decide("n0", "n1", 0x30))
    assert seq_a == seq_b


class _SinkConn:
    """Inner connection stub: records delivered frames, never blocks."""

    def __init__(self):
        self.frames = []
        self.closed = False

    def send(self, chan_id, msg, timeout=None):
        self.frames.append((chan_id, bytes(msg)))
        return True

    def try_send(self, chan_id, msg):
        return self.send(chan_id, msg)

    def close(self):
        self.closed = True

    def is_closed(self):
        return self.closed


_DET_KEYS = ("frames", "dropped", "duplicated", "corrupted", "reordered")
_DET_PROFILE = NetProfile(
    "det-test",
    latency_ms=0.1,
    loss=0.2,
    duplicate=0.1,
    corrupt=0.1,
    reorder=0.1,
    reorder_extra_ms=1.0,
)


def _det_stats(*conns):
    return {k: sum(c.stats[k] for c in conns) for k in _DET_KEYS}


def test_shaper_stream_reproducible_and_survives_reconnect():
    """Same seed => same per-link decision stream; and the stream picks
    up where it left off across a reconnect (the rng lives on the
    LinkShaper keyed by (src, dst), not on the connection)."""
    msgs = [b"frame-%03d" % i for i in range(120)]

    # one continuous connection
    s1 = LinkShaper(_DET_PROFILE, seed=4)
    c1 = s1.wrap(_SinkConn(), "a", "b")
    for m in msgs:
        c1.send(0x30, m)
    baseline = _det_stats(c1)
    assert baseline["dropped"] > 0 and baseline["corrupted"] > 0

    # same seed, reconnect after 60 frames: cumulative stream identical
    s2 = LinkShaper(_DET_PROFILE, seed=4)
    c2a = s2.wrap(_SinkConn(), "a", "b")
    for m in msgs[:60]:
        c2a.send(0x30, m)
    c2a.close()
    c2b = s2.wrap(_SinkConn(), "a", "b")
    for m in msgs[60:]:
        c2b.send(0x30, m)
    assert _det_stats(c2a, c2b) == baseline

    # different link names draw from a DIFFERENT stream (domain includes
    # src/dst), and a different seed diverges too
    s3 = LinkShaper(_DET_PROFILE, seed=4)
    c3 = s3.wrap(_SinkConn(), "b", "a")
    for m in msgs:
        c3.send(0x30, m)
    assert _det_stats(c3) != baseline
    s4 = LinkShaper(_DET_PROFILE, seed=5)
    c4 = s4.wrap(_SinkConn(), "a", "b")
    for m in msgs:
        c4.send(0x30, m)
    assert _det_stats(c4) != baseline
    for c in (c1, c2b, c3, c4):
        c.close()


def test_profiles_declared_as_data():
    assert {"lan", "intercontinental", "lossy-edge", "congested", "flapping"} <= set(
        PROFILES
    )
    assert get_profile("lan").latency_ms < get_profile("intercontinental").latency_ms
    with pytest.raises(KeyError, match="known"):
        get_profile("dial-up")


# -- adaptive transport units ----------------------------------------------


def test_bounded_send_queue_oldest_bulk_drop():
    q = BoundedSendQueue(3)
    q.put((1, 0, 0x30, b"bulk-old"))
    q.put((1, 1, 0x30, b"bulk-new"))
    q.put((0, 2, 0x20, b"prio-a"))
    # full: the newcomer (priority) evicts the OLDEST bulk frame
    q.put((0, 3, 0x20, b"prio-b"))
    assert q.dropped == 1 and q.qsize() == 3
    # everything queued outranks a bulk newcomer except bulk itself: a
    # worse-than-everything newcomer is rejected outright
    q.put((1, 4, 0x30, b"bulk-next"))  # evicts bulk-new
    assert q.dropped == 2
    with pytest.raises(queue.Full):
        q.put((2, 5, 0x32, b"worst"))
    # drain order: most-important lane first, FIFO within a lane
    drained = [q.get(timeout=0) for _ in range(3)]
    assert [d[3] for d in drained] == [b"prio-a", b"prio-b", b"bulk-next"]
    with pytest.raises(queue.Empty):
        q.get(timeout=0.01)


def test_estimator_rtt_loss_quarantine_hysteresis():
    cfg = NetTransportConfig(
        ping_timeout=1.0, quarantine_after=2, requalify_after=2
    )
    est = PeerNetEstimator(cfg)
    assert est.send_timeout() == cfg.max_send_timeout  # no sample yet
    p = est.next_ping(100.0)
    est.on_pong(p, 100.05)
    assert abs(est.srtt - 0.05) < 1e-9
    assert est.send_timeout() < cfg.max_send_timeout

    # every probe times out: the loss EWMA climbs past the quarantine
    # threshold, and two consecutive bad ticks (hysteresis) quarantine
    t = 101.0
    while est.loss < cfg.quarantine_loss:
        ping = est.next_ping(t)
        assert ping is not None
        est.expire(t + 2.0)
        t += 2.0
    est.note_tick(backlog=0)
    assert not est.quarantined  # one bad tick is not enough
    est.note_tick(backlog=0)
    assert est.quarantined and est.transitions == 1

    # recovery: pongs decay the loss estimate; two good ticks requalify
    while est.loss >= cfg.quarantine_loss:
        ping = est.next_ping(t)
        est.on_pong(ping, t + 0.05)
        t += 1.0
    est.note_tick(backlog=0)
    est.note_tick(backlog=0)
    assert not est.quarantined and est.transitions == 2
    snap = est.snapshot()
    assert snap["pongs"] >= 1 and snap["ping_timeouts"] >= 1


# -- weather-corrupted frames: caught, never committed, link heals ---------


def test_corruption_caught_never_committed_and_link_heals():
    """A shaper-corrupted frame makes the receiving reactor fail decode
    and stop the peer (verify-before-apply: the bytes never land). The
    net must still commit everything identically on every node, and the
    torn link must heal through the scoreboard's roster re-dial (in-proc
    nets have no PEX ensure-loop)."""
    shaper = LinkShaper(
        NetProfile("corrupty", latency_ms=1.0, corrupt=0.08), seed=3
    )
    net = LocalNet(3, use_device_verifier=False, netem=shaper)
    net.start()
    try:
        txs = [b"weather-%d=v" % i for i in range(20)]
        for tx in txs:
            net.broadcast_tx(tx)
        assert net.wait_all_committed(txs, timeout=90)
        snap = shaper.snapshot()
        assert snap["total"]["corrupted"] >= 1, snap["total"]
        # identical committed sets: nothing corrupted ever landed
        logs = [
            {h for _seq, h in n.tx_store.committed_range(0, n.tx_store.seq_count())}
            for n in net.nodes
        ]
        assert logs[0] == logs[1] == logs[2]
        # the corrupt-frame teardown(s) heal: full mesh again
        assert wait_until(
            lambda: all(n.switch.n_peers() == 2 for n in net.nodes), timeout=30
        ), [n.switch.n_peers() for n in net.nodes]
    finally:
        net.stop()


# -- satellite 3: flapping reconnect drill ---------------------------------


def test_flapping_reconnect_drill_bounded_dials():
    """Under flapping weather a torn link heals through the jittered-
    backoff roster re-dial without a dial storm, and once the weather
    clears the mesh converges and stays converged."""
    net = LocalNet(3, use_device_verifier=False, netem="flapping", netem_seed=3)
    net.start()
    try:
        txs = [b"flap-%d=v" % i for i in range(10)]
        for tx in txs:
            net.broadcast_tx(tx)
        assert net.wait_all_committed(txs, timeout=90)

        # tear one link down mid-weather (the flap schedule itself drops
        # frames silently; the teardown is the reconnect drill)
        victim = net.nodes[1].switch.get_peer("node0")
        assert victim is not None
        net.nodes[1].switch.stop_peer(victim, reason="drill: weather teardown")
        assert wait_until(
            lambda: all(n.switch.n_peers() == 2 for n in net.nodes), timeout=30
        ), [n.switch.n_peers() for n in net.nodes]
        heals = sum(n.health.registry.peer_reconnects for n in net.nodes)
        assert heals >= 1

        # calm weather: still converged, dial attempts stayed bounded
        net.set_net_profile("lan")
        more = [b"calm-%d=v" % i for i in range(5)]
        for tx in more:
            net.broadcast_tx(tx)
        assert net.wait_all_committed(more, timeout=60)
        fails = sum(n.health.registry.reconnect_failures for n in net.nodes)
        assert fails <= 20, f"dial storm: {fails} failed re-dial attempts"
    finally:
        net.stop()


# -- composability: ChaosRouter + LinkShaper on the same net ---------------


def test_chaos_and_shaper_compose():
    net = LocalNet(
        3,
        use_device_verifier=False,
        fault_plan=FaultSpec(drop=0.05, seed=5),
        netem="lan",
        netem_seed=5,
    )
    net.start()
    try:
        txs = [b"compose-%d=v" % i for i in range(10)]
        for tx in txs:
            net.broadcast_tx(tx)
        assert net.wait_all_committed(txs, timeout=90)
        assert net.shaper.snapshot()["total"]["frames"] > 0
        assert len(net.chaos.plan.trace) > 0  # chaos really intercepted
    finally:
        net.stop()


# -- satellite 5a: tier-1 gate over the real-socket scenario matrix --------


def test_wan_matrix_smoke_gate():
    """tools/soak.py --wan-matrix --smoke end to end: a 3-process net
    over real TCP walked through all five weather profiles live, with
    zero admitted-tx loss, prefix-stable commit logs, cross-node
    committed-set equality, per-profile latency budgets, and a healed
    mesh — exit 1 on any breach."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "tools/soak.py", "--wan-matrix", "--smoke"],
        cwd=repo,
        capture_output=True,
        text=True,
        timeout=110,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, f"\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    assert "SOAK OK (wan-matrix)" in proc.stdout
    # the machine-readable contract (scenario/harness.py): exactly one
    # final RESULT JSON line, exit code 0 <=> ok
    import json as _json

    last = [l for l in proc.stdout.strip().splitlines() if l][-1]
    assert last.startswith("RESULT "), proc.stdout
    payload = _json.loads(last[len("RESULT "):])
    assert payload["ok"] is True and payload["breach"] is None
    assert payload["mode"] == "wan-matrix" and len(payload["scenarios"]) >= 5
