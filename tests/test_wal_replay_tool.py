"""WAL inspection tool (reference consensus/replay_file.go analog)."""

import conftest  # noqa: F401

import io
import sys

from txflow_tpu.consensus.ticker import TimeoutInfo
from txflow_tpu.consensus.types import Proposal
from txflow_tpu.consensus.wal import ConsensusWAL
from txflow_tpu.tools import wal_replay
from txflow_tpu.types.block_vote import BlockVote


def _write_sample(path):
    w = ConsensusWAL(str(path))
    w.write_timeout(TimeoutInfo(duration=0.1, height=1, round=0, step=1))
    w.write_proposal(
        Proposal(height=1, round=0, pol_round=-1, block_hash=b"\x01" * 32,
                 timestamp_ns=1, signature=b"\x02" * 64),
        None,
    )
    w.write_vote(
        BlockVote(height=1, round=0, type=1, block_id=b"\x01" * 32,
                  timestamp_ns=2, validator_address=b"\x03" * 20,
                  signature=b"\x04" * 64)
    )
    w.write_end_height(1)
    w.write_timeout(TimeoutInfo(duration=0.1, height=2, round=0, step=1))
    w.close()


def test_read_and_summarize(tmp_path):
    path = tmp_path / "cons.wal"
    _write_sample(path)
    frames = wal_replay.read_wal(str(path))
    assert [f["t"] for f in frames] == [
        "timeout", "proposal", "vote", "end_height", "timeout",
    ]
    assert frames[1]["height"] == 1 and frames[1]["has_block"] is False
    summary = wal_replay.summarize(str(path))
    assert summary[1] == {"proposals": 1, "votes": 1, "timeouts": 1, "ended": True}
    assert summary[2]["ended"] is False


def test_cli_output(tmp_path, capsys):
    path = tmp_path / "cons.wal"
    _write_sample(path)
    assert wal_replay.main([str(path), "--limit", "2"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 2
    assert wal_replay.main([str(path), "--summary"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 2  # heights 1 and 2
    assert wal_replay.main([]) == 2
