"""Pool mechanics, mirroring reference txvotepool/ and mempool/ tests:
availability firing (:122), serial reap vs counter app (:166), WAL (:253),
max-msg-size boundary (:305), byte accounting (:357), cache LRU behavior.
"""

import hashlib
import os

import pytest

from txflow_tpu.abci import AppConns, CounterApplication, KVStoreApplication
from txflow_tpu.pool import (
    ErrMempoolIsFull,
    ErrTxInCache,
    ErrTxTooLarge,
    Mempool,
    TxInfo,
    TxVotePool,
)
from txflow_tpu.pool.txvotepool import vote_key
from txflow_tpu.types import MockPV, TxVote
from txflow_tpu.types.tx_vote import encode_tx_vote
from txflow_tpu.utils.cache import LRUCache
from txflow_tpu.utils.config import MempoolConfig

CHAIN_ID = "txflow-test"


def make_vote(i: int, pv: MockPV | None = None, height: int = 1) -> TxVote:
    pv = pv or MockPV()
    tx = b"tx%d" % i
    vote = TxVote(
        height=height,
        tx_hash=hashlib.sha256(tx).hexdigest().upper(),
        tx_key=hashlib.sha256(tx).digest(),
        timestamp_ns=1700000000_000000000 + i,
        validator_address=pv.get_address(),
    )
    pv.sign_tx_vote(CHAIN_ID, vote)
    return vote


# ---- LRU cache (reference cache_test.go) ----


def test_cache_lru_eviction_and_dedup():
    c = LRUCache(3)
    k = [b"%d" % i for i in range(5)]
    assert c.push(k[0]) and c.push(k[1]) and c.push(k[2])
    assert not c.push(k[0])  # dup
    assert c.push(k[3])  # evicts k[1] (k[0] was refreshed by the dup push)
    assert k[1] not in c and k[0] in c
    c.remove(k[0])
    assert c.push(k[0])


def test_unlocked_lru_cache_matches_locked_and_guards_free_threading():
    """UnlockedLRUCache is semantically the locked cache minus the mutex;
    its lock-freedom is only sound under the GIL, so on a free-threaded
    build the constructor must hand back a locked LRUCache instead."""
    import txflow_tpu.utils.cache as cache_mod
    from txflow_tpu.utils.cache import UnlockedLRUCache

    u, l = UnlockedLRUCache(3), LRUCache(3)
    for key in [b"0", b"1", b"2", b"0", b"3", b"4"]:
        assert u.push(key) == l.push(key)
    assert len(u) == len(l) == 3
    for key in (b"0", b"2", b"3", b"4"):
        assert (key in u) == (key in l)

    # simulate a free-threaded build: construction transparently degrades
    # to the locked implementation (same API, GIL-independent safety).
    # The GIL is a property of the interpreter launch, so it is weighed
    # ONCE at import (_GIL_ENABLED) — patch the constant, not the probe.
    orig = cache_mod._GIL_ENABLED
    cache_mod._GIL_ENABLED = False
    try:
        fallback = UnlockedLRUCache(3)
        assert isinstance(fallback, LRUCache)
        assert fallback.push(b"x") and not fallback.push(b"x")
        assert isinstance(cache_mod.make_lru(3), LRUCache)
    finally:
        cache_mod._GIL_ENABLED = orig

    # make_lru is the one construction seam (txlint unlocked-lru rule):
    # GIL build -> owner-serialized unlocked cache; size<=0 -> NopCache
    assert isinstance(cache_mod.make_lru(3), UnlockedLRUCache)
    assert isinstance(cache_mod.make_lru(0), cache_mod.NopCache)


# ---- TxVotePool ----


def test_votepool_ingest_dedup_and_bytes():
    pool = TxVotePool(MempoolConfig(cache_size=100))
    v = make_vote(0)
    pool.check_tx(v)
    assert pool.size() == 1
    assert pool.txs_bytes() == len(encode_tx_vote(v))
    with pytest.raises(ErrTxInCache):
        pool.check_tx(v, TxInfo(sender_id=7))
    # the duplicate's sender was recorded for gossip suppression
    assert pool.has_sender(vote_key(v), 7)
    pool.remove([vote_key(v)])
    assert pool.size() == 0 and pool.txs_bytes() == 0


def test_votepool_size_cap():
    pool = TxVotePool(MempoolConfig(size=2, cache_size=100))
    pool.check_tx(make_vote(0))
    pool.check_tx(make_vote(1))
    with pytest.raises(ErrMempoolIsFull):
        pool.check_tx(make_vote(2))


def test_votepool_max_msg_size_boundary():
    pool = TxVotePool(MempoolConfig(cache_size=100, max_msg_bytes=64))
    with pytest.raises(ErrTxTooLarge):
        pool.check_tx(make_vote(0))  # a full vote is ~190 bytes > 64-8


def test_votepool_availability_fires_once_per_height():
    pool = TxVotePool(MempoolConfig(cache_size=100))
    ev = pool.txs_available()
    assert not ev.is_set()
    v0, v1 = make_vote(0), make_vote(1)
    pool.check_tx(v0)
    assert ev.is_set()
    pool.check_tx(v1)  # no re-fire needed; still set
    # update to next height re-arms, and fires again since one vote remains
    pool.update(2, [v0])
    assert pool.size() == 1
    assert ev.is_set()


def test_votepool_update_removes_and_caches_committed():
    pool = TxVotePool(MempoolConfig(cache_size=100))
    pv = MockPV()
    votes = [make_vote(i, pv) for i in range(3)]
    for v in votes:
        pool.check_tx(v)
    pool.update(2, votes[:2])
    assert pool.size() == 1
    # committed votes cannot re-enter (cache)
    with pytest.raises(ErrTxInCache):
        pool.check_tx(votes[0])


def test_votepool_wal_replay(tmp_path):
    wal_path = str(tmp_path / "votepool.wal")
    pool = TxVotePool(MempoolConfig(cache_size=100), wal_path=wal_path)
    votes = [make_vote(i) for i in range(4)]
    for v in votes:
        pool.check_tx(v)
    pool.close_wal()
    assert os.path.getsize(wal_path) > 0

    pool2 = TxVotePool(MempoolConfig(cache_size=100), wal_path=wal_path)
    assert pool2.replay_wal() == 4
    assert pool2.size() == 4
    assert [v.signature for _, v in pool2.entries()] == [v.signature for v in votes]


def test_votepool_wal_torn_tail(tmp_path):
    wal_path = str(tmp_path / "votepool.wal")
    pool = TxVotePool(MempoolConfig(cache_size=100), wal_path=wal_path)
    for i in range(3):
        pool.check_tx(make_vote(i))
    pool.close_wal()
    with open(wal_path, "r+b") as f:
        f.truncate(os.path.getsize(wal_path) - 5)  # torn final frame
    pool2 = TxVotePool(MempoolConfig(cache_size=100), wal_path=wal_path)
    assert pool2.replay_wal() == 2


def test_votepool_drain_batch_order_and_skip():
    pool = TxVotePool(MempoolConfig(cache_size=100))
    votes = [make_vote(i) for i in range(5)]
    for v in votes:
        pool.check_tx(v)
    got = pool.drain_batch(3)
    assert [v.signature for _, v in got] == [v.signature for v in votes[:3]]
    skip = {got[0][0]}
    got2 = pool.drain_batch(10, skip=skip)
    assert len(got2) == 4


# ---- Mempool ----


def test_mempool_checktx_via_app_and_get_tx():
    app = KVStoreApplication()
    conns = AppConns(app)
    pool = Mempool(MempoolConfig(cache_size=100), conns.mempool)
    tx = b"k=v"
    pool.check_tx(tx)
    key = hashlib.sha256(tx).digest()
    assert pool.get_tx(key) == tx
    assert pool.get_tx(b"\x00" * 32) is None
    with pytest.raises(ErrTxInCache):
        pool.check_tx(tx)


def test_mempool_serial_counter_rejects_bad_nonce():
    app = CounterApplication(serial=True)
    conns = AppConns(app)
    pool = Mempool(MempoolConfig(cache_size=100), conns.mempool)
    pool.check_tx((0).to_bytes(8, "big"))
    pool.check_tx((1).to_bytes(8, "big"))
    # app state advanced: CheckTx compares against tx_count delivered so far;
    # a nonce below it is rejected and evicted from cache
    app.tx_count = 5
    with pytest.raises(ValueError):
        pool.check_tx((3).to_bytes(8, "big"))
    assert pool.size() == 2


def test_mempool_update_cache_semantics():
    from txflow_tpu.abci.types import ResponseDeliverTx

    pool = Mempool(MempoolConfig(cache_size=100))
    t1, t2 = b"a", b"b"
    pool.check_tx(t1)
    pool.check_tx(t2)
    pool.lock()
    pool.update(2, [t1, t2], [ResponseDeliverTx(code=0), ResponseDeliverTx(code=1)])
    pool.unlock()
    assert pool.size() == 0
    # valid committed tx stays cached; invalid one may be resubmitted
    with pytest.raises(ErrTxInCache):
        pool.check_tx(t1)
    pool.check_tx(t2)


def test_mempool_reap_bytes_and_gas():
    app = KVStoreApplication()
    conns = AppConns(app)
    pool = Mempool(MempoolConfig(cache_size=100), conns.mempool)
    txs = [b"tx-%05d" % i for i in range(10)]
    for t in txs:
        pool.check_tx(t)
    assert pool.reap_max_txs(3) == txs[:3]
    assert pool.reap_max_txs(-1) == txs
    # each tx is 8 bytes, gas 1
    assert pool.reap_max_bytes_max_gas(20, -1) == txs[:2]
    assert pool.reap_max_bytes_max_gas(-1, 4) == txs[:4]


def test_ingest_log_compaction_bounds_memory():
    """The ingest log drops its dead prefix (IngestLogPool._log_compact)
    while stable cursors keep observing every live entry exactly once."""
    from txflow_tpu.pool import base as pool_base
    from txflow_tpu.pool.txvotepool import TxVotePool

    old_threshold = pool_base.COMPACT_THRESHOLD
    pool_base.COMPACT_THRESHOLD = 16
    try:
        pool = TxVotePool(MempoolConfig(size=100000, cache_size=0))
        cursor, seen = 0, 0
        for i in range(200):
            v = TxVote(
                height=1,
                tx_hash="AB",
                tx_key=b"\x00" * 32,
                validator_address=b"x" * 20,
                signature=b"sig-%d" % i,
            )
            pool.check_tx(v)
            if i % 3 == 2:
                items, cursor = pool.entries_from(cursor, limit=1000)
                seen += len(items)
                pool.remove([k for k, _, _, _ in items])
        items, cursor = pool.entries_from(cursor, limit=1000)
        seen += len(items)
        assert seen == 200
        assert len(pool._log) < 2 * pool_base.COMPACT_THRESHOLD
        assert pool._log_base > 150
    finally:
        pool_base.COMPACT_THRESHOLD = old_threshold


# ---- check_tx vs check_tx_many parity (the batched twins must not drift) ----


def _drive_one_by_one(check, items):
    out = []
    for it in items:
        try:
            check(it)
            out.append(None)
        except Exception as e:
            out.append(e)
    return out


def test_votepool_check_tx_many_parity():
    """One ingest sequence — accepts, a duplicate, an oversized vote, a
    pool-full rejection — pushed through check_tx one-by-one and through
    check_tx_many as a batch: identical per-position error types and
    identical final pool state (check_tx_many inlines a non-raising twin
    of _ingest_locked; this is the drift alarm)."""
    pv = MockPV()
    v0, v1, v2, v3 = (make_vote(i, pv) for i in range(4))
    big = make_vote(99, pv)
    big.tx_hash = "A" * 1024  # encodes past max_msg_bytes
    seq = [v0, v1, v0, big, v2, v3]

    def mk():
        return TxVotePool(MempoolConfig(size=3, cache_size=100, max_msg_bytes=256))

    a, b = mk(), mk()
    errs_one = _drive_one_by_one(a.check_tx, seq)
    errs_many = b.check_tx_many(seq)

    want = [None, ErrTxInCache, ErrTxTooLarge, ErrMempoolIsFull]
    assert [type(e) for e in errs_one] == [type(e) for e in errs_many]
    assert [type(e) for e in errs_many] == [
        type(None), type(None), ErrTxInCache, ErrTxTooLarge,
        type(None), ErrMempoolIsFull,
    ], want
    assert a.size() == b.size() == 3
    assert a.txs_bytes() == b.txs_bytes()
    assert [v.signature for _, v in a.entries()] == [
        v.signature for _, v in b.entries()
    ]
    for v in (v0, v1, v2):
        assert a.has(vote_key(v)) and b.has(vote_key(v))
    # rejected votes left no residue in either pool
    for v in (big, v3):
        assert not a.has(vote_key(v)) and not b.has(vote_key(v))


def test_votepool_origin_parity():
    """Ingest-origin stamping through both twins: the sender id frozen on
    an entry at ingest (what invalid-verdict attribution charges) must be
    identical whether the vote arrived via check_tx or check_tx_many, a
    later add_sender must never rewrite it, and a local/unattributed
    ingest must read back as UNKNOWN_PEER_ID (drift alarm for the
    accountable-gossip origin branch of the twins)."""
    from txflow_tpu.pool.txvotepool import UNKNOWN_PEER_ID

    pv = MockPV()
    v0, v1, v2 = (make_vote(i, pv) for i in range(3))

    def mk():
        return TxVotePool(MempoolConfig(size=10, cache_size=100))

    a, b = mk(), mk()
    a.check_tx(v0, tx_info=TxInfo(sender_id=5))
    a.check_tx(v1, tx_info=TxInfo(sender_id=7))
    a.check_tx(v2)  # local: no TxInfo
    b.check_tx_many([v0, v1], tx_info=TxInfo(sender_id=5))
    b.check_tx_many([v2])
    keys = [vote_key(v) for v in (v0, v1, v2)]
    assert a.origins_of(keys) == [5, 7, UNKNOWN_PEER_ID]
    assert b.origins_of(keys) == [5, 5, UNKNOWN_PEER_ID]
    # origin is frozen at ingest: extra senders accumulate, attribution
    # stays with the first relayer
    for p in (a, b):
        p.add_sender(keys[0], 9)
        assert p.origins_of(keys[:1]) == [p.origins_of(keys[:1])[0]]
    assert a.origins_of(keys[:1]) == [5]
    assert b.origins_of(keys[:1]) == [5]
    # unknown keys attribute to nobody
    assert a.origins_of([b"\x00" * 32]) == [UNKNOWN_PEER_ID]


def test_votepool_lane_eviction_parity():
    """Lane-aware ingest through both twins: priority votes land on the
    priority log, and at pool-full a priority vote evicts the oldest
    bulk vote while a bulk vote still bounces — identically via check_tx
    and check_tx_many (drift alarm for the lane/eviction branch)."""
    from txflow_tpu.pool.mempool import LANE_BULK, LANE_PRIORITY

    pv = MockPV()
    bulk = [make_vote(i, pv) for i in range(3)]
    prio = make_vote(50, pv)
    bulk_late = make_vote(51, pv)
    prio_keys = {prio.tx_key}

    def mk():
        p = TxVotePool(MempoolConfig(size=3, cache_size=100))
        p.lane_of_vote = lambda v: (
            LANE_PRIORITY if v.tx_key in prio_keys else LANE_BULK
        )
        return p

    seq = bulk + [bulk_late, prio]  # full -> bulk bounces, priority evicts
    a, b = mk(), mk()
    errs_one = _drive_one_by_one(a.check_tx, seq)
    errs_many = b.check_tx_many(seq)
    assert [type(e) for e in errs_one] == [type(e) for e in errs_many]
    assert [type(e) for e in errs_many] == [
        type(None), type(None), type(None), ErrMempoolIsFull, type(None),
    ]
    for p in (a, b):
        assert p.size() == 3
        assert p.has(vote_key(prio))
        assert not p.has(vote_key(bulk[0]))  # oldest bulk vote evicted
        assert not p.in_cache(vote_key(bulk[0]))  # re-deliverable
        items, _ = p.priority_entries_from(0, limit=10)
        assert [k for k, _v, _h, _s in items] == [vote_key(prio)]
        # ingest-time lane freezing (both twins must stamp it): the
        # priority log + the bulk walk are an exact partition of the
        # live entries, even after the hook's answer changes
        assert p.prio_seq() == 1
        p.lane_of_vote = lambda v: LANE_PRIORITY  # drift: all prio now
        bitems, _ = p.bulk_entries_from(0, limit=10)
        bulk_keys = [k for k, _v, _h, _s in bitems]
        assert vote_key(prio) not in bulk_keys  # frozen prio stays out
        assert set(bulk_keys) == {
            vote_key(bulk[1]), vote_key(bulk[2])
        }  # frozen bulk stays in, despite the hook now saying priority


def test_votepool_wal_degradation_parity(tmp_path):
    """WAL EIO through both twins (drift alarm for the degrade branch):
    a failing WAL append must not raise out of either ingest path, must
    flip wal_degraded identically, and the votes must still land — the
    WAL is a restart-recovery aid, not the admission ledger."""
    from txflow_tpu.utils import failpoints

    pv = MockPV()
    votes = [make_vote(i, pv) for i in range(4)]

    def mk(name):
        p = TxVotePool(MempoolConfig(size=10, cache_size=100))
        p.init_wal(str(tmp_path / name))
        return p

    a, b = mk("one"), mk("many")
    try:
        failpoints.arm("wal.write", after=0)
        errs_one = _drive_one_by_one(a.check_tx, votes)
        errs_many = b.check_tx_many(votes)
    finally:
        failpoints.disarm(None)
    assert [type(e) for e in errs_one] == [type(e) for e in errs_many]
    assert all(e is None for e in errs_one)
    for p in (a, b):
        assert p.wal_degraded
        assert p.wal_errors >= 1
        assert p.size() == 4
        for v in votes:
            assert p.has(vote_key(v))
    assert [v.signature for _, v in a.entries()] == [
        v.signature for _, v in b.entries()
    ]


def test_mempool_check_tx_many_parity():
    """Mempool twin of the votepool parity test: dup, byte-budget full,
    pre_check rejection, and size-cap full must come out of check_tx and
    check_tx_many with the same error types, order, and pool state."""
    import hashlib as _h

    def mk():
        pool = Mempool(MempoolConfig(size=3, cache_size=100, max_txs_bytes=48))
        pool.pre_check = lambda tx: "contains !" if b"!" in tx else None
        return pool

    seq = [b"a=1", b"b=2", b"a=1", b"x" * 64, b"bad!", b"c=3", b"d=4"]
    a, b = mk(), mk()
    errs_one = _drive_one_by_one(a.check_tx, seq)
    errs_many = b.check_tx_many(seq)

    assert [type(e) for e in errs_one] == [type(e) for e in errs_many]
    assert [type(e) for e in errs_many] == [
        type(None), type(None), ErrTxInCache, ErrMempoolIsFull,
        ValueError, type(None), ErrMempoolIsFull,
    ]
    assert a.size() == b.size() == 3
    assert a.txs_bytes() == b.txs_bytes() == 9
    assert [t for _, t in a.entries()] == [t for _, t in b.entries()] == [
        b"a=1", b"b=2", b"c=3"
    ]
    assert a.reap_max_txs(10) == b.reap_max_txs(10)
    # a pre_check rejection must not poison the dedup cache: the same tx
    # is retryable once the pool drains (cache.remove on reject)
    for pool in (a, b):
        assert _h.sha256(b"bad!").digest() not in pool.cache


def test_pool_trace_span_parity():
    """The twins must also agree on tracing: one accepted item = exactly
    one ingest span, duplicates and rejections record nothing — whether
    ingested one-by-one or as a batch, in both pools (sample_rate=1 so
    every tx is sampled)."""
    from txflow_tpu.trace.tracer import Tracer
    from txflow_tpu.utils.config import TraceConfig

    tcfg = TraceConfig(sample_rate=1)

    pv = MockPV()
    v0, v1 = make_vote(0, pv), make_vote(1, pv)
    vseq = [v0, v1, v0]  # accept, accept, dup

    def mk_vp():
        p = TxVotePool(MempoolConfig(size=10, cache_size=100))
        p.tracer = Tracer(tcfg)
        return p

    a, b = mk_vp(), mk_vp()
    _drive_one_by_one(a.check_tx, vseq)
    b.check_tx_many(vseq)
    for p in (a, b):
        names = [s["name"] for s in p.tracer.spans()]
        assert names == ["vote_ingest", "vote_ingest"]
        assert p.tracer.open_count() == 0
    assert [s["tx"] for s in a.tracer.spans()] == [
        s["tx"] for s in b.tracer.spans()
    ]

    tseq = [b"a=1", b"b=2", b"a=1"]  # accept, accept, dup

    def mk_mp():
        p = Mempool(MempoolConfig(size=10, cache_size=100))
        p.tracer = Tracer(tcfg)
        return p

    c, d = mk_mp(), mk_mp()
    _drive_one_by_one(c.check_tx, tseq)
    d.check_tx_many(tseq)
    for p in (c, d):
        names = [s["name"] for s in p.tracer.spans()]
        assert names == ["mempool_ingest", "mempool_ingest"]
        # the mempool also anchors the e2e clock at first sight
        assert len(p.tracer._anchors) == 2
    assert [s["tx"] for s in c.tracer.spans()] == [
        s["tx"] for s in d.tracer.spans()
    ]
