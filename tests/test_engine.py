"""TxFlow engine end-to-end + golden parity (reference txflow/service_test.go
and the SURVEY §4 contract: batched device decisions == scalar reference path).
"""

import hashlib

import numpy as np
import pytest

from txflow_tpu.abci import AppConns, KVStoreApplication
from txflow_tpu.engine import TxExecutor, TxFlow
from txflow_tpu.pool import Mempool, TxVotePool
from txflow_tpu.store import MemDB, TxStore
from txflow_tpu.types import MockPV, TxVote, Validator, ValidatorSet
from txflow_tpu.utils.config import EngineConfig, MempoolConfig
from txflow_tpu.utils.events import EventBus, EventTx
from txflow_tpu.verifier import ScalarVoteVerifier

CHAIN_ID = "txflow-test"
HEIGHT = 1


def make_pvs(n=4):
    pvs = sorted((MockPV() for _ in range(n)), key=lambda p: p.get_address())
    vals = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs])
    by_addr = {pv.get_address(): pv for pv in pvs}
    return [by_addr[v.address] for v in vals], vals


def make_engine(vals, app=None, use_device=True, max_batch=1024, verifier=None):
    conns = AppConns(app or KVStoreApplication())
    mempool = Mempool(MempoolConfig(cache_size=1000), conns.mempool)
    commitpool = Mempool(MempoolConfig(cache_size=1000))
    votepool = TxVotePool(MempoolConfig(cache_size=10000))
    tx_store = TxStore(MemDB())
    bus = EventBus()
    execu = TxExecutor(conns.consensus, mempool, event_bus=bus)
    flow = TxFlow(
        CHAIN_ID,
        HEIGHT,
        vals,
        votepool,
        mempool,
        commitpool,
        execu,
        tx_store,
        config=EngineConfig(max_batch=max_batch, use_device=use_device),
        verifier=verifier,
    )
    return flow, mempool, commitpool, votepool, tx_store, conns.app, bus


def sign_vote(pv, tx: bytes, height=HEIGHT, ts=1700000000_000000000) -> TxVote:
    v = TxVote(
        height=height,
        tx_hash=hashlib.sha256(tx).hexdigest().upper(),
        tx_key=hashlib.sha256(tx).digest(),
        timestamp_ns=ts,
        validator_address=pv.get_address(),
    )
    pv.sign_tx_vote(CHAIN_ID, v)
    return v


def test_end_to_end_commit_on_quorum():
    pvs, vals = make_pvs(4)
    flow, mempool, commitpool, votepool, tx_store, app, bus = make_engine(vals)
    sub = bus.subscribe(EventTx)

    txs = [b"k%d=v%d" % (i, i) for i in range(5)]
    for tx in txs:
        mempool.check_tx(tx)
    for tx in txs:
        for pv in pvs[:3]:  # exactly quorum: 30 >= 27
            votepool.check_tx(sign_vote(pv, tx))

    processed = flow.step()
    assert processed == 15

    # every tx committed: app saw it, commitpool holds it, store certifies it
    assert app.tx_count == 5
    assert app.state[b"k0"] == b"v0"
    assert commitpool.size() == 5
    assert mempool.size() == 0  # removed by executor commit/update
    for tx in txs:
        tx_hash = hashlib.sha256(tx).hexdigest().upper()
        commit = tx_store.load_tx_commit(tx_hash)
        assert commit is not None and len(commit.commits) == 3
    # quorum votes purged from the pool, in-flight sets dropped
    assert votepool.size() == 0
    assert flow.vote_sets == {}
    # commit events fired per tx
    # commit events are fanned out by the executor's event worker thread
    # (off the commit path): collect with a timeout instead of an instant
    # drain
    events = []
    while len(events) < 5:
        ev = sub.get(timeout=5.0)
        assert ev is not None, f"only {len(events)} commit events arrived"
        events.append(ev)
    assert len(events) == 5 and events[0].data.tx == txs[0]


def test_no_commit_below_quorum():
    pvs, vals = make_pvs(4)
    flow, mempool, commitpool, votepool, tx_store, app, _ = make_engine(vals)
    tx = b"under=quorum"
    mempool.check_tx(tx)
    for pv in pvs[:2]:  # 20 < 27
        votepool.check_tx(sign_vote(pv, tx))
    flow.step()
    assert app.tx_count == 0
    assert commitpool.size() == 0
    assert votepool.size() == 2  # votes stay pending
    tx_hash = hashlib.sha256(tx).hexdigest().upper()
    assert flow.vote_sets[tx_hash].stake() == 20
    # third vote arrives in a later batch: quorum crosses using prior stake
    votepool.check_tx(sign_vote(pvs[2], tx))
    flow.step()
    assert app.tx_count == 1
    assert votepool.size() == 0


def test_byzantine_and_invalid_votes_rejected():
    pvs, vals = make_pvs(4)
    flow, mempool, _, votepool, _, app, _ = make_engine(vals)
    tx = b"target=1"
    mempool.check_tx(tx)

    good = sign_vote(pvs[0], tx)
    votepool.check_tx(good)
    # corrupt signature
    bad = sign_vote(pvs[1], tx)
    bad.signature = bad.signature[:-1] + bytes([bad.signature[-1] ^ 1])
    votepool.check_tx(bad)
    # non-validator vote
    stranger = MockPV()
    votepool.check_tx(sign_vote(stranger, tx))
    # conflicting second signature from validator 0 (different timestamp)
    conflict = sign_vote(pvs[0], tx, ts=1700000001_000000000)
    votepool.check_tx(conflict)

    flow.step()
    flow.step()  # second pass clears the conflicting leftover
    assert app.tx_count == 0
    tx_hash = hashlib.sha256(tx).hexdigest().upper()
    assert flow.vote_sets[tx_hash].stake() == 10  # only the good vote counted
    # bad votes were removed from the pool; the good one stays available
    # for gossip until its tx commits (reference purges only on commit)
    assert votepool.size() == 1
    assert votepool.has(__import__("txflow_tpu.pool.txvotepool", fromlist=["vote_key"]).vote_key(good))


def test_late_votes_for_committed_tx_are_dropped():
    pvs, vals = make_pvs(4)
    flow, mempool, _, votepool, tx_store, app, _ = make_engine(vals)
    tx = b"late=vote"
    mempool.check_tx(tx)
    for pv in pvs[:3]:
        votepool.check_tx(sign_vote(pv, tx))
    flow.step()
    assert app.tx_count == 1
    # the 4th vote arrives after commit
    votepool.check_tx(sign_vote(pvs[3], tx))
    flow.step()
    assert votepool.size() == 0
    assert app.tx_count == 1  # not re-committed
    assert flow.vote_sets == {}


def test_batched_matches_scalar_reference_engine():
    """Golden parity: identical commit decisions, app state and stores for a
    shuffled, adversarial vote stream (BASELINE config 4 in miniature)."""
    import random

    rng = random.Random(42)
    pvs, vals = make_pvs(7)  # total 70, quorum 47 -> 5 votes needed
    txs = [b"ptx%d=%d" % (i, i) for i in range(12)]

    stream = []
    for t_i, tx in enumerate(txs):
        n_votes = rng.randint(2, 7)
        voters = rng.sample(range(7), n_votes)
        for vi in voters:
            vote = sign_vote(pvs[vi], tx)
            if rng.random() < 0.15:  # corrupt some
                vote.signature = bytes(64)
            stream.append(vote)
    rng.shuffle(stream)

    # scalar reference engine: one vote at a time through add_vote
    flow_s, mem_s, commit_s, pool_s, store_s, app_s, _ = make_engine(vals, use_device=False)
    for tx in txs:
        mem_s.check_tx(tx)
    for v in stream:
        flow_s.try_add_vote(v.copy())

    # batched device engine: same stream via the pool, uneven batch sizes
    flow_b, mem_b, commit_b, pool_b, store_b, app_b, _ = make_engine(vals, max_batch=17)
    for tx in txs:
        mem_b.check_tx(tx)
    for v in stream:
        try:
            pool_b.check_tx(v)
        except Exception:
            pass
    while flow_b.step():
        pass

    assert app_b.tx_count == app_s.tx_count
    assert app_b.state == app_s.state
    assert app_b.digest == app_s.digest  # commit ORDER identical, not just set
    for tx in txs:
        tx_hash = hashlib.sha256(tx).hexdigest().upper()
        cs, cb = store_s.load_tx_commit(tx_hash), store_b.load_tx_commit(tx_hash)
        assert (cs is None) == (cb is None)
        if cs is not None:
            assert {c.validator_address for c in cs.commits} == {
                c.validator_address for c in cb.commits
            }
    # uncommitted stake identical
    for tx_hash, vs in flow_s.vote_sets.items():
        assert flow_b.vote_sets[tx_hash].stake() == vs.stake()


def test_group_commit_matches_per_tx_commit():
    """EngineConfig.commit_interval > 1 (ABCI Commit fence amortized over a
    group of fast-path txs) must be observably identical to the reference-
    faithful per-tx path: same committed set, same app tx counts, same
    per-tx commit events, pools drained."""
    import hashlib as _h

    from txflow_tpu.node import LocalNet
    from txflow_tpu.utils.config import test_config as make_test_config
    from txflow_tpu.utils.events import EventTx

    results = {}
    for interval in (1, 4):
        cfg = make_test_config()
        cfg.engine.commit_interval = interval
        net = LocalNet(4, use_device_verifier=False, config=cfg)
        events = [[] for _ in net.nodes]
        for i, node in enumerate(net.nodes):
            node.event_bus.subscribe_callback(
                EventTx, (lambda lst: (lambda ev: lst.append(ev.data.tx_hash)))(events[i])
            )
        net.start()
        try:
            txs = [b"gc%d-%d=v" % (interval, i) for i in range(10)]
            for tx in txs:
                net.broadcast_tx(tx)
            assert net.wait_all_committed(txs, timeout=60)
            hashes = sorted(_h.sha256(tx).hexdigest().upper() for tx in txs)
            for i, node in enumerate(net.nodes):
                for h in hashes:
                    assert node.tx_store.load_tx_votes(h), (interval, h)
                assert sorted(events[i]) == hashes, (interval, i)
            results[interval] = {
                "tx_counts": sorted(n.app.tx_count for n in net.nodes),
                "committed": sorted(
                    int(n.metrics.committed_txs.value()) for n in net.nodes
                ),
            }
        finally:
            net.stop()
    assert results[1] == results[4], results


def test_quorum_before_tx_defers_apply_until_bytes_arrive():
    """A vote quorum can land (gossip) before the tx bytes reach the
    local mempool. The certificate must persist immediately, but the
    ABCI apply must DEFER until the bytes arrive — not be silently
    skipped (r5 soak: post-partition churn left a node with the
    certificate, no apply, and claim_vtx blocking the block path's
    delivery too — permanent state divergence)."""
    import hashlib as _h
    import time as _t

    from txflow_tpu.node import LocalNet

    # mempool gossip OFF: tx bytes only exist where we put them
    net = LocalNet(4, use_device_verifier=False, mempool_broadcast=False)
    net.start()
    try:
        tx = b"late-bytes=v"
        tx_hash = _h.sha256(tx).hexdigest().upper()
        # nodes 1-3 get the tx (and their signers vote); node 0 does NOT
        for node in net.nodes[1:]:
            node.mempool.check_tx(tx)
        deadline = _t.monotonic() + 30
        while _t.monotonic() < deadline:
            if all(n.tx_store.has_tx(tx_hash) for n in net.nodes):
                break
            _t.sleep(0.02)
        # every node holds the certificate (3/4 quorum formed via gossip)
        for n in net.nodes:
            assert n.tx_store.has_tx(tx_hash), "certificate missing"
        # nodes 1-3 applied; node 0 must have DEFERRED, not dropped
        deadline = _t.monotonic() + 10
        while _t.monotonic() < deadline:
            if all(n.app.state.get(b"late-bytes") == b"v" for n in net.nodes[1:]):
                break
            _t.sleep(0.02)
        for n in net.nodes[1:]:
            assert n.app.state.get(b"late-bytes") == b"v"
        assert net.nodes[0].app.state.get(b"late-bytes") is None
        assert tx_hash in net.nodes[0].txflow._unapplied

        # the bytes arrive late: the committer retry applies them
        net.nodes[0].mempool.check_tx(tx)
        deadline = _t.monotonic() + 15
        while _t.monotonic() < deadline:
            if net.nodes[0].app.state.get(b"late-bytes") == b"v":
                break
            _t.sleep(0.02)
        assert net.nodes[0].app.state.get(b"late-bytes") == b"v", (
            "deferred apply never ran after the bytes arrived"
        )
        assert tx_hash not in net.nodes[0].txflow._unapplied
    finally:
        net.stop()


def test_two_engines_shared_cache_both_commit():
    """Two co-located engines sharing one VerifyCache (the bench/LocalNet
    deployment shape): claim semantics mean an engine meeting the other's
    in-flight verifies DEFERS those votes and re-offers them next step —
    both engines must still commit every tx, each verifying only a share
    of the unique votes (process-wide verify count < 2x the vote count)."""
    import threading
    import time as _time

    from txflow_tpu.verifier import ScalarVoteVerifier, VerifyCache

    pvs, vals = make_pvs(4)
    cache = VerifyCache()
    engines = []
    for _ in range(2):
        ver = ScalarVoteVerifier(vals, shared_cache=cache)
        flow, mempool, commitpool, votepool, tx_store, app, bus = make_engine(
            vals, use_device=False, verifier=ver
        )
        engines.append((flow, mempool, votepool, app))

    txs = [b"sc%d=v" % i for i in range(40)]
    votes = [sign_vote(pv, tx) for tx in txs for pv in pvs[:3]]
    for flow, mempool, votepool, app in engines:
        for tx in txs:
            mempool.check_tx(tx)

    # start both engines, then feed votes so the step loops race on the
    # same misses (the deterministic single-step path can't interleave)
    for flow, *_ in engines:
        flow.start()
    try:
        for v in votes:
            for _, _, votepool, _ in engines:
                votepool.check_tx(v)
        deadline = _time.monotonic() + 20
        while _time.monotonic() < deadline:
            if all(app.tx_count == len(txs) for *_, app in engines):
                break
            _time.sleep(0.01)
        for flow, _, votepool, app in engines:
            assert app.tx_count == len(txs), (
                f"engine committed {app.tx_count}/{len(txs)}"
            )
    finally:
        for flow, *_ in engines:
            flow.stop()
    # sharing must have deduped verify work: misses == claimed verifies,
    # and claims guarantee each unique vote is verified at most once
    # process-wide (absent TTL expiry, which this run is too short for)
    assert cache.misses <= len(votes)
    assert cache.hits > 0


def test_block_claim_before_committer_wake_credits_apply_once():
    """A quorum decided without tx bytes is queued for the committer AND
    registered as unapplied; if a block claims the delivery (claim_vtx)
    before the committer wake processes the queued item, the apply credit
    must be taken exactly once — double-counting let commits_drained()
    report True while later decided commits were still queued (r5
    review)."""
    from txflow_tpu.types import TxVoteSet

    pvs, vals = make_pvs(4)
    flow, mempool, commitpool, votepool, tx_store, app, _ = make_engine(
        vals, use_device=False
    )
    # no flow.start(): the committer wake is driven by hand below
    tx = b"claimrace=1"  # bytes NEVER enter the mempool
    tx_hash = hashlib.sha256(tx).hexdigest().upper()
    vs = TxVoteSet(CHAIN_ID, HEIGHT, tx_hash, hashlib.sha256(tx).digest(), vals)
    for pv in pvs[:3]:
        added, err = vs.add_vote(sign_vote(pv, tx))
        assert added, err
    with flow._mtx:
        flow._enqueue_commit(vs)
    assert flow._decided_count == 1 and tx_hash in flow._unapplied

    # block path claims the delivery first (this credits the apply)
    assert flow.claim_vtx(tx) is True
    assert flow._applied_count == 1

    # the committer wake now processes the stale queued item: it must NOT
    # credit the apply again
    item = flow._commit_q.get_nowait()
    flow._commit_batch([item], purge=[], interval=1)
    assert flow._applied_count == 1, "apply credited twice for one decision"

    # a second, normal decision must still be visibly un-drained until its
    # own wake applies it
    tx2 = b"claimrace=2"
    mempool.check_tx(tx2)
    tx2_hash = hashlib.sha256(tx2).hexdigest().upper()
    vs2 = TxVoteSet(CHAIN_ID, HEIGHT, tx2_hash, hashlib.sha256(tx2).digest(), vals)
    for pv in pvs[:3]:
        vs2.add_vote(sign_vote(pv, tx2))
    with flow._mtx:
        flow._enqueue_commit(vs2)
    assert not flow.commits_drained(), (
        "drained while a decided commit is still queued"
    )
    item2 = flow._commit_q.get_nowait()
    flow._commit_batch([item2], purge=[], interval=1)
    assert flow._applied_count == 2 == flow._decided_count
    assert app.tx_count == 1  # only tx2 applied here (tx1 went to a block)


def test_more_validators_than_hosted_nodes_commit():
    """BASELINE configs 2-3 topology: a 16-entry validator set hosted by
    only 4 full nodes; the other validators' votes arrive pregenerated
    (as if gossiped from remote peers). Every hosted node must still
    commit every tx — quorum is 2/3 of the WHOLE set's stake."""
    from txflow_tpu.node import LocalNet

    pvs, vals = make_pvs(16)
    net = LocalNet(
        chain_id=CHAIN_ID,
        use_device_verifier=False,
        priv_vals=pvs,
        sign=False,
        mempool_broadcast=False,
        n_nodes=4,
    )
    assert len(net.nodes) == 4 and net.val_set.size() == 16
    txs = [b"mv%d=v" % i for i in range(10)]
    votes = [sign_vote(pv, tx, height=0) for tx in txs for pv in pvs[:11]]
    net.start()
    try:
        for nd in net.nodes:
            nd.mempool.check_tx_many(txs)
        # votes enter round-robin across hosted nodes (the bench's
        # injection shape); gossip fans them out
        for vi in range(11):
            net.nodes[vi % 4].tx_vote_pool.check_tx_many(
                [v for v in votes if v.validator_address == pvs[vi].get_address()]
            )
        assert net.wait_all_committed(txs, timeout=30)
        for nd in net.nodes:
            for tx in txs:
                h = hashlib.sha256(tx).hexdigest().upper()
                cert = nd.tx_store.load_tx_commit(h)
                assert cert is not None and len(cert.commits) == 11
    finally:
        net.stop()
