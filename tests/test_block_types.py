"""Block-path type tests: Block encode/hash/validate, BlockVoteSet /
HeightVoteSet quorum semantics, BlockStore persistence.

Mirrors the reference's types/block_test.go, consensus/types tests and
store/store_test.go scopes (SURVEY §4 contract tests 1-2 for the block
path); quorum/conflict cases follow types/vote_set_test.go:84-276.
"""

import conftest  # noqa: F401  (forces the CPU mesh before jax loads)

import hashlib

import pytest

from txflow_tpu.state import state_from_genesis
from txflow_tpu.store.block_store import BlockStore
from txflow_tpu.store.db import MemDB
from txflow_tpu.types.block import Block, Data, decode_block, encode_block
from txflow_tpu.types.block_vote import (
    PRECOMMIT,
    PREVOTE,
    BlockVote,
    BlockVoteSet,
    ErrConflictingBlockVote,
    HeightVoteSet,
    decode_block_commit,
    decode_block_vote,
    encode_block_commit,
    encode_block_vote,
)
from txflow_tpu.types.genesis import GenesisDoc, GenesisValidator
from txflow_tpu.types.priv_validator import MockPV
from txflow_tpu.types.validator import Validator, ValidatorSet

CHAIN_ID = "test-block-types"


def make_valset(n=4, power=10):
    pvs = [MockPV(hashlib.sha256(b"btv-%d" % i).digest()) for i in range(n)]
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), power) for pv in pvs])
    by_addr = {pv.get_address(): pv for pv in pvs}
    sorted_pvs = [by_addr[v.address] for v in vs]
    return vs, sorted_pvs


def make_state(vs):
    gen = GenesisDoc(
        chain_id=CHAIN_ID,
        validators=[GenesisValidator(v.pub_key, v.voting_power) for v in vs],
    )
    return state_from_genesis(gen)


def make_test_block(state, txs=(b"a=1", b"b=2"), vtxs=(b"c=3",), height=1):
    proposer = state.validators.get_proposer().address
    return state.make_block(height, list(txs), list(vtxs), None, proposer)


def signed_block_vote(pv, height, round_, vtype, block_id, chain_id=CHAIN_ID):
    v = BlockVote(
        height=height,
        round=round_,
        type=vtype,
        block_id=block_id,
        validator_address=pv.get_address(),
    )
    pv.sign_block_vote(chain_id, v)
    return v


# ---------------------------------------------------------------- Block


def test_block_encode_decode_roundtrip():
    vs, _ = make_valset()
    state = make_state(vs)
    b = make_test_block(state)
    raw = encode_block(b)
    b2 = decode_block(raw)
    assert b2.hash() == b.hash()
    assert b2.txs == b.txs and b2.vtxs == b.vtxs
    assert b2.header.chain_id == CHAIN_ID
    assert b2.validate_basic() is None


def test_block_hash_covers_vtxs():
    """The reference's Data.Hash omits Vtxs (types/block.go:305-313 defect,
    SURVEY §0); the rebuild merkle-commits them."""
    d1 = Data(txs=[b"a"], vtxs=[b"v1"])
    d2 = Data(txs=[b"a"], vtxs=[b"v2"])
    assert d1.hash() != d2.hash()
    vs, _ = make_valset()
    state = make_state(vs)
    b1 = make_test_block(state, vtxs=(b"v1",))
    b2 = make_test_block(state, vtxs=(b"v2",))
    b2.header.time_ns = b1.header.time_ns
    b2.fill_header()
    assert b1.hash() != b2.hash()


def test_block_validate_basic_rejects_tampering():
    vs, _ = make_valset()
    state = make_state(vs)
    b = make_test_block(state)
    assert b.validate_basic() is None
    b.data.txs.append(b"sneaky=1")  # data no longer matches header.data_hash
    assert b.validate_basic() is not None


# ---------------------------------------------------------- BlockVoteSet


def test_block_vote_wire_roundtrip():
    vs, pvs = make_valset()
    v = signed_block_vote(pvs[0], 3, 1, PREVOTE, b"\x11" * 32)
    v2 = decode_block_vote(encode_block_vote(v))
    assert v2.height == 3 and v2.round == 1 and v2.type == PREVOTE
    assert v2.block_id == v.block_id
    assert v2.signature == v.signature
    assert v2.verify(CHAIN_ID, pvs[0].get_pub_key())


def test_block_voteset_quorum_at_two_thirds_plus_one():
    vs, pvs = make_valset(4)  # power 10 each, total 40, quorum 27
    bvs = BlockVoteSet(CHAIN_ID, 1, 0, PREVOTE, vs)
    block_id = b"\x22" * 32
    for i, pv in enumerate(pvs[:2]):
        added, err = bvs.add_vote(signed_block_vote(pv, 1, 0, PREVOTE, block_id))
        assert added and err is None
    assert not bvs.has_two_thirds_majority()  # 20 < 27
    bvs.add_vote(signed_block_vote(pvs[2], 1, 0, PREVOTE, block_id))
    assert bvs.has_two_thirds_majority()  # 30 >= 27
    assert bvs.two_thirds_majority() == block_id


def test_block_voteset_nil_votes_and_split():
    vs, pvs = make_valset(4)
    bvs = BlockVoteSet(CHAIN_ID, 1, 0, PRECOMMIT, vs)
    block_id = b"\x33" * 32
    bvs.add_vote(signed_block_vote(pvs[0], 1, 0, PRECOMMIT, block_id))
    bvs.add_vote(signed_block_vote(pvs[1], 1, 0, PRECOMMIT, b""))
    bvs.add_vote(signed_block_vote(pvs[2], 1, 0, PRECOMMIT, b""))
    # 2/3 ANY reached (30), but no block has quorum
    assert bvs.has_two_thirds_any()
    assert not bvs.has_two_thirds_majority()
    bvs.add_vote(signed_block_vote(pvs[3], 1, 0, PRECOMMIT, b""))
    assert bvs.two_thirds_majority() == b""  # nil decision


def test_block_voteset_rejects_dup_conflict_stranger_badsig():
    vs, pvs = make_valset(4)
    bvs = BlockVoteSet(CHAIN_ID, 1, 0, PREVOTE, vs)
    block_id = b"\x44" * 32
    v = signed_block_vote(pvs[0], 1, 0, PREVOTE, block_id)
    assert bvs.add_vote(v)[0]
    # exact duplicate: not added, no error
    added, err = bvs.add_vote(v)
    assert not added and err is None
    # conflicting vote from the same validator
    v2 = signed_block_vote(pvs[0], 1, 0, PREVOTE, b"\x55" * 32)
    added, err = bvs.add_vote(v2)
    assert not added and isinstance(err, ErrConflictingBlockVote)
    # unknown validator
    stranger = MockPV(hashlib.sha256(b"stranger").digest())
    added, err = bvs.add_vote(signed_block_vote(stranger, 1, 0, PREVOTE, block_id))
    assert not added and err is not None
    # bad signature
    v3 = signed_block_vote(pvs[1], 1, 0, PREVOTE, block_id)
    v3.signature = bytes(64)
    added, err = bvs.add_vote(v3)
    assert not added and err is not None
    # stake unaffected by all the rejects
    assert not bvs.has_two_thirds_majority()


def test_block_voteset_make_commit():
    vs, pvs = make_valset(4)
    bvs = BlockVoteSet(CHAIN_ID, 2, 1, PRECOMMIT, vs)
    block_id = b"\x66" * 32
    for pv in pvs[:3]:
        bvs.add_vote(signed_block_vote(pv, 2, 1, PRECOMMIT, block_id))
    commit = bvs.make_commit(block_id)
    assert commit.block_id == block_id
    assert commit.height() == 2 and commit.round() == 1
    assert len(commit.precommits) == 3
    c2 = decode_block_commit(encode_block_commit(commit))
    assert c2.block_id == commit.block_id
    assert len(c2.precommits) == 3
    assert c2.precommits[0].verify(CHAIN_ID, vs.get_by_index(
        vs.index_of(c2.precommits[0].validator_address)).pub_key)


# --------------------------------------------------------- HeightVoteSet


def test_height_vote_set_rounds_and_pol():
    vs, pvs = make_valset(4)
    hvs = HeightVoteSet(CHAIN_ID, 1, vs)
    hvs.set_round(0)
    block_id = b"\x77" * 32
    for pv in pvs[:3]:
        hvs.add_vote(signed_block_vote(pv, 1, 1, PREVOTE, block_id))
    assert hvs.prevotes(1).has_two_thirds_majority()
    assert hvs.pol_info() == (1, block_id)
    assert not hvs.precommits(1).has_two_thirds_any()


def test_height_vote_set_peer_catchup_round_bound():
    """A peer may name at most 2 rounds beyond round+1 (reference
    height_vote_set.go:35-115) — an unbounded-allocation guard."""
    vs, pvs = make_valset(4)
    hvs = HeightVoteSet(CHAIN_ID, 1, vs)
    hvs.set_round(0)
    # own votes (no peer id): not bounded
    added, _ = hvs.add_vote(signed_block_vote(pvs[0], 1, 9, PREVOTE, b""))
    assert added
    # peer votes: rounds 5 and 6 accepted as the peer's 2 catchup rounds
    for r in (5, 6):
        added, _ = hvs.add_vote(
            signed_block_vote(pvs[1], 1, r, PREVOTE, b""), peer_id="peerA"
        )
        assert added
    # third catchup round from the same peer: rejected
    added, err = hvs.add_vote(
        signed_block_vote(pvs[1], 1, 7, PREVOTE, b""), peer_id="peerA"
    )
    assert not added and err is not None
    # near rounds (<= round+1) are always accepted
    added, _ = hvs.add_vote(
        signed_block_vote(pvs[2], 1, 1, PREVOTE, b""), peer_id="peerA"
    )
    assert added


# ------------------------------------------------------------ BlockStore


def test_block_store_roundtrip_and_watermark():
    vs, pvs = make_valset(4)
    state = make_state(vs)
    db = MemDB()
    store = BlockStore(db)
    assert store.height() == 0 and store.base() == 0

    block = make_test_block(state)
    block_id = block.hash()
    bvs = BlockVoteSet(CHAIN_ID, 1, 0, PRECOMMIT, vs)
    for pv in pvs[:3]:
        bvs.add_vote(signed_block_vote(pv, 1, 0, PRECOMMIT, block_id))
    seen = bvs.make_commit(block_id)

    store.save_block(block, seen)
    assert store.height() == 1 and store.base() == 1
    loaded = store.load_block(1)
    assert loaded is not None and loaded.hash() == block_id
    sc = store.load_seen_commit(1)
    assert sc is not None and sc.block_id == block_id and len(sc.precommits) == 3

    # non-contiguous save refused (reference SaveBlock panics)
    block3 = make_test_block(state, height=3)
    with pytest.raises(ValueError):
        store.save_block(block3, seen)

    # watermark survives a reopen on the same db
    store2 = BlockStore(db)
    assert store2.height() == 1
    assert store2.load_block(2) is None


def test_block_store_extended_seen_commit():
    vs, pvs = make_valset(4)
    state = make_state(vs)
    store = BlockStore(MemDB())
    block = make_test_block(state)
    block_id = block.hash()
    bvs = BlockVoteSet(CHAIN_ID, 1, 0, PRECOMMIT, vs)
    for pv in pvs[:3]:
        bvs.add_vote(signed_block_vote(pv, 1, 0, PRECOMMIT, block_id))
    commit = bvs.make_commit(block_id)
    store.save_block(block, commit)
    # late precommit folded in (consensus _extend_last_commit path)
    late = signed_block_vote(pvs[3], 1, 0, PRECOMMIT, block_id)
    commit.precommits.append(late)
    store.save_seen_commit(1, commit)
    sc = store.load_seen_commit(1)
    assert len(sc.precommits) == 4
