"""FilePV + remote signer tests (reference privval/*_test.go scopes):
key/state persistence, double-sign protection across restarts, socket
signer round-trips incl. a refusal crossing the wire, and a LocalNet
running entirely on FilePVs.
"""

import conftest  # noqa: F401

import hashlib

import pytest

from txflow_tpu.consensus.types import Proposal
from txflow_tpu.crypto import ed25519
from txflow_tpu.node import LocalNet
from txflow_tpu.privval import (
    ErrDoubleSign,
    FilePV,
    SignerClient,
    SignerServer,
)
from txflow_tpu.types import TxVote
from txflow_tpu.types.block_vote import PRECOMMIT, PREVOTE, BlockVote
from txflow_tpu.utils.config import test_config as make_test_config

CHAIN_ID = "test-privval"


def block_vote(pv, height, round_, vtype, block_id):
    return BlockVote(
        height=height,
        round=round_,
        type=vtype,
        block_id=block_id,
        validator_address=pv.get_address(),
    )


def test_filepv_generate_and_reload(tmp_path):
    pv = FilePV.load_or_generate(str(tmp_path))
    addr, pub = pv.get_address(), pv.get_pub_key()
    pv2 = FilePV.load_or_generate(str(tmp_path))  # reload from disk
    assert pv2.get_address() == addr and pv2.get_pub_key() == pub
    # signature verifies against the persisted key
    v = TxVote(height=0, tx_hash="AA" * 32, tx_key=b"\xaa" * 32,
               validator_address=addr)
    pv2.sign_tx_vote(CHAIN_ID, v)
    assert v.verify(CHAIN_ID, pub) is None


def test_filepv_double_sign_protection(tmp_path):
    pv = FilePV.load_or_generate(str(tmp_path))
    a = block_vote(pv, 5, 0, PREVOTE, b"\x11" * 32)
    pv.sign_block_vote(CHAIN_ID, a)
    sig_a = a.signature

    # identical message at the same HRS: idempotent, same signature
    a2 = block_vote(pv, 5, 0, PREVOTE, b"\x11" * 32)
    a2.timestamp_ns = a.timestamp_ns
    pv.sign_block_vote(CHAIN_ID, a2)
    assert a2.signature == sig_a

    # conflicting block at the same HRS: refused
    b = block_vote(pv, 5, 0, PREVOTE, b"\x22" * 32)
    with pytest.raises(ErrDoubleSign):
        pv.sign_block_vote(CHAIN_ID, b)

    # HRS regression: refused (precommit signed, then another prevote)
    pc = block_vote(pv, 5, 0, PRECOMMIT, b"\x11" * 32)
    pv.sign_block_vote(CHAIN_ID, pc)
    with pytest.raises(ErrDoubleSign):
        pv.sign_block_vote(CHAIN_ID, block_vote(pv, 5, 0, PREVOTE, b"\x33" * 32))

    # progress is fine
    nxt = block_vote(pv, 6, 0, PREVOTE, b"\x44" * 32)
    pv.sign_block_vote(CHAIN_ID, nxt)
    assert nxt.signature


def test_filepv_double_sign_protection_survives_restart(tmp_path):
    pv = FilePV.load_or_generate(str(tmp_path))
    v = block_vote(pv, 7, 1, PRECOMMIT, b"\x55" * 32)
    pv.sign_block_vote(CHAIN_ID, v)

    # "restart": reload from the persisted state file
    pv2 = FilePV.load_or_generate(str(tmp_path))
    assert (pv2.last_height, pv2.last_round, pv2.last_step) == (7, 1, 3)
    with pytest.raises(ErrDoubleSign):
        pv2.sign_block_vote(CHAIN_ID, block_vote(pv2, 7, 1, PRECOMMIT, b"\x66" * 32))
    with pytest.raises(ErrDoubleSign):
        pv2.sign_block_vote(CHAIN_ID, block_vote(pv2, 7, 0, PREVOTE, b"\x66" * 32))


def test_filepv_proposal_hrs(tmp_path):
    pv = FilePV.load_or_generate(str(tmp_path))
    p = Proposal(height=3, round=0, pol_round=-1, block_hash=b"\x10" * 32,
                 timestamp_ns=123)
    pv.sign_proposal(CHAIN_ID, p)
    assert p.signature
    # proposing a different block at the same height/round: refused
    p2 = Proposal(height=3, round=0, pol_round=-1, block_hash=b"\x20" * 32,
                  timestamp_ns=456)
    with pytest.raises(ErrDoubleSign):
        pv.sign_proposal(CHAIN_ID, p2)
    # but signing the round's prevote afterwards is fine (step advances)
    v = block_vote(pv, 3, 0, PREVOTE, b"\x10" * 32)
    pv.sign_block_vote(CHAIN_ID, v)
    assert v.signature


def test_remote_signer_round_trip(tmp_path):
    file_pv = FilePV.load_or_generate(str(tmp_path))
    server = SignerServer(file_pv)
    server.start()
    try:
        client = SignerClient(*server.addr)
        assert client.get_pub_key() == file_pv.get_pub_key()
        assert client.get_address() == file_pv.get_address()

        # tx vote through the socket
        key = hashlib.sha256(b"remote=1").digest()
        tv = TxVote(height=0, tx_hash=key.hex().upper(), tx_key=key,
                    validator_address=client.get_address())
        client.sign_tx_vote(CHAIN_ID, tv)
        assert tv.verify(CHAIN_ID, client.get_pub_key()) is None

        # block vote through the socket
        bv = block_vote(client, 9, 0, PREVOTE, b"\x77" * 32)
        client.sign_block_vote(CHAIN_ID, bv)
        assert bv.verify(CHAIN_ID, client.get_pub_key())

        # double-sign refusal crosses the wire as ErrDoubleSign
        conflicting = block_vote(client, 9, 0, PREVOTE, b"\x88" * 32)
        with pytest.raises(ErrDoubleSign):
            client.sign_block_vote(CHAIN_ID, conflicting)

        # proposal signing through the socket
        p = Proposal(height=10, round=0, pol_round=-1,
                     block_hash=b"\x99" * 32, timestamp_ns=1)
        client.sign_proposal(CHAIN_ID, p)
        assert ed25519.verify(
            client.get_pub_key(), p.sign_bytes(CHAIN_ID), p.signature
        )
        client.close()
    finally:
        server.stop()


def test_localnet_runs_on_file_pvs(tmp_path):
    """4 validators with FilePV keys from a temp dir: fast path commits
    and the block path produces blocks under real double-sign-protected
    signing (reference LoadOrGenFilePV at node boot, node/node.go:95)."""
    pvs = [FilePV.load_or_generate(str(tmp_path / f"val{i}")) for i in range(4)]
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(
        4,
        use_device_verifier=False,
        enable_consensus=True,
        config=cfg,
        priv_vals=pvs,
    )
    net.start()
    try:
        txs = [b"fpv-%d=v" % i for i in range(4)]
        for tx in txs:
            net.broadcast_tx(tx)
        assert net.wait_all_committed(txs, timeout=60)
        for node in net.nodes:
            assert node.consensus.wait_for_height(2, timeout=60)
        # last-sign-state advanced on every validator
        for pv in pvs:
            assert pv.last_height >= 1
    finally:
        net.stop()


def test_node_greeting_sign_and_verify():
    """Node identity greeting (reference node/id.go — vestigial there,
    implemented here): signed greeting verifies, tampered does not."""
    import hashlib

    from txflow_tpu.crypto import ed25519
    from txflow_tpu.node.id import NodeID, PrivNodeID

    seed = hashlib.sha256(b"nid").digest()
    nid = NodeID("n0", ed25519.public_key_from_seed(seed))
    sg = PrivNodeID(nid, seed).sign_greeting("0.3.0", "txflow-test", "hi")
    assert sg.verify()
    sg.greeting.message = "tampered"
    assert not sg.verify()
