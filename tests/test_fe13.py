"""Radix-2^13 field arithmetic parity (fe13) + end-to-end kernel parity
under TXFLOW_FE_RADIX=13.

The fe13 module is the 20-limb upgrade of ops/fe.py; every op must agree
with python-int ground truth on random and adversarial values, and the
full verify kernel must reproduce the radix-8 accept/reject decisions
bit-for-bit (the radix is an internal representation choice — Go's
crypto/ed25519 semantics, types/tx_vote.go:110-119, cannot depend on it).
"""

import conftest  # noqa: F401

import hashlib
import os
import subprocess
import sys

import numpy as np
import jax.numpy as jnp
import pytest

from txflow_tpu.ops import fe13

P = fe13.P_INT


def rnd_ints(n, seed):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        out.append(int.from_bytes(rng.bytes(32), "little") % P)
    return out


def test_limb_roundtrip_and_bytes():
    vals = rnd_ints(20, 1) + [0, 1, 19, P - 1, 2**255 - 20]
    for v in vals:
        limbs = fe13.int_to_limbs(v)
        assert limbs.shape == (fe13.NLIMB,)
        assert (limbs >= 0).all() and (limbs <= fe13.MASK).all()
        assert fe13.limbs_to_int(limbs) == v
        b = (v % 2**256).to_bytes(32, "little")
        assert fe13.limbs_to_int(fe13.bytes_to_limbs(b)) == v


def test_bytes_to_limbs_device_matches_host():
    rng = np.random.default_rng(2)
    raw = rng.integers(0, 256, size=(64, 32), dtype=np.uint8)
    dev = np.asarray(fe13.bytes_to_limbs_device(jnp.asarray(raw)))
    for i in range(raw.shape[0]):
        host = fe13.bytes_to_limbs(raw[i].tobytes())
        np.testing.assert_array_equal(dev[i], host)


def _as_batch(vals):
    return jnp.asarray(np.stack([fe13.int_to_limbs(v) for v in vals]))


def test_mul_add_sub_parity():
    a_vals = rnd_ints(50, 3)
    b_vals = rnd_ints(50, 4)
    a, b = _as_batch(a_vals), _as_batch(b_vals)
    mul = fe13.fe_mul(a, b)
    add = fe13.fe_add(a, b)
    sub = fe13.fe_sub(a, b)
    for i, (x, y) in enumerate(zip(a_vals, b_vals)):
        assert fe13.limbs_to_int(mul[i]) % P == (x * y) % P
        assert fe13.limbs_to_int(add[i]) % P == (x + y) % P
        assert fe13.limbs_to_int(sub[i]) % P == (x - y) % P


def test_mul_bounds_after_add_chain():
    """The documented normalized bound: outputs of add/sub/mul chains stay
    legal fe_mul inputs (limbs <= ~9408) and results stay exact."""
    a_vals = rnd_ints(16, 5)
    b_vals = rnd_ints(16, 6)
    a, b = _as_batch(a_vals), _as_batch(b_vals)
    s = fe13.fe_add(a, b)           # carried sum
    d = fe13.fe_sub(s, b)           # back to a (mod p)
    m = fe13.fe_mul(s, d)
    assert int(np.asarray(s).max()) <= 9408
    assert int(np.asarray(d).max()) <= 9408
    assert int(np.asarray(m).max()) <= 9408
    for i, (x, y) in enumerate(zip(a_vals, b_vals)):
        assert fe13.limbs_to_int(m[i]) % P == ((x + y) * x) % P


def test_freeze_and_inv_parity():
    vals = rnd_ints(24, 7) + [0, 1, P - 1, 19]
    x = _as_batch(vals)
    sq = fe13.fe_sq(x)
    frozen = fe13.fe_freeze(sq)
    fr = np.asarray(frozen)
    for i, v in enumerate(vals):
        got = fe13.limbs_to_int(fr[i])
        assert got == (v * v) % P  # frozen = canonical, no mod needed
        assert (fr[i] >= 0).all() and (fr[i] <= fe13.MASK).all()
    nz = [v for v in vals if v != 0]
    inv = fe13.fe_inv(_as_batch(nz))
    for i, v in enumerate(nz):
        assert (fe13.limbs_to_int(inv[i]) * v) % P == 1


def test_freeze_edge_values():
    """Values engineered to need both top-bit folds and both conditional
    p-subtractions."""
    edge = [P - 1, P, P + 1, 2 * P - 1, 2**255 - 1, 2**255, 19, 0]
    # feed them in UNREDUCED limb form (value possibly >= p)
    x = jnp.asarray(
        np.stack([
            np.array(
                [(v >> (13 * i)) & fe13.MASK for i in range(fe13.NLIMB)],
                dtype=np.int32,
            )
            for v in edge
        ])
    )
    fr = np.asarray(fe13.fe_freeze(x))
    for i, v in enumerate(edge):
        assert fe13.limbs_to_int(fr[i]) == v % P


def test_full_kernel_parity_radix13():
    """End-to-end: the verify kernel under TXFLOW_FE_RADIX=13 reproduces
    the host verifier's accept/reject decisions on an adversarial batch
    (run in a subprocess — the radix is an import-time choice)."""
    code = r"""
import os
os.environ["TXFLOW_FE_RADIX"] = "13"
os.environ["JAX_PLATFORMS"] = "cpu"
import hashlib
import numpy as np
from txflow_tpu.crypto import ed25519 as host_ed
from txflow_tpu.ops import fe, ed25519_batch

assert fe.NLIMB == 20 and fe.RADIX == 13, "radix switch did not engage"

seeds = [hashlib.sha256(b"r13-%d" % i).digest() for i in range(4)]
pubs = [host_ed.public_key_from_seed(s) for s in seeds]
epoch = ed25519_batch.EpochTables(pubs)
assert epoch.tables.shape[-1] == 20

msgs, sigs, vidx, expect = [], [], [], []
for t in range(24):
    msg = b"radix13-parity-%d" % t
    vi = t % 4
    sig = host_ed.sign(seeds[vi], msg)
    mode = t % 4
    if mode == 1:
        sig = sig[:8] + bytes([sig[8] ^ 1]) + sig[9:]   # corrupt R
    elif mode == 2:
        sig = sig[:40] + bytes([sig[40] ^ 1]) + sig[41:]  # corrupt S
    elif mode == 3 and t % 8 == 7:
        vi = (vi + 1) % 4  # wrong key
    msgs.append(msg); sigs.append(sig); vidx.append(vi)
    expect.append(host_ed.verify(pubs[vi], msg, sig))

batch = ed25519_batch.prepare_batch(msgs, sigs, np.array(vidx), epoch)
got = ed25519_batch.verify_batch(batch)
assert list(got) == expect, (list(got), expect)

# compact/gather path too
import jax.numpy as jnp
cb = ed25519_batch.prepare_compact(msgs, sigs, np.array(vidx), epoch)
got2 = np.asarray(ed25519_batch.verify_kernel_gather(
    jnp.asarray(cb.s_nibbles), jnp.asarray(cb.h_nibbles),
    jnp.asarray(cb.val_idx.astype(np.int32)), jnp.asarray(epoch.tables),
    jnp.asarray(cb.r_y), jnp.asarray(cb.r_sign), jnp.asarray(cb.pre_ok)))
assert list(got2) == expect, (list(got2), expect)
print("RADIX13 KERNEL PARITY OK")
"""
    env = dict(os.environ)
    env["TXFLOW_FE_RADIX"] = "13"
    # strip the axon site hook: with the TPU tunnel wedged it can hang
    # `import jax` even under JAX_PLATFORMS=cpu (see bench._sanitized_cpu_env)
    parts = [
        p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and ".axon_site" not in p
    ]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(parts + [repo])
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=repo,
        env=env,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "RADIX13 KERNEL PARITY OK" in r.stdout


@pytest.mark.slow  # 8-way mesh compile of the radix13 kernel: ~65s on 1-core CPU
def test_sharded_mesh_parity_radix13():
    """The 8-device shard_map verify+tally path under TXFLOW_FE_RADIX=13:
    decisions must match the scalar golden model (the radix swap must
    compose with the vote-axis sharding, psum tally included). Subprocess:
    the radix is an import-time choice."""
    code = r"""
import os
os.environ["TXFLOW_FE_RADIX"] = "13"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
import hashlib
import numpy as np
from txflow_tpu.crypto import ed25519 as host_ed
from txflow_tpu.ops import fe
from txflow_tpu.parallel import make_mesh
from txflow_tpu.types import TxVote, Validator, ValidatorSet, canonical_sign_bytes
from txflow_tpu.verifier import DeviceVoteVerifier, ScalarVoteVerifier

assert fe.NLIMB == 20

seeds = [hashlib.sha256(b"m13-%d" % i).digest() for i in range(4)]
pubs = [host_ed.public_key_from_seed(s) for s in seeds]
vals = ValidatorSet([Validator.from_pub_key(p, 10) for p in pubs])
seed_by_pub = dict(zip(pubs, seeds))
seeds_sorted = [seed_by_pub[v.pub_key] for v in vals]

msgs, sigs, vidx, slot = [], [], [], []
for t in range(4):
    h = hashlib.sha256(b"tx%d" % t).hexdigest().upper()
    for vi in range(4):
        m = canonical_sign_bytes("mesh13", 1, h, 1700000000_000000000 + t)
        s = host_ed.sign(seeds_sorted[vi], m)
        if (t * 4 + vi) % 5 == 3:
            s = s[:12] + bytes([s[12] ^ 1]) + s[13:]  # corrupt some
        msgs.append(m); sigs.append(s); vidx.append(vi); slot.append(t)

mesh = make_mesh(8)
dev = DeviceVoteVerifier(vals, mesh=mesh)
sca = ScalarVoteVerifier(vals)
rd = dev.verify_and_tally(msgs, sigs, np.array(vidx), np.array(slot), 4)
rs = sca.verify_and_tally(msgs, sigs, np.array(vidx), np.array(slot), 4)
np.testing.assert_array_equal(rd.valid, rs.valid)
np.testing.assert_array_equal(rd.stake.astype(np.int64), rs.stake)
np.testing.assert_array_equal(rd.maj23, rs.maj23)
print("MESH RADIX13 PARITY OK")
"""
    env = dict(os.environ)
    env["TXFLOW_FE_RADIX"] = "13"
    parts = [
        p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and ".axon_site" not in p
    ]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(parts + [repo])
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=repo,
        env=env,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "MESH RADIX13 PARITY OK" in r.stdout
