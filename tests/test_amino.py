"""Amino codec wire-format tests.

The zero-time vector is taken from the reference's pinned amino output
(types/vote_test.go:62: the timestamp field of an empty CanonicalVote) —
it proves seconds use two's-complement uvarint, not zigzag.
"""

from txflow_tpu.codec import amino


def test_uvarint_roundtrip():
    for n in [0, 1, 127, 128, 300, 2**32, 2**63, 2**64 - 1]:
        enc = amino.uvarint(n)
        r = amino.AminoReader(enc)
        assert r.read_uvarint() == n
        assert r.eof()


def test_varint_twos_complement():
    # -62135596800 (the Go zero-time unix seconds) must encode as the
    # 10-byte uvarint from the reference vector.
    want = bytes([0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1])
    assert amino.varint(-62135596800) == want
    r = amino.AminoReader(want)
    assert r.read_varint() == -62135596800


def test_zero_time_body_matches_reference_vector():
    # types/vote_test.go:62: field 5 (timestamp) body of zero CanonicalVote is
    # 0xb bytes: 0x8 (field 1 varint) + 10-byte seconds; nanos elided.
    zero_time_unix_ns = -62135596800 * 1_000_000_000
    body = amino.encode_time_body(zero_time_unix_ns)
    assert body == bytes(
        [0x8, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1]
    )
    assert amino.decode_time_body(body) == zero_time_unix_ns


def test_time_body_with_nanos():
    # 2017-12-25T03:00:01.234Z = 1514170801 s + 234ms
    ns = 1514170801 * 1_000_000_000 + 234_000_000
    body = amino.encode_time_body(ns)
    r = amino.AminoReader(body)
    fnum, typ3 = r.read_field_key()
    assert (fnum, typ3) == (1, amino.TYP3_VARINT)
    assert r.read_varint() == 1514170801
    fnum, typ3 = r.read_field_key()
    assert (fnum, typ3) == (2, amino.TYP3_VARINT)
    assert r.read_uvarint() == 234_000_000
    assert r.eof()
    assert amino.decode_time_body(body) == ns


def test_fixed64():
    assert amino.fixed64(1) == bytes([1, 0, 0, 0, 0, 0, 0, 0])
    r = amino.AminoReader(amino.fixed64(-5))
    assert r.read_fixed64() == -5


def test_field_key():
    # (5 << 3) | 2 = 0x2a — the timestamp field tag in the reference vectors.
    assert amino.field_key(5, amino.TYP3_BYTELEN) == bytes([0x2A])
    assert amino.field_key(2, amino.TYP3_8BYTE) == bytes([0x11])


def test_uvarint_overflow_rejected():
    import pytest

    # 11-byte varint and 10-byte with final byte > 1 overflow 64 bits.
    r = amino.AminoReader(bytes([0x80] * 10 + [0x02]))
    with pytest.raises(ValueError):
        r.read_uvarint()
    r = amino.AminoReader(bytes([0xFF] * 9 + [0x02]))
    with pytest.raises(ValueError):
        r.read_uvarint()
    # Max uint64 still decodes.
    r = amino.AminoReader(amino.uvarint(2**64 - 1))
    assert r.read_uvarint() == 2**64 - 1
