"""Process-backed host prep (engine.hostprep.ProcHostPrepPool).

The process backend is a pure parallelization of the existing host-prep
row functions — every output must be byte-identical to the serial numpy
path and to the thread-pool path, because all three run the SAME row
core (prep_proc.prep_rows_cat / sign_rows). Covered here:

- randomized byte-parity of process-pool compact prep vs serial vs
  thread pool, over adversarial rows (corrupt sigs, wrong-length and
  empty sigs, adversarial all-zero 64-byte sigs, non-minimal S >= L,
  out-of-range validator indices) at partial-shard sizes;
- sign-bytes parity vs canonical_sign_bytes, including the hostile
  oversize-field decline (returns None, caller falls back);
- mid-run restage: a second epoch (different validator set) through the
  SAME pool stays byte-identical;
- spawn-failure fallback: make_host_pool degrades to the thread backend;
- shutdown hygiene: close() joins workers and unlinks every shm segment
  (no /dev/shm leaks), and the atexit sweep is idempotent;
- engine-level: a process-backend engine's commit certificates are
  byte-identical to the scalar try_add_vote golden path.
"""

import hashlib
import os

import numpy as np
import pytest

from test_pipeline import (
    _wait_quiescent,
    make_engine as make_threaded_engine,
    make_pvs,
    sign_vote,
)
from test_verifier import make_batch, make_valset
from txflow_tpu import prep_proc
from txflow_tpu.crypto import ed25519 as host_ed
from txflow_tpu.engine.hostprep import (
    HostPrepPool,
    ProcHostPrepPool,
    close_all_pools,
    make_host_pool,
)
from txflow_tpu.ops import ed25519_batch
from txflow_tpu.types.tx_vote import canonical_sign_bytes

COMPACT_FIELDS = ("s_nibbles", "h_nibbles", "val_idx", "r_y", "r_sign", "pre_ok")


def _shm_names() -> set:
    """Shared-memory DATA segments (the unlink contract's subject).
    ``sem.mp-*`` entries are multiprocessing queue semaphores — freed
    when the queue objects are garbage-collected, not by pool close."""
    try:
        return {n for n in os.listdir("/dev/shm") if not n.startswith("sem.")}
    except OSError:
        return set()


@pytest.fixture(scope="module")
def proc_pool():
    """One spawned pool for the parity tests (spawn costs ~1.5 s on the
    1-core CI box; the tests exercise distinct calls, not distinct
    pools)."""
    pool = make_host_pool(3, backend="process", name="hostprep-proctest")
    if pool.backend != "process":
        pytest.skip("process pool unavailable on this platform")
    yield pool
    pool.close()


def _adversarial_batch(vals, seeds, n):
    """Adversarial rows beyond make_batch's corrupt modes: the sig-shape
    attacks only the cat-form representation could get wrong."""
    msgs, sigs, vidx, _ = make_batch(
        vals, seeds, n_txs=-(-n // len(seeds)),
        corrupt=("ok", "flip", "ok", "wrongkey", "badidx"),
    )
    msgs, sigs, vidx = msgs[:n], list(sigs[:n]), np.array(vidx[:n])
    L = prep_proc.L
    for i in range(0, n, 13):
        sigs[i] = b""  # empty: length-invalid
    for i in range(1, n, 17):
        sigs[i] = sigs[i][:40]  # truncated: length-invalid
    for i in range(2, n, 19):
        sigs[i] = bytes(64)  # adversarial all-zero: length-VALID, S=0
    for i in range(3, n, 23):
        # non-minimal scalar: S >= L must fail ScMinimal
        s_bad = (L + 5).to_bytes(32, "little")
        sigs[i] = sigs[i][:32] + s_bad
    for i in range(4, n, 29):
        vidx[i] = -2  # negative validator index
    return msgs, sigs, vidx


@pytest.mark.parametrize("n", [601, 293])  # partial, non-worker-divisible
def test_process_pool_compact_parity(proc_pool, n):
    """Process-pool prepare_compact == serial == thread pool, field for
    field, over adversarial rows at partial-shard sizes."""
    vals, seeds = make_valset(4)
    msgs, sigs, vidx = _adversarial_batch(vals, seeds, n)
    epoch = ed25519_batch.EpochTables([v.pub_key for v in vals])

    serial = ed25519_batch.prepare_compact(msgs, sigs, vidx, epoch)
    shm_before = proc_pool.stats()["shm_calls"]
    proc = ed25519_batch.prepare_compact(msgs, sigs, vidx, epoch, pool=proc_pool)
    assert proc_pool.stats()["shm_calls"] == shm_before + 1, (
        "process pool never took the shared-memory path"
    )
    thread_pool = HostPrepPool(3, name="hostprep-proctest-t")
    try:
        threaded = ed25519_batch.prepare_compact(
            msgs, sigs, vidx, epoch, pool=thread_pool
        )
    finally:
        thread_pool.close()
    for field in COMPACT_FIELDS:
        np.testing.assert_array_equal(
            getattr(serial, field), getattr(proc, field), err_msg=field
        )
        np.testing.assert_array_equal(
            getattr(serial, field), getattr(threaded, field), err_msg=field
        )


def test_process_pool_sign_bytes_parity(proc_pool):
    """sign_bytes_shm == canonical_sign_bytes row for row, and hostile
    oversize fields make the shm path decline (None) instead of
    truncating."""
    heights = [1, 2, 2**40, 7, 0]
    hashes = [hashlib.sha256(b"t%d" % i).hexdigest().upper() for i in range(5)]
    ts = [1700000000_000000000 + i for i in range(5)]
    out = proc_pool.sign_bytes_shm(heights, hashes, ts, "proc-chain")
    assert out is not None
    rows, wait_s = out
    assert wait_s >= 0.0
    expect = [
        canonical_sign_bytes("proc-chain", h, x, t)
        for h, x, t in zip(heights, hashes, ts)
    ]
    assert rows == expect

    # hostile: a tx_hash past the shm stride bound declines the fast path
    big = proc_pool.sign_bytes_shm([1], ["A" * 2048], [1], "proc-chain")
    assert big is None


def test_process_pool_mid_run_restage(proc_pool):
    """A second epoch (different validator set) through the SAME pool:
    the per-call shm protocol holds no per-epoch state to go stale."""
    for tag, n_vals in (("a", 4), ("b", 7)):
        vals, seeds = make_valset(n_vals)
        msgs, sigs, vidx = _adversarial_batch(vals, seeds, 300 + n_vals)
        epoch = ed25519_batch.EpochTables([v.pub_key for v in vals])
        serial = ed25519_batch.prepare_compact(msgs, sigs, vidx, epoch)
        proc = ed25519_batch.prepare_compact(
            msgs, sigs, vidx, epoch, pool=proc_pool
        )
        for field in COMPACT_FIELDS:
            np.testing.assert_array_equal(
                getattr(serial, field), getattr(proc, field),
                err_msg=f"{tag}:{field}",
            )


def test_spawn_failure_falls_back_to_threads():
    """An unspawnable process pool degrades to the thread backend —
    callers keep a working pool, never an exception."""
    pool = make_host_pool(
        3, backend="process", name="hostprep-bogus", mp_context="bogus"
    )
    try:
        assert pool.backend == "thread"
        assert isinstance(pool, HostPrepPool)
        assert pool.workers == 3
    finally:
        pool.close()


def test_close_releases_workers_and_shm():
    """close() joins every worker process and unlinks every tracked shm
    segment; the atexit sweep (close_all_pools) is an idempotent no-op
    afterwards."""
    before = _shm_names()
    pool = ProcHostPrepPool(3, name="hostprep-closetest")
    vals, seeds = make_valset(4)
    msgs, sigs, vidx = _adversarial_batch(vals, seeds, 300)
    epoch = ed25519_batch.EpochTables([v.pub_key for v in vals])
    out = pool.prepare_compact_shm(msgs, sigs, vidx, epoch)
    assert out is not None
    procs = list(pool._procs)
    assert procs, "no worker processes spawned"
    pool.close()
    for p in procs:
        assert not p.is_alive(), "worker process leaked past close()"
    leaked = _shm_names() - before
    assert not leaked, f"shm segments leaked: {leaked}"
    close_all_pools()  # idempotent with everything already closed


def test_engine_process_backend_certificates_match_golden():
    """An engine on the process host-prep backend commits byte-identical
    certificates to the scalar try_add_vote golden path (same stream,
    ~15% corrupted signatures)."""
    import random

    rng = random.Random(31)
    pvs, vals = make_pvs(4)
    txs = [b"proc%d=%d" % (i, i) for i in range(80)]  # 80*4=320 >= pool gate
    stream = []
    for tx in txs:
        for vi in range(4):
            vote = sign_vote(pvs[vi], tx)
            if rng.random() < 0.15:
                vote.signature = bytes(64)
            stream.append(vote)
    rng.shuffle(stream)

    flow_s, mem_s, _, store_s, app_s = make_threaded_engine(
        vals, use_device=False
    )
    for tx in txs:
        mem_s.check_tx(tx)
    for v in stream:
        flow_s.try_add_vote(v.copy())

    flow_p, mem_p, pool_p, store_p, app_p = make_threaded_engine(
        vals, use_device=False, host_prep_workers=3,
        host_prep_backend="process", max_batch=1024,
    )
    for tx in txs:
        mem_p.check_tx(tx)
    for v in stream:  # queue before start: one big pooled drain
        try:
            pool_p.check_tx(v)
        except Exception:
            pass  # cache dup (zeroed sigs share a vote key) — scalar saw it
    flow_p.start()
    try:
        assert _wait_quiescent(flow_p, pool_p), "process engine never drained"
        stats = flow_p.pipeline_stats()
        pool_stats = flow_p._host_pool.stats()
    finally:
        flow_p.stop()

    if stats["host_prep_backend"] == "process":
        assert pool_stats["shm_calls"] > 0, (
            "process backend ran but never took the shm sign-bytes path"
        )
    assert app_p.tx_count == app_s.tx_count
    assert app_p.state == app_s.state
    assert app_p.digest == app_s.digest  # commit ORDER identical
    committed = 0
    for tx in txs:
        tx_hash = hashlib.sha256(tx).hexdigest().upper()
        cs = store_s.load_tx_commit(tx_hash)
        cp = store_p.load_tx_commit(tx_hash)
        assert (cs is None) == (cp is None)
        if cs is not None:
            committed += 1
            assert [
                (c.validator_address, c.signature) for c in cs.commits
            ] == [(c.validator_address, c.signature) for c in cp.commits]
    assert committed > 0, "stream never formed a quorum — test is vacuous"
