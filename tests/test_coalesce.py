"""Shape-stable batch coalescing, background warmup, and adaptive depth.

Three invariants from the compile-free hot path work:

1. the coalescer only changes WHEN votes are dispatched, never what is
   decided — certificates stay byte-identical to the scalar golden path,
   including linger-deadline flushes and the cold-shape scalar fallback
   mid-promotion;
2. the shape registry's enumeration is a superset of every shape the
   coalescer can make the verifier emit (so prewarm/background warmup
   covers the hot path: compile_in_run == 0 by construction);
3. the adaptive depth controller steers pipeline_depth from overlap
   signals with bounded, damped movement.
"""

import hashlib
import threading
import time

import numpy as np
import pytest

from test_pipeline import (
    _mixed_stream,
    _wait_quiescent,
    make_engine,
    make_pvs,
    sign_vote,
)
from test_verifier import make_batch, make_valset
from txflow_tpu.engine.adaptive import AdaptiveDepthController
from txflow_tpu.engine.shapes import BackgroundWarmer, ShapeWarmRegistry
from txflow_tpu.engine.txflow import _BatchCoalescer
from txflow_tpu.verifier import (
    DeviceVoteVerifier,
    ScalarVoteVerifier,
    VerifyCache,
)


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


# ---- _BatchCoalescer unit behavior ------------------------------------


def test_coalescer_dispatches_full_buckets_only():
    clk = FakeClock()
    co = _BatchCoalescer((8, 32, 128), cap=64, min_batch=4, linger=0.01, clock=clk)
    # cap excludes 128; min_batch excludes nothing else
    assert co.targets == [8, 32]
    # below the smallest bucket: hold (deadline armed, no dispatch)
    assert co.decide(5) == 0
    # backlog covers a bucket: exactly the LARGEST covered bucket drains
    assert co.decide(9) == 8
    assert co.decide(32) == 32
    assert co.decide(70) == 32  # remainder carries to the next decide
    assert co.full_batches == 3
    assert co.linger_flushes == 0


def test_coalescer_linger_deadline_flushes_partial():
    clk = FakeClock()
    co = _BatchCoalescer((8,), cap=64, min_batch=1, linger=0.5, clock=clk)
    assert co.decide(3) == 0  # arms deadline at t+0.5
    clk.t += 0.3
    assert co.decide(3) == 0  # still inside the linger window
    clk.t += 0.3
    assert co.decide(3) == 3  # deadline passed: flush the whole backlog
    assert co.linger_flushes == 1
    # deadline re-arms fresh for the next partial
    assert co.decide(2) == 0
    clk.t += 0.6
    assert co.decide(2) == 2
    assert co.linger_flushes == 2


def test_coalescer_idle_flush_and_wait_budget():
    clk = FakeClock()
    co = _BatchCoalescer((8,), cap=64, min_batch=1, linger=10.0, clock=clk)
    # nothing pending: note_idle is a no-op, wait budget is the poll
    co.note_idle()
    assert co.wait_budget(0.25, 0.05) == 0.25
    assert co.decide(3) == 0
    # deadline armed: the wait is clipped to idle_flush so idleness is
    # detected on that scale, never a full 10 s linger
    assert co.wait_budget(0.25, 0.05) == 0.05
    co.note_idle()  # pool wait timed out with votes pending
    assert co.decide(3) == 3
    assert co.linger_flushes == 1


def test_coalescer_degrades_to_cap_when_no_bucket_fits():
    co = _BatchCoalescer((256, 1024), cap=64, min_batch=1, linger=0.01)
    assert co.targets == [64]
    assert co.decide(64) == 64


# ---- adaptive depth controller ----------------------------------------


def test_adaptive_depth_controller_steers_from_overlap():
    ctrl = AdaptiveDepthController(
        depth=2, min_depth=2, max_depth=4, window=8, cooldown=1
    )

    def window_obs(ratio):
        # feed one full window whose busy/active delta has that ratio
        return ctrl.observe(
            ctrl._last_busy + ratio, ctrl._last_active + 1.0,
            ctrl._last_steps + ctrl.window,
        )

    # sub-window feeds never move the depth
    assert ctrl.observe(0.1, 1.0, ctrl.window - 1) == 2
    # low overlap: the device idled while the engine worked -> grow
    assert window_obs(0.5) == 3
    assert ctrl.changes == 1
    # cooldown window: even a terrible ratio holds the new depth
    assert window_obs(0.5) == 3
    # cooldown over: grow again, then clamp at max
    assert window_obs(0.5) == 4
    assert window_obs(0.5) == 4  # cooldown
    assert window_obs(0.5) == 4  # at max_depth: no further growth
    # saturated device: probe down (damped), never below the floor
    for _ in range(10):
        window_obs(1.0)
    assert ctrl.depth == ctrl.min_depth == 2
    assert ctrl.changes >= 3
    assert ctrl.stats()["last_window_ratio"] == 1.0
    # mid-band ratio: hold
    held = window_obs(0.9)
    assert held == 2 and ctrl.depth == 2


def test_adaptive_depth_engine_wiring():
    """adaptive_depth=True wires a controller into the pipelined loop:
    the engine still commits correctly, pipeline_stats reports the
    controller, and synthetic overlap signals move the depth the fill
    stage honors (_target_depth) — the ROADMAP static-depth item."""
    pvs, vals = make_pvs(4)
    flow, mempool, votepool, store, app = make_engine(
        vals,
        use_device=False,
        coalesce=False,
        adaptive_depth=True,
        pipeline_depth=2,
        pipeline_depth_max=6,
        min_batch=1,
        max_batch=8,
    )
    txs = [b"ad%d=v" % i for i in range(12)]
    for tx in txs:
        mempool.check_tx(tx)
    flow.start()
    try:
        for tx in txs:
            for pv in pvs[:3]:
                votepool.check_tx(sign_vote(pv, tx))
        assert _wait_quiescent(flow, votepool)
    finally:
        flow.stop()
    assert app.tx_count == len(txs)

    ctrl = flow._depth_ctrl
    assert ctrl is not None
    stats = flow.pipeline_stats()
    assert stats["adaptive_depth"]["depth"] == ctrl.depth == flow._target_depth()
    # synthetic idle-device windows grow the live depth...
    d0 = ctrl.depth
    grown = ctrl.observe(
        ctrl._last_busy + 0.1, ctrl._last_active + 1.0,
        ctrl._last_steps + ctrl.window,
    )
    assert grown == min(d0 + 1, ctrl.max_depth)
    assert flow._target_depth() == grown
    assert flow.pipeline_stats()["depth"] == grown
    # ...and saturated windows walk it back to the floor
    for _ in range(20):
        ctrl.observe(
            ctrl._last_busy + 1.0, ctrl._last_active + 1.0,
            ctrl._last_steps + ctrl.window,
        )
    assert ctrl.depth == ctrl.min_depth
    assert flow._target_depth() == ctrl.min_depth
    assert ctrl.changes >= 2


# ---- coalescing parity (satellite: the golden-path guarantee) ---------


class FakeWarmGate:
    """Stands in for ShapeWarmRegistry in the engine's cold-shape gate:
    starts cold (every batch demoted to the scalar fallback), promotes
    when the test flips ``warm`` — exercising the fallback->device
    promotion boundary without a device."""

    def __init__(self):
        self.warm = False
        self.warmed: set = set()

    def is_batch_warm(self, n, n_slots=1):
        return self.warm

    def enumerate_shapes(self, n=1, full=True):
        return [("verify", 8, 8)]


@pytest.mark.parametrize("seed", [41, 97])
def test_coalescing_parity_with_cold_fallback(seed):
    """Randomized stream through the coalescing engine — including
    linger-deadline flushes and the cold-shape scalar fallback flipping
    to the primary verifier MID-RUN — produces certificates
    byte-identical to the scalar try_add_vote golden path."""
    pvs, vals = make_pvs(7)  # total 70, quorum 47 -> 5 votes needed
    txs = [b"co%d-%d=%d" % (seed, i, i) for i in range(16)]
    stream = _mixed_stream(pvs, txs, seed)

    # sub-bucket tail: fed only after the main stream drains, so these 3
    # votes can never join a full bucket — they MUST leave via the linger
    # deadline (stake 30 < quorum 47: pending in a vote set, no commit)
    tail_tx = b"co%d-tail=1" % seed
    tail = [sign_vote(pv, tail_tx) for pv in pvs[:3]]

    # scalar golden path
    flow_s, mem_s, _, store_s, app_s = make_engine(vals, use_device=False)
    for tx in txs + [tail_tx]:
        mem_s.check_tx(tx)
    for v in stream + tail:
        flow_s.try_add_vote(v.copy())

    # coalescing engine: duck-typed bucket ladder on a scalar verifier
    # (the coalescer activates off verifier.buckets, device not needed)
    primary = ScalarVoteVerifier(vals)
    primary.buckets = (8, 32)
    primary_calls = {"n": 0}
    orig_vt = primary.verify_and_tally

    def spy(*a, **kw):
        primary_calls["n"] += 1
        return orig_vt(*a, **kw)

    primary.verify_and_tally = spy
    flow_p, mem_p, pool_p, store_p, app_p = make_engine(
        vals,
        use_device=False,
        verifier=primary,
        max_batch=32,
        min_batch=4,
        pipeline_depth=3,
        coalesce=True,
        coalesce_linger=0.02,
    )
    # cold-shape gate: batches demote to the fallback until promotion
    gate = FakeWarmGate()
    flow_p._warm_gate = gate
    flow_p._cold_fallback = ScalarVoteVerifier(vals)
    for tx in txs + [tail_tx]:
        mem_p.check_tx(tx)
    flow_p.start()
    try:
        assert flow_p._coalescer is not None, "bucket ladder not picked up"
        half = len(stream) // 2
        for v in stream[:half]:
            try:
                pool_p.check_tx(v)
            except Exception:
                pass  # stranger/dup — the scalar path saw the vote anyway
        deadline = time.monotonic() + 10.0
        while flow_p._cold_fallback_votes == 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert flow_p._cold_fallback_votes > 0, "no batch took the fallback"
        gate.warm = True  # background warmer finished: promote
        for v in stream[half:]:
            try:
                pool_p.check_tx(v)
            except Exception:
                pass
        assert _wait_quiescent(flow_p, pool_p), "coalescing engine never drained"
        for v in tail:
            pool_p.check_tx(v)
        assert _wait_quiescent(flow_p, pool_p), "tail dribble never flushed"
    finally:
        flow_p.stop()

    # the dispatch-shaping actually happened: canonical full buckets AND
    # linger flushes for the sub-bucket tail, then post-promotion batches
    # on the primary verifier
    co = flow_p._coalescer
    assert co.full_batches > 0
    assert co.linger_flushes > 0
    assert primary_calls["n"] > 0, "no batch promoted to the primary verifier"
    stats = flow_p.pipeline_stats()
    assert stats["coalesce"]["enabled"]
    assert stats["coalesce"]["cold_fallback_votes"] == flow_p._cold_fallback_votes
    assert stats["warmup"]["total_shapes"] == 1

    # decisions byte-identical to the golden path
    assert app_p.tx_count == app_s.tx_count
    assert app_p.state == app_s.state
    assert app_p.digest == app_s.digest  # commit ORDER identical
    for tx in txs + [tail_tx]:
        tx_hash = hashlib.sha256(tx).hexdigest().upper()
        cs = store_s.load_tx_commit(tx_hash)
        cp = store_p.load_tx_commit(tx_hash)
        assert (cs is None) == (cp is None)
        if cs is not None:
            assert [
                (c.validator_address, c.signature) for c in cs.commits
            ] == [(c.validator_address, c.signature) for c in cp.commits]
    for tx_hash, vs in flow_s.vote_sets.items():
        assert flow_p.vote_sets[tx_hash].stake() == vs.stake()


def test_coalescer_inactive_without_bucket_ladder():
    """A plain scalar verifier exposes no buckets: coalesce=True must
    leave the legacy min_batch/_form_batch path untouched."""
    pvs, vals = make_pvs(4)
    flow, mempool, votepool, _, app = make_engine(
        vals, use_device=False, coalesce=True, min_batch=1
    )
    tx = b"nocoal=1"
    mempool.check_tx(tx)
    flow.start()
    try:
        for pv in pvs[:3]:
            votepool.check_tx(sign_vote(pv, tx))
        assert _wait_quiescent(flow, votepool)
    finally:
        flow.stop()
    assert flow._coalescer is None
    assert app.tx_count == 1
    assert flow.pipeline_stats()["coalesce"]["enabled"] is False


# ---- shape registry covers every coalescer-emittable shape ------------


def test_registry_enumerates_every_coalescer_shape():
    """Tier-1 guard for compile_in_run == 0: for EVERY batch size the
    coalescer can emit (bucket sizes, linger flushes of any smaller
    size, retry-inflated sizes up to the cap), the shapes the verifier
    can dispatch are inside the prewarm enumeration."""
    vals, _seeds = make_valset(4)
    # cached config (the engine/bench default): slot width is pinned to
    # the floor bucket, so containment must hold for ANY n_slots
    dev = DeviceVoteVerifier(vals, buckets=(64, 256), shared_cache=VerifyCache())
    reg = ShapeWarmRegistry(dev)
    universe = set(reg.enumerate_shapes(full=True))
    sizes = sorted(
        {1, 2, dev.max_batch}
        | {b for b in dev.buckets}
        | {b - 1 for b in dev.buckets}
        | {b + 1 for b in dev.buckets if b + 1 <= dev.max_batch}
        | set(dev.miss_buckets)
    )
    for n in sizes:
        for n_slots in (1, max(1, n // 2), n):
            got = set(reg.shapes_for_batch(n, n_slots))
            assert got, f"no shapes predicted for n={n}"
            assert got <= universe, (n, n_slots, got - universe)

    # fused config: slot bucket tracks n_slots; warmup's contract covers
    # the single-slot and full-width combos the engine dispatches
    dev_f = DeviceVoteVerifier(vals, buckets=(64, 256))
    reg_f = ShapeWarmRegistry(dev_f)
    universe_f = set(reg_f.enumerate_shapes(full=True))
    for n in (1, 63, 64, 65, 256):
        for n_slots in (1, n):
            got = set(reg_f.shapes_for_batch(n, n_slots))
            assert got <= universe_f, (n, n_slots, got - universe_f)

    # scalar verifier: no compiled shapes, every batch warm by definition
    reg_s = ShapeWarmRegistry(ScalarVoteVerifier(vals))
    assert reg_s.shapes_for_batch(100) == []
    assert reg_s.is_batch_warm(100)


def test_background_warmer_promotes_registry():
    """BackgroundWarmer compiles the enumeration off the hot path: the
    registry flips from cold to warm without prewarm, and nothing the
    warmer compiled reads as an in-run compile."""
    vals, _seeds = make_valset(4)
    dev = DeviceVoteVerifier(vals, buckets=(64,), shared_cache=VerifyCache())
    reg = ShapeWarmRegistry(dev)
    assert not reg.is_batch_warm(5)
    warmer = BackgroundWarmer(reg, full=True)
    warmer._run()  # synchronous: the thread body, minus the thread
    assert warmer.compiled >= 1 and warmer.failed == 0
    assert reg.is_batch_warm(5)
    assert reg.is_batch_warm(64)
    assert reg.cold_shapes() == []  # warmer compiles are warm, not cold
    # a warmed registry stays consistent with a real dispatch
    msgs, sigs, vidx, slot = make_batch(vals, _seeds, n_txs=2)
    dev.verify_and_tally(msgs, sigs, vidx, slot, 2)
    assert reg.cold_shapes() == []

    # scalar verifier: start() is a no-op, no thread ever exists
    w2 = BackgroundWarmer(ShapeWarmRegistry(ScalarVoteVerifier(vals)))
    w2.start()
    assert w2._thread is None and not w2.done()


# ---- claim staleness across a slow dispatch (ADVICE r5) ---------------


def test_dispatch_heartbeats_claims_across_slow_compile():
    """_dispatch_verify_only must re-stamp the caller's VerifyCache
    claims on BOTH sides of the self._fn call: a cold-shape compile in
    there can exceed claim_ttl by orders of magnitude, and a stale claim
    hands the same votes (and the same compile) to every other engine."""
    vals, seeds = make_valset(4)
    cache = VerifyCache(claim_ttl=0.2)
    dev = DeviceVoteVerifier(vals, shared_cache=cache)
    msgs, sigs, vidx, _slot = make_batch(vals, seeds, n_txs=2)
    keys = [
        VerifyCache.key(msgs[i], sigs[i], dev._pub_keys[int(vidx[i])])
        for i in range(len(msgs))
    ]
    _, pend = cache.lookup_or_claim_many(keys)
    assert not pend.any()  # this "engine" owns every claim
    aged = time.monotonic() - 100 * cache.claim_ttl

    def age_claims():
        with cache._mtx:
            for k in keys:
                cache._inflight[k] = aged

    age_claims()  # simulate the stamps going stale before dispatch
    orig_fn = dev._fn
    seen = {}

    def slow_fn(*args):
        # another engine probing MID-DISPATCH: the pre-dispatch heartbeat
        # must have re-stamped, so the probe defers instead of stealing
        # the claims (and launching its own compile of the same shape)
        _, mid = cache.lookup_or_claim_many(keys)
        seen["mid_owned"] = bool(mid.all())
        out = orig_fn(*args)
        # stale again while the dispatch finishes: only the POST-dispatch
        # heartbeat can keep ownership into the readback window
        age_claims()
        return out

    dev._fn = slow_fn
    try:
        dev._dispatch_verify_only(msgs, sigs, vidx, claim_keys=keys)
    finally:
        dev._fn = orig_fn
    assert seen["mid_owned"], "claims went stale during the dispatch"
    _, after = cache.lookup_or_claim_many(keys)
    assert after.all(), "claims went stale between dispatch and readback"
    cache.release_many(keys)


def test_claim_keepalive_first_beat_is_immediate():
    """claim_keepalive's first heartbeat fires at thread start, not one
    interval in: with a short TTL the claims may be near-stale by the
    time the thread is scheduled."""
    cache = VerifyCache(claim_ttl=0.5)
    keys = [b"k%d" % i for i in range(3)]
    cache.lookup_or_claim_many(keys)
    aged = time.monotonic() - 100 * cache.claim_ttl
    with cache._mtx:
        for k in keys:
            cache._inflight[k] = aged
    with cache.claim_keepalive(keys):
        # well inside the first ttl/2 interval: the immediate beat must
        # already have re-stamped the aged claims
        deadline = time.monotonic() + 1.0
        while time.monotonic() < deadline:
            with cache._mtx:
                fresh = all(
                    cache._inflight[k] > aged for k in keys
                )
            if fresh:
                break
            time.sleep(0.005)
        assert fresh, "first keepalive beat did not fire immediately"
        _, pend = cache.lookup_or_claim_many(keys)
        assert pend.all()
    cache.release_many(keys)


# ---- LocalNet guard (satellite: partial hosting + consensus) ----------


def test_localnet_rejects_consensus_with_partial_hosting():
    """enable_consensus with a hosted subset silently hangs at round 0
    (the missing validators never prevote): must fail fast instead."""
    from txflow_tpu.node import LocalNet

    with pytest.raises(ValueError, match="hosting all"):
        LocalNet(4, n_nodes=2, enable_consensus=True)
    # the non-consensus subset config stays legal (bench 16/64-validator
    # sweeps host 4 nodes); no start() — construction is the assertion
    net = LocalNet(4, n_nodes=2, enable_consensus=False)
    assert len(net.nodes) == 2
