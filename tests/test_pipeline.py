"""Pipelined verify engine: submit/collect overlap, parity, drain, shapes.

Covers the verify pipeline introduced for overlap of host prep, device
verify, and commit routing:

- randomized parity: the threaded pipelined engine (pipeline_depth >= 2)
  produces BYTE-identical commit certificates and commit order to the
  scalar ``try_add_vote`` golden path, shared VerifyCache on or off;
- drain-on-stop: ``stop()`` collects every in-flight ticket — no leaked
  cache claims, no lost votes, pipeline-depth gauge back to 0;
- step accounting: ``step()`` returns decided + dropped, and
  ``last_step_stats`` reconciles decided + requeued == verified batch;
- ShapeWarmRegistry: prewarm covers every shape a run dispatches
  (compile_in_run() False), cold dispatches are detected;
- async submit surfaces: VerifierMux ticket path and the
  ResilientVoteVerifier collect-time fallback (FlakyVerifier
  fail_at="result").
"""

import hashlib
import random
import time

import numpy as np
import pytest

from txflow_tpu.abci import AppConns, KVStoreApplication
from txflow_tpu.engine import ShapeWarmRegistry, TxExecutor, TxFlow
from txflow_tpu.faults import FlakyVerifier
from txflow_tpu.pool import Mempool, TxVotePool
from txflow_tpu.store import MemDB, TxStore
from txflow_tpu.types import MockPV, TxVote, Validator, ValidatorSet
from txflow_tpu.types.tx_vote import canonical_sign_bytes
from txflow_tpu.utils.config import EngineConfig, MempoolConfig
from txflow_tpu.utils.events import EventBus
from txflow_tpu.verifier import (
    ResilientVoteVerifier,
    ScalarVoteVerifier,
    VerifierMux,
    VerifyCache,
)

CHAIN_ID = "txflow-test"
HEIGHT = 1


def make_pvs(n=4):
    pvs = sorted((MockPV() for _ in range(n)), key=lambda p: p.get_address())
    vals = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs])
    by_addr = {pv.get_address(): pv for pv in pvs}
    return [by_addr[v.address] for v in vals], vals


def make_engine(vals, use_device=True, verifier=None, **cfg_kw):
    conns = AppConns(KVStoreApplication())
    mempool = Mempool(MempoolConfig(cache_size=4000), conns.mempool)
    commitpool = Mempool(MempoolConfig(cache_size=4000))
    votepool = TxVotePool(MempoolConfig(cache_size=20000))
    tx_store = TxStore(MemDB())
    bus = EventBus()
    execu = TxExecutor(conns.consensus, mempool, event_bus=bus)
    flow = TxFlow(
        CHAIN_ID,
        HEIGHT,
        vals,
        votepool,
        mempool,
        commitpool,
        execu,
        tx_store,
        config=EngineConfig(use_device=use_device, **cfg_kw),
        verifier=verifier,
    )
    return flow, mempool, votepool, tx_store, conns.app


def sign_vote(pv, tx: bytes, height=HEIGHT, ts=1700000000_000000000) -> TxVote:
    v = TxVote(
        height=height,
        tx_hash=hashlib.sha256(tx).hexdigest().upper(),
        tx_key=hashlib.sha256(tx).digest(),
        timestamp_ns=ts,
        validator_address=pv.get_address(),
    )
    pv.sign_tx_vote(CHAIN_ID, v)
    return v


def _mixed_stream(pvs, txs, seed):
    """Randomized vote stream: <=1 vote per (tx, validator), ~15%
    corrupted signatures, plus stranger (non-validator) votes."""
    rng = random.Random(seed)
    stranger = MockPV()
    stream = []
    for tx in txs:
        voters = rng.sample(range(len(pvs)), rng.randint(2, len(pvs)))
        for vi in voters:
            vote = sign_vote(pvs[vi], tx)
            if rng.random() < 0.15:
                vote.signature = bytes(64)  # byzantine: garbage signature
            stream.append(vote)
        if rng.random() < 0.3:
            stream.append(sign_vote(stranger, tx))
    rng.shuffle(stream)
    return stream


def _wait_quiescent(flow, votepool, timeout=30.0):
    """Wait until the threaded engine has visited every pool entry, holds
    no retries, and drained its commit queue — twice in a row, so a batch
    formed between the checks can't fake quiescence."""
    deadline = time.monotonic() + timeout
    stable = 0
    while time.monotonic() < deadline:
        idle = (
            flow._drain_cursor >= votepool.seq()
            and not flow._retry
            and flow.commits_drained()
        )
        stable = stable + 1 if idle else 0
        if stable >= 3:
            return True
        time.sleep(0.02)
    return False


@pytest.mark.parametrize("seed,shared_cache", [(11, False), (23, True)])
def test_pipelined_matches_scalar_golden_path(seed, shared_cache):
    """Commit certificates from the threaded pipelined engine are
    BYTE-identical (same signatures, same order) to the scalar
    ``try_add_vote`` reference, for a shuffled honest/byzantine stream."""
    pvs, vals = make_pvs(7)  # total 70, quorum 47 -> 5 votes needed
    txs = [b"pp%d-%d=%d" % (seed, i, i) for i in range(14)]
    stream = _mixed_stream(pvs, txs, seed)

    # scalar golden path: one vote at a time through try_add_vote
    flow_s, mem_s, _, store_s, app_s = make_engine(vals, use_device=False)
    for tx in txs:
        mem_s.check_tx(tx)
    for v in stream:
        flow_s.try_add_vote(v.copy())

    # pipelined engine: same stream via the pool, threaded run loop with
    # tickets in flight; small batches force many overlapping steps
    verifier = None
    if shared_cache:
        verifier = ScalarVoteVerifier(vals, shared_cache=VerifyCache())
    flow_p, mem_p, pool_p, store_p, app_p = make_engine(
        vals,
        use_device=False,
        verifier=verifier,
        max_batch=17,
        min_batch=1,
        pipeline_depth=3,
    )
    for tx in txs:
        mem_p.check_tx(tx)
    flow_p.start()
    try:
        for v in stream:
            try:
                pool_p.check_tx(v)
            except Exception:
                pass  # cache dup etc. — the scalar path saw the vote anyway
        assert _wait_quiescent(flow_p, pool_p), "pipelined engine never drained"
    finally:
        flow_p.stop()

    assert app_p.tx_count == app_s.tx_count
    assert app_p.state == app_s.state
    assert app_p.digest == app_s.digest  # commit ORDER identical
    for tx in txs:
        tx_hash = hashlib.sha256(tx).hexdigest().upper()
        cs = store_s.load_tx_commit(tx_hash)
        cp = store_p.load_tx_commit(tx_hash)
        assert (cs is None) == (cp is None)
        if cs is not None:
            # byte-identical certificates: same validators, same
            # signatures, same order
            assert [
                (c.validator_address, c.signature) for c in cs.commits
            ] == [(c.validator_address, c.signature) for c in cp.commits]
    for tx_hash, vs in flow_s.vote_sets.items():
        assert flow_p.vote_sets[tx_hash].stake() == vs.stake()
    stats = flow_p.pipeline_stats()
    assert stats["depth"] == 3 and stats["steps"] > 0


def test_stop_drains_inflight_tickets():
    """stop() must collect and route every in-flight ticket: the cache
    holds no stranded claims, the depth gauge reads 0, and every injected
    vote is either decided or still in the pool (none lost)."""
    pvs, vals = make_pvs(4)
    cache = VerifyCache()
    flow, mempool, votepool, store, app = make_engine(
        vals,
        use_device=False,
        verifier=ScalarVoteVerifier(vals, shared_cache=cache),
        max_batch=8,
        min_batch=1,
        pipeline_depth=4,
    )
    txs = [b"drain%d=v" % i for i in range(50)]
    votes = [sign_vote(pv, tx) for tx in txs for pv in pvs[:3]]
    for tx in txs:
        mempool.check_tx(tx)
    flow.start()
    try:
        for v in votes:
            votepool.check_tx(v)
    finally:
        # stop with work still flowing: the run loop's finally block must
        # drain the in-flight tail
        flow.stop()

    assert flow.metrics.pipeline_depth.value() == 0, "orphaned tickets"
    assert not cache._inflight, "leaked cache claims after stop"
    # no vote lost: whatever was not decided is still in the pool or the
    # retry set, so serial steps can finish the job deterministically
    while flow.step():
        pass
    assert app.tx_count == len(txs)
    for tx in txs:
        cert = store.load_tx_commit(hashlib.sha256(tx).hexdigest().upper())
        assert cert is not None and len(cert.commits) == 3
    assert not cache._inflight


def test_step_accounting_reconciles():
    """step() returns decided + dropped; requeued votes are NOT counted
    until the step that decides them, and last_step_stats always
    reconciles decided + requeued == verified batch size."""
    pvs, vals = make_pvs(4)
    flow, mempool, votepool, _, app = make_engine(vals, use_device=False)
    tx = b"acct=1"
    mempool.check_tx(tx)
    for pv in pvs[:3]:
        votepool.check_tx(sign_vote(pv, tx))
    # conflicting second vote from validator 0 (same (tx, validator), new
    # timestamp): the in-batch first-occurrence mask defers it to _retry
    votepool.check_tx(sign_vote(pvs[0], tx, ts=1700000001_000000000))

    got = flow.step()
    s = flow.last_step_stats
    assert s["batch"] == 4
    assert s["decided"] + s["requeued"] == s["batch"]
    assert s["requeued"] == 1  # the in-batch duplicate
    assert got == s["decided"] + s["dropped"] == 3
    assert app.tx_count == 1  # quorum 30 >= 27 committed

    # the requeued conflict's tx has committed meanwhile, so the next
    # step drops it at DRAIN time (late vote, never re-verified): counted
    # once, as a drop, not as a decision
    got2 = flow.step()
    s2 = flow.last_step_stats
    assert s2 == {"decided": 0, "requeued": 0, "dropped": 1, "batch": 0}
    assert got2 == 1
    total = s["decided"] + s2["decided"] + s["dropped"] + s2["dropped"]
    assert total == 4, "every vote counted exactly once across steps"
    while flow.step():
        pass  # terminates: no votes left
    assert votepool.size() == 0


@pytest.mark.slow
def test_shape_warm_registry_covers_run():
    """prewarm() compiles and snapshots every reachable shape; a dispatch
    inside the covered envelope is compile-free (compile_in_run() False),
    and an unwarmed verifier's dispatch is flagged cold."""
    from txflow_tpu.verifier import DeviceVoteVerifier

    pvs, vals = make_pvs(4)
    ver = DeviceVoteVerifier(vals, buckets=(8,), shared_cache=False)
    reg = ShapeWarmRegistry(ver)
    warm = reg.prewarm(full=True)
    assert warm, "prewarm recorded no shapes"
    # the prediction mirrors warmup's coverage: everything it enumerates
    # was actually dispatched
    assert set(reg.enumerate_shapes(full=True)) <= set(warm)

    # a real batch inside the warmed envelope: no cold compile
    msgs, sigs, vidx, slot = [], [], [], []
    for t in range(2):
        tx_hash = hashlib.sha256(b"shape-tx%d" % t).hexdigest().upper()
        for vi, pv in enumerate(pvs):
            v = TxVote(
                height=HEIGHT,
                tx_hash=tx_hash,
                tx_key=hashlib.sha256(b"shape-tx%d" % t).digest(),
                timestamp_ns=1700000000_000000000,
                validator_address=pv.get_address(),
            )
            pv.sign_tx_vote(CHAIN_ID, v)
            msgs.append(canonical_sign_bytes(CHAIN_ID, HEIGHT, tx_hash, v.timestamp_ns))
            sigs.append(v.signature)
            vidx.append(vi)
            slot.append(t)
    res = ver.verify_and_tally(msgs, sigs, np.array(vidx), np.array(slot), 2)
    assert bool(res.valid.all())
    assert reg.cold_shapes() == []
    assert reg.compile_in_run() is False

    # an unwarmed registry flags the same dispatch as an in-run compile
    ver2 = DeviceVoteVerifier(vals, buckets=(8,), shared_cache=False)
    reg2 = ShapeWarmRegistry(ver2)  # no prewarm
    ver2.verify_and_tally(msgs, sigs, np.array(vidx), np.array(slot), 2)
    assert reg2.compile_in_run() is True


def test_engine_prewarms_shapes_on_start():
    """EngineConfig.prewarm_shapes builds the registry at start() so no
    shape compiles inside the pipeline (scalar verifier degrades to the
    empty shape set, exercising the gate cheaply in tier-1)."""
    pvs, vals = make_pvs(4)
    flow, mempool, votepool, _, app = make_engine(
        vals, use_device=False, prewarm_shapes=True
    )
    flow.start()
    try:
        assert flow._shape_registry is not None
        assert flow._shape_registry.cold_shapes() == []
        tx = b"prewarm=v"
        mempool.check_tx(tx)
        for pv in pvs[:3]:
            votepool.check_tx(sign_vote(pv, tx))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and app.tx_count < 1:
            time.sleep(0.01)
        assert app.tx_count == 1
    finally:
        flow.stop()


def _rig_batch(pvs, vals, n_txs=2):
    by_addr = {pv.get_address(): pv for pv in pvs}
    msgs, sigs, vidx, slot = [], [], [], []
    for t in range(n_txs):
        tx_hash = hashlib.sha256(b"rig-tx%d" % t).hexdigest().upper()
        for vi, val in enumerate(vals.validators):
            v = TxVote(
                height=HEIGHT,
                tx_hash=tx_hash,
                tx_key=hashlib.sha256(b"rig-tx%d" % t).digest(),
                timestamp_ns=1700000000_000000000 + t,
                validator_address=val.address,
            )
            by_addr[val.address].sign_tx_vote(CHAIN_ID, v)
            msgs.append(canonical_sign_bytes(CHAIN_ID, HEIGHT, tx_hash, v.timestamp_ns))
            sigs.append(v.signature)
            vidx.append(vi)
            slot.append(t)
    return (msgs, sigs, np.array(vidx), np.array(slot), n_txs)


def _assert_same(result, golden):
    np.testing.assert_array_equal(result.valid, golden.valid)
    np.testing.assert_array_equal(result.stake, golden.stake)
    np.testing.assert_array_equal(result.maj23, golden.maj23)


def test_mux_submit_returns_tickets():
    """VerifierMux.submit: the caller gets a ticket immediately and can
    dispatch the next batch before collecting — results identical to the
    blocking path, in submission order, and stop() leaves nothing hung."""
    pvs, vals = make_pvs(4)
    golden_ver = ScalarVoteVerifier(vals)
    mux = VerifierMux(ScalarVoteVerifier(vals), gather_wait=0.002, pipeline_depth=2)

    # not started: passthrough still returns a working ticket
    batch_a = _rig_batch(pvs, vals, n_txs=2)
    t = mux.submit(*batch_a)
    _assert_same(t.result(), golden_ver.verify_and_tally(*batch_a))

    mux.start()
    try:
        t1 = mux.submit(*batch_a)
        batch_b = _rig_batch(pvs, vals, n_txs=3)
        t2 = mux.submit(*batch_b)  # dispatched before t1 is collected
        _assert_same(t1.result(), golden_ver.verify_and_tally(*batch_a))
        _assert_same(t2.result(), golden_ver.verify_and_tally(*batch_b))
        _assert_same(t2.result(), golden_ver.verify_and_tally(*batch_b))  # memoized
    finally:
        mux.stop()


def test_resilient_collect_failure_falls_back():
    """A ticket whose READBACK fails (FlakyVerifier fail_at='result')
    must surface the degradation policy at collect time: the batch is
    re-served via the blocking policy path and the error is recorded."""
    pvs, vals = make_pvs(4)
    batch = _rig_batch(pvs, vals)
    golden = ScalarVoteVerifier(vals).verify_and_tally(*batch)

    flaky = FlakyVerifier(
        ScalarVoteVerifier(vals), fail_calls=(0,), fail_at="result"
    )
    r = ResilientVoteVerifier(
        flaky,
        fallback=ScalarVoteVerifier(vals),
        max_attempts=2,
        backoff_base=0.001,
        sleep=lambda _s: None,
    )
    ticket = r.submit(*batch)  # dispatch succeeds; readback will fail
    _assert_same(ticket.result(), golden)
    assert r.device_failures >= 1
    assert flaky.calls >= 2, "policy re-run never went back to the device"
    assert r.device_healthy  # the re-run succeeded on the device lane

    # dispatch-time failure degrades the same way
    flaky2 = FlakyVerifier(
        ScalarVoteVerifier(vals), fail_calls=(0,), fail_at="submit"
    )
    r2 = ResilientVoteVerifier(
        flaky2,
        fallback=ScalarVoteVerifier(vals),
        max_attempts=2,
        backoff_base=0.001,
        sleep=lambda _s: None,
    )
    _assert_same(r2.submit(*batch).result(), golden)
    assert r2.device_failures >= 1


def test_segs_for_tx_indexed():
    """The per-tx index returns exactly the live votes for one tx, in
    insertion order, and stays consistent through remove/update/flush."""
    from txflow_tpu.pool.txvotepool import vote_key

    pvs, vals = make_pvs(4)
    pool = TxVotePool(MempoolConfig(cache_size=1000))
    tx_a, tx_b = b"seg-a=v", b"seg-b=v"
    votes_a = [sign_vote(pv, tx_a) for pv in pvs]
    votes_b = [sign_vote(pv, tx_b) for pv in pvs[:2]]
    for v in votes_a + votes_b:
        pool.check_tx(v)
    h_a = hashlib.sha256(tx_a).hexdigest().upper()
    h_b = hashlib.sha256(tx_b).hexdigest().upper()
    assert pool.segs_for_tx(h_a) == [v._seg_cache for v in votes_a]
    assert pool.segs_for_tx(h_b) == [v._seg_cache for v in votes_b]
    assert pool.segs_for_tx(h_a, limit=2) == [v._seg_cache for v in votes_a[:2]]
    assert pool.segs_for_tx("NOPE") == []

    pool.remove([vote_key(votes_a[0])])
    assert pool.segs_for_tx(h_a) == [v._seg_cache for v in votes_a[1:]]
    pool.update(1, votes_a[1:])
    assert pool.segs_for_tx(h_a) == []
    assert pool._by_tx.get(h_a) is None  # empty buckets are pruned
    assert pool.segs_for_tx(h_b) == [v._seg_cache for v in votes_b]
    pool.flush()
    assert pool.segs_for_tx(h_b) == []
    assert pool._by_tx == {}
