"""Accountable vote gossip (health/byzantine.py + reactor pre-checks +
engine verdict attribution): a Byzantine vote flood is struck, quarantined
at the front door, and priced out of the device — while honest traffic
commits with zero loss and certificates stay byte-identical to the scalar
golden path.

Layers under test:
- ByzantineLedger unit behavior: breaker window/decay, replay opt-in,
  origin attribution, scoreboard charging, sync-strike unification;
- TxVotePool origin bookkeeping (both ingest twins) + add_sender codes;
- engine _route_result -> on_invalid_votes -> ledger strikes;
- reactor O(1) pre-checks (unknown validator / stale height / replay)
  with per-peer accounting, deterministic via crafted frames;
- the tier-1 LocalNet drill: 1-of-4 Byzantine validator + 1 malicious
  non-validator peer, all honest txs commit, every adversary struck AND
  quarantined, post-quarantine device waste bounded (< 5% invalid);
- the equivocator: fast-path stake counted once, block-path evidence
  slashed everywhere (PR 7 bridge), post-slash votes pre-dropped;
- the selective withholder: liveness holds, withheld txs certify
  without the withholder's key.
"""

import hashlib
import time

from txflow_tpu.abci import KVStoreApplication
from txflow_tpu.epoch import EpochConfig
from txflow_tpu.faults import (
    ByzantineVoteGen,
    IdenticalVoteReplayer,
    SelectiveWithholder,
    SigGarbageFlooder,
    StaleVoteSpammer,
    TxVoteEquivocator,
)
from txflow_tpu.faults.byzantine import _encode_vote_frame
from txflow_tpu.health.byzantine import (
    DROP_QUARANTINED,
    DROP_REPLAYED_SIG,
    DROP_STALE_HEIGHT,
    DROP_UNKNOWN_VALIDATOR,
    ByzantineConfig,
    ByzantineLedger,
)
from txflow_tpu.health.config import HealthConfig
from txflow_tpu.node.localnet import LocalNet
from txflow_tpu.node.node import Node, NodeConfig
from txflow_tpu.p2p import connect_switches
from txflow_tpu.p2p.base import CHANNEL_TXVOTE
from txflow_tpu.pool import TxVotePool
from txflow_tpu.pool.mempool import TxInfo
from txflow_tpu.types import MockPV
from txflow_tpu.utils.config import MempoolConfig
from txflow_tpu.utils.config import test_config as make_test_config

from test_engine import make_engine, make_pvs, sign_vote


def wait_until(pred, timeout=30.0, poll=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return pred()


class FakeScoreboard:
    def __init__(self):
        self.calls = []  # (node_id, points)

    def punish(self, node_id, points, now=None):
        self.calls.append((node_id, points))

    def total(self, node_id):
        return sum(p for n, p in self.calls if n == node_id)


# -- ByzantineLedger units -------------------------------------------------


def test_ledger_breaker_trips_on_bad_rate_and_expires():
    led = ByzantineLedger(
        ByzantineConfig(min_samples=8, max_bad_rate=0.5, quarantine_secs=10.0)
    )
    # 4 kept + 4 unknown-validator drops = 8 judged events, half bad
    led.note_frame("p", 4, {DROP_UNKNOWN_VALIDATOR: 4}, now=0.0)
    assert led.quarantined("p", now=0.5)
    assert led.quarantined("p", now=9.9)
    assert not led.quarantined("p", now=10.1)  # sentence served
    snap = led.snapshot(now=1.0)
    assert snap["quarantines"] == 1
    assert snap["strikes"] >= 1
    assert snap["pre_verify_drops"] == 4
    assert snap["quarantined_peers"] == ["p"]
    rec = snap["peers"]["p"]
    assert rec["relayed"] == 4 and rec["quarantined"]
    assert rec["drops"] == {DROP_UNKNOWN_VALIDATOR: 4}


def test_ledger_below_min_samples_never_trips():
    led = ByzantineLedger(ByzantineConfig(min_samples=32, max_bad_rate=0.5))
    # 100% bad rate but only 8 samples: the breaker must hold fire
    led.note_frame("p", 0, {DROP_STALE_HEIGHT: 8}, now=0.0)
    assert not led.quarantined("p", now=0.1)


def test_ledger_window_decays_ratio_preserving():
    led = ByzantineLedger(ByzantineConfig(window=8, min_samples=100))
    led.note_frame("p", 8, now=0.0)  # hits window -> halves
    rec = led._peers["p"]
    assert rec.win_events == 4 and rec.win_bad == 0
    led.note_frame("p", 0, {DROP_UNKNOWN_VALIDATOR: 4}, now=0.1)
    rec = led._peers["p"]
    # 8 events (4 old good + 4 new bad) halved to 4 events / 2 bad:
    # the bad fraction survives the decay, the raw magnitude does not
    assert rec.win_events == 4 and rec.win_bad == 2


def test_ledger_replay_breaker_is_opt_in():
    flood = {DROP_REPLAYED_SIG: 8}
    off = ByzantineLedger(
        ByzantineConfig(min_samples=4, max_bad_rate=0.5, quarantine_replays=False)
    )
    off.note_frame("p", 0, flood, now=0.0)
    # replays are counted and surfaced but never trip the default breaker
    # (watchdog re-offers are honest same-peer repeats)
    assert not off.quarantined("p", now=0.1)
    assert off.snapshot(now=0.1)["peers"]["p"]["drops"] == flood

    on = ByzantineLedger(
        ByzantineConfig(min_samples=4, max_bad_rate=0.5, quarantine_replays=True)
    )
    on.note_frame("p", 0, flood, now=0.0)
    assert on.quarantined("p", now=0.1)


def test_ledger_attributes_origins_and_charges_scoreboard():
    sb = FakeScoreboard()
    led = ByzantineLedger(ByzantineConfig(strike_penalty=0.75), scoreboard=sb)
    led.register_peer(7, "peer-a")
    led.register_peer(9, "peer-b")
    # two verdicts for peer-a, one for peer-b; 0 = local/RPC/WAL ingest
    # and 42 was never registered: both must be skipped, not crash
    led.note_invalid_origins([7, 7, 9, 0, 42], now=1.0)
    assert led.strikes_of("peer-a") == 2
    assert led.strikes_of("peer-b") == 1
    assert sb.total("peer-a") == 2 * 0.75
    assert sb.total("peer-b") == 0.75
    snap = led.snapshot(now=1.0)
    assert snap["strikes"] == 3
    assert snap["peers"]["peer-a"]["invalid"] == 2


def test_ledger_verdict_flood_trips_once_and_charges_trip_penalty():
    sb = FakeScoreboard()
    led = ByzantineLedger(
        ByzantineConfig(
            min_samples=4, max_bad_rate=0.5, strike_penalty=0.5,
            quarantine_penalty=16.0, quarantine_secs=30.0,
        ),
        scoreboard=sb,
    )
    led.register_peer(1, "flooder")
    led.note_invalid_origins([1, 1, 1, 1], now=0.0)
    assert led.quarantined("flooder", now=0.1)
    assert led.snapshot(now=0.1)["quarantines"] == 1
    assert sb.total("flooder") == 4 * 0.5 + 16.0
    # more verdicts while serving the sentence: strikes accrue, but no
    # re-trip (and no second quarantine_penalty) until it expires
    led.note_invalid_origins([1, 1, 1, 1], now=1.0)
    assert led.snapshot(now=1.1)["quarantines"] == 1
    assert sb.total("flooder") == 8 * 0.5 + 16.0


def test_ledger_sync_strike_quarantines_without_double_charge():
    sb = FakeScoreboard()
    led = ByzantineLedger(ByzantineConfig(), scoreboard=sb)
    led.note_sync_strike("forger", now=0.0)
    # a peer proven to forge sync data loses its vote-gossip privileges
    assert led.quarantined("forger", now=0.1)
    snap = led.snapshot(now=0.1)
    assert snap["peers"]["forger"]["sync_strikes"] == 1
    assert snap["peers"]["forger"]["quarantines"] == 1
    # the sync client already charged the scoreboard for this offense;
    # the ledger must not double-charge it
    assert sb.calls == []


# -- TxVotePool origin bookkeeping ----------------------------------------


def test_pool_origin_set_by_both_ingest_twins():
    pvs, _vals = make_pvs(4)
    pool = TxVotePool(MempoolConfig(cache_size=100))
    v1 = sign_vote(pvs[0], b"origin-a")
    v2 = sign_vote(pvs[1], b"origin-b")
    v3 = sign_vote(pvs[2], b"origin-c")
    pool.check_tx(v1, tx_info=TxInfo(sender_id=5))       # raising twin
    pool.check_tx_many([v2], tx_info=TxInfo(sender_id=7))  # batch twin
    pool.check_tx(v3)  # local ingest: no peer to strike
    keys = [v.vote_key() for v in (v1, v2, v3)]
    assert pool.origins_of(keys) == [5, 7, 0]


def test_pool_add_sender_codes_and_origin_stability():
    pvs, _vals = make_pvs(4)
    pool = TxVotePool(MempoolConfig(cache_size=100))
    v = sign_vote(pvs[0], b"codes")
    pool.check_tx(v, tx_info=TxInfo(sender_id=3))
    key = v.vote_key()
    assert pool.add_sender(key, 4) == TxVotePool.SENDER_ADDED
    assert pool.add_sender(key, 4) == TxVotePool.SENDER_REPEAT
    # the origin peer re-sending is also a repeat...
    assert pool.add_sender(key, 3) == TxVotePool.SENDER_REPEAT
    # ...and extra senders never rewrite the attribution
    assert pool.origins_of([key]) == [3]
    pool.remove([key])
    assert pool.add_sender(key, 4) == TxVotePool.SENDER_GONE
    assert pool.origins_of([key]) == [0]
    # truthiness contract for pre-ledger callers: only GONE falls through
    assert not TxVotePool.SENDER_GONE
    assert TxVotePool.SENDER_ADDED and TxVotePool.SENDER_REPEAT


# -- engine -> ledger flow -------------------------------------------------


def test_engine_attributes_invalid_verdicts_to_origin():
    pvs, vals = make_pvs(4)
    flow, mempool, _commit, votepool, _store, app, _bus = make_engine(
        vals, use_device=False
    )
    sb = FakeScoreboard()
    led = ByzantineLedger(ByzantineConfig(strike_penalty=0.75), scoreboard=sb)
    led.register_peer(5, "flooder")
    flow.on_invalid_votes = led.note_invalid_origins

    tx = b"attr=1"
    mempool.check_tx(tx)
    for pv in pvs[:3]:
        votepool.check_tx(sign_vote(pv, tx))
    garbage = sign_vote(pvs[3], tx)
    garbage.signature = bytes(64)
    votepool.check_tx(garbage, tx_info=TxInfo(sender_id=5))
    flow.step()

    # honest quorum committed; the forged vote struck its relaying peer
    assert app.tx_count == 1
    assert led.strikes_of("flooder") == 1
    assert led.snapshot()["peers"]["flooder"]["invalid"] == 1
    assert sb.total("flooder") == 0.75

    # a locally-ingested garbage vote (origin 0) strikes nobody
    tx2 = b"attr=2"
    mempool.check_tx(tx2)
    bad_local = sign_vote(pvs[0], tx2)
    bad_local.signature = b"\x01" * 64  # distinct forgery, distinct pool key
    votepool.check_tx(bad_local)
    flow.step()
    assert led.snapshot()["strikes"] == 1


def test_accountable_parity_batched_vs_scalar():
    """Acceptance pin: with the full accountability chain wired (per-peer
    origins on ingest + verdict attribution to a live ledger), the batched
    engine's commit decisions, app digest, and certificates remain
    byte-identical to the scalar reference on a randomized adversarial
    stream — accountability observes the verify path, never steers it."""
    import random

    rng = random.Random(1337)
    pvs, vals = make_pvs(7)  # total 70, quorum 47 -> 5 votes needed
    txs = [b"acct%d=%d" % (i, i) for i in range(12)]

    stream = []
    n_corrupt = 0
    for tx in txs:
        for vi in rng.sample(range(7), rng.randint(2, 7)):
            vote = sign_vote(pvs[vi], tx)
            if rng.random() < 0.15:
                # distinct garbage per vote so every forgery is its own
                # pool entry (and its own attributed verdict)
                vote.signature = hashlib.sha256(
                    b"corrupt%d" % len(stream)
                ).digest() * 2
                n_corrupt += 1
            stream.append(vote)
    rng.shuffle(stream)

    # scalar reference engine: one vote at a time, no accountability
    flow_s, mem_s, _cs, _ps, store_s, app_s, _ = make_engine(vals, use_device=False)
    for tx in txs:
        mem_s.check_tx(tx)
    for v in stream:
        flow_s.try_add_vote(v.copy())

    # batched engine with the ledger wired and every vote peer-attributed
    sb = FakeScoreboard()
    led = ByzantineLedger(ByzantineConfig(), scoreboard=sb)
    for pid, nid in ((1, "relay-1"), (2, "relay-2"), (3, "relay-3")):
        led.register_peer(pid, nid)
    flow_b, mem_b, _cb, pool_b, store_b, app_b, _ = make_engine(
        vals, use_device=False, max_batch=17
    )
    flow_b.on_invalid_votes = led.note_invalid_origins
    for tx in txs:
        mem_b.check_tx(tx)
    for i, v in enumerate(stream):
        pool_b.check_tx(v, tx_info=TxInfo(sender_id=1 + i % 3))
    while flow_b.step():
        pass

    assert app_b.tx_count == app_s.tx_count
    assert app_b.state == app_s.state
    assert app_b.digest == app_s.digest  # commit ORDER identical
    for tx in txs:
        tx_hash = hashlib.sha256(tx).hexdigest().upper()
        cs = store_s.load_tx_commit(tx_hash)
        cb = store_b.load_tx_commit(tx_hash)
        assert (cs is None) == (cb is None)
        if cs is not None:
            assert {c.validator_address for c in cs.commits} == {
                c.validator_address for c in cb.commits
            }
    for tx_hash, vs in flow_s.vote_sets.items():
        assert flow_b.vote_sets[tx_hash].stake() == vs.stake()
    # and the ledger saw exactly the forged deliveries, no more
    assert led.snapshot()["strikes"] == n_corrupt
    snap_peers = led.snapshot()["peers"]
    assert sum(p["invalid"] for p in snap_peers.values()) == n_corrupt


# -- reactor pre-checks: deterministic crafted frames ---------------------


def test_reactor_pre_checks_count_per_peer():
    """Unknown-validator / stale-height / replayed-signature votes die at
    the pool boundary, each counted against the relaying peer — and a
    pre-dropped frame re-delivered is re-judged (never wire-cached)."""
    rogue = ByzantineVoteGen(
        MockPV(hashlib.sha256(b"rogue-signer").digest()), "txflow-localnet"
    )
    net = LocalNet(
        2,
        use_device_verifier=False,
        # huge min_samples: accounting only, the breaker must hold fire
        byzantine_config=ByzantineConfig(min_samples=100_000),
    )
    honest = ByzantineVoteGen(net.priv_vals[0], net.chain_id)
    try:
        net.start()
        victim = net.nodes[1]
        snap = lambda: victim.byzantine_ledger.snapshot()  # noqa: E731
        drops = lambda: snap()["peers"].get("node0", {}).get("drops", {})  # noqa: E731

        # unknown validator: well-formed votes from a signer outside the set
        unknown_frame = _encode_vote_frame(
            [rogue.honest_vote(b"rogue-tx%d" % i) for i in range(3)]
        )
        net.nodes[0].switch.broadcast(CHANNEL_TXVOTE, unknown_frame)
        assert wait_until(lambda: drops().get(DROP_UNKNOWN_VALIDATOR) == 3)
        # pre-dropped segs are NOT wire-cached: redelivery is re-judged
        net.nodes[0].switch.broadcast(CHANNEL_TXVOTE, unknown_frame)
        assert wait_until(lambda: drops().get(DROP_UNKNOWN_VALIDATOR) == 6)

        # stale height: validly signed, far behind the victim's state
        victim.update_state(50)
        stale_frame = _encode_vote_frame(
            [honest.honest_vote(b"stale-tx%d" % i, height=1) for i in range(2)]
        )
        net.nodes[0].switch.broadcast(CHANNEL_TXVOTE, stale_frame)
        assert wait_until(lambda: drops().get(DROP_STALE_HEIGHT) == 2)

        # replay: a frame of fresh valid votes, sent three times — first
        # delivery kept, each repeat counted as a same-peer replay
        live_frame = _encode_vote_frame(
            [honest.honest_vote(b"live-tx%d" % i, height=50) for i in range(2)]
        )
        for _ in range(3):
            net.nodes[0].switch.broadcast(CHANNEL_TXVOTE, live_frame)
        assert wait_until(lambda: drops().get(DROP_REPLAYED_SIG) == 4)

        s = snap()
        assert s["pre_verify_drops"] == 6 + 2 + 4
        assert s["peers"]["node0"]["relayed"] >= 2  # the kept live votes
        assert not victim.byzantine_ledger.quarantined("node0")
        # the /health section and the metrics family surface the same story
        # (the monitor republishes the ledger on its tick cadence)
        assert wait_until(
            lambda: victim.health.snapshot()["byzantine"].get("pre_verify_drops")
            == 12,
            timeout=20,
        )
        exposition = victim.metrics_registry.expose()
        assert "txflow_byzantine_drop_unknown_validator 6.0" in exposition
        assert "txflow_byzantine_drop_stale_height 2.0" in exposition
        assert "txflow_byzantine_drop_replayed_sig 4.0" in exposition
    finally:
        net.stop()


# -- the tier-1 drill: survive a Byzantine vote flood ---------------------


def test_drill_byzantine_flood_localnet():
    """1-of-4 Byzantine validator (signer disarmed, floods garbage +
    stale votes through its own switch) plus a malicious non-validator
    peer (replays + unknown-signer floods). All honest txs commit with
    zero loss, every adversary is struck AND quarantined on every honest
    node, and once quarantined the flood stops reaching the device:
    < 5% of subsequently dispatched votes are invalid."""
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    # Phase 1 runs with the breaker held open (huge min_samples) so every
    # attack class provably lands in the accounting while the flood is at
    # full blast; the config object is SHARED by every node's ledger, so
    # tightening it live (phase 2) arms all breakers at once — the
    # already-poisoned windows trip on the very next judged frame.
    byz = ByzantineConfig(
        min_samples=1_000_000,
        max_bad_rate=0.5,
        stale_height_slack=8,
        quarantine_replays=True,
        replay_min_samples=1_000_000,
        replay_max_rate=0.7,
        quarantine_secs=600.0,  # outlives the assertion window
        # zero per-strike score, keeping the links up: the drill pins the
        # gossip protections; scoreboard charging is unit-tested and the
        # score-floor evict/redial cycle is sync/health-tested
        strike_penalty=0.0,
        quarantine_penalty=0.5,
    )
    net = LocalNet(
        4,
        use_device_verifier=False,
        enable_consensus=True,
        config=cfg,
        byzantine_config=byz,
        # The evil peer is SILENT until the flood phase, but honest nodes
        # gossip at it from connect: the scoreboard marks the quiet link
        # stale (stale_after 2s, -1/tick) and walks it to the score floor
        # in ~4s — evicting every evil link before the flood's drops can
        # be recorded whenever consensus reaches the flood phase late.
        # The drill pins the vote-accounting ledger; scoreboard eviction
        # has its own health/sync tests, so disarm the floor here.
        health_config=HealthConfig(
            redial_lost_peers=True, stale_penalty=0.0, score_floor=-1e9
        ),
    )
    # node0 turns Byzantine: honest fast-path signer disarmed (its
    # consensus identity stays — quorum is now exactly the 3 honest keys)
    net.nodes[0].txvote_reactor.priv_val = None
    gen0 = ByzantineVoteGen(net.priv_vals[0], net.chain_id, seed=1)
    rogue = ByzantineVoteGen(
        MockPV(hashlib.sha256(b"evil-rogue").digest()), net.chain_id, seed=2
    )
    # the malicious non-validator: a full node outside the validator set
    evil = Node(
        node_id="evil-peer",
        chain_id=net.chain_id,
        val_set=net.val_set,
        app=KVStoreApplication(),
        priv_val=None,
        node_config=NodeConfig(
            config=cfg,
            use_device_verifier=False,
            enable_consensus=False,
            sign_votes=False,
            health=False,
            sync=False,
            byzantine_config=byz,
        ),
    )

    honest_txs: list[bytes] = []
    # Forgeries target "ghost" txs that never reach any mempool: their
    # vote slots stay open forever, so every garbage signature is actually
    # judged on the verify path (votes for already-committed txs are
    # late-dropped without a verdict — free for the defender, but useless
    # for pinning attribution).
    ghost_txs = [b"ghost-target%d" % i for i in range(8)]
    targets = lambda: ghost_txs + honest_txs  # noqa: E731
    height_fn = lambda: net.nodes[1].state_view().last_block_height  # noqa: E731
    flooder = SigGarbageFlooder(
        net.nodes[0].switch, gen0, targets, height_fn,
        victim_address=net.priv_vals[1].get_address(), batch=8, interval=0.03,
    )
    staler = StaleVoteSpammer(
        net.nodes[0].switch, gen0, targets, height_fn,
        lag=1000, batch=4, interval=0.05,
    )
    rogue_flooder = SigGarbageFlooder(
        evil.switch, rogue, targets, height_fn,
        batch=12, interval=0.02,
    )
    replayer = None
    drivers = []
    honest = lambda: net.nodes[1:]  # noqa: E731

    def quarantined_everywhere(nid):
        return all(n.byzantine_ledger.quarantined(nid) for n in honest())

    def drop_everywhere(nid, reason):
        return all(
            n.byzantine_ledger.snapshot()["peers"]
            .get(nid, {}).get("drops", {}).get(reason, 0) > 0
            for n in honest()
        )

    try:
        net.start()
        evil.start()
        for n in net.nodes:
            connect_switches(evil.switch, n.switch)

        # let consensus outrun the stale slack so the stale pre-check has
        # a real horizon to enforce
        assert wait_until(lambda: height_fn() >= 10, timeout=90), height_fn()

        batch_a = [b"under-fire%d=v" % i for i in range(6)]
        honest_txs.extend(batch_a)
        for tx in batch_a:
            net.broadcast_tx(tx, node_index=1)

        # evil replays one frame of validly-signed votes forever; the votes
        # target ghost txs so the pool entries never purge and every
        # redelivery is a countable sender-repeat rather than a dup of a
        # committed vote. The frame's height sits FAR ahead of the chain:
        # consensus keeps advancing under skip_timeout_commit, and a frame
        # built at the live height crosses the stale horizon (slack 8)
        # while it is still queued behind the garbage flood on a slow CI
        # box — after which every redelivery is stale-dropped and the
        # replay class can never land in the accounting. The stale class
        # has its own dedicated spammer; this frame must stay fresh.
        h = height_fn() + 100_000
        replayer = IdenticalVoteReplayer(
            evil.switch,
            [
                ByzantineVoteGen(net.priv_vals[2], net.chain_id).honest_vote(tx, h)
                for tx in ghost_txs[:3]
            ],
            interval=0.01,
        )
        # phase 1: every adversary fires at once, breaker held open
        for d in (replayer, rogue_flooder, staler, flooder):
            d.start()
            drivers.append(d)

        # zero admitted-tx loss while the flood is at full blast
        assert net.wait_all_committed(batch_a, timeout=90)

        # every attack class lands in every honest ledger's accounting
        # (generous windows: the replay/stale frames queue behind the
        # full-blast garbage flood in a single-core CI box's ingest)
        assert wait_until(
            lambda: drop_everywhere("node0", DROP_STALE_HEIGHT), timeout=120
        )
        assert wait_until(
            lambda: drop_everywhere("evil-peer", DROP_REPLAYED_SIG), timeout=120
        )
        assert wait_until(
            lambda: drop_everywhere("evil-peer", DROP_UNKNOWN_VALIDATOR),
            timeout=120,
        )
        # ...and forged-signature verdicts attributed back to node0
        assert wait_until(
            lambda: all(
                n.byzantine_ledger.snapshot()["peers"]["node0"]["invalid"] > 0
                for n in honest()
            ),
            timeout=120,
        )
        for n in honest():
            assert n.byzantine_ledger.strikes_of("node0") > 0
        assert not any(
            n.byzantine_ledger.quarantined(nid)
            for n in honest()
            for nid in ("node0", "evil-peer")
        )

        # phase 2: arm the breakers — the poisoned windows trip on the
        # next judged frame from each adversary
        byz.min_samples = 24
        byz.replay_min_samples = 48
        assert wait_until(lambda: quarantined_everywhere("node0"), timeout=120)
        assert wait_until(lambda: quarantined_everywhere("evil-peer"), timeout=120)
        for n in honest():
            # the trip itself is a strike: a pure pre-drop flooder (never
            # judged on the device) still ends up on the strike record
            assert n.byzantine_ledger.strikes_of("evil-peer") > 0
        # the gate is absorbing the still-running flood at the front door
        assert wait_until(
            lambda: drop_everywhere("node0", DROP_QUARANTINED), timeout=30
        )
        assert wait_until(
            lambda: drop_everywhere("evil-peer", DROP_QUARANTINED), timeout=30
        )

        # post-quarantine waste bound: wait for in-flight garbage verdicts
        # to drain, then commit a fresh batch under the (blocked) flood
        def invalids():
            return [int(n.metrics.invalid_votes.value()) for n in honest()]

        stable = invalids()
        stable_since = time.monotonic()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            cur = invalids()
            if cur != stable:
                stable, stable_since = cur, time.monotonic()
            elif time.monotonic() - stable_since >= 1.0:
                break
            time.sleep(0.1)
        base = [
            (int(n.metrics.verified_votes.value()), int(n.metrics.invalid_votes.value()))
            for n in honest()
        ]

        batch_b = [b"post-quarantine%d=v" % i for i in range(6)]
        honest_txs.extend(batch_b)
        for tx in batch_b:
            net.broadcast_tx(tx, node_index=2)
        assert net.wait_all_committed(batch_b, timeout=90)

        for n, (v0, i0) in zip(honest(), base):
            dv = int(n.metrics.verified_votes.value()) - v0
            di = int(n.metrics.invalid_votes.value()) - i0
            assert dv > 0, "honest votes must still reach the device"
            rate = di / (di + dv)
            assert rate < 0.05, (
                f"{n.node_id}: post-quarantine invalid rate {rate:.3f} "
                f"(invalid {di} / dispatched {di + dv})"
            )

        # ground truth: the adversaries really were firing the whole time
        for d in drivers:
            assert d.frames > 0 and d.emitted > 0
    finally:
        for d in drivers:
            d.stop()
        evil.stop()
        net.stop()


# -- equivocator: fast path counts once, evidence path slashes ------------


def test_drill_equivocator_evidence_to_slash():
    """The TxVoteEquivocator's fast-path pairs never double-count stake
    (first-signature-wins), and the same signer's block-path conduct —
    bridged through block_evidence -> EvidencePool — is slashed on every
    node within one epoch. Post-slash, the offender's fast-path votes
    become unknown-validator pre-drops on the honest ledgers."""
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(
        4,
        use_device_verifier=False,
        enable_consensus=True,
        config=cfg,
        epoch_config=EpochConfig(length=4, slash_fraction=1.0),
    )
    offender = net.priv_vals[0]
    off_addr = offender.get_address()
    gen = ByzantineVoteGen(offender, net.chain_id)
    eq_txs: list[bytes] = []
    eq = TxVoteEquivocator(
        net.nodes[0].switch, gen, lambda: eq_txs,
        lambda: net.nodes[1].state_view().last_block_height, interval=0.02,
    )
    try:
        net.start()
        pre = b"eq-pre=v"
        eq_txs.append(pre)
        eq.start()
        net.broadcast_tx(pre)
        assert net.wait_all_committed([pre], timeout=60)
        # equivocating pairs flooded the fast path; certificates still
        # carry each validator at most once
        h = hashlib.sha256(pre).hexdigest().upper()
        for n in net.nodes:
            addrs = [v.validator_address for v in n.tx_store.load_tx_votes(h)]
            assert len(addrs) == len(set(addrs))

        ev = eq.block_evidence(height=1)
        added, err = net.nodes[1].evidence_pool.add(ev)
        assert added, err
        assert wait_until(
            lambda: all(
                n.state_view().validators.get_by_address(off_addr)[1] is None
                for n in net.nodes
            ),
            timeout=60,
        ), [n.epoch_manager.snapshot() for n in net.nodes]

        # the slashed key's still-flooding equivocation pairs now die at
        # the pre-check: unknown validator, attributed to its node
        assert wait_until(
            lambda: net.nodes[1].byzantine_ledger.snapshot()["peers"]
            .get("node0", {}).get("drops", {}).get(DROP_UNKNOWN_VALIDATOR, 0)
            > 0,
            timeout=30,
        )

        # liveness with the reduced set
        post = b"eq-post=v"
        eq_txs.append(post)
        net.broadcast_tx(post, node_index=1)
        assert net.wait_all_committed([post], timeout=60)
        h2 = hashlib.sha256(post).hexdigest().upper()
        for n in net.nodes:
            addrs = {v.validator_address for v in n.tx_store.load_tx_votes(h2)}
            assert off_addr not in addrs
    finally:
        eq.stop()
        net.stop()


# -- selective withholder: liveness adversary ------------------------------


def test_selective_withholder_cannot_block_commits():
    """A validator that signs only txs it favors: every tx still commits
    (honest stake clears quorum without it), and the withheld txs'
    certificates provably exclude its key."""
    net = LocalNet(4, use_device_verifier=False)
    withholder = SelectiveWithholder(
        net.nodes[0], lambda tx: not tx.startswith(b"victim")
    )
    withholder.install()  # disarms node0's honest signer, pre-start
    try:
        net.start()
        favored = [b"fav%d=v" % i for i in range(3)]
        victims = [b"victim%d=v" % i for i in range(3)]
        for tx in favored + victims:
            net.broadcast_tx(tx, node_index=1)
        assert net.wait_all_committed(favored + victims, timeout=60)
        assert wait_until(lambda: withholder.withheld >= len(victims), timeout=30)
        assert withholder.signed >= 1
        addr0 = net.priv_vals[0].get_address()
        for tx in victims:
            h = hashlib.sha256(tx).hexdigest().upper()
            for n in net.nodes:
                assert addr0 not in {
                    v.validator_address for v in n.tx_store.load_tx_votes(h)
                }
    finally:
        withholder.stop()
        net.stop()


# -- /health + metrics surface --------------------------------------------


def test_health_surfaces_byzantine_section():
    net = LocalNet(2, use_device_verifier=False)
    try:
        net.start()
        led = net.nodes[0].byzantine_ledger
        led.note_sync_strike("node1")
        # the monitor tick republishes the ledger into /health
        assert wait_until(
            lambda: net.nodes[0].health.snapshot()["byzantine"].get("strikes", 0)
            >= 1,
            timeout=20,
        )
        byz = net.nodes[0].health.snapshot()["byzantine"]
        assert "node1" in byz["quarantined_peers"]
        assert byz["peers"]["node1"]["sync_strikes"] == 1
        expo = net.nodes[0].metrics_registry.expose()
        assert "txflow_byzantine_strikes" in expo
        assert "txflow_byzantine_quarantines" in expo
        assert "txflow_byzantine_quarantined_peers 1.0" in expo
    finally:
        net.stop()
