"""Host ed25519 golden-model tests: RFC 8032 vectors + pure/openssl agreement."""

import hashlib

from txflow_tpu.crypto import ed25519


# RFC 8032 section 7.1 test vectors.
RFC_VECTORS = [
    {
        "seed": bytes.fromhex(
            "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
        ),
        "pub": bytes.fromhex(
            "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
        ),
        "msg": b"",
        "sig": bytes.fromhex(
            "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
            "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"
        ),
    },
    {
        "seed": bytes.fromhex(
            "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb"
        ),
        "pub": bytes.fromhex(
            "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c"
        ),
        "msg": bytes([0x72]),
        "sig": bytes.fromhex(
            "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
            "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00"
        ),
    },
    {
        "seed": bytes.fromhex(
            "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7"
        ),
        "pub": bytes.fromhex(
            "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025"
        ),
        "msg": bytes([0xAF, 0x82]),
        "sig": bytes.fromhex(
            "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
            "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a"
        ),
    },
]


def test_rfc8032_vectors_pure():
    for v in RFC_VECTORS:
        assert ed25519.public_key_from_seed(v["seed"]) == v["pub"]
        assert ed25519.sign_pure(v["seed"], v["msg"]) == v["sig"]
        assert ed25519.verify_pure(v["pub"], v["msg"], v["sig"])
        # Corrupt each part.
        bad_sig = bytes([v["sig"][0] ^ 1]) + v["sig"][1:]
        assert not ed25519.verify_pure(v["pub"], v["msg"], bad_sig)
        assert not ed25519.verify_pure(v["pub"], v["msg"] + b"x", v["sig"])


def test_fast_path_agrees_with_pure():
    seed = hashlib.sha256(b"txflow test seed").digest()
    pub = ed25519.public_key_from_seed(seed)
    for i in range(8):
        msg = f"message {i}".encode()
        sig_fast = ed25519.sign(seed, msg)
        sig_pure = ed25519.sign_pure(seed, msg)
        assert sig_fast == sig_pure  # both RFC 8032 deterministic
        assert ed25519.verify(pub, msg, sig_fast)
        assert ed25519.verify_pure(pub, msg, sig_fast)
        assert not ed25519.verify(pub, msg + b"!", sig_fast)


def test_s_malleability_rejected():
    # S >= L must be rejected (Go ScMinimal), even when the point equation
    # would hold for S' = S + L.
    seed = hashlib.sha256(b"malleability").digest()
    pub = ed25519.public_key_from_seed(seed)
    msg = b"vote"
    sig = ed25519.sign_pure(seed, msg)
    s = int.from_bytes(sig[32:], "little")
    s_mall = s + ed25519.L
    if s_mall < 2**256:
        sig_mall = sig[:32] + s_mall.to_bytes(32, "little")
        assert not ed25519.verify_pure(pub, msg, sig_mall)
        assert not ed25519.verify(pub, msg, sig_mall)


def test_invalid_pubkey_rejected():
    assert not ed25519.verify_pure(bytes(31), b"m", bytes(64))
    # All-0xff is (with overwhelming likelihood) not a valid encoding.
    assert not ed25519.verify_pure(bytes([0xFF]) * 32, b"m", bytes(64))


def test_point_ops_consistency():
    # 2B via double == B + B via unified add; scalar_mult distributes.
    d2 = ed25519.point_double(ed25519.BASE)
    a2 = ed25519.point_add(ed25519.BASE, ed25519.BASE)
    assert ed25519.point_equal(d2, a2)
    k1, k2 = 123456789, 987654321
    lhs = ed25519.scalar_mult(k1 + k2, ed25519.BASE)
    rhs = ed25519.point_add(
        ed25519.scalar_mult(k1, ed25519.BASE), ed25519.scalar_mult(k2, ed25519.BASE)
    )
    assert ed25519.point_equal(lhs, rhs)
    # Compress/decompress roundtrip.
    pt = ed25519.scalar_mult(k1, ed25519.BASE)
    enc = ed25519.point_compress(pt)
    dec = ed25519.point_decompress(enc)
    assert dec is not None and ed25519.point_equal(pt, dec)


def test_x_zero_sign_bit_matches_openssl():
    # Non-canonical encodings with x=0 and sign bit 1 (e.g. y=1 -> identity)
    # are accepted by Go's ref10/OpenSSL decompression; the golden model must
    # agree so golden and fast paths share one accept set.
    enc = bytes([0x01] + [0] * 30 + [0x80])
    pt = ed25519.point_decompress(enc)
    assert pt is not None and ed25519.point_equal(pt, ed25519.IDENTITY)
