"""Real-TCP transport tests: two switches over loopback sockets exchange
framed messages and run actual vote gossip — the DCN path that in-proc
nets bypass (reference MultiplexTransport slot, node/node.go:420-505).
"""

import conftest  # noqa: F401

import hashlib
import socket
import threading
import time

from txflow_tpu.node.node import Node, NodeConfig
from txflow_tpu.p2p.transport import (
    ConnectionClosed,
    MAX_FRAME_BYTES,
    TCPConnection,
    tcp_connect,
    tcp_listen,
)
from txflow_tpu.types import TxVote
from txflow_tpu.types.priv_validator import MockPV
from txflow_tpu.types.validator import Validator, ValidatorSet
from txflow_tpu.utils.config import test_config as make_test_config

CHAIN_ID = "test-tcp"


def wait_until(pred, timeout=30.0, poll=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return False


def test_tcp_connection_framing_roundtrip():
    srv = tcp_listen("127.0.0.1", 0)
    host, port = srv.getsockname()
    got = {}

    def server():
        s, _ = srv.accept()
        conn = TCPConnection(s)
        got["frame"] = conn.recv(timeout=5)
        conn.send(0x42, b"pong" * 1000)
        got["closed_ok"] = True
        try:
            conn.recv(timeout=5)
        except ConnectionClosed:
            got["peer_close_seen"] = True

    t = threading.Thread(target=server, daemon=True)
    t.start()
    client = tcp_connect(host, port)
    client.send(0x41, b"ping" * 1000)
    chan, payload = client.recv(timeout=5)
    assert (chan, payload) == (0x42, b"pong" * 1000)
    client.close()
    t.join(timeout=5)
    assert got["frame"] == (0x41, b"ping" * 1000)
    assert got.get("peer_close_seen")
    srv.close()


def test_tcp_oversized_frame_rejected():
    srv = tcp_listen("127.0.0.1", 0)
    host, port = srv.getsockname()

    def server():
        s, _ = srv.accept()
        conn = TCPConnection(s)
        # hand-craft a frame header claiming an absurd length
        import struct

        s.sendall(struct.pack("!BI", 0x01, MAX_FRAME_BYTES + 1))

    threading.Thread(target=server, daemon=True).start()
    client = tcp_connect(host, port)
    try:
        client.recv(timeout=5)
        assert False, "oversized frame must close the connection"
    except ConnectionClosed:
        pass
    finally:
        client.close()
        srv.close()


def build_node(i, pvs, vs):
    cfg = make_test_config()
    return Node(
        node_id=f"tcp-node{i}",
        chain_id=CHAIN_ID,
        val_set=vs,
        app=__import__(
            "txflow_tpu.abci.kvstore", fromlist=["KVStoreApplication"]
        ).KVStoreApplication(),
        priv_val=pvs[i],
        node_config=NodeConfig(config=cfg, use_device_verifier=False,
                               enable_consensus=False),
    )


def test_vote_gossip_over_real_tcp_sockets():
    """Two validator nodes connected through actual TCP sockets (dial +
    accept + node-id handshake): txs and votes cross the wire and commit
    on both sides."""
    pvs = [MockPV(hashlib.sha256(b"tcp-%d" % i).digest()) for i in range(2)]
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs])
    by_addr = {pv.get_address(): pv for pv in pvs}
    pvs_sorted = [by_addr[v.address] for v in vs]
    nodes = [build_node(i, pvs_sorted, vs) for i in range(2)]
    for n in nodes:
        n.start()
    srv = tcp_listen("127.0.0.1", 0)
    host, port = srv.getsockname()

    accepted = {}

    def acceptor():
        s, _ = srv.accept()
        accepted["peer"] = nodes[0].switch.accept_tcp(s)

    t = threading.Thread(target=acceptor, daemon=True)
    t.start()
    peer0 = nodes[1].switch.dial_tcp(host, port)
    t.join(timeout=5)
    assert peer0.node_id == "tcp-node0"
    assert accepted["peer"].node_id == "tcp-node1"

    try:
        txs = [b"tcp-%d=v" % i for i in range(5)]
        for tx in txs:
            nodes[0].broadcast_tx(tx)
        # mempool gossip + per-tx signing + vote gossip over the socket;
        # 2-of-2 quorum requires BOTH validators' votes to cross TCP
        assert wait_until(
            lambda: all(n.is_committed(tx) for n in nodes for tx in txs)
        ), "txs must commit on both TCP-connected nodes"
        # is_committed is a DECISION-time fact; the ABCI apply runs on the
        # pipelined committer thread and may trail it by a beat — poll for
        # app-state convergence instead of reading the hash instantly
        assert wait_until(
            lambda: nodes[0].app.app_hash() == nodes[1].app.app_hash()
            and nodes[0].app.tx_count == len(txs)
        ), (
            f"app state diverged: {nodes[0].app.app_hash().hex()} vs "
            f"{nodes[1].app.app_hash().hex()}"
        )
    finally:
        for n in nodes:
            n.stop()
        srv.close()
