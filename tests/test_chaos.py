"""Chaos suite: the fault-injection subsystem (txflow_tpu/faults/) driven
against live LocalNets.

Each fault class gets at least one fast deterministic scenario in tier-1;
long soaks are marked ``slow``. Every network scenario asserts the two
paper-level properties:

- SAFETY: no conflicting commit certificates — on every node, every
  committed tx's certificate is built from distinct in-set validators
  whose signatures verify, none byzantine, summing past 2/3 stake;
- LIVENESS: every honest client tx commits on every node.
"""

import hashlib
import time

import numpy as np
import pytest

from txflow_tpu.crypto import ed25519 as host_ed
from txflow_tpu.faults import (
    ChaosRouter,
    CrashDrill,
    FaultPlan,
    FaultSpec,
    FlakyVerifier,
    InjectedDeviceError,
    byzantine,
)
from txflow_tpu.faults.plan import DELIVER, GOSSIP_CHANNELS
from txflow_tpu.node.localnet import LocalNet
from txflow_tpu.p2p.base import CHANNEL_CONSENSUS_STATE, CHANNEL_TXVOTE
from txflow_tpu.pool.evidence import EvidencePool
from txflow_tpu.types import MockPV, TxVote, Validator, ValidatorSet
from txflow_tpu.types.tx_vote import canonical_sign_bytes
from txflow_tpu.verifier import ResilientVoteVerifier, ScalarVoteVerifier

CHAIN_ID = "txflow-localnet"  # LocalNet default


def wait_until(pred, timeout=20.0, poll=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return False


def _mkpvs(n, tag=b"chaos-val"):
    return [MockPV(hashlib.sha256(tag + b"%d" % i).digest()) for i in range(n)]


def assert_certificate_safety(net, txs, byz_addrs=frozenset()):
    """No conflicting certificates: every node's certificate for every tx
    is distinct, in-set, non-byzantine validators with verifying
    signatures whose stake clears the >2/3 quorum."""
    total = net.val_set.total_voting_power()
    for node in net.nodes:
        for tx in txs:
            h = hashlib.sha256(tx).hexdigest().upper()
            votes = node.tx_store.load_tx_votes(h)
            assert votes, f"{node.node_id}: no certificate for {h[:12]}"
            addrs = [v.validator_address for v in votes]
            assert len(addrs) == len(set(addrs)), (
                f"{node.node_id}: duplicate validator in certificate {h[:12]}"
            )
            stake = 0
            for v in votes:
                assert v.validator_address not in byz_addrs, (
                    f"{node.node_id}: byzantine validator certified {h[:12]}"
                )
                _, val = net.val_set.get_by_address(v.validator_address)
                assert val is not None, f"{node.node_id}: out-of-set validator"
                assert v.verify(net.chain_id, val.pub_key) is None, (
                    f"{node.node_id}: unverifiable signature in cert {h[:12]}"
                )
                stake += val.voting_power
            assert stake * 3 > total * 2, (
                f"{node.node_id}: certificate {h[:12]} below quorum "
                f"({stake}/{total})"
            )


# ------------------------------------------------------ FaultPlan (pure)


def test_fault_plan_same_seed_same_trace():
    """Same seed => identical per-link fault trace, independent of how
    calls from different links interleave."""
    spec = FaultSpec(seed=11, drop=0.2, duplicate=0.1, delay=0.2)
    links = [("n0", "n1"), ("n1", "n0"), ("n0", "n2"), ("n2", "n1")]

    def drive(plan, order):
        for i in range(200):
            for src, dst in order:
                plan.decide(src, dst, CHANNEL_TXVOTE)

    a, b = FaultPlan(spec), FaultPlan(spec)
    drive(a, links)
    drive(b, list(reversed(links)))  # different cross-link interleaving
    assert a.trace, "a 0.5 total fault rate over 800 draws must fire"
    for src, dst in links:
        assert a.link_trace(src, dst) == b.link_trace(src, dst)
    # a different seed yields a different pattern
    c = FaultPlan(FaultSpec(seed=12, drop=0.2, duplicate=0.1, delay=0.2))
    drive(c, links)
    assert c.link_trace("n0", "n1") != a.link_trace("n0", "n1")


def test_fault_plan_scope_does_not_consume_randomness():
    """Out-of-scope (consensus) traffic interleaved into a link must not
    shift the gossip-channel decision stream."""
    spec = FaultSpec(seed=3, drop=0.3, delay=0.3)
    assert CHANNEL_CONSENSUS_STATE not in GOSSIP_CHANNELS
    a, b = FaultPlan(spec), FaultPlan(spec)
    for i in range(100):
        a.decide("x", "y", CHANNEL_TXVOTE)
        kind, delay = b.decide("x", "y", CHANNEL_CONSENSUS_STATE)
        assert (kind, delay) == (DELIVER, 0.0)
        b.decide("x", "y", CHANNEL_TXVOTE)
    assert a.link_trace("x", "y") == b.link_trace("x", "y")


def test_fault_spec_validates():
    with pytest.raises(ValueError):
        FaultSpec(drop=0.7, delay=0.6)  # probabilities sum past 1
    with pytest.raises(ValueError):
        FaultSpec(delay_min=0.2, delay_max=0.1)


# ------------------------------------------------- lossy links (LocalNet)


def test_chaos_lossy_links_all_commit():
    """drop + duplicate + delay on every gossip link: anti-entropy
    regossip restores liveness; certificates stay clean."""
    spec = FaultSpec(
        seed=7, drop=0.15, duplicate=0.1, delay=0.15,
        delay_min=0.001, delay_max=0.02,
    )
    net = LocalNet(4, use_device_verifier=False, fault_plan=spec)
    txs = [b"lossy-%d=v" % i for i in range(8)]
    try:
        net.start()
        for i, tx in enumerate(txs):
            net.broadcast_tx(tx, node_index=i % 4)
        assert net.wait_all_committed(txs, timeout=60), (
            f"liveness under loss: stats={dict(net.chaos.stats)}"
        )
        assert_certificate_safety(net, txs)
        # the plan actually fired each fault class
        assert net.chaos.stats["drop"] > 0
        assert net.chaos.stats["duplicate"] > 0
        assert net.chaos.stats["delay"] > 0
    finally:
        net.stop()


def test_chaos_partition_halts_then_heals():
    """A 2/2 partition starves quorum (neither side has 2/3 stake); after
    heal(), regossip carries the backlog and every node commits."""
    net = LocalNet(4, use_device_verifier=False, fault_plan=FaultSpec(seed=0))
    pre = b"pre-partition=v"
    cut = b"cut-partition=v"
    try:
        net.start()
        net.broadcast_tx(pre)
        assert net.wait_all_committed([pre], timeout=30)

        net.chaos.partition({"node0", "node1"})  # node2/node3: implicit group
        net.broadcast_tx(cut)
        h = hashlib.sha256(cut).hexdigest().upper()
        time.sleep(1.2)
        assert not any(n.tx_store.has_tx(h) for n in net.nodes), (
            "a 2-of-4 side must not reach the 2/3 quorum"
        )
        assert net.chaos.stats["partitioned"] > 0

        net.chaos.heal()
        assert net.wait_all_committed([cut], timeout=60), (
            "liveness must resume after heal"
        )
        assert_certificate_safety(net, [pre, cut])
    finally:
        net.stop()


# ---------------------------------------------------- byzantine validators


def test_byzantine_vote_flood_excluded_from_certificates():
    """One validator floods equivocating / garbage / wrong-chain / forged /
    stale votes: commits keep flowing, and no certificate anywhere
    contains an unverifiable vote or counts a validator twice."""
    pvs = _mkpvs(4)
    net = LocalNet(4, use_device_verifier=False, priv_vals=pvs)
    gen = byzantine.ByzantineVoteGen(pvs[0], CHAIN_ID, seed=5)
    txs = [b"byz-%d=v" % i for i in range(4)]
    try:
        net.start()
        for tx in txs:
            net.broadcast_tx(tx)
        # hostile flood into node1's pool (gossip spreads it from there)
        pool = net.nodes[1].tx_vote_pool
        a, b = gen.equivocating_pair(txs[0])
        pool.check_tx(a)
        pool.check_tx(b)
        pool.check_tx(gen.garbage_signature_vote(txs[1]))
        pool.check_tx(gen.wrong_chain_vote(txs[2]))
        pool.check_tx(gen.forged_address_vote(txs[3], pvs[1].get_address()))
        pool.check_tx(gen.stale_vote(txs[0], height=0))
        assert net.wait_all_committed(txs, timeout=60)
        # pvs[0] is equivocating but its signatures are VALID: it may
        # legitimately appear in certificates — at most once per tx, with
        # a verifying signature (assert_certificate_safety checks both)
        assert_certificate_safety(net, txs)
    finally:
        net.stop()


def test_byzantine_garbage_signer_liveness():
    """A validator whose every signature fails verification (withheld
    stake, effectively): 3/4 honest stake still commits everything and
    the byzantine address never enters a certificate."""
    pvs = _mkpvs(4, tag=b"garbage-val")
    pvs[0].break_tx_vote_signing = True  # signs for the wrong chain id
    net = LocalNet(4, use_device_verifier=False, priv_vals=pvs)
    txs = [b"garbage-%d=v" % i for i in range(4)]
    try:
        net.start()
        for i, tx in enumerate(txs):
            net.broadcast_tx(tx, node_index=i % 4)
        assert net.wait_all_committed(txs, timeout=60), (
            "3 honest of 4 must keep committing"
        )
        assert_certificate_safety(
            net, txs, byz_addrs={pvs[0].get_address()}
        )
    finally:
        net.stop()


def test_block_equivocation_evidence_admitted_and_forged_rejected():
    """Block-path equivocation goes through types/evidence.py: a validly
    double-signed pair is admitted to the pool; a forged accusation (bad
    second signature) is rejected."""
    pv = MockPV(hashlib.sha256(b"equivocator").digest())
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10)])
    pool = EvidencePool("ev-chain", lambda: vs)

    ev = byzantine.equivocating_block_votes(pv, "ev-chain", height=5)
    added, err = pool.add(ev)
    assert added and err is None
    assert pool.has(ev) and len(pool.pending()) == 1
    # duplicate submission: known, not an error
    added, err = pool.add(ev)
    assert not added and err is None

    forged = byzantine.forged_block_vote_evidence(pv, "ev-chain", height=6)
    added, err = pool.add(forged)
    assert not added and err is not None
    assert len(pool.pending()) == 1

    # an out-of-set accuser is rejected too
    stranger = MockPV(hashlib.sha256(b"stranger").digest())
    added, err = pool.add(
        byzantine.equivocating_block_votes(stranger, "ev-chain", height=7)
    )
    assert not added and err is not None


# ------------------------------------------------------ crash-restart drill


def test_crash_drill_restart_replays_exactly_once(tmp_path):
    """Kill the drill node right after a commit persists; the restarted
    node (fresh app) replays every commit exactly once, in order."""
    import collections

    from txflow_tpu.abci import KVStoreApplication

    class CountingKVStore(KVStoreApplication):
        def __init__(self):
            super().__init__()
            self.delivered = collections.Counter()

        def deliver_tx(self, tx):
            self.delivered[bytes(tx)] += 1
            return super().deliver_tx(tx)

    drill = CrashDrill(tmp_path)
    try:
        drill.start()
        pre = [b"drill-%d=v" % i for i in range(3)]
        for tx in pre:
            drill.submit(tx)
        assert drill.wait_committed(pre)
        order_before = drill.committed_order()

        victim = b"drill-victim=v"
        from txflow_tpu.utils import failpoints

        failpoints.arm("txflow-after-commit")
        drill.submit(victim)
        drill.crash(failpoint="txflow-after-commit")

        app2 = CountingKVStore()
        drill.restart(app2)
        assert drill.restarts == 1
        assert drill.wait_committed(pre + [victim])
        for tx in pre + [victim]:
            assert app2.delivered[tx] == 1, (
                f"{tx} delivered {app2.delivered[tx]}x"
            )
        # replay converges: pre-crash order is a prefix of the new order
        order_after = drill.committed_order()
        assert order_after[: len(order_before)] == order_before
        # the restarted node still makes progress
        fresh = b"drill-fresh=v"
        drill.submit(fresh)
        assert drill.wait_committed([fresh])
        # wait_committed sees the persisted certificate, which lands
        # ahead of the async committer's app delivery — give the
        # delivery a bounded window before asserting exactly-once
        deadline = time.monotonic() + 10.0
        while app2.delivered[fresh] == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert app2.delivered[fresh] == 1
    finally:
        drill.stop()


# --------------------------------------------- verifier graceful degradation


def _degradation_rig():
    """A 4-validator batch plus a golden result to compare every path to."""
    pvs = _mkpvs(4, tag=b"deg-val")
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs])
    by_addr = {pv.get_address(): pv for pv in pvs}
    msgs, sigs, vidx, slot = [], [], [], []
    for t in range(2):
        tx_hash = hashlib.sha256(b"deg-tx%d" % t).hexdigest().upper()
        for vi, val in enumerate(vs.validators):
            v = TxVote(
                height=1,
                tx_hash=tx_hash,
                tx_key=hashlib.sha256(b"deg-tx%d" % t).digest(),
                timestamp_ns=1_700_000_000_000_000_000 + t,
                validator_address=val.address,
            )
            by_addr[val.address].sign_tx_vote(CHAIN_ID, v)
            msgs.append(
                canonical_sign_bytes(CHAIN_ID, 1, tx_hash, v.timestamp_ns)
            )
            sigs.append(v.signature)
            vidx.append(vi)
            slot.append(t)
    batch = (msgs, sigs, np.array(vidx), np.array(slot), 2)
    golden = ScalarVoteVerifier(vs).verify_and_tally(*batch)
    return vs, batch, golden


def _assert_same(result, golden):
    np.testing.assert_array_equal(result.valid, golden.valid)
    np.testing.assert_array_equal(result.stake, golden.stake)
    np.testing.assert_array_equal(result.maj23, golden.maj23)


def test_resilient_verifier_retries_demotes_and_repromotes():
    """The full policy, deterministically: bounded retry with exponential
    backoff -> demotion to the CPU fallback -> probe after the interval
    -> re-promotion. Decisions are bit-identical on every path."""
    vs, batch, golden = _degradation_rig()
    flaky = FlakyVerifier(ScalarVoteVerifier(vs))
    sleeps, now, transitions = [], [0.0], []
    r = ResilientVoteVerifier(
        flaky,
        fallback=ScalarVoteVerifier(vs),
        max_attempts=3,
        backoff_base=0.01,
        backoff_max=0.04,
        probe_interval=5.0,
        sleep=sleeps.append,
        clock=lambda: now[0],
    )
    r.on_state_change = transitions.append

    _assert_same(r.verify_and_tally(*batch), golden)  # healthy: device path
    assert flaky.calls == 1 and r.fallback_calls == 0 and r.device_healthy

    flaky.failing = True
    _assert_same(r.verify_and_tally(*batch), golden)  # served by fallback
    assert sleeps == [0.01, 0.02], "exponential backoff between attempts"
    assert r.device_failures == 3 and r.demotions == 1
    assert not r.device_healthy and r.fallback_calls == 1
    assert isinstance(r.last_error, InjectedDeviceError)
    assert transitions == [False]

    # demoted + probe not due: the device is not even tried
    calls = flaky.calls
    _assert_same(r.verify_and_tally(*batch), golden)
    assert flaky.calls == calls and r.fallback_calls == 2

    # probe due, device still down: one probe burst, stays demoted
    now[0] = 6.0
    _assert_same(r.verify_and_tally(*batch), golden)
    assert flaky.calls == calls + 3 and r.fallback_calls == 3
    assert r.demotions == 1, "a failed probe is not a second demotion"

    # next caller inside the re-armed interval skips the device again
    now[0] = 7.0
    calls = flaky.calls
    _assert_same(r.verify_and_tally(*batch), golden)
    assert flaky.calls == calls

    # device recovers; the next probe re-promotes
    flaky.failing = False
    now[0] = 20.0
    _assert_same(r.verify_and_tally(*batch), golden)
    assert r.repromotions == 1 and r.device_healthy
    assert transitions == [False, True]
    fallback_calls = r.fallback_calls
    _assert_same(r.verify_and_tally(*batch), golden)  # back on the device
    assert r.fallback_calls == fallback_calls


def test_localnet_commits_through_device_outage_and_recovery():
    """End-to-end degradation: every node's engine shares a resilient
    verifier whose device is down from the start — commits flow on the
    CPU fallback; when the device heals, a probe re-promotes it and
    later commits ride the device path again."""
    pvs = _mkpvs(4, tag=b"outage-val")
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs])
    flaky = FlakyVerifier(ScalarVoteVerifier(vs))
    flaky.failing = True
    resilient = ResilientVoteVerifier(
        flaky,
        fallback=ScalarVoteVerifier(vs),
        max_attempts=2,
        backoff_base=0.001,
        probe_interval=0.2,
    )
    net = LocalNet(
        4, use_device_verifier=False, priv_vals=pvs, verifier=resilient
    )
    try:
        net.start()
        down = [b"outage-%d=v" % i for i in range(3)]
        for tx in down:
            net.broadcast_tx(tx)
        assert net.wait_all_committed(down, timeout=60), (
            "fallback must keep commits flowing while the device is down"
        )
        assert not resilient.device_healthy and resilient.demotions == 1
        assert resilient.fallback_calls > 0
        assert_certificate_safety(net, down)

        flaky.failing = False  # device recovers
        up = [b"recovered-%d=v" % i for i in range(3)]
        for tx in up:
            net.broadcast_tx(tx)
        assert net.wait_all_committed(up, timeout=60)
        assert wait_until(lambda: resilient.device_healthy, timeout=20), (
            "a probe within probe_interval must re-promote the device"
        )
        assert resilient.repromotions == 1
        assert_certificate_safety(net, up)
    finally:
        net.stop()


# --------------------------------------------------------------- slow soaks


@pytest.mark.slow
def test_chaos_soak_loss_partition_byzantine():
    """Everything at once, longer: lossy links + a partition cycle + a
    garbage-signing validator + an equivocation flood, 32 txs."""
    pvs = _mkpvs(4, tag=b"soak-val")
    pvs[3].break_tx_vote_signing = True
    spec = FaultSpec(
        seed=99, drop=0.2, duplicate=0.15, delay=0.2,
        delay_min=0.001, delay_max=0.05,
    )
    net = LocalNet(4, use_device_verifier=False, priv_vals=pvs, fault_plan=spec)
    gen = byzantine.ByzantineVoteGen(pvs[0], CHAIN_ID, seed=99)
    txs = [b"soak-%d=v" % i for i in range(32)]
    try:
        net.start()
        for i, tx in enumerate(txs[:16]):
            net.broadcast_tx(tx, node_index=i % 4)
            if i % 4 == 0:
                a, b = gen.equivocating_pair(tx)
                net.nodes[1].tx_vote_pool.check_tx(a)
                net.nodes[1].tx_vote_pool.check_tx(b)
        assert net.wait_all_committed(txs[:16], timeout=120)

        net.chaos.partition({"node0"}, {"node1"})  # 1/1/2: no quorum anywhere
        time.sleep(1.0)
        net.chaos.heal()

        for i, tx in enumerate(txs[16:]):
            net.broadcast_tx(tx, node_index=i % 4)
        assert net.wait_all_committed(txs, timeout=120), (
            f"soak liveness: stats={dict(net.chaos.stats)}"
        )
        assert_certificate_safety(net, txs, byz_addrs={pvs[3].get_address()})
    finally:
        net.stop()


@pytest.mark.slow
def test_chaos_soak_seed_replay_matches():
    """Same seed, same workload => the same per-link fault trace from a
    live net (plan determinism holds under real thread interleaving)."""
    def run(seed):
        spec = FaultSpec(seed=seed, drop=0.1, duplicate=0.1, delay=0.1)
        net = LocalNet(4, use_device_verifier=False, fault_plan=spec)
        txs = [b"replay-%d=v" % i for i in range(8)]
        try:
            net.start()
            for i, tx in enumerate(txs):
                net.broadcast_tx(tx, node_index=i % 4)
            assert net.wait_all_committed(txs, timeout=60)
        finally:
            net.stop()
        return net.chaos.plan

    p1, p2 = run(4242), run(4242)
    # the nets are concurrent systems: message COUNTS per link can differ
    # between runs (regossip timing), so compare the common prefix of
    # each link's decision stream — determinism means the streams agree
    # wherever both runs drew them
    links = {(s, d) for (s, d, _, _, _) in p1.trace} | {
        (s, d) for (s, d, _, _, _) in p2.trace
    }
    assert links, "chaos must have fired"
    for src, dst in links:
        drawn = min(
            p1._counts.get((src, dst), 0), p2._counts.get((src, dst), 0)
        )
        assert drawn > 0
        t1 = [e for e in p1.link_trace(src, dst) if e[0] < drawn]
        t2 = [e for e in p2.link_trace(src, dst) if e[0] < drawn]
        assert t1 == t2
