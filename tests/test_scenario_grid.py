"""Scenario-grid tests (ISSUE-16): spec determinism + PRNG-domain
disjointness under composition, the harness breach/exit-code contract,
results-matrix banking under clean-supersede, the CLI preview flags, and
the tier-1 live smoke gate over real-TCP ProcNets.

The load-bearing property here is the composition rule from
scenario/spec.py: every axis draws from its OWN sha256-scoped PRNG
domain, so toggling one axis's level leaves every other axis's drawn
schedule byte-identical. That is what makes a grid walk DIAGNOSABLE —
a red tile differs from its green neighbor in exactly one axis's
schedule, never in collateral re-draws.
"""

import conftest  # noqa: F401

import json
import os
import subprocess
import sys
from dataclasses import replace

import pytest

from txflow_tpu.scenario import bank
from txflow_tpu.scenario import harness as H
from txflow_tpu.scenario.spec import (
    AXES,
    GridSpec,
    TileSpec,
    axis_seed,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sched_json(plan):
    """The byte-stability handle: one canonical string per axis."""
    return {
        axis: json.dumps(sched, sort_keys=True)
        for axis, sched in plan.schedules().items()
    }


# -- axis PRNG domains ------------------------------------------------------


def test_axis_seed_domains_all_disjoint():
    """No two (seed, axis, level) triples may share a stream seed — the
    foundation of the byte-stability contract."""
    seeds = {}
    for grid_seed in (0, 1, 7):
        for axis, levels in AXES.items():
            for level in levels:
                s = axis_seed(grid_seed, axis, level)
                assert s not in seeds.values(), (grid_seed, axis, level)
                seeds[(grid_seed, axis, level)] = s
    # and the derivation is stable (pure function of its inputs)
    assert axis_seed(7, "weather", "lan") == axis_seed(7, "weather", "lan")


def test_materialize_is_deterministic():
    """Same seed, same tile => byte-identical schedules, across fresh
    GridSpec instances (no hidden shared-RNG state)."""
    for tile in GridSpec(seed=7).smoke_diagonal():
        a = _sched_json(GridSpec(seed=7).materialize(tile))
        b = _sched_json(GridSpec(seed=7).materialize(tile))
        assert a == b, tile.tile_id


def test_toggling_one_axis_leaves_others_byte_stable():
    """THE composition property: for a fixed seed, changing one axis's
    level re-draws only that axis's schedule — the other three are
    json-byte-identical. Checked from a fully-composed base tile across
    every alternate level of every axis."""
    grid = GridSpec(seed=3)
    base_tile = TileSpec(
        adversary="fleet",
        weather="lossy-edge",
        overload="flood",
        stake="churning",
        seed=3,
    )
    base = _sched_json(grid.materialize(base_tile))
    for axis, levels in AXES.items():
        for level in levels:
            if level == base_tile.level(axis):
                continue
            variant_tile = replace(base_tile, **{axis: level})
            variant = _sched_json(grid.materialize(variant_tile))
            for other in AXES:
                if other == axis:
                    continue
                assert variant[other] == base[other], (
                    f"toggling {axis} -> {level} re-drew the {other} "
                    f"schedule"
                )


def test_seed_scopes_every_drawing_axis():
    """A different grid seed must re-draw the drawn parts of every axis
    (constants like budget tables may coincide; drawn values may not)."""
    tile0 = TileSpec(
        adversary="fleet", weather="flapping", overload="flood",
        stake="churning", seed=0,
    )
    tile1 = replace(tile0, seed=1)
    p0 = GridSpec(seed=0).materialize(tile0)
    p1 = GridSpec(seed=1).materialize(tile1)
    assert p0.adversary["drivers"] != p1.adversary["drivers"]
    assert p0.weather["shaper_seed"] != p1.weather["shaper_seed"]
    assert p0.overload["intervals"] != p1.overload["intervals"]
    assert p0.stake["churn"] != p1.stake["churn"]


# -- spec validation + tile enumeration ------------------------------------


def test_tile_and_grid_validation():
    with pytest.raises(ValueError):
        TileSpec(adversary="bogus")
    with pytest.raises(ValueError):
        TileSpec(weather="dial-up")
    with pytest.raises(ValueError):
        GridSpec(n_validators=3)  # adversary tiles need honest quorum
    with pytest.raises(ValueError):
        GridSpec.from_dict({"axes": {"tides": ["high"]}})
    with pytest.raises(ValueError):
        GridSpec.from_dict({"axes": {"weather": ["lan", "dial-up"]}})
    with pytest.raises(ValueError):
        GridSpec.from_dict({"axes": {"overload": []}})


def test_smoke_diagonal_covers_every_level():
    grid = GridSpec(seed=5)
    tiles = grid.smoke_diagonal()
    assert len(tiles) == max(len(ls) for ls in AXES.values())
    for axis, levels in AXES.items():
        assert {t.level(axis) for t in tiles} == set(levels)
    # the acceptance tile: all four axes off-baseline at once
    assert any(t.composed for t in tiles)
    assert all(t.seed == 5 for t in tiles)
    assert len({t.tile_id for t in tiles}) == len(tiles)


def test_full_tiles_is_the_configured_cross_product():
    grid = GridSpec()
    want = 1
    for levels in AXES.values():
        want *= len(levels)
    tiles = grid.full_tiles()
    assert len(tiles) == want
    assert len({t.tile_id for t in tiles}) == want
    # a spec file restricting axes walks the restricted product
    small = GridSpec.from_dict(
        {"axes": {"weather": ["lan", "congested"], "stake": ["uniform"]}}
    )
    assert len(small.full_tiles()) == (
        len(AXES["adversary"]) * 2 * len(AXES["overload"]) * 1
    )


def test_tile_plan_derived_facts():
    grid = GridSpec(seed=2)
    quiet = grid.materialize(TileSpec(seed=2))
    assert quiet.adversary_index is None
    assert quiet.consensus is False
    assert quiet.budget_scale == 1.0
    assert quiet.net_signature == ("stake", "uniform")

    hot = grid.materialize(
        TileSpec(
            adversary="flooder", weather="congested", overload="flood",
            stake="churning", seed=2,
        )
    )
    powers = hot.stake["powers"]
    # the adversary slot is the smallest stake: quarantining it must
    # never cost the honest side its 2n/3
    assert hot.adversary_index == powers.index(min(powers))
    assert hot.consensus is True  # churn rides the block path
    assert hot.budget_scale > 1.0
    # churn never re-weights the (potential) adversary slot
    for ev in hot.stake["churn"]:
        assert ev["validator"] != hot.adversary_index


# -- harness: breach classes, exit codes, RESULT line ----------------------


def test_exit_code_contract():
    assert H.EXIT_CODES == {
        "infra": 1, "loss": 10, "divergence": 11,
        "slo": 12, "adversary": 13, "liveness": 14,
    }
    assert set(H.BREACH_CLASSES) == set(H.EXIT_CODES)
    assert H.worst_breach(["slo", "loss", "liveness"]) == "loss"
    assert H.worst_breach(["slo", "infra"]) == "slo"
    assert H.worst_breach([]) is None
    with pytest.raises(ValueError):
        H.Breach("meteor", "not a class")


def _last_result_line(out: str) -> dict:
    lines = [l for l in out.strip().splitlines() if l]
    assert lines[-1].startswith("RESULT "), out
    return json.loads(lines[-1][len("RESULT "):])


def test_emit_result_line_shape(capsys):
    code = H.emit_result("unit", False, "slo", "too slow", p50_ms=900)
    assert code == 12
    payload = _last_result_line(capsys.readouterr().out)
    assert payload == {
        "mode": "unit", "ok": False, "exit_code": 12, "breach": "slo",
        "detail": "too slow", "p50_ms": 900,
    }
    assert H.emit_result("unit", True, probes=3) == 0
    payload = _last_result_line(capsys.readouterr().out)
    assert payload["ok"] is True and payload["breach"] is None


def test_run_mode_maps_breaches_to_exit_codes(capsys):
    with pytest.raises(SystemExit) as e:
        H.run_mode("unit", lambda: {"probes": 9})
    assert e.value.code == 0
    assert _last_result_line(capsys.readouterr().out)["probes"] == 9

    def lose():
        raise H.Breach("loss", "a tx went missing")

    with pytest.raises(SystemExit) as e:
        H.run_mode("unit", lose)
    assert e.value.code == 10
    out = capsys.readouterr().out
    assert "SOAK STALL" in out
    assert _last_result_line(out)["breach"] == "loss"

    def crash():
        raise RuntimeError("socket fell over")

    with pytest.raises(SystemExit) as e:
        H.run_mode("unit", crash)
    assert e.value.code == 1
    assert _last_result_line(capsys.readouterr().out)["breach"] == "infra"


# -- banking: fingerprints + clean-supersede -------------------------------


def _verdict(tile, ok, breach=None):
    return {"tile": tile, "pass": ok, "breach": breach, "detail": ""}


def test_verdict_fingerprint_pins_identity():
    verdicts = [_verdict("a", True), _verdict("b", False, "slo")]
    fp = bank.verdict_fingerprint(verdicts)
    assert fp == bank.verdict_fingerprint([dict(v) for v in verdicts])
    # order, verdicts and breach classes are all identity
    assert fp != bank.verdict_fingerprint(list(reversed(verdicts)))
    assert fp != bank.verdict_fingerprint(
        [_verdict("a", True), _verdict("b", True)]
    )
    assert fp != bank.verdict_fingerprint(
        [_verdict("a", True), _verdict("b", False, "loss")]
    )


def test_matrix_clean_semantics():
    grid = GridSpec()
    red = bank.build_matrix(grid, "smoke-diagonal", [_verdict("a", False, "slo")])
    assert bank.matrix_clean(red)  # red tiles are data, not dirt
    assert not bank.matrix_clean(
        bank.build_matrix(grid, "smoke-diagonal", [_verdict("a", False, "infra")])
    )
    assert not bank.matrix_clean(bank.build_matrix(grid, "smoke-diagonal", []))
    assert not bank.matrix_clean(
        bank.build_matrix(grid, "smoke-diagonal", [_verdict("a", True)], error="boom")
    )


def test_bank_clean_supersede(tmp_path):
    path = str(tmp_path / "grid.json")
    grid = GridSpec()
    clean_a = bank.build_matrix(grid, "smoke-diagonal", [_verdict("a", True)])
    assert bank.bank_matrix(clean_a, path)
    banked = bank.load_banked(path)
    assert banked["clean"] is True and banked["passed"] == 1

    # a dirty run must never displace the clean bank
    dirty = bank.build_matrix(
        grid, "smoke-diagonal", [_verdict("a", False, "infra")]
    )
    assert not bank.bank_matrix(dirty, path)
    assert bank.load_banked(path)["verdict_fingerprint"] == (
        clean_a["verdict_fingerprint"]
    )

    # a clean run with RED tiles still supersedes: regressions must be
    # allowed to update the reference they will be blamed against
    clean_red = bank.build_matrix(
        grid, "smoke-diagonal", [_verdict("a", False, "slo")]
    )
    assert bank.bank_matrix(clean_red, path)
    assert bank.load_banked(path)["failed"] == 1


# -- CLI preview flags (no nets) -------------------------------------------


def _run_grid_cli(*argv, timeout=60):
    return subprocess.run(
        [sys.executable, "tools/scenario_grid.py", *argv],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=timeout,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )


def test_grid_cli_list():
    proc = _run_grid_cli("--smoke", "--list")
    assert proc.returncode == 0, proc.stderr
    lines = proc.stdout.strip().splitlines()
    assert lines[0].startswith("smoke-diagonal: 5 tiles")
    tile_lines = [l for l in lines[1:] if "adv=" in l]
    assert len(tile_lines) == 5
    assert any("[composed]" in l for l in tile_lines)


def test_grid_cli_dry_run_schedules():
    proc = _run_grid_cli("--smoke", "--dry-run", "--only", "adv=fleet")
    assert proc.returncode == 0, proc.stderr
    body = proc.stdout
    start = body.index("{")
    plan = json.loads(body[start:])
    assert set(plan["schedules"]) == set(AXES)
    kinds = [d["kind"] for d in plan["schedules"]["adversary"]["drivers"]]
    assert kinds == ["sig-garbage", "unknown-signer", "replayer"]
    assert plan["adversary_index"] is not None


def test_grid_cli_empty_filter_is_infra():
    proc = _run_grid_cli("--smoke", "--only", "adv=nonesuch")
    assert proc.returncode == 1
    payload = _last_result_line(proc.stdout)
    assert payload["breach"] == "infra" and payload["ok"] is False


# -- tier-1 live gate: one real-TCP tile through the full runner path ------


def test_scenario_grid_smoke_gate(tmp_path):
    """tools/scenario_grid.py --smoke --only <baseline tile> end to end:
    a 4-process real-TCP net judged on zero admitted-tx loss, cross-node
    committed-set equality, prefix stability and the weather-profile SLO
    — banked under clean-supersede, exit 0, one final RESULT line. (The
    full 5-tile diagonal incl. adversary/churn tiles is the slow-marked
    test below; this keeps the live gate inside the tier-1 budget.)"""
    out = str(tmp_path / "matrix.json")
    proc = _run_grid_cli(
        "--smoke", "--only", "adv=none|wan=lan", "--out", out, timeout=110
    )
    assert proc.returncode == 0, (
        f"\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    assert "SOAK OK (scenario-grid)" in proc.stdout
    payload = _last_result_line(proc.stdout)
    assert payload["ok"] is True and payload["tiles"] == 1
    assert payload["banked"] is True
    matrix = bank.load_banked(out)
    assert matrix["clean"] is True and matrix["passed"] == 1
    assert matrix["verdict_fingerprint"] == payload["fingerprint"]


@pytest.mark.slow
def test_scenario_grid_smoke_diagonal_reproducible(tmp_path):
    """The acceptance check, live: the full smoke diagonal twice under
    one seed — 5/5 green both times, identical verdict fingerprints."""
    fingerprints = []
    for run in ("a", "b"):
        out = str(tmp_path / f"matrix-{run}.json")
        proc = _run_grid_cli("--smoke", "--out", out, timeout=900)
        assert proc.returncode == 0, (
            f"run {run}:\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
        )
        payload = _last_result_line(proc.stdout)
        assert payload["tiles"] == 5 and payload["passed"] == 5
        fingerprints.append(payload["fingerprint"])
    assert fingerprints[0] == fingerprints[1]


@pytest.mark.slow
def test_scenario_grid_full_cross_product_restricted(tmp_path):
    """--full walks the configured cross-product (offline-soak knobs).
    Restricted to 2x2 adversary x overload on one stake table so the
    whole product shares a single bring-up."""
    spec = {
        "seed": 11,
        "axes": {
            "adversary": ["none", "flooder"],
            "weather": ["lan"],
            "overload": ["none", "surge"],
            "stake": ["uniform"],
        },
    }
    spec_path = tmp_path / "grid.json"
    spec_path.write_text(json.dumps(spec))
    out = str(tmp_path / "matrix.json")
    proc = _run_grid_cli(
        "--full", "--spec", str(spec_path), "--out", out, timeout=1800
    )
    assert proc.returncode == 0, (
        f"\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    payload = _last_result_line(proc.stdout)
    assert payload["tiles"] == 4 and payload["passed"] == 4
    assert payload["kind"] == "full"
