"""Block-path consensus tests: round-state machine with a manual ticker,
multi-node block production over LocalNet, fast-path Vtx inclusion,
validator rotation via ABCI EndBlock, and block catchup for a late peer.

Mirrors the reference's consensus/state_test.go (mockTicker-driven
transitions, common_test.go:698-741), consensus/reactor_test.go:93-484
(N-node nets asserting NewBlock progress + validator-set changes), and the
fast-sync catchup behavior the framework folds into the consensus channel
(MSG_BLOCK_REQUEST/RESPONSE, consensus/reactor.py).
"""

import conftest  # noqa: F401

import hashlib
import json
import time

from txflow_tpu.consensus.state import ConsensusState
from txflow_tpu.consensus.ticker import ManualTicker
from txflow_tpu.node import LocalNet
from txflow_tpu.node.node import Node, NodeConfig
from txflow_tpu.p2p import connect_switches
from txflow_tpu.pool.mempool import Mempool
from txflow_tpu.state import BlockExecutor, StateStore, state_from_genesis
from txflow_tpu.store.block_store import BlockStore
from txflow_tpu.store.db import MemDB
from txflow_tpu.abci.kvstore import KVStoreApplication
from txflow_tpu.abci.proxy import AppConns
from txflow_tpu.types.block_vote import PRECOMMIT, PREVOTE, BlockVote
from txflow_tpu.types.genesis import GenesisDoc, GenesisValidator
from txflow_tpu.types.priv_validator import MockPV
from txflow_tpu.types.validator import Validator, ValidatorSet
from txflow_tpu.utils.config import test_config as make_test_config

CHAIN_ID = "test-consensus"


def make_valset(n=4, power=10):
    pvs = [MockPV(hashlib.sha256(b"cons-%d" % i).digest()) for i in range(n)]
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), power) for pv in pvs])
    by_addr = {pv.get_address(): pv for pv in pvs}
    return vs, [by_addr[v.address] for v in vs]


def build_consensus(pv, vs, app=None, wal_path=""):
    """One standalone ConsensusState wired to real stores + a kvstore app."""
    gen = GenesisDoc(
        chain_id=CHAIN_ID,
        validators=[GenesisValidator(v.pub_key, v.voting_power) for v in vs],
    )
    state = state_from_genesis(gen)
    app = app or KVStoreApplication()
    proxy = AppConns(app)
    from txflow_tpu.abci.types import ValidatorUpdate

    proxy.consensus.init_chain_sync(
        [ValidatorUpdate(gv.pub_key, gv.power) for gv in gen.validators]
    )
    state_store = StateStore(MemDB())
    mempool = Mempool(make_test_config().mempool, proxy_app_conn=proxy.mempool)
    commitpool = Mempool(make_test_config().mempool)
    block_exec = BlockExecutor(state_store, proxy.consensus, mempool, commitpool)
    block_store = BlockStore(MemDB())
    tickers = []

    def ticker_factory(fire):
        t = ManualTicker(fire)
        tickers.append(t)
        return t

    cfg = make_test_config().consensus
    cs = ConsensusState(
        cfg,
        state,
        block_exec,
        block_store,
        tx_notifier=mempool,
        commitpool=commitpool,
        priv_val=pv,
        wal_path=wal_path,
        ticker_factory=ticker_factory,
    )
    return cs, tickers[0], mempool, app


def wait_until(pred, timeout=10.0, poll=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return False


def sign_vote(pv, height, round_, vtype, block_id):
    v = BlockVote(
        height=height,
        round=round_,
        type=vtype,
        block_id=block_id,
        validator_address=pv.get_address(),
    )
    pv.sign_block_vote(CHAIN_ID, v)
    return v


# ------------------------------------------------- state machine (manual)


def test_round_transitions_to_commit_with_manual_ticker():
    """NewHeight -> NewRound -> Propose -> Prevote -> Precommit -> Commit,
    driven by hand-fed timeouts and hand-signed peer votes (the reference's
    validatorStub pattern, common_test.go:65-124)."""
    vs, pvs = make_valset(4)
    # our validator must be height-1's proposer so _decide_proposal runs
    proposer_addr = vs.copy().get_proposer().address
    me = next(pv for pv in pvs if pv.get_address() == proposer_addr)
    others = [pv for pv in pvs if pv is not me]

    cs, ticker, mempool, app = build_consensus(me, vs)
    proposals = []
    votes = []
    cs.broadcast_proposal = lambda p, b: proposals.append((p, b))
    cs.broadcast_vote = lambda v: votes.append(v)
    cs.start()
    try:
        mempool.check_tx(b"k=v")
        # NewHeight timeout fires immediately at genesis
        assert wait_until(lambda: ticker.pending() is not None)
        ticker.fire_next()
        # proposer broadcasts a proposal and its own prevote
        assert wait_until(lambda: len(proposals) == 1)
        assert wait_until(
            lambda: any(v.type == PREVOTE for v in votes)
        ), "own prevote expected"
        block = proposals[0][1]
        block_id = block.hash()
        my_prevote = next(v for v in votes if v.type == PREVOTE)
        assert my_prevote.block_id == block_id

        # two more prevotes complete the polka -> own precommit for block
        for pv in others[:2]:
            cs.add_vote(sign_vote(pv, 1, 0, PREVOTE, block_id))
        assert wait_until(
            lambda: any(v.type == PRECOMMIT and v.block_id == block_id for v in votes)
        ), "own precommit after polka expected"
        rs = cs.round_state()
        assert rs.locked_block is not None and rs.locked_block.hash() == block_id

        # two more precommits -> commit, state advances, block persisted
        for pv in others[:2]:
            cs.add_vote(sign_vote(pv, 1, 0, PRECOMMIT, block_id))
        assert wait_until(lambda: cs.state.last_block_height == 1)
        assert cs.block_store.height() == 1
        stored = cs.block_store.load_block(1)
        assert stored is not None and stored.hash() == block_id
        assert b"k=v" in stored.txs
        assert app.state.get(b"k") == b"v"  # delivered through ABCI
        # round state reset for height 2
        assert cs.round_state().height == 2
    finally:
        cs.stop()


def test_precommit_nil_without_polka():
    """No +2/3 prevotes for a block -> precommit nil, no lock (reference
    enterPrecommit :1072-1086)."""
    vs, pvs = make_valset(4)
    proposer_addr = vs.copy().get_proposer().address
    me = next(pv for pv in pvs if pv.get_address() == proposer_addr)
    others = [pv for pv in pvs if pv is not me]
    cs, ticker, mempool, _ = build_consensus(me, vs)
    votes = []
    cs.broadcast_vote = lambda v: votes.append(v)
    cs.start()
    try:
        assert wait_until(lambda: ticker.pending() is not None)
        ticker.fire_next()  # NewHeight -> round 0, propose, own prevote
        assert wait_until(lambda: any(v.type == PREVOTE for v in votes))
        # prevotes split between nil and the block: 2/3 ANY but no polka
        my_block = next(v for v in votes if v.type == PREVOTE).block_id
        cs.add_vote(sign_vote(others[0], 1, 0, PREVOTE, b""))
        cs.add_vote(sign_vote(others[1], 1, 0, PREVOTE, b"\x99" * 32))
        # prevote-wait timeout fires -> precommit nil
        assert wait_until(
            lambda: ticker.pending() is not None
            and ticker.pending().step == 5  # PREVOTE_WAIT
        )
        ticker.fire_next()
        assert wait_until(lambda: any(v.type == PRECOMMIT for v in votes))
        pc = next(v for v in votes if v.type == PRECOMMIT)
        assert pc.block_id == b""  # nil precommit
        assert cs.round_state().locked_block is None
        assert my_block  # (sanity: we did prevote a real block)
    finally:
        cs.stop()


def test_future_round_votes_trigger_round_catchup():
    """+2/3 prevotes in a higher round pull the node into that round
    (reference :615-616 catchup path)."""
    vs, pvs = make_valset(4)
    # pick a NON-proposer so no own proposal interferes
    proposer_addr = vs.copy().get_proposer().address
    me = next(pv for pv in pvs if pv.get_address() != proposer_addr)
    others = [pv for pv in pvs if pv is not me]
    cs, ticker, _, _ = build_consensus(me, vs)
    cs.start()
    try:
        assert wait_until(lambda: ticker.pending() is not None)
        ticker.fire_next()  # into round 0
        assert wait_until(lambda: cs.round_state().round == 0)
        for pv in others:  # 3 x prevote nil at round 3 = 2/3 any
            cs.add_vote(sign_vote(pv, 1, 3, PREVOTE, b""), peer_id="p")
        assert wait_until(lambda: cs.round_state().round == 3)
    finally:
        cs.stop()


# ------------------------------------------------------ LocalNet: blocks


def test_localnet_produces_blocks_with_fastpath_vtxs():
    """4 validators, fast path + consensus on: txs commit in realtime via
    vote quorum, then re-enter the chain as Vtxs in blocks; the commitpool
    drains; every node stores identical blocks (BASELINE config 5 shape)."""
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(4, use_device_verifier=False, enable_consensus=True, config=cfg)
    net.start()
    try:
        txs = [b"blk-%d=v%d" % (i, i) for i in range(8)]
        for tx in txs:
            net.broadcast_tx(tx)
        assert net.wait_all_committed(txs, timeout=60), "fast path must commit"
        # every node advances several heights
        for node in net.nodes:
            assert node.consensus.wait_for_height(2, timeout=60)
        # every committed tx enters the chain EXACTLY once — normally as a
        # Vtx (fast-path commit re-proposed from the commitpool), or as a
        # block Tx if the proposer reaped it before its votes aggregated;
        # never both (claim + commitpool dedup)
        store = net.nodes[0].block_store

        def chain_txs():
            vtxs, btxs = [], []
            for h in range(1, store.height() + 1):
                b = store.load_block(h)
                if b is not None:
                    vtxs.extend(b.vtxs)
                    btxs.extend(b.txs)
            return vtxs, btxs

        def all_included_once():
            vtxs, btxs = chain_txs()
            combined = vtxs + btxs
            return set(txs) <= set(combined) and all(
                combined.count(t) == 1 for t in txs
            )

        assert wait_until(all_included_once, timeout=60), (
            f"chain must include each tx exactly once: {chain_txs()}"
        )
        # all nodes agree on every block hash up to the min shared height
        min_h = min(n.block_store.height() for n in net.nodes)
        assert min_h >= 2
        for h in range(1, min_h + 1):
            hashes = {n.block_store.load_block(h).hash() for n in net.nodes}
            assert len(hashes) == 1, f"fork at height {h}"
        # commitpool drained on nodes that included the vtxs
        assert wait_until(
            lambda: all(n.commitpool.size() == 0 for n in net.nodes), timeout=30
        )
        # fast path stays at the committed height
        for node in net.nodes:
            assert node.committed_height_view >= 2
    finally:
        net.stop()


def test_localnet_validator_rotation_applies_at_h_plus_2():
    """A val:pubkey!power tx delivered through a block updates the
    validator set two heights later (reference state/execution.go:390-451,
    consensus/reactor_test.go:323-484)."""
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    # sign=False: txs stay unconfirmed so blocks carry them as Txs (ABCI
    # EndBlock validator updates only flow from block-delivered txs)
    net = LocalNet(
        4, use_device_verifier=False, enable_consensus=True, config=cfg, sign=False
    )
    net.start()
    try:
        new_pv = MockPV(hashlib.sha256(b"late-joiner").digest())
        new_pub = new_pv.get_pub_key()
        tx = b"val:" + new_pub.hex().encode() + b"!5"
        net.broadcast_tx(tx)

        # wait until some block contains the tx
        def rotated():
            return all(
                n.consensus.state.validators.has_address(
                    Validator.from_pub_key(new_pub, 5).address
                )
                for n in net.nodes
            )

        assert wait_until(rotated, timeout=90), "validator set must rotate"
        # the rotation landed exactly 2 heights after the tx's block
        store = net.nodes[0].block_store
        tx_height = None
        for h in range(1, store.height() + 1):
            if tx in store.load_block(h).txs:
                tx_height = h
                break
        assert tx_height is not None
        st = net.nodes[0].consensus.state
        assert st.last_height_validators_changed == tx_height + 2
    finally:
        net.stop()


def test_localnet_late_peer_catches_up_via_block_requests():
    """3 connected validators progress; the 4th connects later and pulls
    missed blocks through MSG_BLOCK_REQUEST/RESPONSE (the framework's
    fast-sync analog)."""
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(4, use_device_verifier=False, enable_consensus=True, config=cfg)
    # start nodes but connect only 0-1-2 (3 of 4 = 30/40 >= 27 quorum)
    for node in net.nodes:
        node.start()
    for i in range(3):
        for j in range(i + 1, 3):
            connect_switches(net.nodes[i].switch, net.nodes[j].switch)
    try:
        for tx in (b"cu-1=v", b"cu-2=v"):
            net.nodes[0].broadcast_tx(tx)
        for node in net.nodes[:3]:
            assert node.consensus.wait_for_height(3, timeout=60)
        assert net.nodes[3].block_store.height() == 0  # isolated so far

        # connect the straggler to one peer; catchup rides the step msg
        connect_switches(net.nodes[0].switch, net.nodes[3].switch)
        assert net.nodes[3].consensus.wait_for_height(3, timeout=60), (
            "late peer must catch up via block responses"
        )
        # caught-up blocks are the same blocks
        for h in range(1, 4):
            assert (
                net.nodes[3].block_store.load_block(h).hash()
                == net.nodes[0].block_store.load_block(h).hash()
            )
    finally:
        net.stop()


def test_byzantine_proposer_equivocates_network_still_commits():
    """The proposer sends DIFFERENT proposals to different peers
    (reference byzantine_test.go:26-273's core scenario): the honest
    majority still advances — a round may fail, but later rounds/heights
    commit, no fork forms, and equivocation cannot split the chain."""
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(4, use_device_verifier=False, enable_consensus=True, config=cfg)

    # hijack node0's proposal broadcast: craft a SECOND, different block
    # and send it to half the peers (its reactor pushes the real one)
    byz_node = net.nodes[0]
    byz_cs = byz_node.consensus
    orig_decide = byz_cs._decide_proposal
    equivocations = []  # delivered conflicting proposals (must be > 0)
    equivocations_errors = []

    def evil_decide(height, round_):
        orig_decide(height, round_)  # normal proposal to everyone
        # conflicting block (different time => different hash), signed
        # proposal for the same height/round, pushed to ONE peer only
        try:
            rs = byz_cs.rs
            state = byz_cs.state
            block2 = state.make_block(
                height, [b"evil=1"], [], rs.last_commit,
                byz_node.priv_val.get_address(),
            )
            from txflow_tpu.consensus.types import Proposal

            p2 = Proposal(
                height=height, round=round_, pol_round=-1,
                block_hash=block2.hash(), timestamp_ns=1,
            )
            byz_node.priv_val.sign_proposal(net.chain_id, p2)
            from txflow_tpu.consensus.reactor import _encode_proposal_msg
            from txflow_tpu.p2p.base import CHANNEL_CONSENSUS_STATE

            peers = byz_node.switch.peers()
            if peers and peers[0].try_send(
                CHANNEL_CONSENSUS_STATE, _encode_proposal_msg(p2, block2)
            ):
                equivocations.append(height)
        except Exception as e:
            equivocations_errors.append(repr(e))

    byz_cs._decide_proposal = evil_decide
    net.start()
    try:
        txs = [b"byz-%d=v" % i for i in range(4)]
        for tx in txs:
            net.broadcast_tx(tx)
        # liveness despite equivocating proposals
        for node in net.nodes:
            assert node.consensus.wait_for_height(3, timeout=90), (
                "honest majority must keep committing blocks"
            )
        # proposer duty rotates: keep the chain running until the
        # byzantine validator has actually had a turn (and equivocated)
        assert wait_until(lambda: bool(equivocations), timeout=60), (
            f"byzantine validator never proposed: {equivocations_errors[:3]}"
        )
        h_after = net.nodes[1].consensus.state.last_block_height + 2
        for node in net.nodes:
            assert node.consensus.wait_for_height(h_after, timeout=60), (
                "chain must keep committing after equivocation"
            )
        # safety: no fork — all nodes agree on every committed block
        min_h = min(n.block_store.height() for n in net.nodes)
        for h in range(1, min_h + 1):
            hashes = {n.block_store.load_block(h).hash() for n in net.nodes}
            assert len(hashes) == 1, f"fork at height {h}"
        # the byzantine payload must actually have been exercised — a
        # silently-broken evil_decide would turn this into a trivial
        # all-honest liveness test
        assert equivocations, (
            f"no conflicting proposal was ever delivered: {equivocations_errors[:3]}"
        )
        # the evil block's tx never entered the chain
        for h in range(1, min_h + 1):
            b = net.nodes[1].block_store.load_block(h)
            assert b"evil=1" not in b.txs
    finally:
        net.stop()


# ------------------------------------------- per-peer gossip state (PRS)


def test_peer_round_state_suppresses_known_votes():
    """Re-offer gossip sends a peer only what it lacks: votes covered by
    the peer's announced bitmask (or already pushed down the reliable
    lane) are skipped, and the proposal is skipped once the peer reports
    having one (reference PeerState bitarrays, consensus/reactor.go:
    904-1340)."""
    import json as _json

    from txflow_tpu.consensus.reactor import (
        MSG_ROUND_STEP,
        MSG_VOTE,
        ConsensusReactor,
    )
    from txflow_tpu.types.block_vote import PREVOTE

    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(4, use_device_verifier=False, enable_consensus=True, config=cfg)
    net.start()
    try:
        net.broadcast_tx(b"prs=1")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(n.consensus.state.last_block_height >= 1 for n in net.nodes):
                break
            time.sleep(0.05)
        node = net.nodes[0]
        reactor = node.consensus_reactor
        rs = node.consensus.round_state()

        class FakePeer:
            node_id = "fake-peer"

            def __init__(self):
                self.kv = {}
                self.sent = []

            def set(self, k, v):
                self.kv[k] = v

            def get(self, k, default=None):
                return self.kv.get(k, default)

            def try_send(self, chan, msg):
                self.sent.append(msg)
                return True

            def is_running(self):
                return True

        # The live consensus keeps churning rounds; retry until a full
        # announce->offer cycle runs within ONE stable round so the
        # informed mask describes the same votes the offer would ship.
        votes_to_naive = []
        for _attempt in range(20):
            before = node.consensus.round_state().round_step_key()
            naive = FakePeer()
            # the naive peer announces its POSITION (current height/round,
            # no vote knowledge): reliable-lane marks only apply to peers
            # whose tracked height matches, exactly like a real follower
            rs_now = node.consensus.round_state()
            # arm the rate limiter BEFORE the announce: receive() would
            # otherwise run its own _send_round_data and mark every vote,
            # making the measured explicit offer vacuously empty
            naive.kv["consensus_rd_last"] = time.monotonic()
            reactor.receive(
                0x20, naive,
                bytes([MSG_ROUND_STEP]) + _json.dumps({
                    "height": rs_now.height, "round": rs_now.round,
                    "step": int(rs_now.step),
                    "committed": node.consensus.state.last_block_height,
                    "has_proposal": False,
                }).encode(),
            )
            naive.sent.clear()
            naive.kv.pop("consensus_rd_last", None)
            reactor._send_round_data(naive, current_round_only=True)
            votes_to_naive = [m for m in naive.sent if m and m[0] == MSG_VOTE]

            summary = node.consensus.round_summary()
            informed = FakePeer()
            reactor.receive(
                0x20, informed,
                bytes([MSG_ROUND_STEP]) + _json.dumps(summary).encode(),
            )
            informed.sent.clear()  # drop anything receive() itself pushed
            # bypass the shared rate limiter state
            informed.kv.pop("consensus_rd_last", None)
            reactor._send_round_data(informed, current_round_only=True)
            votes_to_informed = [
                m for m in informed.sent if m and m[0] == MSG_VOTE
            ]
            if node.consensus.round_state().round_step_key() != before:
                continue  # round moved mid-check: masks vs offer raced
            assert votes_to_informed == [], (
                f"informed peer was re-sent {len(votes_to_informed)} votes "
                f"(naive baseline: {len(votes_to_naive)})"
            )
            break
        else:
            raise AssertionError("no stable round observed in 20 attempts")
        # and a second offer to the naive peer is ALSO empty now: the
        # first send marked its PeerRoundState via the reliable lane
        # (same stable-round guard — a new round legitimately re-offers)
        if votes_to_naive:
            naive.sent.clear()
            naive.kv.pop("consensus_rd_last", None)
            reactor._send_round_data(naive, current_round_only=True)
            resent = [m for m in naive.sent if m and m[0] == MSG_VOTE]
            # votes that arrived between the two offers are legitimately
            # new; what must never happen is the SAME vote twice
            dup = set(resent) & set(votes_to_naive)
            assert not dup, f"reliable-lane sends were re-offered: {len(dup)}"
    finally:
        net.stop()


# ------------------------------------ part-set proposals + parallel sync


def test_oversize_block_propagates_as_parts(monkeypatch):
    """A block whose encoding exceeds one part ships as a parts header +
    MSG_BLOCK_PART chunks and still commits network-wide (reference part-
    set gossip, consensus/reactor.go:465-530; MakePartSet state.go:945-
    962). PART_SIZE is patched down so ordinary txs exercise the path."""
    import txflow_tpu.consensus.reactor as creactor

    monkeypatch.setattr(creactor, "PART_SIZE", 512)
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(4, use_device_verifier=False, enable_consensus=True, config=cfg)
    net.start()
    try:
        # enough tx bytes that every non-empty block encodes > 512 B
        txs = [b"part-%03d=%s" % (i, b"x" * 200) for i in range(8)]
        for tx in txs:
            net.broadcast_tx(tx)
        for node in net.nodes:
            assert node.consensus.wait_for_height(2, timeout=60)
        hashes = {
            node.block_store.load_block(1).hash() for node in net.nodes
        }
        assert len(hashes) == 1, "nodes committed different blocks"
        # the chunked path actually ran: some block's encoding was > part
        big = False
        for h in range(1, net.nodes[0].block_store.height() + 1):
            from txflow_tpu.types.block import encode_block

            if len(encode_block(net.nodes[0].block_store.load_block(h))) > 512:
                big = True
        assert big, "no block exceeded the patched part size"
    finally:
        net.stop()


def test_sync_pump_fills_window_across_peers():
    """The request pool keeps SYNC_WINDOW block requests in flight,
    round-robined across every peer that has the height (reference bcv1
    request pool, node/node.go:369-385) — not one block per RTT."""
    from txflow_tpu.consensus.reactor import (
        MSG_BLOCK_REQUEST,
        SYNC_WINDOW,
        ConsensusReactor,
    )

    cfg = make_test_config()
    net = LocalNet(1, use_device_verifier=False, enable_consensus=True, config=cfg)
    node = net.nodes[0]  # constructed but NOT started: height stays 0
    try:
        reactor = node.consensus_reactor

        class FakePeer:
            def __init__(self, nid, height):
                self.node_id = nid
                self.kv = {"consensus_height": height}
                self.sent = []

            def set(self, k, v):
                self.kv[k] = v

            def get(self, k, default=None):
                return self.kv.get(k, default)

            def try_send(self, chan, msg):
                self.sent.append(msg)
                return True

            def is_running(self):
                return True

        a, b = FakePeer("peer-a", 40), FakePeer("peer-b", 40)

        class FakeSwitch:
            def peers(self):
                return [a, b]

        reactor.switch = FakeSwitch()
        reactor._sync_pump()
        reqs_a = [m for m in a.sent if m and m[0] == MSG_BLOCK_REQUEST]
        reqs_b = [m for m in b.sent if m and m[0] == MSG_BLOCK_REQUEST]
        assert len(reqs_a) + len(reqs_b) == SYNC_WINDOW, (
            f"window not filled: {len(reqs_a)}+{len(reqs_b)}"
        )
        assert reqs_a and reqs_b, "requests not distributed across peers"
        heights = sorted(
            json.loads(m[1:])["height"] for m in reqs_a + reqs_b
        )
        assert heights == list(range(1, SYNC_WINDOW + 1))
        # pump again immediately: everything in flight, nothing re-asked
        a.sent.clear(); b.sent.clear()
        reactor._sync_pump()
        assert not a.sent and not b.sent
    finally:
        pass  # never started: nothing to stop


def test_forged_parts_header_rejected():
    """A parts header without the current proposer's valid signature must
    not open an assembly buffer (unauthenticated first-header-wins would
    let anyone block assembly of the real proposal — r5 review), and an
    over-large part count is rejected outright."""
    import json as _json

    from txflow_tpu.consensus.reactor import MSG_PROPOSAL
    from txflow_tpu.types.part_set import make_part_set

    cfg = make_test_config()
    net = LocalNet(4, use_device_verifier=False, enable_consensus=True, config=cfg)
    node = net.nodes[0]  # constructed, not started: stable round state
    reactor = node.consensus_reactor

    class FakePeer:
        node_id = "forger"

        def __init__(self):
            self.kv = {}

        def set(self, k, v):
            self.kv[k] = v

        def get(self, k, default=None):
            return self.kv.get(k, default)

        def try_send(self, chan, msg):
            return True

    rs = node.consensus.round_state()
    # distinct part contents (identical parts would make the reversed-
    # hashes probe below a no-op)
    header, _ = make_part_set(
        b"".join(bytes([i]) * 512 for i in range(4)), part_size=512
    )
    forged = {
        "height": rs.height, "round": rs.round, "pol_round": -1,
        "block_hash": ("ab" * 32), "ts": 0, "sig": "cc" * 64,
        "parts": header.to_wire(),
    }
    reactor.receive(
        0x20, FakePeer(), bytes([MSG_PROPOSAL]) + _json.dumps(forged).encode()
    )
    assert reactor._part_bufs == {}, "forged header opened an assembly buffer"

    # header whose hash list disagrees with its root is invalid outright
    bad = dict(forged)
    bad_parts = header.to_wire()
    bad_parts["hashes"] = list(reversed(bad_parts["hashes"]))
    bad["parts"] = bad_parts
    try:
        reactor.receive(
            0x20, FakePeer(), bytes([MSG_PROPOSAL]) + _json.dumps(bad).encode()
        )
        raised = False
    except ValueError:
        raised = True
    assert raised, "inconsistent part-set header accepted"
    assert reactor._part_bufs == {}


def test_parallel_sync_50_blocks():
    """Judge r4 item 8 done-criteria: a fresh node syncs a 50+ block
    chain through the parallel request pool (window overlap itself is
    pinned deterministically by test_sync_pump_fills_window_across_peers;
    here: convergence and block identity at 50+ heights, timed)."""
    cfg = make_test_config()
    net = LocalNet(4, use_device_verifier=False, enable_consensus=True, config=cfg)
    for node in net.nodes:
        node.start()
    for i in range(3):
        for j in range(i + 1, 3):
            connect_switches(net.nodes[i].switch, net.nodes[j].switch)
    try:
        net.nodes[0].broadcast_tx(b"seed=1")
        # empty blocks churn fast under skip_timeout_commit
        for node in net.nodes[:3]:
            assert node.consensus.wait_for_height(50, timeout=120), (
                f"chain only reached {node.consensus.state.last_block_height}"
            )
        assert net.nodes[3].block_store.height() == 0

        t0 = time.monotonic()
        connect_switches(net.nodes[0].switch, net.nodes[3].switch)
        connect_switches(net.nodes[1].switch, net.nodes[3].switch)
        connect_switches(net.nodes[2].switch, net.nodes[3].switch)
        assert net.nodes[3].consensus.wait_for_height(50, timeout=120), (
            f"late node stuck at {net.nodes[3].consensus.state.last_block_height}"
        )
        parallel_t = time.monotonic() - t0
        synced = net.nodes[3].block_store.height()
        assert synced >= 50
        for h in (1, 25, 50):
            assert (
                net.nodes[3].block_store.load_block(h).hash()
                == net.nodes[0].block_store.load_block(h).hash()
            )
        # informational timing (absolute-rate asserts flake on loaded
        # CI boxes; the overlap property is pinned by the sync-pump test)
        rate = synced / max(parallel_t, 1e-6)
        print(f"parallel sync: {synced} blocks in {parallel_t:.2f}s ({rate:.0f} blocks/s)")
    finally:
        net.stop()


def test_validator_rotation_with_fast_path_on():
    """A val: tx must rotate the set even with the fast path RUNNING:
    the app flags it block-only via ResponseCheckTx.fast_path=False,
    honest validators refuse to sign it (no fast quorum can form), the
    block carries it as a Tx, and EndBlock applies the update at H+2.
    Ordinary txs keep fast-committing alongside (r5 soak follow-up: a
    fast-committed val: tx silently never rotated — BeginBlock clears
    the app's pending updates)."""
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(4, use_device_verifier=False, enable_consensus=True, config=cfg)
    net.start()
    try:
        # ordinary tx fast-commits
        net.broadcast_tx(b"fastok=1")
        assert net.wait_all_committed([b"fastok=1"], timeout=30)

        new_pv = MockPV(hashlib.sha256(b"rotate-live").digest())
        new_pub = new_pv.get_pub_key()
        tx = b"val:" + new_pub.hex().encode() + b"!5"
        net.broadcast_tx(tx)
        tx_hash = hashlib.sha256(tx).hexdigest().upper()

        def rotated():
            return all(
                n.consensus.state.validators.has_address(
                    Validator.from_pub_key(new_pub, 5).address
                )
                for n in net.nodes
            )

        assert wait_until(rotated, timeout=90), (
            "validator set must rotate with the fast path on"
        )
        # the val: tx must NOT have fast-committed (no certificate)
        for n in net.nodes:
            assert n.tx_store.load_tx_commit(tx_hash) is None, (
                "block-only tx was fast-committed"
            )
        # it traveled in a block's Txs
        store = net.nodes[0].block_store
        in_block = any(
            tx in store.load_block(h).txs
            for h in range(1, store.height() + 1)
        )
        assert in_block, "val: tx never entered a block"
        # fast path still healthy afterwards
        net.broadcast_tx(b"fastok=2")
        assert net.wait_all_committed([b"fastok=2"], timeout=30)
    finally:
        net.stop()


def test_malformed_announce_stops_peer_not_node():
    """Hostile ROUND_STEP announces (wrong types, oversized vote masks)
    must be contained: the reactor raises, the switch stops THAT peer,
    and the node keeps serving honest peers."""
    import json as _json

    from txflow_tpu.consensus.reactor import MSG_ROUND_STEP

    cfg = make_test_config()
    net = LocalNet(1, use_device_verifier=False, enable_consensus=True, config=cfg)
    node = net.nodes[0]  # constructed, not started: direct receive calls
    reactor = node.consensus_reactor

    class FakePeer:
        node_id = "hostile"

        def __init__(self):
            self.kv = {}

        def set(self, k, v):
            self.kv[k] = v

        def get(self, k, default=None):
            return self.kv.get(k, default)

        def try_send(self, chan, msg):
            return True

    hostile_bodies = [
        {"height": "not-an-int", "committed": 0},
        {"height": 1, "committed": 0, "prevotes": "zz"},  # bad hex
        {"height": 1, "committed": 0, "prevotes": "f" * 100000},  # huge mask
        {"height": 1},  # missing committed
    ]
    for body in hostile_bodies:
        try:
            reactor.receive(
                0x20, FakePeer(),
                bytes([MSG_ROUND_STEP]) + _json.dumps(body).encode(),
            )
            raised = False
        except Exception:
            raised = True  # the switch converts this into stop_peer
        assert raised, f"hostile announce accepted silently: {body}"
    # the reactor still serves a WELL-FORMED announce afterwards
    good = FakePeer()
    reactor.receive(
        0x20, good,
        bytes([MSG_ROUND_STEP])
        + _json.dumps(node.consensus.round_summary()).encode(),
    )
    assert good.get("consensus_height") is not None


def test_byzantine_vote_cannot_censor_block_only_tx():
    """One stray signed vote for a block-only tx must NOT wedge it: an
    in-flight vote set that can never reach quorum (honest validators
    refuse to sign fast_path=False txs) does not reserve the tx, so
    proposers still carry it in blocks and the rotation completes (r5
    review: is_tx_reserved treated any vote set as a permanent claim)."""
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(4, use_device_verifier=False, enable_consensus=True, config=cfg)
    net.start()
    try:
        new_pv = MockPV(hashlib.sha256(b"censor-target").digest())
        tx = b"val:" + new_pv.get_pub_key().hex().encode() + b"!5"
        net.broadcast_tx(tx)
        # a BYZANTINE validator signs the block-only tx (honest ones
        # won't): inject its vote into every node's pool
        tx_key = hashlib.sha256(tx).digest()
        byz = net.priv_vals[0]
        for node in net.nodes:
            from txflow_tpu.types import TxVote

            v = TxVote(
                height=0,
                tx_hash=tx_key.hex().upper(),
                tx_key=tx_key,
                validator_address=byz.get_address(),
            )
            byz.sign_tx_vote(node.chain_id, v)
            try:
                node.tx_vote_pool.check_tx(v)
            except Exception:
                pass

        def rotated():
            return all(
                n.consensus.state.validators.has_address(
                    Validator.from_pub_key(new_pv.get_pub_key(), 5).address
                )
                for n in net.nodes
            )

        assert wait_until(rotated, timeout=90), (
            "one byzantine vote censored the block-only tx"
        )
    finally:
        net.stop()
