"""WAL recovery edge cases: torn tails, corrupted records, garbage.

The CRC-framed WAL (utils/wal.py) promises: replay yields every intact
frame up to the first torn/corrupt one, then TRUNCATES the file there so
future appends restart on a frame boundary — never raising, never
resurrecting bytes past the damage. The consensus WAL layers a typed
JSON envelope on top and must tolerate frames whose CRC is fine but
whose payload no longer decodes.
"""

import struct
import zlib

from txflow_tpu.consensus.ticker import TimeoutInfo
from txflow_tpu.consensus.wal import ConsensusWAL
from txflow_tpu.utils.wal import _HDR, WAL

# ----------------------------------------------------------- utils.wal


def write_frames(path, payloads):
    w = WAL(str(path))
    for p in payloads:
        w.write(p)
    w.close()


def test_replay_truncated_tail_mid_payload(tmp_path):
    """Crash mid-append: the partial last frame is dropped and the file
    is truncated back to the last intact frame boundary."""
    path = tmp_path / "torn.wal"
    write_frames(path, [b"one", b"two", b"three"])
    whole = path.read_bytes()
    path.write_bytes(whole[:-2])  # tear 2 bytes off the last payload

    w = WAL(str(path))
    assert list(w.replay()) == [b"one", b"two"]
    # truncated to the good prefix: a fresh append lands on a boundary
    w.write(b"four")
    assert list(w.replay()) == [b"one", b"two", b"four"]
    w.close()


def test_replay_truncated_tail_mid_header(tmp_path):
    """Tear inside the 8-byte header itself (crash between header and
    payload writes)."""
    path = tmp_path / "torn-hdr.wal"
    write_frames(path, [b"alpha"])
    w = WAL(str(path))
    w.write(b"beta")
    w.close()
    whole = path.read_bytes()
    # keep frame 1 + only 3 bytes of frame 2's header
    keep = _HDR.size + 5 + 3
    path.write_bytes(whole[:keep])

    w = WAL(str(path))
    assert list(w.replay()) == [b"alpha"]
    assert w.size == _HDR.size + 5  # header fragment truncated away
    w.close()


def test_replay_corrupted_record_crc_mismatch(tmp_path):
    """Bit rot inside a middle record: everything from the corrupt frame
    on is dropped — a CRC break means frame boundaries can no longer be
    trusted, so later (intact-looking) frames must NOT be resurrected."""
    path = tmp_path / "rot.wal"
    write_frames(path, [b"good-1", b"good-2", b"good-3"])
    raw = bytearray(path.read_bytes())
    # flip one payload byte of the SECOND frame (header stays valid)
    second_payload_at = (_HDR.size + 6) + _HDR.size
    raw[second_payload_at] ^= 0xFF
    path.write_bytes(bytes(raw))

    w = WAL(str(path))
    assert list(w.replay()) == [b"good-1"]
    assert w.size == _HDR.size + 6
    w.close()


def test_replay_garbage_header_claims_absurd_length(tmp_path):
    """A header whose length field exceeds the file: treated as torn."""
    path = tmp_path / "absurd.wal"
    write_frames(path, [b"ok"])
    with open(path, "ab") as f:
        f.write(_HDR.pack(zlib.crc32(b"x"), 1 << 30))  # 1 GiB claim

    w = WAL(str(path))
    assert list(w.replay()) == [b"ok"]
    assert w.size == _HDR.size + 2
    w.close()


def test_replay_empty_and_pure_garbage_files(tmp_path):
    empty = WAL(str(tmp_path / "empty.wal"))
    assert list(empty.replay()) == []
    empty.close()

    garbage = tmp_path / "garbage.wal"
    garbage.write_bytes(b"\x00\x01\x02 not a wal at all")
    w = WAL(str(garbage))
    assert list(w.replay()) == []
    assert w.size == 0  # truncated to nothing
    w.close()


def test_replay_is_idempotent_after_truncate(tmp_path):
    path = tmp_path / "idem.wal"
    write_frames(path, [b"a", b"b"])
    with open(path, "ab") as f:
        f.write(b"\xde\xad")  # torn tail
    w = WAL(str(path))
    assert list(w.replay()) == [b"a", b"b"]
    assert list(w.replay()) == [b"a", b"b"]  # second pass: already clean
    w.close()


# ------------------------------------------------------ consensus WAL


def test_consensus_wal_skips_undecodable_payload_frames(tmp_path):
    """A frame with a VALID CRC but a payload that no longer decodes as a
    WAL message (e.g. written by a newer version) is skipped per-frame;
    surrounding messages survive."""
    path = tmp_path / "consensus.wal"
    cw = ConsensusWAL(str(path))
    cw.write_timeout(TimeoutInfo(duration=0.1, height=5, round=0, step=1))
    cw.wal.write(b"{json but not a wal message}")
    cw.wal.write(b'{"t": "unknown-kind", "x": 1}')
    cw.write_timeout(TimeoutInfo(duration=0.2, height=5, round=1, step=2))
    cw.close()

    cw = ConsensusWAL(str(path))
    msgs = cw.messages_after_end_height(5)
    assert [k for k, _ in msgs] == ["timeout", "timeout"]
    assert msgs[0][1].height == 5 and msgs[1][1].round == 1
    cw.close()


def test_consensus_wal_torn_tail_recovers_to_marker(tmp_path):
    """Crash right after the EndHeight fsync but mid-write of the next
    message: replay anchors at the marker and the torn frame vanishes."""
    path = tmp_path / "torn-consensus.wal"
    cw = ConsensusWAL(str(path))
    cw.write_timeout(TimeoutInfo(duration=0.1, height=7, round=0, step=1))
    cw.write_end_height(7)
    cw.write_timeout(TimeoutInfo(duration=0.1, height=8, round=0, step=1))
    cw.close()
    raw = path.read_bytes()
    path.write_bytes(raw[:-3])  # tear the post-marker message

    cw = ConsensusWAL(str(path))
    assert cw.messages_after_end_height(7) == []
    # the file healed: appends resume cleanly on the frame boundary
    cw.write_timeout(TimeoutInfo(duration=0.1, height=8, round=1, step=1))
    cw.close()
    cw = ConsensusWAL(str(path))
    msgs = cw.messages_after_end_height(7)
    assert len(msgs) == 1 and msgs[0][1].round == 1
    cw.close()


def test_consensus_wal_corrupt_record_before_marker(tmp_path):
    """Corruption BEFORE the last EndHeight marker also kills the marker
    (frame boundaries after the damage are untrusted): catchup replays
    the surviving prefix instead of wrongly trusting a later anchor."""
    path = tmp_path / "pre-marker.wal"
    cw = ConsensusWAL(str(path))
    cw.write_timeout(TimeoutInfo(duration=0.1, height=3, round=0, step=1))
    first_len = cw.wal.size
    cw.write_timeout(TimeoutInfo(duration=0.2, height=3, round=1, step=1))
    cw.write_end_height(3)
    cw.close()
    raw = bytearray(path.read_bytes())
    raw[first_len + _HDR.size] ^= 0xFF  # corrupt the second message
    path.write_bytes(bytes(raw))

    cw = ConsensusWAL(str(path))
    msgs = cw.messages_after_end_height(3)
    assert [k for k, _ in msgs] == ["timeout"]
    assert msgs[0][1].round == 0
    cw.close()


def test_timeout_info_roundtrip_fields(tmp_path):
    path = tmp_path / "fields.wal"
    cw = ConsensusWAL(str(path))
    ti = TimeoutInfo(duration=1.5, height=42, round=3, step=2)
    cw.write_timeout(ti)
    cw.close()
    cw = ConsensusWAL(str(path))
    [(kind, got)] = cw.messages_after_end_height(42)
    assert kind == "timeout"
    assert (got.duration, got.height, got.round, got.step) == (1.5, 42, 3, 2)
    cw.close()
