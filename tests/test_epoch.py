"""Dynamic validator sets: epoch rotation, evidence-driven slashing, and
quorum safety under power churn (epoch/ + the engine/verifier restage
path), driven at three levels:

- pure units: EpochManager's deterministic chain fold, the stake
  distribution generator, and ValidatorSet/quorum properties at the
  exact 2n/3 boundary under non-uniform stake;
- engine: a mid-run set change revalidates in-flight TxVoteSets (votes
  from removed validators discarded, survivors re-weighted, rotation
  itself can push a pending tx over the line), never mutates an
  already-latched certificate, and triggers ZERO in-run compiles on the
  device verifier (restage = two device_puts on the same shapes);
- LocalNet drills (tier-1): slash-the-equivocator and
  rotation-under-partition, both ending with every node on the
  identical validator-set hash.
"""

import hashlib
import random
import time

import pytest

from txflow_tpu.abci import AppConns, KVStoreApplication
from txflow_tpu.engine import TxExecutor, TxFlow
from txflow_tpu.epoch import EpochConfig, EpochManager
from txflow_tpu.faults import FaultSpec
from txflow_tpu.faults.byzantine import equivocating_block_votes
from txflow_tpu.faults.stake import (
    KINDS,
    churn_schedule,
    gini,
    stake_distribution,
)
from txflow_tpu.node.localnet import LocalNet
from txflow_tpu.pool import Mempool, TxVotePool
from txflow_tpu.store import MemDB, TxStore
from txflow_tpu.types import MockPV, TxVote, Validator, ValidatorSet
from txflow_tpu.types.vote_set import TxVoteSet
from txflow_tpu.utils.config import (
    EngineConfig,
    MempoolConfig,
    test_config as make_test_config,
)

CHAIN_ID = "txflow-localnet"  # LocalNet default
ENGINE_CHAIN = "txflow-epoch-test"


def wait_until(pred, timeout=20.0, poll=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return False


def make_pvs(n=4, powers=None, tag=b"epoch-val"):
    pvs = sorted(
        (MockPV(hashlib.sha256(tag + b"%d" % i).digest()) for i in range(n)),
        key=lambda p: p.get_address(),
    )
    powers = powers or [10] * n
    vals = ValidatorSet(
        [Validator.from_pub_key(pv.get_pub_key(), p) for pv, p in zip(pvs, powers)]
    )
    by_addr = {pv.get_address(): pv for pv in pvs}
    return [by_addr[v.address] for v in vals], vals


def reweighted(pvs, vals, powers):
    """Same validators (minus any with power 0), new powers, pv order."""
    by_addr = {pv.get_address(): p for pv, p in zip(pvs, powers)}
    return ValidatorSet(
        [
            Validator.from_pub_key(pv.get_pub_key(), by_addr[pv.get_address()])
            for pv in pvs
            if by_addr[pv.get_address()] > 0
        ]
    )


def make_engine(vals, use_device=False, verifier=None):
    conns = AppConns(KVStoreApplication())
    mempool = Mempool(MempoolConfig(cache_size=1000), conns.mempool)
    commitpool = Mempool(MempoolConfig(cache_size=1000))
    votepool = TxVotePool(MempoolConfig(cache_size=10000))
    tx_store = TxStore(MemDB())
    execu = TxExecutor(conns.consensus, mempool)
    flow = TxFlow(
        ENGINE_CHAIN,
        1,
        vals,
        votepool,
        mempool,
        commitpool,
        execu,
        tx_store,
        config=EngineConfig(max_batch=1024, use_device=use_device),
        verifier=verifier,
    )
    return flow, mempool, votepool, tx_store


def sign_vote(pv, tx: bytes, height=1, chain=ENGINE_CHAIN) -> TxVote:
    v = TxVote(
        height=height,
        tx_hash=hashlib.sha256(tx).hexdigest().upper(),
        tx_key=hashlib.sha256(tx).digest(),
        timestamp_ns=1700000000_000000000,
        validator_address=pv.get_address(),
    )
    pv.sign_tx_vote(chain, v)
    return v


# ----------------------------------------------- stake distributions


def test_stake_distribution_deterministic_and_shaped():
    for kind in KINDS:
        a = stake_distribution(kind, 8, seed=3)
        b = stake_distribution(kind, 8, seed=3)
        assert a == b, f"{kind}: same seed must reproduce the same powers"
        assert len(a) == 8 and all(p >= 1 for p in a)
        assert stake_distribution(kind, 8, seed=4) != a or kind == "uniform"
    assert gini(stake_distribution("uniform", 8)) == 0.0
    # concentration ordering: whale and longtail are strictly unequal
    assert gini(stake_distribution("whale", 8)) > 0.0
    assert gini(stake_distribution("longtail", 8)) > 0.0
    with pytest.raises(ValueError):
        stake_distribution("nope", 4)


def test_churn_schedule_covers_epochs():
    pubs = [b"\x01" * 32, b"\x02" * 32, b"\x03" * 32]
    sched = churn_schedule(pubs, 3, seed=1)
    assert sorted(sched) == [0, 1, 2]
    for entries in sched.values():
        assert [pk for pk, _ in entries] == pubs
        assert all(p >= 1 for _, p in entries)
    assert sched == churn_schedule(pubs, 3, seed=1)


# ------------------------------- quorum properties at the 2n/3 boundary


def test_quorum_power_exact_two_thirds_boundary_property():
    """quorum_power is the MINIMAL stake strictly exceeding 2/3 of the
    total, for every stake geometry the generator can produce: a random
    subset's stake reaches quorum iff 3*s > 2*total, never at exactly
    2n/3."""
    rng = random.Random(1234)
    for kind in KINDS:
        for trial in range(6):
            n = rng.randrange(1, 12)
            powers = stake_distribution(kind, n, seed=trial)
            _, vs = make_pvs(n, powers, tag=b"q%d-" % trial + kind.encode())
            total = vs.total_voting_power()
            q = vs.quorum_power()
            assert q == total * 2 // 3 + 1
            assert 3 * q > 2 * total, "quorum must strictly exceed 2/3"
            assert 3 * (q - 1) <= 2 * total, "quorum must be minimal"
            for _ in range(20):
                subset = [v for v in vs if rng.random() < 0.5]
                s = sum(v.voting_power for v in subset)
                assert (s >= q) == (3 * s > 2 * total), (
                    f"{kind}: subset stake {s}/{total} disagrees with the "
                    f"2/3 rule at quorum {q}"
                )


def test_update_with_change_set_property_under_churn():
    """Randomized churn (re-weights, removals, a joiner) over whale and
    long-tail sets: the returned set has exactly the expected membership
    and powers, the ORIGINAL set is untouched, and the new quorum is
    consistent with the new total."""
    rng = random.Random(99)
    for kind in ("whale", "longtail"):
        for trial in range(8):
            n = rng.randrange(2, 10)
            powers = stake_distribution(kind, n, seed=100 + trial)
            pvs, vs = make_pvs(n, powers, tag=b"c%d-" % trial + kind.encode())
            orig_hash = vs.hash()
            orig_total = vs.total_voting_power()
            expected = {v.address: (v.pub_key, v.voting_power) for v in vs}
            updates = []
            survivors = n
            for v in list(vs):
                r = rng.random()
                if r < 0.3 and survivors > 1:
                    updates.append((v.pub_key, 0))
                    del expected[v.address]
                    survivors -= 1
                elif r < 0.6:
                    p = rng.randrange(1, 50)
                    updates.append((v.pub_key, p))
                    expected[v.address] = (v.pub_key, p)
            joiner = MockPV(hashlib.sha256(b"joiner%d" % trial).digest())
            jp = rng.randrange(1, 50)
            jval = Validator.from_pub_key(joiner.get_pub_key(), jp)
            updates.append((joiner.get_pub_key(), jp))
            expected[jval.address] = (joiner.get_pub_key(), jp)

            new = vs.update_with_change_set(updates)
            assert {v.address: (v.pub_key, v.voting_power) for v in new} == expected
            new_total = sum(p for _, p in expected.values())
            assert new.total_voting_power() == new_total
            assert new.quorum_power() == new_total * 2 // 3 + 1
            # the original set is immutable
            assert vs.hash() == orig_hash
            assert vs.total_voting_power() == orig_total


# --------------------------------------------------- EpochManager fold


class _Blk:
    def __init__(self, height, evidence=()):
        self.height = height
        self.evidence = list(evidence)


class _St:
    def __init__(self, vs):
        self.next_validators = vs


def test_epoch_manager_slashes_at_boundary_once_per_epoch():
    pvs, vs = make_pvs(2, [10, 10], tag=b"mgr-val")
    mgr = EpochManager(EpochConfig(length=4, slash_fraction=0.5))
    ev = equivocating_block_votes(pvs[0], "mgr-chain", height=2)
    st = _St(vs)
    assert mgr.end_block_updates(_Blk(1), st, []) == []
    assert mgr.end_block_updates(_Blk(2, [ev]), st, []) == []
    # second offense same epoch: deduplicated
    ev2 = equivocating_block_votes(pvs[0], "mgr-chain", height=3, round_=1)
    assert mgr.end_block_updates(_Blk(3, [ev2]), st, []) == []
    changes = mgr.end_block_updates(_Blk(4), st, [])
    assert changes == [(pvs[0].get_pub_key(), 5)], "10 * (1-0.5) = 5, once"
    assert mgr.slashes_applied == 1
    # replayed block below the watermark must not re-arm the offense
    assert mgr.end_block_updates(_Blk(2, [ev]), st, []) == []
    assert mgr.end_block_updates(_Blk(8), st, []) == []
    assert mgr.boundaries_crossed == 2


def test_epoch_manager_full_slash_never_empties_the_set():
    """slash_fraction=1.0 removes — but removing the only validator
    would halt the chain, so the change degrades to a token power 1
    (liveness beats punishment)."""
    pvs, vs = make_pvs(1, [10], tag=b"solo-val")
    mgr = EpochManager(EpochConfig(length=2, slash_fraction=1.0))
    ev = equivocating_block_votes(pvs[0], "solo-chain", height=1)
    st = _St(vs)
    mgr.end_block_updates(_Blk(1, [ev]), st, [])
    changes = mgr.end_block_updates(_Blk(2), st, [])
    assert changes == [(pvs[0].get_pub_key(), 1)]
    vs.update_with_change_set(changes)  # must apply cleanly


def test_epoch_manager_scheduled_rotation_and_rebuild():
    pvs, vs = make_pvs(2, [10, 10], tag=b"rot-val")
    joiner = MockPV(hashlib.sha256(b"rot-joiner").digest())
    cfg = EpochConfig(length=2, schedule={0: [(joiner.get_pub_key(), 7)]})
    mgr = EpochManager(cfg)
    st = _St(vs)
    assert mgr.end_block_updates(_Blk(2), st, []) == [(joiner.get_pub_key(), 7)]
    assert mgr.rotations_applied == 1

    # rebuild refills the pending map from the current partial epoch only
    ev = equivocating_block_votes(pvs[0], "rot-chain", height=3)
    blocks = {1: _Blk(1), 2: _Blk(2), 3: _Blk(3, [ev])}

    class _Store:
        def load_block(self, h):
            return blocks.get(h)

    mgr2 = EpochManager(EpochConfig(length=2, slash_fraction=1.0))
    mgr2.rebuild(_Store(), 3)
    snap = mgr2.snapshot()
    assert snap["pending_slashes"] == 1
    assert snap["pending_addrs"] == [pvs[0].get_address().hex()]
    assert snap["last_boundary_height"] == 2


# --------------------------------------- in-flight vote sets under churn


def test_vote_set_revalidate_drops_reweights_and_latches():
    pvs, vs = make_pvs(4, [10, 10, 10, 10])
    tx = b"reval=1"
    tvs = TxVoteSet(
        ENGINE_CHAIN, 1, hashlib.sha256(tx).hexdigest().upper(),
        hashlib.sha256(tx).digest(), vs,
    )
    for pv in pvs[:2]:  # 20 < 27: in flight
        added, err = tvs.add_vote(sign_vote(pv, tx))
        assert added, err
    assert not tvs.maj23
    # pvs[0] removed, pvs[1] boosted to 40: survivor stake 40 >= 34
    new_vs = reweighted(pvs, vs, [0, 40, 5, 5])
    dropped, quorate = tvs.revalidate(new_vs)
    assert (dropped, quorate) == (1, True)
    assert tvs.maj23 and tvs.sum == 40
    assert pvs[0].get_address() not in tvs.votes


def test_vote_set_revalidate_latched_certificate_is_immutable():
    pvs, vs = make_pvs(4, [10, 10, 10, 10])
    tx = b"latched=1"
    tvs = TxVoteSet(
        ENGINE_CHAIN, 1, hashlib.sha256(tx).hexdigest().upper(),
        hashlib.sha256(tx).digest(), vs,
    )
    for pv in pvs[:3]:  # 30 >= 27: latched
        tvs.add_vote(sign_vote(pv, tx))
    assert tvs.maj23
    before = {a: v.signature for a, v in tvs.votes.items()}
    # even a set that removes every certified voter must not touch it
    dropped, quorate = tvs.revalidate(reweighted(pvs, vs, [0, 0, 0, 10]))
    assert (dropped, quorate) == (0, False)
    assert {a: v.signature for a, v in tvs.votes.items()} == before
    assert tvs.sum == 30 and tvs.val_set is vs


# -------------------------------------------------- engine rotation path


def test_engine_rotation_revalidates_inflight_and_commits():
    """Mid-run set change on the scalar path: the committed certificate
    stays byte-identical, the removed validator's in-flight vote is
    discarded, and the rotation itself pushes the survivor over the NEW
    quorum (commit on rotation, no new votes needed)."""
    pvs, vals = make_pvs(4, [10, 10, 10, 10])
    flow, mempool, votepool, tx_store = make_engine(vals)
    tx_a, tx_b = b"epochA=1", b"epochB=2"
    mempool.check_tx(tx_a)
    mempool.check_tx(tx_b)
    for pv in pvs[:3]:  # tx_a: 30 >= 27, commits
        votepool.check_tx(sign_vote(pv, tx_a))
    for pv in pvs[:2]:  # tx_b: 20 < 27, in flight
        votepool.check_tx(sign_vote(pv, tx_b))
    flow.step()
    h_a = hashlib.sha256(tx_a).hexdigest().upper()
    h_b = hashlib.sha256(tx_b).hexdigest().upper()
    cert_a = tx_store.load_tx_commit(h_a)
    assert cert_a is not None and len(cert_a.commits) == 3
    before = [(c.validator_address, c.signature) for c in cert_a.commits]
    assert tx_store.load_tx_commit(h_b) is None

    # rotation: pvs[0] slashed out, pvs[1] boosted 10 -> 40
    # (total 50, quorum 34: pvs[1]'s surviving vote alone is quorate)
    new_vals = reweighted(pvs, vals, [0, 40, 5, 5])
    flow.update_state(2, new_vals)

    rot = flow.last_rotation
    assert rot is not None and rot["restaged"] is True
    assert rot["votes_dropped"] == 1
    assert rot["commits_on_rotation"] == 1
    assert rot["val_set_hash"] == new_vals.hash().hex()
    # tx_b committed BY the rotation, certified under the new set
    cert_b = tx_store.load_tx_commit(h_b)
    assert cert_b is not None and len(cert_b.commits) == 1
    assert cert_b.commits[0].validator_address == pvs[1].get_address()
    # the pre-rotation certificate was not mutated
    after = [
        (c.validator_address, c.signature)
        for c in tx_store.load_tx_commit(h_a).commits
    ]
    assert after == before


def test_engine_device_rotation_restages_without_recompile():
    """The zero-recompile contract on the device path: a mid-run set
    change with unchanged validator count swaps the staged tables in
    place (restage), the bucket ladder stays keyed by batch size, and
    the post-rotation batch runs on the EXACT shapes the pre-rotation
    batch compiled — shapes_used must not grow."""
    from txflow_tpu.verifier import DeviceVoteVerifier

    pvs, vals = make_pvs(4, [10, 10, 10, 10])
    dv = DeviceVoteVerifier(vals, buckets=(16,))
    flow, mempool, votepool, tx_store = make_engine(
        vals, use_device=True, verifier=dv
    )
    round1 = [b"warm%d=v" % i for i in range(4)]
    for tx in round1:
        mempool.check_tx(tx)
        for pv in pvs[:3]:
            votepool.check_tx(sign_vote(pv, tx))
    flow.step()
    for tx in round1:
        assert tx_store.load_tx_commit(hashlib.sha256(tx).hexdigest().upper())

    shapes_before = set(dv.shapes_used)
    assert shapes_before, "round 1 must have exercised the device path"
    cap_before = dv.capacity
    buckets_before = dv.buckets

    new_vals = reweighted(pvs, vals, [20, 10, 10, 10])
    flow.update_state(2, new_vals)
    assert flow.last_rotation["restaged"] is True, (
        "same validator count must restage in place, not rebuild"
    )
    assert dv.val_set.hash() == new_vals.hash()
    assert dv.capacity == cap_before and dv.buckets == buckets_before

    round2 = [b"rot%d=v" % i for i in range(4)]
    for tx in round2:
        mempool.check_tx(tx)
        for pv in pvs[:3]:
            votepool.check_tx(sign_vote(pv, tx, height=2))
    flow.step()
    for tx in round2:
        # total 50, quorum 34; pvs[:3] carry 20+10+10 or 10+10+10+... —
        # whichever three signed, their stake under the new set clears it
        assert tx_store.load_tx_commit(hashlib.sha256(tx).hexdigest().upper())
    assert set(dv.shapes_used) == shapes_before, (
        "a set change must never introduce a new compiled shape "
        f"(before={shapes_before}, after={set(dv.shapes_used)})"
    )


# --------------------------------------------------- LocalNet drills


def _all_val_hashes(net):
    return {n.state_view().validators.hash() for n in net.nodes}


def _chain_tx_order(node, up_to):
    out = []
    for h in range(1, up_to + 1):
        b = node.block_store.load_block(h)
        if b is not None:
            out.append((h, tuple(b.vtxs), tuple(b.txs)))
    return out


def test_drill_slash_the_equivocator():
    """A double-signing validator's equivocation evidence lands on-chain
    and, within one epoch boundary (+H+2), every node derives the same
    3-validator set with the offender's quorum contribution zeroed —
    and the network keeps committing with the reduced set."""
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(
        4,
        use_device_verifier=False,
        enable_consensus=True,
        config=cfg,
        epoch_config=EpochConfig(length=4, slash_fraction=1.0),
    )
    offender = net.priv_vals[0]
    off_addr = offender.get_address()
    try:
        net.start()
        pre = b"pre-slash=v"
        net.broadcast_tx(pre)
        assert net.wait_all_committed([pre], timeout=30)

        ev = equivocating_block_votes(offender, CHAIN_ID, height=1)
        added, err = net.nodes[1].evidence_pool.add(ev)
        assert added, err

        def slashed_everywhere():
            return all(
                n.state_view().validators.get_by_address(off_addr)[1] is None
                for n in net.nodes
            )

        assert wait_until(slashed_everywhere, timeout=60), (
            "offender must leave every node's set within one epoch: "
            f"snapshots={[n.epoch_manager.snapshot() for n in net.nodes]}"
        )
        # identical derived set on every node, quorum recomputed
        assert len(_all_val_hashes(net)) == 1
        new_set = net.nodes[0].state_view().validators
        assert new_set.size() == 3 and new_set.total_voting_power() == 30
        assert new_set.quorum_power() == 21
        for n in net.nodes:
            snap = n.epoch_manager.snapshot()
            assert snap["slashes_applied"] >= 1
            assert off_addr.hex() in snap["last_slashed"]

        # liveness with the reduced set: a fresh tx commits everywhere,
        # certified by the three survivors only
        post = b"post-slash=v"
        net.broadcast_tx(post, node_index=1)
        assert net.wait_all_committed([post], timeout=30)
        h = hashlib.sha256(post).hexdigest().upper()
        for n in net.nodes:
            votes = n.tx_store.load_tx_votes(h)
            addrs = {v.validator_address for v in votes}
            assert off_addr not in addrs, (
                "a slashed validator must not contribute to new quorums"
            )
            stake = sum(
                new_set.get_by_address(a)[1].voting_power for a in addrs
            )
            assert stake >= new_set.quorum_power()
    finally:
        net.stop()


def test_drill_rotation_under_partition():
    """A scheduled rotation (node1's power 10 -> 30) crosses its epoch
    boundary while the network suffers a 2/2 partition. After heal,
    every node converges to the identical rotated validator-set hash
    and a byte-identical committed-tx order."""
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    pvs = [
        MockPV(hashlib.sha256(b"localnet-val%d" % i).digest()) for i in range(4)
    ]
    net = LocalNet(
        4,
        use_device_verifier=False,
        enable_consensus=True,
        config=cfg,
        priv_vals=pvs,
        fault_plan=FaultSpec(seed=0),
        epoch_config=EpochConfig(
            length=4, schedule={0: [(pvs[1].get_pub_key(), 30)]}
        ),
    )
    boosted = pvs[1].get_address()
    try:
        net.start()
        net.chaos.partition({"node0", "node1"})
        cut = b"cut-rotation=v"
        net.broadcast_tx(cut)
        time.sleep(1.0)
        h_cut = hashlib.sha256(cut).hexdigest().upper()
        assert not any(n.tx_store.has_tx(h_cut) for n in net.nodes), (
            "a 2-of-4 side holds 20 of 40 stake: below every quorum"
        )
        assert net.chaos.stats["partitioned"] > 0

        net.chaos.heal()
        assert net.wait_all_committed([cut], timeout=60), (
            "liveness must resume after heal"
        )

        def rotated_everywhere():
            for n in net.nodes:
                _, val = n.state_view().validators.get_by_address(boosted)
                if val is None or val.voting_power != 30:
                    return False
            return len(_all_val_hashes(net)) == 1

        assert wait_until(rotated_everywhere, timeout=60), (
            "scheduled rotation must reach every node after heal: "
            f"snapshots={[n.epoch_manager.snapshot() for n in net.nodes]}"
        )
        new_set = net.nodes[0].state_view().validators
        assert new_set.total_voting_power() == 60
        assert new_set.quorum_power() == 41

        # byte-identical committed-tx order across the whole network
        post = b"post-rotation=v"
        net.broadcast_tx(post, node_index=2)
        assert net.wait_all_committed([post], timeout=30)
        min_h = min(n.block_store.height() for n in net.nodes)
        assert min_h >= 4, "the chain must have crossed the epoch boundary"
        orders = [_chain_tx_order(n, min_h) for n in net.nodes]
        assert all(o == orders[0] for o in orders[1:]), (
            "nodes disagree on committed-tx order after rotation"
        )
    finally:
        net.stop()
