"""Per-tx tracing (trace/): sampling determinism, ring wraparound, leak
accounting, Prometheus exposition round-trip, Chrome-trace export, the
pipelined-vs-scalar span-parity drill, the LocalNet admission->commit
end-to-end export, and the tier-1 overhead gate (<3% of a scalar
signature verify per traced vote).
"""

import conftest  # noqa: F401

import hashlib
import json
import os
import subprocess
import sys
import time

import pytest

from txflow_tpu.trace.export import merge_by_tx, to_chrome_trace, write_chrome_trace
from txflow_tpu.trace.report import critical_path, format_line, merge_critical_paths
from txflow_tpu.trace.tracer import (
    NULL_TRACER,
    SPAN_COMMIT,
    SPAN_DEVICE,
    SPAN_E2E,
    SPAN_ORDER,
    NullTracer,
    Tracer,
    make_tracer,
)
from txflow_tpu.utils.config import TraceConfig, test_config as make_test_config
from txflow_tpu.utils.metrics import Registry, parse_exposition


def _hash(i: int) -> str:
    return hashlib.sha256(b"trace-tx-%d" % i).hexdigest().upper()


# -- sampling --


def test_sampling_deterministic_and_key_agreement():
    """Same (seed, rate) => same sampled set on every node and every
    replay, and the hex-hash and raw-digest predicates agree (the pools
    sample by key, everything downstream by hex hash)."""
    a = Tracer(TraceConfig(sample_rate=8, seed=42))
    b = Tracer(TraceConfig(sample_rate=8, seed=42))
    picks = []
    for i in range(4096):
        key = hashlib.sha256(b"trace-tx-%d" % i).digest()
        h = key.hex().upper()
        assert a.sampled(h) == b.sampled(h) == a.sampled_key(key)
        picks.append(a.sampled(h))
    frac = sum(picks) / len(picks)
    assert 0.06 < frac < 0.20  # ~1/8 of a uniform hash population
    # a different seed picks a different set
    c = Tracer(TraceConfig(sample_rate=8, seed=43))
    assert [c.sampled(_hash(i)) for i in range(4096)] != picks
    # rate 1 samples everything (the tests' dense mode)
    assert all(
        Tracer(TraceConfig(sample_rate=1)).sampled(_hash(i)) for i in range(64)
    )
    # garbage hashes never sample (defensive, not an error path)
    assert not a.sampled("not-hex!")


def test_ring_wraparound():
    tr = Tracer(TraceConfig(sample_rate=1, ring_capacity=16))
    for i in range(40):
        tr.span(_hash(i), SPAN_COMMIT, float(i), float(i) + 0.5)
    spans = tr.spans()
    assert len(spans) == 16
    # oldest-first, holding exactly the LAST capacity spans
    assert [s["start"] for s in spans] == [float(i) for i in range(24, 40)]
    assert tr.dropped() == 24
    assert tr.digest()["dropped"] == 24
    tr.reset()
    assert tr.spans() == [] and tr.dropped() == 0


def test_open_span_leak_accounting():
    tr = Tracer(TraceConfig(sample_rate=1))
    s1 = tr.begin(_hash(1), SPAN_DEVICE, 1.0)
    s2 = tr.begin(_hash(2), SPAN_DEVICE, 2.0)
    assert tr.open_count() == 2
    tr.finish(s1, 1.5)
    tr.abandon(s2)  # shed work closes without recording
    assert tr.open_count() == 0
    assert [s["name"] for s in tr.spans()] == [SPAN_DEVICE]
    # finish/abandon of id 0 (the NullTracer begin() return) are no-ops
    tr.finish(0)
    tr.abandon(0)
    assert tr.open_count() == 0


def test_anchor_latch_and_fifo_bound():
    tr = Tracer(TraceConfig(sample_rate=1, ring_capacity=16))  # anchor cap 64
    for i in range(70):
        tr.anchor(_hash(i), float(i))
    # the first 6 aged out FIFO; latching them records nothing
    tr.latch(_hash(0), t=100.0)
    assert tr.spans() == []
    tr.latch(_hash(69), t=100.0)
    (span,) = tr.spans()
    assert span["name"] == SPAN_E2E and span["start"] == 69.0
    # anchor is idempotent: re-anchoring does not reset the clock
    tr.anchor(_hash(42), 1.0)
    tr.anchor(_hash(42), 50.0)
    tr.latch(_hash(42), t=60.0)
    assert tr.spans()[-1]["start"] == 42.0  # the first anchor won


def test_null_tracer_and_config_switch():
    """enabled=False must be zero-cost AND zero-state: every method is a
    constant-return no-op with the same surface as the real tracer."""
    assert make_tracer(TraceConfig(enabled=False)) is NULL_TRACER
    assert isinstance(make_tracer(TraceConfig(enabled=True)), Tracer)
    n = NullTracer()
    assert not n.active
    assert not n.sampled(_hash(1)) and not n.sampled_key(b"\x00" * 32)
    assert n.begin(_hash(1), SPAN_DEVICE) == 0
    n.span(_hash(1), SPAN_DEVICE, 0.0, 1.0)
    n.finish(0)
    n.abandon(0)
    n.anchor(_hash(1))
    n.latch(_hash(1))
    assert n.open_count() == 0 and n.spans() == []
    assert n.digest()["enabled"] is False
    d = n.dump("node9")
    assert d["node"] == "node9" and d["spans"] == []


# -- metrics exposition --


def test_trace_metrics_prometheus_roundtrip():
    """The txflow_trace_* exposition must survive a scrape-parse: TYPE/
    HELP present, bucket counts cumulative and ending at +Inf, _sum and
    _count consistent with the observations."""
    reg = Registry()
    tr = Tracer(TraceConfig(sample_rate=1), registry=reg)
    for i in range(10):
        tr.span(_hash(i), SPAN_COMMIT, 0.0, 0.003)  # 3ms each
    fams = parse_exposition(reg.expose())
    name = "txflow_trace_span_commit_apply_seconds"
    assert fams[name]["type"] == "histogram"
    buckets = fams[name]["buckets"]
    assert buckets[-1][0] == "+Inf" and buckets[-1][1] == 10
    counts = [c for _, c in buckets]
    assert counts == sorted(counts)  # cumulative
    assert fams[name]["samples"][f"{name}_count"] == 10
    assert abs(fams[name]["samples"][f"{name}_sum"] - 0.03) < 1e-9
    assert fams["txflow_trace_spans_recorded_total"]["samples"][
        "txflow_trace_spans_recorded_total"
    ] == 10
    # digest quantiles: all 10 observations sit in the (2.5ms, 5ms] bucket
    q = tr.digest()["latency_ms"]["commit_apply"]
    assert q["count"] == 10
    assert 2.5 <= q["p50"] <= 5.0 and 2.5 <= q["p999"] <= 5.0


# -- export --


def _fake_dumps():
    # two nodes whose monotonic clocks start at different origins but
    # whose wall clocks agree: the merge must land both on one timeline
    return [
        {
            "node": "node0", "base_wall_ns": 1_000_000_000,
            "base_mono": 100.0, "open_spans": 0, "dropped": 0,
            "spans": [
                {"tx": _hash(1), "name": "mempool_ingest",
                 "start": 100.0, "end": 100.0},
                {"tx": _hash(1), "name": "commit_apply",
                 "start": 100.2, "end": 100.3},
            ],
        },
        {
            "node": "node1", "base_wall_ns": 1_000_000_000,
            "base_mono": 500.0, "open_spans": 0, "dropped": 0,
            "spans": [
                {"tx": _hash(1), "name": "vote_ingest",
                 "start": 500.1, "end": 500.1},
            ],
        },
    ]


def test_merge_by_tx_aligns_wall_clock():
    merged = merge_by_tx(_fake_dumps())
    spans = merged[_hash(1)]
    assert [s["name"] for s in spans] == [
        "mempool_ingest", "vote_ingest", "commit_apply",
    ]  # sorted by wall-clock ts despite different mono origins
    assert spans[0]["node"] == "node0" and spans[1]["node"] == "node1"
    assert spans[1]["ts_us"] - spans[0]["ts_us"] == pytest.approx(1e5)


def test_chrome_trace_structure(tmp_path):
    doc = to_chrome_trace(_fake_dumps())
    assert doc["displayTimeUnit"] == "ms"
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["args"]["name"] for e in meta if e["name"] == "process_name"} == {
        "node0", "node1",
    }
    assert len(xs) == 3
    for e in xs:
        assert e["args"]["tx"] == _hash(1)
        assert e["dur"] >= 0.0
        # track ids follow commit-path order
        assert e["tid"] == SPAN_ORDER.index(e["name"]) + 1
    out = tmp_path / "t.json"
    assert write_chrome_trace(str(out), _fake_dumps()) == 3
    assert len(json.loads(out.read_text())["traceEvents"]) == len(doc["traceEvents"])


def test_trace_export_cli(tmp_path):
    """tools/trace_export.py merges dump files (and unwraps the RPC
    {"result": ...} envelope) into a Perfetto-openable file."""
    d0, d1 = _fake_dumps()
    p0 = tmp_path / "d0.json"
    p1 = tmp_path / "d1.json"
    p0.write_text(json.dumps(d0))
    p1.write_text(json.dumps({"result": d1}))  # as saved from a raw RPC reply
    out = tmp_path / "merged.json"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "trace_export.py"),
         str(p0), str(p1), "--out", str(out)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr
    assert "3 spans from 2 node(s)" in r.stdout
    assert len([
        e for e in json.loads(out.read_text())["traceEvents"] if e["ph"] == "X"
    ]) == 3


# -- critical-path attribution --


def test_critical_path_attribution():
    stats = {"prep_s": 2.0, "lock_wait_s": 0.5, "route_s": 1.0,
             "dispatch_wait_s": 6.0}
    digest = {"latency_ms": {
        "linger": {"sum_ms": 1500.0, "p50": 1.0},
        "e2e": {"p50": 30.0},
        "vote_ingest": {"p50": 0.0},
        "host_prep": {"p50": 2.0},
        "device_verify": {"p50": 5.0},
        "quorum_latch": {"p50": 1.0},
        "commit_apply": {"p50": 2.0},
    }}
    cp = critical_path(stats, digest)
    assert cp["host_s"] == pytest.approx(2.5)  # prep - lock_wait + route
    assert cp["device_s"] == 6.0 and cp["lock_wait_s"] == 0.5
    assert cp["linger_s"] == 1.5
    assert cp["bound"] == "device"
    assert sum(cp["fractions"].values()) == pytest.approx(1.0, abs=0.01)
    # e2e p50 30ms minus 11ms of in-node stages = 19ms network residual
    assert cp["network_residual_ms"] == pytest.approx(19.0)
    fleet = merge_critical_paths([cp, cp])
    assert fleet["device_s"] == 12.0 and fleet["bound"] == "device"
    assert fleet["network_residual_ms"] == pytest.approx(19.0)
    assert "bound=device" in format_line(fleet)
    # empty inputs stay shaped (no div-by-zero, no fractions)
    empty = critical_path({}, {})
    assert "fractions" not in empty and "bound" not in empty
    assert merge_critical_paths([]) == {
        "host_s": 0, "device_s": 0, "lock_wait_s": 0, "linger_s": 0,
    }


# -- bench helpers --


def test_bench_lane_quantiles_and_slo_gate():
    from bench import lane_quantiles, slo_breached

    q = lane_quantiles([float(i) for i in range(1, 101)])
    assert q["count"] == 100
    assert q["p50_ms"] == 51.0 and q["p99_ms"] == 100.0  # nearest-rank
    assert lane_quantiles([]) == {
        "count": 0, "p50_ms": None, "p99_ms": None, "p999_ms": None,
    }
    ok = {"lanes": {"priority": {"p99_ms": 80.0}}}
    assert not slo_breached(ok, None)  # no budget => no gate
    assert not slo_breached(ok, 100.0)
    assert slo_breached(ok, 50.0)
    # the gate must not pass on absent data
    assert slo_breached({}, 100.0)
    assert slo_breached({"lanes": {"priority": {"p99_ms": None}}}, 100.0)


# -- end-to-end: LocalNet span parity + export --


def _run_traced_net(depth: int, tag: bytes, n_txs: int = 24):
    from txflow_tpu.node import LocalNet

    cfg = make_test_config()
    cfg.trace.sample_rate = 1  # dense: every tx traced
    cfg.engine.pipeline_depth = depth
    net = LocalNet(3, config=cfg, use_device_verifier=False)
    net.start()
    try:
        from txflow_tpu.admission.controller import ErrOverloaded

        txs = [b"%s-%d=v" % (tag, i) for i in range(n_txs)]
        for i, tx in enumerate(txs):
            n0 = net.nodes[0]
            if n0.admission is not None:
                # the RPC edge: admission verdict span, then ingest. The
                # front door may shed under this burst — fine for later
                # txs (family coverage needs SOME admission spans), but
                # tx 0 anchors the ordering assertion, so it must land.
                try:
                    n0.admission.admit_rpc(tx, hashlib.sha256(tx).digest())
                except ErrOverloaded:
                    assert i > 0, "first tx must not be shed on an idle net"
            net.broadcast_tx(tx)
        assert net.wait_all_committed(txs, timeout=120.0)
        # every begun span must close once commits drained (leak gate)
        deadline = time.monotonic() + 10.0
        while any(n.tracer.open_count() for n in net.nodes):
            assert time.monotonic() < deadline, [
                n.tracer.open_count() for n in net.nodes
            ]
            time.sleep(0.05)
        return (
            net.trace_dumps(),
            [n.txflow.pipeline_stats() for n in net.nodes],
            [n.tracer.digest() for n in net.nodes],
        )
    finally:
        net.stop()


def test_localnet_trace_parity_and_export(tmp_path):
    """The pipelined engine and the serial engine must emit the same
    span families for the same workload (parity drill: instrumentation
    lives in the shared prep/submit/collect/route helpers, and a refactor
    that drops a span in one mode fails here) — and the merged export
    must cover admission -> commit for a single tx on one timeline."""
    dumps_pipe, stats_pipe, digests_pipe = _run_traced_net(3, b"tp")
    dumps_ser, _, _ = _run_traced_net(1, b"ts")

    def families(dumps):
        # linger excluded: deadline flushes are timing-dependent.
        # sync_fetch/sync_verify/sync_apply excluded: a node that
        # briefly lags its peers catches up via the sync channel —
        # whether that happens is scheduler timing and topology, not
        # engine mode, and it emits all three families together
        return {
            s["name"] for d in dumps for s in d["spans"]
        } - {"linger", "sync_fetch", "sync_verify", "sync_apply"}

    fam_pipe, fam_ser = families(dumps_pipe), families(dumps_ser)
    assert fam_pipe == fam_ser
    assert {
        "admission", "mempool_ingest", "vote_ingest", "host_prep",
        "device_verify", "quorum_latch", "commit_apply", "e2e",
    } <= fam_pipe

    # merged view: one tx's spans cover admission -> commit_apply in
    # wall-clock order, with spans from every node (gossip + votes)
    merged = merge_by_tx(dumps_pipe)
    tx0 = hashlib.sha256(b"tp-0=v").hexdigest().upper()
    spans = merged[tx0]
    names = [s["name"] for s in spans]
    assert names[0] == "admission"
    assert "commit_apply" in names
    assert names.index("admission") < names.index("commit_apply")
    assert {s["node"] for s in spans} == {"node0", "node1", "node2"}

    out = tmp_path / "localnet_trace.json"
    n_events = write_chrome_trace(str(out), dumps_pipe)
    assert n_events == sum(len(d["spans"]) for d in dumps_pipe) > 0

    # critical-path attribution over the live run: busy seconds present,
    # fractions normalized, a bound named, network residual measurable
    # from the e2e digest
    cps = [
        critical_path(s, d) for s, d in zip(stats_pipe, digests_pipe)
    ]
    fleet = merge_critical_paths(cps)
    assert fleet["host_s"] >= 0.0 and "bound" in fleet
    assert any("e2e" in (d.get("latency_ms") or {}) for d in digests_pipe)


# -- overhead gate --


def test_trace_overhead_gate():
    """Default-on tracing must cost <3% of the verify hot path. The unit
    of work on that path is one signature verify; the tracer's per-vote
    cost at the default 1/64 sampling is one sampled() check plus 1/64
    of a span record. Measured against the repo's own scalar ed25519
    verify (the cheapest verifier this repo ever runs per vote)."""
    from txflow_tpu.crypto.ed25519 import public_key_from_seed, sign, verify

    seed = hashlib.sha256(b"trace-overhead").digest()
    pub = public_key_from_seed(seed)
    msg = b"trace-overhead-msg"
    sig = sign(seed, msg)

    n_verify = 30
    t0 = time.perf_counter()
    for _ in range(n_verify):
        assert verify(pub, msg, sig)
    per_verify = (time.perf_counter() - t0) / n_verify

    tr = Tracer(TraceConfig())  # default-on: sample_rate 64
    keys = [hashlib.sha256(b"ov-%d" % i).digest() for i in range(512)]
    hashes = [k.hex().upper() for k in keys]
    n_iter = 20_000
    t0 = time.perf_counter()
    for i in range(n_iter):
        h = hashes[i & 511]
        if tr.sampled_key(keys[i & 511]):
            tr.span(h, SPAN_COMMIT, 0.0, 0.001)
    per_vote = (time.perf_counter() - t0) / n_iter

    ratio = per_vote / per_verify
    assert ratio < 0.03, (
        f"tracing cost {per_vote * 1e6:.2f}us/vote is {ratio:.1%} of a "
        f"scalar verify ({per_verify * 1e3:.2f}ms) — over the 3% budget"
    )
