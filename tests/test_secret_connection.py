"""Authenticated-transport tests: X25519 agreement, secret-connection
handshake with verified ed25519 identities, tamper rejection, and full
vote gossip between nodes over authenticated TCP (the upstream secret-
connection slot the reference relies on for every socket).
"""

import conftest  # noqa: F401

import hashlib
import socket
import struct
import threading
import time

from txflow_tpu.crypto import ed25519, x25519
from txflow_tpu.crypto.hash import address_hash
from txflow_tpu.node.node import Node, NodeConfig
from txflow_tpu.p2p.secret import SecretConnection
from txflow_tpu.p2p.transport import ConnectionClosed, tcp_connect_raw, tcp_listen
from txflow_tpu.types.priv_validator import MockPV
from txflow_tpu.types.validator import Validator, ValidatorSet
from txflow_tpu.utils.config import test_config as make_test_config

CHAIN_ID = "test-secret"


def test_x25519_rfc7748_vector():
    # RFC 7748 §5.2 test vector 1
    k = bytes.fromhex(
        "a546e36bf0527c9d3b16154b82465edd62144c0ac1fc5a18506a2244ba449ac4"
    )
    u = bytes.fromhex(
        "e6db6867583030db3594c1a424b15f7c726624ec26b3353b10a903a6d0ab1c4c"
    )
    want = bytes.fromhex(
        "c3da55379de9c6908e94ea4df28d084f32eccf03491c71f754b4075577a28552"
    )
    assert x25519.scalar_mult(k, u) == want
    # DH property
    a, b = x25519.generate_private(), x25519.generate_private()
    assert x25519.shared_secret(a, x25519.public_key(b)) == x25519.shared_secret(
        b, x25519.public_key(a)
    )


def _pair(seed_a, seed_b):
    srv = tcp_listen("127.0.0.1", 0)
    host, port = srv.getsockname()
    out = {}

    def acceptor():
        s, _ = srv.accept()
        try:
            out["b"] = SecretConnection(s, seed_b)
        except Exception as e:
            out["b_err"] = e

    t = threading.Thread(target=acceptor, daemon=True)
    t.start()
    a = SecretConnection(tcp_connect_raw(host, port), seed_a)
    t.join(timeout=10)
    srv.close()
    return a, out.get("b"), out.get("b_err")


def test_secret_connection_handshake_and_identity():
    seed_a = hashlib.sha256(b"node-a").digest()
    seed_b = hashlib.sha256(b"node-b").digest()
    a, b, err = _pair(seed_a, seed_b)
    assert err is None
    # each side learned the VERIFIED identity of the other
    assert a.peer_pub_key == ed25519.public_key_from_seed(seed_b)
    assert b.peer_pub_key == ed25519.public_key_from_seed(seed_a)
    assert a.peer_id == address_hash(ed25519.public_key_from_seed(seed_b)).hex().upper()

    # bidirectional encrypted frames
    a.send(0x30, b"hello" * 100)
    chan, msg = b.recv(timeout=5)
    assert (chan, msg) == (0x30, b"hello" * 100)
    b.send(0x32, b"world")
    assert a.recv(timeout=5) == (0x32, b"world")
    a.close()
    b.close()


def test_secret_connection_rejects_tampered_frames():
    seed_a = hashlib.sha256(b"tamper-a").digest()
    seed_b = hashlib.sha256(b"tamper-b").digest()
    # man-in-the-middle relay that flips one ciphertext bit
    srv = tcp_listen("127.0.0.1", 0)
    host, port = srv.getsockname()
    out = {}

    def acceptor():
        s, _ = srv.accept()
        out["b"] = SecretConnection(s, seed_b)
        try:
            out["got"] = out["b"].recv(timeout=5)
        except ConnectionClosed:
            out["rejected"] = True

    t = threading.Thread(target=acceptor, daemon=True)
    t.start()
    a = SecretConnection(tcp_connect_raw(host, port), seed_a)
    t_start = time.monotonic()
    while "b" not in out and time.monotonic() - t_start < 5:
        time.sleep(0.01)
    # craft a frame, then corrupt it on the wire: send through the raw
    # socket with a flipped bit in the ciphertext
    ct = a._send_aead.encrypt(a._nonce(a._send_ctr), bytes([0x30]) + b"payload", b"")
    a._send_ctr += 1
    bad = bytearray(ct)
    bad[5] ^= 0x01
    a._sock.sendall(struct.pack("!I", len(bad)) + bytes(bad))
    t.join(timeout=10)
    assert out.get("rejected"), "tampered frame must close the connection"
    a.close()
    out["b"].close()


def test_vote_gossip_over_authenticated_tcp():
    """Two nodes with ed25519 node keys: the switch uses secret
    connections; peer ids are the verified key addresses; txs commit."""
    pvs = [MockPV(hashlib.sha256(b"sec-%d" % i).digest()) for i in range(2)]
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs])
    by_addr = {pv.get_address(): pv for pv in pvs}
    pvs_sorted = [by_addr[v.address] for v in vs]
    node_seeds = [hashlib.sha256(b"nodekey-%d" % i).digest() for i in range(2)]

    def build(i):
        return Node(
            node_id=f"sec-node{i}",
            chain_id=CHAIN_ID,
            val_set=vs,
            app=__import__(
                "txflow_tpu.abci.kvstore", fromlist=["KVStoreApplication"]
            ).KVStoreApplication(),
            priv_val=pvs_sorted[i],
            node_config=NodeConfig(
                config=make_test_config(),
                use_device_verifier=False,
                enable_consensus=False,
                node_key_seed=node_seeds[i],
            ),
        )

    nodes = [build(0), build(1)]
    for n in nodes:
        n.start()
    srv = tcp_listen("127.0.0.1", 0)
    host, port = srv.getsockname()
    acc = {}

    def acceptor():
        s, _ = srv.accept()
        acc["peer"] = nodes[0].switch.accept_tcp(s)

    t = threading.Thread(target=acceptor, daemon=True)
    t.start()
    peer0 = nodes[1].switch.dial_tcp(host, port)
    t.join(timeout=10)

    # peer ids are derived from the VERIFIED node pubkeys
    assert peer0.node_id == address_hash(
        ed25519.public_key_from_seed(node_seeds[0])
    ).hex().upper()
    assert acc["peer"].node_id == address_hash(
        ed25519.public_key_from_seed(node_seeds[1])
    ).hex().upper()

    try:
        txs = [b"sec-%d=v" % i for i in range(3)]
        for tx in txs:
            nodes[0].broadcast_tx(tx)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(n.is_committed(tx) for n in nodes for tx in txs):
                break
            time.sleep(0.02)
        assert all(n.is_committed(tx) for n in nodes for tx in txs)
    finally:
        for n in nodes:
            n.stop()
        srv.close()
