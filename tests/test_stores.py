"""Store tests vs MemDB/FileDB (reference tx/store_test.go, dbm memdb)."""

import hashlib

from txflow_tpu.store import FileDB, MemDB, TxStore
from txflow_tpu.types import MockPV, TxVote, TxVoteSet, Validator, ValidatorSet

CHAIN_ID = "txflow-test"


def build_voteset(n_vals=4, tx=b"tx-1", height=3):
    pvs = [MockPV() for _ in range(n_vals)]
    vals = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs])
    tx_hash = hashlib.sha256(tx).hexdigest().upper()
    tx_key = hashlib.sha256(tx).digest()
    vs = TxVoteSet(CHAIN_ID, height, tx_hash, tx_key, vals)
    for pv in pvs:
        v = TxVote(height, tx_hash, tx_key, 1700000000_000000000, pv.get_address())
        pv.sign_tx_vote(CHAIN_ID, v)
        added, err = vs.add_vote(v)
        assert added and err is None
    return vs, vals


def test_txstore_save_load_roundtrip():
    db = MemDB()
    store = TxStore(db)
    vs, vals = build_voteset()
    assert store.height() == 0
    store.save_tx(vs)
    assert store.height() == 3
    assert store.has_tx(vs.tx_hash)

    votes = store.load_tx_votes(vs.tx_hash)
    assert len(votes) == 4
    assert {v.validator_address for v in votes} == {v.validator_address for v in vs.get_votes()}

    loaded = store.load_tx(vs.tx_hash, CHAIN_ID, vals)
    assert loaded.has_two_thirds_majority()
    commit = store.load_tx_commit(vs.tx_hash)
    assert commit is not None and commit.height() == 3
    assert len(commit.commits) == 4

    assert store.load_tx_votes("FF" * 32) is None
    assert store.load_tx_commit("FF" * 32) is None


def test_txstore_height_watermark_persists():
    db = MemDB()
    store = TxStore(db)
    vs, _ = build_voteset(height=9)
    store.save_tx(vs)
    store2 = TxStore(db)
    assert store2.height() == 9


def test_filedb_durability_and_truncation(tmp_path):
    path = str(tmp_path / "kv.db")
    db = FileDB(path)
    db.set(b"a", b"1")
    db.set_sync(b"b", b"2")
    db.delete(b"a")
    db.close()

    db2 = FileDB(path)
    assert db2.get(b"a") is None
    assert db2.get(b"b") == b"2"
    assert list(db2.iterate()) == [(b"b", b"2")]
    db2.close()

    # torn tail: corrupt the last record, reopen truncates it
    import os

    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 3)
    db3 = FileDB(path)
    assert db3.get(b"b") == b"2"  # set_sync'd record intact
    db3.close()


def test_memdb_iterate_range():
    db = MemDB()
    for k in (b"a", b"b", b"c", b"d"):
        db.set(k, k)
    assert [k for k, _ in db.iterate(b"b", b"d")] == [b"b", b"c"]


def test_filedb_set_many_atomicish_and_torn_tail(tmp_path):
    """The committer's batched write group (set_many): one appended
    buffer, at most one fsync; a crash mid-append leaves a clean record
    prefix after reopen (same torn-tail contract as single sets)."""
    import os

    path = str(tmp_path / "batch.db")
    db = FileDB(path)
    pairs = [(b"k%02d" % i, b"v%02d" % i) for i in range(16)]
    db.set_many(pairs[:8], sync=True)
    size_after_first = os.path.getsize(path)
    db.set_many(pairs[8:], sync=False)
    db.close()

    db2 = FileDB(path)
    for k, v in pairs:
        assert db2.get(k) == v
    db2.close()

    # torn tail INSIDE the second group: reopen keeps the clean prefix
    # (first group fully intact — it was fsynced) and whatever whole
    # records of the second group survived
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 5)
    db3 = FileDB(path)
    for k, v in pairs[:8]:
        assert db3.get(k) == v
    # the torn record itself must be gone, not half-visible
    assert db3.get(pairs[-1][0]) is None
    db3.close()

    # degenerate truncation to mid-first-group: still a clean prefix
    with open(path, "r+b") as f:
        f.truncate(size_after_first - 2)
    db4 = FileDB(path)
    assert db4.get(pairs[0][0]) == pairs[0][1]
    assert db4.get(pairs[7][0]) is None  # its record was torn
    db4.close()


def test_tx_store_batch_matches_per_item(tmp_path):
    """save_txs_batch writes byte-identical rows to per-item save_tx
    (same keys, same blobs, same commit-order log)."""
    db_a, db_b = MemDB(), MemDB()
    sa, sb = TxStore(db_a), TxStore(db_b)
    sets = []
    for t in range(5):
        vs, _vals = build_voteset(tx=b"batch-%d" % t, height=t + 1)
        sets.append((vs, vs.get_votes()))
    for vs, votes in sets:
        sa.save_tx(vs, votes=votes)
    sb.save_txs_batch(sets)
    assert dict(db_a.iterate()) == dict(db_b.iterate())
