"""Unit tests for the self-healing liveness layer (txflow_tpu/health/).

Everything here runs against fakes with an explicit clock — no LocalNet,
no threads, no sleeps. The live-network behavior (partition -> watchdog
re-offers + score-driven reconnects -> commit parity) is covered by
tests/test_self_healing.py.
"""

import pytest

from txflow_tpu.health import (
    DegradedModeRegistry,
    HealthConfig,
    PeerScoreBoard,
    QuorumStallWatchdog,
)
from txflow_tpu.utils.metrics import Registry

# ------------------------------------------------------------- fakes


class FakeStats:
    def __init__(self):
        self.send_attempts = 0
        self.send_ok = 0
        self.send_fail = 0
        self.recv_count = 0
        self.duplicates = 0


class FakePeer:
    def __init__(self, node_id):
        self.node_id = node_id
        self.stats = FakeStats()
        self.sent = []  # (chan_id, msg) accepted by try_send

    def try_send(self, chan_id, msg):
        self.sent.append((chan_id, msg))
        return True


class FakeSwitch:
    def __init__(self, peer_ids=()):
        self._peers = {pid: FakePeer(pid) for pid in peer_ids}
        self.stopped = []  # (node_id, reason)

    def peers(self):
        return list(self._peers.values())

    def n_peers(self):
        return len(self._peers)

    def get_peer(self, node_id):
        return self._peers.get(node_id)

    def stop_peer(self, peer, reason=None):
        self._peers.pop(peer.node_id, None)
        self.stopped.append((peer.node_id, reason))

    def add_fake_peer(self, node_id):
        p = FakePeer(node_id)
        self._peers[node_id] = p
        return p


def make_board(peer_ids=("a", "b"), reconnector=None, **cfg_kw):
    cfg_kw.setdefault("stale_after", 1.0)
    cfg_kw.setdefault("min_sends_for_stale", 2)
    cfg_kw.setdefault("stale_penalty", 1.0)
    cfg_kw.setdefault("score_floor", -2.0)
    cfg_kw.setdefault("reconnect_base", 0.5)
    cfg_kw.setdefault("reconnect_cap", 4.0)
    cfg_kw.setdefault("reconnect_jitter", 0.0)  # deterministic delays
    cfg = HealthConfig(**cfg_kw)
    sw = FakeSwitch(peer_ids)
    reg = DegradedModeRegistry(Registry())
    board = PeerScoreBoard(sw, cfg, reg, reconnector=reconnector)
    return board, sw, reg


# ------------------------------------------------- peer score board


def test_quiet_idle_link_is_not_stale():
    board, sw, _ = make_board()
    for t in range(1, 20):
        board.tick(now=float(t))
    assert all(s == 0.0 for s in board.scores().values())


def test_blackholed_link_goes_stale_and_is_evicted():
    """Outbound attempts with no inbound progress (the chaos-partition
    signature: the interceptor reports send success) decay the score to
    the floor and evict — but only because a reconnector is wired."""
    board, sw, reg = make_board(reconnector=lambda nid: False)
    peer = sw.get_peer("a")
    for t in range(1, 10):
        peer.stats.send_attempts += 3  # we keep handing it frames
        board.tick(now=float(t))
        if ("a", None) not in [(n, None) for n, _ in sw.stopped] and sw.get_peer(
            "a"
        ) is None:
            break
    assert any(n == "a" for n, _ in sw.stopped), "stale peer must be evicted"
    assert reg.peer_evictions == 1
    # healthy peer b saw no sends: untouched
    assert sw.get_peer("b") is not None


def test_no_eviction_without_reconnector():
    """An eviction with no way back would amputate the peer permanently:
    unwired boards observe scores but never act."""
    board, sw, _ = make_board(reconnector=None)
    peer = sw.get_peer("a")
    for t in range(1, 30):
        peer.stats.send_attempts += 3
        board.tick(now=float(t))
    assert sw.stopped == []
    assert board.scores()["a"] <= -2.0  # score still reflects reality


def test_inbound_progress_rewards_and_clears_staleness():
    board, sw, _ = make_board(reconnector=lambda nid: False)
    peer = sw.get_peer("a")
    # go nearly stale...
    peer.stats.send_attempts += 5
    board.tick(now=1.0)
    board.tick(now=2.5)
    s_stale = board.scores()["a"]
    assert s_stale < 0
    # ...then the peer answers: reward, staleness re-arms
    peer.stats.recv_count += 1
    board.tick(now=2.6)
    assert board.scores()["a"] > s_stale
    board.tick(now=3.0)  # no new sends since progress: not stale again
    assert board.scores()["a"] > s_stale


def test_gossip_redundancy_tolerated_excess_dups_penalized():
    """2-3x duplicate delivery is normal gossip; a peer sending ONLY
    duplicates gets the dup penalty."""
    board, sw, _ = make_board(dup_penalty=0.1)
    peer = sw.get_peer("a")
    # fresh-heavy traffic: 10 frames, 3 dups -> no penalty
    peer.stats.recv_count += 10
    peer.stats.duplicates += 3
    board.tick(now=1.0)
    rewarded = board.scores()["a"]
    assert rewarded > 0
    # dup-only traffic: penalized net of the recv reward
    peer.stats.recv_count += 10
    peer.stats.duplicates += 10
    board.tick(now=2.0)
    assert board.scores()["a"] < rewarded + board.cfg.recv_reward


def test_send_failures_penalized():
    board, sw, _ = make_board()
    peer = sw.get_peer("a")
    peer.stats.send_fail += 2
    board.tick(now=1.0)
    assert board.scores()["a"] == pytest.approx(-2 * board.cfg.send_fail_penalty)


def test_backoff_delay_exponential_and_capped():
    board, _, _ = make_board()
    delays = [board._backoff_delay(level) for level in range(6)]
    assert delays[:4] == [0.5, 1.0, 2.0, 4.0]
    assert delays[4] == delays[5] == 4.0  # capped


def test_backoff_jitter_bounded():
    board, _, _ = make_board(reconnect_jitter=0.25)
    for level in range(5):
        for _ in range(50):
            d = board._backoff_delay(level)
            base = min(0.5 * 2**level, 4.0)
            assert base * 0.75 <= d <= base * 1.25


def test_evict_reconnect_cycle_with_growing_backoff():
    """Evicted peer re-dials on schedule; repeated failures grow the
    delay; a success that then shows inbound progress resets the level."""
    calls = []
    outcome = {"ok": False}

    def reconnector(nid):
        calls.append(nid)
        return outcome["ok"]

    board, sw, reg = make_board(peer_ids=("a",), reconnector=reconnector)
    peer = sw.get_peer("a")
    peer.stats.send_attempts += 5
    board.tick(now=1.0)
    for t in (2.5, 3.0, 3.5):  # decay to the floor -> evict
        board.tick(now=t)
        if sw.get_peer("a") is None:
            break
    assert reg.peer_evictions == 1
    assert "a" in board._pending
    # first redial due at eviction + base(level 0)=0.5, fails -> level up
    board.tick(now=10.0)
    assert calls == ["a"]
    assert reg.reconnect_failures == 1
    due = board._pending["a"]
    assert due == pytest.approx(11.0)  # 10.0 + 0.5 * 2**1
    # now let the redial succeed
    outcome["ok"] = True
    board.tick(now=11.5)
    assert reg.peer_reconnects == 1
    assert "a" not in board._pending
    # reconnected peer shows progress -> backoff level clears
    p2 = sw.add_fake_peer("a")
    p2.stats.recv_count += 1
    board.tick(now=12.0)
    assert board._backoff_level.get("a") is None


def test_reconnect_skipped_when_peer_already_back():
    board, sw, reg = make_board(peer_ids=("a",), reconnector=lambda nid: True)
    board._pending["a"] = 0.0  # due immediately — but the peer is live
    board.tick(now=1.0)
    assert reg.peer_reconnects == 0
    assert "a" not in board._pending


# ------------------------------------------------------ stall watchdog


class FakeEngine:
    def __init__(self):
        self.inflight = []  # (tx_hash, stake)

    def inflight_snapshot(self):
        return list(self.inflight)


class FakeVotePool:
    def __init__(self, segs_by_tx=None):
        self.segs_by_tx = segs_by_tx or {}

    def segs_for_tx(self, tx_hash, limit=512):
        return self.segs_by_tx.get(tx_hash, [])[:limit]


class FakeMempool:
    def __init__(self, txs=None):
        self.txs = txs or {}

    def get_tx(self, tx_key):
        return self.txs.get(tx_key)


TXH = "ab" * 32  # valid hex: the watchdog derives the mempool key from it


def make_watchdog(peer_ids=("a", "b", "c"), stall_timeout=1.0):
    cfg = HealthConfig(stall_timeout=stall_timeout)
    sw = FakeSwitch(peer_ids)
    reg = DegradedModeRegistry(Registry())
    engine = FakeEngine()
    pool = FakeVotePool({TXH: [b"seg1", b"seg2"]})
    mem = FakeMempool({bytes.fromhex(TXH): b"the-tx"})
    wd = QuorumStallWatchdog(engine, pool, mem, sw, cfg, reg)
    return wd, engine, sw, reg


def test_watchdog_quiet_when_quorum_advances():
    wd, engine, sw, reg = make_watchdog()
    engine.inflight = [(TXH, 10)]
    wd.tick(now=0.0)
    engine.inflight = [(TXH, 20)]  # stake advancing: re-armed each tick
    wd.tick(now=1.5)
    engine.inflight = [(TXH, 30)]
    wd.tick(now=3.0)
    assert reg.watchdog_firings == 0
    assert all(p.sent == [] for p in sw.peers())


def test_watchdog_fires_one_peer_then_escalates_to_all():
    wd, engine, sw, reg = make_watchdog()
    engine.inflight = [(TXH, 10)]
    wd.tick(now=0.0)
    wd.tick(now=1.2)  # past stall_timeout: level-0 firing, ONE peer
    assert reg.watchdog_firings == 1
    assert reg.watchdog_escalations == 0
    targeted = [p for p in sw.peers() if p.sent]
    assert len(targeted) == 1
    # votes re-offered as one frame + the tx bytes to the same peer
    assert len(targeted[0].sent) == 2
    assert reg.reoffered_votes == 2 and reg.reoffered_txs == 1
    wd.tick(now=2.4)  # still stuck: escalated firing, ALL peers
    assert reg.watchdog_firings == 2
    assert reg.watchdog_escalations == 1
    assert all(p.sent for p in sw.peers())


def test_watchdog_paced_not_a_flood():
    wd, engine, sw, reg = make_watchdog(stall_timeout=1.0)
    engine.inflight = [(TXH, 10)]
    wd.tick(now=0.0)
    for ms in range(1, 40):  # 0.1s ticks for ~4s
        wd.tick(now=ms / 10.0)
    # one firing per stall_timeout interval, not per tick
    assert reg.watchdog_firings <= 4


def test_watchdog_forgets_committed_txs():
    wd, engine, sw, reg = make_watchdog()
    engine.inflight = [(TXH, 10)]
    wd.tick(now=0.0)
    engine.inflight = []  # committed/purged
    wd.tick(now=5.0)
    assert wd._stalls == {}
    assert reg.watchdog_firings == 0


def test_watchdog_reports_stall_onset_age_across_rearms():
    """oldest_stall_age is measured from stall ONSET: the per-firing
    re-arm paces escalation but must not hide how long the tx is stuck."""
    wd, engine, sw, reg = make_watchdog(stall_timeout=1.0)
    engine.inflight = [(TXH, 10)]
    wd.tick(now=0.0)
    wd.tick(now=1.5)  # fires, re-arms
    wd.tick(now=2.5)  # fires again
    wd.tick(now=3.4)
    snap = reg.snapshot()
    assert snap["watchdog"]["oldest_stall_age"] == pytest.approx(3.4, abs=0.01)


# ----------------------------------------------------------- registry


def test_registry_snapshot_shape_and_metrics_parity():
    reg = DegradedModeRegistry(Registry())
    reg.note_watchdog_fired(escalated=False, votes=3, txs=1)
    reg.note_watchdog_fired(escalated=True, votes=2, txs=0)
    reg.note_peer_evicted()
    reg.note_peer_reconnected()
    reg.note_reconnect_failed()
    snap = reg.snapshot(peer_scores={"a": 1.0})
    assert snap["watchdog"]["firings"] == 2
    assert snap["watchdog"]["escalations"] == 1
    assert snap["watchdog"]["reoffered_votes"] == 5
    assert snap["watchdog"]["reoffered_txs"] == 1
    assert snap["peers"]["evictions"] == 1
    assert snap["peers"]["reconnects"] == 1
    assert snap["peers"]["reconnect_failures"] == 1
    assert snap["peers"]["scores"] == {"a": 1.0}
    # /metrics and /health never disagree about totals
    m = reg.metrics
    assert m.watchdog_firings.value() == 2
    assert m.peer_evictions.value() == 1
    assert m.peer_reconnects.value() == 1


def test_health_config_validation_defaults():
    cfg = HealthConfig()
    assert cfg.tick_interval > 0
    assert cfg.reconnect_base <= cfg.reconnect_cap
    assert cfg.score_floor < 0 < cfg.score_max
