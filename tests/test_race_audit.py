"""Lockset race auditor (analysis.racegraph) + regression tests for the
real findings its rollout surfaced and this change fixed:

- F3: ``DeviceVoteVerifier.shapes_used`` was a plain ``set`` mutated by
  the engine thread (``add`` on dispatch) and the BackgroundWarmer
  thread (``discard``/``in``/snapshot) with no lock — the old
  ``_copy_shape_set`` RuntimeError retry loop papered over concurrent
  resizes. Now ``_ShapeSet``: a ``set`` subclass whose mutators and
  membership take a leaf lock, with a ``snapshot()`` for consistent
  copies.
- F4: ``ByzantineLedger.committee_rescale`` wrote ``_committee_frac``
  under ``_mtx`` but then computed the effective thresholds OUTSIDE the
  lock, racing the gossip threads' ``_judge_locked`` reads. Thresholds
  are now derived under the lock (``_eff_thresholds_locked``).
- F5: ``HostPrepPool.map_shards`` incremented ``steals_total`` outside
  ``_stats_mtx`` in the caller-steals loop — concurrent callers lost
  increments. Steals are now tallied locally and folded in under the
  stats lock.

Auditor tests use PRIVATE RaceAuditor/LockAuditor instances so synthetic
races never pollute the default auditors that tests/conftest.py gates the
whole suite on.
"""

import threading

import pytest

from txflow_tpu.analysis.lockgraph import AuditedLock, LockAuditor
from txflow_tpu.analysis import racegraph
from txflow_tpu.analysis.racegraph import NULL_FIELD, RaceAuditor, shared_field
from txflow_tpu.engine.hostprep import HostPrepPool
from txflow_tpu.engine.shapes import _copy_shape_set
from txflow_tpu.health.byzantine import ByzantineConfig, ByzantineLedger
from txflow_tpu.verifier import _ShapeSet

# ---------------------------------------------------------------------------
# auditor mechanics (Eraser state machine)
# ---------------------------------------------------------------------------


def _make():
    la = LockAuditor()
    aud = RaceAuditor(lock_auditor=la)
    return la, aud


def _on_thread(fn):
    exc = []

    def _wrap():
        try:
            fn()
        except BaseException as e:  # pragma: no cover - surfaced below
            exc.append(e)

    t = threading.Thread(target=_wrap)
    t.start()
    t.join()
    if exc:
        raise exc[0]


def test_consistent_lockset_is_clean():
    la, aud = _make()
    lk = AuditedLock("L", auditor=la)
    f = aud.declare("x")
    with lk:
        f.note_write()

    def locked_write():
        with lk:
            f.note_write()

    _on_thread(locked_write)
    with lk:
        f.note_write()
    assert aud.races() == []
    snap = aud.report()["fields"]["x"]
    assert snap["lockset"] == ["L"]
    assert snap["max_threads"] == 2
    assert snap["racy"] == 0


def test_empty_lockset_two_threads_reports_once():
    la, aud = _make()
    lk = AuditedLock("L", auditor=la)
    f = aud.declare("x")
    with lk:
        f.note_write()  # EXCLUSIVE(main)

    def unlocked():
        for _ in range(5):
            f.note_write()  # same racy site every lap: deduped to one

    _on_thread(unlocked)
    races = aud.races()
    assert len(races) == 1
    r = races[0]
    assert r["field"] == "x"
    assert r["access"] == "write"
    assert "test_race_audit.py" in r["site"]
    assert aud.report()["fields"]["x"]["racy"] == 1


def test_read_only_sharing_is_benign_until_write():
    la, aud = _make()
    f = aud.declare("x")
    f.note_write()  # EXCLUSIVE(main)
    _on_thread(f.note_read)  # SHARED: refine but never report
    assert aud.races() == []
    _on_thread(f.note_write)  # write while shared, empty lockset: report
    assert len(aud.races()) == 1


def test_disjoint_locksets_intersect_to_empty():
    la, aud = _make()
    a = AuditedLock("A", auditor=la)
    b = AuditedLock("B", auditor=la)
    f = aud.declare("x")
    with a:
        f.note_write()

    def under_b():
        with b:
            f.note_write()  # candidate {B}

    def under_a():
        with a:
            f.note_write()  # {B} & {A} = {} -> report

    _on_thread(under_b)
    assert aud.races() == []
    _on_thread(under_a)
    assert len(aud.races()) == 1


def test_handoff_transfers_ownership():
    la, aud = _make()
    f = aud.declare("slot")
    f.note_write()  # owner: main
    f.handoff("queue hand-over to the worker")
    _on_thread(f.note_write)  # new exclusive owner, no report
    assert aud.races() == []
    assert aud.report()["fields"]["slot"]["handoffs"] == 1


def test_handoff_requires_justification():
    la, aud = _make()
    f = aud.declare("slot")
    with pytest.raises(AssertionError):
        f.handoff("")


def test_report_schema_and_reset():
    la, aud = _make()
    f = aud.declare("x")
    f.note_write()
    _on_thread(f.note_write)
    rep = aud.report()
    assert set(rep) == {"fields", "races"}
    s = rep["fields"]["x"]
    assert set(s) == {
        "fields", "reads", "writes", "handoffs", "max_threads",
        "lockset", "racy",
    }
    assert s["writes"] == 2
    aud.reset()
    assert aud.races() == []
    assert aud.report()["fields"]["x"]["racy"] == 0


def test_shared_field_is_noop_when_disabled(monkeypatch):
    monkeypatch.setenv("TXFLOW_RACE_AUDIT", "0")
    f = shared_field("anything")
    assert f is NULL_FIELD
    f.note_read()
    f.note_write()
    f.handoff("no-op")


def test_shared_field_requires_lock_audit(monkeypatch):
    # locksets come from lockgraph's held-stack: race audit without the
    # lock audit would see every lockset empty and cry wolf everywhere
    monkeypatch.setenv("TXFLOW_RACE_AUDIT", "1")
    monkeypatch.setenv("TXFLOW_LOCK_AUDIT", "0")
    assert shared_field("anything") is NULL_FIELD


# ---------------------------------------------------------------------------
# F3 regression: shapes_used is lock-guarded and still set-shaped
# ---------------------------------------------------------------------------


def test_shape_set_is_a_set_and_snapshot_consistent():
    s = _ShapeSet("test.shapes_used")
    s.add(("verify", 64, 64))
    s.add(("fused", 256, 64))
    s.discard(("verify", 64, 64))
    assert ("fused", 256, 64) in s
    assert ("verify", 64, 64) not in s
    # reader idiom the warm registry and the drills rely on
    assert set(s) == {("fused", 256, 64)}
    assert s.snapshot() == {("fused", 256, 64)}
    assert _copy_shape_set(s) == {("fused", 256, 64)}


def test_shape_set_concurrent_mutation_never_tears():
    s = _ShapeSet("test.shapes_used.stress")
    stop = threading.Event()
    errors = []

    def writer():
        try:
            i = 0
            while not stop.is_set():
                shape = ("verify", i % 64, 64)
                s.add(shape)
                s.discard(shape)
                i += 1
        except BaseException as e:  # pragma: no cover
            errors.append(e)

    def reader():
        try:
            while not stop.is_set():
                s.snapshot()
                ("verify", 1, 64) in s  # noqa: B015 - exercising __contains__
                _copy_shape_set(s)
        except BaseException as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer) for _ in range(2)] + [
        threading.Thread(target=reader) for _ in range(2)
    ]
    for t in threads:
        t.start()
    stop_timer = threading.Timer(0.5, stop.set)
    stop_timer.start()
    for t in threads:
        t.join()
    stop_timer.cancel()
    assert errors == []


# ---------------------------------------------------------------------------
# F4 regression: committee thresholds derived under the ledger lock
# ---------------------------------------------------------------------------


def test_committee_rescale_values_and_restore():
    led = ByzantineLedger(ByzantineConfig())
    # defaults: min_samples=32, max_bad_rate=0.5
    assert led.committee_rescale(0.25) == (8, 0.2)  # both floors engage
    assert led.committee_rescale(0.5) == (16, 0.25)
    assert led.committee_rescale(1.0) == (32, 0.5)
    assert led.committee_rescale(2.0) == (32, 0.5)  # clamped to full-set


def test_committee_rescale_concurrent_with_judging():
    led = ByzantineLedger(ByzantineConfig(min_samples=8, window=64))
    errors = []
    stop = threading.Event()

    def rescaler():
        try:
            f = 0.1
            while not stop.is_set():
                led.committee_rescale(f)
                f = 1.0 if f < 0.5 else 0.1
        except BaseException as e:  # pragma: no cover
            errors.append(e)

    def judge():
        try:
            i = 0
            while not stop.is_set():
                led.note_frame(f"peer-{i % 4}", kept=3,
                               drops={"stale_height": 1})
                i += 1
        except BaseException as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=rescaler)] + [
        threading.Thread(target=judge) for _ in range(2)
    ]
    for t in threads:
        t.start()
    timer = threading.Timer(0.5, stop.set)
    timer.start()
    for t in threads:
        t.join()
    timer.cancel()
    assert errors == []
    snap = led.snapshot()
    assert set(snap["breaker"]) == {"min_samples", "max_bad_rate"}


# ---------------------------------------------------------------------------
# F5 regression: caller-steal accounting folds in under the stats lock
# ---------------------------------------------------------------------------


class _NoWorkerPool(HostPrepPool):
    """Workers exit immediately: the CALLER must steal every queued
    shard — deterministic steal counts for the accounting regression."""

    def _worker(self):
        return


def test_steal_accounting_exact_when_serial():
    pool = _NoWorkerPool(workers=4)
    try:
        results, _wait = pool.map_shards(8, lambda lo, hi: (lo, hi))
        assert results == [(0, 2), (2, 4), (4, 6), (6, 8)]
        st = pool.stats()
        assert st["jobs_total"] == 4
        # all three non-inline shards were stolen by the caller — every
        # steal must be counted
        assert st["steals_total"] == 3
    finally:
        pool.close()


def test_concurrent_map_shards_jobs_total_exact():
    pool = HostPrepPool(workers=2)
    calls = 16
    try:
        def caller():
            for _ in range(calls):
                pool.map_shards(4, lambda lo, hi: hi - lo)

        threads = [threading.Thread(target=caller) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        st = pool.stats()
        assert st["jobs_total"] == 4 * calls * 2  # 2 shards per call
        assert st["steals_total"] >= 0
    finally:
        pool.close()


# ---------------------------------------------------------------------------
# end-to-end: a lock-disciplined pool holds its lockset under the
# DEFAULT auditor (the one the conftest gate reads)
# ---------------------------------------------------------------------------


def test_ingest_log_field_holds_lockset_under_default_auditor():
    if not racegraph.audit_enabled():
        pytest.skip("race audit disarmed (TXFLOW_RACE_AUDIT != 1)")
    from txflow_tpu.pool.base import IngestLogPool

    class _DrillPool(IngestLogPool):
        def add(self, key: bytes) -> None:
            with self._mtx:
                self._items[key] = key
                self._log_append(key)

    pool = _DrillPool()
    pool.add(b"a")
    _on_thread(lambda: pool.add(b"b"))
    out, _pos = pool._entries_from(0, 10)
    assert [k for k, _ in out] == [b"a", b"b"]
    summary = racegraph.default_race_auditor().report()["fields"]
    s = summary["pool._DrillPool.ingest_log"]
    assert s["racy"] == 0
    assert s["lockset"] == ["pool._DrillPool._mtx"]
