"""Gossip-layer tests (reference txvotepool/reactor_test.go, mempool/reactor_test.go).

Covers: N-node vote/tx convergence over in-memory switches, sender
suppression, byzantine-vote rejection across the network, peer-stop and
switch-stop thread hygiene (the reference's leaktest checks).
"""

import hashlib
import threading
import time

import pytest

from txflow_tpu.node import LocalNet
from txflow_tpu.p2p import (
    CHANNEL_TXVOTE,
    Switch,
    connect_switches,
    make_connected_switches,
)
from txflow_tpu.pool.mempool import Mempool
from txflow_tpu.pool.txvotepool import TxVotePool, vote_key
from txflow_tpu.reactors import StateView, TxVoteReactor
from txflow_tpu.types import TxVote
from txflow_tpu.types.priv_validator import MockPV
from txflow_tpu.types.validator import Validator, ValidatorSet
from txflow_tpu.utils.config import MempoolConfig, test_config

CHAIN_ID = "gossip-test"


def _valset(n=4, power=10):
    pvs = [MockPV(hashlib.sha256(b"gossip%d" % i).digest()) for i in range(n)]
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), power) for pv in pvs])
    return pvs, vs


def _vote(pv, tx: bytes, height=0) -> TxVote:
    key = hashlib.sha256(tx).digest()
    v = TxVote(
        height=height,
        tx_hash=key.hex().upper(),
        tx_key=key,
        validator_address=pv.get_address(),
    )
    pv.sign_tx_vote(CHAIN_ID, v)
    return v


def _wait(cond, timeout=10.0, poll=0.01) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(poll)
    return False


def make_vote_switches(n=4):
    """N switches with vote reactors only (no engine): pure gossip rig."""
    pvs, vs = _valset(n)
    pools, mempools = [], []

    def init(i, sw):
        pool = TxVotePool(MempoolConfig())
        mp = Mempool(MempoolConfig())
        pools.append(pool)
        mempools.append(mp)
        reactor = TxVoteReactor(
            lambda: StateView(CHAIN_ID, 0, vs),
            mp,
            pool,
            priv_val=None,  # votes injected directly; no sign routine
            poll_interval=0.01,
        )
        sw.add_reactor("txvote", reactor)
        return sw

    switches = make_connected_switches(n, init)
    return switches, pools, mempools, pvs, vs


def test_vote_gossip_converges_4_nodes():
    switches, pools, _, pvs, _ = make_vote_switches(4)
    try:
        txs = [b"tx-%d" % i for i in range(25)]
        votes = [_vote(pvs[i % 4], tx) for i, tx in enumerate(txs)]
        for v in votes:
            pools[0].check_tx(v)
        assert _wait(lambda: all(p.size() == len(votes) for p in pools))
        # every pool holds exactly the same votes
        keys = {vote_key(v) for v in votes}
        for p in pools:
            assert {k for k, _ in p.entries()} == keys
    finally:
        for sw in switches:
            sw.stop()


def test_gossip_sender_suppression():
    """A vote gossiped by a peer is never echoed back to that peer: after
    convergence each pool records the vote's sender, and pools stay at
    exactly one copy (dedup would catch echoes, but senders prove the
    suppression bookkeeping)."""
    switches, pools, _, pvs, _ = make_vote_switches(3)
    try:
        v = _vote(pvs[0], b"suppress-me")
        pools[0].check_tx(v)
        assert _wait(lambda: all(p.size() == 1 for p in pools))
        k = vote_key(v)
        # origin pool: sender is UNKNOWN (0); replicas: the real peer id
        assert pools[0].has_sender(k, 0)
        for p in pools[1:]:
            assert not p.has_sender(k, 0)
    finally:
        for sw in switches:
            sw.stop()


def test_height_throttle_defers_future_votes():
    """Votes two heights ahead of a peer are withheld until it catches up
    (reference 'allow for a lag of 1 block', txvotepool/reactor.go:240)."""
    switches, pools, _, pvs, _ = make_vote_switches(2)
    try:
        # peer height defaults to 0; a height-5 vote must NOT be sent
        v = _vote(pvs[0], b"future-tx", height=5)
        pools[0].check_tx(v)
        time.sleep(0.3)
        assert pools[1].size() == 0
        # raise the peer's view of our... of ITS height: node1's reactor
        # tells node0 its height via MSG_HEIGHT
        switches[1].reactors["txvote"].broadcast_height(4)
        assert _wait(lambda: pools[1].size() == 1)
    finally:
        for sw in switches:
            sw.stop()


def test_bad_frame_stops_peer():
    switches, pools, _, pvs, _ = make_vote_switches(2)
    try:
        assert switches[0].n_peers() == 1
        # node1 sends garbage on the vote channel -> node0 stops the peer
        switches[1].peers()[0].send(CHANNEL_TXVOTE, b"\x01\xff\xff\xff")
        assert _wait(lambda: switches[0].n_peers() == 0)
    finally:
        for sw in switches:
            sw.stop()


def test_byzantine_votes_rejected_across_network():
    """One validator signs with a wrong chain id: every honest node still
    commits every tx off the 3 honest votes and tallies the byzantine
    signature as invalid (reference byzantine pattern, MockPV breakage)."""
    pvs = [MockPV(hashlib.sha256(b"byz%d" % i).digest()) for i in range(4)]
    pvs[0].break_tx_vote_signing = True
    net = LocalNet(4, use_device_verifier=False, priv_vals=pvs)
    net.start()
    try:
        txs = [b"byz-tx-%d=v" % i for i in range(6)]
        for tx in txs:
            net.broadcast_tx(tx)
        assert net.wait_all_committed(txs, timeout=30)
        byz_addr = pvs[0].get_address()
        for node in net.nodes:
            # byzantine validator never appears in any commit certificate
            for tx in txs:
                tx_hash = hashlib.sha256(tx).hexdigest().upper()
                votes = node.tx_store.load_tx_votes(tx_hash)
                assert votes, tx_hash
                assert byz_addr not in {v.validator_address for v in votes}
        # at least the byzantine node itself verified (and rejected) its own
        # signatures; on other nodes a byz vote may arrive after commit and
        # be dropped unverified, so only the network-wide count is stable
        assert sum(n.metrics.invalid_votes.value() for n in net.nodes) > 0
    finally:
        net.stop()


def test_peer_stop_ends_broadcast_threads():
    """Reference leaktest: stopping peers/switches must not leak routines."""
    before = threading.active_count()
    switches, pools, _, pvs, _ = make_vote_switches(3)
    # traffic so broadcast threads are live
    pools[0].check_tx(_vote(pvs[0], b"leak-tx"))
    assert _wait(lambda: all(p.size() == 1 for p in pools))
    for sw in switches:
        sw.stop()
    assert _wait(lambda: threading.active_count() <= before, timeout=10)


def test_localnet_full_path_device():
    """4 nodes, device verifier, real sign routines: end-to-end commit."""
    net = LocalNet(4, use_device_verifier=True)
    net.start()
    try:
        txs = [b"dev-%d=v" % i for i in range(8)]
        for tx in txs:
            net.broadcast_tx(tx)
        assert net.wait_all_committed(txs, timeout=240)
        # commit certificates are quorum-sized (3 of 4 at equal stake)
        node = net.nodes[0]
        for tx in txs:
            tx_hash = hashlib.sha256(tx).hexdigest().upper()
            votes = node.tx_store.load_tx_votes(tx_hash)
            assert len(votes) >= 3
    finally:
        net.stop()


def test_node_clean_stop_no_thread_leak():
    before = threading.active_count()
    net = LocalNet(3, use_device_verifier=False)
    net.start()
    net.broadcast_tx(b"stop-tx=v")
    assert net.wait_all_committed([b"stop-tx=v"], timeout=20)
    net.stop()
    assert _wait(lambda: threading.active_count() <= before, timeout=10)


def test_partition_halts_quorum_and_heals():
    """Safety + liveness under partition (the property the reference's
    byzantine partition test exercises, consensus/byzantine_test.go): a
    2-2 split of a 4-validator net leaves both sides below the >2/3
    quorum, so NO tx commits anywhere; reconnecting the cut restores
    commits for both the stalled tx and fresh traffic."""
    from txflow_tpu.p2p import connect_switches

    net = LocalNet(4, use_device_verifier=False)
    net.start()
    try:
        # cut {0,1} from {2,3}
        for i in (0, 1):
            for j in (2, 3):
                sw = net.nodes[i].switch
                peer = sw.get_peer(net.nodes[j].switch.node_id)
                if peer is not None:
                    sw.stop_peer(peer, reason="partition")
                sw2 = net.nodes[j].switch
                peer2 = sw2.get_peer(net.nodes[i].switch.node_id)
                if peer2 is not None:
                    sw2.stop_peer(peer2, reason="partition")

        tx = b"part=1"
        net.broadcast_tx(tx)          # enters side {0,1} only
        net.nodes[2].broadcast_tx(tx)  # and side {2,3}
        time.sleep(1.5)  # generous: votes can only gather 2/4 per side
        assert not any(n.is_committed(tx) for n in net.nodes), (
            "2 of 4 validators must never reach >2/3"
        )

        # heal: reconnect the cut pairs
        for i in (0, 1):
            for j in (2, 3):
                connect_switches(net.nodes[i].switch, net.nodes[j].switch)
        assert net.wait_all_committed([tx], timeout=30), "heal must unblock"

        tx2 = b"part=2"
        net.broadcast_tx(tx2)
        assert net.wait_all_committed([tx2], timeout=30)
    finally:
        net.stop()
