"""Catch-up sync (txflow_tpu/sync/): wiped, lagging, and freshly-joined
nodes recover committed state from peers — under fire.

Covers the ISSUE 9 acceptance drills:

- LocalNet node wiped mid-run under chaos (gossip AND sync channels
  intercepted) rejoins via sync and converges to byte-identical
  certificate rows within the FaultSpec liveness budget;
- a Byzantine sync server feeding forged certificates / wrong epoch
  snapshots / truncated ranges is detected, scored down, banned, and
  rotated away from without poisoning the recovering node's state;
- graceful degradation to the fallback state when no peer can serve;
- wire codec roundtrips and TxStore ranged-read primitives.
"""

import hashlib
import os
import time

import pytest

from txflow_tpu.faults.plan import FaultSpec, GOSSIP_CHANNELS, SYNC_CHANNELS
from txflow_tpu.node.localnet import LocalNet
from txflow_tpu.state.store import StateStore
from txflow_tpu.store.db import MemDB
from txflow_tpu.store.tx_store import TxStore, _encode_votes
from txflow_tpu.sync import wire
from txflow_tpu.sync.config import SyncConfig
from txflow_tpu.sync.manager import SyncError, SyncManager
from txflow_tpu.types import MockPV, TxVote, TxVoteSet, Validator, ValidatorSet


# -- helpers --


def _fast_sync_cfg(**kw) -> SyncConfig:
    base = dict(
        poll_interval=0.05,
        status_interval=0.1,
        request_timeout=1.0,
        backoff_base=0.05,
        backoff_cap=0.5,
        fallback_cooldown=0.5,
        byzantine_ban=60.0,
    )
    base.update(kw)
    return SyncConfig(**base)


def _commit_set(net, txs, node_index=0, timeout=60):
    for tx in txs:
        net.broadcast_tx(tx, node_index=node_index)
    assert net.wait_all_committed(txs, timeout=timeout)


def _wait_has_all(node, hashes, timeout):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(node.tx_store.has_tx(h) for h in hashes):
            return True
        time.sleep(0.1)
    return False


def _mkvote(pv, chain_id, tx):
    key = hashlib.sha256(tx).digest()
    v = TxVote(
        height=0,
        tx_hash=key.hex().upper(),
        tx_key=key,
        validator_address=pv.get_address(),
    )
    pv.sign_tx_vote(chain_id, v)
    return v


# -- wire codec --


def test_wire_status_roundtrip():
    frame = wire.encode_status(12345, 67)
    assert frame[0] == wire.MSG_STATUS
    assert wire.decode_status(frame) == (12345, 67)


def test_wire_range_req_roundtrip():
    frame = wire.encode_range_req(9, 1024, 64)
    assert frame[0] == wire.MSG_RANGE_REQ
    assert wire.decode_range_req(frame) == (9, 1024, 64)


def test_wire_range_resp_roundtrip():
    pvs = [MockPV(hashlib.sha256(b"wirev%d" % i).digest()) for i in range(3)]
    vals = ValidatorSet(
        [Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs]
    )
    entries = [
        ("AA" * 32, b"cert-blob-1", b"tx-bytes-1"),
        ("BB" * 32, b"cert-blob-2", b""),
    ]
    frame = wire.encode_range_resp(7, 100, 250, entries, {0: vals})
    req_id, start, advert, got, snaps = wire.decode_range_resp(frame)
    assert (req_id, start, advert) == (7, 100, 250)
    assert got == entries
    assert list(snaps) == [0]
    assert [(v.address, v.voting_power) for v in snaps[0]] == [
        (v.address, v.voting_power) for v in vals
    ]


# -- TxStore ranged reads + tx-bytes rows --


def test_tx_store_ranged_reads():
    pv = MockPV(hashlib.sha256(b"storev").digest())
    vals = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10)])
    store = TxStore(MemDB())
    hashes = []
    for i in range(5):
        tx = b"range-%d=v" % i
        v = _mkvote(pv, "store-chain", tx)
        vs = TxVoteSet("store-chain", 0, v.tx_hash, v.tx_key, vals)
        vs.add_verified_vote(v)
        store.save_tx(vs, votes=[v], tx=tx)
        hashes.append(v.tx_hash)
    assert store.seq_count() == 5
    got = store.committed_range(0, 5)
    assert [h for _seq, h in got] == hashes
    assert [s for s, _h in got] == list(range(5))
    # partial windows clamp
    assert [h for _s, h in store.committed_range(3, 10)] == hashes[3:]
    assert store.committed_range(5, 10) == []
    # raw cert row + tx bytes roundtrip, byte-identical re-save
    for i, h in enumerate(hashes):
        cert = store.load_cert_row(h)
        assert cert is not None
        tx = store.load_tx_bytes(h)
        assert tx == b"range-%d=v" % i
    assert store.load_cert_row("CC" * 32) is None
    assert store.load_tx_bytes("CC" * 32) is None


# -- the tier-1 wipe-and-rejoin drill (chaos on gossip AND sync) --


def test_wipe_and_rejoin_under_chaos(tmp_path):
    spec = FaultSpec(
        seed=21,
        drop=0.05,
        delay=0.1,
        delay_max=0.01,
        channels=GOSSIP_CHANNELS | SYNC_CHANNELS,
        liveness_budget=60.0,
    )
    net = LocalNet(
        4,
        use_device_verifier=False,
        enable_consensus=False,
        fault_plan=spec,
        regossip_interval=0.2,
        sync_config=_fast_sync_cfg(),
    )
    net.make_durable(3, str(tmp_path / "node3"))
    net.start()
    try:
        first = [b"fee=1;wipe-%d=v" % i for i in range(30)]
        _commit_set(net, first, timeout=spec.liveness_budget)
        net.crash_node(3)
        net.wipe_node(3)
        assert os.listdir(tmp_path / "node3") == []  # really wiped
        # the flood continues while node 3 is gone — it must catch up on
        # txs it never saw, not just replay what it had
        second = [b"fee=1;late-%d=v" % i for i in range(15)]
        for tx in second:
            net.broadcast_tx(tx, node_index=1)
        # wait for the live quorum to commit the late batch before the
        # revive (wait_all_committed would poll the dead node), so the
        # wiped node recovers the whole set via sync instead of racing
        # in-flight votes into natively-latched certificates
        late_hashes = [hashlib.sha256(t).hexdigest().upper() for t in second]
        for i in (0, 1, 2):
            assert _wait_has_all(
                net.nodes[i], late_hashes, spec.liveness_budget
            ), f"live node {i} never committed the late batch"
        node3 = net.revive_node(3)
        want = [
            hashlib.sha256(t).hexdigest().upper() for t in first + second
        ]
        assert _wait_has_all(node3, want, spec.liveness_budget), (
            f"wiped node did not converge within the liveness budget: "
            f"{node3.sync_manager.snapshot()}"
        )
        # byte-identical certificates: each recovered H: row must equal
        # some live peer's row exactly (re-save is deterministic; under
        # chaos the manager rotates servers, and each peer legitimately
        # latched its own 2n/3 vote subset, so "which peer" varies)
        for h in want:
            live_rows = {
                net.nodes[i].tx_store.load_cert_row(h) for i in (0, 1, 2)
            }
            assert node3.tx_store.load_cert_row(h) in live_rows
            assert node3.tx_store.load_tx_bytes(h) == net.nodes[0].tx_store.load_tx_bytes(h)
        snap = node3.sync_manager.snapshot()
        assert snap["applied"] > 0  # recovery went through the sync path
        # sync metrics visible in the node's own registry
        expo = node3.metrics_registry.expose()
        assert "txflow_sync_txs_applied" in expo
    finally:
        net.stop()


def test_rejoin_commit_order_matches_server(tmp_path):
    """Quiet (no chaos, no rotation) wipe-rejoin: the recovered node's
    commit-order log must be byte-for-byte the serving peer's prefix —
    sync applies in the server's per-node order, never a reshuffle."""
    net = LocalNet(
        4,
        use_device_verifier=False,
        enable_consensus=False,
        sync_config=_fast_sync_cfg(),
    )
    net.make_durable(3, str(tmp_path / "node3"))
    net.start()
    try:
        txs = [b"fee=1;order-%d=v" % i for i in range(25)]
        _commit_set(net, txs)
        net.crash_node(3)
        net.wipe_node(3)
        node3 = net.revive_node(3)
        want = [hashlib.sha256(t).hexdigest().upper() for t in txs]
        assert _wait_has_all(node3, want, 30)
        server_id = node3.sync_manager.last_server
        server = next(n for n in net.nodes if n.node_id == server_id)
        n3 = node3.tx_store.seq_count()
        mine = [h for _s, h in node3.tx_store.committed_range(0, n3)]
        theirs = [h for _s, h in server.tx_store.committed_range(0, n3)]
        assert mine == theirs
    finally:
        net.stop()


# -- Byzantine sync servers --


def _byzantine_drill(tmp_path, tamper, expect_ban=True):
    """Shared rig: commit, wipe node 3, make node 0 a Byzantine sync
    server via the tamper hook, revive node 3 — it must strike/ban node
    0, rotate to an honest server, and still converge cleanly.

    node 0 is deterministically the FIRST server tried: revive_node
    reconnects peers in index order and _select_peer breaks the
    equal-advert/equal-score tie on iteration order, so the tampered
    response is always what the client sees first.
    """
    net = LocalNet(
        4,
        use_device_verifier=False,
        enable_consensus=False,
        sync_config=_fast_sync_cfg(),
    )
    net.make_durable(3, str(tmp_path / "node3"))
    net.start()
    try:
        txs = [b"fee=1;byz-%d=v" % i for i in range(20)]
        _commit_set(net, txs)
        net.crash_node(3)
        net.wipe_node(3)
        net.nodes[0].sync_reactor.tamper = tamper
        node3 = net.revive_node(3)
        want = [hashlib.sha256(t).hexdigest().upper() for t in txs]
        assert _wait_has_all(node3, want, 45), node3.sync_manager.snapshot()
        snap = node3.sync_manager.snapshot()
        # detected: the lie was a strike, not a silent accept; the liar
        # is locally banned from re-selection and the client rotated to
        # an honest server
        assert snap["byzantine_strikes"] >= 1, snap
        if expect_ban:
            assert "node0" in snap["banned_peers"], snap
        assert snap["rotations"] >= 1
        # ... and the recovered state is NOT poisoned: rows match an
        # honest server byte-for-byte
        src = net.nodes[1]
        for h in want:
            assert node3.tx_store.load_cert_row(h) == src.tx_store.load_cert_row(h)
        return snap
    finally:
        net.stop()


def test_byzantine_forged_certificate(tmp_path):
    def forge(entries, snapshots):
        out = []
        for h, cert, tx in entries:
            # flip a byte inside the cert blob's middle (signature
            # region): the cert still decodes, a signature no longer
            # verifies
            mid = len(cert) // 2
            cert = cert[:mid] + bytes([cert[mid] ^ 0xFF]) + cert[mid + 1 :]
            out.append((h, cert, tx))
        return out, snapshots

    _byzantine_drill(tmp_path, forge)


def test_byzantine_wrong_epoch_snapshot(tmp_path):
    evil_pv = MockPV(hashlib.sha256(b"evil-epoch").digest())
    evil_set = ValidatorSet([Validator.from_pub_key(evil_pv.get_pub_key(), 99)])

    def wrong_epoch(entries, snapshots):
        # claim every served height's votes were cast under a different
        # validator set — the client's OWN record must win, and the
        # mismatch must read as a strike
        return entries, {h: evil_set for h in snapshots} or {0: evil_set}

    _byzantine_drill(tmp_path, wrong_epoch)


def test_byzantine_truncated_range(tmp_path):
    def truncate(entries, snapshots):
        # serve fewer entries than the response's own advert admits
        return entries[: max(1, len(entries) // 2)], snapshots

    _byzantine_drill(tmp_path, truncate)


def test_byzantine_tx_hash_mismatch(tmp_path):
    def swap_tx(entries, snapshots):
        # serve tx bytes that don't hash to the certified tx_hash
        return [(h, cert, tx + b"!") for h, cert, tx in entries], snapshots

    _byzantine_drill(tmp_path, swap_tx)


# -- graceful degradation: no peer can serve --


def test_fallback_when_no_peer_can_serve(tmp_path):
    """Every candidate server serves empty ranges (a Byzantine strike),
    so both get banned and no servable peer remains -> after max_rounds
    failed rounds the client degrades to the consensus-block fallback
    state instead of spinning, and surfaces it in /health's sync
    section."""
    net = LocalNet(
        3,
        use_device_verifier=False,
        enable_consensus=False,
        sync_config=_fast_sync_cfg(max_rounds=2, fallback_cooldown=30.0),
    )
    net.make_durable(2, str(tmp_path / "node2"))
    net.start()
    try:
        txs = [b"fee=1;fb-%d=v" % i for i in range(10)]
        _commit_set(net, txs)
        net.crash_node(2)
        net.wipe_node(2)

        def serve_nothing(entries, snapshots):
            return [], {}

        for i in (0, 1):
            net.nodes[i].sync_reactor.tamper = serve_nothing
        node2 = net.revive_node(2)
        deadline = time.monotonic() + 30
        snap = {}
        while time.monotonic() < deadline:
            snap = node2.sync_manager.snapshot()
            if snap["state"] == "fallback":
                break
            time.sleep(0.1)
        assert snap["state"] == "fallback", snap
        assert snap["fallbacks"] >= 1
        # degraded, loudly: the health registry flips unhealthy
        reg = node2.health.registry
        reg.refresh(node2)
        health = reg.snapshot()
        assert health["sync"]["state"] == "fallback"
        assert not health["healthy"]
    finally:
        net.stop()


# -- stall / rotation / backoff --


def test_stall_rotates_to_live_server(tmp_path):
    """A server that never answers range requests is a stall (timeout),
    not a Byzantine strike: milder penalty, rotation, and the client
    still converges via the next peer."""
    net = LocalNet(
        3,
        use_device_verifier=False,
        enable_consensus=False,
        sync_config=_fast_sync_cfg(request_timeout=0.4),
    )
    net.make_durable(2, str(tmp_path / "node2"))
    net.start()
    try:
        txs = [b"fee=1;stall-%d=v" % i for i in range(10)]
        _commit_set(net, txs)
        net.crash_node(2)
        net.wipe_node(2)

        # node 0 adverts (status flows) but its range responses arrive
        # far past request_timeout: to the client that is a stall, not a
        # provable lie
        def black_hole(entries, snapshots):
            time.sleep(5)  # well past request_timeout
            return entries, snapshots

        net.nodes[0].sync_reactor.tamper = black_hole
        node2 = net.revive_node(2)
        want = [hashlib.sha256(t).hexdigest().upper() for t in txs]
        assert _wait_has_all(node2, want, 45), node2.sync_manager.snapshot()
        snap = node2.sync_manager.snapshot()
        # if node 0 was ever selected first, a timeout + rotation was
        # recorded; either way convergence happened via a live server
        assert snap["applied"] >= len(want)
        if snap["timeouts"]:
            assert snap["rotations"] >= 1
            assert "node0" not in snap["banned_peers"]  # stall != byzantine
    finally:
        net.stop()


# -- lagging (not wiped) node: tail catch-up --


def test_lagging_node_catches_up_without_wipe(tmp_path):
    """A node partitioned away (links cut, no wipe) falls behind, then
    rejoins: sync must close the gap from roughly its own count, and the
    txs it already has must dedup (fetched counts only NEW work)."""
    net = LocalNet(
        4,
        use_device_verifier=False,
        enable_consensus=False,
        sync_config=_fast_sync_cfg(),
    )
    net.make_durable(3, str(tmp_path / "node3"))
    net.start()
    try:
        first = [b"fee=1;lag-a-%d=v" % i for i in range(12)]
        _commit_set(net, first)
        # crash (not wipe): committed state survives on disk
        net.crash_node(3)
        second = [b"fee=1;lag-b-%d=v" % i for i in range(12)]
        for tx in second:
            net.broadcast_tx(tx, node_index=0)
        time.sleep(0.5)
        node3 = net.revive_node(3)
        assert node3.tx_store.seq_count() >= len(first)  # durable state intact
        want = [hashlib.sha256(t).hexdigest().upper() for t in first + second]
        assert _wait_has_all(node3, want, 45), node3.sync_manager.snapshot()
    finally:
        net.stop()


# -- honest short responses: resume, never strike --


def test_honest_byte_capped_responses_resume(tmp_path):
    """A max_resp_bytes small enough that every honest response is
    byte-capped to ~1 entry: the client must treat the short prefixes as
    progress and resume, NOT strike the honest servers Byzantine (which
    used to ban every peer in turn and wedge sync in fallback)."""
    net = LocalNet(
        4,
        use_device_verifier=False,
        enable_consensus=False,
        sync_config=_fast_sync_cfg(max_resp_bytes=256),
    )
    net.make_durable(3, str(tmp_path / "node3"))
    net.start()
    try:
        txs = [b"fee=1;cap-%d=v" % i for i in range(20)]
        _commit_set(net, txs)
        net.crash_node(3)
        net.wipe_node(3)
        node3 = net.revive_node(3)
        want = [hashlib.sha256(t).hexdigest().upper() for t in txs]
        assert _wait_has_all(node3, want, 45), node3.sync_manager.snapshot()
        snap = node3.sync_manager.snapshot()
        assert snap["byzantine_strikes"] == 0, snap
        assert snap["banned_peers"] == [], snap
    finally:
        net.stop()


# -- unit rigs: manager verify / lag internals --


class _StubTxFlow:
    def __init__(self, vals):
        self.val_set = vals
        self.applied = []

    def apply_synced_commit(self, vs, votes, tx):
        self.applied.append(vs.tx_hash)
        return True


class _StubPeer:
    def __init__(self, node_id="server"):
        self.node_id = node_id


def _val_set(tag, n, power=10):
    pvs = [MockPV(hashlib.sha256(b"%s-%d" % (tag, i)).digest()) for i in range(n)]
    vals = ValidatorSet(
        [Validator.from_pub_key(pv.get_pub_key(), power) for pv in pvs]
    )
    return pvs, vals


def _signed_votes(chain_id, pvs, tx, height):
    key = hashlib.sha256(tx).digest()
    votes = []
    for pv in pvs:
        v = TxVote(
            height=height,
            tx_hash=key.hex().upper(),
            tx_key=key,
            validator_address=pv.get_address(),
        )
        pv.sign_tx_vote(chain_id, v)
        votes.append(v)
    return votes


def _entry(chain_id, pvs, tx, height):
    """One served (tx_hash, cert_blob, tx) triple signed at ``height``."""
    votes = _signed_votes(chain_id, pvs, tx, height)
    return (votes[0].tx_hash, _encode_votes(votes), tx)


def _unit_manager(vals, state_store=None):
    return SyncManager(
        "unit-chain",
        TxStore(MemDB()),
        _StubTxFlow(vals),
        switch=None,
        state_store=state_store,
        config=SyncConfig(),
    )


def test_lag_ignores_banned_peer_adverts():
    """A Byzantine-struck peer's inflated advert must stop counting
    toward lag() — otherwise one liar advertising 2^60 keeps the node
    cycling syncing->fallback (and /health unhealthy) forever."""
    _pvs, vals = _val_set(b"lagv", 1)
    mgr = _unit_manager(vals)
    mgr.note_status("liar", 2**60, 0)
    mgr.note_status("honest", 5, 0)
    assert mgr.lag() == 2**60
    mgr._strike(_StubPeer("liar"), SyncError("forged", byzantine=True))
    # the liar's advert is dropped and its ban excludes any re-advert
    assert mgr.lag() == 5
    mgr.note_status("liar", 2**60, 0)  # re-advert while banned: ignored
    assert mgr.lag() == 5
    snap = mgr.snapshot()
    assert snap["best_advert"] == 5
    assert "liar" in snap["banned_peers"]


def test_mixed_height_certificate_is_byzantine():
    """votes[0].height selects the validator set: a certificate mixing
    vote heights could tally other-height votes under the wrong stake
    weights, so it must be rejected as a strike, not verified."""
    pvs, vals = _val_set(b"mixv", 4)
    mgr = _unit_manager(vals)
    tx = b"mixed=v"
    votes = _signed_votes("unit-chain", pvs[:2], tx, height=3)
    votes += _signed_votes("unit-chain", pvs[2:], tx, height=4)
    entry = (votes[0].tx_hash, _encode_votes(votes), tx)
    with pytest.raises(SyncError) as ei:
        mgr._verify_apply(_StubPeer(), [entry], {})
    assert ei.value.byzantine
    assert "mixing vote heights" in str(ei.value)
    assert mgr.txflow.applied == []


# -- epoch-crossing recovery: trust-chain snapshot verification --


def test_fresh_node_verifies_under_endorsed_snapshot():
    """A wiped/fresh node with no record for a height verifies under the
    server's snapshot when the certificate's proven signers carry a 2/3
    quorum of the set it does trust — and pins the learned set (memory +
    state store) so later heights resolve as its own record."""
    old_pvs, old_vals = _val_set(b"epoch-old", 4)
    # rotate ONE validator out: 3/4 of the old set's power still signs,
    # above the old set's 2/3 quorum -> the transition is endorsed
    new_pvs = old_pvs[:3] + _val_set(b"epoch-new", 1)[0]
    new_vals = ValidatorSet(
        [Validator.from_pub_key(pv.get_pub_key(), 10) for pv in new_pvs]
    )
    state_store = StateStore(MemDB())
    mgr = _unit_manager(old_vals, state_store=state_store)
    tx = b"rotated=v"
    entry = _entry("unit-chain", new_pvs, tx, height=7)
    applied = mgr._verify_apply(_StubPeer(), [entry], {7: new_vals})
    assert applied == 1
    assert mgr.txflow.applied == [entry[0]]
    # learned + persisted: height 7 now resolves locally
    pinned = state_store.load_validators(7)
    assert pinned is not None
    assert [(v.address, v.voting_power) for v in pinned] == [
        (v.address, v.voting_power) for v in new_vals
    ]
    vals7, on_record = mgr._vals_for(7)
    assert on_record


def test_fresh_node_rejects_unendorsed_snapshot():
    """A snapshot whose signers share no stake with any set we trust is
    refused — but NOT as a Byzantine strike (our record may merely be
    stale), so the round fails toward rotation/fallback and nothing is
    applied."""
    _old_pvs, old_vals = _val_set(b"anchor", 4)
    evil_pvs, evil_vals = _val_set(b"usurper", 4)
    mgr = _unit_manager(old_vals)
    entry = _entry("unit-chain", evil_pvs, b"usurped=v", height=7)
    with pytest.raises(SyncError) as ei:
        mgr._verify_apply(_StubPeer(), [entry], {7: evil_vals})
    assert not ei.value.byzantine
    assert "endorse" in str(ei.value)
    assert mgr.txflow.applied == []


def test_snapshot_mismatch_against_own_record_is_byzantine():
    """When the client HAS a record for the height, a contradicting
    server snapshot stays what it always was: proof of a bad server."""
    old_pvs, old_vals = _val_set(b"record", 4)
    _evil_pvs, evil_vals = _val_set(b"claimant", 4)
    mgr = _unit_manager(old_vals)
    mgr._trusted_vals[7] = old_vals  # our own record for the height
    entry = _entry("unit-chain", old_pvs, b"recorded=v", height=7)
    with pytest.raises(SyncError) as ei:
        mgr._verify_apply(_StubPeer(), [entry], {7: evil_vals})
    assert ei.value.byzantine
    assert mgr.txflow.applied == []


# -- sync-only chaos scoping (satellite: FaultSpec.sync_only) --


def test_fault_spec_sync_only_scoping():
    from txflow_tpu.p2p.base import CHANNEL_SYNC, CHANNEL_MEMPOOL

    spec = FaultSpec(seed=5, drop=0.5, delay=0.2)
    sync_spec = spec.sync_only()
    assert sync_spec.channels == SYNC_CHANNELS
    assert CHANNEL_SYNC in sync_spec.channels
    assert CHANNEL_MEMPOOL not in sync_spec.channels
    # the default scope must NOT silently grow to include sync: that
    # would shift every existing seeded chaos stream (one PRNG draw per
    # in-scope message)
    assert CHANNEL_SYNC not in GOSSIP_CHANNELS
    assert spec.channels == GOSSIP_CHANNELS
    # knobs carry over
    assert sync_spec.drop == spec.drop and sync_spec.seed == spec.seed
