"""Crash-consistency tests: armed failpoints kill a node mid-commit; a
restart over the same durable artifacts (FileDB stores + pool WALs +
consensus WAL) must reconstruct identical state with no double delivery.

Mirrors the reference's crashingWAL restart loops and handshake replay
matrix (consensus/replay_test.go:113-180, 488-527) and the fail.Fail()
crash hooks compiled into the commit paths (txflowstate/execution.go:87,
95; state/execution.go:138-180; consensus/state.go:1277-1334). The
restart model: durable stores survive, the ABCI app restarts EMPTY and is
rebuilt by the Handshaker (block replay incl. Vtxs + fast-path commit
redelivery in commit order) — so "no double delivery" is an exactly-once
assertion over the rebuilt app's deliver stream.
"""

import conftest  # noqa: F401

import collections
import hashlib
import time

import pytest

from txflow_tpu.abci.kvstore import KVStoreApplication
from txflow_tpu.node.node import Node, NodeConfig
from txflow_tpu.store.db import FileDB
from txflow_tpu.types import TxVote
from txflow_tpu.types.priv_validator import MockPV
from txflow_tpu.types.validator import Validator, ValidatorSet
from txflow_tpu.utils import failpoints
from txflow_tpu.utils.config import test_config as make_test_config

CHAIN_ID = "test-crash"


class CountingKVStore(KVStoreApplication):
    """kvstore that records every delivered tx (exactly-once oracle)."""

    def __init__(self):
        super().__init__()
        self.delivered = collections.Counter()

    def deliver_tx(self, tx):
        self.delivered[bytes(tx)] += 1
        return super().deliver_tx(tx)


def wait_until(pred, timeout=20.0, poll=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return False


def build_node(tmp_path, enable_consensus=False, app=None):
    """Single-validator node over durable artifacts under tmp_path."""
    pv = MockPV(hashlib.sha256(b"crash-val").digest())
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10)])
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    cfg.mempool.wal_dir = str(tmp_path)
    node = Node(
        node_id="crash-node",
        chain_id=CHAIN_ID,
        val_set=vs,
        app=app or CountingKVStore(),
        priv_val=pv,
        node_config=NodeConfig(
            config=cfg,
            use_device_verifier=False,
            enable_consensus=enable_consensus,
            consensus_wal_path=str(tmp_path / "consensus.wal"),
        ),
        tx_store_db=FileDB(str(tmp_path / "txstore.db")),
        state_db=FileDB(str(tmp_path / "state.db")),
        block_db=FileDB(str(tmp_path / "blocks.db")),
    )
    return node, pv


def sign_tx_vote(pv, tx):
    key = hashlib.sha256(tx).digest()
    v = TxVote(
        height=0,
        tx_hash=key.hex().upper(),
        tx_key=key,
        validator_address=pv.get_address(),
    )
    pv.sign_tx_vote(CHAIN_ID, v)
    return v


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.disarm()
    yield
    failpoints.disarm()


# -------------------------------------------------- fast-path crash points


@pytest.mark.parametrize("point", ["txflow-before-commit", "txflow-after-commit"])
def test_engine_crash_then_restart_replays_exactly_once(tmp_path, point):
    """Kill the fast path around the app Commit; the restarted node's app
    is rebuilt with each committed tx delivered exactly once, in the
    commit order persisted by the TxStore."""
    node, pv = build_node(tmp_path)
    node.start()
    committed = [b"pre-%d=v" % i for i in range(3)]
    for tx in committed:
        node.broadcast_tx(tx)
        node.tx_vote_pool.check_tx(sign_tx_vote(pv, tx))
    assert wait_until(lambda: all(node.is_committed(t) for t in committed))

    failpoints.arm(point)
    victim = b"victim=v"
    node.broadcast_tx(victim)
    node.tx_vote_pool.check_tx(sign_tx_vote(pv, victim))
    assert wait_until(lambda: failpoints.fired(point)), "failpoint must fire"
    node.stop()  # crash: partial commit state on disk
    failpoints.disarm()

    # restart over the same artifacts; handshake rebuilds the app
    app2 = CountingKVStore()
    node2, pv = build_node(tmp_path, app=app2)
    node2.start()
    try:
        # pre-crash commits: exactly once each
        for tx in committed:
            assert node2.is_committed(tx)
            assert app2.delivered[tx] == 1, f"{tx} delivered {app2.delivered[tx]}x"
        # the victim: at most once (before-commit: save_tx may or may not
        # have landed; after-commit: must be there exactly once)
        assert app2.delivered[victim] <= 1
        if point == "txflow-after-commit":
            assert node2.is_committed(victim)
            assert app2.delivered[victim] == 1
        # commit order replay preserved the persisted order prefix
        order = node2.tx_store.committed_hashes_in_order()
        want = [hashlib.sha256(t).hexdigest().upper() for t in committed]
        assert order[: len(want)] == want
        # the node still works: a fresh tx commits
        fresh = b"fresh=v"
        node2.broadcast_tx(fresh)
        node2.tx_vote_pool.check_tx(sign_tx_vote(pv, fresh))
        assert wait_until(lambda: node2.is_committed(fresh))
        # store-then-apply: the TxStore row (is_committed) lands before the
        # app delivery, so give the committer its window instead of racing it
        assert wait_until(lambda: app2.delivered[fresh] == 1)
    finally:
        node2.stop()


# ------------------------------------------------- block-path crash points


@pytest.mark.parametrize(
    "point",
    [
        "consensus-after-save-block",
        "consensus-after-end-height",
        "block-after-exec",
        "block-after-commit",
        "block-after-save",
    ],
)
def test_consensus_crash_then_restart_resumes_chain(tmp_path, point):
    """Kill consensus at every commit-path failpoint; the restarted node's
    handshake reconciles app/store/state heights and block production
    resumes with no tx delivered twice (single-validator chain: quorum of
    one, so the node commits blocks alone)."""
    node, pv = build_node(tmp_path, enable_consensus=True)
    node.start()
    txs = [b"blk-%d=v" % i for i in range(3)]
    for tx in txs:
        node.broadcast_tx(tx)
        node.tx_vote_pool.check_tx(sign_tx_vote(pv, tx))
    assert wait_until(lambda: all(node.is_committed(t) for t in txs))
    assert node.consensus.wait_for_height(2, timeout=30)

    failpoints.arm(point)
    assert wait_until(lambda: failpoints.fired(point), timeout=30), (
        f"{point} must fire during block production"
    )
    crash_store_h = node.block_store.height()
    node.stop()
    failpoints.disarm()

    app2 = CountingKVStore()
    node2, pv = build_node(tmp_path, enable_consensus=True, app=app2)
    node2.start()
    try:
        st = node2.consensus.state
        # handshake reconciled the three height domains
        assert st.last_block_height == node2.block_store.height()
        assert node2.block_store.height() >= crash_store_h - 1
        # every fast-committed tx delivered exactly once into the new app
        for tx in txs:
            assert app2.delivered[tx] == 1, f"{tx} delivered {app2.delivered[tx]}x"
        # chain liveness: new blocks after restart
        h = st.last_block_height
        assert node2.consensus.wait_for_height(h + 2, timeout=30), (
            "block production must resume after crash recovery"
        )
        # and the fast path still commits new txs exactly once
        fresh = b"post-crash=v"
        node2.broadcast_tx(fresh)
        node2.tx_vote_pool.check_tx(sign_tx_vote(pv, fresh))
        assert wait_until(lambda: node2.is_committed(fresh))
        # the certificate is a decision-time fact; the ABCI apply runs a
        # beat later on the committer thread (engine commits_drained
        # docstring) — wait for the apply, then pin exactly-once
        assert wait_until(lambda: app2.delivered[fresh] >= 1)
        assert app2.delivered[fresh] == 1
    finally:
        node2.stop()


def test_handshaker_state_catchup_is_deterministic(tmp_path):
    """Crash between block save and state save ('consensus-after-save-
    block'), restart TWICE: both restarts must converge to the identical
    state bytes (the chain app hash is a pure function of block history)."""
    node, pv = build_node(tmp_path, enable_consensus=True)
    node.start()
    node.broadcast_tx(b"det=v")
    node.tx_vote_pool.check_tx(sign_tx_vote(pv, b"det=v"))
    assert wait_until(lambda: node.is_committed(b"det=v"))
    assert node.consensus.wait_for_height(2, timeout=30)
    failpoints.arm("consensus-after-save-block")
    assert wait_until(lambda: failpoints.fired("consensus-after-save-block"), timeout=30)
    node.stop()
    failpoints.disarm()

    node2, _ = build_node(tmp_path, enable_consensus=True)
    node2.start()
    state_a = node2.consensus.state.bytes()
    h_a = node2.consensus.state.last_block_height
    node2.stop()

    node3, _ = build_node(tmp_path, enable_consensus=True)
    node3.start()
    try:
        # heights can only have advanced between restarts if node2 ran
        # briefly; compare at the common height via the state store's
        # persisted snapshot determinism: same artifacts -> same state
        if node3.consensus.state.last_block_height == h_a:
            assert node3.consensus.state.bytes() == state_a
        else:
            assert node3.consensus.state.last_block_height >= h_a
    finally:
        node3.stop()


# ------------------------------------------- multi-node restart + rejoin


def test_node_restart_rejoins_and_converges(tmp_path):
    """A validator goes down mid-net, the other 3 keep committing (3/4
    quorum), and a REBUILT node over the same durable artifacts rejoins:
    handshake replays its own history into a fresh app, parallel catchup
    pulls the blocks it missed, and every tx from before/during/after the
    outage is applied exactly once everywhere."""
    from txflow_tpu.p2p import connect_switches

    pvs = [MockPV(hashlib.sha256(b"rj-%d" % i).digest()) for i in range(4)]
    vs = ValidatorSet(
        [Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs]
    )
    by_addr = {pv.get_address(): pv for pv in pvs}
    pvs = [by_addr[v.address] for v in vs]
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True

    def build(i, app):
        durable = i == 2
        return Node(
            node_id=f"rj-node{i}",
            chain_id=CHAIN_ID,
            val_set=vs,
            app=app,
            priv_val=pvs[i],
            node_config=NodeConfig(
                config=cfg,
                use_device_verifier=False,
                enable_consensus=True,
                consensus_wal_path=(
                    str(tmp_path / "n2-consensus.wal") if durable else ""
                ),
            ),
            tx_store_db=FileDB(str(tmp_path / "n2-txstore.db")) if durable else None,
            state_db=FileDB(str(tmp_path / "n2-state.db")) if durable else None,
            block_db=FileDB(str(tmp_path / "n2-blocks.db")) if durable else None,
        )

    apps = [CountingKVStore() for _ in range(4)]
    nodes = [build(i, apps[i]) for i in range(4)]
    for n in nodes:
        n.start()
    for i in range(4):
        for j in range(i + 1, 4):
            connect_switches(nodes[i].switch, nodes[j].switch)
    try:
        batch_a = [b"rj-a%d=v" % i for i in range(6)]
        for tx in batch_a:
            nodes[0].broadcast_tx(tx)
        assert wait_until(
            lambda: all(n.is_committed(t) for n in nodes for t in batch_a),
            timeout=30,
        ), "batch A must commit on all 4"

        # node 2 goes down; 3/4 keeps the net live
        nodes[2].stop()
        batch_b = [b"rj-b%d=v" % i for i in range(6)]
        for tx in batch_b:
            nodes[0].broadcast_tx(tx)
        live = [nodes[0], nodes[1], nodes[3]]
        assert wait_until(
            lambda: all(n.is_committed(t) for n in live for t in batch_b),
            timeout=30,
        ), "3/4 must keep committing"
        # let blocks carrying batch B land
        h_live = max(n.consensus.state.last_block_height for n in live)

        # rebuild node 2 over its artifacts with a FRESH app; reconnect
        app2 = CountingKVStore()
        nodes[2] = build(2, app2)
        nodes[2].start()
        for j in (0, 1, 3):
            connect_switches(nodes[2].switch, nodes[j].switch)

        batch_c = [b"rj-c%d=v" % i for i in range(6)]
        for tx in batch_c:
            nodes[2].broadcast_tx(tx)
        assert wait_until(
            lambda: all(
                n.is_committed(t)
                for n in nodes
                for t in batch_a + batch_b + batch_c
            ),
            timeout=60,
        ), "rejoined net must commit everything everywhere"
        # the rejoined node caught up past the outage blocks
        assert wait_until(
            lambda: nodes[2].consensus.state.last_block_height >= h_live,
            timeout=60,
        ), "restarted node never caught up"
        # exactly-once on the rebuilt app: every batch tx delivered once
        assert wait_until(
            lambda: all(
                app2.delivered[t] == 1
                for t in batch_a + batch_b + batch_c
            ),
            timeout=30,
        ), {
            t: app2.delivered[t]
            for t in batch_a + batch_b + batch_c
            if app2.delivered[t] != 1
        }
        # content convergence with a node that never restarted
        def kv_equal():
            s0 = {
                k: v
                for k, v in apps[0].state.items()
                if k.startswith(b"rj-")
            }
            s2 = {
                k: v
                for k, v in app2.state.items()
                if k.startswith(b"rj-")
            }
            return s0 == s2

        assert wait_until(kv_equal, timeout=30), "kv state diverged after rejoin"
    finally:
        for n in nodes:
            try:
                n.stop()
            except Exception:
                pass


def test_wal_backlog_larger_than_queue_does_not_deadlock_start(tmp_path):
    """One height's WAL can hold more messages than the consensus queue's
    capacity; start() must replay them synchronously (the reference's
    catchupReplay shape) instead of enqueueing into a queue nobody drains
    yet — a 300 s churn soak wedged node revival exactly there (r5)."""
    import queue as _q
    import threading

    node, pv = build_node(tmp_path, enable_consensus=True)
    node.start()
    assert wait_until(lambda: node.consensus.state.last_block_height >= 1, 20)
    node.stop()

    # stuff the restart WAL with a same-height vote backlog
    node2, pv2 = build_node(tmp_path, enable_consensus=True)
    cs = node2.consensus  # the ConsensusState
    h = cs.state.last_block_height
    wal = cs.wal
    from txflow_tpu.types.block_vote import PREVOTE, BlockVote

    for i in range(32):
        v = BlockVote(
            height=h + 1,
            round=0,
            type=PREVOTE,
            block_id=b"\x11" * 32,
            timestamp_ns=1700000000_000000000 + i,
            validator_address=pv2.get_address(),
        )
        pv2.sign_block_vote(CHAIN_ID, v)
        wal.write_vote(v)
    backlog = wal.messages_after_end_height(h)
    assert len(backlog) > 4, "need a real backlog for the regression"
    cs._queue = _q.Queue(maxsize=4)  # far smaller than the backlog

    done = threading.Event()
    t = threading.Thread(target=lambda: (node2.start(), done.set()), daemon=True)
    t.start()
    assert done.wait(30), (
        "start() deadlocked replaying a WAL backlog larger than the queue"
    )
    node2.stop()
