"""txlint gate + per-pass fixture tests.

test_tree_is_clean is the tier-1 wiring of ``tools/lint.py --check``: the
committed tree must carry zero unsuppressed violations (and zero parse
errors). The fixture tests prove each pass actually FIRES on a minimal
reproduction, so a refactor that silently lobotomizes a pass fails here
rather than letting the tree gate rot into a no-op.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

from txflow_tpu.analysis.core import lint_source, lint_tree
from txflow_tpu.analysis.twins import TwinPathPass, update_pins

REPO_ROOT = Path(__file__).resolve().parent.parent


def _src(text: str) -> str:
    return textwrap.dedent(text)


def _rules(violations) -> list[str]:
    return [v.rule for v in violations]


# ---------------------------------------------------------------------------
# the tree gate (tier-1)
# ---------------------------------------------------------------------------


def test_tree_is_clean():
    report = lint_tree(REPO_ROOT)
    assert report["errors"] == []
    msgs = "\n".join(v.format() for v in report["violations"])
    assert not report["violations"], f"unsuppressed txlint violations:\n{msgs}"
    # every suppression in the tree documents itself
    for v in report["suppressed"]:
        assert v.justification, v.format()


def test_cli_check_and_json():
    out = subprocess.run(
        [sys.executable, str(REPO_ROOT / "tools" / "lint.py"), "--check"],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    out = subprocess.run(
        [sys.executable, str(REPO_ROOT / "tools" / "lint.py"), "--json"],
        capture_output=True, text=True,
    )
    assert out.returncode == 0
    report = json.loads(out.stdout)
    assert report["violations"] == []
    assert report["files_scanned"] > 50
    assert isinstance(report["suppressed_counts"], dict)


# ---------------------------------------------------------------------------
# lock-blocking
# ---------------------------------------------------------------------------


def test_lock_blocking_direct():
    active, _ = lint_source(_src("""
        class C:
            def send(self, frame):
                with self._mtx:
                    self.sock.sendall(frame)
    """), "txflow_tpu/x.py")
    assert _rules(active) == ["lock-blocking"]
    assert "sendall" in active[0].message
    assert "_mtx" in active[0].message


def test_lock_blocking_taint_through_self_call():
    active, _ = lint_source(_src("""
        class C:
            def _flush(self):
                self.wal.write(b"x")

            def ingest(self, tx):
                with self._mtx:
                    self._flush()
    """), "txflow_tpu/x.py")
    assert _rules(active) == ["lock-blocking"]
    assert "reaches blocking" in active[0].message


def test_lock_blocking_outside_lock_is_fine():
    active, _ = lint_source(_src("""
        class C:
            def send(self, frame):
                self.sock.sendall(frame)
                with self._mtx:
                    self.n += 1
    """), "txflow_tpu/x.py")
    assert active == []


def test_lock_blocking_cond_wait_on_held_lock_allowed():
    active, _ = lint_source(_src("""
        class C:
            def pop(self):
                with self._cond:
                    self._cond.wait()
                with self._mtx:
                    self._other.wait()
    """), "txflow_tpu/x.py")
    # waiting on the condition you hold releases it (sanctioned); waiting
    # on anything else while holding a lock is the classic stall
    assert _rules(active) == ["lock-blocking"]
    assert "_other" in active[0].message


def test_suppression_honored_and_recorded():
    active, suppressed = lint_source(_src("""
        class C:
            def send(self, frame):
                with self._wlock:
                    self.sock.sendall(frame)  # txlint: allow(lock-blocking) -- wlock exists to serialize frame writes
    """), "txflow_tpu/x.py")
    assert active == []
    assert _rules(suppressed) == ["lock-blocking"]
    assert suppressed[0].justification.startswith("wlock exists")


def test_suppressed_seed_does_not_taint_callers():
    active, suppressed = lint_source(_src("""
        class C:
            def _flush(self):
                self.wal.write(b"x")  # txlint: allow(lock-blocking) -- append order must match insert order

            def ingest(self, tx):
                with self._mtx:
                    self._flush()
    """), "txflow_tpu/x.py")
    # sanctioning the seed sanctions the chain that reaches it
    assert active == []


def test_bad_suppression_missing_justification():
    active, _ = lint_source(_src("""
        class C:
            def send(self, frame):
                with self._mtx:
                    self.sock.sendall(frame)  # txlint: allow(lock-blocking)
    """), "txflow_tpu/x.py")
    # the allow() without a justification is itself flagged AND does not
    # suppress the underlying violation
    assert sorted(_rules(active)) == ["bad-suppression", "lock-blocking"]


def test_bad_suppression_unknown_rule():
    active, _ = lint_source(_src("""
        x = 1  # txlint: allow(made-up-rule) -- because
    """), "txflow_tpu/x.py")
    assert _rules(active) == ["bad-suppression"]
    assert "made-up-rule" in active[0].message


# ---------------------------------------------------------------------------
# nondeterminism
# ---------------------------------------------------------------------------

_CLOCK_SRC = _src("""
    import time

    def stamp():
        return time.time_ns()
""")


def test_nondeterminism_wall_clock_in_consensus_scope():
    active, _ = lint_source(_CLOCK_SRC, "txflow_tpu/consensus/state.py")
    assert _rules(active) == ["nondeterminism"]
    assert "utils.clock" in active[0].message


def test_nondeterminism_out_of_scope_is_fine():
    active, _ = lint_source(_CLOCK_SRC, "txflow_tpu/p2p/switch.py")
    assert active == []


def test_nondeterminism_clock_seam_allowed():
    active, _ = lint_source(_src("""
        from ..utils.clock import now_ns

        def stamp():
            return now_ns()
    """), "txflow_tpu/consensus/state.py")
    assert active == []


def test_nondeterminism_unseeded_rng_and_set_iteration():
    active, _ = lint_source(_src("""
        import random

        def pick(peers):
            r = random.Random(42)          # seeded: fine
            random.shuffle(peers)          # process-global rng: flagged
            for p in set(peers):           # set order: flagged
                pass
    """), "txflow_tpu/consensus/reactor.py")
    assert sorted(_rules(active)) == ["nondeterminism", "nondeterminism"]


# ---------------------------------------------------------------------------
# thread-join
# ---------------------------------------------------------------------------


def test_thread_join_leaked_thread():
    active, _ = lint_source(_src("""
        import threading

        class Worker:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()
    """), "txflow_tpu/x.py")
    assert _rules(active) == ["thread-join"]


def test_thread_join_daemon_or_joined_ok():
    active, _ = lint_source(_src("""
        import threading

        class A:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

        class B:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def stop(self):
                self._t.join()
    """), "txflow_tpu/x.py")
    assert active == []


# ---------------------------------------------------------------------------
# hotpath-sync
# ---------------------------------------------------------------------------

_HOT_SRC = _src("""
    class TxFlow:
        def _collect(self, prep, ticket):
            n = ticket.count.item()
            return n

        def stats(self):
            return self.total.item()
""")


def test_hotpath_sync_in_engine_hot_func():
    active, _ = lint_source(_HOT_SRC, "txflow_tpu/engine/txflow.py")
    # layered: .item() in _collect (hot func) is hotpath-sync; the same
    # call in stats() (cold func, hot MODULE) is host-sync — each site
    # reported exactly once
    assert sorted(_rules(active)) == ["host-sync", "hotpath-sync"]
    hot = next(v for v in active if v.rule == "hotpath-sync")
    cold = next(v for v in active if v.rule == "host-sync")
    assert "_collect" in hot.message
    assert hot.line != cold.line


def test_hotpath_sync_other_modules_exempt():
    # no enumerated hot funcs in verifier.py -> no hotpath-sync; the
    # module-wide host-sync pass still covers every function there
    active, _ = lint_source(_HOT_SRC, "txflow_tpu/verifier.py")
    assert _rules(active) == ["host-sync", "host-sync"]
    active, _ = lint_source(_HOT_SRC, "txflow_tpu/node/node.py")
    assert active == []  # cold module: neither pass applies


# ---------------------------------------------------------------------------
# unlocked-lru
# ---------------------------------------------------------------------------


def test_unlocked_lru_direct_construction_flagged():
    active, _ = lint_source(_src("""
        from ..utils.cache import UnlockedLRUCache

        class Pool:
            def __init__(self):
                self.cache = UnlockedLRUCache(100)
    """), "txflow_tpu/pool/x.py")
    assert _rules(active) == ["unlocked-lru"]
    assert "make_lru" in active[0].message


def test_unlocked_lru_factory_module_exempt():
    active, _ = lint_source(
        "c = UnlockedLRUCache(10)\n", "txflow_tpu/utils/cache.py"
    )
    assert active == []


# ---------------------------------------------------------------------------
# trace-clock
# ---------------------------------------------------------------------------

_RAW_CLOCK_SRC = _src("""
    import time

    def stamp():
        return time.monotonic()
""")


def test_trace_clock_raw_clock_in_traced_module():
    active, _ = lint_source(_RAW_CLOCK_SRC, "txflow_tpu/pool/mempool.py")
    assert _rules(active) == ["trace-clock"]
    assert "utils.clock" in active[0].message


def test_trace_clock_reference_not_just_call():
    # passing the function as a callback smuggles the raw clock too
    active, _ = lint_source(_src("""
        import time

        class C:
            def __init__(self):
                self._clock = time.perf_counter
    """), "txflow_tpu/engine/txflow.py")
    assert _rules(active) == ["trace-clock"]


def test_trace_clock_from_import_flagged():
    active, _ = lint_source(
        "from time import monotonic\n", "txflow_tpu/reactors/x.py"
    )
    assert _rules(active) == ["trace-clock"]


def test_trace_clock_seam_and_sleep_allowed():
    active, _ = lint_source(_src("""
        import time

        from ..utils.clock import monotonic

        def pace():
            t0 = monotonic()
            time.sleep(0.01)
            return monotonic() - t0
    """), "txflow_tpu/trace/tracer.py")
    assert active == []


def test_trace_clock_out_of_scope_exempt():
    # engine/ is scoped to the ONE traced file; execution.py keeps its
    # untraced perf_counter accounting, and p2p is outside the scope
    for path in ("txflow_tpu/engine/execution.py", "txflow_tpu/p2p/switch.py"):
        active, _ = lint_source(_RAW_CLOCK_SRC, path)
        assert active == [], path


def test_trace_clock_suppression_honored():
    active, suppressed = lint_source(_src("""
        import time

        def stamp():
            return time.time()  # txlint: allow(trace-clock) -- wall stamp for log line only
    """), "txflow_tpu/admission/controller.py")
    assert active == []
    assert _rules(suppressed) == ["trace-clock"]


# ---------------------------------------------------------------------------
# twin-path
# ---------------------------------------------------------------------------


def _twin_repo(tmp_path: Path) -> tuple[Path, Path]:
    root = tmp_path / "repo"
    (root / "pkg").mkdir(parents=True)
    (root / "tests").mkdir()
    (root / "pkg" / "pool.py").write_text(_src("""
        class Pool:
            def check_tx(self, tx):
                return tx * 1

            def check_tx_many(self, txs):
                return [t * 1 for t in txs]
    """))
    (root / "tests" / "test_parity.py").write_text("def test_parity(): pass\n")
    pin_file = tmp_path / "twins.json"
    pin_file.write_text(json.dumps({
        "twins": {
            "pool-ingest": {
                "functions": {
                    "pkg/pool.py::Pool.check_tx": None,
                    "pkg/pool.py::Pool.check_tx_many": None,
                },
                "parity_tests": {"tests/test_parity.py": None},
            }
        }
    }))
    update_pins(root, pin_file)
    return root, pin_file


def test_twin_path_clean_after_pinning(tmp_path):
    root, pin_file = _twin_repo(tmp_path)
    assert TwinPathPass(pin_file).finalize(root) == []


def test_twin_path_twin_changed_without_parity_test(tmp_path):
    # change one twin, leave the parity test alone -> hard failure
    root, pin_file = _twin_repo(tmp_path)
    src = root / "pkg" / "pool.py"
    src.write_text(src.read_text().replace("tx * 1", "tx * 2", 1))
    out = TwinPathPass(pin_file).finalize(root)
    assert _rules(out) == ["twin-path"]
    assert "byte-identical" in out[0].message


def test_twin_path_paired_change_wants_repin_then_passes(tmp_path):
    root, pin_file = _twin_repo(tmp_path)
    (root / "pkg" / "pool.py").write_text(
        (root / "pkg" / "pool.py").read_text().replace("* 1", "* 2")
    )
    test_f = root / "tests" / "test_parity.py"
    test_f.write_text(test_f.read_text() + "def test_more(): pass\n")
    out = TwinPathPass(pin_file).finalize(root)
    assert _rules(out) == ["twin-path"]
    assert "--update-pins" in out[0].message
    update_pins(root, pin_file)
    assert TwinPathPass(pin_file).finalize(root) == []


def test_twin_path_missing_target(tmp_path):
    root, pin_file = _twin_repo(tmp_path)
    (root / "pkg" / "pool.py").write_text("class Pool:\n    pass\n")
    out = TwinPathPass(pin_file).finalize(root)
    assert _rules(out) == ["twin-path"]
    assert "not found" in out[0].message


def test_committed_pins_are_recorded():
    """The committed twins.json must carry real fingerprints (null pins
    would make the pass vacuous) and point at files that exist."""
    pins = json.loads(
        (REPO_ROOT / "txflow_tpu" / "analysis" / "twins.json").read_text()
    )
    assert pins["twins"], "no twin groups registered"
    for twin in pins["twins"].values():
        for spec, fp in twin["functions"].items():
            assert fp, f"unrecorded pin for {spec} — run tools/lint.py --update-pins"
            assert (REPO_ROOT / spec.partition("::")[0]).exists()
        for rel, fp in twin["parity_tests"].items():
            assert fp and (REPO_ROOT / rel).exists()


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

_DEVICE_SYNC_SRC = _src("""
    import numpy as np
    import jax.numpy as jnp

    def helper(x):
        y = jnp.sum(x)
        v = float(y)
        h = np.asarray(jnp.dot(x, x))
        x.block_until_ready()
        return v, h, x.item()
""")


def test_host_sync_device_values_in_hot_module():
    active, _ = lint_source(_DEVICE_SYNC_SRC, "txflow_tpu/engine/newmod.py")
    assert _rules(active) == ["host-sync"] * 4


def test_host_sync_taint_through_local_assignment():
    # device provenance survives a chain of local names
    active, _ = lint_source(_src("""
        import numpy as np
        import jax.numpy as jnp

        def f(x):
            a = jnp.sum(x)
            b = a
            return int(b)
    """), "txflow_tpu/parallel/newmod.py")
    assert _rules(active) == ["host-sync"]
    assert "int()" in active[0].message


def test_host_sync_host_data_is_clean():
    # np.asarray/float on HOST data is the normal prep path, not a sync
    active, _ = lint_source(_src("""
        import numpy as np

        def pack(val_idx, limbs):
            vi = np.asarray(val_idx, dtype=np.int64)
            return float(len(limbs)) + vi.sum()
    """), "txflow_tpu/ops/newmod.py")
    assert active == []


def test_host_sync_seams_and_cold_modules_exempt():
    # the staging ring's readback thread IS the sanctioned transfer
    active, _ = lint_source(_src("""
        import numpy as np
        import jax.numpy as jnp

        class StageSlot:
            def _run(self):
                self._host = np.asarray(jnp.asarray(self._dev))
    """), "txflow_tpu/parallel/staging.py")
    assert active == []
    active, _ = lint_source(_DEVICE_SYNC_SRC, "txflow_tpu/rpc/server.py")
    assert active == []


def test_host_sync_suppression_honored():
    active, suppressed = lint_source(_src("""
        import jax.numpy as jnp

        def warm(x):
            jnp.sum(x).block_until_ready()  # txlint: allow(host-sync) -- warmup path runs before serving
    """), "txflow_tpu/engine/newmod.py")
    assert active == []
    assert _rules(suppressed) == ["host-sync"]


# ---------------------------------------------------------------------------
# recompile-hazard
# ---------------------------------------------------------------------------


def test_recompile_hazard_raw_size_flagged():
    active, _ = lint_source(_src("""
        def dispatch(self, msgs):
            n = len(msgs)
            b = bucket_size(n, self.buckets)
            ok = _pad(msgs, b - n)
            bad = _pad(msgs, n)
            self.shapes_used.add(("fused", b, n))
    """), "txflow_tpu/verifier.py", [_passes().RecompileHazardPass()])
    assert _rules(active) == ["recompile-hazard"] * 2
    assert "_pad width" in active[0].message
    assert "shapes_used" in active[1].message


def test_recompile_hazard_ladder_provenance_propagates():
    # bucket ladder -> locals -> arithmetic -> subscripts: all blessed
    active, _ = lint_source(_src("""
        def dispatch(self, msgs, full):
            n = len(msgs)
            b = bucket_size(n, self.buckets, multiple=self._n_shards)
            b_slots = self.buckets[0]
            limit = self.max_batch if full else bucket_size(n, self.buckets)
            pad = b - n
            _pad(msgs, pad)
            _pad(msgs, min(limit, b))
            self.shapes_used.add(("verify", b, b_slots))
            for shape in self.enumerate_shapes(n):
                self.shapes_used.add(shape)
    """), "txflow_tpu/verifier.py", [_passes().RecompileHazardPass()])
    assert active == []


def test_recompile_hazard_out_of_scope_exempt():
    active, _ = lint_source(
        "def f(n):\n    _pad([], n)\n",
        "txflow_tpu/node/node.py", [_passes().RecompileHazardPass()],
    )
    assert active == []


# ---------------------------------------------------------------------------
# seed-domain
# ---------------------------------------------------------------------------


def test_seed_domain_inline_literal_flagged():
    active, _ = lint_source(_src("""
        import hashlib

        def seed(s):
            return hashlib.sha256(b"mystream|%d" % s).digest()
    """), "txflow_tpu/newmod.py", [_passes().SeedDomainPass()])
    assert _rules(active) == ["seed-domain"]
    assert "utils.domains" in active[0].message


def test_seed_domain_joiner_and_plain_hashes_clean():
    # the b"|" joiner, |-prefixed format suffixes, and ordinary payload
    # hashing are not domain tags
    active, _ = lint_source(_src("""
        import hashlib
        from ..utils.domains import NETEM_LINK

        def seed(tag, s):
            h = hashlib.sha256()
            h.update(tag)
            h.update(b"|")
            h.update(s)
            hashlib.sha256(NETEM_LINK + b"|%d" % 3)
            return hashlib.sha256(b"ev-blockvote" + s).digest()
    """), "txflow_tpu/newmod.py", [_passes().SeedDomainPass()])
    assert active == []


def test_seed_domain_registry_duplicate_flagged():
    active, _ = lint_source(_src("""
        A = _register("a", b"one")
        B = _register("b", b"one")
        C = _register("a", b"two")
    """), "txflow_tpu/utils/domains.py", [_passes().SeedDomainPass()])
    rules = _rules(active)
    assert rules == ["seed-domain"] * 2
    assert "duplicate domain tag" in active[0].message
    assert "duplicate domain name" in active[1].message


# ---------------------------------------------------------------------------
# shared-decl
# ---------------------------------------------------------------------------


def test_shared_decl_annotation_required_and_validated():
    active, _ = lint_source(_src("""
        class C:
            def __init__(self):
                self._a = shared_field("c.a")
                self._b = shared_field("c.b")  # txlint: shared(self._mtx)
                self._c = shared_field("c.c")  # txlint: shared(banana)
                self._d = shared_field("c.d")  # txlint: shared(handoff)
                self.e = 1  # txlint: shared(self._mtx)
    """), "txflow_tpu/newmod.py", [_passes().SharedDeclPass()])
    msgs = {v.line: v.message for v in active}
    assert sorted(msgs) == [4, 6, 8]
    assert "without a" in msgs[4]
    assert "banana" in msgs[6]
    assert "dangling" in msgs[8]


def test_shared_decl_tree_declarations_all_annotated():
    # the committed tree's own declarations satisfy the pass (subset of
    # test_tree_is_clean, kept separate so a regression names the rule)
    report = lint_tree(REPO_ROOT)
    assert [v for v in report["violations"] if v.rule == "shared-decl"] == []


# ---------------------------------------------------------------------------
# stale-suppression (+ --prune-suppressions)
# ---------------------------------------------------------------------------


def test_stale_suppression_flagged_only_with_full_pass_set():
    src = _src("""
        def f():
            return 1  # txlint: allow(lock-blocking) -- nothing blocks here anymore
    """)
    active, _ = lint_source(src, "txflow_tpu/newmod.py")
    assert _rules(active) == ["stale-suppression"]
    # a subset run can't tell live from stale: no false positives
    active, _ = lint_source(src, "txflow_tpu/newmod.py", [_passes().HotPathPass()])
    assert active == []


def test_live_suppression_not_stale():
    active, suppressed = lint_source(_src("""
        class C:
            def send(self, frame):
                with self._mtx:
                    self.sock.sendall(frame)  # txlint: allow(lock-blocking) -- serializes whole-frame writes
    """), "txflow_tpu/newmod.py")
    assert active == []
    assert _rules(suppressed) == ["lock-blocking"]


def test_docstring_example_never_suppresses_or_goes_stale():
    active, _ = lint_source(_src('''
        """Docs: use  # txlint: allow(lock-blocking) -- why  to suppress."""

        def f():
            return 1
    '''), "txflow_tpu/newmod.py")
    assert active == []


def test_prune_suppressions_rewrites_stale_lines(tmp_path):
    # drive the CLI prune path against a scratch repo shaped like ours
    import subprocess as sp
    root = tmp_path / "repo"
    (root / "txflow_tpu").mkdir(parents=True)
    (root / "tools").mkdir()
    mod = root / "txflow_tpu" / "m.py"
    mod.write_text(
        "def f():\n"
        "    return 1  # txlint: allow(lock-blocking) -- stale by construction\n"
    )
    lint_py = (REPO_ROOT / "tools" / "lint.py").read_text().replace(
        "REPO_ROOT = Path(__file__).resolve().parent.parent",
        f"REPO_ROOT = Path({str(root)!r})\n"
        f"import sys; sys.path.insert(0, {str(REPO_ROOT)!r})",
    )
    (root / "tools" / "lint.py").write_text(lint_py)
    out = sp.run(
        [sys.executable, str(root / "tools" / "lint.py"), "--prune-suppressions"],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "pruned 1 stale suppression(s)" in out.stdout
    assert mod.read_text() == "def f():\n    return 1\n"


# ---------------------------------------------------------------------------
# --json golden schema
# ---------------------------------------------------------------------------


def test_json_schema_matches_golden():
    """The --json output shape is a consumer contract (profile_host,
    bench lint stamp, CI): keys, violation fields, the rule inventory
    and documented exit codes are pinned by the golden file."""
    from txflow_tpu.analysis.core import RULES, report_to_json

    golden = json.loads(
        (REPO_ROOT / "tests" / "golden" / "lint_schema.json").read_text()
    )
    report = report_to_json(lint_tree(REPO_ROOT))
    assert sorted(report) == golden["top_level_keys"]
    assert sorted(RULES) == golden["rules"]
    for v in report["violations"] + report["suppressed"]:
        assert sorted(v) == golden["violation_keys"]
    assert isinstance(report["files_scanned"], int)
    assert all(isinstance(n, int) for n in report["counts"].values())
    assert golden["exit_codes"] == {
        "clean": 0, "check_violations": 1, "scan_errors": 2,
    }


def test_cli_race_report(tmp_path):
    import subprocess as sp
    dump = REPO_ROOT / ".race_audit.json"
    if not dump.exists():  # produced by any audited suite run
        dump.write_text(json.dumps({"fields": {}, "races": []}))
    out = sp.run(
        [sys.executable, str(REPO_ROOT / "tools" / "lint.py"), "--race-report"],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "race audit:" in out.stdout


def _passes():
    from txflow_tpu.analysis import passes as _p
    return _p
