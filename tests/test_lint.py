"""txlint gate + per-pass fixture tests.

test_tree_is_clean is the tier-1 wiring of ``tools/lint.py --check``: the
committed tree must carry zero unsuppressed violations (and zero parse
errors). The fixture tests prove each pass actually FIRES on a minimal
reproduction, so a refactor that silently lobotomizes a pass fails here
rather than letting the tree gate rot into a no-op.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

from txflow_tpu.analysis.core import lint_source, lint_tree
from txflow_tpu.analysis.twins import TwinPathPass, update_pins

REPO_ROOT = Path(__file__).resolve().parent.parent


def _src(text: str) -> str:
    return textwrap.dedent(text)


def _rules(violations) -> list[str]:
    return [v.rule for v in violations]


# ---------------------------------------------------------------------------
# the tree gate (tier-1)
# ---------------------------------------------------------------------------


def test_tree_is_clean():
    report = lint_tree(REPO_ROOT)
    assert report["errors"] == []
    msgs = "\n".join(v.format() for v in report["violations"])
    assert not report["violations"], f"unsuppressed txlint violations:\n{msgs}"
    # every suppression in the tree documents itself
    for v in report["suppressed"]:
        assert v.justification, v.format()


def test_cli_check_and_json():
    out = subprocess.run(
        [sys.executable, str(REPO_ROOT / "tools" / "lint.py"), "--check"],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    out = subprocess.run(
        [sys.executable, str(REPO_ROOT / "tools" / "lint.py"), "--json"],
        capture_output=True, text=True,
    )
    assert out.returncode == 0
    report = json.loads(out.stdout)
    assert report["violations"] == []
    assert report["files_scanned"] > 50
    assert isinstance(report["suppressed_counts"], dict)


# ---------------------------------------------------------------------------
# lock-blocking
# ---------------------------------------------------------------------------


def test_lock_blocking_direct():
    active, _ = lint_source(_src("""
        class C:
            def send(self, frame):
                with self._mtx:
                    self.sock.sendall(frame)
    """), "txflow_tpu/x.py")
    assert _rules(active) == ["lock-blocking"]
    assert "sendall" in active[0].message
    assert "_mtx" in active[0].message


def test_lock_blocking_taint_through_self_call():
    active, _ = lint_source(_src("""
        class C:
            def _flush(self):
                self.wal.write(b"x")

            def ingest(self, tx):
                with self._mtx:
                    self._flush()
    """), "txflow_tpu/x.py")
    assert _rules(active) == ["lock-blocking"]
    assert "reaches blocking" in active[0].message


def test_lock_blocking_outside_lock_is_fine():
    active, _ = lint_source(_src("""
        class C:
            def send(self, frame):
                self.sock.sendall(frame)
                with self._mtx:
                    self.n += 1
    """), "txflow_tpu/x.py")
    assert active == []


def test_lock_blocking_cond_wait_on_held_lock_allowed():
    active, _ = lint_source(_src("""
        class C:
            def pop(self):
                with self._cond:
                    self._cond.wait()
                with self._mtx:
                    self._other.wait()
    """), "txflow_tpu/x.py")
    # waiting on the condition you hold releases it (sanctioned); waiting
    # on anything else while holding a lock is the classic stall
    assert _rules(active) == ["lock-blocking"]
    assert "_other" in active[0].message


def test_suppression_honored_and_recorded():
    active, suppressed = lint_source(_src("""
        class C:
            def send(self, frame):
                with self._wlock:
                    self.sock.sendall(frame)  # txlint: allow(lock-blocking) -- wlock exists to serialize frame writes
    """), "txflow_tpu/x.py")
    assert active == []
    assert _rules(suppressed) == ["lock-blocking"]
    assert suppressed[0].justification.startswith("wlock exists")


def test_suppressed_seed_does_not_taint_callers():
    active, suppressed = lint_source(_src("""
        class C:
            def _flush(self):
                self.wal.write(b"x")  # txlint: allow(lock-blocking) -- append order must match insert order

            def ingest(self, tx):
                with self._mtx:
                    self._flush()
    """), "txflow_tpu/x.py")
    # sanctioning the seed sanctions the chain that reaches it
    assert active == []


def test_bad_suppression_missing_justification():
    active, _ = lint_source(_src("""
        class C:
            def send(self, frame):
                with self._mtx:
                    self.sock.sendall(frame)  # txlint: allow(lock-blocking)
    """), "txflow_tpu/x.py")
    # the allow() without a justification is itself flagged AND does not
    # suppress the underlying violation
    assert sorted(_rules(active)) == ["bad-suppression", "lock-blocking"]


def test_bad_suppression_unknown_rule():
    active, _ = lint_source(_src("""
        x = 1  # txlint: allow(made-up-rule) -- because
    """), "txflow_tpu/x.py")
    assert _rules(active) == ["bad-suppression"]
    assert "made-up-rule" in active[0].message


# ---------------------------------------------------------------------------
# nondeterminism
# ---------------------------------------------------------------------------

_CLOCK_SRC = _src("""
    import time

    def stamp():
        return time.time_ns()
""")


def test_nondeterminism_wall_clock_in_consensus_scope():
    active, _ = lint_source(_CLOCK_SRC, "txflow_tpu/consensus/state.py")
    assert _rules(active) == ["nondeterminism"]
    assert "utils.clock" in active[0].message


def test_nondeterminism_out_of_scope_is_fine():
    active, _ = lint_source(_CLOCK_SRC, "txflow_tpu/p2p/switch.py")
    assert active == []


def test_nondeterminism_clock_seam_allowed():
    active, _ = lint_source(_src("""
        from ..utils.clock import now_ns

        def stamp():
            return now_ns()
    """), "txflow_tpu/consensus/state.py")
    assert active == []


def test_nondeterminism_unseeded_rng_and_set_iteration():
    active, _ = lint_source(_src("""
        import random

        def pick(peers):
            r = random.Random(42)          # seeded: fine
            random.shuffle(peers)          # process-global rng: flagged
            for p in set(peers):           # set order: flagged
                pass
    """), "txflow_tpu/consensus/reactor.py")
    assert sorted(_rules(active)) == ["nondeterminism", "nondeterminism"]


# ---------------------------------------------------------------------------
# thread-join
# ---------------------------------------------------------------------------


def test_thread_join_leaked_thread():
    active, _ = lint_source(_src("""
        import threading

        class Worker:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()
    """), "txflow_tpu/x.py")
    assert _rules(active) == ["thread-join"]


def test_thread_join_daemon_or_joined_ok():
    active, _ = lint_source(_src("""
        import threading

        class A:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

        class B:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def stop(self):
                self._t.join()
    """), "txflow_tpu/x.py")
    assert active == []


# ---------------------------------------------------------------------------
# hotpath-sync
# ---------------------------------------------------------------------------

_HOT_SRC = _src("""
    class TxFlow:
        def _collect(self, prep, ticket):
            n = ticket.count.item()
            return n

        def stats(self):
            return self.total.item()
""")


def test_hotpath_sync_in_engine_hot_func():
    active, _ = lint_source(_HOT_SRC, "txflow_tpu/engine/txflow.py")
    # .item() in _collect (hot) fires; in stats() (cold) it does not
    assert _rules(active) == ["hotpath-sync"]
    assert "_collect" in active[0].message


def test_hotpath_sync_other_modules_exempt():
    active, _ = lint_source(_HOT_SRC, "txflow_tpu/verifier.py")
    assert active == []


# ---------------------------------------------------------------------------
# unlocked-lru
# ---------------------------------------------------------------------------


def test_unlocked_lru_direct_construction_flagged():
    active, _ = lint_source(_src("""
        from ..utils.cache import UnlockedLRUCache

        class Pool:
            def __init__(self):
                self.cache = UnlockedLRUCache(100)
    """), "txflow_tpu/pool/x.py")
    assert _rules(active) == ["unlocked-lru"]
    assert "make_lru" in active[0].message


def test_unlocked_lru_factory_module_exempt():
    active, _ = lint_source(
        "c = UnlockedLRUCache(10)\n", "txflow_tpu/utils/cache.py"
    )
    assert active == []


# ---------------------------------------------------------------------------
# trace-clock
# ---------------------------------------------------------------------------

_RAW_CLOCK_SRC = _src("""
    import time

    def stamp():
        return time.monotonic()
""")


def test_trace_clock_raw_clock_in_traced_module():
    active, _ = lint_source(_RAW_CLOCK_SRC, "txflow_tpu/pool/mempool.py")
    assert _rules(active) == ["trace-clock"]
    assert "utils.clock" in active[0].message


def test_trace_clock_reference_not_just_call():
    # passing the function as a callback smuggles the raw clock too
    active, _ = lint_source(_src("""
        import time

        class C:
            def __init__(self):
                self._clock = time.perf_counter
    """), "txflow_tpu/engine/txflow.py")
    assert _rules(active) == ["trace-clock"]


def test_trace_clock_from_import_flagged():
    active, _ = lint_source(
        "from time import monotonic\n", "txflow_tpu/reactors/x.py"
    )
    assert _rules(active) == ["trace-clock"]


def test_trace_clock_seam_and_sleep_allowed():
    active, _ = lint_source(_src("""
        import time

        from ..utils.clock import monotonic

        def pace():
            t0 = monotonic()
            time.sleep(0.01)
            return monotonic() - t0
    """), "txflow_tpu/trace/tracer.py")
    assert active == []


def test_trace_clock_out_of_scope_exempt():
    # engine/ is scoped to the ONE traced file; execution.py keeps its
    # untraced perf_counter accounting, and p2p is outside the scope
    for path in ("txflow_tpu/engine/execution.py", "txflow_tpu/p2p/switch.py"):
        active, _ = lint_source(_RAW_CLOCK_SRC, path)
        assert active == [], path


def test_trace_clock_suppression_honored():
    active, suppressed = lint_source(_src("""
        import time

        def stamp():
            return time.time()  # txlint: allow(trace-clock) -- wall stamp for log line only
    """), "txflow_tpu/admission/controller.py")
    assert active == []
    assert _rules(suppressed) == ["trace-clock"]


# ---------------------------------------------------------------------------
# twin-path
# ---------------------------------------------------------------------------


def _twin_repo(tmp_path: Path) -> tuple[Path, Path]:
    root = tmp_path / "repo"
    (root / "pkg").mkdir(parents=True)
    (root / "tests").mkdir()
    (root / "pkg" / "pool.py").write_text(_src("""
        class Pool:
            def check_tx(self, tx):
                return tx * 1

            def check_tx_many(self, txs):
                return [t * 1 for t in txs]
    """))
    (root / "tests" / "test_parity.py").write_text("def test_parity(): pass\n")
    pin_file = tmp_path / "twins.json"
    pin_file.write_text(json.dumps({
        "twins": {
            "pool-ingest": {
                "functions": {
                    "pkg/pool.py::Pool.check_tx": None,
                    "pkg/pool.py::Pool.check_tx_many": None,
                },
                "parity_tests": {"tests/test_parity.py": None},
            }
        }
    }))
    update_pins(root, pin_file)
    return root, pin_file


def test_twin_path_clean_after_pinning(tmp_path):
    root, pin_file = _twin_repo(tmp_path)
    assert TwinPathPass(pin_file).finalize(root) == []


def test_twin_path_twin_changed_without_parity_test(tmp_path):
    # change one twin, leave the parity test alone -> hard failure
    root, pin_file = _twin_repo(tmp_path)
    src = root / "pkg" / "pool.py"
    src.write_text(src.read_text().replace("tx * 1", "tx * 2", 1))
    out = TwinPathPass(pin_file).finalize(root)
    assert _rules(out) == ["twin-path"]
    assert "byte-identical" in out[0].message


def test_twin_path_paired_change_wants_repin_then_passes(tmp_path):
    root, pin_file = _twin_repo(tmp_path)
    (root / "pkg" / "pool.py").write_text(
        (root / "pkg" / "pool.py").read_text().replace("* 1", "* 2")
    )
    test_f = root / "tests" / "test_parity.py"
    test_f.write_text(test_f.read_text() + "def test_more(): pass\n")
    out = TwinPathPass(pin_file).finalize(root)
    assert _rules(out) == ["twin-path"]
    assert "--update-pins" in out[0].message
    update_pins(root, pin_file)
    assert TwinPathPass(pin_file).finalize(root) == []


def test_twin_path_missing_target(tmp_path):
    root, pin_file = _twin_repo(tmp_path)
    (root / "pkg" / "pool.py").write_text("class Pool:\n    pass\n")
    out = TwinPathPass(pin_file).finalize(root)
    assert _rules(out) == ["twin-path"]
    assert "not found" in out[0].message


def test_committed_pins_are_recorded():
    """The committed twins.json must carry real fingerprints (null pins
    would make the pass vacuous) and point at files that exist."""
    pins = json.loads(
        (REPO_ROOT / "txflow_tpu" / "analysis" / "twins.json").read_text()
    )
    assert pins["twins"], "no twin groups registered"
    for twin in pins["twins"].values():
        for spec, fp in twin["functions"].items():
            assert fp, f"unrecorded pin for {spec} — run tools/lint.py --update-pins"
            assert (REPO_ROOT / spec.partition("::")[0]).exists()
        for rel, fp in twin["parity_tests"].items():
            assert fp and (REPO_ROOT / rel).exists()
