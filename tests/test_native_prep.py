"""Native C prep parity vs the pure-Python path (the golden oracle).

The C module reimplements SHA-512 (FIPS 180-4) and the mod-L reduction
from the spec; these tests pin it bit-for-bit against hashlib and against
``_prepare_compact_py``, including the adversarial edges: S >= L
(ScMinimal reject), short/long signatures, off-range validator indices,
off-curve pubkeys, empty messages, and extreme digests.
"""

import hashlib

import numpy as np
import pytest

from txflow_tpu import native
from txflow_tpu.crypto import ed25519 as host_ed
from txflow_tpu.ops import ed25519_batch

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C compiler available"
)

L = host_ed.L


def test_sha512_matches_hashlib():
    rng = np.random.default_rng(7)
    # lengths straddling every padding branch: block size 128, the 112-byte
    # length-fits boundary, multi-block
    for n in [0, 1, 55, 56, 63, 64, 111, 112, 113, 127, 128, 129, 255, 256, 1000]:
        data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        assert native.sha512(data) == hashlib.sha512(data).digest(), n


def test_mod_l_reduction_edges():
    # drive reduce_mod_l through prep_batch with a fixed (R, A, msg) whose
    # digest we recompute host-side; cover random + structured extremes by
    # brute-forcing messages until digests hit high/low ranges is not
    # possible, so instead verify h == int(sha512(R|A|msg)) % L for many
    # random inputs — every fold path (4-fold worst case) is exercised by
    # uniform 512-bit digests with overwhelming probability over 200 trials.
    rng = np.random.default_rng(8)
    seeds = [rng.integers(0, 256, 32, dtype=np.uint8).tobytes() for _ in range(4)]
    pubs_list = [host_ed.public_key_from_seed(s) for s in seeds]
    epoch = ed25519_batch.EpochTables(pubs_list)
    n = 200
    msgs, sigs, vidx = [], [], []
    for i in range(n):
        m = rng.integers(0, 256, int(rng.integers(0, 120)), dtype=np.uint8).tobytes()
        vi = int(rng.integers(0, 4))
        msgs.append(m)
        sigs.append(host_ed.sign(seeds[vi], m))
        vidx.append(vi)
    batch = ed25519_batch._prepare_compact_native(msgs, sigs, np.array(vidx), epoch)
    assert batch.pre_ok.all()
    for i in range(n):
        digest = hashlib.sha512(sigs[i][:32] + pubs_list[vidx[i]] + msgs[i]).digest()
        want = int.from_bytes(digest, "little") % L
        # reconstruct h from MSB-first nibbles
        got = 0
        for nib in batch.h_nibbles[i]:
            got = (got << 4) | int(nib)
        assert got == want, i


def _mk_epoch(n_vals=4):
    seeds = [hashlib.sha256(b"npv%d" % i).digest() for i in range(n_vals)]
    pubs = [host_ed.public_key_from_seed(s) for s in seeds]
    return seeds, pubs, ed25519_batch.EpochTables(pubs)


def test_prepare_compact_native_matches_python():
    seeds, pubs, epoch = _mk_epoch()
    rng = np.random.default_rng(9)
    msgs, sigs, vidx = [], [], []
    # honest votes
    for i in range(40):
        m = b"msg-%d" % i
        vi = i % 4
        msgs.append(m)
        sigs.append(host_ed.sign(seeds[vi], m))
        vidx.append(vi)
    # S >= L: craft sig with S = L (and S = 2^256-1)
    for bad_s in [L, 2**256 - 1, L - 1]:  # L-1 passes ScMinimal (sig invalid later)
        msgs.append(b"bad-s")
        sigs.append(bytes(32) + bad_s.to_bytes(32, "little"))
        vidx.append(0)
    # wrong-length signatures
    for ln in [0, 63, 65]:
        msgs.append(b"bad-len")
        sigs.append(b"\x01" * ln)
        vidx.append(1)
    # off-range validator indices
    for bad_vi in [-1, 4, 1000]:
        m = b"bad-vi"
        msgs.append(m)
        sigs.append(host_ed.sign(seeds[0], m))
        vidx.append(bad_vi)
    # empty message
    msgs.append(b"")
    sigs.append(host_ed.sign(seeds[2], b""))
    vidx.append(2)

    vidx = np.array(vidx)
    a = ed25519_batch._prepare_compact_native(msgs, sigs, vidx, epoch)
    b = ed25519_batch._prepare_compact_py(msgs, sigs, vidx, epoch)
    np.testing.assert_array_equal(a.pre_ok, b.pre_ok)
    np.testing.assert_array_equal(a.s_nibbles, b.s_nibbles)
    np.testing.assert_array_equal(a.h_nibbles, b.h_nibbles)
    np.testing.assert_array_equal(a.val_idx, b.val_idx)
    np.testing.assert_array_equal(a.r_y, b.r_y)
    np.testing.assert_array_equal(a.r_sign, b.r_sign)
    # sanity: the honest votes all pass prechecks, the crafted ones fail
    assert a.pre_ok[:40].all()
    assert not a.pre_ok[40:42].any()  # S >= L
    assert a.pre_ok[42]  # S = L - 1 is minimal
    assert not a.pre_ok[43:49].any()  # bad lengths + bad indices


def test_off_curve_key_rejected_in_prechecks():
    seeds, pubs, _ = _mk_epoch()
    off_curve = bytes([2] + [0] * 31)  # y=2 has no square x (checked below)
    assert host_ed.point_decompress(off_curve) is None
    epoch = ed25519_batch.EpochTables([pubs[0], off_curve])
    m = b"oc"
    sigs = [host_ed.sign(seeds[0], m), host_ed.sign(seeds[0], m)]
    a = ed25519_batch._prepare_compact_native([m, m], sigs, np.array([0, 1]), epoch)
    b = ed25519_batch._prepare_compact_py([m, m], sigs, np.array([0, 1]), epoch)
    np.testing.assert_array_equal(a.pre_ok, b.pre_ok)
    assert list(a.pre_ok) == [True, False]


def test_malformed_pubkey_length_does_not_misalign_epoch():
    """A wrong-length pubkey must not crash EpochTables nor shift later
    validators' rows in the native prep gather (r3 review finding)."""
    seeds, pubs, _ = _mk_epoch()
    epoch = ed25519_batch.EpochTables([pubs[0], b"\x01" * 31, pubs[1]])
    assert list(epoch.key_ok) == [True, False, True]
    m = b"align"
    sigs = [
        host_ed.sign(seeds[0], m),
        host_ed.sign(seeds[0], m),
        host_ed.sign(seeds[1], m),
    ]
    a = ed25519_batch._prepare_compact_native(
        [m, m, m], sigs, np.array([0, 1, 2]), epoch
    )
    b = ed25519_batch._prepare_compact_py(
        [m, m, m], sigs, np.array([0, 1, 2]), epoch
    )
    np.testing.assert_array_equal(a.pre_ok, b.pre_ok)
    np.testing.assert_array_equal(a.h_nibbles, b.h_nibbles)
    assert list(a.pre_ok) == [True, False, True]
    # validator 2's h must be computed with ITS OWN key bytes
    digest = hashlib.sha512(sigs[2][:32] + pubs[1] + m).digest()
    want = int.from_bytes(digest, "little") % L
    got = 0
    for nib in a.h_nibbles[2]:
        got = (got << 4) | int(nib)
    assert got == want


def test_sign_bytes_batch_parity():
    """Native batch sign bytes are byte-identical to the Python encoder
    across edge cases: zero height, empty hash/chain, negative and huge
    timestamps, varint boundary values."""
    import time as _t

    from txflow_tpu import native
    from txflow_tpu.types.tx_vote import canonical_sign_bytes

    if not native.available():
        import pytest

        pytest.skip("no C compiler")

    cases = [
        (0, "", 0, ""),
        (1, "AB" * 32, 1700000000_000000000, "chain-x"),
        (2**62, "FF" * 32, -1, "c"),
        (127, "00" * 32, 999_999_999, "txflow-localnet"),
        (128, "CD" * 32, 1_000_000_000, ""),
        (7, "E" * 10, -1_500_000_001, "n" * 100),
        (0, "AA" * 32, _t.time_ns(), "txflow-bench"),
    ]
    batch = native.sign_bytes_batch(
        [h for h, _, _, _ in cases],
        [x for _, x, _, _ in cases],
        [t for _, _, t, _ in cases],
        "shared-chain",
    )
    assert batch is not None
    for (h, x, t, _), got in zip(cases, batch):
        assert got == canonical_sign_bytes("shared-chain", h, x, t), (h, x, t)
    # per-case chain ids too (the engine always uses one chain, but the
    # helper must not silently assume it)
    for h, x, t, c in cases:
        got = native.sign_bytes_batch([h], [x], [t], c)
        assert got is not None and got[0] == canonical_sign_bytes(c, h, x, t)


def test_sign_bytes_many_primes_cache():
    """sign_bytes_many returns the same bytes as per-vote sign_bytes and
    primes the per-vote cache for signed votes."""
    import hashlib

    from txflow_tpu.types import TxVote
    from txflow_tpu.types.priv_validator import MockPV
    from txflow_tpu.types.tx_vote import sign_bytes_many

    pv = MockPV()
    votes = []
    for i in range(8):
        key = hashlib.sha256(b"sbm-%d" % i).digest()
        v = TxVote(height=0, tx_hash=key.hex().upper(), tx_key=key,
                   validator_address=pv.get_address())
        pv.sign_tx_vote("chain-sbm", v)
        votes.append(v)
    expect = [canonical_expected.sign_bytes("chain-sbm") for canonical_expected in [v.copy() for v in votes]]
    got = sign_bytes_many(votes, "chain-sbm")
    assert got == expect
    # cache primed: second call is pure cache hits (no native needed)
    assert sign_bytes_many(votes, "chain-sbm") == expect
    assert all(v._sb_cache is not None for v in votes)


def test_sign_bytes_batch_hostile_lengths_safe():
    """Attacker-length fields must never reach the C stack buffer (r5
    review: a gossiped vote with a 5000-char tx_hash segfaulted the
    process pre-signature-check). Oversized items come back as None and
    the sign_bytes_many path falls back to Python for them, bytes-equal."""
    from txflow_tpu import native
    from txflow_tpu.types import TxVote
    from txflow_tpu.types.tx_vote import canonical_sign_bytes, sign_bytes_many

    if not native.available():
        import pytest

        pytest.skip("no C compiler")

    evil_hash = "A" * 5000
    batch = native.sign_bytes_batch([1], [evil_hash], [123], "chain")
    assert batch is not None and batch[0] is None  # rejected, no crash
    # oversized chain id likewise
    batch = native.sign_bytes_batch([1], ["AB" * 32], [123], "c" * 4096)
    assert batch is not None and batch[0] is None
    # mixed batch: the hostile item falls back, the honest one is native;
    # both byte-equal to the Python encoder
    v_evil = TxVote(height=1, tx_hash=evil_hash, tx_key=b"\x00" * 32,
                    timestamp_ns=123, validator_address=b"\x01" * 20)
    v_ok = TxVote(height=1, tx_hash="CD" * 32, tx_key=b"\x00" * 32,
                  timestamp_ns=456, validator_address=b"\x01" * 20)
    got = sign_bytes_many([v_evil, v_ok], "chain-h")
    assert got[0] == canonical_sign_bytes("chain-h", 1, evil_hash, 123)
    assert got[1] == canonical_sign_bytes("chain-h", 1, "CD" * 32, 456)
