"""Runtime lock-order auditor (analysis.lockgraph) + regression tests for
the two lock-discipline findings txlint surfaced and this change fixed:

- F1: Mempool.check_tx held the pool lock across a socket ABCI CheckTx
  round trip (every reader stalled behind the app process);
- F2: TxFlow._route_result ran commit effects (save_tx fsync, ABCI apply)
  inside the engine lock on the inline-commit path.

Auditor tests use PRIVATE LockAuditor instances so they never pollute the
default auditor that tests/conftest.py gates the whole suite on.
"""

import hashlib
import threading

import pytest

from txflow_tpu.abci import AppConns, KVStoreApplication
from txflow_tpu.abci.types import ResponseCheckTx
from txflow_tpu.analysis.lockgraph import (
    AuditedLock,
    AuditedRLock,
    LockAuditor,
    make_lock,
    make_rlock,
    sanctioned_blocking,
)
from txflow_tpu.crypto.hash import sha256
from txflow_tpu.engine import TxExecutor, TxFlow
from txflow_tpu.pool import Mempool, TxVotePool
from txflow_tpu.pool.mempool import ErrTxInCache
from txflow_tpu.store import MemDB, TxStore
from txflow_tpu.types import MockPV, TxVote, Validator, ValidatorSet
from txflow_tpu.utils.config import EngineConfig, MempoolConfig

# ---------------------------------------------------------------------------
# auditor mechanics
# ---------------------------------------------------------------------------


def test_opposite_order_acquisition_reports_cycle():
    aud = LockAuditor()
    a = AuditedLock("A", auditor=aud)
    b = AuditedLock("B", auditor=aud)
    # A -> B on one code path, B -> A on another: one unlucky preemption
    # from deadlock even though this run never deadlocked
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    cycles = aud.cycles()
    assert len(cycles) == 1
    assert set(cycles[0]) == {"A", "B"}
    report = aud.report()
    assert report["cycles"] == cycles
    assert {e["from"] for e in report["edges"]} == {"A", "B"}


def test_consistent_order_is_clean():
    aud = LockAuditor()
    a = AuditedLock("A", auditor=aud)
    b = AuditedLock("B", auditor=aud)
    for _ in range(3):
        with a, b:
            pass
    assert aud.cycles() == []


def test_same_name_different_instances_no_phantom_cycle():
    # two LocalNet nodes each own a pool lock with the same NAME; opposite
    # orders across different nodes' instances are harmless
    aud = LockAuditor()
    a1 = AuditedLock("pool._mtx", auditor=aud)
    a2 = AuditedLock("pool._mtx", auditor=aud)
    with a1, a2:
        pass
    with a2, a1:
        pass
    assert len(aud.cycles()) == 1  # instances DO cycle...
    aud2 = LockAuditor()
    b1 = AuditedLock("pool._mtx", auditor=aud2)
    b2 = AuditedLock("other._mtx", auditor=aud2)
    with b1, b2:
        pass  # ...but a single consistent order never does, regardless of names
    assert aud2.cycles() == []


def test_blocking_call_under_lock_reported():
    aud = LockAuditor()
    lk = AuditedLock("engine._mtx", auditor=aud)
    aud.note_blocking("abci.socket-roundtrip")  # nothing held: clean
    assert aud.blocking_violations() == []
    with lk:
        aud.note_blocking("abci.socket-roundtrip")
    (v,) = aud.blocking_violations()
    assert v["desc"] == "abci.socket-roundtrip"
    assert v["held"] == ["engine._mtx"]
    assert v["thread"] and v["stack"]


def test_allow_blocking_lock_is_sanctioned():
    aud = LockAuditor()
    wlock = AuditedLock("conn._wlock", allow_blocking=True, auditor=aud)
    with wlock:
        aud.note_blocking("socket.sendall")
    assert aud.blocking_violations() == []
    # but a non-sanctioned lock held ALONGSIDE it still fires
    mtx = AuditedLock("node._mtx", auditor=aud)
    with mtx, wlock:
        aud.note_blocking("socket.sendall")
    (v,) = aud.blocking_violations()
    assert v["held"] == ["node._mtx"]


def test_sanctioned_blocking_region():
    # runtime counterpart of a static allow(): inside the region probes
    # don't report (the app-Commit fence under the mempool lock), outside
    # it they do again — and the justification is mandatory
    aud = LockAuditor()
    lk = AuditedLock("pool._mtx", auditor=aud)
    with lk:
        with sanctioned_blocking("commit fence atomic with update", auditor=aud):
            aud.note_blocking("abci.socket-roundtrip")
        assert aud.blocking_violations() == []
        aud.note_blocking("abci.socket-roundtrip")
    assert len(aud.blocking_violations()) == 1
    with pytest.raises(AssertionError):
        sanctioned_blocking("")


def test_rlock_recursion_and_condition_protocol():
    aud = LockAuditor()
    rl = AuditedRLock("pool._mtx", auditor=aud)
    cond = threading.Condition(rl)
    got = []

    def consumer():
        with cond:
            while not got:
                cond.wait(timeout=5.0)

    t = threading.Thread(target=consumer, daemon=True)
    t.start()
    with rl:
        with rl:  # recursion: two held-stack entries
            pass
    with cond:
        got.append(1)
        cond.notify()
    t.join(timeout=5.0)
    assert not t.is_alive()
    # wait() released every recursion level; nothing leaked into the
    # held stack, so an unrelated blocking probe is clean
    aud.note_blocking("probe")
    assert aud.blocking_violations() == []
    assert aud.cycles() == []


def test_factories_respect_env(monkeypatch):
    monkeypatch.setenv("TXFLOW_LOCK_AUDIT", "0")
    assert not isinstance(make_lock("x"), AuditedLock)
    assert not isinstance(make_rlock("x"), AuditedRLock)
    monkeypatch.setenv("TXFLOW_LOCK_AUDIT", "1")
    lk = make_lock("x", allow_blocking=True)
    assert isinstance(lk, AuditedLock) and lk._allow_blocking
    assert isinstance(make_rlock("x"), AuditedRLock)


def test_sleep_probe_installed_by_conftest():
    # conftest runs install_probes() for the audited suite; a lock-free
    # sleep must not record anything on the default auditor
    import os
    import time

    from txflow_tpu.analysis.lockgraph import default_auditor

    if os.environ.get("TXFLOW_LOCK_AUDIT") != "1":
        pytest.skip("suite running with the lock audit disabled")

    before = len(default_auditor().blocking_violations())
    time.sleep(0)
    assert time.sleep.__name__ == "_audited_sleep"
    assert len(default_auditor().blocking_violations()) == before


def test_reset_clears_tables():
    aud = LockAuditor()
    a = AuditedLock("A", auditor=aud)
    b = AuditedLock("B", auditor=aud)
    with a, b:
        aud.note_blocking("x")
    with b, a:
        pass
    assert aud.cycles() and aud.blocking_violations()
    aud.reset()
    assert aud.cycles() == []
    assert aud.blocking_violations() == []


# ---------------------------------------------------------------------------
# F1 regression: mempool app round trip runs outside the pool lock
# ---------------------------------------------------------------------------


class _SlowRemoteApp:
    """Remote (socket-shaped) app conn whose CheckTx parks until released."""

    is_local = False

    def __init__(self):
        self.entered = threading.Event()
        self.release = threading.Event()
        self.calls = 0

    def check_tx_sync(self, tx: bytes) -> ResponseCheckTx:
        self.calls += 1
        self.entered.set()
        assert self.release.wait(timeout=10.0), "test released nobody"
        return ResponseCheckTx(code=0, gas_wanted=1)


def test_mempool_remote_checktx_does_not_hold_pool_lock():
    app = _SlowRemoteApp()
    mp = Mempool(MempoolConfig(cache_size=100), app)
    tx = b"f1-regression-tx"
    err: list = []

    def ingest():
        try:
            mp.check_tx(tx)
        except Exception as e:  # pragma: no cover - failure detail
            err.append(e)

    t = threading.Thread(target=ingest, daemon=True)
    t.start()
    assert app.entered.wait(timeout=10.0)
    try:
        # the app round trip is in flight: the pool lock must be FREE
        # (pre-fix this deadlocked until the app returned)
        assert mp._mtx.acquire(timeout=2.0), (
            "pool lock held across the remote CheckTx round trip"
        )
        mp._mtx.release()
        assert mp.size() == 0  # admitted but not yet inserted
        # the dedup cache RESERVED the key at admission: a concurrent dup
        # answers immediately instead of racing the in-flight round trip
        with pytest.raises(ErrTxInCache):
            mp.check_tx(tx)
        assert app.calls == 1
    finally:
        app.release.set()
    t.join(timeout=10.0)
    assert not err, err
    assert mp.size() == 1
    assert mp.get_tx(sha256(tx)) == tx


def test_mempool_remote_checktx_rejection_rolls_back_cache():
    class _RejectApp:
        is_local = False

        def check_tx_sync(self, tx):
            return ResponseCheckTx(code=1, log="nope")

    mp = Mempool(MempoolConfig(cache_size=100), _RejectApp())
    tx = b"rejected-once"
    with pytest.raises(ValueError):
        mp.check_tx(tx)
    # the reservation was rolled back: the tx may be resubmitted (e.g.
    # after the app state changes) instead of bouncing off the cache
    with pytest.raises(ValueError):
        mp.check_tx(tx)
    assert mp.size() == 0


# ---------------------------------------------------------------------------
# F2 regression: inline commit effects run after the engine lock drops
# ---------------------------------------------------------------------------

CHAIN_ID = "txflow-test"
HEIGHT = 1


def _make_engine():
    pvs = sorted((MockPV() for _ in range(4)), key=lambda p: p.get_address())
    vals = ValidatorSet(
        [Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs]
    )
    by_addr = {pv.get_address(): pv for pv in pvs}
    pvs = [by_addr[v.address] for v in vals]
    conns = AppConns(KVStoreApplication())
    mempool = Mempool(MempoolConfig(cache_size=1000), conns.mempool)
    commitpool = Mempool(MempoolConfig(cache_size=1000))
    votepool = TxVotePool(MempoolConfig(cache_size=10000))
    tx_store = TxStore(MemDB())
    execu = TxExecutor(conns.consensus, mempool)
    flow = TxFlow(
        CHAIN_ID, HEIGHT, vals, votepool, mempool, commitpool, execu,
        tx_store,
        # inline-commit path under test: decisions + effects on the step
        # thread (no committer thread), host verify (no device needed)
        config=EngineConfig(use_device=False, pipeline_commits=False),
    )
    return flow, pvs, votepool, mempool


def _vote(pv, tx: bytes) -> TxVote:
    v = TxVote(
        height=HEIGHT,
        tx_hash=hashlib.sha256(tx).hexdigest().upper(),
        tx_key=hashlib.sha256(tx).digest(),
        timestamp_ns=1700000000_000000000,
        validator_address=pv.get_address(),
    )
    pv.sign_tx_vote(CHAIN_ID, v)
    return v


def test_inline_commit_effects_run_with_engine_lock_released():
    flow, pvs, votepool, mempool = _make_engine()
    held_at: dict[str, bool] = {}

    orig_save = flow.tx_store.save_tx
    orig_apply = flow.tx_executor.apply_tx

    def save_tx(vs, **kw):
        held_at["save_tx"] = flow._mtx._is_owned()
        return orig_save(vs, **kw)

    def apply_tx(*a, **kw):
        held_at["apply_tx"] = flow._mtx._is_owned()
        return orig_apply(*a, **kw)

    flow.tx_store.save_tx = save_tx
    flow.tx_executor.apply_tx = apply_tx

    tx = b"f2=inline"
    mempool.check_tx(tx)
    for pv in pvs[:3]:  # 30 >= 27: quorum
        votepool.check_tx(_vote(pv, tx))
    flow.step()

    assert held_at == {"save_tx": False, "apply_tx": False}, held_at
    assert flow.tx_store.load_tx_commit(
        hashlib.sha256(tx).hexdigest().upper()
    ) is not None


# ---------------------------------------------------------------------------
# cycle detection over the newer cross-thread components: the StagingRing
# readback daemon, the host-prep pools' caller-steals path, and the sync
# manager's advert map. These drive REAL interleavings under the default
# auditor (the one conftest's sessionfinish gate checks) and then assert
# the component's locks sit in no cycle and no blocking violation — so a
# lock added to one of these paths in the wrong order fails HERE, naming
# the component, instead of only in the end-of-suite gate.
# ---------------------------------------------------------------------------


def _require_default_audit():
    import os

    if os.environ.get("TXFLOW_LOCK_AUDIT") != "1":
        pytest.skip("suite running with the lock audit disabled")


def _assert_locks_clean(names: set):
    from txflow_tpu.analysis.lockgraph import default_auditor

    aud = default_auditor()
    for cyc in aud.cycles():
        assert not (set(cyc) & names), f"lock cycle through {names}: {cyc}"
    for v in aud.blocking_violations():
        assert not (set(v["held"]) & names), v


def test_staging_ring_daemon_and_submitters_lock_order():
    _require_default_audit()
    import numpy as np

    from txflow_tpu.parallel.staging import StagingRing

    ring = StagingRing(depth=2, name="audit-ring")
    errs: list = []

    def churn():
        try:
            for i in range(15):
                # depth=2 with eager result(): exercises both the queued
                # path (daemon holds the slot) and the sync fallback
                slot = ring.submit(np.full(16, i))
                assert int(ring.result(slot)[0]) == i
                ring.stats()  # nests _stats_mtx -> _q_mtx: pins the order
        except Exception as e:  # pragma: no cover - failure detail
            errs.append(e)

    threads = [threading.Thread(target=churn, daemon=True) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert not errs, errs
    ring.close()
    assert ring.stats()["in_flight"] == 0
    assert ring.stats()["slots_total"] == 45
    _assert_locks_clean(
        {"parallel.StagingRing._q_mtx", "parallel.StagingRing._stats_mtx"}
    )


def test_hostprep_caller_steals_shared_pool_lock_order():
    # three engines sharing one pool, each stealing queued shards off the
    # common queue: the F5 fix folds per-call steal tallies in under
    # _stats_mtx, and this pins that the fold introduces no lock edge
    _require_default_audit()
    from txflow_tpu.engine.hostprep import HostPrepPool

    pool = HostPrepPool(workers=4, name="audit-prep")
    errs: list = []

    def caller():
        try:
            for _ in range(8):
                results, _ = pool.map_shards(48, lambda lo, hi: (lo, hi))
                assert results == pool.shard_bounds(48)
                pool.stats()
        except Exception as e:  # pragma: no cover - failure detail
            errs.append(e)

    threads = [threading.Thread(target=caller, daemon=True) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert not errs, errs
    try:
        # every shard of every call accounted exactly once, no lost
        # increments across the 3 concurrent callers
        assert pool.stats()["jobs_total"] == 3 * 8 * 4
    finally:
        pool.close()
    _assert_locks_clean({"engine.HostPrepPool._stats_mtx"})


def test_proc_pool_delegation_keeps_thread_pool_lock_order():
    # ProcHostPrepPool delegates generic map_shards to its embedded
    # thread pool; the proc pool's own stats lock must stay disjoint
    # from the inner pool's on that path
    _require_default_audit()
    from txflow_tpu.engine.hostprep import make_host_pool

    pool = make_host_pool(3, backend="process", name="audit-procprep")
    try:
        results, _ = pool.map_shards(30, lambda lo, hi: hi - lo)
        assert sum(results) == 30
        pool.stats()
    finally:
        pool.close()
    _assert_locks_clean(
        {
            "engine.ProcHostPrepPool._stats_mtx",
            "engine.HostPrepPool._stats_mtx",
        }
    )


def test_sync_manager_advert_threads_lock_order():
    # gossip recv threads write adverts while the chooser reads them
    # through lag()/_servable_adverts(); all under sync.SyncManager._mtx
    _require_default_audit()
    from txflow_tpu.sync.manager import SyncManager

    sm = SyncManager("audit-chain", TxStore(MemDB()), txflow=None, switch=None)
    stop = threading.Event()
    errs: list = []

    def recv(peer: str):
        try:
            i = 0
            while not stop.is_set():
                sm.note_status(peer, i, i)
                if i % 7 == 6:
                    sm.note_peer_gone(peer)
                i += 1
        except Exception as e:  # pragma: no cover - failure detail
            errs.append(e)

    threads = [
        threading.Thread(target=recv, args=(f"peer-{k}",), daemon=True)
        for k in range(3)
    ]
    for t in threads:
        t.start()
    for _ in range(200):
        sm.lag()
        sm._servable_adverts()
    stop.set()
    for t in threads:
        t.join(timeout=10.0)
    assert not errs, errs
    assert set(sm._servable_adverts()) <= {"peer-0", "peer-1", "peer-2"}
    _assert_locks_clean({"sync.SyncManager._mtx"})


def test_inline_commit_decision_semantics_unchanged():
    # same decisions as before the split: commit exactly at quorum, dedup
    # late votes, purge quorum votes from the pool
    flow, pvs, votepool, mempool = _make_engine()
    tx = b"f2=semantics"
    mempool.check_tx(tx)
    for pv in pvs[:2]:
        votepool.check_tx(_vote(pv, tx))
    flow.step()
    assert flow.tx_store.load_tx_commit(
        hashlib.sha256(tx).hexdigest().upper()
    ) is None  # 20 < 27
    votepool.check_tx(_vote(pvs[2], tx))
    flow.step()
    commit = flow.tx_store.load_tx_commit(hashlib.sha256(tx).hexdigest().upper())
    assert commit is not None and len(commit.commits) == 3
    assert votepool.size() == 0
    assert flow.vote_sets == {}
