"""Disk-full / EIO drills (utils/failpoints.py): a node whose durable
writes start failing DEGRADES — keeps committing in memory, flips the
storage section of /health, sheds bulk load at the admission edge — and
never crashes or silently drops an admitted tx.

Failpoints are process-global and STICKY once fired: every test disarms
in a finally block BEFORE tearing the net down, or unrelated tests
inherit the armed point.
"""

import hashlib
import time

import pytest

from txflow_tpu.admission import (
    AdmissionConfig,
    AdmissionController,
    ErrOverloaded,
)
from txflow_tpu.node.localnet import LocalNet
from txflow_tpu.pool.mempool import LANE_BULK, LANE_PRIORITY, Mempool
from txflow_tpu.utils import failpoints
from txflow_tpu.utils.config import MempoolConfig


def _single_node_net(tmp_path):
    """One validator (power 10 >= quorum 7) commits solo — the smallest
    rig whose commit path still exercises every durable write."""
    net = LocalNet(1, use_device_verifier=False, enable_consensus=False)
    net.make_durable(0, str(tmp_path / "node0"))
    return net


def _wait(pred, timeout=20.0, poll=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return pred()


def test_filedb_enospc_degrades_not_crashes(tmp_path):
    net = _single_node_net(tmp_path)
    net.start()
    try:
        node = net.nodes[0]
        # healthy baseline: durable commits land
        first = [b"fee=1;pre-%d=v" % i for i in range(5)]
        for tx in first:
            net.broadcast_tx(tx)
        assert net.wait_all_committed(first)
        assert not node.txflow.storage_degraded

        # disk fills: every subsequent FileDB append raises ENOSPC
        failpoints.arm("filedb.append", after=0)
        second = [b"fee=1;post-%d=v" % i for i in range(5)]
        for tx in second:
            net.broadcast_tx(tx)
        want = [hashlib.sha256(t).hexdigest().upper() for t in second]
        # the node keeps DECIDING: commits apply in memory even though
        # the certificate rows can't be persisted
        assert _wait(
            lambda: all(node.txflow.is_tx_committed(h) for h in want)
        ), "node stopped committing when its disk filled"
        assert _wait(lambda: node.txflow.storage_degraded)
        assert node.txflow.storage_errors > 0
        assert node.txflow.storage_last_error
        # loud degradation, machine-readable: metrics + /health
        assert "txflow_storage_errors" in node.metrics_registry.expose()
        reg = node.health.registry
        reg.refresh(node)
        snap = reg.snapshot()
        assert snap["storage"]["degraded"]
        assert snap["storage"]["errors"] > 0
        assert not snap["healthy"]
        # the admission edge sheds bulk while storage is degraded (the
        # node-wired degraded_source hook)
        assert node.admission._storage_degraded()
        with pytest.raises(ErrOverloaded):
            node.admission.admit_rpc(b"shedme=v", hashlib.sha256(b"shedme=v").digest())
    finally:
        failpoints.disarm(None)
        net.stop()


def test_wal_eio_degrades_pools_not_drops(tmp_path):
    net = _single_node_net(tmp_path)
    net.start()
    try:
        node = net.nodes[0]
        warm = [b"fee=1;warm-%d=v" % i for i in range(3)]
        for tx in warm:
            net.broadcast_tx(tx)
        assert net.wait_all_committed(warm)
        assert not node.mempool.wal_degraded

        # WAL device starts erroring (EIO): admitted txs must still flow
        # to commit — the WAL is a restart-recovery aid, not the
        # admission ledger
        failpoints.arm("wal.write", after=0)
        after = [b"fee=1;eio-%d=v" % i for i in range(3)]
        for tx in after:
            net.broadcast_tx(tx)
        assert net.wait_all_committed(after), "tx dropped when the WAL went EIO"
        assert _wait(lambda: node.mempool.wal_degraded)
        reg = node.health.registry
        reg.refresh(node)
        snap = reg.snapshot()
        assert snap["storage"]["mempool_wal_degraded"]
        assert not snap["healthy"]
    finally:
        failpoints.disarm(None)
        net.stop()


def test_degraded_source_sheds_bulk_spares_priority():
    """Unit: the degraded_source hook makes _bulk_shed fire — bulk txs
    get ErrOverloaded at the RPC edge, priority txs still land."""
    from txflow_tpu.utils.metrics import Registry

    pool = Mempool(MempoolConfig(cache_size=100))
    # isolated registry: the module-level GLOBAL one is shared by every
    # controller built without an explicit registry, and other tests
    # assert absolute counter values on it
    adm = AdmissionController(pool, cfg=AdmissionConfig(), registry=Registry())
    pool.lane_of = adm.lane_of
    key = lambda tx: hashlib.sha256(tx).digest()

    assert adm.admit_rpc(b"b0=v", key(b"b0=v"), now=1000.0) == LANE_BULK
    adm.degraded_source = lambda: True
    with pytest.raises(ErrOverloaded):
        adm.admit_rpc(b"b1=v", key(b"b1=v"), now=1000.0)
    assert adm.admit_rpc(b"fee=2;p0=v", key(b"fee=2;p0=v"), now=1000.0) == LANE_PRIORITY
    # a faulting source must fail open, not error the admit path
    adm.degraded_source = lambda: 1 / 0
    assert adm.admit_rpc(b"b1=v", key(b"b1=v"), now=1000.0) == LANE_BULK


def test_failpoint_is_sticky_until_disarmed():
    try:
        failpoints.arm("filedb.append", after=2)
        for _ in range(2):
            failpoints.fail("filedb.append")  # under the threshold
        with pytest.raises(failpoints.FailpointError):
            failpoints.fail("filedb.append")
        # sticky: keeps failing until disarmed
        with pytest.raises(failpoints.FailpointError):
            failpoints.fail("filedb.append")
        assert failpoints.fired("filedb.append")
        failpoints.disarm("filedb.append")
        failpoints.fail("filedb.append")  # no raise
    finally:
        failpoints.disarm(None)
