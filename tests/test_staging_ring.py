"""Double-buffered device readback (parallel.staging.StagingRing).

The ring changes WHERE the packed-result ``np.asarray`` runs — a
dedicated readback thread instead of the ticket waiter — never what it
reads, so every decision and certificate must stay byte-identical to the
synchronous path. Covered here:

- ring semantics: eager readback, overlap (hidden_s) accounting,
  depth overflow degrading to a synchronous non-blocking readback,
  error capture + re-raise at the waiter, close drains queued slots
  and post-close submits degrade to synchronous;
- certificate byte-parity: a device engine with the staging ring on
  commits byte-identical certificates to the scalar ``try_add_vote``
  golden path;
- drain-on-stop: stopping an engine with staged readbacks in flight
  settles every slot (in_flight back to 0) and strands no VerifyCache
  claims.
"""

import hashlib
import time

import numpy as np
import pytest

from test_pipeline import (
    _wait_quiescent,
    make_engine as make_threaded_engine,
    make_pvs,
    sign_vote,
)
from txflow_tpu.parallel.staging import StagingRing, StageSlot
from txflow_tpu.verifier import DeviceVoteVerifier, VerifyCache

BUCKETS = (8, 32)  # CPU-sized compiles (same ladder as test_mesh_engine)


# ---- ring unit semantics ----------------------------------------------


def test_ring_eager_readback_and_overlap_accounting():
    """A submitted slot is read back WITHOUT the caller waiting; the
    overlap ledger credits readback seconds the caller never blocked
    on (hidden_s), and result() returns the host bytes."""
    ring = StagingRing(depth=2, name="t-eager")
    try:
        arr = np.arange(64, dtype=np.int64)
        slot = ring.submit(arr)
        # the readback thread lands the transfer with no result() call
        assert slot._done.wait(timeout=5.0), "eager readback never ran"
        time.sleep(0.01)  # caller does "work" the readback hid under
        host = ring.result(slot)
        np.testing.assert_array_equal(host, arr)
        stats = ring.stats()
        assert stats["slots_total"] == 1
        assert stats["in_flight"] == 0
        assert stats["readback_s"] >= 0.0
        # waited ~0 while the readback had already landed: every
        # readback second counts as hidden
        assert stats["hidden_s"] <= stats["readback_s"] + 1e-9
    finally:
        ring.close()


def test_ring_depth_overflow_degrades_to_synchronous():
    """More un-awaited submits than ``depth`` NEVER block the submitter:
    the overflow readback runs synchronously on the caller (buffers stay
    bounded by degradation). Blocking would deadlock engines sharing the
    ring — each fills ahead of its own collector on one loop thread, so
    every permit holder can end up parked in submit() at once while the
    result() calls that release permits never run."""
    ring = StagingRing(depth=1, name="t-depth")
    try:
        first = ring.submit(np.zeros(4))
        # full ring: the second submit returns an already-landed slot
        second = ring.submit(np.ones(4))
        assert second._done.is_set(), "overflow submit did not run inline"
        assert not second._queued
        np.testing.assert_array_equal(ring.result(second), np.ones(4))
        np.testing.assert_array_equal(ring.result(first), np.zeros(4))
        stats = ring.stats()
        assert stats["sync_readbacks"] == 1
        assert stats["slots_total"] == 2
        assert stats["in_flight"] == 0
        # the sync slot held no permit: result(second) must not inflate
        # the semaphore, so the freed ring stages the next submit again
        third = ring.submit(np.full(4, 2))
        assert third._queued
        np.testing.assert_array_equal(ring.result(third), np.full(4, 2))
        assert ring.stats()["sync_readbacks"] == 1
    finally:
        ring.close()


def test_ring_error_captured_and_reraised_at_waiter():
    """A readback that raises surfaces at result(), not in the thread —
    and the ring keeps serving later slots."""

    class Boom:
        def __array__(self, dtype=None):
            raise RuntimeError("device readback failed")

    ring = StagingRing(depth=2, name="t-error")
    try:
        bad = ring.submit(Boom())
        with pytest.raises(RuntimeError, match="device readback failed"):
            ring.result(bad)
        good = ring.submit(np.full(3, 7))
        np.testing.assert_array_equal(ring.result(good), np.full(3, 7))
    finally:
        ring.close()


def test_ring_close_drains_and_degrades_to_synchronous():
    """close() completes already-queued slots (their waiters still get
    bytes); submits after close run synchronously — the drain path is
    never lossy."""
    ring = StagingRing(depth=4, name="t-close")
    queued = [ring.submit(np.full(2, i)) for i in range(3)]
    ring.close()
    for i, slot in enumerate(queued):
        np.testing.assert_array_equal(ring.result(slot), np.full(2, i))
    late = ring.submit(np.full(2, 9))  # post-close: synchronous slot
    np.testing.assert_array_equal(ring.result(late), np.full(2, 9))
    ring.close()  # idempotent


# ---- engine-level parity + drain --------------------------------------


def _quorum_stream(pvs, txs, corrupt_every=7):
    stream = []
    for i, tx in enumerate(txs):
        for vi, pv in enumerate(pvs):
            vote = sign_vote(pv, tx)
            if (i + vi) % corrupt_every == 0:
                vote.signature = bytes(64)
            stream.append(vote)
    return stream


def test_staged_engine_certificates_match_golden():
    """Device engine with the staging ring on: certificates, app state,
    and commit order byte-identical to the scalar try_add_vote golden
    path — and the run actually staged readbacks (slots_total > 0)."""
    pvs, vals = make_pvs(4)
    txs = [b"sr%d=%d" % (i, i) for i in range(24)]
    stream = _quorum_stream(pvs, txs)

    flow_s, mem_s, _, store_s, app_s = make_threaded_engine(
        vals, use_device=False
    )
    for tx in txs:
        mem_s.check_tx(tx)
    for v in stream:
        flow_s.try_add_vote(v.copy())

    verifier = DeviceVoteVerifier(vals, buckets=BUCKETS, staging_ring=2)
    verifier.warmup(full=True)  # compile outside the drain-wait windows
    flow_d, mem_d, pool_d, store_d, app_d = make_threaded_engine(
        vals, verifier=verifier, max_batch=32, min_batch=4,
        pipeline_depth=2, coalesce=True, coalesce_linger=0.02,
    )
    for tx in txs:
        mem_d.check_tx(tx)
    flow_d.start()
    try:
        for v in stream:
            try:
                pool_d.check_tx(v)
            except Exception:
                pass  # cache dup (zeroed sigs share a vote key)
        assert _wait_quiescent(flow_d, pool_d, timeout=90.0), (
            "staged engine never drained"
        )
        stats = flow_d.pipeline_stats()
    finally:
        flow_d.stop()

    ring = stats.get("staging")
    assert ring is not None and ring["slots_total"] > 0, (
        "run never staged a readback — parity test is vacuous"
    )
    assert app_d.tx_count == app_s.tx_count
    assert app_d.state == app_s.state
    assert app_d.digest == app_s.digest  # commit ORDER identical
    committed = 0
    for tx in txs:
        tx_hash = hashlib.sha256(tx).hexdigest().upper()
        cs = store_s.load_tx_commit(tx_hash)
        cd = store_d.load_tx_commit(tx_hash)
        assert (cs is None) == (cd is None)
        if cs is not None:
            committed += 1
            assert [
                (c.validator_address, c.signature) for c in cs.commits
            ] == [(c.validator_address, c.signature) for c in cd.commits]
    assert committed > 0, "stream never formed a quorum — test is vacuous"


def test_stop_drains_staged_slots_and_claims():
    """stop() with staged readbacks in flight: every slot settles
    (in_flight 0), the depth gauge reads 0, and the shared VerifyCache
    holds no stranded claims (the claim keepalive exits at ticket
    result, which the drain must reach for every in-flight ticket)."""
    pvs, vals = make_pvs(4)
    cache = VerifyCache()
    verifier = DeviceVoteVerifier(
        vals, buckets=BUCKETS, shared_cache=cache, staging_ring=2
    )
    verifier.warmup(full=True)
    flow, mempool, votepool, store, app = make_threaded_engine(
        vals, verifier=verifier, max_batch=32, min_batch=4,
        pipeline_depth=4, coalesce=True, coalesce_linger=0.01,
    )
    txs = [b"sd%d=v" % i for i in range(40)]
    votes = [sign_vote(pv, tx) for tx in txs for pv in pvs[:3]]
    for tx in txs:
        mempool.check_tx(tx)
    flow.start()
    try:
        for v in votes:
            votepool.check_tx(v)
    finally:
        # stop with work still flowing: the run loop's finally block
        # must collect the staged in-flight tail
        flow.stop()

    assert flow.metrics.pipeline_depth.value() == 0, "orphaned tickets"
    assert not cache._inflight, "leaked cache claims after stop"
    ring = verifier.staging_stats()
    if ring is not None:  # the run may stop before the first dispatch
        assert ring["in_flight"] == 0, "staged slot leaked past stop()"
