"""Overload front-door tests (ISSUE 6): admission control semantics at
the RPC edge (429 + Retry-After, byte-consistent dup replies, counted
503 connection shedding), fee/priority mempool lanes, the address-book
reconnect hook over real TCP, and the multi-process ProcNet harness.

The full overload soak (5x offered load + chaos + blackhole healing) is
``tools/soak.py --overload``; its smoke form runs here under the slow
marker.
"""

import conftest  # noqa: F401

import hashlib
import http.client
import json
import os
import socket
import subprocess
import sys
import time

import pytest

from txflow_tpu.admission import FeeLaneClassifier, parse_fee
from txflow_tpu.pool.mempool import LANE_BULK, LANE_PRIORITY, Mempool
from txflow_tpu.utils.config import MempoolConfig, test_config as make_test_config


# -- lanes: classifier + pool plumbing --


def test_parse_fee_and_classifier():
    assert parse_fee(b"fee=7;k=v") == 7
    assert parse_fee(b"k=v") == 0
    assert parse_fee(b"fee=;k=v") == 0
    assert parse_fee(b"fee=nope;k=v") == 0
    assert parse_fee(b"fee=1" + b"x" * 100) == 0  # no terminator in scan range
    clf = FeeLaneClassifier(priority_fee_threshold=3)
    assert clf(b"fee=3;k=v") == LANE_PRIORITY
    assert clf(b"fee=2;k=v") == LANE_BULK
    assert clf(b"k=v") == LANE_BULK


def test_mempool_priority_lane_log_and_reap():
    pool = Mempool(MempoolConfig(cache_size=100))
    pool.lane_of = FeeLaneClassifier(1)

    bulk = [b"b%d=v" % i for i in range(4)]
    prio = [b"fee=2;p%d=v" % i for i in range(3)]
    pool.check_tx(bulk[0])
    pool.check_tx(prio[0])
    pool.check_tx(bulk[1])
    pool.check_tx(prio[1])
    pool.check_tx(bulk[2])
    pool.check_tx(prio[2])
    pool.check_tx(bulk[3])

    assert pool.lane_size(LANE_PRIORITY) == 3
    assert pool.lane_size(LANE_BULK) == 4

    # the priority walk sees ONLY priority txs, in insertion order
    items, pos = pool.priority_entries_from(0, limit=10)
    assert [it[1] for it in items] == prio
    assert all(it[4] == LANE_PRIORITY for it in items)
    # cursor resumes (no re-delivery)
    again, _ = pool.priority_entries_from(pos, limit=10)
    assert again == []

    # the main walk now carries the lane in slot 4
    allitems, _ = pool.entries_from(0, limit=10)
    assert len(allitems) == 7
    assert sum(1 for it in allitems if it[4] == LANE_PRIORITY) == 3

    # reaps serve the priority lane FIRST, insertion order within lanes
    reaped = pool.reap_max_txs(5)
    assert reaped[:3] == prio and reaped[3:] == bulk[:2]

    # committing a priority tx updates the lane accounting
    pool.lock()
    try:
        pool.update(1, [prio[0]])
    finally:
        pool.unlock()
    assert pool.lane_size(LANE_PRIORITY) == 2
    assert pool.size() == 6


def test_bulk_rate_token_bucket():
    """cfg.bulk_rate caps BULK admissions per second (token bucket);
    priority ignores the bucket entirely."""
    from txflow_tpu.admission import (
        AdmissionConfig,
        AdmissionController,
        ErrOverloaded,
    )

    pool = Mempool(MempoolConfig(cache_size=100))
    adm = AdmissionController(
        pool, cfg=AdmissionConfig(bulk_rate=2.0, bulk_burst=2.0)
    )
    pool.lane_of = adm.lane_of

    def key(tx):
        return hashlib.sha256(tx).digest()

    t0 = 1000.0
    # burst depth 2: two bulk admits pass, the third sheds
    assert adm.admit_rpc(b"b0=v", key(b"b0=v"), now=t0) == LANE_BULK
    assert adm.admit_rpc(b"b1=v", key(b"b1=v"), now=t0) == LANE_BULK
    with pytest.raises(ErrOverloaded):
        adm.admit_rpc(b"b2=v", key(b"b2=v"), now=t0)
    assert adm.metrics.rejected_overload.value() == 1
    # priority is never rate-capped
    assert adm.admit_rpc(b"fee=2;p=v", key(b"fee=2;p=v"), now=t0) == LANE_PRIORITY
    # tokens refill at bulk_rate: +0.5s -> one more bulk admit
    assert adm.admit_rpc(b"b2=v", key(b"b2=v"), now=t0 + 0.5) == LANE_BULK
    with pytest.raises(ErrOverloaded):
        adm.admit_rpc(b"b3=v", key(b"b3=v"), now=t0 + 0.5)
    # a shed tx was never pushed into the dedup: the retry is not a dup
    assert adm.admit_rpc(b"b3=v", key(b"b3=v"), now=t0 + 2.0) == LANE_BULK


def test_priority_sender_budget_fairness():
    """One whale tagged ``from=<id>;`` can't starve the priority lane:
    past its per-sender token budget it loses the lane's unconditional
    admission and is subjected to the bulk shed rules. Other senders and
    untagged txs are untouched, and lane ASSIGNMENT never changes."""
    from txflow_tpu.admission import (
        AdmissionConfig,
        AdmissionController,
        ErrOverloaded,
    )
    from txflow_tpu.admission.classifier import parse_sender

    assert parse_sender(b"fee=2;from=alice;k=v") == "alice"
    assert parse_sender(b"fee=2;k=v") == ""
    assert parse_sender(b"fee=2;from=alice") == ""  # no terminator

    from txflow_tpu.utils.metrics import Registry

    pool = Mempool(MempoolConfig(cache_size=100))
    adm = AdmissionController(
        pool,
        cfg=AdmissionConfig(priority_sender_rate=1.0, priority_sender_burst=1.0),
        registry=Registry(),  # keep absolute counter asserts isolated
    )
    pool.lane_of = adm.lane_of
    # force the shed verdict deterministically (storage degraded): an
    # over-budget priority sender gets exactly the bulk treatment
    adm.degraded_source = lambda: True

    def key(tx):
        return hashlib.sha256(tx).digest()

    t0 = 1000.0
    whale = [b"fee=2;from=alice;w%d=v" % i for i in range(3)]
    assert adm.admit_rpc(whale[0], key(whale[0]), now=t0) == LANE_PRIORITY
    with pytest.raises(ErrOverloaded):
        adm.admit_rpc(whale[1], key(whale[1]), now=t0)
    assert adm.metrics.priority_sender_limited.value() >= 1
    assert adm.metrics.priority_sender_shed.value() >= 1
    # a different tagged sender has its own budget
    other = b"fee=2;from=bob;k=v"
    assert adm.admit_rpc(other, key(other), now=t0) == LANE_PRIORITY
    # untagged priority txs are exempt (no sender identity to budget)
    untagged = b"fee=2;solo=v"
    assert adm.admit_rpc(untagged, key(untagged), now=t0) == LANE_PRIORITY
    # tokens refill: the whale is priority again a second later
    assert adm.admit_rpc(whale[2], key(whale[2]), now=t0 + 1.5) == LANE_PRIORITY
    assert adm.metrics.priority_sender_tracked.value() == 2.0


def test_priority_sender_budget_disabled_by_default():
    from txflow_tpu.admission import AdmissionConfig, AdmissionController
    from txflow_tpu.utils.metrics import Registry

    pool = Mempool(MempoolConfig(cache_size=100))
    adm = AdmissionController(pool, cfg=AdmissionConfig(), registry=Registry())
    pool.lane_of = adm.lane_of
    adm.degraded_source = lambda: True  # even while shedding bulk ...
    t0 = 1000.0
    for i in range(10):
        tx = b"fee=2;from=alice;d%d=v" % i
        # ... rate 0 = no per-sender budget: priority admits untouched
        assert adm.admit_rpc(tx, hashlib.sha256(tx).digest(), now=t0) == LANE_PRIORITY
    assert adm._sender_buckets == {}


def test_vote_pool_priority_lane_and_eviction():
    """Priority-tx votes ride the vote pool's priority log, and when the
    pool is FULL a priority vote evicts the oldest bulk vote instead of
    bouncing (a bounced vote is a quorum signature lost)."""
    from txflow_tpu.pool.txvotepool import TxVotePool
    from txflow_tpu.types.tx_vote import TxVote

    prio_keys = {hashlib.sha256(b"fee=2;p=v").digest()}

    def mk_vote(i, tx_key):
        return TxVote(
            height=0,
            tx_hash=tx_key.hex().upper(),
            tx_key=tx_key,
            timestamp_ns=i + 1,
            validator_address=b"\x01" * 20,
            # vote_key() is sha256(signature): keep them distinct
            signature=i.to_bytes(2, "big") * 32,
        )

    pool = TxVotePool(MempoolConfig(size=3, cache_size=100))
    pool.lane_of_vote = lambda v: (
        LANE_PRIORITY if v.tx_key in prio_keys else LANE_BULK
    )

    bulk_votes = [
        mk_vote(i, hashlib.sha256(b"b%d=v" % i).digest()) for i in range(3)
    ]
    for v in bulk_votes:
        pool.check_tx(v)
    assert pool.size() == 3  # full

    pv = mk_vote(10, next(iter(prio_keys)))
    pool.check_tx(pv)  # no raise: evicts the oldest bulk vote
    assert pool.size() == 3
    assert not pool.has(bulk_votes[0].vote_key())
    assert pool.has(pv.vote_key())
    # the evicted vote left the dedup cache too: regossip can re-deliver
    assert not pool.in_cache(bulk_votes[0].vote_key())

    # the priority walk sees ONLY the priority vote
    items, pos = pool.priority_entries_from(0, limit=10)
    assert [k for k, _v, _h, _s in items] == [pv.vote_key()]
    again, _ = pool.priority_entries_from(pos, limit=10)
    assert again == []

    # batched ingest path: bulk bounces while full, priority evicts
    from txflow_tpu.pool.mempool import ErrMempoolIsFull

    b4 = mk_vote(11, hashlib.sha256(b"b4=v").digest())
    p2k = hashlib.sha256(b"fee=2;p2=v").digest()
    prio_keys.add(p2k)
    p2 = mk_vote(12, p2k)
    errs = pool.check_tx_many([b4, p2])
    assert isinstance(errs[0], ErrMempoolIsFull)
    assert errs[1] is None
    assert pool.has(p2.vote_key())


# -- RPC edge semantics --


def _single_node(mempool_size=10, admission_config=None):
    from txflow_tpu.abci.kvstore import KVStoreApplication
    from txflow_tpu.node.node import Node, NodeConfig
    from txflow_tpu.types.priv_validator import MockPV
    from txflow_tpu.types.validator import Validator, ValidatorSet

    pv = MockPV(hashlib.sha256(b"overload-val").digest())
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10)])
    cfg = make_test_config()
    cfg.mempool.size = mempool_size
    node = Node(
        node_id="overload-node",
        chain_id="txflow-overload",
        val_set=vs,
        app=KVStoreApplication(),
        priv_val=pv,
        node_config=NodeConfig(
            config=cfg,
            use_device_verifier=False,
            enable_consensus=False,
            rpc_port=0,
            admission_config=admission_config,
        ),
    )
    node.start()
    return node


def _http_get(addr, path):
    """(status, reason, content_type, body_bytes) without raising."""
    host, port = addr
    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return (
            resp.status,
            resp.reason,
            resp.getheader("Content-Type"),
            resp.getheader("Retry-After"),
            resp.read(),
        )
    finally:
        conn.close()


def test_rpc_429_retry_after_on_high_water():
    """Pool past high water: bulk submissions shed with 429 + Retry-After
    while priority submissions keep landing (the lanes' whole point)."""
    node = _single_node(mempool_size=10)
    try:
        # fill to 90% with bulk through the trusted local edge
        for i in range(9):
            node.broadcast_tx(b"fill%d=v" % i)
        assert node.mempool.size() == 9

        status, _, ctype, retry_after, body = _http_get(
            node.rpc.addr, '/broadcast_tx?tx="shed-me=v"'
        )
        assert status == 429
        assert retry_after is not None and int(retry_after) >= 1
        assert "json" in ctype
        payload = json.loads(body)
        assert payload["error"] == "overloaded"
        assert payload["retry_after"] > 0
        assert node.admission.metrics.rejected_overload.value() >= 1

        # priority lane stays open at the same pool level
        status, _, _, _, body = _http_get(
            node.rpc.addr, '/broadcast_tx?tx="fee=5;vip=v"'
        )
        assert status == 200
        res = json.loads(body)["result"]
        assert res["code"] == 0
        assert node.mempool.lane_size(LANE_PRIORITY) == 1
        assert node.admission.metrics.admitted_priority.value() == 1

        # the shed tx never reached the pool or its cache: a retry after
        # the pool drains must succeed, not dup-bounce (step past the
        # cached pressure verdict, as a Retry-After-honoring client would)
        node.mempool.flush()
        time.sleep(node.admission.cfg.pressure_interval * 2)
        status, _, _, _, body = _http_get(
            node.rpc.addr, '/broadcast_tx?tx="shed-me=v"'
        )
        assert status == 200
        assert json.loads(body)["result"].get("duplicate") is None
    finally:
        node.stop()


def test_rpc_dup_replies_byte_consistent():
    """Edge-dedup hits and mempool-cache hits must answer with the same
    bytes: a client cannot tell (nor needs to) WHERE the dup was caught."""
    node = _single_node(mempool_size=100)
    try:
        # seed via the trusted local edge: the pool cache knows the tx,
        # the RPC edge dedup does NOT
        node.broadcast_tx(b"dup-k=v")

        # first RPC submit: admitted at the edge, then the POOL reports
        # the dup (ErrTxInCache path)
        pool_hit = _http_get(node.rpc.addr, '/broadcast_tx?tx="dup-k=v"')
        # second RPC submit: the EDGE dedup rejects before any pool work
        edge_hit = _http_get(node.rpc.addr, '/broadcast_tx?tx="dup-k=v"')

        assert pool_hit == edge_hit  # status, reason, headers, body — all
        status, _, _, _, body = edge_hit
        assert status == 200
        res = json.loads(body)["result"]
        assert res["duplicate"] is True
        assert res["hash"] == hashlib.sha256(b"dup-k=v").hexdigest().upper()
        assert node.admission.metrics.rejected_dup.value() >= 1
    finally:
        node.stop()


def test_rpc_conn_cap_sheds_with_503_and_counter():
    """Over the connection cap the listener answers a minimal 503 (not a
    bare reset) and counts the rejection in txflow_rpc_rejected_total."""
    node = _single_node(mempool_size=100)
    try:
        httpd = node.rpc._httpd
        # drain the semaphore so the next accept is over-cap
        taken = 0
        while httpd._conn_sem.acquire(blocking=False):
            taken += 1
        try:
            host, port = node.rpc.addr
            with socket.create_connection((host, port), timeout=10) as s:
                s.sendall(b"GET /health HTTP/1.1\r\nHost: x\r\n\r\n")
                s.settimeout(10)
                data = b""
                while b"\r\n\r\n" not in data:
                    chunk = s.recv(4096)
                    if not chunk:
                        break
                    data += chunk
                head, _, rest = data.partition(b"\r\n\r\n")
                assert b"503" in head.split(b"\r\n")[0]
                assert b"Retry-After: 1" in head
                n = int(
                    [
                        ln.split(b":")[1]
                        for ln in head.split(b"\r\n")
                        if ln.lower().startswith(b"content-length")
                    ][0]
                )
                while len(rest) < n:
                    chunk = s.recv(4096)
                    if not chunk:
                        break
                    rest += chunk
                assert json.loads(rest) == {"error": "too many open connections"}
        finally:
            for _ in range(taken):
                httpd._conn_sem.release()
        counter = node.metrics_registry.counter("rpc", "rejected_total")
        assert counter.value() >= 1
        assert "txflow_rpc_rejected_total" in node.metrics_registry.expose()
    finally:
        node.stop()


def test_gossip_ingest_shed_under_overload():
    """A full pool pauses BULK gossip ingest (counted) while priority
    gossip still lands — the reactor-side backpressure arm."""
    node = _single_node(mempool_size=10)
    try:
        for i in range(9):
            node.broadcast_tx(b"gfill%d=v" % i)
        adm = node.admission
        assert adm.overloaded() is True
        assert adm.admit_gossip(b"gossip-bulk=v") is False
        assert adm.metrics.rejected_gossip.value() >= 1
        assert adm.admit_gossip(b"fee=9;gossip-vip=v") is True
        assert adm.gossip_paused() is True

        # hysteresis: drain below low water -> gossip resumes
        node.mempool.flush()
        time.sleep(adm.cfg.pressure_interval * 2)
        assert adm.overloaded() is False
        assert adm.admit_gossip(b"gossip-bulk=v") is True
        assert adm.gossip_paused() is False
    finally:
        node.stop()


# -- real-TCP healing: the address-book reconnect hook --


def test_book_reconnector_heals_evicted_tcp_peer():
    from txflow_tpu.abci.kvstore import KVStoreApplication
    from txflow_tpu.node.node import Node, NodeConfig
    from txflow_tpu.p2p.pex import book_reconnector
    from txflow_tpu.types.priv_validator import MockPV
    from txflow_tpu.types.validator import Validator, ValidatorSet

    pvs = [MockPV(hashlib.sha256(b"heal-val%d" % i).digest()) for i in range(2)]
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs])
    by_addr = {pv.get_address(): pv for pv in pvs}
    nodes = []
    try:
        for i in range(2):
            node = Node(
                node_id=f"heal-{i}",
                chain_id="txflow-heal",
                val_set=vs,
                app=KVStoreApplication(),
                priv_val=by_addr[vs.get_by_index(i).address],
                node_config=NodeConfig(
                    config=make_test_config(),
                    use_device_verifier=False,
                    enable_consensus=False,
                    node_key_seed=hashlib.sha256(b"heal-key-%d" % i).digest(),
                ),
            )
            node.start()
            nodes.append(node)
        a, b = nodes
        # keyed TCP assembly: PEX + address book are auto-enabled and the
        # health layer's reconnector is the book-backed dial (the seed's
        # comment said "a TCP assembly would wire a dial" — now it IS)
        assert a.address_book is not None and a.pex is not None
        assert a.health.scoreboard.reconnector is not None

        host, port = b.switch.listen_tcp("127.0.0.1", 0)
        peer = a.switch.dial_tcp(host, port)
        b_id = peer.node_id
        assert b_id == b.switch.node_id
        # the PEX handshake teaches A the peer's listen address; don't
        # race it — seed the entry the way the advert would
        a.address_book.add(b_id, host, port)

        # evict (what the scoreboard does at score_floor) ...
        a.switch.stop_peer(peer, reason="test eviction")
        deadline = time.monotonic() + 10
        while (
            a.switch.get_peer(b_id) is not None
            or b.switch.get_peer(a.switch.node_id) is not None
        ):
            assert time.monotonic() < deadline, "old link never tore down"
            time.sleep(0.05)

        # ... and heal through the SAME hook the scoreboard drains
        reconnect = a.health.scoreboard.reconnector
        assert reconnect(b_id) is True
        assert a.switch.get_peer(b_id) is not None

        # unknown peer: the hook reports failure (backoff continues)
        assert book_reconnector(a.switch, a.address_book)("NOPE") is False
    finally:
        for node in nodes:
            node.stop()


# -- multi-process net (tools/soak.py --overload rides this harness) --


def test_procnet_two_process_commit():
    from txflow_tpu.node.procnet import ProcNet

    net = ProcNet(2, spec={"seed_prefix": "pn-smoke", "chain_id": "txflow-pn"})
    net.start(timeout=90)
    try:
        tx = b"pn-k=v"
        res = net.rpc_json(0, '/broadcast_tx?tx="pn-k=v"')["result"]
        tx_hash = hashlib.sha256(tx).hexdigest().upper()
        assert res["hash"] == tx_hash
        sub = net.rpc_json(1, f"/subscribe_tx?hash={tx_hash}&timeout=30")["result"]
        assert sub["committed"] is True, sub
        # both children expose admission metrics over real sockets
        assert net.metrics_value(0, "txflow_admission_admitted_bulk") >= 1
    finally:
        net.stop()


# -- txlint: the admit path must never block --


def test_txlint_flags_blocking_admit_path():
    from txflow_tpu.analysis.core import lint_source

    src = (
        "import time\n"
        "class AdmissionController:\n"
        "    def admit_rpc(self, tx, key):\n"
        "        time.sleep(0.1)\n"
        "        return 0\n"
        "    def _bulk_shed(self):\n"
        "        return self.fut.result()\n"
        "    def not_hot(self):\n"
        "        time.sleep(1.0)\n"
    )
    active, _ = lint_source(src, "txflow_tpu/admission/controller.py")
    hot = [v for v in active if v.rule == "hotpath-sync"]
    assert len(hot) == 2, [v.format() for v in hot]
    assert {4, 7} == {v.line for v in hot}
    assert all("admit-path" in v.message for v in hot)

    # the shipped controller stays clean under the same pass
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "txflow_tpu", "admission", "controller.py")) as f:
        real = f.read()
    active, _ = lint_source(real, "txflow_tpu/admission/controller.py")
    assert [v for v in active if v.rule == "hotpath-sync"] == []


# -- the full overload soak (wall-clock heavy: slow marker) --


@pytest.mark.slow
def test_overload_soak_smoke():
    """tools/soak.py --overload --smoke must pass its SLOs end to end:
    flat priority p50 under 429-shedding flood, chaos faults, and a
    blackholed node healing via the address-book re-dial."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "tools/soak.py", "--overload", "--smoke"],
        cwd=repo,
        capture_output=True,
        text=True,
        timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, f"\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    assert "SOAK OK (overload)" in proc.stdout
