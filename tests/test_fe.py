"""Field arithmetic golden tests: limb ops vs python-int arithmetic."""

import random

import numpy as np
import pytest

from txflow_tpu.crypto.ed25519 import P
from txflow_tpu.ops import fe

rng = random.Random(0xFE)


def rand_fe(n):
    return [rng.randrange(P) for _ in range(n)]


def to_limb_batch(vals):
    return np.stack([fe.int_to_limbs(v) for v in vals])


EDGE = [0, 1, 2, 19, 38, P - 1, P - 2, 2**255 - 1, 2**254, 0xFF, 1 << 248]


def check_normalized(out):
    out = np.asarray(out)
    assert out.min() >= 0
    assert out.max() < 512, out.max()


def test_roundtrip():
    for v in EDGE + rand_fe(16):
        assert fe.limbs_to_int(fe.int_to_limbs(v)) == v


def test_mul():
    a_vals = EDGE + rand_fe(32)
    b_vals = list(reversed(EDGE)) + rand_fe(32)
    out = fe.fe_mul(to_limb_batch(a_vals), to_limb_batch(b_vals))
    check_normalized(out)
    for av, bv, o in zip(a_vals, b_vals, np.asarray(out)):
        assert fe.limbs_to_int(o) % P == (av * bv) % P


def test_mul_worst_case_bounds():
    # Max legal input limbs (1311) must not overflow int32 anywhere.
    a = np.full((1, fe.NLIMB), 1311, np.int32)
    out = np.asarray(fe.fe_mul(a, a))
    check_normalized(out)
    assert fe.limbs_to_int(out[0]) % P == (fe.limbs_to_int(a[0]) ** 2) % P


def test_add_sub():
    a_vals, b_vals = rand_fe(16), rand_fe(16)
    a, b = to_limb_batch(a_vals), to_limb_batch(b_vals)
    s = fe.fe_sub(a, b)
    check_normalized(s)
    for av, bv, o in zip(a_vals, b_vals, np.asarray(s)):
        assert fe.limbs_to_int(o) % P == (av - bv) % P
    # add -> mul composition (documented bound path)
    m = fe.fe_mul(fe.fe_add(a, b), fe.fe_add(b, a))
    check_normalized(m)
    for av, bv, o in zip(a_vals, b_vals, np.asarray(m)):
        assert fe.limbs_to_int(o) % P == ((av + bv) ** 2) % P


def test_mul_small():
    a_vals = rand_fe(8) + EDGE
    out = fe.fe_mul_small(to_limb_batch(a_vals), 121666)
    check_normalized(out)
    for av, o in zip(a_vals, np.asarray(out)):
        assert fe.limbs_to_int(o) % P == (av * 121666) % P


def test_freeze_canonical():
    # Non-canonical representations of known values must freeze exactly.
    cases = []
    for v in [0, 1, 19, P - 1, P - 2]:
        cases.append((fe.int_to_limbs(v), v))
    cases.append((fe.P_LIMBS.copy(), 0))  # p ≡ 0
    p_plus_1 = fe.P_LIMBS.copy()
    p_plus_1[0] += 1
    cases.append((p_plus_1, 1))
    cases.append((2 * fe.P_LIMBS + fe.int_to_limbs(5), 5))  # 2p + 5
    big = np.full(fe.NLIMB, 511, np.int32)  # arbitrary non-canonical
    cases.append((big, fe.limbs_to_int(big) % P))
    arr = np.stack([c[0] for c in cases])
    out = np.asarray(fe.fe_freeze(arr))
    assert out.min() >= 0 and out.max() <= 255
    for (_, want), o in zip(cases, out):
        assert fe.limbs_to_int(o) == want


def test_inv():
    vals = [v for v in EDGE if v % P != 0] + rand_fe(8)
    out = np.asarray(fe.fe_inv(to_limb_batch(vals)))
    check_normalized(out)
    for v, o in zip(vals, out):
        assert fe.limbs_to_int(o) % P == pow(v, P - 2, P)


@pytest.mark.parametrize("value", [2**31 - 1])
def test_carry_extreme(value):
    # fe_carry must settle the largest fold outputs into normalized limbs.
    x = np.full((1, fe.NLIMB), value, np.int32)
    out = np.asarray(fe.fe_carry(x, passes=6))
    check_normalized(out)
    assert fe.limbs_to_int(out[0]) % P == fe.limbs_to_int(x[0]) % P
