"""Multi-host operation, for real: two validator nodes in SEPARATE OS
processes, peered over authenticated TCP (secret connections), with the tx
submitted through the child's HTTP RPC by an external client and its
commit observed on both sides.

This is the process-boundary analog of the reference's multi-machine
deployment surface (reference node/node.go:795-819 transport listen +
:878-986 RPC): everything crosses real sockets — no in-proc pipes, no
shared memory, two independent Python runtimes.
"""

import conftest  # noqa: F401

import hashlib
import json
import os
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD_SCRIPT = r"""
import json, os, sys, hashlib, signal
sys.path.insert(0, os.environ["TXFLOW_REPO"])
from txflow_tpu.node.node import Node, NodeConfig
from txflow_tpu.types.priv_validator import MockPV
from txflow_tpu.types.validator import Validator, ValidatorSet
from txflow_tpu.abci.kvstore import KVStoreApplication
from txflow_tpu.utils.config import test_config

pvs = [MockPV(hashlib.sha256(b"mp-val%d" % i).digest()) for i in range(2)]
vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs])
by_addr = {pv.get_address(): pv for pv in pvs}
me = by_addr[vs.get_by_index(1).address]  # child runs validator index 1

node = Node(
    node_id="mp-child",
    chain_id="txflow-mp",
    val_set=vs,
    app=KVStoreApplication(),
    priv_val=me,
    node_config=NodeConfig(
        config=test_config(),
        use_device_verifier=False,
        enable_consensus=False,
        rpc_port=0,
        node_key_seed=hashlib.sha256(b"mp-key-child").digest(),
    ),
)
node.start()
host, port = node.switch.listen_tcp("127.0.0.1", 0)
rhost, rport = node.rpc.addr
print(json.dumps({"p2p": [host, port], "rpc": [rhost, rport]}), flush=True)
signal.sigwait([signal.SIGTERM, signal.SIGINT])
node.stop()
"""


def rpc_get(addr, path):
    host, port = addr
    with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=30) as r:
        return json.loads(r.read().decode())


def test_two_process_net_commits_via_rpc(tmp_path):
    from txflow_tpu.abci.kvstore import KVStoreApplication
    from txflow_tpu.node.node import Node, NodeConfig
    from txflow_tpu.types.priv_validator import MockPV
    from txflow_tpu.types.validator import Validator, ValidatorSet
    from txflow_tpu.utils.config import test_config

    script = tmp_path / "child_node.py"
    script.write_text(CHILD_SCRIPT)
    env = dict(
        os.environ,
        TXFLOW_REPO=REPO,
        JAX_PLATFORMS="cpu",
    )
    child = subprocess.Popen(
        [sys.executable, str(script)],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
    )
    parent = None
    try:
        line = child.stdout.readline()
        assert line, child.stderr.read()
        addrs = json.loads(line)

        # parent process: validator index 0 of the same 2-validator set
        pvs = [MockPV(hashlib.sha256(b"mp-val%d" % i).digest()) for i in range(2)]
        vs = ValidatorSet(
            [Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs]
        )
        by_addr = {pv.get_address(): pv for pv in pvs}
        parent = Node(
            node_id="mp-parent",
            chain_id="txflow-mp",
            val_set=vs,
            app=KVStoreApplication(),
            priv_val=by_addr[vs.get_by_index(0).address],
            node_config=NodeConfig(
                config=test_config(),
                use_device_verifier=False,
                enable_consensus=False,
                node_key_seed=hashlib.sha256(b"mp-key-parent").digest(),
            ),
        )
        parent.start()
        peer = parent.switch.dial_tcp(*addrs["p2p"])
        # authenticated link: the peer id is the child's verified key address
        assert peer.node_id != parent.switch.node_id

        # external client submits through the CHILD's RPC; quorum (2/2)
        # requires both processes to sign and cross-gossip votes
        tx = b"mp-k=v"
        res = rpc_get(addrs["rpc"], '/broadcast_tx?tx="mp-k=v"')["result"]
        tx_hash = hashlib.sha256(tx).hexdigest().upper()
        assert res["hash"] == tx_hash

        sub = rpc_get(
            addrs["rpc"], f"/subscribe_tx?hash={tx_hash}&timeout=30"
        )["result"]
        assert sub["committed"] is True, sub

        # ... and the PARENT process committed it too, off its own quorum
        deadline = time.time() + 30
        while time.time() < deadline and not parent.is_committed(tx):
            time.sleep(0.1)
        assert parent.is_committed(tx)
        votes = parent.tx_store.load_tx_votes(tx_hash)
        assert votes and len(votes) == 2  # both processes' signatures
    finally:
        if parent is not None:
            parent.stop()
        child.terminate()
        try:
            child.wait(timeout=10)
        except subprocess.TimeoutExpired:
            child.kill()
