"""TxVoteSet quorum semantics (mirrors reference types/vote_set_test.go)."""

import pytest

from txflow_tpu.crypto.hash import tx_hash, tx_key
from txflow_tpu.types import (
    ErrVoteInvalidSignature,
    ErrVoteInvalidValidatorIndex,
    ErrVoteNonDeterministicSignature,
    MockPV,
    TxVote,
    TxVoteSet,
    Validator,
    ValidatorSet,
)

CHAIN_ID = "test_chain"


def rand_vote_set(n: int, power: int = 1):
    pvs = [MockPV() for _ in range(n)]
    vals = [Validator.from_pub_key(pv.get_pub_key(), power) for pv in pvs]
    val_set = ValidatorSet(vals)
    # Order signers to match validator-set (address-sorted) order.
    pvs.sort(key=lambda pv: pv.get_address())
    tx = b"the tx"
    vote_set = TxVoteSet(CHAIN_ID, 1, tx_hash(tx), tx_key(tx), val_set)
    return vote_set, val_set, pvs, tx


def signed_vote(pv: MockPV, tx: bytes, height: int = 1) -> TxVote:
    vote = TxVote(
        height=height,
        tx_hash=tx_hash(tx),
        tx_key=tx_key(tx),
        validator_address=pv.get_address(),
    )
    pv.sign_tx_vote(CHAIN_ID, vote)
    return vote


def test_add_vote():
    vote_set, _, pvs, tx = rand_vote_set(10)
    pv = pvs[0]
    assert vote_set.get_by_address(pv.get_address()) is None
    assert not vote_set.has_two_thirds_majority()

    added, err = vote_set.add_vote(signed_vote(pv, tx))
    assert added and err is None
    assert vote_set.get_by_address(pv.get_address()) is not None
    assert vote_set.stake() == 1
    assert not vote_set.has_two_thirds_majority()


def test_duplicate_vote_silently_ignored():
    vote_set, _, pvs, tx = rand_vote_set(4)
    vote = signed_vote(pvs[0], tx)
    added, err = vote_set.add_vote(vote)
    assert added and err is None
    added, err = vote_set.add_vote(vote.copy())
    assert not added and err is None  # exact duplicate: no error
    assert vote_set.stake() == 1


def test_non_deterministic_signature_rejected():
    vote_set, _, pvs, tx = rand_vote_set(4)
    v1 = signed_vote(pvs[0], tx)
    added, _ = vote_set.add_vote(v1)
    assert added
    # Same validator, different timestamp => different signature.
    v2 = TxVote(
        height=1,
        tx_hash=tx_hash(tx),
        tx_key=tx_key(tx),
        timestamp_ns=v1.timestamp_ns + 1,
        validator_address=pvs[0].get_address(),
    )
    pvs[0].sign_tx_vote(CHAIN_ID, v2)
    assert v2.signature != v1.signature
    added, err = vote_set.add_vote(v2)
    assert not added
    assert isinstance(err, ErrVoteNonDeterministicSignature)
    assert vote_set.stake() == 1  # first-signature-wins, not double counted


def test_non_validator_rejected():
    vote_set, _, _, tx = rand_vote_set(4)
    outsider = MockPV()
    added, err = vote_set.add_vote(signed_vote(outsider, tx))
    assert not added
    assert isinstance(err, ErrVoteInvalidValidatorIndex)


def test_bad_signature_rejected():
    vote_set, _, pvs, tx = rand_vote_set(4)
    vote = signed_vote(pvs[0], tx)
    vote.signature = bytes(64)
    added, err = vote_set.add_vote(vote)
    assert not added
    assert isinstance(err, ErrVoteInvalidSignature)
    # Signature by the wrong key.
    vote = signed_vote(pvs[0], tx)
    vote.signature = MockPV().sign_bytes_raw(vote.sign_bytes(CHAIN_ID))
    added, err = vote_set.add_vote(vote)
    assert not added
    assert isinstance(err, ErrVoteInvalidSignature)


def test_two_thirds_majority_equal_power():
    # 10 validators, power 1 each: quorum = 10*2//3 + 1 = 7.
    vote_set, _, pvs, tx = rand_vote_set(10)
    for i in range(6):
        added, _ = vote_set.add_vote(signed_vote(pvs[i], tx))
        assert added
    assert not vote_set.has_two_thirds_majority()
    assert not vote_set.has_two_thirds_any()
    added, _ = vote_set.add_vote(signed_vote(pvs[6], tx))
    assert added
    assert vote_set.has_two_thirds_majority()
    assert vote_set.has_two_thirds_any()
    assert vote_set.is_commit()


def test_two_thirds_majority_weighted():
    # Powers 1,1,1,10 => total 13, quorum = 13*2//3+1 = 9: only the big
    # validator matters.
    pvs = [MockPV() for _ in range(4)]
    pvs.sort(key=lambda pv: pv.get_address())
    powers = [1, 1, 1, 10]
    vals = [
        Validator.from_pub_key(pv.get_pub_key(), p) for pv, p in zip(pvs, powers)
    ]
    val_set = ValidatorSet(vals)
    tx = b"weighted"
    vote_set = TxVoteSet(CHAIN_ID, 1, tx_hash(tx), tx_key(tx), val_set)
    by_addr = {pv.get_address(): (pv, p) for pv, p in zip(pvs, powers)}

    small = [pv for pv, p in by_addr.values() if p == 1]
    big = next(pv for pv, p in by_addr.values() if p == 10)
    for pv in small:
        vote_set.add_vote(signed_vote(pv, tx))
    assert vote_set.stake() == 3
    assert not vote_set.has_two_thirds_majority()
    vote_set.add_vote(signed_vote(big, tx))
    assert vote_set.stake() == 13
    assert vote_set.has_two_thirds_majority()


def test_make_commit():
    vote_set, _, pvs, tx = rand_vote_set(4)
    with pytest.raises(RuntimeError):
        vote_set.make_commit()
    for pv in pvs[:3]:  # quorum = 4*2//3+1 = 3
        vote_set.add_vote(signed_vote(pv, tx))
    assert vote_set.has_two_thirds_majority()
    commit = vote_set.make_commit()
    assert commit.tx_hash == tx_hash(tx)
    assert len(commit.commits) == 3
    assert commit.height() == 1
    # Commit sigs are real verifiable votes.
    for cs in commit.commits:
        vote = cs.to_vote()
        _, val = vote_set.val_set.get_by_address(vote.validator_address)
        assert vote.verify(CHAIN_ID, val.pub_key) is None


def test_add_verified_matches_add_vote_decisions():
    # The device-batch path (verify in batch, then add_verified_vote) must make
    # identical decisions to the scalar add_vote path.
    vote_set_a, _, pvs, tx = rand_vote_set(7)
    vote_set_b = TxVoteSet(
        CHAIN_ID, 1, tx_hash(tx), tx_key(tx), vote_set_a.val_set
    )
    votes = [signed_vote(pv, tx) for pv in pvs]
    votes += [votes[0].copy()]  # duplicate
    for v in votes:
        added_a, err_a = vote_set_a.add_vote(v)
        # Simulate the batched path: signature pre-verified.
        _, val = vote_set_b.val_set.get_by_address(v.validator_address)
        assert v.verify(CHAIN_ID, val.pub_key) is None
        added_b, err_b = vote_set_b.add_verified_vote(v)
        assert added_a == added_b
        assert (err_a is None) == (err_b is None)
    assert vote_set_a.stake() == vote_set_b.stake()
    assert vote_set_a.has_two_thirds_majority() == vote_set_b.has_two_thirds_majority()


def test_quorum_accessor_quirks():
    # total_stake() mirrors the reference's odd 2/3-of-total return.
    vote_set, val_set, pvs, tx = rand_vote_set(10)
    assert vote_set.total_stake() == val_set.total_voting_power() * 2 // 3
    for pv in pvs:
        vote_set.add_vote(signed_vote(pv, tx))
    assert vote_set.has_all()
