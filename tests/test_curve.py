"""Batched curve ops vs the scalar pure-python golden model."""

import random

import numpy as np
import jax.numpy as jnp

from txflow_tpu.crypto import ed25519 as host_ed
from txflow_tpu.ops import curve, fe

rng = random.Random(0xC0)


def rand_point():
    k = rng.randrange(host_ed.L)
    return host_ed.scalar_mult(k, host_ed.BASE)


def ext_to_limbs(points):
    """List of python-int extended points -> batched limb coords."""
    coords = []
    for c in range(4):
        coords.append(np.stack([fe.int_to_limbs(p[c]) for p in points]))
    return tuple(jnp.asarray(c) for c in coords)


def assert_points_equal(dev_ext, host_points):
    X, Y, Z, _ = (np.asarray(c) for c in dev_ext)
    for i, hp in enumerate(host_points):
        x, y, z = fe.limbs_to_int(X[i]), fe.limbs_to_int(Y[i]), fe.limbs_to_int(Z[i])
        hx, hy, hz, _ = hp
        assert (x * hz - hx * z) % host_ed.P == 0
        assert (y * hz - hy * z) % host_ed.P == 0


def test_double():
    pts = [rand_point() for _ in range(8)] + [host_ed.IDENTITY]
    out = curve.ext_double(ext_to_limbs(pts))
    assert_points_equal(out, [host_ed.point_double(p) for p in pts])


def test_pniels_add():
    ps = [rand_point() for _ in range(8)]
    qs = [rand_point() for _ in range(8)]
    tables = np.stack([curve.build_pniels_table(q) for q in qs])  # [8,16,4,32]
    # entry 1 of each table is 1*q in PNiels form
    n = tuple(jnp.asarray(tables[:, 1, c, :]) for c in range(4))
    out = curve.pniels_add(ext_to_limbs(ps), n)
    assert_points_equal(out, [host_ed.point_add(p, q) for p, q in zip(ps, qs)])


def test_pniels_add_identity():
    ps = [rand_point() for _ in range(4)]
    tables = np.stack([curve.build_pniels_table(p) for p in ps])
    n = tuple(jnp.asarray(tables[:, 0, c, :]) for c in range(4))  # entry 0 = id
    out = curve.pniels_add(ext_to_limbs(ps), n)
    assert_points_equal(out, ps)


def test_table_entries():
    q = rand_point()
    t = curve.build_pniels_table(q)
    for k in range(16):
        kq = host_ed.scalar_mult(k, q)
        ypx, ymx = fe.limbs_to_int(t[k, 0]), fe.limbs_to_int(t[k, 1])
        if k == 0:
            assert (ypx, ymx) == (1, 1)
            continue
        zinv = pow(kq[2], host_ed.P - 2, host_ed.P)
        xa, ya = kq[0] * zinv % host_ed.P, kq[1] * zinv % host_ed.P
        assert ypx == (ya + xa) % host_ed.P
        assert ymx == (ya - xa) % host_ed.P


def test_double_scalar_mul_and_encode():
    B = 6
    ss = [rng.randrange(host_ed.L) for _ in range(B)]
    hs = [rng.randrange(host_ed.L) for _ in range(B)]
    As = [rand_point() for _ in range(B)]
    a_tables = jnp.asarray(np.stack([curve.build_pniels_table(a) for a in As]))
    s_nib = jnp.asarray(np.stack([curve.scalar_to_nibbles(s) for s in ss]))
    h_nib = jnp.asarray(np.stack([curve.scalar_to_nibbles(h) for h in hs]))
    out = curve.double_scalar_mul(s_nib, h_nib, jnp.asarray(curve.BASE_TABLE), a_tables)
    want = [
        host_ed.point_add(
            host_ed.scalar_mult(s, host_ed.BASE), host_ed.scalar_mult(h, a)
        )
        for s, h, a in zip(ss, hs, As)
    ]
    assert_points_equal(out, want)
    # encode path: canonical y + x parity must match host compression
    y, par = curve.ext_encode(out)
    for i, w in enumerate(want):
        enc = host_ed.point_compress(w)
        want_y = int.from_bytes(enc, "little") & ((1 << 255) - 1)
        assert fe.limbs_to_int(np.asarray(y)[i]) == want_y
        assert int(np.asarray(par)[i]) == enc[31] >> 7


def test_scalar_edge_cases():
    # s=0, h=0 -> identity; encode(identity) = (y=1, parity 0)
    zero = jnp.zeros((1, curve.NWINDOWS), jnp.int32)
    tab = jnp.asarray(curve.build_pniels_table(rand_point()))[None]
    out = curve.double_scalar_mul(zero, zero, jnp.asarray(curve.BASE_TABLE), tab)
    y, par = curve.ext_encode(out)
    assert fe.limbs_to_int(np.asarray(y)[0]) == 1
    assert int(np.asarray(par)[0]) == 0
