"""Property/fuzz tests: codec round-trips and parser crash-resistance.

The wire surface (vote decode, block decode, WAL frames, native prep) is
attacker-facing — every byte string a peer can send must either decode to
a value that re-encodes canonically or raise ValueError; nothing may
crash with any other exception, loop, or mis-round-trip. Hypothesis
drives both structured round-trips and byte-level mutations.
"""

import conftest  # noqa: F401

import hashlib

import pytest

# hypothesis (requirements-dev.txt) is preferred: full strategies +
# shrinking. Without it, the deterministic fallback shim keeps the fuzz
# bodies running in tier-1 (seeded examples, no shrinking) instead of
# skipping the whole file; the importorskip is the last-resort guard if
# the shim itself cannot load.
try:
    from hypothesis import given, settings, strategies as st
except ImportError:
    try:
        from _hypothesis_fallback import given, settings, st
    except ImportError:  # pragma: no cover
        pytest.importorskip("hypothesis")

from txflow_tpu import native
from txflow_tpu.codec import amino
from txflow_tpu.types.tx_vote import TxVote, decode_tx_vote, encode_tx_vote

ADDR = st.binary(min_size=20, max_size=20)
SIG = st.binary(min_size=64, max_size=64)
HASH_HEX = st.text(alphabet="0123456789ABCDEF", min_size=64, max_size=64)


@st.composite
def votes(draw):
    return TxVote(
        height=draw(st.integers(min_value=0, max_value=2**62)),
        tx_hash=draw(HASH_HEX),
        tx_key=draw(st.binary(min_size=32, max_size=32)),
        timestamp_ns=draw(st.integers(min_value=0, max_value=2**62)),
        validator_address=draw(ADDR),
        signature=draw(SIG),
    )


@given(votes())
@settings(max_examples=300, deadline=None)
def test_vote_roundtrip_and_cache(v):
    wire = encode_tx_vote(v)
    d = decode_tx_vote(wire)
    assert (
        d.height,
        d.tx_hash,
        d.tx_key,
        d.timestamp_ns,
        d.validator_address,
        d.signature,
    ) == (
        v.height,
        v.tx_hash,
        v.tx_key,
        v.timestamp_ns,
        v.validator_address,
        v.signature,
    )
    # canonical input must be cached AND re-encode identically
    assert d._wire_cache == wire
    assert encode_tx_vote(d) == wire


@given(votes(), st.data())
@settings(max_examples=300, deadline=None)
def test_vote_decode_never_crashes_on_mutation(v, data):
    """Arbitrary byte mutations: decode either raises ValueError or
    returns a vote whose re-encode is canonical (never the mutated bytes
    unless they equal the canonical encoding)."""
    wire = bytearray(encode_tx_vote(v))
    n_mut = data.draw(st.integers(min_value=1, max_value=6))
    for _ in range(n_mut):
        i = data.draw(st.integers(min_value=0, max_value=len(wire) - 1))
        wire[i] = data.draw(st.integers(min_value=0, max_value=255))
    raw = bytes(wire)
    try:
        d = decode_tx_vote(raw)
    except ValueError:
        return
    except UnicodeDecodeError:
        return  # tx_hash is a str field; invalid utf-8 is a decode error
    cached = d._wire_cache  # BEFORE encode: encode itself populates it
    re = encode_tx_vote(d)
    if cached is not None:
        # decode only ever caches input bytes proven canonical
        assert cached == raw == re


@given(st.binary(max_size=300))
@settings(max_examples=300, deadline=None)
def test_vote_decode_arbitrary_bytes(raw):
    try:
        d = decode_tx_vote(raw)
    except (ValueError, UnicodeDecodeError):
        return
    encode_tx_vote(d)  # whatever decoded must re-encode without error


@given(st.integers(min_value=0, max_value=2**64 - 1))
@settings(max_examples=200, deadline=None)
def test_uvarint_roundtrip(n):
    buf = amino.uvarint(n)
    r = amino.AminoReader(buf)
    assert r.read_uvarint() == n and r.eof()


@given(st.integers(min_value=-(2**63), max_value=2**63 - 1))
@settings(max_examples=200, deadline=None)
def test_time_body_roundtrip(ns):
    body = amino.encode_time_body(ns)
    assert amino.decode_time_body(body) == ns


@given(st.binary(max_size=200), st.binary(min_size=64, max_size=64),
       st.binary(min_size=32, max_size=32))
@settings(max_examples=150, deadline=None)
def test_native_prep_matches_python_on_random_inputs(msg, sig, pub):
    """Random (msg, sig, pub): native and Python prep agree bit-for-bit —
    including non-curve pubkeys and random S values straddling L."""
    if not native.available():
        return
    import numpy as np

    from txflow_tpu.ops import ed25519_batch

    epoch = ed25519_batch.EpochTables([pub])
    a = ed25519_batch._prepare_compact_native([msg], [sig], np.array([0]), epoch)
    b = ed25519_batch._prepare_compact_py([msg], [sig], np.array([0]), epoch)
    np.testing.assert_array_equal(a.pre_ok, b.pre_ok)
    np.testing.assert_array_equal(a.s_nibbles, b.s_nibbles)
    np.testing.assert_array_equal(a.h_nibbles, b.h_nibbles)
    np.testing.assert_array_equal(a.r_y, b.r_y)
    np.testing.assert_array_equal(a.r_sign, b.r_sign)


@given(st.binary(max_size=400))
@settings(max_examples=200, deadline=None)
def test_block_decode_arbitrary_bytes(raw):
    from txflow_tpu.types.block import decode_block, encode_block

    try:
        b = decode_block(raw)
    except (ValueError, UnicodeDecodeError):
        return
    encode_block(b)


@given(st.binary(max_size=300))
@settings(max_examples=200, deadline=None)
def test_block_vote_decode_arbitrary_bytes(raw):
    from txflow_tpu.types.block_vote import decode_block_vote, encode_block_vote

    try:
        v = decode_block_vote(raw)
    except (ValueError, UnicodeDecodeError):
        return
    encode_block_vote(v)


@given(st.binary(max_size=300))
@settings(max_examples=200, deadline=None)
def test_evidence_decode_arbitrary_bytes(raw):
    from txflow_tpu.types.evidence import decode_evidence, encode_evidence

    try:
        ev = decode_evidence(raw)
    except (ValueError, UnicodeDecodeError):
        return
    encode_evidence(ev)


@given(st.binary(max_size=300))
@settings(max_examples=200, deadline=None)
def test_consensus_wal_frame_arbitrary_bytes(raw):
    """WAL frames come from our own disk, but the decode path is shared
    with catchup replay of possibly-torn logs: ValueError or a decodable
    message, never another exception."""
    from txflow_tpu.consensus.wal import decode_wal_message

    try:
        decode_wal_message(raw)
    except (ValueError, UnicodeDecodeError):
        return


# ---------------------------------------------------------------- native
# batch decoder parity: the C field locator must reproduce the Python
# decoder EXACTLY — accept-set, field values, wire-cache decision.


@given(st.binary(max_size=400))
@settings(max_examples=400, deadline=None)
def test_native_batch_decode_parity_fuzz(data):
    from txflow_tpu import native
    from txflow_tpu.types.tx_vote import decode_tx_vote, decode_tx_votes_many

    if not native.available():
        return
    try:
        expect = decode_tx_vote(data)
        err = None
    except ValueError:
        expect, err = None, True
    try:
        # 16 copies: below that decode_tx_votes_many's crossover takes the
        # pure-Python branch and the native decoder would never run,
        # making this parity test vacuous (r5 review)
        got = decode_tx_votes_many([data] * 16)[0]
        gerr = None
    except ValueError:
        got, gerr = None, True
    assert bool(err) == bool(gerr), (data.hex(), err, gerr)
    if expect is not None:
        assert got.height == expect.height
        assert got.tx_hash == expect.tx_hash
        assert got.tx_key == expect.tx_key
        assert got.timestamp_ns == expect.timestamp_ns
        assert got.validator_address == expect.validator_address
        assert got.signature == expect.signature
        assert got._wire_cache == expect._wire_cache


def test_native_batch_decode_roundtrip_real_votes():
    import hashlib

    from txflow_tpu.types import TxVote, encode_tx_vote
    from txflow_tpu.types.priv_validator import MockPV
    from txflow_tpu.types.tx_vote import decode_tx_votes_many
    from txflow_tpu import native

    if not native.available():
        import pytest

        pytest.skip("no C compiler")
    pv = MockPV()
    segs, votes = [], []
    for i in range(32):
        key = hashlib.sha256(b"nd-%d" % i).digest()
        v = TxVote(height=i % 3, tx_hash=key.hex().upper(), tx_key=key,
                   validator_address=pv.get_address())
        pv.sign_tx_vote("nd-chain", v)
        votes.append(v)
        segs.append(encode_tx_vote(v))
    got = decode_tx_votes_many(segs)
    for v, g, seg in zip(votes, got, segs):
        assert (g.height, g.tx_hash, g.tx_key, g.timestamp_ns,
                g.validator_address, g.signature) == (
            v.height, v.tx_hash, v.tx_key, v.timestamp_ns,
            v.validator_address, v.signature)
        assert g._wire_cache == seg  # canonical: cache primed
