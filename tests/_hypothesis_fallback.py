"""Deterministic mini-harness standing in for hypothesis.

tests/test_fuzz_codec.py prefers the real hypothesis (listed in
requirements-dev.txt): full strategy library, shrinking, example
database. On boxes without it, this shim keeps the fuzz bodies RUNNING
in tier-1 instead of skipping — seeded pseudo-random examples, no
shrinking, same test code. Only the exact API surface the fuzz file uses
is implemented (given/settings + binary/text/integers/composite/data);
anything else raises so accidental divergence is loud.

Determinism: every test draws from ``random.Random(sha256(test name))``,
so a failure reproduces by re-running the test — the property the
hypothesis example database provides, minus shrinking.
"""

from __future__ import annotations

import functools
import hashlib
import random

# fallback runs trade example count for tier-1 wall time; the real
# hypothesis honors the test's own max_examples
_MAX_EXAMPLES_CAP = 120


class Strategy:
    def __init__(self, draw_fn):
        self._draw = draw_fn


def binary(min_size: int = 0, max_size: int | None = None) -> Strategy:
    hi = max_size if max_size is not None else max(min_size, 64)

    def draw(rng: random.Random):
        n = rng.randint(min_size, hi)
        return rng.getrandbits(8 * n).to_bytes(n, "big") if n else b""

    return Strategy(draw)


def integers(min_value=None, max_value=None) -> Strategy:
    lo = min_value if min_value is not None else -(2**63)
    hi = max_value if max_value is not None else 2**63 - 1

    def draw(rng: random.Random):
        # bias toward the edges: codec bugs live at 0 / max / length caps
        r = rng.random()
        if r < 0.1:
            return lo
        if r < 0.2:
            return hi
        return rng.randint(lo, hi)

    return Strategy(draw)


def text(alphabet: str, min_size: int = 0, max_size: int | None = None) -> Strategy:
    hi = max_size if max_size is not None else max(min_size, 32)

    def draw(rng: random.Random):
        n = rng.randint(min_size, hi)
        return "".join(rng.choice(alphabet) for _ in range(n))

    return Strategy(draw)


def composite(fn):
    @functools.wraps(fn)
    def make(*args, **kwargs):
        def draw_value(rng: random.Random):
            return fn(lambda s: s._draw(rng), *args, **kwargs)

        return Strategy(draw_value)

    return make


class _Data:
    def __init__(self, rng: random.Random):
        self._rng = rng

    def draw(self, strategy: Strategy):
        return strategy._draw(self._rng)


def data() -> Strategy:
    return Strategy(lambda rng: _Data(rng))


def settings(max_examples: int = 100, deadline=None):
    def deco(fn):
        fn._fallback_max_examples = max_examples
        return fn

    return deco


def given(*strategies: Strategy):
    def deco(fn):
        n = min(
            getattr(fn, "_fallback_max_examples", 100), _MAX_EXAMPLES_CAP
        )

        # deliberately NOT functools.wraps: pytest would introspect the
        # wrapped signature via __wrapped__ and demand fixtures for the
        # strategy parameters
        def runner():
            seed = hashlib.sha256(fn.__name__.encode()).digest()
            rng = random.Random(int.from_bytes(seed[:8], "big"))
            for i in range(n):
                drawn = tuple(s._draw(rng) for s in strategies)
                try:
                    fn(*drawn)
                except Exception as e:
                    raise AssertionError(
                        f"{fn.__name__} failed on fallback example "
                        f"{i}/{n}: args={drawn!r}"
                    ) from e

        runner.__name__ = fn.__name__
        runner.__doc__ = fn.__doc__
        return runner

    return deco


class _St:
    binary = staticmethod(binary)
    integers = staticmethod(integers)
    text = staticmethod(text)
    composite = staticmethod(composite)
    data = staticmethod(data)

    def __getattr__(self, name):  # loud on unimplemented strategies
        raise AttributeError(
            f"hypothesis fallback shim has no strategy {name!r} — extend "
            "tests/_hypothesis_fallback.py or install hypothesis"
        )


st = _St()
