"""Out-of-process ABCI: the app runs behind a socket server — in-process
for protocol tests, in a REAL subprocess for the end-to-end commit test —
and the node drives it through RemoteAppConns (the process boundary the
reference opens at node/node.go:576 createAndStartProxyAppConns).
"""

import conftest  # noqa: F401

import hashlib
import subprocess
import sys
import time

import pytest

from txflow_tpu.abci import wire
from txflow_tpu.abci.client import RemoteAppConns
from txflow_tpu.abci.kvstore import KVStoreApplication
from txflow_tpu.abci.server import ABCIServer
from txflow_tpu.abci.types import (
    RequestBeginBlock,
    RequestEndBlock,
    ResponseCheckTx,
    ResponseDeliverTx,
    ResponseEndBlock,
    ResponseInfo,
    ValidatorUpdate,
)


def test_wire_roundtrip():
    """Every message kind survives encode->decode both directions."""
    reqs = [
        (wire.ECHO, {"raw": b"hello"}),
        (wire.FLUSH, {}),
        (wire.INFO, {}),
        (wire.CHECK_TX, {"raw": b"k=v"}),
        (wire.DELIVER_TX, {"raw": b"\x00\xff" * 10}),
        (wire.COMMIT, {}),
        (wire.END_BLOCK, {"height": 42}),
        (wire.QUERY, {"path": "/store", "raw": b"key"}),
    ]
    for kind, kw in reqs:
        enc = wire.encode_request(kind, **kw)
        k2, fields = wire.decode_request(enc)
        assert k2 == kind
        for key, val in kw.items():
            assert fields[key] == val

    enc = wire.encode_request(
        wire.INIT_CHAIN, validators=[ValidatorUpdate(b"\x01" * 32, 10)]
    )
    _, fields = wire.decode_request(enc)
    assert fields["validators"][0].pub_key == b"\x01" * 32
    assert fields["validators"][0].power == 10

    req = RequestBeginBlock(
        hash=b"\xaa" * 20, height=7, proposer_address=b"\xbb" * 20,
        byzantine_validators=[(b"\xcc" * 20, 3)],
    )
    _, fields = wire.decode_request(wire.encode_request(wire.BEGIN_BLOCK, req=req))
    got = fields["req"]
    assert (got.hash, got.height, got.proposer_address) == (
        req.hash, req.height, req.proposer_address
    )
    assert got.byzantine_validators == [(b"\xcc" * 20, 3)]

    # responses
    pairs = [
        (wire.CHECK_TX, ResponseCheckTx(code=3, data=b"d", log="l", gas_wanted=9)),
        (wire.DELIVER_TX, ResponseDeliverTx(code=0, data=b"x", tags=[(b"k", b"v")])),
        (wire.END_BLOCK, ResponseEndBlock(validator_updates=[ValidatorUpdate(b"\x02" * 32, 5)])),
        (wire.INFO, ResponseInfo(data="kv", version="1", last_block_height=4, last_block_app_hash=b"h")),
    ]
    for kind, res in pairs:
        k2, got = wire.decode_response(wire.encode_response(kind, res))
        assert k2 == kind
        assert type(got) is type(res)

    k2, err = wire.decode_response(wire.encode_response(wire.EXCEPTION, "boom"))
    assert k2 == wire.EXCEPTION and isinstance(err, RuntimeError)

    # malformed input raises ValueError, never IndexError (peer-facing)
    for bad in (b"", bytes([wire.INIT_CHAIN]) + b"\xff\xff\xff\xff\xff\xff",
                bytes([99]) + b"x"):
        with pytest.raises(ValueError):
            wire.decode_request(bad)


def test_socket_client_pipelines_and_flush_fence():
    """Async deliveries pipeline on the wire; flush resolves them in
    order; sync calls fence implicitly; app exceptions surface remotely."""

    class Boomy(KVStoreApplication):
        def query(self, path, data):
            if path == "/boom":
                raise RuntimeError("kaboom")
            return super().query(path, data)

    srv = ABCIServer(Boomy())
    srv.start()
    try:
        conns = RemoteAppConns(f"{srv.addr[0]}:{srv.addr[1]}")
        assert conns.consensus.echo(b"ping") == b"ping"

        results = [conns.consensus.deliver_tx_async(b"k%d=v%d" % (i, i)) for i in range(50)]
        conns.consensus.flush()
        assert all(r.value.code == 0 for r in results)
        # an eager .value read (in-process proxy habit) forces the fence
        # itself instead of returning None — drop-in parity
        eager = conns.consensus.deliver_tx_async(b"kx=vx")
        assert eager.value.code == 0
        commit = conns.consensus.commit_sync()
        assert commit.data  # kvstore app hash

        q = conns.query.query_sync("/store", b"k7")
        assert q.value == b"v7"

        with pytest.raises(RuntimeError, match="kaboom"):
            conns.query.query_sync("/boom", b"")
        # connection stays serviceable after a remote exception
        assert conns.query.query_sync("/store", b"k8").value == b"v8"

        # a LARGE pipelined burst must not deadlock the socket pair (the
        # server's dedicated writer thread exists exactly for this: a
        # read-then-write loop wedges once both directions' buffers fill)
        big = [
            conns.consensus.deliver_tx_async(b"big%d=%s" % (i, b"x" * 200))
            for i in range(5000)
        ]
        conns.consensus.flush()
        assert all(r.value.code == 0 for r in big)
        conns.close()
    finally:
        srv.stop()


def test_node_commits_through_subprocess_app():
    """End-to-end across a REAL process boundary: kvstore in a subprocess,
    a node fast-path-commits txs through it, state queries come back over
    the query connection."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "txflow_tpu.abci.server", "--app", "kvstore",
         "--addr", "127.0.0.1:0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=str(__import__("pathlib").Path(__file__).resolve().parents[1]),
    )
    try:
        line = proc.stdout.readline()
        assert "serving kvstore on" in line, line
        addr = line.strip().rsplit(" ", 1)[-1]

        from txflow_tpu.node.node import Node, NodeConfig
        from txflow_tpu.types.priv_validator import MockPV
        from txflow_tpu.types.validator import Validator, ValidatorSet
        from txflow_tpu.types import TxVote
        from txflow_tpu.utils.config import test_config

        pvs = [MockPV(hashlib.sha256(b"abci-%d" % i).digest()) for i in range(4)]
        vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs])
        node = Node(
            node_id="n0", chain_id="abci-chain", val_set=vs, app=addr,
            priv_val=pvs[0],
            node_config=NodeConfig(
                config=test_config(), use_device_verifier=False,
                sign_votes=False, enable_consensus=False,
            ),
        )
        assert node.app is None  # the app lives in the other process
        node.start()
        try:
            txs = [b"sub-%d=v%d" % (i, i) for i in range(20)]
            for tx in txs:
                node.mempool.check_tx(tx)
            for tx in txs:
                key = hashlib.sha256(tx).digest()
                for pv in pvs:
                    v = TxVote(height=0, tx_hash=key.hex().upper(), tx_key=key,
                               validator_address=pv.get_address())
                    pv.sign_tx_vote("abci-chain", v)
                    node.tx_vote_pool.check_tx(v)
            deadline = time.monotonic() + 60
            for tx in txs:
                h = hashlib.sha256(tx).hexdigest().upper()
                while not node.tx_store.has_tx(h):
                    assert time.monotonic() < deadline, "commit timeout"
                    time.sleep(0.01)
            # the app state lives in the subprocess: query round trip
            res = node.proxy_app.query.query_sync("/store", b"sub-3")
            assert res.value == b"v3"
            assert node.txflow.app_hash  # commit hashes flowed back
        finally:
            node.stop()
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_exception_mid_drain_keeps_stream_aligned():
    """An app EXCEPTION for one pipelined request must not desync the
    connection: later pipelined responses still resolve, the fence's own
    frame is consumed, and the NEXT call reads its own response — not a
    stale frame (r4 advisor: _drain_pending previously abandoned the
    remaining responses in the socket)."""

    class Exploding(KVStoreApplication):
        def deliver_tx(self, tx: bytes) -> ResponseDeliverTx:
            if tx.startswith(b"boom"):
                raise RuntimeError("mid-pipeline kaboom")
            return super().deliver_tx(tx)

    srv = ABCIServer(Exploding())
    srv.start()
    try:
        conns = RemoteAppConns(f"{srv.addr[0]}:{srv.addr[1]}")
        c = conns.consensus
        rs = [
            c.deliver_tx_async(b"a=1"),
            c.deliver_tx_async(b"boom"),
            c.deliver_tx_async(b"b=2"),
            c.deliver_tx_async(b"c=3"),
        ]
        with pytest.raises(RuntimeError, match="mid-pipeline kaboom"):
            c.flush()
        # entries after the failed one were still drained and resolved
        assert rs[0].value.code == 0
        assert rs[2].value.code == 0
        assert rs[3].value.code == 0
        # the failed entry re-raises its recorded error on read
        with pytest.raises(RuntimeError, match="mid-pipeline kaboom"):
            _ = rs[1].value
        # and the connection is ALIGNED: a fresh sync call gets its own
        # response, not the leftover of an unread frame
        assert c.deliver_tx_async(b"d=4").value.code == 0
        commit = c.commit_sync()
        assert commit.data
        conns.close()
    finally:
        srv.stop()


def test_async_callback_fires_at_fence_without_forcing_flush():
    """Registering a callback must not itself force a flush round-trip;
    callbacks fire in submit order when a fence resolves the entries
    (reference ReqRes callback-at-flush semantics)."""
    srv = ABCIServer(KVStoreApplication())
    srv.start()
    try:
        conns = RemoteAppConns(f"{srv.addr[0]}:{srv.addr[1]}")
        seen = []
        for i in range(5):
            conns.mempool.check_tx_async(
                b"cb%d=v" % i, callback=lambda r, i=i: seen.append((i, r.code))
            )
        assert seen == []  # nothing fired yet: no fence has run
        conns.mempool.flush()
        assert seen == [(i, 0) for i in range(5)]
        conns.close()
    finally:
        srv.stop()


def test_check_tx_fast_path_flag_crosses_the_socket():
    """An out-of-process app's block-only verdict (fast_path=False) must
    survive the wire round trip — losing it would let validators fast-
    sign EndBlock-coupled txs (wire.py uv(block_only) field)."""

    class Flagger(KVStoreApplication):
        def check_tx(self, tx: bytes) -> ResponseCheckTx:
            if tx.startswith(b"block-only:"):
                return ResponseCheckTx(gas_wanted=7, fast_path=False)
            return ResponseCheckTx(gas_wanted=1)

    srv = ABCIServer(Flagger())
    srv.start()
    try:
        conns = RemoteAppConns(f"{srv.addr[0]}:{srv.addr[1]}")
        r1 = conns.mempool.check_tx_sync(b"block-only:val")
        assert r1.fast_path is False and r1.gas_wanted == 7
        r2 = conns.mempool.check_tx_sync(b"normal=1")
        assert r2.fast_path is True and r2.gas_wanted == 1
        conns.close()
    finally:
        srv.stop()
