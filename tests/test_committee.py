"""Sublinear certificates: per-epoch committee sampling + batched
certificate verification (committee/), driven at three levels:

- pure units: seed domain separation, sampling determinism /
  stake-proportionality / safety floors, the vote-height -> epoch
  committee mapping, and the circuit-breaker threshold rescale
  (health/byzantine.py committee_rescale) with its pinned trip points;
- engine: a committee swap at an epoch boundary revalidates in-flight
  vote sets against the new committee, never mutates a latched
  certificate, and on the device path restages with ZERO new compiled
  shapes (the committee analog of test_epoch's rotation contract);
  BatchCertVerifier's fused one-call path is pinned decision-for-
  decision against the scalar golden path;
- LocalNet drills (tier-1): a committee rotating at an epoch boundary
  mid-flood with zero admitted-tx loss, and the slash bridge — an
  equivocating committee member is slashed out and the next epoch's
  sample excludes it.
"""

import hashlib
import time

import numpy as np

from txflow_tpu.committee import (
    SEED_DOMAIN,
    BatchCertVerifier,
    CommitteeSchedule,
    committee_seed,
    sample_committee,
)
from txflow_tpu.epoch import EpochConfig
from txflow_tpu.faults.byzantine import equivocating_block_votes
from txflow_tpu.health.byzantine import (
    DROP_NON_COMMITTEE,
    ByzantineConfig,
    ByzantineLedger,
)
from txflow_tpu.node.localnet import LocalNet
from txflow_tpu.types import MockPV, TxVote, Validator, ValidatorSet
from txflow_tpu.types.tx_vote import canonical_sign_bytes
from txflow_tpu.utils.config import test_config as make_test_config
from txflow_tpu.abci import AppConns, KVStoreApplication
from txflow_tpu.engine import TxExecutor, TxFlow
from txflow_tpu.pool import Mempool, TxVotePool
from txflow_tpu.store import MemDB, TxStore
from txflow_tpu.utils.config import EngineConfig, MempoolConfig
from txflow_tpu.verifier import ScalarVoteVerifier

CHAIN_ID = "txflow-localnet"  # LocalNet default
ENGINE_CHAIN = "txflow-epoch-test"


def wait_until(pred, timeout=20.0, poll=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return False


def make_pvs(n=4, powers=None, tag=b"epoch-val"):
    pvs = sorted(
        (MockPV(hashlib.sha256(tag + b"%d" % i).digest()) for i in range(n)),
        key=lambda p: p.get_address(),
    )
    powers = powers or [10] * n
    vals = ValidatorSet(
        [Validator.from_pub_key(pv.get_pub_key(), p) for pv, p in zip(pvs, powers)]
    )
    by_addr = {pv.get_address(): pv for pv in pvs}
    return [by_addr[v.address] for v in vals], vals


def make_engine(vals, use_device=False, verifier=None):
    conns = AppConns(KVStoreApplication())
    mempool = Mempool(MempoolConfig(cache_size=1000), conns.mempool)
    commitpool = Mempool(MempoolConfig(cache_size=1000))
    votepool = TxVotePool(MempoolConfig(cache_size=10000))
    tx_store = TxStore(MemDB())
    execu = TxExecutor(conns.consensus, mempool)
    flow = TxFlow(
        ENGINE_CHAIN,
        1,
        vals,
        votepool,
        mempool,
        commitpool,
        execu,
        tx_store,
        config=EngineConfig(max_batch=1024, use_device=use_device),
        verifier=verifier,
    )
    return flow, mempool, votepool, tx_store


def sign_vote(pv, tx: bytes, height=1, chain=ENGINE_CHAIN) -> TxVote:
    v = TxVote(
        height=height,
        tx_hash=hashlib.sha256(tx).hexdigest().upper(),
        tx_key=hashlib.sha256(tx).digest(),
        timestamp_ns=1700000000_000000000,
        validator_address=pv.get_address(),
    )
    pv.sign_tx_vote(chain, v)
    return v

# ------------------------------------------------------- sampler units


def test_committee_seed_domain_separation():
    s = committee_seed("chain-a", 3)
    assert s == committee_seed("chain-a", 3), "seed must be deterministic"
    assert s != committee_seed("chain-b", 3), "chain_id must separate seeds"
    assert s != committee_seed("chain-a", 4), "epoch must separate seeds"
    assert SEED_DOMAIN.startswith(b"txflow/committee/"), (
        "domain tag is versioned wire surface — renaming it re-elects "
        "every historical committee"
    )


def test_sample_deterministic_and_epoch_varying():
    _, vals = make_pvs(12, tag=b"committee-val")
    a = sample_committee(vals, "c", 0, 4)
    b = sample_committee(vals, "c", 0, 4)
    assert [v.address for v in a] == [v.address for v in b], (
        "same (set, chain, epoch) must elect the identical committee"
    )
    assert a.size() == 4
    # across epochs the sample must actually move (rotation is the
    # point); with 495 possible 4-of-12 committees, 8 identical
    # consecutive samples would mean the epoch is not feeding the seed
    others = [
        frozenset(v.address for v in sample_committee(vals, "c", e, 4))
        for e in range(1, 9)
    ]
    assert any(o != frozenset(v.address for v in a) for o in others)


def test_sample_stake_proportional():
    """A validator holding half the total stake must appear in nearly
    every epoch's committee; a minnow with 1/110 of stake must not."""
    pvs, _ = make_pvs(11, tag=b"whale-val")
    whale = pvs[0].get_address()
    vals = ValidatorSet(
        [
            Validator.from_pub_key(pv.get_pub_key(), 100 if i == 0 else 10)
            for i, pv in enumerate(pvs)
        ]
    )
    hits = sum(
        1
        for e in range(40)
        if sample_committee(vals, "c", e, 3).has_address(whale)
    )
    assert hits >= 34, f"50%-stake whale sampled in only {hits}/40 epochs"


def test_sample_floors():
    _, vals = make_pvs(8, tag=b"floor-val")
    # size floor: asking for 2 with min_size=4 yields 4
    assert sample_committee(vals, "c", 0, 2, min_size=4).size() == 4
    # full-set passthrough IS the identity object (the engine's
    # rotation check then sees no change at all)
    assert sample_committee(vals, "c", 0, 8) is vals
    assert sample_committee(vals, "c", 0, 99) is vals
    # stake floor: with uniform stake, >= 3/4 of total power requires
    # at least 6 of the 8 members regardless of the size target
    c = sample_committee(vals, "c", 0, 2, min_size=2, min_stake_frac=0.75)
    assert c.total_voting_power() >= 60 and c.size() >= 6


def test_schedule_vote_height_mapping_and_cache():
    _, vals = make_pvs(8, tag=b"sched-val")
    cfg = EpochConfig(length=4, committee_size=4)
    sched = CommitteeSchedule("c", cfg)
    # a vote at height h certifies the tx committing at h+1: heights
    # 1..3 map to epoch 0, the boundary height 4 already votes under
    # epoch 1's committee
    assert sched.epoch_for_vote_height(0) == 0
    assert sched.epoch_for_vote_height(3) == 0
    assert sched.epoch_for_vote_height(4) == 1
    c0 = sched.for_vote_height(1, vals)
    assert sched.for_vote_height(2, vals) is c0, (
        "same (epoch, set) must return the cached object — the engine's "
        "identity check depends on it"
    )
    # length=0: every height is epoch 0 — a static committee
    static = CommitteeSchedule("c", EpochConfig(length=0, committee_size=4))
    assert static.for_vote_height(999, vals) is static.for_vote_height(1, vals)
    # a rotated full set (different hash) can never be served the old
    # sample: drop one member and the cache key changes
    smaller = ValidatorSet(list(vals.validators)[:-1])
    c0b = sched.for_vote_height(1, smaller)
    assert c0b is not c0
    assert all(smaller.has_address(v.address) for v in c0b)


# ------------------------------------- satellite 1: breaker rescale


def test_breaker_committee_rescale_pinned_points():
    """The PR 14 circuit breaker restated in committee terms: thresholds
    scale with the committee fraction, pinned at the exact points —
    floors keep a tiny committee from hair-triggering the breaker."""
    led = ByzantineLedger(ByzantineConfig())  # min_samples=32, rate=0.5
    assert led.committee_rescale(0.5) == (16, 0.25)
    assert led.committee_rescale(0.125) == (8, 0.2), (
        "32*0.125=4 and 0.5*0.125=0.0625 must clamp to the (8, 0.2) floors"
    )
    assert led.committee_rescale(1.0) == (32, 0.5), (
        "full-set fraction must restore the configured thresholds"
    )
    snap = led.snapshot()
    assert snap["breaker"] == {"min_samples": 32, "max_bad_rate": 0.5}
    # the soak/drill rigs ARM the breaker by mutating cfg mid-run; the
    # committee scaling must compose with that, not snapshot over it
    led.committee_rescale(0.5)
    led.cfg.min_samples = 24
    assert led.snapshot()["breaker"]["min_samples"] == 12
    led.committee_rescale(1.0)
    assert led.snapshot()["breaker"]["min_samples"] == 24


def test_breaker_trips_at_committee_scaled_threshold():
    """After rescale(0.5) a flooding peer trips at 16 judged-bad events
    — half the full-set 32 — and non_committee is a breaker reason."""
    led = ByzantineLedger(ByzantineConfig(quarantine_secs=60.0))
    led.committee_rescale(0.5)
    led.note_frame("flooder", kept=0, drops={DROP_NON_COMMITTEE: 15}, now=1.0)
    assert not led.quarantined("flooder", now=1.0)
    led.note_frame("flooder", kept=0, drops={DROP_NON_COMMITTEE: 1}, now=1.0)
    assert led.quarantined("flooder", now=1.0), (
        "16 bad of 16 judged must trip the rescaled (16, 0.25) breaker"
    )
    assert led.snapshot()["peers"]["flooder"]["drops"] == {
        DROP_NON_COMMITTEE: 16
    }


# -------------------------------------------- BatchCertVerifier parity


def _vote_batch(pvs, vals, chain, spec):
    """Build (msgs, sigs, val_idx, tx_slot, n_slots) from a spec of
    (slot, pv_index, corrupt) triples, all votes at height 1."""
    msgs, sigs, vidx, slot = [], [], [], []
    addr_to_idx = {v.address: i for i, v in enumerate(vals)}
    n_slots = max(s for s, _, _ in spec) + 1
    for s, pi, corrupt in spec:
        tx = b"bparity-%d=v" % s
        v = sign_vote(pvs[pi], tx, chain=chain)
        sig = bytearray(v.signature)
        if corrupt:
            sig[5] ^= 0xFF
        msgs.append(canonical_sign_bytes(chain, 1, v.tx_hash, v.timestamp_ns))
        sigs.append(bytes(sig))
        vidx.append(addr_to_idx[pvs[pi].get_address()])
        slot.append(s)
    return msgs, sigs, np.array(vidx), np.array(slot), n_slots


def test_batch_cert_verifier_decision_parity():
    """One fused device call, identical decisions: valid/invalid
    signatures, duplicate (slot, validator) rows, quorum bits and the
    dropped mask must all match the scalar golden path bit-for-bit."""
    pvs, vals = make_pvs(4, tag=b"bparity-val")
    spec = [
        (0, 0, False), (0, 1, False), (0, 2, False),  # slot 0: quorate
        (1, 0, False), (1, 1, True),                  # slot 1: one bad sig
        (2, 0, False), (2, 0, False), (2, 1, False),  # slot 2: dup row
        (3, 3, False),                                # slot 3: below quorum
    ]
    batch = _vote_batch(pvs, vals, ENGINE_CHAIN, spec)
    golden = ScalarVoteVerifier(vals).verify_and_tally(*batch)
    bv = BatchCertVerifier(vals, min_batch=4)
    got = bv.verify_and_tally(*batch)
    assert bv.batch_calls == 1 and bv.scalar_calls == 0, (
        "9 rows >= min_batch must take the ONE-device-call path"
    )
    assert bv.batched_votes == len(batch[0])
    for field in ("valid", "stake", "maj23", "dropped"):
        assert np.array_equal(getattr(got, field), getattr(golden, field)), (
            f"batched {field} diverged from the scalar golden path: "
            f"{getattr(got, field)} vs {getattr(golden, field)}"
        )
    # explicit quorum override follows the same parity
    g2 = ScalarVoteVerifier(vals).verify_and_tally(*batch, quorum=20)
    b2 = bv.verify_and_tally(*batch, quorum=20)
    assert np.array_equal(b2.maj23, g2.maj23)


def test_batch_cert_verifier_small_batch_falls_through():
    pvs, vals = make_pvs(4, tag=b"bsmall-val")
    batch = _vote_batch(pvs, vals, ENGINE_CHAIN, [(0, 0, False), (0, 1, False)])
    bv = BatchCertVerifier(vals, min_batch=4)
    golden = ScalarVoteVerifier(vals).verify_and_tally(*batch)
    got = bv.verify_and_tally(*batch)
    assert bv.scalar_calls == 1 and bv.batch_calls == 0
    assert np.array_equal(got.valid, golden.valid)
    assert np.array_equal(got.maj23, golden.maj23)


def test_batch_cert_verifier_restage():
    """A committee swap restages the batch tables in place and the next
    call verifies under the new set — same-size swap, fresh tables."""
    pvs, vals = make_pvs(8, tag=b"brestage-val")
    c0 = sample_committee(vals, ENGINE_CHAIN, 0, 4)
    c1 = sample_committee(vals, ENGINE_CHAIN, 1, 4)
    assert frozenset(v.address for v in c0) != frozenset(
        v.address for v in c1
    ), "test setup: epochs 0/1 must elect different committees"
    by_addr = {pv.get_address(): pv for pv in pvs}
    bv = BatchCertVerifier(c0, min_batch=4)

    def quorate_batch(committee):
        members = [by_addr[v.address] for v in committee]
        idx = {v.address: i for i, v in enumerate(committee)}
        msgs, sigs, vidx, slot = [], [], [], []
        for s in range(2):
            tx = b"brestage-%d=v" % s
            for pv in members[:3]:
                v = sign_vote(pv, tx, chain=ENGINE_CHAIN)
                msgs.append(
                    canonical_sign_bytes(
                        ENGINE_CHAIN, 1, v.tx_hash, v.timestamp_ns
                    )
                )
                sigs.append(v.signature)
                vidx.append(idx[pv.get_address()])
                slot.append(s)
        return msgs, sigs, np.array(vidx), np.array(slot), 2

    r0 = bv.verify_and_tally(*quorate_batch(c0))
    assert bool(r0.valid.all()) and bool(r0.maj23.all())
    assert bv.restage(c1) is True
    r1 = bv.verify_and_tally(*quorate_batch(c1))
    assert bool(r1.valid.all()) and bool(r1.maj23.all())
    assert bv.batch_calls == 2


# ----------------------------------------------------- engine swaps


def test_engine_committee_swap_revalidates_and_preserves_certs():
    """Epoch boundary committee handoff on the engine: in-flight vote
    sets revalidate against the new committee (votes from rotated-out
    members dropped), a latched certificate is never mutated, and the
    tx completes under the new committee's quorum."""
    pvs, vals = make_pvs(8, tag=b"epoch-val")
    c0 = sample_committee(vals, ENGINE_CHAIN, 0, 4)  # members 0,2,3,4
    c1 = sample_committee(vals, ENGINE_CHAIN, 1, 4)  # members 0,2,4,5
    idx0 = sorted(
        i for i, pv in enumerate(pvs) if c0.has_address(pv.get_address())
    )
    idx1 = sorted(
        i for i, pv in enumerate(pvs) if c1.has_address(pv.get_address())
    )
    assert idx0 != idx1, "epochs 0/1 must elect different committees"
    dropped_members = [i for i in idx0 if i not in idx1]
    assert dropped_members, "the swap must rotate at least one member out"

    flow, mempool, votepool, tx_store = make_engine(c0)
    tx_a, tx_b = b"comm-a=v", b"comm-b=v"
    mempool.check_tx(tx_a)
    mempool.check_tx(tx_b)
    # tx_a: 3 committee votes, 30 >= 27 — commits under c0
    for i in idx0[:3]:
        votepool.check_tx(sign_vote(pvs[i], tx_a))
    # tx_b: one vote that survives the swap, one from a member rotating
    # out — 20 < 27, in flight across the boundary
    survivor = [i for i in idx0 if i in idx1][0]
    votepool.check_tx(sign_vote(pvs[survivor], tx_b))
    votepool.check_tx(sign_vote(pvs[dropped_members[0]], tx_b))
    flow.step()
    h_a = hashlib.sha256(tx_a).hexdigest().upper()
    h_b = hashlib.sha256(tx_b).hexdigest().upper()
    cert_a = tx_store.load_tx_commit(h_a)
    assert cert_a is not None and len(cert_a.commits) == 3
    before = [(c.validator_address, c.signature) for c in cert_a.commits]
    assert tx_store.load_tx_commit(h_b) is None

    flow.update_state(2, c1)
    rot = flow.last_rotation
    assert rot is not None and rot["restaged"] is True
    assert rot["votes_dropped"] == 1, (
        "the rotated-out member's in-flight vote must be discarded"
    )
    assert rot["val_set_hash"] == c1.hash().hex()

    # two more c1 members push tx_b over the NEW committee's quorum
    fresh = [i for i in idx1 if i != survivor][:2]
    for i in fresh:
        votepool.check_tx(sign_vote(pvs[i], tx_b, height=2))
    flow.step()
    cert_b = tx_store.load_tx_commit(h_b)
    assert cert_b is not None
    signers = {c.validator_address for c in cert_b.commits}
    assert pvs[dropped_members[0]].get_address() not in signers
    assert all(c1.has_address(a) for a in signers)
    # the pre-swap certificate is untouched
    after = [
        (c.validator_address, c.signature)
        for c in tx_store.load_tx_commit(h_a).commits
    ]
    assert after == before


def test_engine_device_committee_swap_zero_recompile():
    """The acceptance contract: an equal-size committee handoff at an
    epoch boundary restages the device verifier in place — shapes_used
    after the swap is EXACTLY the pre-swap set (zero recompiles)."""
    from txflow_tpu.verifier import DeviceVoteVerifier

    pvs, vals = make_pvs(8, tag=b"epoch-val")
    c0 = sample_committee(vals, ENGINE_CHAIN, 0, 4)
    c1 = sample_committee(vals, ENGINE_CHAIN, 1, 4)
    assert c0.size() == c1.size(), (
        "constant committee_size is what makes the swap shape-stable"
    )
    by_addr = {pv.get_address(): pv for pv in pvs}
    dv = DeviceVoteVerifier(c0, buckets=(16,))
    flow, mempool, votepool, tx_store = make_engine(
        c0, use_device=True, verifier=dv
    )
    members0 = [by_addr[v.address] for v in c0]
    round1 = [b"cwarm%d=v" % i for i in range(4)]
    for tx in round1:
        mempool.check_tx(tx)
        for pv in members0[:3]:
            votepool.check_tx(sign_vote(pv, tx))
    flow.step()
    for tx in round1:
        assert tx_store.load_tx_commit(hashlib.sha256(tx).hexdigest().upper())

    shapes_before = set(dv.shapes_used)
    assert shapes_before, "round 1 must have exercised the device path"

    flow.update_state(2, c1)
    assert flow.last_rotation["restaged"] is True, (
        "an equal-size committee swap must restage in place"
    )
    assert dv.val_set.hash() == c1.hash()

    members1 = [by_addr[v.address] for v in c1]
    round2 = [b"cswap%d=v" % i for i in range(4)]
    for tx in round2:
        mempool.check_tx(tx)
        for pv in members1[:3]:
            votepool.check_tx(sign_vote(pv, tx, height=2))
    flow.step()
    for tx in round2:
        assert tx_store.load_tx_commit(hashlib.sha256(tx).hexdigest().upper())
    assert set(dv.shapes_used) == shapes_before, (
        "a committee swap must never introduce a new compiled shape "
        f"(before={shapes_before}, after={set(dv.shapes_used)})"
    )


# --------------------------------------------------- LocalNet drills


def _assert_cert_committee_only(net, tx, min_height=0):
    """Every signer of the tx's certificate was a member of the
    committee IN FORCE AT THAT VOTE'S HEIGHT — derived from the
    deterministic schedule, so the check is immune to the chain
    advancing (and the committee rotating) while we read."""
    sched = net.nodes[0].committee_schedule
    full = net.nodes[0].state_view().validators
    h = hashlib.sha256(tx).hexdigest().upper()
    cert = net.nodes[0].tx_store.load_tx_commit(h)
    assert cert is not None and cert.commits
    for c in cert.commits:
        assert c.height >= min_height
        com = sched.for_vote_height(c.height, full)
        assert com.has_address(c.validator_address), (
            f"cert signer {c.validator_address.hex()} is not in the "
            f"committee for vote height {c.height}"
        )
    return cert


def test_drill_committee_rotation_mid_flood():
    """Satellite 3: the committee rotates at an epoch boundary while a
    tx flood is in flight. In-flight vote sets revalidate, latched
    certificates stay immutable byte-for-byte, the handoff restages the
    engine in place, and zero admitted txs are lost.

    Epochs roll every 4 blocks for the whole run, so every assertion is
    phrased against the deterministic schedule (committee for the
    height a vote was cast at), never against "the current committee" —
    which can rotate between any two reads."""
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(
        6,
        use_device_verifier=False,
        enable_consensus=True,
        config=cfg,
        epoch_config=EpochConfig(length=4, committee_size=4),
    )
    try:
        net.start()
        full = net.nodes[0].state_view().validators
        # the drill only means something if adjacent epochs actually
        # elect different committees (they do: deterministic)
        e0 = frozenset(
            v.address for v in sample_committee(full, CHAIN_ID, 0, 4)
        )
        e1 = frozenset(
            v.address for v in sample_committee(full, CHAIN_ID, 1, 4)
        )
        assert e0 != e1 and len(e0) == len(e1) == 4
        for n in net.nodes:
            com = n.state_view().committee
            assert com is not None and com.size() == 4

        # phase A: flood; capture the latched certificates
        pre = [b"pre-churn-%d=v" % i for i in range(6)]
        for i, tx in enumerate(pre):
            net.broadcast_tx(tx, node_index=i % len(net.nodes))
        assert net.wait_all_committed(pre, timeout=60)
        pre_certs = {}
        for tx in pre:
            cert = _assert_cert_committee_only(net, tx)
            pre_certs[tx] = [
                (c.validator_address, c.signature) for c in cert.commits
            ]

        # phase B launches NOW so vote sets are in flight across swaps
        mid = [b"mid-churn-%d=v" % i for i in range(6)]
        for i, tx in enumerate(mid):
            net.broadcast_tx(tx, node_index=i % len(net.nodes))

        def past_first_boundary():
            return all(
                n.state_view().last_block_height >= 5 for n in net.nodes
            )

        assert wait_until(past_first_boundary, timeout=60), (
            "the chain must cross the first epoch boundary: "
            f"heights={[n.state_view().last_block_height for n in net.nodes]}"
        )
        # zero admitted-tx loss: the mid-flood corpus commits everywhere
        assert net.wait_all_committed(mid, timeout=60), (
            "in-flight txs must survive the committee handoff"
        )
        for tx in mid:
            _assert_cert_committee_only(net, tx)

        # every node crossed >=1 boundary: the handoff restaged the
        # engine in place (equal-size swap => no rebuild, no recompile)
        for n in net.nodes:
            rot = n.txflow.last_rotation
            assert rot is not None and rot["restaged"] is True, (
                f"committee handoff must restage in place, got {rot}"
            )

        # post-boundary: fresh txs certify under post-swap committees
        # (all their votes are cast at heights past the first boundary)
        post = [b"post-churn-%d=v" % i for i in range(4)]
        for i, tx in enumerate(post):
            net.broadcast_tx(tx, node_index=i % len(net.nodes))
        assert net.wait_all_committed(post, timeout=60)
        for tx in post:
            _assert_cert_committee_only(net, tx, min_height=4)

        # latched pre-boundary certificates were never mutated
        for tx, before in pre_certs.items():
            h = hashlib.sha256(tx).hexdigest().upper()
            cert = net.nodes[0].tx_store.load_tx_commit(h)
            after = [(c.validator_address, c.signature) for c in cert.commits]
            assert after == before, (
                "a latched maj23 certificate must be immutable across "
                "the committee handoff"
            )
    finally:
        net.stop()


def test_drill_slashed_member_excluded_from_next_sample():
    """Satellite 2: the equivocator -> evidence -> slash bridge reaches
    the sampler. A committee member caught double-signing is slashed out
    of the validator set at the epoch boundary, and every later epoch's
    committee — sampled from the post-slash set — excludes it."""
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(
        6,
        use_device_verifier=False,
        enable_consensus=True,
        config=cfg,
        epoch_config=EpochConfig(
            length=4, slash_fraction=1.0, committee_size=4
        ),
    )
    try:
        net.start()
        full0 = net.nodes[0].state_view().validators
        # the offender is an epoch-0 COMMITTEE member: the bridge must
        # evict a validator that is actively signing certificates
        com0 = sample_committee(full0, CHAIN_ID, 0, 4)
        offender = next(
            pv
            for pv in net.priv_vals
            if com0.has_address(pv.get_address())
        )
        off_addr = offender.get_address()

        pre = b"pre-comm-slash=v"
        net.broadcast_tx(pre)
        assert net.wait_all_committed([pre], timeout=60)

        ev = equivocating_block_votes(offender, CHAIN_ID, height=1)
        added, err = net.nodes[1].evidence_pool.add(ev)
        assert added, err

        def slashed_and_resampled():
            for n in net.nodes:
                if n.state_view().validators.get_by_address(off_addr)[1] is not None:
                    return False
                com = n.state_view().committee
                if com is None or com.has_address(off_addr):
                    return False
            return True

        assert wait_until(slashed_and_resampled, timeout=90), (
            "slash must remove the offender from the set AND from the "
            "next epoch's sample: "
            f"snapshots={[n.epoch_manager.snapshot() for n in net.nodes]}"
        )
        new_set = net.nodes[0].state_view().validators
        assert new_set.size() == 5
        # EVERY epoch's committee over the post-slash set excludes the
        # offender — the sampler only draws from the set it is handed
        for epoch in range(8):
            com = sample_committee(new_set, CHAIN_ID, epoch, 4)
            assert not com.has_address(off_addr)
            assert com.size() == 4

        # liveness: a fresh tx certifies under post-slash committees,
        # never carrying the offender
        post = b"post-comm-slash=v"
        net.broadcast_tx(post, node_index=1)
        assert net.wait_all_committed([post], timeout=60)
        h = hashlib.sha256(post).hexdigest().upper()
        sched = net.nodes[0].committee_schedule
        for n in net.nodes:
            votes = n.tx_store.load_tx_votes(h)
            assert votes
            for v in votes:
                assert v.validator_address != off_addr, (
                    "a slashed validator must not sign new certificates"
                )
                com = sched.for_vote_height(v.height, new_set)
                assert com.has_address(v.validator_address)
    finally:
        net.stop()
