"""RPC surface tests: an external-style HTTP client (urllib) submits a tx
to a 4-node LocalNet, long-polls its commit, and reads status/blocks/
validators/metrics — the operator/client surface of reference
node/node.go:878-1007.
"""

import conftest  # noqa: F401

import hashlib
import json
import time
import urllib.request

from txflow_tpu.node import LocalNet
from txflow_tpu.utils.config import test_config as make_test_config


def rpc_get(addr, path):
    host, port = addr
    with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=30) as r:
        body = r.read().decode()
        ctype = r.headers.get("Content-Type", "")
    if "text/plain" in ctype:
        return body
    return json.loads(body)


def test_rpc_end_to_end_client_flow():
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(
        4, use_device_verifier=False, enable_consensus=True, config=cfg, rpc=True
    )
    net.start()
    try:
        addr0 = net.nodes[0].rpc.addr
        addr2 = net.nodes[2].rpc.addr

        # health + status
        health = rpc_get(addr0, "/health")["result"]
        assert {"healthy", "watchdog", "peers", "verifier", "progress"} <= set(
            health
        )
        st = rpc_get(addr0, "/status")["result"]
        assert st["node_info"]["network"] == "txflow-localnet"
        assert st["node_info"]["protocol_version"]["block"] >= 1
        assert st["health"]["monitored"] is True

        # client submits a tx to node0 over HTTP
        tx = b"rpc-k=v"
        res = rpc_get(addr0, '/broadcast_tx?tx="rpc-k=v"')["result"]
        assert res["hash"] == hashlib.sha256(tx).hexdigest().upper()

        # ... and long-polls the commit on a DIFFERENT node (gossip + vote
        # quorum must carry it across)
        sub = rpc_get(addr2, f"/subscribe_tx?hash={res['hash']}&timeout=30")[
            "result"
        ]
        assert sub["committed"] is True, sub

        # tx lookup shows the fast-path certificate
        info = rpc_get(addr2, f"/tx?hash={res['hash']}")["result"]
        assert info["committed"] and info["votes"] >= 3

        # hex-form broadcast works too
        res2 = rpc_get(addr0, "/broadcast_tx?tx=0x6b323d7632")["result"]  # k2=v2
        sub2 = rpc_get(addr0, f"/subscribe_tx?hash={res2['hash']}&timeout=30")[
            "result"
        ]
        assert sub2["committed"] is True

        # blocks become queryable once consensus advances
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if rpc_get(addr0, "/blockchain")["result"]["height"] >= 1:
                break
            time.sleep(0.05)
        chain = rpc_get(addr0, "/blockchain")["result"]
        assert chain["height"] >= 1
        blk = rpc_get(addr0, "/block?height=1")["result"]
        assert blk["height"] == 1 and blk["hash"]

        # validator set
        vals = rpc_get(addr0, "/validators")["result"]
        assert vals["count"] == 4 and vals["total_power"] == 40

        # app query round-trips through ABCI once the tx landed
        q = rpc_get(addr0, '/abci_query?path=/store&data=rpc-k')["result"]
        assert bytes.fromhex(q["value"]) == b"v"

        # Prometheus text exposition
        metrics = rpc_get(addr0, "/metrics")
        assert "txflow_" in metrics and "committed" in metrics

        # unknown routes 404 cleanly
        try:
            rpc_get(addr0, "/nope")
            assert False, "expected HTTPError"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        net.stop()


def test_tx_indexer_and_debug_endpoints():
    """Indexer queries by height and tag, plus the profiling hooks
    (reference indexer service node/node.go:211-238, pprof :724-728)."""
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(
        4, use_device_verifier=False, enable_consensus=True, config=cfg, rpc=True
    )
    net.start()
    try:
        addr = net.nodes[0].rpc.addr
        res = rpc_get(addr, '/broadcast_tx?tx="idx-k=v"')["result"]
        sub = rpc_get(addr, f"/subscribe_tx?hash={res['hash']}&timeout=30")["result"]
        assert sub["committed"]

        # indexed record by hash via the indexer (kvstore tags app.key);
        # the commit EVENT fires on the committer thread just after the
        # store row the subscription watches, so allow it a moment
        idx = net.nodes[0].tx_indexer
        deadline = time.monotonic() + 10
        rec = None
        while time.monotonic() < deadline and rec is None:
            rec = idx.get(res["hash"])
            time.sleep(0.02)
        assert rec is not None and rec["hash"] == res["hash"]
        # tag search through RPC
        found = rpc_get(addr, "/tx_search?key=app.key&value=idx-k=v")["result"]
        assert found["total"] >= 1
        assert any(t["hash"] == res["hash"] for t in found["txs"])
        # height search returns it once it's known at its indexed height
        by_h = rpc_get(addr, f"/tx_search?height={rec['height']}")["result"]
        assert any(t["hash"] == res["hash"] for t in by_h["txs"])

        # thread stack dump (pprof-goroutine analog)
        stacks = rpc_get(addr, "/debug/stacks")["result"]
        assert stacks["count"] >= 3
        assert any("consensus" in name for name in stacks["threads"])
    finally:
        net.stop()


def test_grpc_broadcast_api():
    """core_grpc.BroadcastAPI analog (reference node/node.go:972-986): an
    external gRPC client pings and broadcasts a tx; the response carries
    the executed result after fast-path commit."""
    import grpc

    from txflow_tpu.codec import amino
    from txflow_tpu.node import LocalNet
    from txflow_tpu.rpc.grpc_server import (
        GRPCBroadcastServer,
        decode_request_broadcast_tx,
    )

    net = LocalNet(4, use_device_verifier=False)
    net.start()
    srv = GRPCBroadcastServer(net.nodes[0])
    try:
        host, port = srv.start()
        ident = lambda b: b
        chan = grpc.insecure_channel(f"{host}:{port}")
        ping = chan.unary_unary(
            "/core_grpc.BroadcastAPI/Ping",
            request_serializer=ident, response_deserializer=ident,
        )
        assert ping(b"", timeout=10) == b""

        tx = b"grpc-k=v"
        req = bytes(amino.field_key(1, amino.TYP3_BYTELEN)) + bytes(
            amino.length_prefixed(tx)
        )
        assert decode_request_broadcast_tx(req) == tx
        bcast = chan.unary_unary(
            "/core_grpc.BroadcastAPI/BroadcastTx",
            request_serializer=ident, response_deserializer=ident,
        )
        resp = bcast(req, timeout=60)
        # ResponseBroadcastTx: field 1 = check_tx (code absent => 0),
        # field 2 = deliver_tx present on successful commit
        r = amino.AminoReader(resp)
        fields = {}
        while not r.eof():
            fnum, typ3 = r.read_field_key()
            fields[fnum] = r.read_bytes()
        assert 1 in fields and fields[1] == b""  # check code 0, no log
        assert 2 in fields  # delivered
        assert net.nodes[0].is_committed(tx)
        chan.close()
    finally:
        srv.stop()
        net.stop()


def test_websocket_event_stream():
    """RFC 6455 WS subscription (reference WS RPC, node/node.go:914-922):
    a raw-socket client upgrades, subscribes to Tx events, and receives a
    commit event as a JSON text frame."""
    import base64
    import hashlib as _hl
    import socket
    import struct

    from txflow_tpu.node import LocalNet

    net = LocalNet(4, use_device_verifier=False, rpc=True)
    net.start()
    try:
        host, port = net.nodes[0].rpc.addr
        s = socket.create_connection((host, port), timeout=30)
        key = base64.b64encode(b"0123456789abcdef").decode()
        s.sendall(
            (
                f"GET /websocket HTTP/1.1\r\nHost: {host}\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\n"
                "Sec-WebSocket-Version: 13\r\n\r\n"
            ).encode()
        )
        # read the 101 response headers
        buf = b""
        while b"\r\n\r\n" not in buf:
            buf += s.recv(1024)
        head = buf.split(b"\r\n\r\n", 1)[0].decode()
        assert "101" in head.splitlines()[0]
        want = base64.b64encode(
            _hl.sha1(
                (key + "258EAFA5-E914-47DA-95CA-C5AB0DC85B11").encode()
            ).digest()
        ).decode()
        assert want in head
        rest = buf.split(b"\r\n\r\n", 1)[1]

        def send_text(payload: bytes):
            mask = b"\x01\x02\x03\x04"
            masked = bytes(c ^ mask[i % 4] for i, c in enumerate(payload))
            s.sendall(bytes([0x81, 0x80 | len(payload)]) + mask + masked)

        recv_buf = [rest]

        def read_exact(n):
            out = b""
            while len(out) < n:
                if recv_buf[0]:
                    take = recv_buf[0][: n - len(out)]
                    recv_buf[0] = recv_buf[0][len(take):]
                    out += take
                else:
                    recv_buf[0] = s.recv(4096)
                    if not recv_buf[0]:
                        raise ConnectionError("closed")
            return out

        def read_frame():
            b0, b1 = read_exact(2)
            n = b1 & 0x7F
            if n == 126:
                (n,) = struct.unpack(">H", read_exact(2))
            return b0 & 0x0F, read_exact(n)

        send_text(b'{"subscribe": "Tx"}')
        op, data = read_frame()
        assert op == 1 and json.loads(data)["subscribed"] == "Tx"

        tx = b"ws-k=v"
        net.broadcast_tx(tx)
        tx_hash = hashlib.sha256(tx).hexdigest().upper()
        deadline = time.time() + 60
        seen = False
        while time.time() < deadline and not seen:
            op, data = read_frame()
            if op == 1:
                ev = json.loads(data)
                if ev.get("hash") == tx_hash:
                    seen = True
        assert seen, "commit event must stream over the websocket"
        s.close()
    finally:
        net.stop()


def test_broadcast_tx_commit():
    """One-call submit-and-wait (tendermint broadcast_tx_commit)."""
    from txflow_tpu.node import LocalNet

    net = LocalNet(4, use_device_verifier=False, rpc=True)
    net.start()
    try:
        addr = net.nodes[0].rpc.addr
        res = rpc_get(addr, '/broadcast_tx_commit?tx="btc-k=v"')["result"]
        assert res["committed"] is True
        assert res["hash"] == hashlib.sha256(b"btc-k=v").hexdigest().upper()
    finally:
        net.stop()


def test_rpc_route_parity():
    """The reference's rpccore.Routes surface (node/node.go:898-986):
    /commit for light-client certificate flows, /genesis, /net_info,
    /block_results, /unconfirmed_txs, /num_unconfirmed_txs,
    /consensus_state, /dump_consensus_state, /broadcast_evidence."""
    cfg = make_test_config()
    cfg.consensus.skip_timeout_commit = True
    net = LocalNet(
        4, use_device_verifier=False, enable_consensus=True, config=cfg, rpc=True
    )
    net.start()
    try:
        addr0 = net.nodes[0].rpc.addr

        # drive one tx through so a block commits
        res = rpc_get(addr0, '/broadcast_tx?tx="parity-k=v"')["result"]
        assert rpc_get(addr0, f"/subscribe_tx?hash={res['hash']}&timeout=30")[
            "result"
        ]["committed"]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if rpc_get(addr0, "/blockchain")["result"]["height"] >= 1:
                break
            time.sleep(0.05)

        # /genesis
        gen = rpc_get(addr0, "/genesis")["result"]["genesis"]
        assert gen["chain_id"] == "txflow-localnet"
        assert len(gen["validators"]) == 4

        # /net_info: full mesh = 3 peers
        ni = rpc_get(addr0, "/net_info")["result"]
        assert ni["n_peers"] == 3 and len(ni["peers"]) == 3

        # /commit: header + sealing commit, signatures verifiable
        cm = rpc_get(addr0, "/commit?height=1")["result"]
        assert cm["header"]["height"] == 1
        assert cm["commit"]["block_id"] == cm["block_id"]
        assert len(cm["commit"]["precommits"]) >= 3  # quorum of 4
        from txflow_tpu.types.block_vote import BlockVote, PRECOMMIT

        for pc in cm["commit"]["precommits"]:
            v = BlockVote(
                height=pc["height"], round=pc["round"], type=PRECOMMIT,
                block_id=bytes.fromhex(pc["block_id"]),
                timestamp_ns=pc["timestamp_ns"],
                validator_address=bytes.fromhex(pc["validator_address"]),
                signature=bytes.fromhex(pc["signature"]),
            )
            _, val = net.val_set.get_by_address(v.validator_address)
            assert val is not None and v.verify("txflow-localnet", val.pub_key)

        # /block_results: persisted ABCI responses for the block
        br = rpc_get(addr0, "/block_results?height=1")["result"]
        assert br["height"] == 1 and isinstance(br["deliver_tx"], list)

        # /unconfirmed_txs + /num_unconfirmed_txs: the tx may fast-commit
        # between inject and query (signing nodes vote immediately), so
        # accept EITHER pending-visible or already-committed
        tx = b"pending-tx=1"
        net.nodes[0].mempool.check_tx(tx)
        ut = rpc_get(addr0, "/unconfirmed_txs?limit=10")["result"]
        assert {"n_txs", "total", "total_bytes", "txs"} <= set(ut)
        in_pool = any(bytes.fromhex(t) == tx for t in ut["txs"])
        committed = net.nodes[0].txflow.is_tx_committed(
            hashlib.sha256(tx).hexdigest().upper()
        )
        assert in_pool or committed, (ut, committed)
        nut = rpc_get(addr0, "/num_unconfirmed_txs")["result"]
        assert "total" in nut and "vote_pool" in nut

        # /consensus_state + /dump_consensus_state
        cs = rpc_get(addr0, "/consensus_state")["result"]["round_state"]
        assert cs["height"] >= 1 and "step" in cs
        dcs = rpc_get(addr0, "/dump_consensus_state")["result"]["round_state"]
        assert "votes" in dcs and len(dcs["validators"]) == 4

        # /broadcast_evidence: a real equivocation proof is admitted and
        # gossiped; garbage is rejected
        from txflow_tpu.types.block_vote import PREVOTE
        from txflow_tpu.types.evidence import (
            DuplicateBlockVoteEvidence,
            encode_evidence,
        )

        pv = net.priv_vals[1]
        votes = []
        for bid in (b"\x01" * 20, b"\x02" * 20):
            bv = BlockVote(height=1, round=0, type=PREVOTE, block_id=bid,
                           validator_address=pv.get_address())
            pv.sign_block_vote("txflow-localnet", bv)
            votes.append(bv)
        ev = DuplicateBlockVoteEvidence(*votes)
        out = rpc_get(
            addr0, f"/broadcast_evidence?evidence={encode_evidence(ev).hex()}"
        )["result"]
        assert out["added"] is True
        assert net.nodes[0].evidence_pool.has(ev)
        try:
            rpc_get(addr0, "/broadcast_evidence?evidence=ffff")
            assert False, "expected HTTPError"
        except urllib.error.HTTPError as e:
            assert e.code == 500
    finally:
        net.stop()


def test_rpc_hardening_body_cap_and_connection_cap():
    """Oversized POST bodies get 413 + connection close; connections past
    MAX_OPEN_CONNECTIONS are refused instead of spawning threads
    (reference MaxOpenConnections / request limits, node/node.go:925-929)."""
    import socket

    import txflow_tpu.rpc.server as rpcmod

    net = LocalNet(1, use_device_verifier=False, rpc=True)
    net.start()
    try:
        host, port = net.nodes[0].rpc.addr

        # -- oversized body: 413, connection closed, server still alive --
        s = socket.create_connection((host, port), timeout=10)
        s.sendall(
            b"POST /status HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: %d\r\n\r\n" % (rpcmod.MAX_BODY_BYTES + 1)
        )
        s.sendall(b"x" * 1024)  # partial body; server must not wait for it
        resp = s.recv(4096)
        assert b"413" in resp.split(b"\r\n", 1)[0], resp[:100]
        s.close()
        # server still serves normal requests afterwards
        assert rpc_get((host, port), "/health")["result"]["healthy"] is True

        # -- connection flood: at most MAX_OPEN_CONNECTIONS serviced --
        old_cap = rpcmod.MAX_OPEN_CONNECTIONS
        sem = net.nodes[0].rpc._httpd._conn_sem
        # shrink the live semaphore to a tiny cap for the test; drain
        # twice with a settle gap — a handler thread from an earlier
        # request in this test may release its permit AFTER the first
        # drain, silently raising the effective capacity (flake)
        drained = 0
        while sem.acquire(blocking=False):
            drained += 1
        time.sleep(0.3)
        while sem.acquire(blocking=False):
            drained += 1
        for _ in range(2):  # leave capacity 2
            sem.release()
        try:
            conns = []
            served, refused = 0, 0
            for _ in range(6):
                c = socket.create_connection((host, port), timeout=5)
                try:
                    c.sendall(b"GET /health HTTP/1.1\r\nHost: x\r\n\r\n")
                except OSError:
                    # server already RST the over-cap connection before
                    # our send landed: that IS a refusal
                    refused += 1
                    c.close()
                    continue
                conns.append(c)
                time.sleep(0.05)
            for c in conns:
                c.settimeout(2)
                try:
                    data = c.recv(2048)
                except (TimeoutError, OSError):
                    data = b""
                if b"200" in data.split(b"\r\n", 1)[0] if data else False:
                    served += 1
                else:
                    refused += 1
            assert served <= 2, f"cap not enforced: {served} served"
            assert refused >= 4, f"expected refusals, got {refused}"
            for c in conns:
                c.close()
        finally:
            # restore the semaphore's capacity
            for _ in range(drained - 2):
                sem.release()
        # normal service restored
        time.sleep(0.1)
        assert rpc_get((host, port), "/health")["result"]["healthy"] is True
    finally:
        net.stop()
