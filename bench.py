"""North-star benchmark: BASELINE config 1, measured end to end.

Protocol (BASELINE.json config 1-2): an N-validator in-process network —
every node runs the full fast path (txvotepool -> batched device
verify+tally -> TxStore persist -> kvstore ABCI execute -> pool purge ->
commitpool) over real gossip reactors wired with in-memory pipes. Txs are
pre-seeded into every mempool and TxVotes are PREGENERATED (signing sits
outside the timed loop, per the config's "pregenerated TxVotes replayed
through txvotepool"); the timed phase streams each validator's votes into
its own node's vote pool in chunks, vote gossip fans them out, and every
node independently verifies, tallies, and commits every tx.

Metric: committed TxVotes/sec summed over nodes (votes inside commit
certificates persisted to TxStores) + p50 tx-commit latency (vote-chunk
injection -> per-node commit event). Baseline: the reference's hot path is
one pure-Go ed25519 verify per vote, single-threaded (reference
txflow/service.go:123-166, ~50-100us/verify => 10-20k votes/s/core;
BASELINE.md). vs_baseline measures against the generous end, 20,000/s.

Robustness contract with the driver: prints EXACTLY ONE JSON line on
stdout no matter what. The TPU backend is probed in a subprocess first
(round 1 recorded both an UNAVAILABLE init failure and a multi-minute
init hang); on probe failure the bench falls back to CPU and says so in
the JSON.
"""

import glob
import hashlib
import json
import os
import statistics
import subprocess
import sys
import time

# Persistent XLA compilation cache: the verify kernel compiles in ~60-90s
# per shape on TPU; caching across processes means the driver's bench run
# reuses this session's compiles instead of paying them again.
_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _CACHE_DIR)


def _cli_or_env(flag: str, env: str, default: str) -> str:
    if flag in sys.argv:
        return sys.argv[sys.argv.index(flag) + 1]
    return os.environ.get(env, default)


# --mesh-devices N (BENCH_MESH_DEVICES): shard the device verify across an
# N-way mesh (parallel.mesh). --host-prep-workers N (BENCH_HOST_PREP_WORKERS):
# parallelize the host prep path (sign-bytes assembly + compact-batch prep)
# across N worker threads. Both 0/1 = the single-device, serial-host default.
_MESH_DEVICES = int(_cli_or_env("--mesh-devices", "BENCH_MESH_DEVICES", "0") or 0)
_HOST_PREP_WORKERS = int(
    _cli_or_env("--host-prep-workers", "BENCH_HOST_PREP_WORKERS", "0") or 0
)
# --host-prep-backend {thread,process} (BENCH_HOST_PREP_BACKEND): run the
# host-prep pool as worker THREADS (historical default, GIL-shared) or
# worker PROCESSES over shared memory (engine.hostprep.ProcHostPrepPool —
# sidesteps the GIL for the sign-bytes/compact prep inner loops; falls
# back to threads when process spawn fails). --staging-ring N
# (BENCH_STAGING_RING): depth of the device readback ring (2 = double
# buffering, <=1 = historical synchronous readback). --wide-buckets
# (BENCH_WIDE_BUCKETS=1): let the coalescer drain the verifier ladder's
# rungs above EngineConfig.max_batch, gated by the adaptive linger
# controller's latency verdict.
_HOST_PREP_BACKEND = (
    _cli_or_env("--host-prep-backend", "BENCH_HOST_PREP_BACKEND", "thread")
    or "thread"
)
_STAGING_RING = int(_cli_or_env("--staging-ring", "BENCH_STAGING_RING", "2") or 2)
_WIDE_BUCKETS = (
    "--wide-buckets" in sys.argv
    or os.environ.get("BENCH_WIDE_BUCKETS", "0") == "1"
)
# --validators N (BENCH_VALIDATORS): validator-set size. --committee-size N
# (BENCH_COMMITTEE_SIZE): per-epoch tx-vote committee sampling (committee/)
# — only the deterministic stake-proportional sample signs, certificates
# carry >2/3 of COMMITTEE stake, and verification is one batched device
# call. 0 (default) = full-set seed behavior. The sublinear-certificate
# acceptance config is --validators 256 --committee-size 32: cert votes,
# cert bytes and votes gossiped per tx are then flat in validator count.
_N_VALIDATORS = int(_cli_or_env("--validators", "BENCH_VALIDATORS", "4") or 4)
_COMMITTEE_SIZE = int(
    _cli_or_env("--committee-size", "BENCH_COMMITTEE_SIZE", "0") or 0
)
if _MESH_DEVICES > 1:
    # the CPU platform exposes ONE device unless told otherwise, and the
    # flag is read when jax initializes its backends — so it must be in
    # the environment before ANY jax import below (probe subprocesses and
    # CPU re-execs inherit it). Harmless on real TPU: it only shapes the
    # host platform.
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + f" --xla_force_host_platform_device_count={_MESH_DEVICES}"
        ).strip()

_PROBE_DIAGNOSTICS: dict = {}
if os.environ.get("BENCH_PROBE_DIAG"):
    # carried across the sanitized CPU re-exec (see _force_cpu)
    try:
        _PROBE_DIAGNOSTICS.update(json.loads(os.environ["BENCH_PROBE_DIAG"]))
    except ValueError:
        pass


def _resolve_platform() -> str:
    """Probe the default JAX backend in a subprocess; fall back to CPU.

    One LONG-budget attempt (round-2 postmortem: chip init hung past two
    180 s probes and the bench recorded a CPU number; the init needs to be
    treated as a debugging target, so on failure the diagnostics — stderr
    tail, accel device nodes, competing processes — go into the JSON)."""
    if os.environ.get("BENCH_PLATFORM"):
        plat = os.environ["BENCH_PLATFORM"]
        if plat == "cpu":
            _force_cpu()
        return plat
    probe = (
        "import time; t0=time.time(); import jax; d=jax.devices(); "
        "print(jax.default_backend()); "
        "import sys; print('init_s=%.1f devices=%s' % (time.time()-t0, d), file=sys.stderr)"
    )
    budget = float(os.environ.get("BENCH_PROBE_TIMEOUT", "600"))
    t0 = time.time()
    try:
        r = subprocess.run(
            [sys.executable, "-c", probe],
            capture_output=True,
            text=True,
            timeout=budget,
        )
        if r.returncode == 0 and r.stdout.strip():
            return r.stdout.strip().splitlines()[-1]
        _PROBE_DIAGNOSTICS["probe_rc"] = r.returncode
        _PROBE_DIAGNOSTICS["probe_stderr_tail"] = (r.stderr or "")[-1500:]
    except subprocess.TimeoutExpired as e:
        _PROBE_DIAGNOSTICS["probe_timeout_s"] = round(time.time() - t0, 1)
        _PROBE_DIAGNOSTICS["probe_stderr_tail"] = (
            (e.stderr or b"").decode("utf-8", "replace")[-1500:]
            if e.stderr
            else ""
        )
    # init failed: capture environment evidence for the postmortem
    _PROBE_DIAGNOSTICS["accel_devices"] = sorted(
        glob.glob("/dev/accel*") + glob.glob("/dev/vfio/*")
    )
    _PROBE_DIAGNOSTICS["tpu_env"] = {
        k: v
        for k, v in os.environ.items()
        if "TPU" in k or "JAX" in k or "XLA" in k
    }
    try:
        out = subprocess.run(
            ["ps", "-eo", "pid,etime,comm"], capture_output=True, text=True, timeout=5
        ).stdout
        _PROBE_DIAGNOSTICS["python_processes"] = [
            l.strip() for l in out.splitlines() if "python" in l
        ][:20]
    except Exception:
        pass
    print("bench: TPU probe failed; diagnostics captured", file=sys.stderr)
    _force_cpu()
    return "cpu"


def _sanitized_cpu_env() -> dict:
    """Env for a CPU re-exec with the axon site hook REMOVED.

    r5 observed failure mode: with the tunnel wedged in accept-and-stall,
    the PYTHONPATH site hook (.axon_site sitecustomize) hangs `import
    jax` ITSELF — even under JAX_PLATFORMS=cpu — so no amount of
    in-process pinning can save a fallback once jax is imported. The only
    robust fallback is a re-exec without the hook on PYTHONPATH."""
    env = dict(os.environ, BENCH_PLATFORM="cpu", JAX_PLATFORMS="cpu")
    parts = [
        p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and ".axon_site" not in p
    ]
    if parts:
        env["PYTHONPATH"] = os.pathsep.join(parts)
    else:
        env.pop("PYTHONPATH", None)
    env["BENCH_CPU_SANITIZED"] = "1"
    return env


def _force_cpu() -> None:
    """Pin this process to the CPU backend.

    The environment's PJRT site hook can pre-register the TPU platform and
    ignore the JAX_PLATFORMS env var, so the pin must also go through
    jax.config after import — BEFORE any backend is created (a TPU client
    init here can hang for minutes). When the site hook is present and the
    probe says the tunnel is wedged, even importing jax can hang (r5) —
    re-exec with a sanitized env instead of pinning in-process."""
    if (
        os.environ.get("BENCH_CPU_SANITIZED") != "1"
        and ".axon_site" in os.environ.get("PYTHONPATH", "")
        and "jax" not in sys.modules
    ):
        env = _sanitized_cpu_env()
        if _PROBE_DIAGNOSTICS:
            blob = json.dumps(_PROBE_DIAGNOSTICS)
            if len(blob) <= 30000:  # never ship truncated (= invalid) JSON
                env["BENCH_PROBE_DIAG"] = blob
        os.execve(sys.executable, [sys.executable] + sys.argv, env)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


BASELINE_VOTES_PER_SEC = 20_000.0  # reference CPU ceiling, BASELINE.md


# -- latency-SLO helpers (importable; tests/test_trace.py unit-tests
# these without running a net) --


def lane_quantiles(lat_ms: list) -> dict:
    """p50/p99/p999 (nearest-rank) of one lane's latency sample."""
    if not lat_ms:
        return {"count": 0, "p50_ms": None, "p99_ms": None, "p999_ms": None}
    s = sorted(lat_ms)
    def pick(q):
        return s[min(len(s) - 1, int(q * len(s)))]
    return {
        "count": len(s),
        "p50_ms": round(pick(0.50), 2),
        "p99_ms": round(pick(0.99), 2),
        "p999_ms": round(pick(0.999), 2),
    }


def slo_breached(result: dict, budget_ms) -> bool:
    """Did the run breach the priority-lane p99 budget? A missing lane
    measurement counts as a breach — the gate must not pass on absent
    data."""
    if budget_ms is None:
        return False
    p99 = ((result.get("lanes") or {}).get("priority") or {}).get("p99_ms")
    return p99 is None or p99 > float(budget_ms)


def run_latency_slo(platform: str) -> dict:
    """``--latency-slo``: mixed priority/bulk offered load against a
    LocalNet with the admission front door's fee-lane classifier active;
    reports per-lane p50/p99/p999 inject->commit latency plus the
    host/device critical-path attribution (trace/report.py). Uses the
    scalar verifier — this mode gates tail latency and attribution, not
    device throughput — so it runs identically on CPU and TPU hosts."""
    import statistics as _st  # noqa: F401  (parallel to run_bench imports)

    from txflow_tpu.node import LocalNet
    from txflow_tpu.trace.report import critical_path, merge_critical_paths
    from txflow_tpu.utils.config import test_config
    from txflow_tpu.utils.events import EventTx

    n_vals = _N_VALIDATORS
    n_txs = int(os.environ.get("BENCH_SLO_TXS", "256"))
    prio_frac = float(os.environ.get("BENCH_SLO_PRIORITY_FRAC", "0.25"))
    pace_tps = float(os.environ.get("BENCH_SLO_PACE_TPS", "200"))
    # --net-profile <name> (BENCH_NET_PROFILE): run the SLO under WAN
    # weather (netem/) — every link shaped + the adaptive peer transport
    # on; the result stamps the profile and per-peer RTT/loss so two runs
    # under different weather are comparable at a glance
    net_profile = _cli_or_env("--net-profile", "BENCH_NET_PROFILE", "") or None
    net_seed = int(_cli_or_env("--net-seed", "BENCH_NET_SEED", "11") or 11)
    cfg = test_config()
    cfg.mempool.size = max(cfg.mempool.size, 8 * n_txs)
    cfg.mempool.cache_size = max(cfg.mempool.cache_size, 2 * cfg.mempool.size)
    cfg.trace.sample_rate = int(os.environ.get("BENCH_SLO_SAMPLE_RATE", "4"))
    # the latency mode opts into the full p50 toolkit: deadline-aware
    # lane split (on by default), speculative quorum commit (off by
    # default globally — commit ORDER may shift across txs, certificates
    # don't), and adaptive linger steering against the SLO budget
    cfg.engine.speculative_commit = (
        os.environ.get("BENCH_SLO_SPECULATIVE", "1") == "1"
    )
    cfg.engine.adaptive_linger = (
        os.environ.get("BENCH_SLO_ADAPTIVE_LINGER", "1") == "1"
    )
    if os.environ.get("BENCH_SLO_BUDGET_MS"):
        cfg.engine.slo_budget_ms = float(os.environ["BENCH_SLO_BUDGET_MS"])
    net = LocalNet(
        n_vals,
        chain_id="txflow-bench",
        config=cfg,
        use_device_verifier=False,
        index_txs=False,
        netem=net_profile,
        netem_seed=net_seed,
    )

    # deterministic lane mix: every ceil(1/frac)-th tx carries a
    # fee-prefix above the classifier threshold and rides priority
    stride = max(1, round(1.0 / prio_frac)) if prio_frac > 0 else 0
    corpus = []  # (tx, is_priority)
    for i in range(n_txs):
        if stride and i % stride == 0:
            corpus.append((b"fee=9;p%d=v" % i, True))
        else:
            corpus.append((b"slo-b%d=v" % i, False))

    commit_times = [dict() for _ in net.nodes]

    def make_cb(idx):
        def cb(ev):
            commit_times[idx][ev.data.tx_hash] = time.perf_counter()
        return cb

    for i, node in enumerate(net.nodes):
        node.event_bus.subscribe_callback(EventTx, make_cb(i))

    net.start()
    inject_t: dict[str, float] = {}
    lane_of: dict[str, bool] = {}
    t0 = time.perf_counter()
    interval = 1.0 / pace_tps if pace_tps > 0 else 0.0
    for i, (tx, prio) in enumerate(corpus):
        if interval:
            delay = t0 + i * interval - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
        tx_hash = hashlib.sha256(tx).hexdigest().upper()
        node = net.nodes[i % len(net.nodes)]
        inject_t[tx_hash] = time.perf_counter()
        lane_of[tx_hash] = prio
        node.broadcast_tx(tx)
    ok = net.wait_all_committed([tx for tx, _ in corpus], timeout=300.0)
    if not ok:
        raise RuntimeError("timeout waiting for commits")

    lat = {"priority": [], "bulk": []}
    for times in commit_times:
        for tx_hash, t_inj in inject_t.items():
            t_c = times.get(tx_hash)
            if t_c is not None:
                lane = "priority" if lane_of[tx_hash] else "bulk"
                lat[lane].append((t_c - t_inj) * 1e3)

    pipe_stats = [n.txflow.pipeline_stats() for n in net.nodes]
    per_node = [
        critical_path(s, n.tracer.digest())
        for s, n in zip(pipe_stats, net.nodes)
    ]
    trace_digest = net.nodes[0].tracer.digest()
    network = None
    if net_profile is not None:
        # per-link weather observations (RTT/loss from the in-band pings,
        # shaper counters) — captured BEFORE stop so estimators are live
        peers = {}
        shaper_snap = None
        for node in net.nodes:
            snap = node.switch.net_snapshot()
            shaper_snap = snap.get("shaper") or shaper_snap
            for pid, ps in (snap.get("peers") or {}).items():
                peers[f"{node.node_id}->{pid}"] = {
                    "rtt_ms": ps.get("rtt_ms"),
                    "loss": ps.get("loss"),
                    "quarantined": ps.get("quarantined"),
                }
        # ONE shaper serves the whole LocalNet: any node's view is the
        # aggregate
        network = {
            "profile": net_profile,
            "seed": net_seed,
            "peers": peers,
            "shaper": shaper_snap,
        }
    net.stop()
    lanes = {k: lane_quantiles(v) for k, v in lat.items()}
    return {
        "metric": "latency_slo",
        "net_profile": net_profile,
        "network": network,
        "lanes": lanes,
        # headline numbers at the top level so the bank's supersede rule
        # (and a human eyeballing the artifact) need no nested digging
        "priority_p50_ms": (lanes.get("priority") or {}).get("p50_ms"),
        "priority_p99_ms": (lanes.get("priority") or {}).get("p99_ms"),
        # engine-side lane/spec accounting, summed over nodes
        "lane_stats": {
            "prio_batches": sum(
                (s.get("lanes") or {}).get("prio_batches", 0)
                for s in pipe_stats
            ),
            "prio_votes": sum(
                (s.get("lanes") or {}).get("prio_votes", 0)
                for s in pipe_stats
            ),
        },
        "spec_stats": {
            "enabled": cfg.engine.speculative_commit,
            "commits": sum(
                (s.get("spec") or {}).get("commits", 0) for s in pipe_stats
            ),
            "saved_s": round(
                sum(
                    (s.get("spec") or {}).get("saved_s", 0.0)
                    for s in pipe_stats
                ),
                4,
            ),
        },
        "adaptive_linger": next(
            (
                s["adaptive_linger"]
                for s in pipe_stats
                if s.get("adaptive_linger")
            ),
            None,
        ),
        "critical_path": merge_critical_paths(per_node),
        "critical_path_per_node": per_node,
        "trace_latency_ms": trace_digest.get("latency_ms", {}),
        "trace_sample_rate": trace_digest.get("sample_rate"),
        "platform": platform,
        "validators": n_vals,
        "nodes": len(commit_times),
        "txs": n_txs,
        "priority_frac": prio_frac,
        "pace_tps": pace_tps,
    }


def run_bench(platform: str) -> dict:
    from txflow_tpu.node import LocalNet
    from txflow_tpu.types import TxVote
    from txflow_tpu.utils.events import EventTx

    n_vals = _N_VALIDATORS
    # --stake-dist {uniform,whale,longtail} (or BENCH_STAKE_DIST): run the
    # same corpus under a non-uniform stake distribution (faults/stake.py).
    # Uniform powers never exercise the interesting quorum geometry — a
    # whale's single vote being 1/3+ of total, or a long tail where 2n/3
    # needs most of the set — and throughput can differ because quorums
    # latch after different vote counts per tx.
    from txflow_tpu.faults.stake import gini, stake_distribution

    stake_dist = os.environ.get("BENCH_STAKE_DIST", "uniform")
    if "--stake-dist" in sys.argv:
        stake_dist = sys.argv[sys.argv.index("--stake-dist") + 1]
    if stake_dist not in ("uniform", "whale", "longtail"):
        raise ValueError(
            f"--stake-dist must be uniform|whale|longtail, got {stake_dist!r}"
        )
    stake_powers = stake_distribution(
        stake_dist, n_vals, seed=int(os.environ.get("BENCH_STAKE_SEED", "0"))
    )
    # On the CPU fallback the TPU-shaped curve kernel is ~100x slower than
    # host crypto, so the bench drops to the framework's documented
    # fallback rung (SURVEY §7 hard-part 1): the scalar host verifier
    # behind the same VoteVerifier interface, with a smaller corpus.
    on_cpu = platform == "cpu"
    verifier_kind = os.environ.get("BENCH_VERIFIER", "scalar" if on_cpu else "device")
    n_txs = int(os.environ.get("BENCH_TXS", "512" if on_cpu else "8192"))
    chunk = int(os.environ.get("BENCH_CHUNK", "512" if on_cpu else "2048"))
    warm_txs = min(64 if on_cpu else 1024, n_txs)

    import hashlib as _h

    from txflow_tpu.types.priv_validator import MockPV
    from txflow_tpu.types.validator import Validator, ValidatorSet

    priv_vals = [
        MockPV(_h.sha256(b"localnet-val%d" % i).digest()) for i in range(n_vals)
    ]
    val_set = ValidatorSet(
        [
            Validator.from_pub_key(pv.get_pub_key(), p)
            for pv, p in zip(priv_vals, stake_powers)
        ]
    )
    # --committee-size N: sample the static (epoch-0) committee exactly as
    # every node will (same chain_id, same sha256 seed domain), so the
    # bench can pregenerate votes for COMMITTEE MEMBERS ONLY — that is
    # the sublinear claim: votes gossiped per tx, certificate votes and
    # verify cost all track committee size, not validator count
    committee_set = None
    signer_idx = list(range(n_vals))
    epoch_config = None
    if _COMMITTEE_SIZE > 0:
        from txflow_tpu.committee import sample_committee
        from txflow_tpu.epoch import EpochConfig

        epoch_config = EpochConfig(committee_size=_COMMITTEE_SIZE)
        committee_set = sample_committee(
            val_set, "txflow-bench", 0, _COMMITTEE_SIZE,
            min_size=epoch_config.committee_min_size,
            min_stake_frac=epoch_config.committee_min_stake_frac,
        )
        members = {v.address for v in committee_set}
        signer_idx = [
            i for i, pv in enumerate(priv_vals) if pv.get_address() in members
        ]
    # the set the verifiers stage on: the committee IS the tally set in
    # committee mode (its quorum_power() is the committee quorum)
    engine_val_set = committee_set if committee_set is not None else val_set

    shared_verifier = None
    device_verifier = None
    warm_registry = None
    if verifier_kind == "device":
        # ONE verifier for all nodes (same validator set): shared device
        # epoch tables, and a single bucket so exactly one kernel shape
        # compiles (the persistent cache then makes reruns warm-start)
        from txflow_tpu.verifier import DeviceVoteVerifier

        bucket = int(os.environ.get("BENCH_BUCKET", "4096"))
        # cross-engine verify-result cache (verifier.VerifyCache): the 4
        # co-located engines see the same gossiped votes; without it each
        # unique vote is device-verified 4x for zero information
        share_cache = os.environ.get("BENCH_SHARE_CACHE", "1") == "1"
        # two buckets: per-engine batches compile at `bucket`; the mux's
        # merged cross-engine batches land in the 4x bucket
        mesh = None
        if _MESH_DEVICES > 1:
            from txflow_tpu.parallel.mesh import make_mesh

            try:
                mesh = make_mesh(_MESH_DEVICES)
            except Exception as e:
                print(
                    f"bench: {_MESH_DEVICES}-device mesh unavailable ({e}); "
                    "running single-device",
                    file=sys.stderr,
                )
        shared_verifier = DeviceVoteVerifier(
            engine_val_set, buckets=(bucket, 4 * bucket), shared_cache=share_cache,
            mesh=mesh, host_prep_workers=_HOST_PREP_WORKERS,
            host_prep_backend=_HOST_PREP_BACKEND, staging_ring=_STAGING_RING,
        )
        device_verifier = shared_verifier  # pre-mux handle for prep stats
        t0 = time.time()
        # warm every shape the run can hit (verifier.warmup full=True:
        # the cached path's _verify_only miss ladder, or the no-cache
        # fused combos) — a cold shape would compile mid-measurement.
        # The registry snapshots the warm set so the result JSON can
        # PROVE the timed phase ran compile-free (r5 postmortem: one
        # missed shape buried the headline under ~160 s of compile).
        from txflow_tpu.engine import ShapeWarmRegistry

        warm_registry = ShapeWarmRegistry(shared_verifier)
        warm_shapes = warm_registry.prewarm(full=True)
        print(
            f"bench: kernel warm in {time.time()-t0:.1f}s "
            f"({len(warm_shapes)} shapes)",
            file=sys.stderr,
        )

        # supplementary metric: steady-state device-step throughput at the
        # bucket size (prep + kernel + packed readback, no pools/gossip/
        # commit) — the capability ceiling the end-to-end number runs under
        import numpy as _np

        _n = bucket
        _sigs = [b"\x00" * 64] * _n
        _vidx = _np.zeros(_n, _np.int64)
        _slot = _np.arange(_n, dtype=_np.int64) % max(_n // n_vals, 1)

        def _probe_msgs(it):
            # distinct per iteration: with the shared VerifyCache on, a
            # repeated batch would measure cache hits, not device work
            return [b"kbench-%d-%d" % (it, i) for i in range(_n)]

        shared_verifier.verify_and_tally(_probe_msgs(-1), _sigs, _vidx, _slot, _n)
        _t0 = time.time()
        for _it in range(3):
            shared_verifier.verify_and_tally(_probe_msgs(_it), _sigs, _vidx, _slot, _n)
        device_step_votes_per_sec = round(3 * _n / (time.time() - _t0), 1)
        print(
            f"bench: device step {device_step_votes_per_sec:.0f} votes/s",
            file=sys.stderr,
        )

        # measured on-TPU: merged cross-engine batches LOST ~17% end to end
        # (10.6k vs 12.7k votes/s) — per-vote kernel cost is nearly flat in
        # batch size (27.6 us at 4096 vs 25.6 at 16384), so the mux's
        # padding waste on partial merges + gather latency outweigh the
        # ~8 ms fixed per-call cost it amortizes. Kept opt-in for hardware
        # where the fixed cost is real (remote/tunneled accelerators).
        if os.environ.get("BENCH_MUX", "0") == "1":
            from txflow_tpu.verifier import VerifierMux

            shared_verifier = VerifierMux(
                shared_verifier,
                max_batch_per_caller=bucket,
                gather_wait=float(os.environ.get("BENCH_MUX_WAIT", "0.02")),
            )
            shared_verifier.start()
    elif committee_set is not None:
        # committee mode on the CPU fallback: ONE BatchCertVerifier
        # staged on the committee, shared by all nodes — every engine
        # verify batch is a single fused ed25519_batch dispatch (the
        # verifier's batch_calls counter is stamped into the result as
        # evidence). No verify cache: the cache-claim protocol is a
        # per-signature loop and would defeat the one-call-per-batch
        # claim this config exists to measure.
        from txflow_tpu.committee import BatchCertVerifier

        shared_verifier = BatchCertVerifier(engine_val_set)
    else:
        # CPU fallback: ONE scalar verifier with the cross-engine verify
        # cache shared by all nodes — host ed25519 is ~269 us/verify on
        # this class of core, and without the cache every vote pays it
        # once per node
        from txflow_tpu.verifier import ScalarVoteVerifier

        if os.environ.get("BENCH_SHARE_CACHE", "1") == "1":
            shared_verifier = ScalarVoteVerifier(val_set, shared_cache=True)

    from txflow_tpu.utils.config import test_config

    cfg = test_config()
    # pools must hold the whole pregenerated corpus (default caps mirror the
    # reference's 5000-tx mempool; the bench replays n_txs + warmup at once)
    cfg.mempool.size = max(cfg.mempool.size, 4 * (n_txs + warm_txs) * (n_vals + 1))
    cfg.mempool.cache_size = max(cfg.mempool.cache_size, 2 * cfg.mempool.size)
    if verifier_kind == "device":
        # one device step costs ~140 ms fixed on the tunneled TPU (kernel +
        # single packed readback) regardless of fill, so hold steps until
        # they approach the bucket instead of firing at the CPU-tuned 256
        cfg.engine.min_batch = int(os.environ.get("BENCH_MIN_BATCH", "3072"))
        # at saturation the pool always holds >= min_batch so the hold
        # never fires; it only delays LIGHT-load steps, i.e. it is pure
        # added latency in the p50 phase — keep it short
        cfg.engine.batch_wait = float(os.environ.get("BENCH_BATCH_WAIT", "0.05"))
    # amortize the ABCI app-Commit fence over groups of fast-path commits
    # (per-tx delivery/certificates/events unchanged; engine/execution.py
    # apply_tx_batch). 1 = reference-faithful per-tx fence.
    # measured on-TPU: per-tx fencing (1) beat interval 16 end-to-end
    # (12.7k vs 9.7k votes/s) — the fence is not the binding cost there
    cfg.engine.commit_interval = int(os.environ.get("BENCH_COMMIT_INTERVAL", "1"))
    cfg.engine.idle_flush = float(os.environ.get("BENCH_IDLE_FLUSH", cfg.engine.idle_flush))
    # verify tickets in flight per engine (<=1 = serial reference loop)
    cfg.engine.pipeline_depth = int(
        os.environ.get("BENCH_PIPELINE_DEPTH", cfg.engine.pipeline_depth)
    )
    # shape-stable coalescing: engines dispatch only canonical bucket
    # sizes (full buckets, or linger flushes padded to one) so every
    # batch lands on a prewarmed shape — compile_in_run == 0 by design
    cfg.engine.coalesce = os.environ.get("BENCH_COALESCE", "1") == "1"
    cfg.engine.coalesce_linger = float(
        os.environ.get("BENCH_COALESCE_LINGER", cfg.engine.coalesce_linger)
    )
    # adaptive pipeline depth from the live overlap ratio (opt-in: the
    # banked baselines were measured at fixed depth)
    cfg.engine.adaptive_depth = os.environ.get("BENCH_ADAPTIVE_DEPTH", "0") == "1"
    # background warmup instead of the blocking prewarm above (opt-in —
    # the bench's default contract prewarms fully so the timed phase is
    # provably compile-free; this exercises the serve-while-compiling
    # path: cold batches take the scalar fallback until promotion)
    cfg.engine.background_warmup = (
        os.environ.get("BENCH_BACKGROUND_WARMUP", "0") == "1"
    )
    # engines bank their own compiles in the same persistent cache the
    # bench process already points JAX at (module top)
    cfg.engine.compilation_cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR", _CACHE_DIR
    )
    # mesh-sharded verify + multi-worker host prep: the shared verifier
    # above already carries the mesh; mirroring the knobs into the engine
    # config makes the coalescer round bucket targets to shard
    # divisibility and wires each engine's prep loop to the (shared)
    # host-prep pool
    cfg.engine.mesh_devices = _MESH_DEVICES
    cfg.engine.host_prep_workers = _HOST_PREP_WORKERS
    cfg.engine.host_prep_backend = _HOST_PREP_BACKEND
    cfg.engine.staging_ring = _STAGING_RING
    cfg.engine.wide_buckets = _WIDE_BUCKETS

    # BASELINE config 5: BENCH_CONSENSUS=1 runs the block-path ticker
    # DURING the vote flood (blocks carry the fast-path commits as Vtxs).
    # Blocks tick at a REAL commit cadence: with skip_timeout_commit the
    # ticker fires back-to-back and reaps every tx into block.Txs before
    # the fast path's batching window elapses (measured: 29 blocks, zero
    # fast-path certificates) — which measures the fallback, not the
    # fast path the config exists to exercise.
    with_consensus = os.environ.get("BENCH_CONSENSUS", "0") == "1"
    if with_consensus:
        cfg.consensus.skip_timeout_commit = False
        cfg.consensus.timeout_commit = float(
            os.environ.get("BENCH_TIMEOUT_COMMIT", "1.0")
        )

    # 16/64-validator configs host 4 full nodes: the other validators'
    # votes are pregenerated and replayed (indistinguishable from votes
    # gossiped in from remote peers), so the run scales the REAL config
    # 2-3 axes — [V] epoch-table gather, 2/3-of-64 quorum math, votes/tx
    # volume — without co-locating 64 full-mesh nodes in one process
    # (~4k threads on one core: the r5 64-val run never finished).
    # consensus-enabled runs default to hosting EVERY validator: the
    # block path needs 2/3 of the consensus voters present. That caps how
    # large a consensus bench can be — co-locating tens of full-mesh
    # nodes in one process measures thread thrash, not the protocol (the
    # 64-node r5 run never finished) — so fail fast instead of hanging.
    if with_consensus and n_vals > 8:
        raise ValueError(
            f"BENCH_CONSENSUS=1 hosts all {n_vals} validators as full "
            "in-process nodes; beyond 8 that topology thrashes one host "
            "(use <= 8 validators for consensus-enabled runs)"
        )
    default_nodes = n_vals if with_consensus else min(n_vals, 4)
    n_nodes = int(os.environ.get("BENCH_NODES", str(default_nodes)))
    if with_consensus and n_nodes < n_vals:
        # the block path needs 2/3 of the CONSENSUS voters hosted; with a
        # 4-of-16 subset blocks can never commit and the run would
        # silently measure zero consensus interference (config 5's whole
        # point). Host every validator for consensus-enabled runs.
        raise ValueError(
            f"BENCH_CONSENSUS=1 requires hosting all {n_vals} validators "
            f"(BENCH_NODES={n_nodes}): a hosted subset cannot reach block "
            "quorum"
        )
    net = LocalNet(
        n_vals,
        chain_id="txflow-bench",
        config=cfg,
        use_device_verifier=verifier_kind == "device",
        sign=False,  # pregenerated-vote replay: no signTxRoutine
        mempool_broadcast=False,  # txs are pre-seeded on every node
        priv_vals=priv_vals,
        verifier=shared_verifier,
        enable_consensus=with_consensus,
        index_txs=False,  # nothing queries /tx_search during the bench
        n_nodes=n_nodes,
        voting_powers=stake_powers,
        epoch_config=epoch_config,
    )

    # -- pregenerate txs + every validator's votes (untimed) --
    # BASELINE config 4 (adversarial mix): --byzantine-frac 0.25 (or
    # BENCH_BYZANTINE=0.25) corrupts that fraction of validator 0's
    # signatures; quorum still forms from the honest 3/4, the invalid
    # votes burn verify work, and the run asserts none of them ever lands
    # in a commit certificate.
    byz_frac = float(_cli_or_env("--byzantine-frac", "BENCH_BYZANTINE", "0") or 0)

    # committee mode: ONLY committee members sign — that is the gossip
    # saving itself (votes per tx = committee size). The latency probe
    # anchors on the first signer, which is validator 0 only when it made
    # the sample.
    probe_vi = signer_idx[0]

    def make_corpus(tag: str, count: int):
        txs = [b"%s-%d=v" % (tag.encode(), i) for i in range(count)]
        votes_by_val: list[list[TxVote]] = [[] for _ in range(n_vals)]
        for t_i, tx in enumerate(txs):
            tx_key = hashlib.sha256(tx).digest()
            tx_hash = tx_key.hex().upper()
            for vi in signer_idx:
                pv = net.priv_vals[vi]
                vote = TxVote(
                    height=0,
                    tx_hash=tx_hash,
                    tx_key=tx_key,
                    validator_address=pv.get_address(),
                )
                pv.sign_tx_vote("txflow-bench", vote)
                if vi == 0 and byz_frac > 0 and (t_i % 100) < byz_frac * 100:
                    sig = bytearray(vote.signature)
                    sig[7] ^= 0xFF
                    vote.signature = bytes(sig)
                votes_by_val[vi].append(vote)
        return txs, votes_by_val

    warm_corpus = make_corpus("warm", warm_txs)
    main_corpus = make_corpus("tx", n_txs)

    # commit-latency probes: per node, tx_hash -> commit wall time
    commit_times: list[dict[str, float]] = [dict() for _ in net.nodes]

    def make_cb(idx):
        def cb(ev):
            commit_times[idx][ev.data.tx_hash] = time.perf_counter()

        return cb

    for i, node in enumerate(net.nodes):
        node.event_bus.subscribe_callback(EventTx, make_cb(i))

    net.start()

    def seed_and_replay(txs, votes_by_val, chunk_size, pace_votes_per_sec=0.0):
        """Seed txs everywhere, then stream votes in chunks; returns
        (wall_seconds, inject_time per tx_hash). With a pace, chunks are
        released on a fixed schedule (offered load) instead of back to
        back — that is what makes the measured commit latency a SERVICE
        latency rather than a saturated-queue depth."""
        # txs are seeded per chunk, right before their votes: seeding the
        # whole corpus up front lets the block ticker (BENCH_CONSENSUS=1)
        # reap not-yet-voted txs into blocks and front-run the replayed
        # vote flood (measured: negative commit "latencies", zero
        # fast-path certificates) — in a live system a validator signs
        # within milliseconds of mempool arrival, which per-chunk seeding
        # models and up-front seeding does not.
        inject_t: dict[str, float] = {}
        t0 = time.perf_counter()
        chunk_interval = (
            (chunk_size * len(signer_idx)) / pace_votes_per_sec
            if pace_votes_per_sec
            else 0.0
        )
        for i, base in enumerate(range(0, len(txs), chunk_size)):
            if chunk_interval:
                target = t0 + i * chunk_interval
                delay = target - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
            # batched seeding: one lock-group ingest per (node, chunk)
            # instead of a lock acquire + notify per item on this thread
            # (r5 instrumented profile: 32768 per-vote check_tx calls)
            tx_chunk = txs[base : base + chunk_size]
            for node in net.nodes:
                node.mempool.check_tx_many(tx_chunk)
            t_chunk = time.perf_counter()
            # validator vi's votes enter at node vi % n_nodes: with more
            # validators than hosted nodes (configs 2-3) the extra
            # validators' votes arrive as if gossiped in from remote
            # peers, spread across the hosted nodes' ingest points
            for vi in signer_idx:
                node = net.nodes[vi % len(net.nodes)]
                vote_chunk = votes_by_val[vi][base : base + chunk_size]
                if vi == probe_vi:
                    for vote in vote_chunk:
                        inject_t[vote.tx_hash] = t_chunk
                node.tx_vote_pool.check_tx_many(vote_chunk)
        ok = net.wait_all_committed(txs, timeout=600.0)
        wall = time.perf_counter() - t0
        if not ok:
            raise RuntimeError("timeout waiting for commits")
        return wall, inject_t

    def p50_of(inject_t) -> float:
        lat_ms = []
        for times in commit_times:
            for tx_hash, t_inj in inject_t.items():
                t_c = times.get(tx_hash)
                if t_c is not None:
                    lat_ms.append((t_c - t_inj) * 1e3)
        return statistics.median(lat_ms) if lat_ms else float("nan")

    # warmup: compiles every kernel shape + exercises the full pipeline
    seed_and_replay(*warm_corpus, chunk)
    warm_committed = net.committed_votes_total()

    # phase 1 — THROUGHPUT: the whole corpus offered as fast as possible
    wall, _ = seed_and_replay(*main_corpus, chunk)
    committed = net.committed_votes_total() - warm_committed
    votes_per_sec = committed / wall

    # Residual-compile guard (r5 postmortem: a 169 s phase 1 contained
    # ~160 s of ONE remote kernel compile for a shape the warmup missed,
    # and the contaminated 580-votes/s headline got banked). End-to-end
    # throughput can be host-bound to a fraction of the device-step rate,
    # but a result BELOW device_step/5 is not a steady state this
    # pipeline can produce — by then the compile is banked in the
    # persistent cache, so one rerun with a fresh corpus measures clean.
    phase1_rerun = False
    first_pass_votes_per_sec = votes_per_sec
    audit_corpora = [main_corpus]
    if (
        verifier_kind == "device"
        and device_step_votes_per_sec > 0
        and votes_per_sec < device_step_votes_per_sec / 5
    ):
        print(
            f"bench: phase 1 at {votes_per_sec:.0f} votes/s << device step "
            f"{device_step_votes_per_sec:.0f} — suspected in-run compile; "
            "re-measuring once",
            file=sys.stderr,
        )
        rerun_corpus = make_corpus("rerun", n_txs)
        audit_corpora.append(rerun_corpus)
        before = net.committed_votes_total()
        wall2, _ = seed_and_replay(*rerun_corpus, chunk)
        committed2 = net.committed_votes_total() - before
        rerun_votes_per_sec = committed2 / wall2
        phase1_rerun = True
        if rerun_votes_per_sec > 2 * votes_per_sec:
            # materially faster warm rerun CONFIRMS the compile theory:
            # report the warm steady state as the headline
            committed, wall, votes_per_sec = committed2, wall2, rerun_votes_per_sec

    # phase 2 — LATENCY: a smaller corpus offered at ~60% of measured
    # capacity, in small chunks, so p50 reflects pipeline service time.
    # The pacing axis must match the capacity axis: seed_and_replay paces
    # INJECTED votes (n_txs * n_vals unique votes per run), so capacity is
    # measured on that same axis from phase 1's wall clock — votes_per_sec
    # (committed, summed over nodes) is ~n_nodes x larger and would pace
    # the wrong load (r3 review finding).
    injected_per_sec = (n_txs * len(signer_idx)) / wall
    p50 = float("nan")
    if os.environ.get("BENCH_LATENCY", "1") == "1":
        lat_txs = max(64, min(n_txs // 4, 2048))
        lat_corpus = make_corpus("lat", lat_txs)
        lat_chunk = max(8, min(chunk // 8, 256))
        _, inject_t = seed_and_replay(
            *lat_corpus, lat_chunk, 0.6 * injected_per_sec
        )
        p50 = p50_of(inject_t)

    # phase 2b — LATENCY SWEEP (judge r4 item 9: the reference's headline
    # is realtime per-tx commit): p50 at light offered loads, where the
    # engine's idle_flush mode should commit a tx's vote burst without
    # sitting out the full batch_wait. BENCH_LATENCY_SWEEP=0 skips.
    latency_sweep = {}
    if (
        os.environ.get("BENCH_LATENCY", "1") == "1"
        and os.environ.get("BENCH_LATENCY_SWEEP", "1") == "1"
    ):
        for frac in (0.1, 0.3):
            sw_txs = max(32, lat_txs // 4)
            sw_corpus = make_corpus("sweep%d" % int(frac * 100), sw_txs)
            _, sw_inject = seed_and_replay(
                *sw_corpus, max(4, lat_chunk // 4), frac * injected_per_sec
            )
            latency_sweep["p50_ms_at_%d%%" % int(frac * 100)] = round(
                p50_of(sw_inject), 2
            )
        latency_sweep["p50_ms_at_60%"] = round(p50, 2)

    result = {
        "metric": "committed_txvotes_per_sec",
        "value": round(votes_per_sec, 1),
        "unit": "votes/s",
        "vs_baseline": round(votes_per_sec / BASELINE_VOTES_PER_SEC, 3),
        # None, not NaN: json.dumps renders NaN as a bare token that
        # strict RFC-8259 parsers (jq, Go) reject
        "p50_commit_latency_ms": round(p50, 2) if p50 == p50 else None,
        "latency_offered_load": "60% of measured throughput",
        **({"latency_sweep": latency_sweep} if latency_sweep else {}),
        "platform": platform,
        "verifier": verifier_kind,
        "validators": n_vals,
        "nodes": len(net.nodes),
        "txs": n_txs,
        "committed_votes": committed,
        "wall_s": round(wall, 3),
        "app_commit_interval": cfg.engine.commit_interval,
        # stake geometry of the run: the Gini coefficient summarizes how
        # concentrated the distribution was (0 = uniform), so two runs'
        # numbers are comparable without re-deriving the power list
        "stake_dist": stake_dist,
        "stake_gini": round(gini(stake_powers), 4),
        # sublinear-certificate axes (committee/): 0 committee_size =
        # full-set seed behavior — legacy bank entries without the key
        # default to 0 on load, so every entry is comparable
        "committee_size": committee_set.size() if committee_set is not None else 0,
        "votes_gossiped_per_tx": len(signer_idx),
    }
    # measured certificate geometry, from committed certs (not the model):
    # in committee mode vote count must track COMMITTEE quorum, flat in
    # validator count; in full-set mode this documents the linear cost
    # the committee config removes
    from txflow_tpu.types import encode_tx_vote as _enc_vote

    cert_votes = []
    cert_bytes = []
    for tx in main_corpus[0][:16]:
        cvs = net.nodes[0].tx_store.load_tx_votes(
            hashlib.sha256(tx).hexdigest().upper()
        )
        if cvs:
            cert_votes.append(len(cvs))
            cert_bytes.append(sum(len(_enc_vote(v)) for v in cvs))
    if cert_votes:
        result["cert_votes"] = round(sum(cert_votes) / len(cert_votes), 1)
        result["cert_bytes"] = round(sum(cert_bytes) / len(cert_bytes))
    if committee_set is not None and hasattr(shared_verifier, "batch_calls"):
        # evidence the verify path was the fused one: device dispatches
        # vs per-signature fallthroughs for small batches
        result["cert_verify_batch_calls"] = shared_verifier.batch_calls
        result["cert_verify_scalar_calls"] = shared_verifier.scalar_calls
        result["cert_verify_batched_votes"] = shared_verifier.batched_votes
    if verifier_kind == "device":
        result["device_step_votes_per_sec"] = device_step_votes_per_sec
    if phase1_rerun:
        # both passes recorded: a reader must be able to tell a CONFIRMED
        # compile (rerun much faster -> rerun is the headline) from a
        # genuine bottleneck (rerun similar -> FIRST pass stays headline)
        result["phase1_first_pass_votes_per_sec"] = round(
            first_pass_votes_per_sec, 1
        )
        result["phase1_rerun_votes_per_sec"] = round(rerun_votes_per_sec, 1)
        result["phase1_compile_confirmed"] = (
            rerun_votes_per_sec > 2 * first_pass_votes_per_sec
        )
    if byz_frac > 0:
        result["byzantine_fraction"] = byz_frac
        byz_addr = net.priv_vals[0].get_address()
        # corrupted votes must never appear in a certificate: validator 0's
        # honest vote for a corrupted slot was never injected, so its
        # address simply must be absent from those txs' certificates
        bad = 0
        # per-corpus enumerate: make_corpus corrupts by each tx's index
        # WITHIN ITS OWN corpus — a concatenated walk would audit honest
        # slots (spurious failure) and skip corrupted ones (r5 review)
        audit_txs = [
            (t_i, tx) for corpus in audit_corpora for t_i, tx in enumerate(corpus[0])
        ]
        for node in net.nodes:
            for t_i, tx in audit_txs:
                if (t_i % 100) < byz_frac * 100:
                    votes = node.tx_store.load_tx_votes(
                        hashlib.sha256(tx).hexdigest().upper()
                    )
                    if votes and byz_addr in {v.validator_address for v in votes}:
                        bad += 1
        result["byzantine_votes_in_certificates"] = bad
        # where the adversarial load was absorbed: pre-verify gate drops
        # (unknown/stale/replayed, before any device work) vs invalid
        # verdicts (paid for a verify slot). Direct pool injection skips
        # the gossip reactor, so drops here come from replay/stale
        # filtering only — the gossip-path gate is drilled in
        # tests/test_byzantine_gossip.py.
        snaps = [n.byzantine_ledger.snapshot() for n in net.nodes]
        pre_drops = sum(s["pre_verify_drops"] for s in snaps)
        invalid = sum(
            int(n.txflow.metrics.invalid_votes.value()) for n in net.nodes
        )
        verified = sum(
            int(n.txflow.metrics.verified_votes.value()) for n in net.nodes
        )
        result["byzantine_pre_verify_drops"] = pre_drops
        result["byzantine_pre_verify_drop_rate"] = round(
            pre_drops / max(pre_drops + verified + invalid, 1), 4
        )
        result["byzantine_invalid_votes"] = invalid
        if bad:
            # a corrupted signature landing in a commit certificate is a
            # soundness regression, not a perf data point — fail loudly
            raise AssertionError(
                f"{bad} byzantine votes appeared in commit certificates"
            )
    if with_consensus:
        result["consensus"] = True
        result["block_height"] = max(n.block_store.height() for n in net.nodes)
    # verify-pipeline overlap: device-busy / engine-active wall time,
    # averaged over nodes (1.0 = verify calls back to back; low values
    # mean host prep/routing dominates — see COMPONENTS.md for tuning)
    pipe_stats = [n.txflow.pipeline_stats() for n in net.nodes]
    ratios = [s["overlap_ratio"] for s in pipe_stats if s["overlap_ratio"] is not None]
    result["pipeline_depth"] = cfg.engine.pipeline_depth
    if ratios:
        result["overlap_ratio"] = round(sum(ratios) / len(ratios), 4)
    # shape-stable coalescing audit (engine._BatchCoalescer, summed over
    # nodes): coalesced_batches dispatched at exactly a canonical bucket
    # (zero padding), linger_flushes partial by deadline, and
    # cold_fallback_votes served on the CPU path while background warmup
    # compiled their shape (0 unless BENCH_BACKGROUND_WARMUP=1)
    # host-prep attribution: sign-bytes assembly wall time and pool-shard
    # wait summed over engines, plus the shared verifier's compact-prep
    # split — this is what the ">= 2x host-prep reduction on a mesh"
    # acceptance check reads
    result["mesh_devices"] = (
        getattr(device_verifier, "_n_shards", 1)
        if device_verifier is not None
        else 0
    )
    result["host_prep_workers"] = _HOST_PREP_WORKERS
    # live backend, per node (a failed process spawn falls back to
    # threads — the result records what actually ran, so bank entries
    # from process- and thread-backend runs are comparable by label)
    backends = {
        s.get("host_prep_backend") for s in pipe_stats
        if s.get("host_prep_backend")
    }
    result["host_prep_backend"] = (
        sorted(backends)[0] if len(backends) == 1
        else (sorted(backends) or None)
    )
    host_prep = {
        "sign_s": round(sum(s.get("prep_sign_s", 0.0) for s in pipe_stats), 4),
        "pool_wait_s": round(
            sum(s.get("prep_pool_wait_s", 0.0) for s in pipe_stats), 4
        ),
    }
    if device_verifier is not None:
        ps = device_verifier.prep_stats()
        host_prep["compact_s"] = round(ps.get("compact_s", 0.0), 4)
        host_prep["compact_pool_wait_s"] = round(
            ps.get("compact_pool_wait_s", 0.0), 4
        )
        pool = getattr(device_verifier, "_host_pool", None)
        pool_stats = pool.stats() if pool is not None else {}
        if pool_stats.get("backend") == "process":
            # shared-memory traffic of the process backend: segment
            # bytes shipped per prep call (engine.hostprep _run_typed)
            host_prep["shm_calls"] = pool_stats.get("shm_calls", 0)
            host_prep["shm_bytes_total"] = pool_stats.get(
                "shm_bytes_total", 0
            )
            host_prep["proc_wait_s"] = round(
                pool_stats.get("proc_wait_s", 0.0), 4
            )
    result["host_prep"] = host_prep
    # double-buffered readback: ring depth + the hidden-overlap ledger
    # (parallel.staging; readback seconds that ran under the engine's
    # next-batch prep instead of on the critical path)
    result["staging_ring"] = _STAGING_RING
    ring_stats = [s.get("staging") for s in pipe_stats if s.get("staging")]
    if device_verifier is not None and not ring_stats:
        dv_ring = device_verifier.staging_stats()
        if dv_ring is not None:
            ring_stats = [dv_ring]
    if ring_stats:
        # engines share the verifier's ring: the snapshots are the same
        # counters, take the freshest rather than summing duplicates
        ring = max(ring_stats, key=lambda r: r.get("slots_total", 0))
        result["staging"] = {
            "depth": ring.get("depth"),
            "slots_total": ring.get("slots_total", 0),
            "readback_s": round(ring.get("readback_s", 0.0), 4),
            "hidden_s": round(ring.get("hidden_s", 0.0), 4),
            "overlap_frac": round(
                ring.get("hidden_s", 0.0) / ring["readback_s"], 4
            ) if ring.get("readback_s") else 0.0,
        }
    coalesce = [s.get("coalesce") or {} for s in pipe_stats]
    result["coalesced_batches"] = sum(c.get("full_batches", 0) for c in coalesce)
    result["linger_flushes"] = sum(c.get("linger_flushes", 0) for c in coalesce)
    result["cold_fallback_votes"] = sum(
        c.get("cold_fallback_votes", 0) for c in coalesce
    )
    if cfg.engine.adaptive_depth:
        depths = [
            (s.get("adaptive_depth") or {}).get("depth") for s in pipe_stats
        ]
        result["adaptive_depth_final"] = [d for d in depths if d is not None]
    if warm_registry is not None:
        # compile-contamination audit: warm_shapes is the prewarmed set,
        # cold_shapes every shape that compiled DURING the timed phases
        result["warm_shapes"] = len(warm_registry.warmed)
        cold = warm_registry.cold_shapes()
        result["compile_in_run"] = bool(cold)
        if cold:
            result["cold_shapes"] = [list(s) for s in cold]
    else:
        # scalar runs have no device programs — nothing can compile
        # mid-run; emit the key anyway so --assert-warm and dashboards
        # read one schema
        result.setdefault("compile_in_run", False)
    if shared_verifier is not None and hasattr(shared_verifier, "stop"):
        result["verifier_mux"] = True
        net.stop()
        shared_verifier.stop()
    else:
        net.stop()
    return result


_ARTIFACT_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_artifacts")
_TPU_LATEST = os.path.join(_ARTIFACT_DIR, "tpu_latest.json")


def _is_contaminated(entry: dict) -> bool:
    """Did this banked measurement's timed phase contain a compile?

    Explicit ``contaminated`` flag first (written by every bank since the
    supersede rule landed). Legacy entries are judged by their own
    evidence: a recorded in-run compile, or — for entries banked before
    ``compile_in_run`` existed at all — a measurement_note that already
    declares itself compromised/superseded (the r5 580-votes/s entry)."""
    if entry.get("contaminated") is not None:
        return bool(entry["contaminated"])
    if entry.get("compile_in_run"):
        return True
    note = str(entry.get("measurement_note", "")).lower()
    return "compile_in_run" not in entry and (
        "contaminated" in note or "superseded" in note
    )


def _bank_tpu_result(result: dict) -> None:
    """Persist every good TPU measurement: the axon tunnel degrades for
    hours at a time (r3: down from 07:30 through round end, so the
    authoritative artifact recorded a CPU fallback although the TPU had
    been measured all morning). The freshest banked measurement becomes
    the fallback payload when a later probe fails.

    Supersede contract: a clean run ALWAYS overwrites (including the
    legacy compile-contaminated 580-votes/s entry); a contaminated run
    never displaces a clean banked measurement — a fallback payload that
    mostly measured one kernel compile is worse than a stale clean one."""
    try:
        os.makedirs(_ARTIFACT_DIR, exist_ok=True)
        result = dict(
            result,
            measured_at_unix=round(time.time(), 1),
            contaminated=bool(result.get("compile_in_run")),
            # backend label makes process- and thread-backend runs
            # comparable bank entries: same supersede contract (clean
            # overwrites clean regardless of backend — the bank tracks
            # the freshest clean measurement, and the label says which
            # host-prep posture produced it)
            host_prep_backend=result.get("host_prep_backend") or "thread",
        )
        existing = _load_banked_tpu()
        if (
            existing is not None
            and result["contaminated"]
            and not _is_contaminated(existing)
        ):
            return
        with open(_TPU_LATEST, "w") as f:
            f.write(json.dumps(result))
    except OSError:
        pass


def _load_banked_tpu() -> dict | None:
    try:
        with open(_TPU_LATEST) as f:
            entry = json.loads(f.read())
        # legacy entries predate the backend label: they all measured
        # the thread backend, stamp it so comparisons are uniform
        entry.setdefault("host_prep_backend", "thread")
        # legacy entries predate committee sampling: all full-set runs
        entry.setdefault("committee_size", 0)
        return entry
    except (OSError, ValueError):
        return None


_COMMITTEE_LATEST = os.path.join(_ARTIFACT_DIR, "committee_latest.json")


def _bank_committee_result(result: dict) -> None:
    """Persist committee-mode measurements in their OWN bank, under the
    same clean-supersede contract as the default-config tpu bank: a clean
    run always overwrites, a contaminated run never displaces a clean
    banked entry. A separate file because committee runs measure a
    different config axis (committee quorum, member-only gossip) — they
    must never overwrite the full-set default-config reference, and vice
    versa. Banked on any platform: the committee_size / cert_votes /
    votes_gossiped_per_tx geometry is platform-independent evidence."""
    try:
        os.makedirs(_ARTIFACT_DIR, exist_ok=True)
        result = dict(
            result,
            measured_at_unix=round(time.time(), 1),
            contaminated=bool(result.get("compile_in_run")),
        )
        existing = _load_banked_committee()
        if (
            existing is not None
            and result["contaminated"]
            and not _is_contaminated(existing)
        ):
            return
        with open(_COMMITTEE_LATEST, "w") as f:
            f.write(json.dumps(result))
    except OSError:
        pass


def _load_banked_committee() -> dict | None:
    try:
        with open(_COMMITTEE_LATEST) as f:
            return json.loads(f.read())
    except (OSError, ValueError):
        return None


_LATENCY_LATEST = os.path.join(_ARTIFACT_DIR, "latency_latest.json")


def _latency_clean(entry: dict) -> bool:
    """Is this latency-SLO measurement fit to be the banked reference?

    Clean means the run actually measured the priority lane (p50 AND p99
    present), finished without an error, and did not breach its own SLO
    gate. Mirrors _is_contaminated's spirit for the throughput bank: a
    banked artifact that mostly measured a timeout is worse than a stale
    clean one."""
    if entry.get("error"):
        return False
    if entry.get("slo_breach"):
        return False
    return (
        entry.get("priority_p50_ms") is not None
        and entry.get("priority_p99_ms") is not None
    )


def _bank_latency_result(result: dict) -> None:
    """Persist the latency-SLO measurement alongside the TPU throughput
    bank, under the same supersede contract (_bank_tpu_result): a clean
    run always overwrites; a dirty run (error / breach / missing lane
    data) never displaces a clean banked entry — so a latency regression
    cannot silently replace the reference numbers it regressed from."""
    try:
        os.makedirs(_ARTIFACT_DIR, exist_ok=True)
        result = dict(result, measured_at_unix=round(time.time(), 1))
        existing = _load_banked_latency()
        if (
            existing is not None
            and not _latency_clean(result)
            and _latency_clean(existing)
        ):
            return
        with open(_LATENCY_LATEST, "w") as f:
            f.write(json.dumps(result))
    except OSError:
        pass


def _load_banked_latency() -> dict | None:
    try:
        with open(_LATENCY_LATEST) as f:
            return json.loads(f.read())
    except (OSError, ValueError):
        return None


def _no_cache_companion(platform: str) -> dict | None:
    """Throughput-only re-run with BENCH_SHARE_CACHE=0, in a subprocess.

    The default configuration shares one VerifyCache across the 4
    co-located engines — a real deployment pattern (SURVEY §2.4), but one
    the Go reference cannot replicate, so the vs-baseline comparison must
    come from the no-cache number (r4 judge item 4). Skipped when the
    caller already chose a cache mode explicitly or this IS the companion.
    """
    if os.environ.get("BENCH_COMPANION") == "1":
        return None
    if _COMMITTEE_SIZE > 0:
        # committee mode never uses the shared verify cache (the batch
        # verifier's one-call-per-batch path is cacheless by design), so
        # there is no cache/no-cache distinction to measure
        return None
    if "BENCH_SHARE_CACHE" in os.environ:
        return None  # explicit choice: report exactly what was asked
    env = dict(
        os.environ,
        BENCH_COMPANION="1",
        BENCH_SHARE_CACHE="0",
        BENCH_LATENCY="0",
        BENCH_PLATFORM=platform,  # no second TPU probe
    )
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True,
            text=True,
            timeout=1800,
            env=env,
        )
        line = (r.stdout or "").strip().splitlines()[-1]
        return json.loads(line)
    except Exception as e:
        return {"error": repr(e)[:200]}


def _stamp_lint(result: dict) -> None:
    """Stamp the tree's static-analysis posture into the result.

    A banked measurement is only trustworthy if the code that produced it
    held the repo invariants the txlint passes encode (no hot-loop syncs,
    no recompile hazards, ...). The digest fingerprints the lint REPORT —
    rule inventory plus every (path, line, rule) finding — so two results
    with equal digests ran under the identical lint verdict, and a result
    from a dirty tree says so on its face. Never fails the bench."""
    try:
        from txflow_tpu.analysis import core as _lint_core

        report = _lint_core.lint_tree(os.path.dirname(os.path.abspath(__file__)))
        blob = json.dumps(
            {
                "rules": sorted(_lint_core.RULES),
                "violations": [
                    [v.path, v.line, v.rule] for v in report["violations"]
                ],
                "suppressed": len(report["suppressed"]),
                "files": report["files_scanned"],
            },
            sort_keys=True,
        )
        result["lint"] = {
            "clean": not report["violations"] and not report["errors"],
            "digest": hashlib.sha256(blob.encode()).hexdigest()[:12],
        }
    except Exception as e:  # pragma: no cover - never block a measurement
        result["lint"] = {"clean": None, "error": repr(e)[:120]}


def main():
    platform = _resolve_platform()
    if "--latency-slo" in sys.argv:
        # tail-latency SLO gate (mirror of --assert-warm's contract: the
        # result line always prints; the breach exits 3 AFTER it)
        budget = os.environ.get("BENCH_SLO_P99_MS")
        if "--slo-p99-ms" in sys.argv:
            budget = sys.argv[sys.argv.index("--slo-p99-ms") + 1]
        try:
            result = run_latency_slo(platform)
        except Exception as e:
            result = {
                "metric": "latency_slo",
                "error": repr(e)[:300],
                "platform": platform,
                "lanes": {},
            }
        if budget is not None:
            result["slo_p99_ms"] = float(budget)
            result["slo_breach"] = slo_breached(result, budget)
        _stamp_lint(result)
        _bank_latency_result(result)
        print(json.dumps(result))
        if result.get("slo_breach"):
            p99 = ((result.get("lanes") or {}).get("priority") or {}).get(
                "p99_ms"
            )
            print(
                f"bench: --latency-slo failed: priority-lane p99 {p99} ms "
                f"over budget {budget} ms",
                file=sys.stderr,
            )
            sys.exit(3)
        return
    try:
        result = run_bench(platform)
        companion = _no_cache_companion(result.get("platform", platform))
        if companion is not None:
            result["metric_definition"] = (
                "committed certificate votes summed over all co-located "
                "nodes per wall second; default config shares one verify-"
                "result cache across the nodes' engines"
            )
            same_platform = companion.get("platform") == result.get("platform")
            if companion.get("value") is not None and same_platform:
                # the honest baseline comparison: the Go reference cannot
                # share verifies across nodes (a 0.0 value is a real —
                # bad — measurement, not a failure)
                result["value_no_shared_cache"] = companion["value"]
                result["vs_baseline"] = round(
                    companion["value"] / BASELINE_VOTES_PER_SEC, 3
                )
            else:
                # companion failed or fell back to a DIFFERENT platform
                # (e.g. tunnel wedged mid-run): a cross-platform or
                # missing ratio would be the exact inflated/mismatched
                # comparison this companion exists to prevent — say so
                # instead of keeping the shared-cache ratio
                result["vs_baseline"] = None
                if companion.get("error"):
                    result["no_cache_companion_error"] = companion["error"]
                elif not same_platform:
                    result["no_cache_companion_error"] = (
                        "companion platform %r != %r"
                        % (companion.get("platform"), result.get("platform"))
                    )
                else:
                    result["no_cache_companion_error"] = "companion returned no value"
    except Exception as e:
        if platform != "cpu" and os.environ.get("BENCH_PLATFORM") != "cpu":
            # TPU path failed mid-run: re-exec once on CPU so the driver
            # still records a real number (flagged by "platform": "cpu").
            print(f"bench: {platform} run failed ({e}); retrying on CPU", file=sys.stderr)
            env = _sanitized_cpu_env()
            env["BENCH_TPU_FELL_BACK"] = "1"
            os.execve(sys.executable, [sys.executable] + sys.argv, env)
        result = {
            "metric": "committed_txvotes_per_sec",
            "value": 0.0,
            "unit": "votes/s",
            "vs_baseline": 0.0,
            "error": repr(e)[:300],
            "platform": platform,
        }
    if _PROBE_DIAGNOSTICS:
        result["probe_diagnostics"] = _PROBE_DIAGNOSTICS
    # stamp before banking so bank entries carry the lint posture too
    _stamp_lint(result)
    if (
        _COMMITTEE_SIZE > 0
        and result.get("value", 0) > 0
        and os.environ.get("BENCH_COMPANION") != "1"
    ):
        # committee-mode runs bank in their own file (clean-supersede),
        # never the default-config tpu bank
        _bank_committee_result(result)
    elif (
        result.get("platform") not in (None, "cpu")
        and result.get("value", 0) > 0
        and os.environ.get("BENCH_COMPANION") != "1"
        and _N_VALIDATORS == 4
        and os.environ.get("BENCH_CONSENSUS", "0") != "1"
        and float(os.environ.get("BENCH_BYZANTINE", "0")) == 0
        and os.environ.get("BENCH_NODES") is None
    ):
        # only the DEFAULT config banks: the no-cache companion and the
        # 16/64-validator / consensus-on sweep runs must never overwrite
        # the banked default-config measurement
        _bank_tpu_result(result)
    elif result.get("platform") == "cpu" and (
        _PROBE_DIAGNOSTICS or os.environ.get("BENCH_TPU_FELL_BACK") == "1"
    ):
        # CPU number ONLY because the TPU was unreachable right now (probe
        # failure / mid-run tunnel loss — never an explicit BENCH_PLATFORM
        # choice). The live CPU run stays the headline — a consumer reading
        # only the top-level metric must see THIS run's measurement (r4
        # advisor) — and the freshest banked TPU measurement rides along
        # under its own key for context.
        banked = _load_banked_tpu()
        if banked is not None:
            banked["banked_age_s"] = round(
                time.time() - banked.get("measured_at_unix", 0), 1
            )
            result["last_known_tpu"] = banked
    print(json.dumps(result))
    if "--assert-warm" in sys.argv or os.environ.get("BENCH_ASSERT_WARM") == "1":
        # CI gate for the shape-stable hot path: with prewarm enabled the
        # steady state must be compile-free — any in-run compile (a shape
        # the registry failed to enumerate, or prewarm off) fails the run
        # AFTER the result line so the measurement is still recorded
        if result.get("compile_in_run"):
            print(
                "bench: --assert-warm failed: hot path compiled in-run "
                f"(cold shapes: {result.get('cold_shapes')})",
                file=sys.stderr,
            )
            sys.exit(3)


if __name__ == "__main__":
    main()
