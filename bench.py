"""North-star benchmark: committed TxVotes/sec through the batched verifier.

Measurement protocol per BASELINE.json config 1-2: a 4-validator set,
pregenerated signed TxVotes (4 votes per tx — every commit decision needs
a full honest quorum at equal stake: quorum = floor(40*2/3)+1 = 27 > 3*10),
replayed through the device verify+tally path in fixed-size batches. The
measured rate counts verified-and-tallied votes per second of sustained
wall-clock, including per-batch host prep (sig parsing, SHA-512 folding,
scalar decomposition, table gather) and the D2H readback of the
valid/stake/maj23 masks — i.e. everything between "votes in the pool" and
"quorum decision on host".

Baseline: the reference's hot path is one pure-Go ed25519 verify per vote,
single-threaded (reference txflow/service.go:123-166, ~50-100us/verify =>
~10-20k votes/s/core; BASELINE.md). vs_baseline is measured against the
generous end of that ceiling, 20,000 votes/s.

Prints exactly one JSON line.
"""

import hashlib
import json
import os
import sys
import time

import numpy as np

BASELINE_VOTES_PER_SEC = 20_000.0  # reference CPU ceiling, BASELINE.md
CHAIN_ID = "txflow-bench"


def main():
    from txflow_tpu.crypto import ed25519 as host_ed
    from txflow_tpu.types import Validator, ValidatorSet, canonical_sign_bytes
    from txflow_tpu.verifier import DeviceVoteVerifier

    n_vals = int(os.environ.get("BENCH_VALIDATORS", "4"))
    batch = int(os.environ.get("BENCH_BATCH", "4096"))
    iters = int(os.environ.get("BENCH_ITERS", "8"))

    seeds = [hashlib.sha256(b"bench-val%d" % i).digest() for i in range(n_vals)]
    pubs = [host_ed.public_key_from_seed(s) for s in seeds]
    vals = ValidatorSet([Validator.from_pub_key(p, 10) for p in pubs])
    seed_by_index = [dict(zip(pubs, seeds))[v.pub_key] for v in vals]

    n_txs = batch // n_vals
    msgs, sigs, vidx, slot = [], [], [], []
    for t in range(n_txs):
        tx_hash = hashlib.sha256(b"bench-tx%d" % t).hexdigest().upper()
        msg = canonical_sign_bytes(CHAIN_ID, 1, tx_hash, 1700000000_000000000 + t)
        for vi in range(n_vals):
            msgs.append(msg)
            sigs.append(host_ed.sign(seed_by_index[vi], msg))
            vidx.append(vi)
            slot.append(t)
    vidx = np.array(vidx)
    slot = np.array(slot, np.int32)

    verifier = DeviceVoteVerifier(vals)

    # warmup: compile + correctness gate (commit decisions must be unanimous)
    r = verifier.verify_and_tally(msgs, sigs, vidx, slot, n_txs)
    assert r.valid.all(), "bench corpus must verify"
    assert r.maj23.all(), "full quorum expected on every tx"

    t0 = time.perf_counter()
    for _ in range(iters):
        r = verifier.verify_and_tally(msgs, sigs, vidx, slot, n_txs)
        assert r.maj23.all()
    dt = time.perf_counter() - t0

    votes_per_sec = iters * len(msgs) / dt
    print(
        json.dumps(
            {
                "metric": "committed_txvotes_per_sec",
                "value": round(votes_per_sec, 1),
                "unit": "votes/s",
                "vs_baseline": round(votes_per_sec / BASELINE_VOTES_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
