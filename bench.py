"""North-star benchmark: BASELINE config 1, measured end to end.

Protocol (BASELINE.json config 1-2): an N-validator in-process network —
every node runs the full fast path (txvotepool -> batched device
verify+tally -> TxStore persist -> kvstore ABCI execute -> pool purge ->
commitpool) over real gossip reactors wired with in-memory pipes. Txs are
pre-seeded into every mempool and TxVotes are PREGENERATED (signing sits
outside the timed loop, per the config's "pregenerated TxVotes replayed
through txvotepool"); the timed phase streams each validator's votes into
its own node's vote pool in chunks, vote gossip fans them out, and every
node independently verifies, tallies, and commits every tx.

Metric: committed TxVotes/sec summed over nodes (votes inside commit
certificates persisted to TxStores) + p50 tx-commit latency (vote-chunk
injection -> per-node commit event). Baseline: the reference's hot path is
one pure-Go ed25519 verify per vote, single-threaded (reference
txflow/service.go:123-166, ~50-100us/verify => 10-20k votes/s/core;
BASELINE.md). vs_baseline measures against the generous end, 20,000/s.

Robustness contract with the driver: prints EXACTLY ONE JSON line on
stdout no matter what. The TPU backend is probed in a subprocess first
(round 1 recorded both an UNAVAILABLE init failure and a multi-minute
init hang); on probe failure the bench falls back to CPU and says so in
the JSON.
"""

import hashlib
import json
import os
import statistics
import subprocess
import sys
import time


def _resolve_platform() -> str:
    """Probe the default JAX backend in a subprocess; fall back to CPU.

    The probe has a hard timeout so a hanging TPU client (round-1
    MULTICHIP artifact) cannot eat the driver's whole budget, and it runs
    twice because a previous holder of the chip may need a moment to die.
    """
    if os.environ.get("BENCH_PLATFORM"):
        plat = os.environ["BENCH_PLATFORM"]
        if plat == "cpu":
            _force_cpu()
        return plat
    probe = "import jax; jax.devices(); print(jax.default_backend())"
    for attempt in range(2):
        try:
            r = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True,
                text=True,
                timeout=180,
            )
            if r.returncode == 0 and r.stdout.strip():
                return r.stdout.strip().splitlines()[-1]
        except subprocess.TimeoutExpired:
            pass
        print(f"bench: TPU probe attempt {attempt + 1} failed", file=sys.stderr)
        time.sleep(3)
    _force_cpu()
    return "cpu"


def _force_cpu() -> None:
    """Pin this process to the CPU backend.

    The environment's PJRT site hook can pre-register the TPU platform and
    ignore the JAX_PLATFORMS env var, so the pin must also go through
    jax.config after import — BEFORE any backend is created (a TPU client
    init here can hang for minutes)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


BASELINE_VOTES_PER_SEC = 20_000.0  # reference CPU ceiling, BASELINE.md


def run_bench(platform: str) -> dict:
    from txflow_tpu.node import LocalNet
    from txflow_tpu.types import TxVote
    from txflow_tpu.utils.events import EventTx

    n_vals = int(os.environ.get("BENCH_VALIDATORS", "4"))
    # On the CPU fallback the TPU-shaped curve kernel is ~100x slower than
    # host crypto, so the bench drops to the framework's documented
    # fallback rung (SURVEY §7 hard-part 1): the scalar host verifier
    # behind the same VoteVerifier interface, with a smaller corpus.
    on_cpu = platform == "cpu"
    verifier_kind = os.environ.get("BENCH_VERIFIER", "scalar" if on_cpu else "device")
    n_txs = int(os.environ.get("BENCH_TXS", "512" if on_cpu else "2048"))
    chunk = int(os.environ.get("BENCH_CHUNK", "512"))
    warm_txs = min(64 if on_cpu else 256, n_txs)

    net = LocalNet(
        n_vals,
        chain_id="txflow-bench",
        use_device_verifier=verifier_kind == "device",
        sign=False,  # pregenerated-vote replay: no signTxRoutine
        mempool_broadcast=False,  # txs are pre-seeded on every node
    )

    # -- pregenerate txs + every validator's votes (untimed) --
    def make_corpus(tag: str, count: int):
        txs = [b"%s-%d=v" % (tag.encode(), i) for i in range(count)]
        votes_by_val: list[list[TxVote]] = [[] for _ in range(n_vals)]
        for tx in txs:
            tx_key = hashlib.sha256(tx).digest()
            tx_hash = tx_key.hex().upper()
            for vi, pv in enumerate(net.priv_vals):
                vote = TxVote(
                    height=0,
                    tx_hash=tx_hash,
                    tx_key=tx_key,
                    validator_address=pv.get_address(),
                )
                pv.sign_tx_vote("txflow-bench", vote)
                votes_by_val[vi].append(vote)
        return txs, votes_by_val

    warm_corpus = make_corpus("warm", warm_txs)
    main_corpus = make_corpus("tx", n_txs)

    # commit-latency probes: per node, tx_hash -> commit wall time
    commit_times: list[dict[str, float]] = [dict() for _ in net.nodes]

    def make_cb(idx):
        def cb(ev):
            commit_times[idx][ev.data.tx_hash] = time.perf_counter()

        return cb

    for i, node in enumerate(net.nodes):
        node.event_bus.subscribe_callback(EventTx, make_cb(i))

    net.start()

    def seed_and_replay(txs, votes_by_val, chunk_size):
        """Seed txs everywhere, then stream votes in chunks; returns
        (wall_seconds, inject_time per tx_hash)."""
        for node in net.nodes:
            for tx in txs:
                node.mempool.check_tx(tx)
        inject_t: dict[str, float] = {}
        t0 = time.perf_counter()
        for base in range(0, len(txs), chunk_size):
            t_chunk = time.perf_counter()
            for vi, node in enumerate(net.nodes):
                pool = node.tx_vote_pool
                for vote in votes_by_val[vi][base : base + chunk_size]:
                    if vi == 0:
                        inject_t[vote.tx_hash] = t_chunk
                    try:
                        pool.check_tx(vote)
                    except Exception:
                        pass
        ok = net.wait_all_committed(txs, timeout=600.0)
        wall = time.perf_counter() - t0
        if not ok:
            raise RuntimeError("timeout waiting for commits")
        return wall, inject_t

    # warmup: compiles every kernel shape + exercises the full pipeline
    seed_and_replay(*warm_corpus, chunk)
    warm_committed = net.committed_votes_total()

    wall, inject_t = seed_and_replay(*main_corpus, chunk)
    committed = net.committed_votes_total() - warm_committed

    lat_ms = []
    for times in commit_times:
        for tx_hash, t_inj in inject_t.items():
            t_c = times.get(tx_hash)
            if t_c is not None:
                lat_ms.append((t_c - t_inj) * 1e3)
    p50 = statistics.median(lat_ms) if lat_ms else float("nan")

    net.stop()
    votes_per_sec = committed / wall
    return {
        "metric": "committed_txvotes_per_sec",
        "value": round(votes_per_sec, 1),
        "unit": "votes/s",
        "vs_baseline": round(votes_per_sec / BASELINE_VOTES_PER_SEC, 3),
        "p50_commit_latency_ms": round(p50, 2),
        "platform": platform,
        "verifier": verifier_kind,
        "validators": n_vals,
        "txs": n_txs,
        "committed_votes": committed,
        "wall_s": round(wall, 3),
    }


def main():
    platform = _resolve_platform()
    try:
        result = run_bench(platform)
    except Exception as e:
        if platform != "cpu" and os.environ.get("BENCH_PLATFORM") != "cpu":
            # TPU path failed mid-run: re-exec once on CPU so the driver
            # still records a real number (flagged by "platform": "cpu").
            print(f"bench: {platform} run failed ({e}); retrying on CPU", file=sys.stderr)
            env = dict(os.environ, BENCH_PLATFORM="cpu", JAX_PLATFORMS="cpu")
            os.execve(sys.executable, [sys.executable] + sys.argv, env)
        result = {
            "metric": "committed_txvotes_per_sec",
            "value": 0.0,
            "unit": "votes/s",
            "vs_baseline": 0.0,
            "error": repr(e)[:300],
            "platform": platform,
        }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
