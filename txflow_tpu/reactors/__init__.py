"""Gossip reactors: mempool tx flood + txvotepool sign/vote flood.

The two pool reactors of the reference — mempool/reactor.go (channel 0x30)
and txvotepool/reactor.go (channel 0x32, including the ``signTxRoutine``
that turns every mempool tx into this validator's TxVote) — rebuilt over
the p2p package with batched frames.
"""

from .mempool_reactor import MempoolReactor
from .txvote_reactor import StateView, TxVoteReactor

__all__ = ["MempoolReactor", "TxVoteReactor", "StateView"]
