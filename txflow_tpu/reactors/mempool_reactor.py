"""Mempool reactor: raw-tx flood on channel 0x30 (reference mempool/reactor.go).

Same shape as the vote reactor: per-peer walk of the pool's ingest log
with sender suppression and a 1-block height-lag throttle (reference
mempool/reactor.go:191-260), batched into framed messages. App-level
CheckTx rejections of gossiped txs are logged-and-ignored, matching the
reference (:137); only undecodable frames stop the peer.
"""

from __future__ import annotations

import threading

from ..analysis.lockgraph import make_lock
import time

from ..codec import amino
from ..crypto.hash import sha256
from ..p2p.base import CHANNEL_MEMPOOL, ChannelDescriptor, Reactor
from ..trace.tracer import NULL_TRACER, SPAN_GOSSIP_INGEST
from ..utils.clock import monotonic
from ..pool.mempool import (
    LANE_PRIORITY,
    ErrMempoolIsFull,
    ErrTxInCache,
    ErrTxTooLarge,
    Mempool,
    TxInfo,
)

MSG_TXS = 1
MSG_HEIGHT = 2

PEER_CATCHUP_SLEEP = 0.005
PEER_HEIGHT_KEY = "mempool_height"


def encode_tx_batch(txs: list[bytes]) -> bytes:
    body = bytearray([MSG_TXS])
    for tx in txs:
        body += amino.length_prefixed(tx)
    return bytes(body)


def decode_tx_batch(body: bytes) -> list[bytes]:
    r = amino.AminoReader(body)
    out = []
    while not r.eof():
        out.append(r.read_bytes())
    return out


class MempoolReactor(Reactor):
    def __init__(
        self,
        mempool: Mempool,
        broadcast: bool = True,
        batch_size: int = 1024,
        poll_interval: float = 0.05,
        regossip_interval: float | None = None,
        admission=None,
    ):
        super().__init__("mempool")
        self.mempool = mempool
        # AdmissionController (or None): sheds gossiped bulk txs before
        # CheckTx and pauses the BULK broadcast walk under overload —
        # priority tx gossip and vote gossip (txvote reactor) never pause
        self.admission = admission
        self.broadcast = broadcast
        self.batch_size = batch_size
        self.poll_interval = poll_interval
        # anti-entropy re-walk cadence for lossy links; None = single-pass
        # walk (see TxVoteReactor.regossip_interval for the rationale)
        self.regossip_interval = regossip_interval
        # per-tx tracing (trace/tracer.py): gossip_ingest spans on the
        # receive path; wired by the node
        self.tracer = NULL_TRACER
        self._running = threading.Event()
        self._peer_ids: dict[str, int] = {}
        self._next_peer_id = 1
        self._ids_mtx = make_lock("reactors.MempoolReactor._ids_mtx")
        self._threads: list[threading.Thread] = []

    def get_channels(self) -> list[ChannelDescriptor]:
        # priority 5 like the reference (mempool/reactor.go:118-125)
        return [ChannelDescriptor(id=CHANNEL_MEMPOOL, priority=5)]

    def on_start(self) -> None:
        self._running.set()

    def on_stop(self) -> None:
        self._running.clear()
        for t in self._threads:
            t.join(timeout=2)
        self._threads = []

    def _peer_id(self, peer) -> int:
        with self._ids_mtx:
            pid = self._peer_ids.get(peer.node_id)
            if pid is None:
                pid = self._next_peer_id
                self._next_peer_id += 1
                self._peer_ids[peer.node_id] = pid
            return pid

    def add_peer(self, peer) -> None:
        self._peer_id(peer)
        # tell the peer our height so its lag throttle tracks us
        peer.try_send(
            CHANNEL_MEMPOOL,
            bytes([MSG_HEIGHT]) + amino.uvarint(max(self.mempool.height, 0)),
        )
        if self.broadcast:
            t = threading.Thread(
                target=self._broadcast_routine,
                args=(peer,),
                name=f"mempool-bcast-{peer.node_id}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    def broadcast_height(self, height: int) -> None:
        """Push a height update to all peers (block-boundary hook)."""
        if self.switch is not None:
            self.switch.broadcast(
                CHANNEL_MEMPOOL, bytes([MSG_HEIGHT]) + amino.uvarint(max(height, 0))
            )

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        if not msg:
            raise ValueError("empty mempool message")
        msg_type = msg[0]
        if msg_type == MSG_TXS:
            txs = decode_tx_batch(msg[1:])  # decode error -> peer stopped
            pid = self._peer_id(peer)
            adm = self.admission
            tr = self.tracer
            for tx in txs:
                if adm is not None and not adm.admit_gossip(tx, peer_id=pid):
                    continue  # shed before CheckTx: overload or peer cap
                # precomputing the key when tracing feeds both the sample
                # check and check_tx (which skips its own hash)
                key = sha256(tx) if tr.active else None
                traced = tr.active and tr.sampled_key(key)
                t0 = monotonic() if traced else 0.0
                try:
                    self.mempool.check_tx(tx, TxInfo(sender_id=pid), key=key)
                except ErrTxInCache:
                    # dup delivery: feeds the peer's health score
                    # (health/peers.py); gossip redundancy is discounted
                    peer.stats.duplicates += 1
                    continue
                except (ErrMempoolIsFull, ErrTxTooLarge, ValueError):
                    continue  # app rejection / dup: log-and-ignore (:137)
                if traced:
                    tr.span(
                        key.hex().upper(), SPAN_GOSSIP_INGEST, t0, monotonic()
                    )
        elif msg_type == MSG_HEIGHT:
            height, _ = amino.read_uvarint(msg, 1)
            peer.set(PEER_HEIGHT_KEY, height)
        else:
            raise ValueError(f"unknown mempool msg type {msg_type}")

    def _broadcast_routine(self, peer) -> None:
        pid = self._peer_id(peer)
        cursor = 0
        pcursor = 0
        pending: list[tuple[bytes, bytes, int, bool, int]] = []
        seq = self.mempool.seq()
        last_rewalk = monotonic()
        while self._running.is_set() and peer.is_running():
            if not pending:
                # priority lane first; the bulk walk pauses entirely while
                # the admission controller reports overload (backpressure
                # on ingest gossip — vote gossip is a different reactor
                # and never pauses)
                pending, pcursor = self.mempool.priority_entries_from(
                    pcursor, limit=self.batch_size
                )
            if not pending:
                adm = self.admission
                if adm is None or not adm.gossip_paused():
                    bulk, cursor = self.mempool.entries_from(
                        cursor, limit=self.batch_size
                    )
                    pending = [it for it in bulk if it[4] != LANE_PRIORITY]
                    if not pending and bulk:
                        continue  # page was all-priority: keep walking
            if not pending:
                if (
                    self.regossip_interval is not None
                    and monotonic() - last_rewalk >= self.regossip_interval
                    and self.mempool.size() > 0
                ):
                    cursor = 0  # anti-entropy re-walk (see __init__)
                    pcursor = 0
                    last_rewalk = monotonic()
                    continue
                seq = self.mempool.wait_for_new(seq, timeout=self.poll_interval)
                continue
            peer_height = peer.get(PEER_HEIGHT_KEY, 0)
            sendable, deferred = [], []
            for item in pending:
                key, tx, h = item[0], item[1], item[2]
                if h - 1 > peer_height:  # allow a lag of 1 block (:236-239)
                    deferred.append(item)
                elif not self.mempool.has_sender(key, pid):
                    sendable.append(tx)
            if sendable:
                if not peer.send(CHANNEL_MEMPOOL, encode_tx_batch(sendable)):
                    time.sleep(PEER_CATCHUP_SLEEP)
                    continue
            pending = deferred
            if deferred:
                time.sleep(PEER_CATCHUP_SLEEP)
