"""TxVote reactor: sign mempool txs + gossip the vote pool (channel 0x32).

Reference: txvotepool/reactor.go. Two duties, preserved:

- ``signTxRoutine`` (:87-138): walk the mempool; if this node's key is in
  the current validator set, sign a TxVote per tx at the state's last
  block height and inject it into the local vote pool.
- per-peer broadcast (:198-265): walk the vote pool from a stable cursor,
  suppress votes the peer itself sent us (sender ids, :298-359), throttle
  votes more than one height ahead of the peer ("allow for a lag of 1
  block", :240), and ship what remains.

Deviation (TPU-first): votes travel in *batched* frames — the consumer is
a device kernel fed thousands of votes per step; one-vote-per-message
framing (reference :244-247) would bottleneck the host. Frame format:
``msg_type u8 | body``; type 1 body = repeated uvarint-length-prefixed
amino TxVote, type 2 body = uvarint height (peer state update, standing in
for the consensus reactor's PeerState that the reference reads at :233).
"""

from __future__ import annotations

import threading

from ..analysis.lockgraph import make_lock
import time
from dataclasses import dataclass
from typing import Callable

from ..codec import amino
from ..trace.tracer import NULL_TRACER, SPAN_PRE_DROP, SPAN_SIGN
from ..utils.clock import monotonic
from ..p2p.base import CHANNEL_TXVOTE, ChannelDescriptor, Reactor
from ..pool.mempool import (
    LANE_PRIORITY,
    ErrMempoolIsFull,
    ErrTxInCache,
    ErrTxTooLarge,
    Mempool,
    TxInfo,
)
from ..pool.txvotepool import TxVotePool
from ..crypto.hash import sha256
from ..types import TxVote, encode_tx_vote
from ..types.tx_vote import decode_tx_votes_many
from ..utils.cache import LRUMap
from ..types.priv_validator import PrivValidator
from ..types.validator import ValidatorSet

MSG_VOTES = 1
MSG_HEIGHT = 2
_MSG_VOTES_B = bytes([MSG_VOTES])

PEER_CATCHUP_SLEEP = 0.005  # reference peerCatchupSleepIntervalMS=100; faster here
PEER_HEIGHT_KEY = "txvote_height"


@dataclass
class StateView:
    """The slice of node state the reactors read (reference reads
    state.State directly, txvotepool/reactor.go:111-115)."""

    chain_id: str
    last_block_height: int
    validators: ValidatorSet
    # committee mode (committee/): the epoch's sampled tx-vote committee
    # for votes at last_block_height. None = full-set mode — every
    # validator signs, no committee pre-check.
    committee: ValidatorSet | None = None


def encode_vote_batch(votes: list[TxVote]) -> bytes:
    body = bytearray([MSG_VOTES])
    for v in votes:
        body += amino.length_prefixed(encode_tx_vote(v))
    return bytes(body)


class TxVoteReactor(Reactor):
    # process-wide decoded-vote cache (see __init__ comment)
    _shared_wire = LRUMap(1 << 16)

    def __init__(
        self,
        get_state: Callable[[], StateView],
        mempool: Mempool,
        tx_vote_pool: TxVotePool,
        priv_val: PrivValidator | None = None,
        broadcast: bool = True,
        batch_size: int = 1024,
        poll_interval: float = 0.05,
        regossip_interval: float | None = None,
    ):
        super().__init__("txvote")
        self.get_state = get_state
        self.mempool = mempool
        self.tx_vote_pool = tx_vote_pool
        self.priv_val = priv_val
        self.broadcast = broadcast
        self.batch_size = batch_size
        self.poll_interval = poll_interval
        # anti-entropy for lossy links (faults.chaos): the cursor walk
        # ships each pool entry to each peer exactly once, so a frame lost
        # in transit is never offered to that peer again. When set, an
        # idle broadcast routine re-walks the live pool every interval;
        # receivers dedup re-offers cheaply (wire cache + pool signature
        # dedup). None (default) keeps the single-pass walk — in-memory
        # pipes don't lose frames, and the re-walk is pure overhead there.
        self.regossip_interval = regossip_interval
        # per-tx tracing (trace/tracer.py): the sign walk records a
        # sign_walk span per sampled tx; wired by the node
        self.tracer = NULL_TRACER
        # accountable gossip (health/byzantine.py, wired by the node):
        # quarantine gate + O(1) pre-check drop accounting. None = every
        # check below short-circuits to the pre-ledger behavior.
        self.ledger = None
        self._running = threading.Event()
        self._peer_ids: dict[str, int] = {}  # node_id -> small int (txVotePoolIDs)
        self._next_peer_id = 1
        self._ids_mtx = make_lock("reactors.TxVoteReactor._ids_mtx")
        self._threads: list[threading.Thread] = []
        self._sign_thread: threading.Thread | None = None
        # wire-segment dedup + decoded-vote sharing: raw segment bytes ->
        # (pool vote key, decoded TxVote). Gossip delivers each vote ~2-3x
        # (independent forwarders) and, with co-located nodes, N nodes
        # each decode the SAME canonical bytes (~10 us each, r3/r4
        # profiles). Canonical wire caching makes all forwarders emit
        # identical bytes, so the raw segment IS the key; the map is
        # PROCESS-WIDE (class attribute) so the first node to decode a
        # vote shares the immutable object with every other node —
        # nothing downstream mutates pooled votes, and the key binds the
        # exact bytes, so a hostile variant encoding simply misses and
        # pays its own decode. Sender bookkeeping stays per-node in the
        # pool; the pool's signature dedup remains authoritative.
        self._seen_wire = TxVoteReactor._shared_wire

    # -- channels --

    def get_channels(self) -> list[ChannelDescriptor]:
        # priority 5, like the reference (txvotepool/reactor.go:142-149)
        return [ChannelDescriptor(id=CHANNEL_TXVOTE, priority=5)]

    # -- lifecycle --

    def on_start(self) -> None:
        self._running.set()
        self._sign_thread = threading.Thread(
            target=self._sign_tx_routine, name="txvote-sign", daemon=True
        )
        self._sign_thread.start()

    def on_stop(self) -> None:
        self._running.clear()
        if self._sign_thread is not None:
            self._sign_thread.join(timeout=2)
            self._sign_thread = None
        for t in self._threads:
            t.join(timeout=2)
        self._threads = []

    # -- peer management --

    def _peer_id(self, peer) -> int:
        with self._ids_mtx:
            pid = self._peer_ids.get(peer.node_id)
            if pid is None:
                pid = self._next_peer_id
                self._next_peer_id += 1
                self._peer_ids[peer.node_id] = pid
                if self.ledger is not None:
                    # bind the pool sender id to the peer's node_id so
                    # engine-side verdict attribution reaches the
                    # scoreboard (which keys on node ids)
                    self.ledger.register_peer(pid, peer.node_id)
            return pid

    def add_peer(self, peer) -> None:
        self._peer_id(peer)  # reserve (reference ids.ReserveForPeer)
        # tell the peer our height so its lag throttle tracks us
        st = self.get_state()
        peer.try_send(
            CHANNEL_TXVOTE,
            bytes([MSG_HEIGHT]) + amino.uvarint(max(st.last_block_height, 0)),
        )
        if self.broadcast:
            t = threading.Thread(
                target=self._broadcast_routine,
                args=(peer,),
                name=f"txvote-bcast-{peer.node_id}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    def remove_peer(self, peer, reason: object = None) -> None:
        # broadcast routine exits on peer.is_running(); id mapping kept so a
        # reconnecting peer reuses its slot (reclaim is a no-op here)
        pass

    # -- receive (reference :170-190) --

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        if not msg:
            raise ValueError("empty txvote message")
        msg_type = msg[0]
        if msg_type == MSG_VOTES:
            ledger = self.ledger
            if ledger is not None and ledger.quarantined(peer.node_id):
                # circuit breaker tripped for this peer: drop the whole
                # frame at the front door. The uvarint skip-walk counts
                # segments without decoding a single vote — a flooding
                # peer costs O(frame bytes) here, never a device slot.
                n = 0
                r = amino.AminoReader(msg, 1)
                while not r.eof():
                    r.read_bytes()
                    n += 1
                ledger.note_frame(peer.node_id, 0, {"quarantined": n})
                return
            pid = self._peer_id(peer)
            r = amino.AminoReader(msg, 1)
            pool = self.tx_vote_pool
            seen = self._seen_wire
            tx_info = TxInfo(sender_id=pid)
            ingest: list = []  # (wk, vote) needing the authoritative path
            fresh_segs: list[bytes] = []  # wire-cache misses: batch decode
            fresh_slots: list[int] = []  # their ingest positions
            n_replayed = 0  # same-peer identical re-sends (ledger window)
            while not r.eof():
                seg = r.read_bytes()  # decode error -> peer stopped
                # raw seg bytes ARE the cache key: siphash of ~150 B costs
                # ~1/4 of a sha256, and peek() reads without the map lock
                # (r5 profile: 12 receive threads contended one lock)
                wk = seg
                hit = seen.peek(wk)
                if hit is not None:
                    vk, vote = hit
                    code = pool.add_sender(vk, pid)
                    if code:
                        # dup AND the pool still holds it: nothing to do
                        # beyond the peer's dup counter (health scoring —
                        # legit gossip redundancy is discounted there).
                        # SENDER_REPEAT = THIS peer already delivered this
                        # exact signature: counted for the ledger's replay
                        # accounting (an honest watchdog re-offer or a
                        # replay flood — the breaker's opt-in rate
                        # threshold tells them apart).
                        # If the pool dropped it (purge/flush/eviction),
                        # fall through to the authoritative check_tx path
                        # — the wire cache must never overrule the pool's
                        # own re-accept policy (r3 review finding) — but
                        # reuse the shared decoded object either way.
                        if code == TxVotePool.SENDER_REPEAT:
                            n_replayed += 1
                        peer.stats.duplicates += 1
                        continue
                    if pool.in_cache(vk):
                        # pool dropped it but its dedup cache still vetoes
                        # re-entry (committed/purged vote being re-
                        # gossiped): check_tx would reject with
                        # ErrTxInCache and no side effects (the entry is
                        # gone, so there is no sender set to update) —
                        # skip the authoritative round trip entirely
                        peer.stats.duplicates += 1
                        continue
                    ingest.append((wk, vote))
                else:
                    # placeholder keeps WIRE order: acceptance at the
                    # pool-full boundary must see votes in arrival order,
                    # not hits-then-misses (r5 review)
                    fresh_slots.append(len(ingest))
                    ingest.append((wk, None))
                    fresh_segs.append(seg)
            if fresh_segs:
                # one C field-walk for the whole frame's unknown segs
                # (decode error -> ValueError -> peer stopped, same as
                # the per-seg decoder)
                for slot, vote in zip(
                    fresh_slots, decode_tx_votes_many(fresh_segs)
                ):
                    ingest[slot] = (ingest[slot][0], vote)
            n_unknown = n_stale = n_noncomm = 0
            if ingest and ledger is not None:
                # O(1)-per-vote pre-checks, BEFORE the pool and the
                # device: a vote from a signer outside the validator set
                # can never reach quorum, and a vote far below our height
                # is either ancient re-gossip or a stale-flood — both die
                # here, order-preserving for everything kept (honest
                # certificate parity). Pre-dropped segs deliberately do
                # NOT enter the wire cache: each re-delivery is re-judged
                # and re-counted against the sender.
                st = self.get_state()
                vals = st.validators
                committee = st.committee
                min_height = st.last_block_height - ledger.cfg.stale_height_slack
                kept = []
                tr = self.tracer
                for wk, vote in ingest:
                    if not vals.has_address(vote.validator_address):
                        n_unknown += 1
                    elif vote.height < min_height:
                        n_stale += 1
                    elif (
                        committee is not None
                        and vote.height == st.last_block_height
                        and not committee.has_address(vote.validator_address)
                    ):
                        # committee mode: a real validator signing a
                        # current-height tx vote from OUTSIDE the epoch's
                        # sampled committee can never reach committee
                        # quorum — O(1) drop before the pool and device.
                        # Gated on exact height: a vote straddling an
                        # epoch boundary belongs to another epoch's
                        # committee and is left to the tally to judge.
                        n_noncomm += 1
                    else:
                        kept.append((wk, vote))
                        continue
                    if tr.active and tr.sampled(vote.tx_hash):
                        t = monotonic()
                        tr.span(vote.tx_hash, SPAN_PRE_DROP, t, t)
                ingest = kept
            if ingest:
                # one pool lock for the whole frame (check_tx_many);
                # full/too-large rejections drop the vote like the
                # reference, in-cache dups still enter the wire cache
                errs = pool.check_tx_many(
                    [v for _, v in ingest], tx_info
                )
                for (wk, vote), err in zip(ingest, errs):
                    if err is None or isinstance(err, ErrTxInCache):
                        seen.put(wk, (vote.vote_key(), vote))
                    if err is not None and isinstance(err, ErrTxInCache):
                        peer.stats.duplicates += 1
            if ledger is not None and (
                ingest or n_unknown or n_stale or n_noncomm or n_replayed
            ):
                drops = {}
                if n_unknown:
                    drops["unknown_validator"] = n_unknown
                if n_stale:
                    drops["stale_height"] = n_stale
                if n_noncomm:
                    drops["non_committee"] = n_noncomm
                if n_replayed:
                    drops["replayed_sig"] = n_replayed
                ledger.note_frame(peer.node_id, len(ingest), drops or None)
        elif msg_type == MSG_HEIGHT:
            height, _ = amino.read_uvarint(msg, 1)
            peer.set(PEER_HEIGHT_KEY, height)
        else:
            raise ValueError(f"unknown txvote msg type {msg_type}")

    def broadcast_height(self, height: int) -> None:
        """Push a height update to all peers (block-boundary hook)."""
        if self.switch is not None:
            self.switch.broadcast(
                CHANNEL_TXVOTE, bytes([MSG_HEIGHT]) + amino.uvarint(max(height, 0))
            )

    # -- sign routine (reference :87-138) --

    def _sign_tx_routine(self) -> None:
        cursor = 0
        pcursor = 0
        seq = self.mempool.seq()
        while self._running.is_set():
            # drain the priority lane first each pass: under overload the
            # bulk walk can be arbitrarily deep, and priority txs must
            # reach quorum at a flat latency regardless (ISSUE 6)
            pitems, pcursor = self.mempool.priority_entries_from(
                pcursor, limit=self.batch_size
            )
            items, cursor = self.mempool.entries_from(cursor, limit=self.batch_size)
            items = pitems + [it for it in items if it[4] != LANE_PRIORITY]
            if not items:
                seq = self.mempool.wait_for_new(seq, timeout=self.poll_interval)
                continue
            st = self.get_state()
            if self.priv_val is None:
                continue
            my_addr = self.priv_val.get_address()
            if not st.validators.has_address(my_addr):
                continue  # keep running: could become a validator any round
            if st.committee is not None and not st.committee.has_address(my_addr):
                # committee mode: only committee members sign tx votes —
                # this is WHERE the gossip savings come from (votes per tx
                # = committee size, not validator count). Keep running:
                # the next epoch's sample may include us.
                continue
            tr = self.tracer
            for tx_key, tx, _h, fast_path, _lane in items:
                if not fast_path:
                    # app flagged this tx block-only (e.g. EndBlock-
                    # coupled validator updates): honest validators do
                    # not sign it, so no fast-path quorum can form and
                    # the block path carries it
                    continue
                traced = tr.active and tr.sampled_key(tx_key)
                t0 = monotonic() if traced else 0.0
                # the mempool key IS sha256(tx) — no recompute
                vote = TxVote(
                    height=st.last_block_height,
                    tx_hash=tx_key.hex().upper(),
                    tx_key=tx_key,
                    validator_address=my_addr,
                )
                self.priv_val.sign_tx_vote(st.chain_id, vote)
                try:
                    self.tx_vote_pool.check_tx(vote)
                except (ErrTxInCache, ErrMempoolIsFull, ErrTxTooLarge):
                    continue
                if traced:
                    tr.span(vote.tx_hash, SPAN_SIGN, t0, monotonic())

    # -- per-peer broadcast (reference :198-265) --

    def _broadcast_routine(self, peer) -> None:
        pid = self._peer_id(peer)
        cursor = 0
        pending: list[tuple[bytes, TxVote, int, bytes]] = []
        seq = self.tx_vote_pool.seq()
        last_rewalk = monotonic()
        while self._running.is_set() and peer.is_running():
            if not pending:
                pending, cursor = self.tx_vote_pool.entries_from(
                    cursor, limit=self.batch_size
                )
            if not pending:
                if (
                    self.regossip_interval is not None
                    and monotonic() - last_rewalk >= self.regossip_interval
                    and self.tx_vote_pool.size() > 0
                ):
                    cursor = 0  # anti-entropy re-walk (see __init__)
                    last_rewalk = monotonic()
                    continue
                seq = self.tx_vote_pool.wait_for_new(seq, timeout=self.poll_interval)
                continue
            peer_height = peer.get(PEER_HEIGHT_KEY, 0)
            known = self.tx_vote_pool.has_sender_many(
                [key for key, _v, _h, _s in pending], pid
            )
            sendable, deferred = [], []
            for (key, vote, _h, seg), peer_has in zip(pending, known):
                if vote.height - 1 > peer_height:  # allow a lag of 1 block
                    deferred.append((key, vote, _h, seg))
                elif not peer_has:
                    sendable.append(seg)
            if sendable:
                # the frame is a join of ingest-time cached segments: the
                # per-peer walk never re-serializes a vote (r4 profile)
                frame = _MSG_VOTES_B + b"".join(sendable)
                if not peer.send(CHANNEL_TXVOTE, frame):
                    time.sleep(PEER_CATCHUP_SLEEP)
                    continue  # retry the same batch
            pending = deferred
            if deferred:
                time.sleep(PEER_CATCHUP_SLEEP)  # peer catching up
