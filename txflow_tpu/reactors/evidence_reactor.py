"""EvidenceReactor: gossip equivocation evidence (the reference wires
tendermint's evidence reactor on channel 0x38, node/node.go:354-367).

Push-on-add plus a periodic re-offer of pending evidence to every peer
(evidence must eventually reach everyone even across joins/partitions;
receivers verify + dedup, so re-offers are idempotent).
"""

from __future__ import annotations

import threading

from ..p2p.base import ChannelDescriptor, Reactor
from ..types.evidence import decode_evidence, encode_evidence

CHANNEL_EVIDENCE = 0x38  # reference channel id

_REOFFER_INTERVAL = 1.0


class EvidenceReactor(Reactor):
    def __init__(self, pool):
        super().__init__("evidence")
        self.pool = pool
        pool.on_add = self._broadcast
        self._stop = threading.Event()

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(id=CHANNEL_EVIDENCE, priority=4)]

    def on_start(self) -> None:
        self._stop.clear()
        threading.Thread(
            target=self._reoffer_loop, name="evidence-gossip", daemon=True
        ).start()

    def on_stop(self) -> None:
        self._stop.set()

    def add_peer(self, peer) -> None:
        self._offer(peer, self.pool.pending())

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        ev = decode_evidence(msg)  # decode error stops the peer (switch)
        # a semantic add error (e.g. the named validator rotated out of
        # OUR current set, or our view lags the sender's) is NOT peer
        # misbehavior — dropping the peer for it would sever honest links
        self.pool.add(ev)

    def _broadcast(self, ev) -> None:
        if self.switch is not None:
            for peer in self.switch.peers():
                self._offer(peer, [ev])

    def _offer(self, peer, evs) -> None:
        """Send each piece of evidence AT MOST ONCE per connection: the
        periodic loop exists to cover joins/races, not to rebroadcast the
        same frames forever."""
        sent: set = peer.get("evidence_sent")  # type: ignore[assignment]
        if sent is None:
            sent = set()
            peer.set("evidence_sent", sent)
        for ev in evs:
            h = ev.hash()
            if h in sent:
                continue
            if peer.try_send(CHANNEL_EVIDENCE, encode_evidence(ev)):
                sent.add(h)

    def _reoffer_loop(self) -> None:
        while not self._stop.wait(_REOFFER_INTERVAL):
            if self.switch is None:
                continue
            pending = self.pool.pending()
            if not pending:
                continue
            for peer in self.switch.peers():
                self._offer(peer, pending)
