"""Fee-prefix lane classifier.

Lanes must be a *deterministic function of the tx bytes*: every honest
node classifies a gossiped tx identically with no coordination, so the
priority lane behaves the same at every edge. The default convention is a
self-describing prefix on the tx bytes themselves —

    b"fee=<n>;<payload>"

— n >= the configured threshold rides the priority lane; anything else
(no prefix, malformed, below threshold) is best-effort bulk. A node
assembly can swap in any other ``tx -> lane`` callable via
``NodeConfig.lane_classifier`` (e.g. stake-weighted per "Weighted Voting
on the Blockchain", arxiv 1903.04213) as long as it stays deterministic.
"""

from __future__ import annotations

from ..pool.mempool import LANE_BULK, LANE_PRIORITY

# the fee prefix is a handful of digits; bound the scan so a hostile
# "fee="-prefixed megabyte tx costs O(1) to classify
_FEE_SCAN_LIMIT = 24


def parse_fee(tx: bytes) -> int:
    """Fee declared by the tx's ``fee=<n>;`` prefix; 0 when absent or
    malformed (malformed never errors — it just rides the bulk lane)."""
    if not tx.startswith(b"fee="):
        return 0
    end = tx.find(b";", 4, _FEE_SCAN_LIMIT)
    if end < 0:
        return 0
    try:
        return int(tx[4:end])
    except ValueError:
        return 0


# sender tags ride the same self-describing prefix convention as fees
# (``fee=<n>;from=<id>;<payload>`` or ``from=<id>;...``); bound the scan
# so classification stays O(1) on hostile megabyte txs
_SENDER_SCAN_LIMIT = 96


def parse_sender(tx: bytes) -> str:
    """Sender identity declared by a ``from=<id>;`` tag in the tx's
    prefix region; "" when absent or malformed (untagged txs carry no
    identity to be fair BETWEEN, so the per-sender budget skips them —
    the lane-wide headroom still bounds the aggregate)."""
    at = tx.find(b"from=", 0, _SENDER_SCAN_LIMIT)
    if at < 0:
        return ""
    end = tx.find(b";", at + 5, at + 5 + _SENDER_SCAN_LIMIT)
    if end < 0:
        return ""
    try:
        return tx[at + 5 : end].decode("ascii")
    except UnicodeDecodeError:
        return ""


class FeeLaneClassifier:
    """tx -> lane via the fee prefix (the default NodeConfig classifier)."""

    def __init__(self, priority_fee_threshold: int = 1):
        self.threshold = priority_fee_threshold

    def __call__(self, tx: bytes) -> int:
        return LANE_PRIORITY if parse_fee(tx) >= self.threshold else LANE_BULK
