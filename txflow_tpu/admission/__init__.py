"""Admission control: the node's front door (ROADMAP "survive a
million-user ingress").

Sits between the RPC/gossip edges and the mempool. Three duties:

- **edge dedup**: replayed tx bytes are rejected at the edge, before any
  signature work or app CheckTx round trip;
- **overload backpressure**: pool high-water marks (with hysteresis)
  propagate to the RPC server (HTTP 429 + Retry-After) and to the
  mempool reactor (bulk ingest gossip pauses/sheds — vote gossip never
  does, quorums must keep forming for what was admitted);
- **fee/priority lanes**: a deterministic classifier (fee-prefix by
  default) splits txs into a priority lane that keeps committing at
  flat p50 under overload and a best-effort bulk lane that sheds.

Every rejection is surfaced via ``txflow_admission_*`` metrics — never a
silent drop.
"""

from .config import AdmissionConfig, soak_spec_overrides
from .classifier import FeeLaneClassifier, parse_fee
from .controller import AdmissionController, ErrDuplicateTx, ErrOverloaded

__all__ = [
    "AdmissionConfig",
    "soak_spec_overrides",
    "AdmissionController",
    "ErrDuplicateTx",
    "ErrOverloaded",
    "FeeLaneClassifier",
    "parse_fee",
]
