"""AdmissionController: edge dedup + overload backpressure + lane
classification, in front of the mempool.

Decision order on the RPC path (``admit_rpc``) is load-bearing:

1. edge dedup **membership check** (no reservation yet) — replayed bytes
   are rejected before anything else, matching the mempool-cache verdict
   a non-replay would eventually get, so the two dup paths answer the
   same thing;
2. lane classification (deterministic from tx bytes — classifier.py);
3. overload / bulk-headroom shed for the bulk lane (429 upstream);
4. only now the key is *pushed* into the edge dedup and the admission
   counted. Pushing before step 3 would poison the client's retry: an
   overload-rejected tx would read as a "duplicate" when resubmitted
   after Retry-After.

The caller owes ``forget(key)`` if the mempool then rejects the tx for
any reason other than its own dup cache (full pool, app rejection, conn
failure) — otherwise legitimate retries would bounce off the edge.

The admit path is called from every RPC handler thread and the gossip
receive path: it must never block (txlint pins this — the admission
functions are in the hotpath-sync no-block set). The pool-occupancy poll
is therefore cached for ``pressure_interval``; between polls the verdict
is O(1) cache/counter work.
"""

from __future__ import annotations

from ..analysis.lockgraph import make_lock
from ..analysis.racegraph import shared_field
from ..pool.mempool import LANE_BULK, LANE_PRIORITY
from ..trace.tracer import NULL_TRACER, SPAN_ADMISSION
from ..utils.cache import make_lru
from ..utils.clock import monotonic
from ..utils.metrics import AdmissionMetrics
from .classifier import FeeLaneClassifier, parse_sender
from .config import AdmissionConfig


class ErrDuplicateTx(Exception):
    """Replayed tx bytes caught by the edge dedup (before signatures)."""


class ErrOverloaded(Exception):
    """Bulk-lane tx shed under overload; retry after ``retry_after`` s."""

    def __init__(self, retry_after: float):
        super().__init__(f"node overloaded; retry after {retry_after}s")
        self.retry_after = retry_after


class AdmissionController:
    def __init__(
        self,
        mempool,
        cfg: AdmissionConfig | None = None,
        registry=None,
        classifier=None,
    ):
        self.mempool = mempool
        self.cfg = cfg or AdmissionConfig()
        self.classifier = classifier or FeeLaneClassifier(
            self.cfg.priority_fee_threshold
        )
        self.metrics = AdmissionMetrics(registry)
        # serializes edge-dedup mutations + the cached overload verdict
        # (make_lru returns the owner-serialized cache on GIL builds; this
        # lock IS that owner)
        self._mtx = make_lock("admission.AdmissionController._mtx")
        # dedup LRU + overload verdict + rate buckets: RPC handler
        # threads and gossip receive threads admit concurrently
        self._sh_state = shared_field("admission.AdmissionController.state")  # txlint: shared(self._mtx)
        self.dedup = make_lru(self.cfg.dedup_size)
        self._overloaded = False
        self._next_poll = 0.0  # monotonic deadline of the cached verdict
        # bulk admit-rate token bucket (cfg.bulk_rate; see config.py) —
        # refilled lazily on each verdict, state guarded by _mtx
        self._bulk_tokens = max(self.cfg.bulk_burst, self.cfg.bulk_rate, 1.0)
        self._bulk_refill_t: float | None = None
        # adaptive bulk rate: when the node assembly injects a cumulative
        # committed-tx counter read here, the bucket's fill tracks the
        # engine's measured commit rate instead of the static knob. The
        # sample runs inside overloaded()'s cadenced branch, so the admit
        # path stays O(1) between pressure polls.
        self.commit_rate_source = None  # () -> cumulative committed txs
        self._cr_count: float | None = None
        self._cr_t: float | None = None
        self._cr_ewma: float | None = None
        self._bulk_rate_eff = self.cfg.bulk_rate
        # per-peer gossip buckets: peer_id -> [tokens, last_refill_t]
        self._peer_buckets: dict[str, list] = {}
        # priority-lane fairness buckets: sender -> [tokens, last_refill_t]
        self._sender_buckets: dict[str, list] = {}
        # durable-path degradation hook (wired by the node): True = the
        # node can no longer persist (disk full / EIO) and must shed
        # ingest like an overloaded node instead of accepting txs it
        # cannot recover after a crash
        self.degraded_source = None  # () -> bool
        # per-tx tracing (trace/tracer.py): the admission verdict is the
        # first span on a traced tx's timeline; wired by the node
        self.tracer = NULL_TRACER

    # -- lane classification (mempool.lane_of hook) --

    def lane_of(self, tx: bytes) -> int:
        """Lane for a tx entering the pool by ANY path (RPC, gossip,
        direct check_tx). Classifier faults demote to bulk — a hostile tx
        must not be able to error the insert path."""
        try:
            lane = self.classifier(tx)
        except Exception:
            return LANE_BULK
        return LANE_PRIORITY if lane == LANE_PRIORITY else LANE_BULK

    # -- overload verdict --

    def overloaded(self, now: float | None = None) -> bool:
        """Hysteresis over pool occupancy: flips on at high_water_frac,
        off at low_water_frac; verdict cached for pressure_interval."""
        if not self.cfg.enabled:
            return False
        if now is None:
            now = monotonic()
        with self._mtx:
            self._sh_state.note_write()
            if now < self._next_poll:
                return self._overloaded
            self._next_poll = now + self.cfg.pressure_interval
        self._sample_commit_rate(now)
        occ = self.mempool.size() / max(1, self.mempool.config.size)
        with self._mtx:
            self._sh_state.note_write()
            if self._overloaded:
                if occ <= self.cfg.low_water_frac:
                    self._overloaded = False
            elif occ >= self.cfg.high_water_frac:
                self._overloaded = True
            over = self._overloaded
        self.metrics.occupancy.set(occ)
        self.metrics.overloaded.set(1.0 if over else 0.0)
        return over

    def _sample_commit_rate(self, now: float) -> None:
        """Adaptive bulk rate: one sample per pressure poll. Reads the
        injected cumulative committed-tx counter (a plain gauge read, no
        locks beyond _mtx), EWMA-smooths the instantaneous rate, and
        moves the effective bucket fill to EWMA * headroom — but only
        when the target leaves the hysteresis band, so a steady workload
        sees a steady admit rate. Floor stops a cold start or a commit
        stall from latching the front door shut."""
        src = self.commit_rate_source
        if src is None:
            return
        try:
            count = float(src())
        except Exception:
            return  # a faulting source must not error the admit path
        cfg = self.cfg
        with self._mtx:
            self._sh_state.note_write()
            if self._cr_count is None or self._cr_t is None:
                self._cr_count, self._cr_t = count, now
                return
            dt = now - self._cr_t
            if dt <= 0:
                return
            inst = max(0.0, count - self._cr_count) / dt
            self._cr_count, self._cr_t = count, now
            if self._cr_ewma is None:
                self._cr_ewma = inst
            else:
                a = cfg.bulk_rate_alpha
                self._cr_ewma = a * inst + (1.0 - a) * self._cr_ewma
            target = max(cfg.bulk_rate_floor, self._cr_ewma * cfg.bulk_rate_headroom)
            eff = self._bulk_rate_eff
            if eff <= 0 or abs(target - eff) > cfg.bulk_rate_hysteresis * eff:
                self._bulk_rate_eff = target
            ewma = self._cr_ewma
            eff = self._bulk_rate_eff
        self.metrics.commit_rate.set(ewma)
        self.metrics.bulk_rate_effective.set(eff)

    def _effective_bulk_rate(self) -> float:
        """The bucket's current fill rate: adaptive when a commit-rate
        source is wired, else the static cfg knob (PR 6 behavior)."""
        if self.commit_rate_source is None:
            return self.cfg.bulk_rate
        return self._bulk_rate_eff

    def _bulk_rate_exceeded(self, now: float | None = None) -> bool:
        """Token-bucket verdict for ONE bulk admission (consumes a token
        on pass). Disabled when the effective rate is 0."""
        rate = self._effective_bulk_rate()
        if rate <= 0:
            return False
        if now is None:
            now = monotonic()
        cap = max(self.cfg.bulk_burst, rate, 1.0)
        with self._mtx:
            self._sh_state.note_write()
            if self._bulk_refill_t is not None and now > self._bulk_refill_t:
                self._bulk_tokens = min(
                    cap, self._bulk_tokens + (now - self._bulk_refill_t) * rate
                )
            self._bulk_refill_t = now
            if self._bulk_tokens >= 1.0:
                self._bulk_tokens -= 1.0
                return False
            return True

    def _storage_degraded(self) -> bool:
        """Durable-path degradation verdict (node-wired; never errors)."""
        src = self.degraded_source
        if src is None:
            return False
        try:
            return bool(src())
        except Exception:
            return False

    def _bulk_shed(self, now: float | None = None) -> bool:
        """Should a bulk-lane tx be shed right now? Storage degradation,
        overload, the bulk lane alone crowding past its headroom fraction
        of the pool, or the bulk admit-rate bucket running dry."""
        if self._storage_degraded():
            return True
        if self.overloaded(now):
            return True
        bulk = self.mempool.lane_size(LANE_BULK)
        if bulk >= self.cfg.bulk_headroom_frac * max(1, self.mempool.config.size):
            return True
        return self._bulk_rate_exceeded(now)

    # -- RPC edge --

    def admit_rpc(self, tx: bytes, key: bytes, now: float | None = None) -> int:
        """Admit a client-submitted tx (key = sha256(tx)); returns its
        lane. Raises ErrDuplicateTx / ErrOverloaded (see module doc for
        the ordering contract)."""
        tr = self.tracer
        traced = tr.active and tr.sampled_key(key)
        t0 = monotonic() if traced else 0.0
        if not self.cfg.enabled:
            return self.lane_of(tx)
        with self._mtx:
            self._sh_state.note_read()
            dup = key in self.dedup
        if dup:
            self.metrics.rejected_dup.add(1)
            raise ErrDuplicateTx(f"tx {key.hex()[:16]} replayed at the edge")
        lane = self.lane_of(tx)
        if lane == LANE_PRIORITY:
            # per-sender fairness: an over-budget priority sender keeps
            # its LANE (lane assignment stays a pure function of the tx
            # bytes) but loses the lane's unconditional admission — its
            # overflow is subjected to the same shed rules as bulk
            sender = parse_sender(tx)
            if sender and self._priority_sender_exceeded(sender, now):
                self.metrics.priority_sender_limited.add(1)
                if self._bulk_shed(now):
                    self.metrics.priority_sender_shed.add(1)
                    raise ErrOverloaded(self.cfg.retry_after)
        elif self._bulk_shed(now):
            self.metrics.rejected_overload.add(1)
            raise ErrOverloaded(self.cfg.retry_after)
        with self._mtx:
            self._sh_state.note_write()
            self.dedup.push(key)
        if lane == LANE_PRIORITY:
            self.metrics.admitted_priority.add(1)
        else:
            self.metrics.admitted_bulk.add(1)
        if traced:
            tr.span(key.hex().upper(), SPAN_ADMISSION, t0, monotonic())
        return lane

    def forget(self, key: bytes) -> None:
        """Roll an admit_rpc reservation back (mempool rejected the tx
        for a non-dup reason) so the client's retry isn't dup-bounced."""
        with self._mtx:
            self._sh_state.note_write()
            self.dedup.remove(key)

    def _priority_sender_exceeded(
        self, sender: str, now: float | None = None
    ) -> bool:
        """Per-sender token-bucket verdict for ONE priority admission
        (consumes a token on pass). Disabled when the rate knob is 0.
        Same bounded-dict discipline as the peer buckets: at
        priority_sender_max the stalest bucket is evicted."""
        rate = self.cfg.priority_sender_rate
        if rate <= 0:
            return False
        if now is None:
            now = monotonic()
        cap = max(self.cfg.priority_sender_burst, rate, 1.0)
        with self._mtx:
            self._sh_state.note_write()
            b = self._sender_buckets.get(sender)
            if b is None:
                if len(self._sender_buckets) >= max(1, self.cfg.priority_sender_max):
                    stalest = min(
                        self._sender_buckets, key=lambda k: self._sender_buckets[k][1]
                    )
                    del self._sender_buckets[stalest]
                b = self._sender_buckets[sender] = [cap, now]
                self.metrics.priority_sender_tracked.set(len(self._sender_buckets))
            tokens, last = b
            if now > last:
                tokens = min(cap, tokens + (now - last) * rate)
            b[1] = now
            if tokens >= 1.0:
                b[0] = tokens - 1.0
                return False
            b[0] = tokens
            return True

    # -- gossip edge --

    def _peer_rate_exceeded(self, peer_id: str, now: float | None = None) -> bool:
        """Per-peer token-bucket verdict for ONE gossiped tx (consumes a
        token on pass). Disabled when cfg.peer_rate == 0. Buckets live in
        a bounded dict: at peer_max the stalest bucket is evicted, so
        peer churn cannot grow memory."""
        rate = self.cfg.peer_rate
        if rate <= 0:
            return False
        if now is None:
            now = monotonic()
        cap = max(self.cfg.peer_burst, rate, 1.0)
        with self._mtx:
            self._sh_state.note_write()
            b = self._peer_buckets.get(peer_id)
            if b is None:
                if len(self._peer_buckets) >= max(1, self.cfg.peer_max):
                    stalest = min(self._peer_buckets, key=lambda k: self._peer_buckets[k][1])
                    del self._peer_buckets[stalest]
                b = self._peer_buckets[peer_id] = [cap, now]
            tokens, last = b
            if now > last:
                tokens = min(cap, tokens + (now - last) * rate)
            b[1] = now
            if tokens >= 1.0:
                b[0] = tokens - 1.0
                return False
            b[0] = tokens
            return True

    def admit_gossip(self, tx: bytes, peer_id: str | None = None) -> bool:
        """Gate a gossiped tx under pressure: bulk sheds (False, counted),
        priority always passes — the admitted lane's quorums must keep
        forming, so priority ingest is never paused. The per-peer rate
        bucket is checked FIRST and is lane-blind: one flooding peer must
        not crowd the shared ingest path, and a hostile peer marking its
        flood priority must not bypass the cap."""
        if not self.cfg.enabled:
            return True
        if peer_id is not None and self._peer_rate_exceeded(peer_id):
            self.metrics.rejected_peer.add(1)
            return False
        if not self.overloaded():
            return True
        if self.lane_of(tx) == LANE_PRIORITY:
            return True
        self.metrics.rejected_gossip.add(1)
        return False

    def gossip_paused(self) -> bool:
        """Should the mempool reactor pause its BULK broadcast walk?
        (The priority walk and vote gossip never pause.)"""
        return self.cfg.enabled and self.overloaded()
