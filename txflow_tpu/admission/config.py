"""AdmissionConfig: front-door tunables (see controller.py for semantics).

One dataclass so a node assembly, a soak rig, or a test can swap the whole
overload posture at once — the HealthConfig pattern.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class AdmissionConfig:
    enabled: bool = True

    # -- overload hysteresis (fractions of config.mempool.size) --
    # pool occupancy at/above high_water flips the node into overload;
    # it stays overloaded until occupancy falls back to low_water. The
    # gap prevents flapping right at the mark (admit one tx -> overloaded
    # -> shed one -> healthy -> ...), which would thrash the gossip pause
    high_water_frac: float = 0.85
    low_water_frac: float = 0.60
    # bulk-lane headroom: even below high water, best-effort txs may not
    # fill the pool past this fraction — the reserve above it belongs to
    # the priority lane, so a bulk flood can never squeeze priority
    # admissions out of the pool entirely
    bulk_headroom_frac: float = 0.70

    # Retry-After seconds handed to 429'd clients
    retry_after: float = 1.0

    # edge dedup LRU (replayed tx bytes rejected before signature work);
    # sized above the mempool dedup cache so the edge absorbs replays the
    # pool cache has already rotated out
    dedup_size: int = 65536

    # fee-prefix lane classifier: txs carrying b"fee=<n>;" with
    # n >= this threshold ride the priority lane (classifier.py)
    priority_fee_threshold: int = 1

    # occupancy poll cadence: the overload verdict is cached this long so
    # the admit path costs O(1) between polls (no pool lock per request)
    pressure_interval: float = 0.05

    # bulk admit-rate cap (token bucket, tx/s; 0 disables). Occupancy
    # watermarks alone admit bulk until buffers FILL — classic
    # bufferbloat: the pool then runs at headroom depth and every queue
    # behind it (vote pool, verify engine) saturates, which taxes the
    # priority lane's latency even though it never queues. Capping the
    # bulk ADMIT RATE below pipeline capacity keeps the system inside
    # its latency headroom while the flood sheds with 429 + Retry-After.
    #
    # When the node wires a commit_rate_source into the controller, this
    # static value becomes only the STARTUP rate: the bucket's fill then
    # tracks the engine's measured commit rate (see the adaptive knobs
    # below). Static assemblies (no source) keep the PR 6 semantics.
    bulk_rate: float = 0.0
    # token-bucket burst depth (tx); 0 = one second's worth of bulk_rate
    bulk_burst: float = 0.0

    # -- adaptive bulk rate (active only with a commit_rate_source) --
    # the bucket refills at EWMA(commit rate) * headroom: slightly above
    # what the pipeline demonstrably drains, so bulk admission can probe
    # upward but cannot outrun commits for long
    bulk_rate_headroom: float = 1.25
    # never adapt below this fill rate (tx/s): a cold start or a commit
    # stall must not latch the front door shut
    bulk_rate_floor: float = 50.0
    # EWMA smoothing for the sampled commit rate (per pressure poll)
    bulk_rate_alpha: float = 0.3
    # hysteresis band: the effective rate only moves when the new target
    # is more than this fraction away from it — a stable workload sees a
    # stable admit rate instead of a jittering one
    bulk_rate_hysteresis: float = 0.2

    # -- per-peer gossip rate cap (token bucket, tx/s; 0 disables) --
    # one flooding peer must not crowd the shared ingest path; the cap is
    # per sender and lane-blind (a hostile peer could mark everything
    # priority, so the priority pass-through must not bypass it)
    peer_rate: float = 0.0
    # per-peer burst depth (tx); 0 = one second's worth of peer_rate
    peer_burst: float = 0.0
    # bounded number of tracked peer buckets (LRU-ish eviction of the
    # stalest bucket when full — unbounded peer churn can't grow memory)
    peer_max: int = 256

    # -- per-sender fairness inside the priority lane (token bucket,
    # tx/s; 0 disables) -- one fee-bearing flooder must not starve other
    # priority senders. Sender identity is the tx's ``from=<id>;`` prefix
    # tag (classifier.parse_sender); lane assignment itself is untouched
    # (it must stay a deterministic function of the tx bytes), so an
    # over-budget sender's txs are instead subjected to the BULK shed
    # rules at the RPC edge (429 under pressure) while on-budget priority
    # senders keep their unconditional admission
    priority_sender_rate: float = 0.0
    # per-sender burst depth (tx); 0 = one second's worth of the rate
    priority_sender_burst: float = 0.0
    # bounded number of tracked sender buckets (stalest-evicted like the
    # peer buckets — hostile sender churn can't grow memory)
    priority_sender_max: int = 256
