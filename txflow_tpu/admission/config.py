"""AdmissionConfig: front-door tunables (see controller.py for semantics).

One dataclass so a node assembly, a soak rig, or a test can swap the whole
overload posture at once — the HealthConfig pattern.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class AdmissionConfig:
    enabled: bool = True

    # -- overload hysteresis (fractions of config.mempool.size) --
    # pool occupancy at/above high_water flips the node into overload;
    # it stays overloaded until occupancy falls back to low_water. The
    # gap prevents flapping right at the mark (admit one tx -> overloaded
    # -> shed one -> healthy -> ...), which would thrash the gossip pause
    high_water_frac: float = 0.85
    low_water_frac: float = 0.60
    # bulk-lane headroom: even below high water, best-effort txs may not
    # fill the pool past this fraction — the reserve above it belongs to
    # the priority lane, so a bulk flood can never squeeze priority
    # admissions out of the pool entirely
    bulk_headroom_frac: float = 0.70

    # Retry-After seconds handed to 429'd clients
    retry_after: float = 1.0

    # edge dedup LRU (replayed tx bytes rejected before signature work);
    # sized above the mempool dedup cache so the edge absorbs replays the
    # pool cache has already rotated out
    dedup_size: int = 65536

    # fee-prefix lane classifier: txs carrying b"fee=<n>;" with
    # n >= this threshold ride the priority lane (classifier.py)
    priority_fee_threshold: int = 1

    # occupancy poll cadence: the overload verdict is cached this long so
    # the admit path costs O(1) between polls (no pool lock per request)
    pressure_interval: float = 0.05

    # bulk admit-rate cap (token bucket, tx/s; 0 disables). Occupancy
    # watermarks alone admit bulk until buffers FILL — classic
    # bufferbloat: the pool then runs at headroom depth and every queue
    # behind it (vote pool, verify engine) saturates, which taxes the
    # priority lane's latency even though it never queues. Capping the
    # bulk ADMIT RATE below pipeline capacity keeps the system inside
    # its latency headroom while the flood sheds with 429 + Retry-After.
    #
    # When the node wires a commit_rate_source into the controller, this
    # static value becomes only the STARTUP rate: the bucket's fill then
    # tracks the engine's measured commit rate (see the adaptive knobs
    # below). Static assemblies (no source) keep the PR 6 semantics.
    bulk_rate: float = 0.0
    # token-bucket burst depth (tx); 0 = one second's worth of bulk_rate
    bulk_burst: float = 0.0

    # -- adaptive bulk rate (active only with a commit_rate_source) --
    # the bucket refills at EWMA(commit rate) * headroom: slightly above
    # what the pipeline demonstrably drains, so bulk admission can probe
    # upward but cannot outrun commits for long
    bulk_rate_headroom: float = 1.25
    # never adapt below this fill rate (tx/s): a cold start or a commit
    # stall must not latch the front door shut
    bulk_rate_floor: float = 50.0
    # EWMA smoothing for the sampled commit rate (per pressure poll)
    bulk_rate_alpha: float = 0.3
    # hysteresis band: the effective rate only moves when the new target
    # is more than this fraction away from it — a stable workload sees a
    # stable admit rate instead of a jittering one
    bulk_rate_hysteresis: float = 0.2

    # -- per-peer gossip rate cap (token bucket, tx/s; 0 disables) --
    # one flooding peer must not crowd the shared ingest path; the cap is
    # per sender and lane-blind (a hostile peer could mark everything
    # priority, so the priority pass-through must not bypass it)
    peer_rate: float = 0.0
    # per-peer burst depth (tx); 0 = one second's worth of peer_rate
    peer_burst: float = 0.0
    # bounded number of tracked peer buckets (LRU-ish eviction of the
    # stalest bucket when full — unbounded peer churn can't grow memory)
    peer_max: int = 256

    # -- per-sender fairness inside the priority lane (token bucket,
    # tx/s; 0 disables) -- one fee-bearing flooder must not starve other
    # priority senders. Sender identity is the tx's ``from=<id>;`` prefix
    # tag (classifier.parse_sender); lane assignment itself is untouched
    # (it must stay a deterministic function of the tx bytes), so an
    # over-budget sender's txs are instead subjected to the BULK shed
    # rules at the RPC edge (429 under pressure) while on-budget priority
    # senders keep their unconditional admission
    priority_sender_rate: float = 0.0
    # per-sender burst depth (tx); 0 = one second's worth of the rate
    priority_sender_burst: float = 0.0
    # bounded number of tracked sender buckets (stalest-evicted like the
    # peer buckets — hostile sender churn can't grow memory)
    priority_sender_max: int = 256


def soak_spec_overrides() -> dict:
    """The shared admission posture for multi-process soak/grid nets
    (tools/soak.py --overload and the scenario-grid runner), as plain
    JSON-able kwargs for the procnode ``admission`` spec field.

    The numbers encode one capacity statement: these boxes run 4 nodes
    on shared cores with the scalar (host) verifier at ~5 ms/signature,
    so system-wide commit capacity is a few tx/s. Admitting bulk faster
    than committing grows the pending backlog (sign walks + regossip
    re-walks scale with it) and probe latency degrades minute over
    minute — 1 tx/s per RPC node holds the backlog in equilibrium while
    the flood sheds with 429 + Retry-After. Tight retry_after and
    pressure_interval keep the shed loop responsive at soak timescales.

    ``bulk_rate_floor`` and ``bulk_rate_headroom`` matter as much as
    ``bulk_rate``: the node wires a commit_rate_source, which flips the
    controller into ADAPTIVE bulk rating — and the adaptive path reads
    ``max(bulk_rate_floor, ewma * headroom)``, never the static
    ``bulk_rate``. The default floor (50 tx/s, sized for device-verify
    builds) silently un-caps a scalar soak box, and the default headroom
    (1.25) admits ABOVE the measured commit rate — correct for a box
    with latency slack, but on a saturated soak box it guarantees a
    growing bulk queue and a priority p50 that degrades minute over
    minute (observed live: p50 3.1s against a 750ms budget).

    Headroom must also divide by the FAN-IN: every node's EWMA measures
    the SYSTEM commit throughput (each node commits every tx), so K
    front doors taking load each admit ``headroom x capacity`` and the
    aggregate is ``K x headroom x capacity``. The soak and grid rigs
    spread their floods over ~2 RPC targets, so per-node headroom must
    sit below 1/2 for the aggregate to stay sub-capacity — 0.35 lands
    the fleet at ~0.7x of what the box has proven it can commit, which
    turns the feedback loop into a drain: the backlog shrinks whenever
    it exists (observed live: headroom 0.7 still left p50 at 1.4s;
    0.35 brought it back under budget).
    """
    return {
        "retry_after": 0.25,
        "pressure_interval": 0.02,
        "bulk_rate": 1.0,
        "bulk_burst": 2.0,
        "bulk_rate_floor": 1.0,
        "bulk_rate_headroom": 0.35,
    }
