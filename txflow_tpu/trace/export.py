"""Cross-node trace merge + Chrome-trace/Perfetto JSON export.

A tracer ``dump()`` is one node's view; ``to_chrome_trace`` merges any
number of them onto one wall-clock timeline (each tracer records its
``base_wall_ns``/``base_mono`` pair, so monotonic span timestamps from
different processes align to within wall-clock skew — fine on one host,
and good enough to eyeball cross-host gossip latency).

The output is the Chrome JSON trace format (the ``traceEvents`` array
of ``ph:"X"`` complete events) which Perfetto and chrome://tracing open
directly: one process per node, one track per span family in
commit-path order, every event tagged with its tx hash in ``args`` so
the Perfetto query engine can follow one transaction across nodes.
"""

from __future__ import annotations

import json

from .tracer import SPAN_ORDER


def _track_id(name: str) -> int:
    """Stable small tid per span family (commit-path order first)."""
    try:
        return SPAN_ORDER.index(name) + 1
    except ValueError:
        return len(SPAN_ORDER) + 1 + (sum(name.encode()) % 32)


def merge_by_tx(dumps: list[dict]) -> dict[str, list[dict]]:
    """tx hash -> spans from EVERY node, each tagged with its node id
    and converted to wall-clock microseconds."""
    out: dict[str, list[dict]] = {}
    for d in dumps:
        base_wall_us = d.get("base_wall_ns", 0) / 1e3
        base_mono = d.get("base_mono", 0.0)
        node = d.get("node", "")
        for s in d.get("spans", []):
            ts = base_wall_us + (s["start"] - base_mono) * 1e6
            out.setdefault(s["tx"], []).append(
                {
                    "node": node,
                    "name": s["name"],
                    "ts_us": ts,
                    "dur_us": max(0.0, (s["end"] - s["start"]) * 1e6),
                }
            )
    for spans in out.values():
        spans.sort(key=lambda s: s["ts_us"])
    return out


def to_chrome_trace(dumps: list[dict]) -> dict:
    """Merged dumps -> {"traceEvents": [...]} (Perfetto-openable)."""
    events: list[dict] = []
    for pid, d in enumerate(dumps):
        node = d.get("node", "") or f"node-{pid}"
        base_wall_us = d.get("base_wall_ns", 0) / 1e3
        base_mono = d.get("base_mono", 0.0)
        events.append(
            {
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": node},
            }
        )
        named: set[int] = set()
        for s in d.get("spans", []):
            tid = _track_id(s["name"])
            if tid not in named:
                named.add(tid)
                events.append(
                    {
                        "name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tid, "args": {"name": s["name"]},
                    }
                )
            events.append(
                {
                    "name": s["name"],
                    "cat": "txflow",
                    "ph": "X",
                    "pid": pid,
                    "tid": tid,
                    "ts": base_wall_us + (s["start"] - base_mono) * 1e6,
                    "dur": max(0.0, (s["end"] - s["start"]) * 1e6),
                    "args": {"tx": s["tx"]},
                }
            )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, dumps: list[dict]) -> int:
    """Write the merged trace; returns the number of span events."""
    doc = to_chrome_trace(dumps)
    with open(path, "w") as f:
        json.dump(doc, f)
    return sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
