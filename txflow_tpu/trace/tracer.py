"""Per-transaction tracing: bounded span rings + latency histograms.

The commit path is instrumented with named spans (SPAN_* constants
below) recorded into a per-node preallocated ring buffer. Design
constraints, in order:

1. **Low overhead, always on.** The sampling decision is one integer
   xor/mod derived from the tx hash (deterministic across nodes and
   replays — never Python ``hash()``, which is PYTHONHASHSEED-salted),
   and a recorded span is one tuple store under a leaf lock. The tier-1
   overhead gate (tests/test_trace.py) pins the per-vote cost under 3%
   of a scalar signature verify.
2. **Deterministic timestamps.** Every clock read routes through the
   ``utils/clock.py`` monotonic seam, enforced by txlint's
   ``trace-clock`` pass over the traced modules — replays pin one
   module and get reproducible spans.
3. **Zero-cost off switch.** ``TraceConfig(enabled=False)`` yields the
   ``NullTracer``: every method is a constant-return no-op, no ring, no
   histograms.

Leak accounting: ``begin()``/``finish()`` pairs (device tickets in
flight, commit queue residency) are tracked in an open table;
``open_count()`` must return 0 after quiescence — ``tools/soak.py
--overload`` asserts this over RPC, the same class of check as the
PR 3 drain-on-stop claim-leak proof.
"""

from __future__ import annotations

from ..analysis.lockgraph import make_lock
from ..utils.clock import monotonic, now_ns
from ..utils.config import TraceConfig
from ..utils.metrics import GLOBAL, Registry

# canonical span names, in commit-path order (export assigns one
# Perfetto track per name, in this order)
SPAN_ADMISSION = "admission"
SPAN_TX_INGEST = "mempool_ingest"
SPAN_GOSSIP_INGEST = "gossip_ingest"
SPAN_SIGN = "sign_walk"
SPAN_VOTE_INGEST = "vote_ingest"
# accountable gossip (health/byzantine.py): a vote rejected by the O(1)
# ingest pre-checks (unknown validator / stale height) — zero-length
# marker at the drop instant, so a trace shows WHERE hostile traffic
# died relative to the honest pipeline
SPAN_PRE_DROP = "pre_verify_drop"
SPAN_LOCK_WAIT = "lock_wait"
SPAN_LINGER = "linger"
# per-lane coalescer holds (ISSUE 12 verify lanes): the engine's bulk
# lane records linger_bulk, the priority lane linger_prio; the plain
# "linger" family remains for single-lane coalescers and old dumps —
# report.py sums all three into the critical-path linger bucket
SPAN_LINGER_PRIO = "linger_prio"
SPAN_LINGER_BULK = "linger_bulk"
# speculative quorum commit: decision-to-route-end window of a commit
# that left early on the device quorum hint — its length IS the route
# tail the early exit removed for that tx
SPAN_SPEC = "spec_commit"
SPAN_PREP = "host_prep"
SPAN_DEVICE = "device_verify"
SPAN_QUORUM = "quorum_latch"
SPAN_COMMIT = "commit_apply"
# catch-up sync (sync/): fetch = request sent -> response received,
# verify = certificate batch re-verification, apply = commit-seam apply
SPAN_SYNC_FETCH = "sync_fetch"
SPAN_SYNC_VERIFY = "sync_verify"
SPAN_SYNC_APPLY = "sync_apply"
SPAN_E2E = "e2e"

SPAN_ORDER = (
    SPAN_ADMISSION, SPAN_TX_INGEST, SPAN_GOSSIP_INGEST, SPAN_SIGN,
    SPAN_VOTE_INGEST, SPAN_PRE_DROP, SPAN_LOCK_WAIT, SPAN_LINGER, SPAN_LINGER_PRIO,
    SPAN_LINGER_BULK, SPAN_PREP, SPAN_DEVICE, SPAN_QUORUM, SPAN_SPEC,
    SPAN_COMMIT, SPAN_SYNC_FETCH, SPAN_SYNC_VERIFY, SPAN_SYNC_APPLY,
    SPAN_E2E,
)


class NullTracer:
    """Zero-cost stand-in when tracing is off: same surface, no state."""

    active = False

    def sampled(self, tx_hash) -> bool:
        return False

    def sampled_key(self, key) -> bool:
        return False

    def span(self, tx_hash, name, start, end) -> None:
        pass

    def begin(self, tx_hash, name, start=None) -> int:
        return 0

    def finish(self, span_id, end=None) -> None:
        pass

    def abandon(self, span_id) -> None:
        pass

    def anchor(self, tx_hash, t=None) -> None:
        pass

    def latch(self, tx_hash, name=SPAN_E2E, t=None) -> None:
        pass

    def open_count(self) -> int:
        return 0

    def spans(self) -> list:
        return []

    def digest(self) -> dict:
        return {"enabled": False, "open_spans": 0, "recorded": 0, "dropped": 0}

    def dump(self, node_id: str = "") -> dict:
        return {
            "node": node_id,
            "base_wall_ns": 0,
            "base_mono": 0.0,
            "spans": [],
            "open_spans": 0,
        }

    def reset(self) -> None:
        pass


NULL_TRACER = NullTracer()

# fine-grained low end (sub-ms host stages) up through multi-second
# commit tails — one ladder for every span family so digests compare
LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class TraceMetrics:
    """``txflow_trace_*`` bundle: per-span-name latency histograms plus
    the recorded/open counters the soak's leak assertion scrapes."""

    def __init__(self, registry: Registry | None = None):
        r = registry or GLOBAL
        self._r = r
        self.spans_recorded = r.counter(
            "trace", "spans_recorded_total", "trace spans recorded into the ring"
        )
        self.open_spans = r.gauge(
            "trace", "open_spans", "begun spans not yet finished (0 after quiescence)"
        )
        self._hists: dict[str, object] = {}

    def observe(self, name: str, duration_s: float) -> None:
        h = self._hists.get(name)
        if h is None:
            # Registry._reg dedupes under its own lock, so a racing first
            # observe lands on the same Histogram instance
            h = self._r.histogram(
                "trace", f"span_{name}_seconds",
                f"{name} span duration", buckets=LATENCY_BUCKETS,
            )
            self._hists[name] = h
        h.observe(duration_s)
        self.spans_recorded.add(1)

    def quantiles_ms(self) -> dict[str, dict]:
        out: dict[str, dict] = {}
        for name in sorted(self._hists):
            h = self._hists[name]
            q = {
                "p50": h.quantile(0.5),
                "p99": h.quantile(0.99),
                "p999": h.quantile(0.999),
            }
            out[name] = {
                k: (round(v * 1e3, 3) if v is not None else None)
                for k, v in q.items()
            }
            out[name]["count"] = h._count
            out[name]["sum_ms"] = round(h._sum * 1e3, 3)
        return out


class Tracer:
    """Per-node span recorder. All timestamps are utils.clock.monotonic
    seconds; ``base_wall_ns``/``base_mono`` anchor them to the wall
    clock so cross-node merges land on one timeline (export.py)."""

    active = True

    def __init__(
        self,
        config: TraceConfig | None = None,
        registry: Registry | None = None,
        node_id: str = "",
    ):
        cfg = config or TraceConfig()
        self.sample_rate = max(1, int(cfg.sample_rate))
        self.seed = int(cfg.seed) & 0xFFFFFFFF
        self.capacity = max(16, int(cfg.ring_capacity))
        self.node_id = node_id
        self._ring: list = [None] * self.capacity
        self._n = 0  # spans ever recorded; ring index = _n % capacity
        self._open: dict[int, tuple] = {}
        self._next_id = 1
        self._anchors: dict[str, float] = {}
        self._anchor_cap = 4 * self.capacity
        self._lk = make_lock("trace.Tracer._lk")
        self.base_wall_ns = now_ns()
        self.base_mono = monotonic()
        self.metrics = TraceMetrics(registry) if registry is not None else None

    # -- sampling (deterministic: same txs on every node / every replay) --

    def sampled(self, tx_hash: str) -> bool:
        """1-in-sample_rate by the leading 32 bits of the tx hash."""
        try:
            v = int(tx_hash[:8], 16)
        except (ValueError, TypeError):
            return False
        return (v ^ self.seed) % self.sample_rate == 0

    def sampled_key(self, key: bytes) -> bool:
        """Same predicate from the raw digest (key[:4] == hex[:8])."""
        if len(key) < 4:
            return False
        return (int.from_bytes(key[:4], "big") ^ self.seed) % self.sample_rate == 0

    # -- span recording --

    def _record(self, tx_hash: str, name: str, start: float, end: float) -> None:
        with self._lk:
            self._ring[self._n % self.capacity] = (tx_hash, name, start, end)
            self._n += 1
        if self.metrics is not None:
            self.metrics.observe(name, max(0.0, end - start))

    def span(self, tx_hash: str, name: str, start: float, end: float) -> None:
        """Record a complete span (both ends measured by the caller)."""
        self._record(tx_hash, name, start, end)

    def begin(self, tx_hash: str, name: str, start: float | None = None) -> int:
        """Open a cross-thread span; returns an id for finish()/abandon().
        Every begun span must be closed — open_count() is the leak
        detector the soak asserts against."""
        t = monotonic() if start is None else start
        with self._lk:
            sid = self._next_id
            self._next_id += 1
            self._open[sid] = (tx_hash, name, t)
        return sid

    def finish(self, span_id: int, end: float | None = None) -> None:
        if not span_id:
            return
        t = monotonic() if end is None else end
        with self._lk:
            entry = self._open.pop(span_id, None)
        if entry is not None:
            self._record(entry[0], entry[1], entry[2], t)

    def abandon(self, span_id: int) -> None:
        """Close without recording (work shed or superseded mid-span)."""
        if not span_id:
            return
        with self._lk:
            self._open.pop(span_id, None)

    # -- end-to-end anchoring (first ingest -> commit) --

    def anchor(self, tx_hash: str, t: float | None = None) -> None:
        """First-seen timestamp for the e2e span (idempotent). Bounded:
        anchors for txs that never commit (shed, evicted) age out FIFO
        instead of growing without bound."""
        tm = monotonic() if t is None else t
        with self._lk:
            if tx_hash in self._anchors:
                return
            if len(self._anchors) >= self._anchor_cap:
                self._anchors.pop(next(iter(self._anchors)))
            self._anchors[tx_hash] = tm

    def latch(self, tx_hash: str, name: str = SPAN_E2E, t: float | None = None) -> None:
        """Close the anchored span (commit applied). No-op when the
        anchor aged out or the tx was never anchored."""
        with self._lk:
            t0 = self._anchors.pop(tx_hash, None)
        if t0 is not None:
            self._record(tx_hash, name, t0, monotonic() if t is None else t)

    # -- introspection --

    def open_count(self) -> int:
        with self._lk:
            return len(self._open)

    def spans(self) -> list[dict]:
        """Ring contents, oldest first, as export-ready dicts."""
        with self._lk:
            n = self._n
            if n <= self.capacity:
                buf = list(self._ring[:n])
            else:
                i = n % self.capacity
                buf = self._ring[i:] + self._ring[:i]
        return [
            {"tx": tx, "name": name, "start": s, "end": e}
            for (tx, name, s, e) in buf
        ]

    def dropped(self) -> int:
        with self._lk:
            return max(0, self._n - self.capacity)

    def digest(self) -> dict:
        """p50/p99/p999 per span family + leak counters (/health)."""
        with self._lk:
            recorded = self._n
            open_spans = len(self._open)
        d = {
            "enabled": True,
            "sample_rate": self.sample_rate,
            "recorded": recorded,
            "dropped": max(0, recorded - self.capacity),
            "open_spans": open_spans,
        }
        if self.metrics is not None:
            self.metrics.open_spans.set(open_spans)
            d["latency_ms"] = self.metrics.quantiles_ms()
        return d

    def dump(self, node_id: str = "") -> dict:
        """Everything export/merge needs from one node."""
        return {
            "node": node_id or self.node_id,
            "base_wall_ns": self.base_wall_ns,
            "base_mono": self.base_mono,
            "open_spans": self.open_count(),
            "dropped": self.dropped(),
            "spans": self.spans(),
        }

    def reset(self) -> None:
        with self._lk:
            self._ring = [None] * self.capacity
            self._n = 0
            self._open.clear()
            self._anchors.clear()


def make_tracer(
    config: TraceConfig | None = None,
    registry: Registry | None = None,
    node_id: str = "",
):
    """Tracer or NullTracer per config — the ONE construction seam."""
    cfg = config or TraceConfig()
    if not getattr(cfg, "enabled", True):
        return NULL_TRACER
    return Tracer(cfg, registry=registry, node_id=node_id)
