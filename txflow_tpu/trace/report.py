"""Critical-path attribution: where did the wall time go?

Folds one node's engine pipeline accounting (``TxFlow.pipeline_stats``)
and its trace digest into the host-prep / device / linger / lock-wait /
network breakdown the ROADMAP's two open perf frontiers are steered by
(the sim predicts the shared-cache config is HOST-bound; this report is
what validates or falsifies that on a live run). Wired into
``profile_host.py`` (per-node lines) and ``bench.py --latency-slo``
(result-JSON ``critical_path``)."""

from __future__ import annotations


def critical_path(pipeline_stats: dict, trace_digest: dict | None = None) -> dict:
    """One node's attribution: seconds + fractions per component.

    Components: ``host_s`` (batch prep + commit routing, minus lock
    wait), ``device_s`` (blocked collecting verify tickets), ``lock_-
    wait_s`` (acquiring the engine mutex), ``linger_s`` (coalescer
    deadline holds, from the trace histogram sums — merged + per-lane
    families; the priority/bulk split is exposed alongside as
    ``linger_prio_s`` / ``linger_bulk_s`` so a lane-split run shows
    WHICH lane paid the hold), ``network_residual_ms`` (e2e p50 minus
    the sum of in-node stage p50s: gossip transit + queueing the
    in-node stages can't see). A speculative-commit run also reports
    ``spec_saved_s`` — the route-tail seconds the early quorum exit
    removed (engine ``spec`` stats) — as attribution context, not a
    busy component (it is time NOT spent)."""
    stats = pipeline_stats or {}
    lat = (trace_digest or {}).get("latency_ms") or {}

    def sum_s(name: str) -> float:
        return (lat.get(name, {}).get("sum_ms") or 0.0) / 1e3

    lock_wait = stats.get("lock_wait_s", 0.0)
    prep = stats.get("prep_s", 0.0)
    route = stats.get("route_s", 0.0)
    host = max(0.0, prep - lock_wait) + route
    linger_prio = sum_s("linger_prio")
    linger_bulk = sum_s("linger_bulk")
    parts = {
        "host_s": host,
        "device_s": stats.get("dispatch_wait_s", 0.0),
        "lock_wait_s": lock_wait,
        # legacy merged family + the per-lane families: a pre-lane trace
        # has only "linger", a lane-split run only the per-lane ones
        "linger_s": sum_s("linger") + linger_prio + linger_bulk,
    }
    busy = sum(parts.values())
    out = {k: round(v, 4) for k, v in parts.items()}
    if linger_prio > 0.0 or linger_bulk > 0.0:
        out["linger_prio_s"] = round(linger_prio, 4)
        out["linger_bulk_s"] = round(linger_bulk, 4)
    spec = stats.get("spec") or {}
    if spec.get("commits"):
        out["spec_saved_s"] = round(spec.get("saved_s", 0.0), 4)
        out["spec_commits"] = int(spec["commits"])
    # When a host-prep pool ran, split the host bucket into the serial
    # remainder vs time spent waiting on pool shards (pool wait is wall
    # time the caller could NOT overlap — the lever sharded host prep
    # pulls on). host_s stays their sum for downstream compat.
    pool_wait = stats.get("prep_pool_wait_s", 0.0)
    if pool_wait > 0.0:
        out["prep_pool_wait_s"] = round(min(pool_wait, host), 4)
        out["prep_serial_s"] = round(host - min(pool_wait, host), 4)
    # Staging-ring split of the device bucket: device_s keeps its
    # historical meaning (wall seconds blocked collecting tickets =
    # dispatch + the readback slice the ring could NOT hide), aliased
    # as device_dispatch_s; readback_overlap_hidden_s is the D2H
    # transfer seconds the ring ran UNDER the engine's next-batch prep
    # (parallel.staging hidden_s) — attribution context like
    # spec_saved_s: time removed from the critical path, not busy time.
    ring = stats.get("staging") or {}
    if ring.get("slots_total"):
        out["device_dispatch_s"] = out["device_s"]
        out["readback_overlap_hidden_s"] = round(
            ring.get("hidden_s", 0.0), 4
        )
    if busy > 0:
        out["fractions"] = {
            k.removesuffix("_s"): round(v / busy, 4) for k, v in parts.items()
        }
        out["bound"] = max(parts, key=parts.get).removesuffix("_s")
    # network + cross-stage queueing residual, per sampled tx (p50s)
    e2e = lat.get("e2e", {}).get("p50")
    if e2e is not None:
        stage_sum = sum(
            lat.get(n, {}).get("p50") or 0.0
            for n in ("vote_ingest", "host_prep", "device_verify",
                      "quorum_latch", "commit_apply", "linger",
                      "linger_prio", "linger_bulk")
        )
        out["network_residual_ms"] = round(max(0.0, e2e - stage_sum), 3)
    return out


def merge_critical_paths(per_node: list[dict]) -> dict:
    """Sum the seconds components across nodes, recompute fractions —
    the fleet-level line bench.py emits."""
    keys = ("host_s", "device_s", "lock_wait_s", "linger_s")
    total = {k: round(sum(cp.get(k, 0.0) for cp in per_node), 4) for k in keys}
    for k in ("prep_serial_s", "prep_pool_wait_s", "linger_prio_s",
              "linger_bulk_s", "spec_saved_s", "device_dispatch_s",
              "readback_overlap_hidden_s"):
        if any(k in cp for cp in per_node):
            total[k] = round(sum(cp.get(k, 0.0) for cp in per_node), 4)
    if any("spec_commits" in cp for cp in per_node):
        total["spec_commits"] = sum(
            cp.get("spec_commits", 0) for cp in per_node
        )
    busy = sum(total[k] for k in keys)
    if busy > 0:
        total["fractions"] = {
            k.removesuffix("_s"): round(v / busy, 4) for k, v in total.items()
            if k in keys
        }
        total["bound"] = max(keys, key=lambda k: total[k]).removesuffix("_s")
    residuals = [
        cp["network_residual_ms"] for cp in per_node
        if cp.get("network_residual_ms") is not None
    ]
    if residuals:
        total["network_residual_ms"] = round(
            sum(residuals) / len(residuals), 3
        )
    return total


def format_line(cp: dict) -> str:
    """One-line rendering for profile_host.py."""
    f = cp.get("fractions") or {}
    parts = " ".join(
        f"{k.removesuffix('_s')}={cp.get(k, 0.0):.3f}s({f.get(k.removesuffix('_s'), 0):.0%})"
        for k in ("host_s", "device_s", "lock_wait_s", "linger_s")
    )
    line = f"critical-path: {parts} bound={cp.get('bound', 'n/a')}"
    if "linger_prio_s" in cp or "linger_bulk_s" in cp:
        line += (
            f" linger[prio={cp.get('linger_prio_s', 0.0):.3f}s"
            f" bulk={cp.get('linger_bulk_s', 0.0):.3f}s]"
        )
    if "prep_pool_wait_s" in cp:
        line += (
            f" host[prep_serial={cp.get('prep_serial_s', 0.0):.3f}s"
            f" prep_pool_wait={cp['prep_pool_wait_s']:.3f}s]"
        )
    if cp.get("readback_overlap_hidden_s") is not None:
        line += (
            f" device[dispatch={cp.get('device_dispatch_s', 0.0):.3f}s"
            f" readback_hidden={cp['readback_overlap_hidden_s']:.3f}s]"
        )
    if cp.get("spec_saved_s") is not None:
        line += (
            f" spec_saved={cp['spec_saved_s']:.3f}s"
            f"({cp.get('spec_commits', 0)})"
        )
    if cp.get("network_residual_ms") is not None:
        line += f" net_residual={cp['network_residual_ms']:.1f}ms"
    return line
