"""txtrace: per-transaction tracing, latency histograms, critical-path
attribution. See tracer.py for the recording model, export.py for the
Perfetto merge, report.py for the host/device breakdown."""

from .export import merge_by_tx, to_chrome_trace, write_chrome_trace
from .report import critical_path, format_line, merge_critical_paths
from .tracer import (
    LATENCY_BUCKETS,
    NULL_TRACER,
    SPAN_ADMISSION,
    SPAN_COMMIT,
    SPAN_DEVICE,
    SPAN_E2E,
    SPAN_GOSSIP_INGEST,
    SPAN_LINGER,
    SPAN_LOCK_WAIT,
    SPAN_ORDER,
    SPAN_PREP,
    SPAN_QUORUM,
    SPAN_SIGN,
    SPAN_TX_INGEST,
    SPAN_VOTE_INGEST,
    NullTracer,
    TraceConfig,
    TraceMetrics,
    Tracer,
    make_tracer,
)

__all__ = [
    "LATENCY_BUCKETS", "NULL_TRACER", "NullTracer", "TraceConfig",
    "TraceMetrics", "Tracer", "make_tracer",
    "SPAN_ADMISSION", "SPAN_COMMIT", "SPAN_DEVICE", "SPAN_E2E",
    "SPAN_GOSSIP_INGEST", "SPAN_LINGER", "SPAN_LOCK_WAIT", "SPAN_ORDER",
    "SPAN_PREP", "SPAN_QUORUM", "SPAN_SIGN", "SPAN_TX_INGEST",
    "SPAN_VOTE_INGEST",
    "merge_by_tx", "to_chrome_trace", "write_chrome_trace",
    "critical_path", "format_line", "merge_critical_paths",
]
