"""Durable stores over a KV database (reference tx/, store/, state/store.go)."""

from .db import DB, FileDB, MemDB
from .tx_store import TxStore

__all__ = ["DB", "FileDB", "MemDB", "TxStore"]
