"""TxStore: durable store of fast-path-committed transactions.

Reference tx/store.go:28-163 — rows keyed ``H:<txhash>`` (the TxVoteSet)
and ``C:<txhash>`` (the Commit certificate), plus a height-watermark JSON
under ``TxStoreHeight``. Values here use the framework's deterministic
codec (votes are amino-compatible; the envelope is length-prefixed
concatenation) — the storage format is node-internal in the reference too.
Load methods raise on undecodable rows (probable disk corruption), like
the reference's panics.
"""

from __future__ import annotations

import json
import threading

from ..analysis.lockgraph import make_lock

from ..codec import amino
from ..types import Commit, CommitSig, TxVote, TxVoteSet, decode_tx_vote, encode_tx_vote
from ..types.validator import ValidatorSet
from .db import DB

_HEIGHT_KEY = b"TxStoreHeight"


def _tx_key(tx_hash: str) -> bytes:
    return b"H:" + tx_hash.encode()


def _commit_key(tx_hash: str) -> bytes:
    return b"C:" + tx_hash.encode()


def _encode_votes(votes: list[TxVote]) -> bytes:
    out = bytearray()
    for v in votes:
        out += amino.length_prefixed(encode_tx_vote(v))
    return bytes(out)


def _decode_votes(data: bytes) -> list[TxVote]:
    votes, off = [], 0
    while off < len(data):
        ln, off = amino.read_uvarint(data, off)
        votes.append(decode_tx_vote(data[off : off + ln]))
        off += ln
    return votes


class TxStore:
    def __init__(self, db: DB):
        self.db = db
        self._mtx = make_lock("store.TxStore._mtx", allow_blocking=True)
        self._height = self._load_height()
        self._seq = self._load_seq()

    def _load_height(self) -> int:
        raw = self.db.get(_HEIGHT_KEY)
        if raw is None:
            return 0
        return json.loads(raw)["height"]

    def _load_seq(self) -> int:
        raw = self.db.get(b"TxStoreSeq")
        return json.loads(raw)["seq"] if raw is not None else 0

    def height(self) -> int:
        with self._mtx:
            return self._height

    # -- save (reference :83-107) --

    def save_tx(
        self,
        vote_set: TxVoteSet,
        commit: Commit | None = None,
        votes: list[TxVote] | None = None,
        tx: bytes | None = None,
    ) -> None:
        """votes: the caller's already-materialized vote_set.get_votes()
        copy, so the commit path doesn't re-copy the set (r3 profile).
        tx: the raw tx bytes when the caller has them — stored under T:
        so a catch-up server can hand a wiped peer the bytes needed to
        re-derive app state (sync/)."""
        if vote_set is None:
            raise ValueError("TxStore can only save a non-nil TxVoteSet")
        with self._mtx:
            rows, sync = self._rows_for(vote_set, commit, votes, tx)
            self.db.set_many(rows, sync=sync)  # txlint: allow(lock-blocking) -- _mtx IS the store's durability point: certificate rows must hit the db in commit order

    def save_txs_batch(
        self, items: list[tuple]
    ) -> None:
        """Certificate rows for a whole committer wake in ONE db write
        group: one store lock, one backend lock / appended buffer / fsync
        (r4 profile: ~6 locked db ops per commit serialized the committer
        thread). Row content and ordering are identical to per-item
        save_tx calls. Items are (vote_set, votes) or
        (vote_set, votes, tx_bytes) tuples."""
        if not items:
            return
        with self._mtx:
            rows: list[tuple[bytes, bytes]] = []
            sync = False
            for item in items:
                vote_set, votes = item[0], item[1]
                tx = item[2] if len(item) > 2 else None
                if vote_set is None:
                    raise ValueError("TxStore can only save a non-nil TxVoteSet")
                r, s = self._rows_for(vote_set, None, votes, tx)
                rows.extend(r)
                sync = sync or s
            self.db.set_many(rows, sync=sync)  # txlint: allow(lock-blocking) -- _mtx IS the store's durability point: certificate rows must hit the db in commit order

    def save_tx_bytes(self, tx_hash: str, tx: bytes) -> None:
        """Late tx-bytes row for a certificate saved before the bytes
        arrived (deferred-apply resolution)."""
        self.db.set(b"T:" + tx_hash.encode(), tx)

    def _rows_for(
        self,
        vote_set: TxVoteSet,
        commit: Commit | None,
        votes: list[TxVote] | None,
        tx: bytes | None = None,
    ) -> tuple[list[tuple[bytes, bytes]], bool]:
        """Rows for one certificate (call under self._mtx). Returns
        (rows, needs_fsync) — fsync when the height watermark advanced
        (the durability point, reference tx/store.go SaveTx)."""
        tx_hash = vote_set.tx_hash
        if votes is None:
            votes = vote_set.get_votes()
        hash_b = tx_hash.encode()
        rows: list[tuple[bytes, bytes]] = [(b"H:" + hash_b, _encode_votes(votes))]
        if tx is not None:
            rows.append((b"T:" + hash_b, tx))
        if commit is None and vote_set.has_two_thirds_majority():
            # the commit certificate is exactly the set's votes (a
            # TxVoteSet only ever holds votes for its own tx), so the
            # row would be byte-identical to H: — load_tx_commit falls
            # back to the H: row instead of storing the blob twice
            pass
        elif commit is not None:
            rows.append(
                (
                    b"C:" + hash_b,
                    _encode_votes([cs.to_vote() for cs in commit.commits]),
                )
            )
        # commit-order log: S:<seq> -> tx_hash, so crash recovery can
        # replay fast-path commits in the exact order they happened
        # (the reference stores no order; its recovery story for the
        # fast path is correspondingly incomplete — SURVEY §0)
        if not self.db.has(b"O:" + hash_b):
            rows.append((b"S:%016d" % self._seq, hash_b))
            rows.append((b"O:" + hash_b, b"%d" % self._seq))
            self._seq += 1
            rows.append((b"TxStoreSeq", b'{"seq": %d}' % self._seq))
        sync = False
        h = vote_set.height()
        if h > self._height:
            self._height = h
            rows.append((_HEIGHT_KEY, b'{"height": %d}' % h))
            sync = True
        return rows, sync

    # -- load (reference :54-80) --

    def load_tx_votes(self, tx_hash: str) -> list[TxVote] | None:
        """The saved votes for a tx hash, or None if unknown."""
        raw = self.db.get(_tx_key(tx_hash))
        if raw is None:
            return None
        return _decode_votes(raw)

    def load_tx(self, tx_hash: str, chain_id: str, val_set: ValidatorSet) -> TxVoteSet | None:
        """Rebuild the TxVoteSet (the reference deserializes it directly)."""
        votes = self.load_tx_votes(tx_hash)
        if votes is None:
            return None
        vs = TxVoteSet(chain_id, votes[0].height if votes else 0, tx_hash, votes[0].tx_key if votes else b"", val_set)
        for v in votes:
            vs.add_verified_vote(v)
        return vs

    def load_tx_commit(self, tx_hash: str) -> Commit | None:
        raw = self.db.get(_commit_key(tx_hash))
        if raw is None:
            # quorum certificates are stored once under H: (identical vote
            # list — see save_tx); a distinct C: row exists only for
            # explicitly supplied commits
            raw = self.db.get(_tx_key(tx_hash))
        if raw is None:
            return None
        votes = _decode_votes(raw)
        return Commit(tx_hash, [CommitSig.from_vote(v) for v in votes])

    def mark_block_committed(self, tx_hash: str) -> None:
        """Durable marker for a tx committed VIA A BLOCK (no fast-path
        certificate exists): keeps has_tx/is_committed stable across LRU
        churn and restarts. Not part of the fast-path commit-order log —
        block replay covers these txs."""
        self.db.set(b"B:" + tx_hash.encode(), b"1")

    def has_tx(self, tx_hash: str) -> bool:
        return self.db.has(_tx_key(tx_hash)) or self.db.has(
            b"B:" + tx_hash.encode()
        )

    def committed_hashes_in_order(self) -> list[str]:
        """Tx hashes in fast-path commit order (crash-recovery replay)."""
        out = []
        for _, v in self.db.iterate(b"S:", b"S;"):
            out.append(v.decode())
        return out

    # -- catch-up sync reads (sync/reactor.py serves from these) --

    def seq_count(self) -> int:
        """Number of fast-path commits in the order log — the node's
        advertised sync height."""
        with self._mtx:
            return self._seq

    def committed_range(self, start: int, count: int) -> list[tuple[int, str]]:
        """(seq, tx_hash) pairs from the commit-order log, seq in
        [start, start+count). Missing seqs (none in normal operation)
        are simply absent from the result."""
        if count <= 0 or start < 0:
            return []
        out: list[tuple[int, str]] = []
        lo = b"S:%016d" % start
        hi = b"S:%016d" % (start + count)
        for k, v in self.db.iterate(lo, hi):
            out.append((int(k[2:]), v.decode()))
        return out

    def load_cert_row(self, tx_hash: str) -> bytes | None:
        """The raw H: certificate row, byte-identical to what this node
        committed — sync serves this blob verbatim so a recovering peer
        re-derives the exact same rows (_encode_votes is deterministic)."""
        return self.db.get(_tx_key(tx_hash))

    def load_tx_bytes(self, tx_hash: str) -> bytes | None:
        return self.db.get(b"T:" + tx_hash.encode())
