"""Key-value database backends (reference tm-cmn/db: goleveldb / memdb).

MemDB mirrors dbm.NewMemDB (every store test fixture); FileDB is the
durable default — an append-only data log with an in-memory index,
compacted on open. Both are thread-safe and iterate in sorted key order
like the reference's backends.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Iterator

from ..utils.failpoints import fail


class DB:
    def get(self, key: bytes) -> bytes | None:
        raise NotImplementedError

    def set(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def set_sync(self, key: bytes, value: bytes) -> None:
        self.set(key, value)

    def set_many(self, pairs: list[tuple[bytes, bytes]], sync: bool = False) -> None:
        """Write a group of rows in one backend transaction-ish unit: one
        lock hold, and for the durable backend one appended buffer with at
        most one fsync — the committer batches a whole wake's certificate
        rows through this (one append+fsync per wake instead of ~6 locked
        writes per commit, r4 profile)."""
        for k, v in pairs:
            self.set(k, v)
        if sync and pairs:
            self.set_sync(pairs[-1][0], pairs[-1][1])

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def iterate(self, start: bytes = b"", end: bytes | None = None) -> Iterator[tuple[bytes, bytes]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemDB(DB):
    def __init__(self):
        self._mtx = threading.Lock()
        self._data: dict[bytes, bytes] = {}

    def get(self, key: bytes) -> bytes | None:
        with self._mtx:
            return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._mtx:
            self._data[key] = value

    def set_many(self, pairs: list[tuple[bytes, bytes]], sync: bool = False) -> None:
        with self._mtx:
            self._data.update(pairs)

    def delete(self, key: bytes) -> None:
        with self._mtx:
            self._data.pop(key, None)

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        with self._mtx:
            keys = sorted(k for k in self._data if k >= start and (end is None or k < end))
            items = [(k, self._data[k]) for k in keys]
        yield from items


_REC = struct.Struct("<IIi")  # crc, key len, value len (-1 = tombstone)


class FileDB(DB):
    """Log-structured KV file: records ``crc | klen | vlen | key | value``.

    Crash behavior matches the WAL: a torn tail is truncated on open. All
    reads are served from the in-memory index, writes append (set_sync
    fsyncs — the durability point the stores rely on).
    """

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._mtx = threading.Lock()
        self._data: dict[bytes, bytes] = {}
        self._load()
        self._f = open(path, "ab")

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        good_end = 0
        with open(self.path, "rb") as f:
            while True:
                hdr = f.read(_REC.size)
                if len(hdr) < _REC.size:
                    break
                crc, klen, vlen = _REC.unpack(hdr)
                body = f.read(klen + max(vlen, 0))
                if len(body) < klen + max(vlen, 0) or zlib.crc32(body) != crc:
                    break
                key = body[:klen]
                if vlen < 0:
                    self._data.pop(key, None)
                else:
                    self._data[key] = body[klen:]
                good_end = f.tell()
        if good_end < os.path.getsize(self.path):
            with open(self.path, "r+b") as f:
                f.truncate(good_end)

    def _append(self, key: bytes, value: bytes | None, sync: bool) -> None:
        fail("filedb.append")  # ENOSPC/EIO drills (tests/test_diskfull.py)
        body = key + (value or b"")
        rec = _REC.pack(zlib.crc32(body), len(key), -1 if value is None else len(value)) + body
        self._f.write(rec)
        self._f.flush()
        if sync:
            os.fsync(self._f.fileno())

    def get(self, key: bytes) -> bytes | None:
        with self._mtx:
            return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._mtx:
            self._data[key] = value
            self._append(key, value, sync=False)

    def set_sync(self, key: bytes, value: bytes) -> None:
        with self._mtx:
            self._data[key] = value
            self._append(key, value, sync=True)

    def set_many(self, pairs: list[tuple[bytes, bytes]], sync: bool = False) -> None:
        """One lock hold, one buffered append (single OS write), at most
        one fsync for the whole group."""
        if not pairs:
            return
        with self._mtx:
            fail("filedb.append")  # ENOSPC/EIO drills (tests/test_diskfull.py)
            buf = bytearray()
            for key, value in pairs:
                self._data[key] = value
                body = key + value
                buf += _REC.pack(zlib.crc32(body), len(key), len(value))
                buf += body
            self._f.write(buf)
            self._f.flush()
            if sync:
                os.fsync(self._f.fileno())

    def delete(self, key: bytes) -> None:
        with self._mtx:
            if key in self._data:
                del self._data[key]
                self._append(key, None, sync=False)

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        with self._mtx:
            keys = sorted(k for k in self._data if k >= start and (end is None or k < end))
            items = [(k, self._data[k]) for k in keys]
        yield from items

    def close(self) -> None:
        with self._mtx:
            if not self._f.closed:
                self._f.flush()
                self._f.close()
