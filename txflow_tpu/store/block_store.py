"""BlockStore: block persistence (reference store/store.go:29-214).

Rows per height: the block itself (the reference splits meta + parts; our
transport carries whole blocks, so one row), the block commit (precommits
that committed it) and the seen-commit (this node's own +2/3 view, which
may be for a later round); plus a height watermark.
"""

from __future__ import annotations

import json
import threading

from ..types.block import Block, decode_block, encode_block
from ..types.block_vote import BlockCommit, decode_block_commit, encode_block_commit
from .db import DB

_HEIGHT_KEY = b"blockStore"


class BlockStore:
    def __init__(self, db: DB):
        self.db = db
        self._mtx = threading.Lock()
        raw = db.get(_HEIGHT_KEY)
        self._height = json.loads(raw)["height"] if raw is not None else 0

    def height(self) -> int:
        with self._mtx:
            return self._height

    def base(self) -> int:
        return 1 if self.height() > 0 else 0

    # -- save (reference SaveBlock :146-188) --

    def save_block(self, block: Block, seen_commit: BlockCommit) -> None:
        height = block.height
        with self._mtx:
            if height != self._height + 1:
                raise ValueError(
                    f"BlockStore can only save contiguous blocks: wanted "
                    f"{self._height + 1}, got {height}"
                )
            self.db.set(b"B:%d" % height, encode_block(block))
            if block.last_commit is not None:
                self.db.set(
                    b"C:%d" % (height - 1), encode_block_commit(block.last_commit)
                )
            self.db.set(b"SC:%d" % height, encode_block_commit(seen_commit))
            self._height = height
            self.db.set_sync(_HEIGHT_KEY, json.dumps({"height": height}).encode())

    # -- load (reference LoadBlock/LoadBlockCommit/LoadSeenCommit) --

    def load_block(self, height: int) -> Block | None:
        raw = self.db.get(b"B:%d" % height)
        return decode_block(raw) if raw is not None else None

    def load_block_commit(self, height: int) -> BlockCommit | None:
        """The commit for block `height`, carried in block height+1."""
        raw = self.db.get(b"C:%d" % height)
        return decode_block_commit(raw) if raw is not None else None

    def load_seen_commit(self, height: int) -> BlockCommit | None:
        raw = self.db.get(b"SC:%d" % height)
        return decode_block_commit(raw) if raw is not None else None

    def save_seen_commit(self, height: int, commit: BlockCommit) -> None:
        """Re-save an extended seen-commit (late precommits folded in for
        commit-gossip liveness, reference consensus/state.go:583-601)."""
        self.db.set(b"SC:%d" % height, encode_block_commit(commit))
