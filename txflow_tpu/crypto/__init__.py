from .hash import address_hash, sha256, tx_hash, tx_key
from . import ed25519

__all__ = ["address_hash", "sha256", "tx_hash", "tx_key", "ed25519"]
