"""Host-side Ed25519 with Go ``crypto/ed25519``-equivalent verify semantics.

This is the scalar golden model for the batched device verifier
(txflow_tpu.ops.ed25519_batch): same accept/reject decisions bit-for-bit.
The reference verifies one vote at a time with Go's ed25519
(types/tx_vote.go:110-119); its exact semantics are:

- signature must be 64 bytes, S = sig[32:] (little-endian) must satisfy S < L
  ("ScMinimal");
- A (pubkey) must decompress onto the curve;
- h = SHA512(R_bytes || A_bytes || msg) reduced mod L;
- compute P = [S]B - [h]A (cofactorless) and accept iff encode(P) equals
  sig[:32] byte-for-byte (Go compares encodings, never decompressing R, so
  non-canonical R encodings are rejected automatically).

Implemented from the RFC 8032 specification with Python integers. When the
``cryptography`` package is importable its OpenSSL backend (same semantics)
is used for the fast host paths ``sign``/``verify``; the pure-Python
``verify_pure`` stays as the audited golden model, and both are cross-tested.
"""

from __future__ import annotations

import hashlib

# Curve constants (RFC 8032 section 5.1).
P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1) mod p

# Base point B.
_BY = (4 * pow(5, P - 2, P)) % P
_BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
BASE_AFFINE = (_BX, _BY)

# Extended homogeneous coordinates (X, Y, Z, T) with x = X/Z, y = Y/Z, T = XY/Z.
IDENTITY = (0, 1, 1, 0)
BASE = (_BX, _BY, 1, (_BX * _BY) % P)


def point_add(Pt, Qt):
    """Unified addition, extended coordinates (RFC 8032 section 5.1.4)."""
    X1, Y1, Z1, T1 = Pt
    X2, Y2, Z2, T2 = Qt
    A = ((Y1 - X1) * (Y2 - X2)) % P
    B = ((Y1 + X1) * (Y2 + X2)) % P
    C = (2 * T1 * T2 * D) % P
    Dv = (2 * Z1 * Z2) % P
    E = B - A
    F = Dv - C
    G = Dv + C
    H = B + A
    return ((E * F) % P, (G * H) % P, (F * G) % P, (E * H) % P)


def point_double(Pt):
    """Dedicated doubling (independent of d) — also what the device kernel uses."""
    X1, Y1, Z1, _ = Pt
    A = (X1 * X1) % P
    B = (Y1 * Y1) % P
    C = (2 * Z1 * Z1) % P
    H = (A + B) % P
    E = (H - (X1 + Y1) * (X1 + Y1)) % P
    G = (A - B) % P
    F = (C + G) % P
    return ((E * F) % P, (G * H) % P, (F * G) % P, (E * H) % P)


def point_neg(Pt):
    X, Y, Z, T = Pt
    return ((-X) % P, Y, Z, (-T) % P)


def point_equal(Pt, Qt) -> bool:
    X1, Y1, Z1, _ = Pt
    X2, Y2, Z2, _ = Qt
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def scalar_mult(k: int, Pt):
    Q = IDENTITY
    while k > 0:
        if k & 1:
            Q = point_add(Q, Pt)
        Pt = point_double(Pt)
        k >>= 1
    return Q


def point_compress(Pt) -> bytes:
    X, Y, Z, _ = Pt
    zinv = pow(Z, P - 2, P)
    x = (X * zinv) % P
    y = (Y * zinv) % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def point_decompress(s: bytes):
    """Decompress 32 bytes to an extended point, or None if off-curve.

    Mirrors RFC 8032 decoding: y is the low 255 bits, sign bit selects x.
    (Like Go's FeFromBytes, y is not checked for canonicality; values >= p
    wrap implicitly, which only affects adversarial non-canonical pubkeys.)
    """
    if len(s) != 32:
        return None
    n = int.from_bytes(s, "little")
    sign = n >> 255
    y = n & ((1 << 255) - 1)
    y2 = (y * y) % P
    u = (y2 - 1) % P
    v = (D * y2 + 1) % P
    # x = sqrt(u/v): candidate x = u * v^3 * (u * v^7)^((p-5)/8)
    v3 = (v * v * v) % P
    v7 = (v3 * v3 * v) % P
    x = (u * v3 * pow(u * v7, (P - 5) // 8, P)) % P
    vx2 = (v * x * x) % P
    if vx2 == u % P:
        pass
    elif vx2 == (-u) % P:
        x = (x * SQRT_M1) % P
    else:
        return None
    # NOTE: like Go's ref10-based ed25519 (and OpenSSL), x=0 with sign bit 1
    # is accepted by negating to zero — RFC 8032's stricter rejection would
    # diverge from the reference's accept set on adversarial encodings.
    if x & 1 != sign:
        x = (P - x) % P
    return (x, y, 1, (x * y) % P)


def sha512_mod_l(data: bytes) -> int:
    return int.from_bytes(hashlib.sha512(data).digest(), "little") % L


def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def public_key_from_seed(seed: bytes) -> bytes:
    if len(seed) != 32:
        raise ValueError("ed25519 seed must be 32 bytes")
    h = hashlib.sha512(seed).digest()
    a = _clamp(h)
    return point_compress(scalar_mult(a, BASE))


def sign_pure(seed: bytes, msg: bytes) -> bytes:
    """RFC 8032 deterministic signature (pure Python)."""
    h = hashlib.sha512(seed).digest()
    a = _clamp(h)
    prefix = h[32:]
    A = point_compress(scalar_mult(a, BASE))
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    Rb = point_compress(scalar_mult(r, BASE))
    k = sha512_mod_l(Rb + A + msg)
    s = (r + k * a) % L
    return Rb + s.to_bytes(32, "little")


def verify_pure(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Go crypto/ed25519-equivalent verification (the golden model)."""
    if len(pub) != 32 or len(sig) != 64:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:  # ScMinimal
        return False
    A = point_decompress(pub)
    if A is None:
        return False
    h = sha512_mod_l(sig[:32] + pub + msg)
    # P = [s]B - [h]A, accept iff encode(P) == sig[:32].
    Pt = point_add(scalar_mult(s, BASE), scalar_mult(h, point_neg(A)))
    return point_compress(Pt) == sig[:32]


# ----------------------------------------------------------------------------
# Fast host paths via the `cryptography` package (OpenSSL), same semantics.

try:  # pragma: no cover - import guard
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.exceptions import InvalidSignature

    HAVE_CRYPTOGRAPHY = True
except Exception:  # pragma: no cover
    HAVE_CRYPTOGRAPHY = False


def sign(seed: bytes, msg: bytes) -> bytes:
    if HAVE_CRYPTOGRAPHY:
        return Ed25519PrivateKey.from_private_bytes(seed).sign(msg)
    return sign_pure(seed, msg)


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    if HAVE_CRYPTOGRAPHY:
        if len(pub) != 32 or len(sig) != 64:
            return False
        try:
            Ed25519PublicKey.from_public_bytes(pub).verify(sig, msg)
            return True
        except (InvalidSignature, ValueError):
            return False
    return verify_pure(pub, msg, sig)


def generate_seed() -> bytes:
    import os

    return os.urandom(32)
