"""Hashing and address derivation (tendermint crypto semantics).

- ``tx_key(tx)``: sha256(tx), the 32-byte map key (reference types/tx_vote.go:38-40).
- ``tx_hash(tx)``: uppercase-hex sha256(tx) — ``fmt.Sprintf("%X", tx.Hash())``
  (reference types/tx_vote.go:43-45; tendermint Tx.Hash is full sha256 in v0.31).
- ``address_hash(pubkey)``: first 20 bytes of sha256 (tendermint v0.31
  ed25519 PubKey.Address / crypto.AddressHash = tmhash.SumTruncated).
"""

from __future__ import annotations

import hashlib

ADDRESS_SIZE = 20


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


def tx_key(tx: bytes) -> bytes:
    return sha256(tx)


def tx_hash(tx: bytes) -> str:
    return sha256(tx).hex().upper()


def address_hash(data: bytes) -> bytes:
    return sha256(data)[:ADDRESS_SIZE]
