"""X25519 (RFC 7748) Diffie-Hellman over curve25519.

Host-side python-int Montgomery ladder — key agreement happens once per
connection, so this is never a hot path (the hot curve math lives in
ops/, on device, for ed25519 verification). Implemented from the RFC's
pseudocode over the same 2^255-19 field as crypto/ed25519.
"""

from __future__ import annotations

import os

P = 2**255 - 19
A24 = 121665  # (486662 - 2) / 4


def _clamp(k: bytes) -> int:
    b = bytearray(k)
    b[0] &= 248
    b[31] &= 127
    b[31] |= 64
    return int.from_bytes(bytes(b), "little")


def _decode_u(u: bytes) -> int:
    b = bytearray(u)
    b[31] &= 127  # RFC 7748: mask the MSB of the final byte
    return int.from_bytes(bytes(b), "little") % P


def _encode_u(x: int) -> bytes:
    return (x % P).to_bytes(32, "little")


def scalar_mult(k: bytes, u: bytes) -> bytes:
    """X25519(k, u): constant-structure Montgomery ladder (RFC 7748 §5)."""
    x1 = _decode_u(u)
    k_int = _clamp(k)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in reversed(range(255)):
        k_t = (k_int >> t) & 1
        swap ^= k_t
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % P
        aa = (a * a) % P
        b = (x2 - z2) % P
        bb = (b * b) % P
        e = (aa - bb) % P
        c = (x3 + z3) % P
        d = (x3 - z3) % P
        da = (d * a) % P
        cb = (c * b) % P
        x3 = (da + cb) % P
        x3 = (x3 * x3) % P
        z3 = (da - cb) % P
        z3 = (x1 * z3 * z3) % P
        x2 = (aa * bb) % P
        z2 = (e * ((aa + A24 * e) % P)) % P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return _encode_u((x2 * pow(z2, P - 2, P)) % P)


BASE_POINT = (9).to_bytes(32, "little")


def generate_private() -> bytes:
    return os.urandom(32)


def public_key(priv: bytes) -> bytes:
    return scalar_mult(priv, BASE_POINT)


def shared_secret(priv: bytes, peer_pub: bytes) -> bytes:
    s = scalar_mult(priv, peer_pub)
    if s == bytes(32):  # all-zero output: low-order point (RFC 7748 §6.1)
        raise ValueError("x25519: low-order peer public key")
    return s
