"""Evidence: provable validator misbehavior (conflicting signed votes).

``DuplicateBlockVoteEvidence`` — two validly-signed block votes from one
validator at the same height/round/type for DIFFERENT block ids: classic
tendermint equivocation (the slot the reference fills with the upstream
evidence pool, node/node.go:354-367).

Deliberately NOT evidence: fast-path TxVote "conflicts". A TxVote's sign
bytes include its signing-time timestamp (reference types/tx_vote.go:66),
so two different signatures from one validator for the same tx are just
an honest re-sign (e.g. after a restart) — there is no conflicting CHOICE
in a yes-only vote. The reference's conflicting-vote TODO
(types/vote_set.go:123-125) is dedup bookkeeping, not slashable behavior;
branding re-signs as equivocation would punish honest nodes (r3 review).
Such votes are dropped first-signature-wins, exactly like the reference.

Evidence verifies self-contained: both signatures check out against the
named validator's pubkey and the contents genuinely conflict.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..codec import amino
from ..crypto.hash import sha256
from .block_vote import BlockVote, decode_block_vote, encode_block_vote

EV_BLOCK_VOTE = 2


@dataclass
class DuplicateBlockVoteEvidence:
    vote_a: BlockVote
    vote_b: BlockVote

    @property
    def validator_address(self) -> bytes:
        return self.vote_a.validator_address

    def height(self) -> int:
        return self.vote_a.height

    def hash(self) -> bytes:
        return sha256(b"ev-blockvote" + self._canonical_pair())

    def _canonical_pair(self) -> bytes:
        a, b = encode_block_vote(self.vote_a), encode_block_vote(self.vote_b)
        return a + b if a <= b else b + a  # order-independent identity

    def verify(self, chain_id: str, pub_key: bytes) -> str | None:
        a, b = self.vote_a, self.vote_b
        if a.validator_address != b.validator_address:
            return "votes from different validators"
        if (a.height, a.round, a.type) != (b.height, b.round, b.type):
            return "votes at different height/round/type"
        if a.block_id == b.block_id:
            return "votes for the same block are not conflicting"
        for v in (a, b):
            if not v.verify(chain_id, pub_key):
                return "invalid signature in evidence"
        return None


def encode_evidence(ev) -> bytes:
    if isinstance(ev, DuplicateBlockVoteEvidence):
        a, b = encode_block_vote(ev.vote_a), encode_block_vote(ev.vote_b)
        return (
            bytes([EV_BLOCK_VOTE])
            + amino.length_prefixed(a)
            + amino.length_prefixed(b)
        )
    raise TypeError(f"unknown evidence type {type(ev)}")


def decode_evidence(data: bytes):
    if not data:
        raise ValueError("empty evidence")  # peer-facing: never IndexError
    kind, rest = data[0], data[1:]
    ln, off = amino.read_uvarint(rest, 0)
    if off + ln > len(rest):
        raise ValueError("truncated evidence vote a")
    a_raw = rest[off : off + ln]
    off += ln
    ln2, off = amino.read_uvarint(rest, off)
    if off + ln2 > len(rest):
        raise ValueError("truncated evidence vote b")
    b_raw = rest[off : off + ln2]
    if kind == EV_BLOCK_VOTE:
        return DuplicateBlockVoteEvidence(
            decode_block_vote(a_raw), decode_block_vote(b_raw)
        )
    raise ValueError(f"unknown evidence kind {kind}")
