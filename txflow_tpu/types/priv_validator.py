"""PrivValidator interface + MockPV (reference types/priv_validator.go).

``MockPV`` keeps the reference's per-message-type breakage switches
(types/priv_validator.go:44-60) used by byzantine tests: a "broken" signer
signs with the wrong chain id, producing signatures that honest verifiers
reject.
"""

from __future__ import annotations

from typing import Protocol

from ..crypto import ed25519
from ..crypto.hash import address_hash
from .tx_vote import TxVote


class PrivValidator(Protocol):
    def get_pub_key(self) -> bytes: ...

    def get_address(self) -> bytes: ...

    def sign_tx_vote(self, chain_id: str, vote: TxVote) -> None: ...

    def sign_block_vote(self, chain_id: str, vote) -> None: ...

    def sign_proposal(self, chain_id: str, proposal) -> None: ...


class MockPV:
    """In-memory signer without safety or persistence — tests only."""

    def __init__(
        self,
        seed: bytes | None = None,
        break_proposal_signing: bool = False,
        break_vote_signing: bool = False,
        break_tx_vote_signing: bool = False,
    ):
        self._seed = seed if seed is not None else ed25519.generate_seed()
        self._pub_key = ed25519.public_key_from_seed(self._seed)
        self.break_proposal_signing = break_proposal_signing
        self.break_vote_signing = break_vote_signing
        self.break_tx_vote_signing = break_tx_vote_signing

    def get_pub_key(self) -> bytes:
        return self._pub_key

    def get_address(self) -> bytes:
        return address_hash(self._pub_key)

    def sign_tx_vote(self, chain_id: str, vote: TxVote) -> None:
        use_chain_id = (
            "incorrect-chain-id" if self.break_tx_vote_signing else chain_id
        )
        vote.signature = ed25519.sign(self._seed, vote.sign_bytes(use_chain_id))

    def sign_block_vote(self, chain_id: str, vote) -> None:
        """Sign a block-path prevote/precommit (reference SignVote)."""
        use_chain_id = "incorrect-chain-id" if self.break_vote_signing else chain_id
        vote.signature = ed25519.sign(self._seed, vote.sign_bytes(use_chain_id))

    def sign_proposal(self, chain_id: str, proposal) -> None:
        """Sign a block proposal (reference SignProposal)."""
        use_chain_id = (
            "incorrect-chain-id" if self.break_proposal_signing else chain_id
        )
        proposal.signature = ed25519.sign(
            self._seed, proposal.sign_bytes(use_chain_id)
        )

    def sign_bytes_raw(self, data: bytes) -> bytes:
        return ed25519.sign(self._seed, data)

    def disable_checks(self) -> None:
        # MockPV has no safety checks, like the reference (:119-122).
        pass

    def __repr__(self) -> str:
        return f"MockPV{{{self.get_address().hex().upper()}}}"


class ErroringMockPVError(Exception):
    pass


class ErroringMockPV(MockPV):
    """Fails every signing request (reference :124-148) — tests only."""

    def sign_tx_vote(self, chain_id: str, vote: TxVote) -> None:
        raise ErroringMockPVError("erroringMockPV always returns an error")

    def sign_block_vote(self, chain_id: str, vote) -> None:
        raise ErroringMockPVError("erroringMockPV always returns an error")

    def sign_proposal(self, chain_id: str, proposal) -> None:
        raise ErroringMockPVError("erroringMockPV always returns an error")
