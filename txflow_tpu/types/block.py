"""Block types: Header + Data{Txs, Vtxs} + block-level Commit.

Reference: types/block.go (forked tendermint Block whose ``Data`` carries
``Vtxs`` — txs already committed via the fast path, riding in blocks for
replayable time-ordering only; they are NOT re-applied, types/block.go:
290-302, state/execution.go:293).

Defect fixed (SURVEY §0): the reference's ``Data.Hash()`` merkle-commits
only ``Txs`` (types/block.go:305-313), leaving Vtxs outside the block
hash. Here the data hash covers both lists (domain-separated), so the
fast-path ordering is integrity-protected by the chain.

Encoding: deterministic field encoding built on the amino primitives
(codec.amino). This is framework-native wire/storage format — the block
path does not need byte-compatibility with tendermint (the TxVote sign
bytes, which DO need it, live in tx_vote.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..codec import amino
from ..crypto.hash import sha256
from .block_vote import BlockCommit, decode_block_commit, encode_block_commit

MAX_CHAIN_ID_LEN = 50


def merkle_root(leaves: list[bytes]) -> bytes:
    """Tendermint's simple merkle tree over sha256(leaf) hashes.

    RFC-6962 style split (largest power of two < n), empty tree = empty
    hash — matches upstream merkle.SimpleHashFromByteSlices semantics used
    by ``Txs.Hash()``."""
    if not leaves:
        return sha256(b"")
    hashes = [sha256(leaf) for leaf in leaves]
    return _merkle_from_hashes(hashes)


def _merkle_from_hashes(hashes: list[bytes]) -> bytes:
    n = len(hashes)
    if n == 1:
        return hashes[0]
    split = 1
    while split * 2 < n:
        split *= 2
    left = _merkle_from_hashes(hashes[:split])
    right = _merkle_from_hashes(hashes[split:])
    return sha256(left + right)


@dataclass
class Data:
    """Block payload: Txs to apply at height+1, Vtxs already fast-committed."""

    txs: list[bytes] = field(default_factory=list)
    vtxs: list[bytes] = field(default_factory=list)

    def hash(self) -> bytes:
        # Defect fix: cover BOTH lists (reference hashes Txs only).
        # Domain separation so ([a], []) != ([], [a]).
        return sha256(
            b"\x00" + merkle_root(self.txs) + b"\x01" + merkle_root(self.vtxs)
        )


@dataclass
class Header:
    chain_id: str = ""
    height: int = 0
    time_ns: int = 0
    num_txs: int = 0
    total_txs: int = 0
    last_block_id: bytes = b""  # previous block hash ("" at height 1)
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    next_validators_hash: bytes = b""
    app_hash: bytes = b""
    last_results_hash: bytes = b""
    proposer_address: bytes = b""
    evidence_hash: bytes = b""  # empty when the block carries no evidence

    def hash(self) -> bytes:
        """Block hash = sha256 of the deterministic header encoding."""
        return sha256(encode_header(self))


@dataclass
class Block:
    header: Header = field(default_factory=Header)
    data: Data = field(default_factory=Data)
    last_commit: BlockCommit | None = None
    # committed equivocation proofs (reference block.Evidence; reaped from
    # the evidence pool into proposals, state/execution.go:103)
    evidence: list = field(default_factory=list)

    @property
    def height(self) -> int:
        return self.header.height

    @property
    def txs(self) -> list[bytes]:
        return self.data.txs

    @property
    def vtxs(self) -> list[bytes]:
        return self.data.vtxs

    def hash(self) -> bytes:
        return self.header.hash()

    def fill_header(self) -> None:
        """Populate derived header fields (reference fillHeader)."""
        if not self.header.data_hash:
            self.header.data_hash = self.data.hash()
        if not self.header.last_commit_hash and self.last_commit is not None:
            self.header.last_commit_hash = self.last_commit.hash()
        if not self.header.evidence_hash and self.evidence:
            self.header.evidence_hash = evidence_root(self.evidence)

    def validate_basic(self) -> str | None:
        """Internal consistency only (reference Block.ValidateBasic)."""
        if len(self.header.chain_id) > MAX_CHAIN_ID_LEN:
            return f"ChainID is too long (max {MAX_CHAIN_ID_LEN})"
        if self.header.height < 0:
            return "negative Height"
        if self.header.num_txs != len(self.data.txs):
            return (
                f"wrong Header.NumTxs: {self.header.num_txs} != {len(self.data.txs)}"
            )
        if self.header.data_hash != self.data.hash():
            return "wrong Header.DataHash"
        if self.header.height > 1:
            if self.last_commit is None:
                return "nil LastCommit at height > 1"
            if self.header.last_commit_hash != self.last_commit.hash():
                return "wrong Header.LastCommitHash"
        return None


def make_block(
    height: int,
    txs: list[bytes],
    vtxs: list[bytes],
    last_commit: BlockCommit | None,
) -> Block:
    """Reference MakeBlock (types/block.go:28-43): header fields that can
    be computed from the block itself; the rest set by state.make_block."""
    b = Block(
        header=Header(height=height, num_txs=len(txs)),
        data=Data(txs=txs, vtxs=vtxs),
        last_commit=last_commit,
    )
    b.fill_header()
    return b


# ---------------------------------------------------------------------------
# encoding


def encode_header(h: Header) -> bytes:
    body = bytearray()

    def bfield(num: int, data: bytes | str) -> None:
        raw = data.encode() if isinstance(data, str) else data
        if raw:
            body.extend(amino.field_key(num, amino.TYP3_BYTELEN))
            body.extend(amino.length_prefixed(raw))

    def vfield(num: int, n: int) -> None:
        if n:
            body.extend(amino.field_key(num, amino.TYP3_VARINT))
            body.extend(amino.varint(n))

    bfield(1, h.chain_id)
    vfield(2, h.height)
    vfield(3, h.time_ns)
    vfield(4, h.num_txs)
    vfield(5, h.total_txs)
    bfield(6, h.last_block_id)
    bfield(7, h.last_commit_hash)
    bfield(8, h.data_hash)
    bfield(9, h.validators_hash)
    bfield(10, h.next_validators_hash)
    bfield(11, h.app_hash)
    bfield(12, h.last_results_hash)
    bfield(13, h.proposer_address)
    bfield(14, h.evidence_hash)  # elided when empty: evidence-free blocks
    # hash identically to pre-evidence encodings
    return bytes(body)


_HEADER_VARINT_FIELDS = {2: "height", 3: "time_ns", 4: "num_txs", 5: "total_txs"}
_HEADER_BYTES_FIELDS = {
    6: "last_block_id",
    7: "last_commit_hash",
    8: "data_hash",
    9: "validators_hash",
    14: "evidence_hash",
    10: "next_validators_hash",
    11: "app_hash",
    12: "last_results_hash",
    13: "proposer_address",
}


def decode_header(data: bytes) -> Header:
    r = amino.AminoReader(data)
    h = Header()
    while not r.eof():
        fnum, typ3 = r.read_field_key()
        if typ3 == amino.TYP3_VARINT and fnum in _HEADER_VARINT_FIELDS:
            setattr(h, _HEADER_VARINT_FIELDS[fnum], r.read_varint())
        elif typ3 == amino.TYP3_BYTELEN and fnum == 1:
            h.chain_id = r.read_bytes().decode()
        elif typ3 == amino.TYP3_BYTELEN and fnum in _HEADER_BYTES_FIELDS:
            setattr(h, _HEADER_BYTES_FIELDS[fnum], r.read_bytes())
        else:
            r.skip_field(typ3)
    return h


def _encode_tx_list(txs: list[bytes]) -> bytes:
    out = bytearray()
    out.extend(amino.uvarint(len(txs)))
    for tx in txs:
        out.extend(amino.length_prefixed(tx))
    return bytes(out)


def _decode_tx_list(r: amino.AminoReader) -> list[bytes]:
    n = r.read_uvarint()
    return [r.read_bytes() for _ in range(n)]


def evidence_root(evs: list) -> bytes:
    from .evidence import encode_evidence

    return merkle_root([encode_evidence(ev) for ev in evs])


def encode_block(b: Block) -> bytes:
    from .evidence import encode_evidence

    body = bytearray()
    body.extend(amino.field_key(1, amino.TYP3_BYTELEN))
    body.extend(amino.length_prefixed(encode_header(b.header)))
    body.extend(amino.field_key(2, amino.TYP3_BYTELEN))
    body.extend(amino.length_prefixed(_encode_tx_list(b.data.txs)))
    body.extend(amino.field_key(3, amino.TYP3_BYTELEN))
    body.extend(amino.length_prefixed(_encode_tx_list(b.data.vtxs)))
    if b.last_commit is not None:
        body.extend(amino.field_key(4, amino.TYP3_BYTELEN))
        body.extend(amino.length_prefixed(encode_block_commit(b.last_commit)))
    for ev in b.evidence:
        body.extend(amino.field_key(5, amino.TYP3_BYTELEN))
        body.extend(amino.length_prefixed(encode_evidence(ev)))
    return bytes(body)


def decode_block(data: bytes) -> Block:
    r = amino.AminoReader(data)
    b = Block()
    while not r.eof():
        fnum, typ3 = r.read_field_key()
        if fnum == 1 and typ3 == amino.TYP3_BYTELEN:
            b.header = decode_header(r.read_bytes())
        elif fnum == 2 and typ3 == amino.TYP3_BYTELEN:
            b.data.txs = _decode_tx_list(amino.AminoReader(r.read_bytes()))
        elif fnum == 3 and typ3 == amino.TYP3_BYTELEN:
            b.data.vtxs = _decode_tx_list(amino.AminoReader(r.read_bytes()))
        elif fnum == 4 and typ3 == amino.TYP3_BYTELEN:
            b.last_commit = decode_block_commit(r.read_bytes())
        elif fnum == 5 and typ3 == amino.TYP3_BYTELEN:
            from .evidence import decode_evidence

            b.evidence.append(decode_evidence(r.read_bytes()))
        else:
            r.skip_field(typ3)
    return b
