"""Validator and ValidatorSet (tendermint v0.31 types, the subset TxFlow uses).

The vote-set quorum math keys off ``GetByAddress`` and ``TotalVotingPower``
(reference types/vote_set.go:102, :158). The set is kept sorted by address
ascending, as upstream does, and additionally maintains dense device-side
arrays (pubkeys, powers) so a validator set can be uploaded once per epoch
and indexed by integer validator id inside the batched verifier.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..crypto.hash import address_hash


@dataclass
class Validator:
    address: bytes
    pub_key: bytes  # ed25519, 32 bytes
    voting_power: int
    proposer_priority: int = 0

    @classmethod
    def from_pub_key(cls, pub_key: bytes, voting_power: int) -> "Validator":
        return cls(address_hash(pub_key), pub_key, voting_power)

    def copy(self) -> "Validator":
        return Validator(
            self.address, self.pub_key, self.voting_power, self.proposer_priority
        )

    def compare_proposer_priority(self, other: "Validator") -> "Validator":
        """The one with higher priority wins; ties break by lower address."""
        if self.proposer_priority > other.proposer_priority:
            return self
        if self.proposer_priority < other.proposer_priority:
            return other
        return self if self.address < other.address else other


class ValidatorSet:
    def __init__(self, validators: list[Validator]):
        self.validators: list[Validator] = sorted(
            (v.copy() for v in validators), key=lambda v: v.address
        )
        self._by_address = {v.address: i for i, v in enumerate(self.validators)}
        if len(self._by_address) != len(self.validators):
            raise ValueError("duplicate validator address")
        self._total_voting_power = sum(v.voting_power for v in self.validators)
        # Dense device-friendly views, built lazily.
        self._pub_keys_np: np.ndarray | None = None
        self._powers_np: np.ndarray | None = None

    def size(self) -> int:
        return len(self.validators)

    def total_voting_power(self) -> int:
        return self._total_voting_power

    def quorum_power(self) -> int:
        """The 2/3+1 stake threshold (types/vote_set.go:158)."""
        return self._total_voting_power * 2 // 3 + 1

    def has_address(self, address: bytes) -> bool:
        return address in self._by_address

    def get_by_address(self, address: bytes) -> tuple[int, Validator | None]:
        idx = self._by_address.get(address)
        if idx is None:
            return -1, None
        return idx, self.validators[idx]

    def get_by_index(self, idx: int) -> Validator | None:
        if 0 <= idx < len(self.validators):
            return self.validators[idx]
        return None

    def index_of(self, address: bytes) -> int:
        return self._by_address.get(address, -1)

    def copy(self) -> "ValidatorSet":
        # deep copy: increment_proposer_priority mutates Validator objects
        # in ITS copy; sharing them would smear rotation state across every
        # holder of the set (state snapshots, engines, round states) and
        # desynchronize proposer selection between nodes (r3 livelock
        # postmortem: split prevotes, rounds looping forever)
        return ValidatorSet([v.copy() for v in self.validators])

    def hash(self) -> bytes:
        """Deterministic digest of (address, pub_key, power) triples, used
        in block headers (upstream ValidatorSet.Hash)."""
        from ..crypto.hash import sha256

        acc = bytearray()
        for v in self.validators:
            acc += v.address
            acc += v.pub_key
            acc += v.voting_power.to_bytes(8, "big", signed=True)
        return sha256(bytes(acc))

    def update_with_change_set(
        self, updates: list[tuple[bytes, int]]
    ) -> "ValidatorSet":
        """Apply ABCI EndBlock validator updates: (pub_key, power) pairs,
        power 0 removes (upstream UpdateWithChangeSet semantics, applied at
        state/execution.go:390-414). Returns a new set; proposer priorities
        of surviving validators are preserved."""
        from ..crypto.hash import address_hash

        by_addr = {v.address: v.copy() for v in self.validators}
        for pub_key, power in updates:
            addr = address_hash(pub_key)
            if power < 0:
                raise ValueError("negative voting power in validator update")
            if power == 0:
                if addr not in by_addr:
                    raise ValueError("removing unknown validator")
                del by_addr[addr]
            elif addr in by_addr:
                by_addr[addr].voting_power = power
            else:
                by_addr[addr] = Validator(addr, pub_key, power)
        if not by_addr:
            raise ValueError("validator update would empty the set")
        return ValidatorSet(list(by_addr.values()))

    def pub_keys_array(self) -> np.ndarray:
        """(n, 32) uint8 array of compressed pubkeys, validator-index order."""
        if self._pub_keys_np is None:
            self._pub_keys_np = np.frombuffer(
                b"".join(v.pub_key for v in self.validators), dtype=np.uint8
            ).reshape(len(self.validators), 32)
        return self._pub_keys_np

    def powers_array(self) -> np.ndarray:
        """(n,) int64 voting powers, validator-index order."""
        if self._powers_np is None:
            self._powers_np = np.array(
                [v.voting_power for v in self.validators], dtype=np.int64
            )
        return self._powers_np

    def __iter__(self):
        return iter(self.validators)

    def __len__(self) -> int:
        return len(self.validators)

    # ---- proposer rotation (consensus block path) ----

    def get_proposer(self) -> Validator:
        if not self.validators:
            raise ValueError("empty validator set")
        best = self.validators[0]
        for v in self.validators[1:]:
            best = best.compare_proposer_priority(v)
        return best

    def increment_proposer_priority(self, times: int = 1) -> "ValidatorSet":
        """Tendermint's round-robin-by-stake rotation (state/execution upstream)."""
        vs = self.copy()
        for _ in range(times):
            for v in vs.validators:
                v.proposer_priority += v.voting_power
            proposer = vs.get_proposer()
            proposer.proposer_priority -= vs._total_voting_power
        return vs
