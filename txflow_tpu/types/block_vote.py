"""Block-path votes: prevote/precommit, BlockVoteSet, HeightVoteSet, Commit.

Reference: upstream tendermint types.Vote/VoteSet as used by the forked
consensus (consensus/state.go, consensus/types/height_vote_set.go:35-115).
Semantics preserved:

- a vote is (height, round, type, block_id) signed by a validator; a nil
  vote has an empty block_id;
- VoteSet tallies stake per block_id; 2/3+1 on one block_id is a polka
  (prevotes) or a commit (precommits); 2/3 of ANY votes unlocks timeouts;
- one vote per validator per (round, type): identical re-submission is a
  silent duplicate, a different block_id is rejected as conflicting (the
  reference detects-then-drops the evidence, types/vote_set.go:123-125);
- Commit = the precommits that committed a block; carried in the next
  block and hashed into its header.

Sign bytes use the framework's deterministic amino-primitive encoding
(chain-id tagged). The TPU batch verifier behind VoteVerifier can verify
these too — block votes are (msg, sig, validator) triples like TxVotes —
but block-path volume is tiny (N votes per block, not per tx), so the
host path is the default.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field, replace

from ..codec import amino
from ..crypto import ed25519
from ..crypto.hash import sha256
from .validator import ValidatorSet

PREVOTE = 1
PRECOMMIT = 2

_TYPE_NAMES = {PREVOTE: "prevote", PRECOMMIT: "precommit"}


def canonical_block_vote_bytes(
    chain_id: str,
    height: int,
    round_: int,
    vote_type: int,
    block_id: bytes,
    timestamp_ns: int,
) -> bytes:
    body = bytearray()
    body += amino.field_key(1, amino.TYP3_8BYTE)
    body += amino.fixed64(height)
    body += amino.field_key(2, amino.TYP3_8BYTE)
    body += amino.fixed64(round_)
    body += amino.field_key(3, amino.TYP3_VARINT)
    body += amino.varint(vote_type)
    if block_id:
        body += amino.field_key(4, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(block_id)
    ts = amino.encode_time_body(timestamp_ns)
    if ts:
        body += amino.field_key(5, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(ts)
    if chain_id:
        body += amino.field_key(6, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(chain_id.encode())
    return amino.length_prefixed(bytes(body))


_BV_SEMANTIC_FIELDS = frozenset(
    (
        "height", "round", "type", "block_id", "timestamp_ns",
        "validator_address", "signature",
    )
)


@dataclass
class BlockVote:
    height: int
    round: int
    type: int  # PREVOTE | PRECOMMIT
    block_id: bytes = b""  # empty = nil vote
    timestamp_ns: int = field(default_factory=_time.time_ns)
    validator_address: bytes = b""
    signature: bytes | None = None
    # wire cache, lazily filled once the vote is signed (immutable from
    # then on); consensus gossip re-offers the same votes every tick per
    # peer, which re-serialized each one (r4 config-5 profile: 93k
    # encodes for ~10k votes). __setattr__ clears it on any semantic
    # write, so tampering can never serve stale bytes.
    _wire_cache: bytes | None = field(default=None, repr=False, compare=False)

    def __setattr__(self, name, value):
        if name in _BV_SEMANTIC_FIELDS:
            object.__setattr__(self, "_wire_cache", None)
        object.__setattr__(self, name, value)

    @property
    def is_nil(self) -> bool:
        return not self.block_id

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical_block_vote_bytes(
            chain_id, self.height, self.round, self.type, self.block_id,
            self.timestamp_ns,
        )

    def verify(self, chain_id: str, pub_key: bytes) -> bool:
        return bool(self.signature) and ed25519.verify(
            pub_key, self.sign_bytes(chain_id), self.signature
        )

    def copy(self) -> "BlockVote":
        return replace(self)

    def __repr__(self) -> str:
        bid = self.block_id.hex()[:12] if self.block_id else "nil"
        return (
            f"BlockVote({_TYPE_NAMES.get(self.type)} h={self.height} "
            f"r={self.round} {bid} val={self.validator_address.hex()[:8]})"
        )


def encode_block_vote(v: BlockVote) -> bytes:
    if v._wire_cache is not None:
        return v._wire_cache
    body = bytearray()
    body += amino.field_key(1, amino.TYP3_VARINT)
    body += amino.varint(v.height)
    body += amino.field_key(2, amino.TYP3_VARINT)
    body += amino.varint(v.round)
    body += amino.field_key(3, amino.TYP3_VARINT)
    body += amino.varint(v.type)
    if v.block_id:
        body += amino.field_key(4, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(v.block_id)
    ts = amino.encode_time_body(v.timestamp_ns)
    if ts:
        body += amino.field_key(5, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(ts)
    if v.validator_address:
        body += amino.field_key(6, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(v.validator_address)
    if v.signature:
        body += amino.field_key(7, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(v.signature)
    out = bytes(body)
    if v.signature is not None:  # immutable once signed
        object.__setattr__(v, "_wire_cache", out)
    return out


def decode_block_vote(data: bytes) -> BlockVote:
    r = amino.AminoReader(data)
    v = BlockVote(height=0, round=0, type=0, timestamp_ns=0)
    while not r.eof():
        fnum, typ3 = r.read_field_key()
        if typ3 == amino.TYP3_VARINT:
            val = r.read_varint()
            if fnum == 1:
                v.height = val
            elif fnum == 2:
                v.round = val
            elif fnum == 3:
                v.type = val
            else:
                pass
        elif typ3 == amino.TYP3_BYTELEN:
            raw = r.read_bytes()
            if fnum == 4:
                v.block_id = raw
            elif fnum == 5:
                v.timestamp_ns = amino.decode_time_body(raw)
            elif fnum == 6:
                v.validator_address = raw
            elif fnum == 7:
                v.signature = raw
        else:
            r.skip_field(typ3)
    return v


@dataclass
class BlockCommit:
    """The precommits that committed a block (upstream types.Commit)."""

    block_id: bytes = b""
    precommits: list[BlockVote] = field(default_factory=list)

    def height(self) -> int:
        return self.precommits[0].height if self.precommits else 0

    def round(self) -> int:
        return self.precommits[0].round if self.precommits else 0

    def hash(self) -> bytes:
        from .block import merkle_root  # cycle-free at call time

        return merkle_root([encode_block_vote(v) for v in self.precommits])


def encode_block_commit(c: BlockCommit) -> bytes:
    body = bytearray()
    if c.block_id:
        body += amino.field_key(1, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(c.block_id)
    for v in c.precommits:
        body += amino.field_key(2, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(encode_block_vote(v))
    return bytes(body)


def decode_block_commit(data: bytes) -> BlockCommit:
    r = amino.AminoReader(data)
    c = BlockCommit()
    while not r.eof():
        fnum, typ3 = r.read_field_key()
        if fnum == 1 and typ3 == amino.TYP3_BYTELEN:
            c.block_id = r.read_bytes()
        elif fnum == 2 and typ3 == amino.TYP3_BYTELEN:
            c.precommits.append(decode_block_vote(r.read_bytes()))
        else:
            r.skip_field(typ3)
    return c


class ErrConflictingBlockVote(Exception):
    pass


class BlockVoteSet:
    """Stake tally for one (height, round, type) (upstream types.VoteSet)."""

    def __init__(
        self, chain_id: str, height: int, round_: int, vote_type: int,
        val_set: ValidatorSet,
    ):
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.type = vote_type
        self.val_set = val_set
        self.votes: dict[bytes, BlockVote] = {}  # validator address -> vote
        self._by_block: dict[bytes, int] = {}  # block_id -> stake
        self._sum = 0
        self._maj23_block: bytes | None = None

    def add_vote(self, vote: BlockVote) -> tuple[bool, Exception | None]:
        if vote.height != self.height or vote.round != self.round or vote.type != self.type:
            return False, ValueError(
                f"vote for wrong (h,r,t): {vote} vs "
                f"({self.height},{self.round},{self.type})"
            )
        _, val = self.val_set.get_by_address(vote.validator_address)
        if val is None:
            return False, ValueError("unknown validator")
        existing = self.votes.get(vote.validator_address)
        if existing is not None:
            if existing.block_id == vote.block_id and existing.signature == vote.signature:
                return False, None  # duplicate
            return False, ErrConflictingBlockVote(f"{existing} vs {vote}")
        if not vote.verify(self.chain_id, val.pub_key):
            return False, ValueError("invalid signature")
        self.votes[vote.validator_address] = vote
        self._sum += val.voting_power
        stake = self._by_block.get(vote.block_id, 0) + val.voting_power
        self._by_block[vote.block_id] = stake
        if self._maj23_block is None and stake >= self.val_set.quorum_power():
            self._maj23_block = vote.block_id
        return True, None

    def two_thirds_majority(self) -> bytes | None:
        """block_id with 2/3+1 stake (b"" = nil decision), or None."""
        return self._maj23_block

    def has_two_thirds_majority(self) -> bool:
        return self._maj23_block is not None

    def has_two_thirds_any(self) -> bool:
        return self._sum >= self.val_set.quorum_power()

    def get_by_address(self, address: bytes) -> BlockVote | None:
        return self.votes.get(address)

    def vote_list(self) -> list[BlockVote]:
        return list(self.votes.values())

    def bitmask(self) -> int:
        """Validator-index bitmask of received votes — the gossip
        announce's compact 'what I have' summary (the reference exchanges
        the same information as per-peer BitArrays via NewRoundStep/
        HasVote, consensus/reactor.go:904-1340)."""
        mask = 0
        for addr in self.votes:
            idx, _ = self.val_set.get_by_address(addr)
            if idx >= 0:
                mask |= 1 << idx
        return mask

    def size(self) -> int:
        return len(self.votes)

    def make_commit(self, block_id: bytes) -> BlockCommit:
        assert self._maj23_block == block_id and block_id
        return BlockCommit(
            block_id,
            [v.copy() for v in self.votes.values() if v.block_id == block_id],
        )


class HeightVoteSet:
    """All rounds' prevotes + precommits for one height (reference
    consensus/types/height_vote_set.go:35-115)."""

    def __init__(self, chain_id: str, height: int, val_set: ValidatorSet):
        self.chain_id = chain_id
        self.height = height
        self.val_set = val_set
        self._sets: dict[tuple[int, int], BlockVoteSet] = {}
        self.round = 0
        # a peer may introduce at most 2 rounds beyond round+1 (its declared
        # catchup rounds) — without this bound a byzantine peer could make
        # us allocate unbounded vote sets by naming arbitrary rounds
        # (reference height_vote_set.go:35-115, the very bound the r2
        # review flagged as missing)
        self._peer_catchup_rounds: dict[str, list[int]] = {}

    def set_round(self, round_: int) -> None:
        """Pre-create sets up to round_ (+1 for catchup, like upstream)."""
        self.round = round_
        for r in range(round_ + 2):
            self._get(r, PREVOTE)
            self._get(r, PRECOMMIT)

    def _get(self, round_: int, vote_type: int) -> BlockVoteSet:
        key = (round_, vote_type)
        vs = self._sets.get(key)
        if vs is None:
            vs = BlockVoteSet(self.chain_id, self.height, round_, vote_type, self.val_set)
            self._sets[key] = vs
        return vs

    def prevotes(self, round_: int) -> BlockVoteSet:
        return self._get(round_, PREVOTE)

    def precommits(self, round_: int) -> BlockVoteSet:
        return self._get(round_, PRECOMMIT)

    def add_vote(
        self, vote: BlockVote, peer_id: str = ""
    ) -> tuple[bool, Exception | None]:
        if vote.type not in (PREVOTE, PRECOMMIT):
            return False, ValueError(f"bad vote type {vote.type}")
        if vote.round > self.round + 1 and peer_id:
            # beyond the rounds we track: admit only a peer's declared
            # catchup rounds, max 2 per peer (height_vote_set.go:84-102)
            rounds = self._peer_catchup_rounds.setdefault(peer_id, [])
            if vote.round not in rounds:
                if len(rounds) >= 2:
                    return False, ValueError(
                        f"unwanted round {vote.round} from peer {peer_id}"
                    )
                rounds.append(vote.round)
        return self._get(vote.round, vote.type).add_vote(vote)

    def pol_info(self) -> tuple[int, bytes | None]:
        """Highest round with a prevote polka: (round, block_id) or (-1, None)."""
        for r in sorted({k[0] for k in self._sets}, reverse=True):
            maj = self.prevotes(r).two_thirds_majority()
            if maj is not None:
                return r, maj
        return -1, None
