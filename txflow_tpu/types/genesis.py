"""GenesisDoc (upstream types.GenesisDoc, consumed at node/node.go:1161-1201).

JSON on disk; provides the initial validator set and chain id from which
``state.State`` is derived when the state DB is empty.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from .validator import Validator, ValidatorSet


@dataclass
class GenesisValidator:
    pub_key: bytes  # ed25519, 32 bytes
    power: int
    name: str = ""


@dataclass
class GenesisDoc:
    chain_id: str
    validators: list[GenesisValidator] = field(default_factory=list)
    genesis_time_ns: int = 0
    app_hash: bytes = b""
    app_state: dict = field(default_factory=dict)

    def validator_set(self) -> ValidatorSet:
        return ValidatorSet(
            [Validator.from_pub_key(gv.pub_key, gv.power) for gv in self.validators]
        )

    def validate(self) -> str | None:
        if not self.chain_id:
            return "genesis doc must include non-empty chain_id"
        if not self.validators:
            return "genesis doc must include at least one validator"
        for gv in self.validators:
            if gv.power <= 0:
                return f"validator {gv.name!r} has non-positive power"
            if len(gv.pub_key) != 32:
                return f"validator {gv.name!r} pub key must be 32 bytes"
        return None

    def to_json(self) -> str:
        return json.dumps(
            {
                "chain_id": self.chain_id,
                "genesis_time_ns": self.genesis_time_ns,
                "app_hash": self.app_hash.hex(),
                "validators": [
                    {"pub_key": gv.pub_key.hex(), "power": gv.power, "name": gv.name}
                    for gv in self.validators
                ],
                "app_state": self.app_state,
            },
            indent=2,
        )

    @classmethod
    def from_json(cls, raw: str) -> "GenesisDoc":
        d = json.loads(raw)
        return cls(
            chain_id=d["chain_id"],
            genesis_time_ns=d.get("genesis_time_ns", 0),
            app_hash=bytes.fromhex(d.get("app_hash", "")),
            validators=[
                GenesisValidator(
                    bytes.fromhex(v["pub_key"]), v["power"], v.get("name", "")
                )
                for v in d.get("validators", [])
            ],
            app_state=d.get("app_state", {}),
        )

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "GenesisDoc":
        with open(path) as f:
            return cls.from_json(f.read())
