"""TxVote: a per-transaction validator vote (reference types/tx_vote.go).

Sign bytes are amino ``MarshalBinaryLengthPrefixed(CanonicalTxVote)`` where
``CanonicalTxVote{Height fixed64, TxHash, TxKey, Timestamp, ChainID}`` — and,
exactly as in the reference, ``CanonicalizeTxVote`` does NOT copy the vote's
TxKey (types/tx_vote.go:185-192), so field 3 always serializes as 32 zero
bytes. Preserving that quirk is required for signature compatibility.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field

from ..codec import amino
from ..crypto import ed25519
from ..crypto.hash import ADDRESS_SIZE, address_hash, sha256

# Maximum amino-encoded vote size, including overhead (types/tx_vote.go:17).
MAX_VOTE_BYTES = 223
# tendermint types.MaxSignatureSize (v0.31).
MAX_SIGNATURE_SIZE = 64

_ZERO_TXKEY = bytes(32)

_SEMANTIC_FIELDS = frozenset(
    ("height", "tx_hash", "tx_key", "timestamp_ns", "validator_address", "signature")
)


def canonical_sign_bytes(
    chain_id: str, height: int, tx_hash: str, timestamp_ns: int
) -> bytes:
    """Length-prefixed amino encoding of CanonicalTxVote.

    Hand-tightened: this runs once per (vote, node) on the verify path
    (a top host cost in the r3 pipeline profile). Field-key bytes are the
    precomputed amino constants — (fnum << 3) | typ3, all < 0x80 — and the
    layout is pinned by the golden vectors in tests/test_tx_vote.py.
    """
    body = bytearray()
    if height != 0:
        body += b"\x09"  # field 1, TYP3_8BYTE
        body += (height & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little")
    if tx_hash:
        hb = tx_hash.encode()
        body += b"\x12"  # field 2, TYP3_BYTELEN
        body += amino.uvarint(len(hb))
        body += hb
    # TxKey: fixed-size array, never elided; canonicalization leaves it zero.
    body += b"\x1a\x20"  # field 3, TYP3_BYTELEN, len 32
    body += _ZERO_TXKEY
    ts_body = amino.encode_time_body(timestamp_ns)
    if ts_body:
        body += b"\x22"  # field 4, TYP3_BYTELEN
        body += amino.uvarint(len(ts_body))
        body += ts_body
    if chain_id:
        cb = chain_id.encode()
        body += b"\x2a"  # field 5, TYP3_BYTELEN
        body += amino.uvarint(len(cb))
        body += cb
    return amino.length_prefixed(bytes(body))


@dataclass
class TxVote:
    height: int
    tx_hash: str  # uppercase hex of sha256(tx)
    tx_key: bytes  # sha256(tx), 32 bytes
    timestamp_ns: int = field(default_factory=_time.time_ns)
    validator_address: bytes = b""
    signature: bytes | None = None
    # encode caches: a signed vote is immutable, and re-deriving sign bytes
    # and wire bytes per engine step measured as a top host cost at bench
    # scale (r3 step profile). Signers mutate fields BEFORE the first
    # encode, so lazy first-use caching is safe; copies carry the caches
    # (any later field write clears them via __setattr__).
    _sb_cache: tuple | None = field(
        default=None, repr=False, compare=False
    )
    _wire_cache: bytes | None = field(default=None, repr=False, compare=False)
    _vk_cache: bytes | None = field(default=None, repr=False, compare=False)
    # length-prefixed wire form (gossip frame segment): decoded votes are
    # shared process-wide by the reactor wire cache, so caching the seg on
    # the object makes every co-located pool's ingest reuse one build
    _seg_cache: bytes | None = field(default=None, repr=False, compare=False)

    def __setattr__(self, name, value):
        # any semantic-field write invalidates the encode caches, so even
        # post-signing tampering (byzantine tests) can never serve stale
        # bytes
        if name in _SEMANTIC_FIELDS:
            object.__setattr__(self, "_sb_cache", None)
            object.__setattr__(self, "_wire_cache", None)
            object.__setattr__(self, "_vk_cache", None)
            object.__setattr__(self, "_seg_cache", None)
        object.__setattr__(self, name, value)

    def sign_bytes(self, chain_id: str) -> bytes:
        c = self._sb_cache
        if c is not None and c[0] == chain_id:
            return c[1]
        sb = canonical_sign_bytes(
            chain_id, self.height, self.tx_hash, self.timestamp_ns
        )
        if self.signature is not None:  # immutable once signed
            self._sb_cache = (chain_id, sb)
        return sb

    def verify(self, chain_id: str, pub_key: bytes) -> str | None:
        """Returns None if valid, else an error string (types/tx_vote.go:110-119)."""
        if address_hash(pub_key) != self.validator_address:
            return "invalid validator address"
        if not self.signature or not ed25519.verify(
            pub_key, self.sign_bytes(chain_id), self.signature
        ):
            return "invalid signature"
        return None

    def validate_basic(self) -> str | None:
        if self.height < 0:
            return "negative height"
        if len(self.validator_address) != ADDRESS_SIZE:
            return (
                f"expected ValidatorAddress size to be {ADDRESS_SIZE} bytes, "
                f"got {len(self.validator_address)} bytes"
            )
        if not self.signature:
            return "signature is missing"
        if len(self.signature) > MAX_SIGNATURE_SIZE:
            return f"signature is too big (max: {MAX_SIGNATURE_SIZE})"
        return None

    def size(self) -> int:
        return len(encode_tx_vote(self))

    def copy(self) -> "TxVote":
        # caches travel with the copy: they only describe the semantic
        # fields, and any later field write clears them via __setattr__ —
        # dropping them here made every commit-certificate encode a full
        # re-serialize (r3 pipeline profile)
        v = TxVote.__new__(TxVote)
        oset = object.__setattr__
        oset(v, "height", self.height)
        oset(v, "tx_hash", self.tx_hash)
        oset(v, "tx_key", self.tx_key)
        oset(v, "timestamp_ns", self.timestamp_ns)
        oset(v, "validator_address", self.validator_address)
        oset(v, "signature", self.signature)
        oset(v, "_sb_cache", self._sb_cache)
        oset(v, "_wire_cache", self._wire_cache)
        oset(v, "_vk_cache", self._vk_cache)
        oset(v, "_seg_cache", self._seg_cache)
        return v

    def vote_key(self) -> bytes:
        """sha256(signature) — dedup cache key (txvotepool/txvotepool.go:467-469).

        Cached: the pool, the engine's purge bookkeeping, and gossip dedup
        all re-derive it for the same immutable vote (~180k calls per 12k
        commits in the r3 profile). __setattr__ clears it on any semantic
        field write, like the encode caches."""
        k = self._vk_cache
        if k is None:
            k = sha256(self.signature or b"")
            object.__setattr__(self, "_vk_cache", k)
        return k


def sign_bytes_many(votes: list["TxVote"], chain_id: str) -> list[bytes]:
    """Sign bytes for a whole drain batch, priming each vote's cache.

    Cache misses batch through the native codec (native/codec.c, ~0.1 us
    per vote vs ~4 us for the per-vote Python encode — a top-5 host cost
    at bench rates, r5 profile); without a C compiler the Python path
    computes them one by one, same bytes either way (parity pinned by
    tests/test_native_prep.py)."""
    out: list[bytes | None] = [None] * len(votes)
    miss: list[int] = []
    for i, v in enumerate(votes):
        c = v._sb_cache
        if c is not None and c[0] == chain_id:
            out[i] = c[1]
        else:
            miss.append(i)
    if miss:
        from .. import native

        batch = native.sign_bytes_batch(
            [votes[i].height for i in miss],
            [votes[i].tx_hash for i in miss],
            [votes[i].timestamp_ns for i in miss],
            chain_id,
        )
        if batch is not None:
            for j, i in enumerate(miss):
                if batch[j] is None:
                    # field bounds exceeded (hostile vote): per-item
                    # Python fallback — same bytes, no native fast path
                    out[i] = votes[i].sign_bytes(chain_id)
                    continue
                out[i] = batch[j]
                if votes[i].signature is not None:  # immutable once signed
                    object.__setattr__(
                        votes[i], "_sb_cache", (chain_id, batch[j])
                    )
        else:
            for i in miss:
                out[i] = votes[i].sign_bytes(chain_id)
    return out  # type: ignore[return-value]


def encode_tx_vote(vote: TxVote) -> bytes:
    """Amino MarshalBinaryBare of the full TxVote struct (WAL/wire form)."""
    if vote._wire_cache is not None:
        return vote._wire_cache
    body = bytearray()
    if vote.height != 0:
        body += amino.field_key(1, amino.TYP3_VARINT)
        body += amino.varint(vote.height)
    if vote.tx_hash:
        body += amino.field_key(2, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(vote.tx_hash.encode())
    body += amino.field_key(3, amino.TYP3_BYTELEN)
    body += amino.length_prefixed(vote.tx_key or _ZERO_TXKEY)
    ts_body = amino.encode_time_body(vote.timestamp_ns)
    if ts_body:
        body += amino.field_key(4, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(ts_body)
    if vote.validator_address:
        body += amino.field_key(5, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(vote.validator_address)
    if vote.signature:
        body += amino.field_key(6, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(vote.signature)
    out = bytes(body)
    if vote.signature is not None:  # immutable once signed
        vote._wire_cache = out
    return out


def _uv(data: bytes, pos: int, end: int) -> tuple[int, int, bool]:
    """Uvarint continuation path (Go binary.Uvarint overflow rules).

    Returns (value, new_pos, minimal): ``minimal`` is False for over-long
    encodings (a trailing 0x00 continuation group). They are ACCEPTED —
    same accept-set as Go — but the caller must refuse the wire cache,
    since our encoder would emit the shorter form."""
    n = 0
    shift = 0
    while True:
        if pos >= end:
            raise ValueError("truncated uvarint")
        b = data[pos]
        pos += 1
        if shift == 63 and b > 1:
            raise ValueError("uvarint overflows 64 bits")
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos, b != 0
        shift += 7
        if shift > 63:
            raise ValueError("uvarint overflows 64 bits")


def decode_tx_vote(data: bytes) -> TxVote:
    """Hand-rolled single-pass parser.

    This runs once per gossiped vote per node — the top pipeline cost in
    the r3 stub-verify profile — so it inlines the one-byte-varint fast
    path and constructs the TxVote via object.__setattr__ instead of the
    guarded dataclass path. The accept-set is identical to the AminoReader
    formulation (pinned by tests/test_tx_vote.py + test_amino.py).

    ``canonical`` tracks whether the input is exactly the byte string our
    own encoder emits (fields strictly ordered, no unknown fields, no
    explicitly-encoded defaults, minimal varints, normalized time body):
    only then are the input bytes cached as the vote's wire form, so
    re-gossip and TxStore certificate encoding never re-serialize.
    Non-canonical peer encodings fall back to a real re-serialize like
    the reference (Go amino re-marshals from the struct). The cache
    contract is exact — cached bytes are bit-identical to
    encode_tx_vote's output — and fuzz-pinned (tests/test_fuzz_codec.py).
    """
    pos = 0
    end = len(data)
    height = 0
    tx_hash = ""
    tx_key = _ZERO_TXKEY
    timestamp_ns = 0
    validator_address = b""
    signature = None
    canonical = True
    prev_fnum = 0
    try:
        while pos < end:
            b = data[pos]
            if b < 0x80:
                key = b
                pos += 1
            else:
                key, pos, mini = _uv(data, pos, end)
                if not mini:
                    canonical = False
            fnum = key >> 3
            typ3 = key & 7
            if fnum <= prev_fnum:
                canonical = False
            prev_fnum = fnum
            if typ3 == 2:  # BYTELEN
                b = data[pos]
                if b < 0x80:
                    ln = b
                    pos += 1
                else:
                    ln, pos, mini = _uv(data, pos, end)
                    if not mini:
                        canonical = False
                npos = pos + ln
                if npos > end:
                    raise ValueError("truncated byte field")
                seg = data[pos:npos]
                pos = npos
                if fnum == 2:
                    tx_hash = seg.decode()
                    if not tx_hash:
                        canonical = False
                elif fnum == 3:
                    if ln != 32:
                        # Go amino unmarshals into [sha256.Size]byte and
                        # errors on any other length; keep the wire
                        # accept-set identical.
                        raise ValueError(f"TxKey must be 32 bytes, got {ln}")
                    tx_key = seg
                elif fnum == 4:
                    timestamp_ns, ts_canon = _decode_ts_body(seg)
                    if not ts_canon:
                        canonical = False
                elif fnum == 5:
                    validator_address = seg
                    if not seg:
                        canonical = False
                elif fnum == 6:
                    signature = seg
                    if not seg:
                        canonical = False
                else:
                    canonical = False  # unknown BYTELEN field: skipped
            elif typ3 == 0:  # VARINT
                b = data[pos]
                if b < 0x80:
                    v = b
                    pos += 1
                else:
                    v, pos, mini = _uv(data, pos, end)
                    if not mini:
                        canonical = False
                if fnum == 1:
                    height = v - (1 << 64) if v >= 1 << 63 else v
                    if height == 0:
                        canonical = False
                else:
                    canonical = False  # unknown varint field: skipped
            elif typ3 == 1:  # 8BYTE
                if pos + 8 > end:
                    raise ValueError("truncated fixed64")
                pos += 8
                canonical = False  # no fixed64 field in TxVote
            else:
                raise ValueError(f"unknown typ3 {typ3}")
    except IndexError:
        raise ValueError("truncated uvarint") from None
    vote = TxVote.__new__(TxVote)
    oset = object.__setattr__
    oset(vote, "height", height)
    oset(vote, "tx_hash", tx_hash)
    oset(vote, "tx_key", tx_key)
    oset(vote, "timestamp_ns", timestamp_ns)
    oset(vote, "validator_address", validator_address)
    oset(vote, "signature", signature)
    oset(vote, "_sb_cache", None)
    oset(vote, "_vk_cache", None)
    oset(vote, "_seg_cache", None)
    if signature and canonical and tx_key is not _ZERO_TXKEY:
        oset(vote, "_wire_cache", bytes(data))
    else:
        oset(vote, "_wire_cache", None)
    return vote


def decode_tx_votes_many(segs: list[bytes]) -> list[TxVote]:
    """Batch decode of gossiped vote segments; raises ValueError on the
    FIRST undecodable segment (same contract as per-seg decode_tx_vote —
    the receive path stops the peer).

    The amino field walk runs in one C call (native/codec.c, a strict
    accept-set mirror of decode_tx_vote, fuzz-pinned by
    tests/test_fuzz_codec.py); Python slices the located fields and
    constructs the TxVote objects — including the strict UTF-8 check of
    tx_hash, which str() performs anyway. Exactness corners the C side
    flags (bit2: timestamps beyond int64) and builds missing native
    support fall back to the Python decoder, identical results.
    """
    from .. import native

    # crossover: the C call's fixed cost (concat + numpy buffers + ctypes
    # marshalling, ~45 us) beats per-seg Python only from ~16-32 segs
    # (measured r5 review: 49 us/vote at n=1, 5.1 at n=32 vs 5.2 pure
    # Python) — steady-state frames with few cache misses stay on the
    # inline decoder
    if len(segs) < 16:
        return [decode_tx_vote(s) for s in segs]
    fields = native.decode_votes_fields(segs)
    if fields is None:
        return [decode_tx_vote(s) for s in segs]
    (
        heights, timestamps, hash_off, hash_len, key_off,
        addr_off, addr_len, sig_off, sig_len, flags, concat,
    ) = fields
    out: list[TxVote] = []
    oset = object.__setattr__
    for i, seg in enumerate(segs):
        f = flags[i]
        if not f & 1:
            raise ValueError("undecodable tx vote segment")
        if f & 4:  # exactness corner: defer to the Python decoder
            out.append(decode_tx_vote(seg))
            continue
        ho = hash_off[i]
        tx_hash = (
            concat[ho : ho + hash_len[i]].decode() if ho >= 0 else ""
        )  # strict utf-8: raises like decode_tx_vote (stops the peer)
        ko = key_off[i]
        tx_key = concat[ko : ko + 32] if ko >= 0 else _ZERO_TXKEY
        ao = addr_off[i]
        addr = concat[ao : ao + addr_len[i]] if ao >= 0 else b""
        so = sig_off[i]
        sig = concat[so : so + sig_len[i]] if so >= 0 else None
        vote = TxVote.__new__(TxVote)
        oset(vote, "height", int(heights[i]))
        oset(vote, "tx_hash", tx_hash)
        oset(vote, "tx_key", tx_key)
        oset(vote, "timestamp_ns", int(timestamps[i]))
        oset(vote, "validator_address", addr)
        oset(vote, "signature", sig)
        oset(vote, "_sb_cache", None)
        oset(vote, "_vk_cache", None)
        oset(vote, "_seg_cache", None)
        if sig and (f & 2) and ko >= 0:
            oset(vote, "_wire_cache", seg)
        else:
            oset(vote, "_wire_cache", None)
        out.append(vote)
    return out


def _decode_ts_body(body: bytes) -> tuple[int, bool]:
    """(unix_ns, canonical): canonical iff body == encode_time_body(ns)."""
    if not body:
        # encode_time_body(0) elides the whole field — an explicit empty
        # field 4 is never something our encoder emits
        return 0, False
    pos = 0
    end = len(body)
    seconds = 0
    nanos = 0
    canonical = True
    prev = 0
    while pos < end:
        b = body[pos]
        if b < 0x80:
            key = b
            pos += 1
        else:
            key, pos, mini = _uv(body, pos, end)
            if not mini:
                canonical = False
        fnum = key >> 3
        typ3 = key & 7
        if fnum <= prev:
            canonical = False
        prev = fnum
        if typ3 == 0:
            b = body[pos] if pos < end else 0x80
            if b < 0x80:
                v = b
                pos += 1
            else:
                v, pos, mini = _uv(body, pos, end)
                if not mini:
                    canonical = False
            if fnum == 1:
                seconds = v - (1 << 64) if v >= 1 << 63 else v
                if seconds == 0:
                    canonical = False
            elif fnum == 2:
                nanos = v
                if not 0 < v < 1_000_000_000:
                    canonical = False
            else:
                canonical = False
        elif typ3 == 1:
            if pos + 8 > end:
                raise ValueError("truncated fixed64")
            pos += 8
            canonical = False
        elif typ3 == 2:
            b = body[pos] if pos < end else 0x80
            if b < 0x80:
                ln = b
                pos += 1
            else:
                ln, pos, mini = _uv(body, pos, end)
                if not mini:
                    canonical = False
            if pos + ln > end:
                raise ValueError("truncated byte field")
            pos += ln
            canonical = False
        else:
            raise ValueError(f"unknown typ3 {typ3}")
    return seconds * 1_000_000_000 + nanos, canonical
