"""TxVote: a per-transaction validator vote (reference types/tx_vote.go).

Sign bytes are amino ``MarshalBinaryLengthPrefixed(CanonicalTxVote)`` where
``CanonicalTxVote{Height fixed64, TxHash, TxKey, Timestamp, ChainID}`` — and,
exactly as in the reference, ``CanonicalizeTxVote`` does NOT copy the vote's
TxKey (types/tx_vote.go:185-192), so field 3 always serializes as 32 zero
bytes. Preserving that quirk is required for signature compatibility.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field, replace

from ..codec import amino
from ..crypto import ed25519
from ..crypto.hash import ADDRESS_SIZE, address_hash, sha256

# Maximum amino-encoded vote size, including overhead (types/tx_vote.go:17).
MAX_VOTE_BYTES = 223
# tendermint types.MaxSignatureSize (v0.31).
MAX_SIGNATURE_SIZE = 64

_ZERO_TXKEY = bytes(32)

_SEMANTIC_FIELDS = frozenset(
    ("height", "tx_hash", "tx_key", "timestamp_ns", "validator_address", "signature")
)


def canonical_sign_bytes(
    chain_id: str, height: int, tx_hash: str, timestamp_ns: int
) -> bytes:
    """Length-prefixed amino encoding of CanonicalTxVote."""
    body = bytearray()
    if height != 0:
        body += amino.field_key(1, amino.TYP3_8BYTE)
        body += amino.fixed64(height)
    if tx_hash:
        body += amino.field_key(2, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(tx_hash.encode())
    # TxKey: fixed-size array, never elided; canonicalization leaves it zero.
    body += amino.field_key(3, amino.TYP3_BYTELEN)
    body += amino.length_prefixed(_ZERO_TXKEY)
    ts_body = amino.encode_time_body(timestamp_ns)
    if ts_body:
        body += amino.field_key(4, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(ts_body)
    if chain_id:
        body += amino.field_key(5, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(chain_id.encode())
    return amino.length_prefixed(bytes(body))


@dataclass
class TxVote:
    height: int
    tx_hash: str  # uppercase hex of sha256(tx)
    tx_key: bytes  # sha256(tx), 32 bytes
    timestamp_ns: int = field(default_factory=_time.time_ns)
    validator_address: bytes = b""
    signature: bytes | None = None
    # encode caches: a signed vote is immutable, and re-deriving sign bytes
    # and wire bytes per engine step measured as a top host cost at bench
    # scale (r3 step profile). Signers mutate fields BEFORE the first
    # encode, so lazy first-use caching is safe; ``copy()`` drops them.
    _sb_cache: tuple | None = field(
        default=None, repr=False, compare=False
    )
    _wire_cache: bytes | None = field(default=None, repr=False, compare=False)

    def __setattr__(self, name, value):
        # any semantic-field write invalidates the encode caches, so even
        # post-signing tampering (byzantine tests) can never serve stale
        # bytes
        if name in _SEMANTIC_FIELDS:
            object.__setattr__(self, "_sb_cache", None)
            object.__setattr__(self, "_wire_cache", None)
        object.__setattr__(self, name, value)

    def sign_bytes(self, chain_id: str) -> bytes:
        c = self._sb_cache
        if c is not None and c[0] == chain_id:
            return c[1]
        sb = canonical_sign_bytes(
            chain_id, self.height, self.tx_hash, self.timestamp_ns
        )
        if self.signature is not None:  # immutable once signed
            self._sb_cache = (chain_id, sb)
        return sb

    def verify(self, chain_id: str, pub_key: bytes) -> str | None:
        """Returns None if valid, else an error string (types/tx_vote.go:110-119)."""
        if address_hash(pub_key) != self.validator_address:
            return "invalid validator address"
        if not self.signature or not ed25519.verify(
            pub_key, self.sign_bytes(chain_id), self.signature
        ):
            return "invalid signature"
        return None

    def validate_basic(self) -> str | None:
        if self.height < 0:
            return "negative height"
        if len(self.validator_address) != ADDRESS_SIZE:
            return (
                f"expected ValidatorAddress size to be {ADDRESS_SIZE} bytes, "
                f"got {len(self.validator_address)} bytes"
            )
        if not self.signature:
            return "signature is missing"
        if len(self.signature) > MAX_SIGNATURE_SIZE:
            return f"signature is too big (max: {MAX_SIGNATURE_SIZE})"
        return None

    def size(self) -> int:
        return len(encode_tx_vote(self))

    def copy(self) -> "TxVote":
        return replace(self, _sb_cache=None, _wire_cache=None)

    def vote_key(self) -> bytes:
        """sha256(signature) — dedup cache key (txvotepool/txvotepool.go:467-469)."""
        return sha256(self.signature or b"")


def encode_tx_vote(vote: TxVote) -> bytes:
    """Amino MarshalBinaryBare of the full TxVote struct (WAL/wire form)."""
    if vote._wire_cache is not None:
        return vote._wire_cache
    body = bytearray()
    if vote.height != 0:
        body += amino.field_key(1, amino.TYP3_VARINT)
        body += amino.varint(vote.height)
    if vote.tx_hash:
        body += amino.field_key(2, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(vote.tx_hash.encode())
    body += amino.field_key(3, amino.TYP3_BYTELEN)
    body += amino.length_prefixed(vote.tx_key or _ZERO_TXKEY)
    ts_body = amino.encode_time_body(vote.timestamp_ns)
    if ts_body:
        body += amino.field_key(4, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(ts_body)
    if vote.validator_address:
        body += amino.field_key(5, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(vote.validator_address)
    if vote.signature:
        body += amino.field_key(6, amino.TYP3_BYTELEN)
        body += amino.length_prefixed(vote.signature)
    out = bytes(body)
    if vote.signature is not None:  # immutable once signed
        vote._wire_cache = out
    return out


def decode_tx_vote(data: bytes) -> TxVote:
    r = amino.AminoReader(data)
    height = 0
    tx_hash = ""
    tx_key = _ZERO_TXKEY
    timestamp_ns = 0
    validator_address = b""
    signature = None
    while not r.eof():
        fnum, typ3 = r.read_field_key()
        if fnum == 1 and typ3 == amino.TYP3_VARINT:
            height = r.read_varint()
        elif fnum == 2 and typ3 == amino.TYP3_BYTELEN:
            tx_hash = r.read_bytes().decode()
        elif fnum == 3 and typ3 == amino.TYP3_BYTELEN:
            tx_key = r.read_bytes()
            if len(tx_key) != 32:
                # Go amino unmarshals into [sha256.Size]byte and errors on
                # any other length; keep the wire accept-set identical.
                raise ValueError(
                    f"TxKey must be 32 bytes, got {len(tx_key)}"
                )
        elif fnum == 4 and typ3 == amino.TYP3_BYTELEN:
            timestamp_ns = amino.decode_time_body(r.read_bytes())
        elif fnum == 5 and typ3 == amino.TYP3_BYTELEN:
            validator_address = r.read_bytes()
        elif fnum == 6 and typ3 == amino.TYP3_BYTELEN:
            signature = r.read_bytes()
        else:
            r.skip_field(typ3)
    return TxVote(
        height=height,
        tx_hash=tx_hash,
        tx_key=tx_key,
        timestamp_ns=timestamp_ns,
        validator_address=validator_address,
        signature=signature,
    )
