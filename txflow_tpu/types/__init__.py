from .tx_vote import (
    MAX_SIGNATURE_SIZE,
    MAX_VOTE_BYTES,
    TxVote,
    canonical_sign_bytes,
    decode_tx_vote,
    encode_tx_vote,
)
from .validator import Validator, ValidatorSet
from .vote_set import (
    Commit,
    CommitSig,
    ErrVoteInvalidSignature,
    ErrVoteInvalidValidatorAddress,
    ErrVoteInvalidValidatorIndex,
    ErrVoteNil,
    ErrVoteNonDeterministicSignature,
    TxVoteSet,
)
from .priv_validator import MockPV, PrivValidator, ErroringMockPV

__all__ = [
    "MAX_SIGNATURE_SIZE",
    "MAX_VOTE_BYTES",
    "TxVote",
    "canonical_sign_bytes",
    "decode_tx_vote",
    "encode_tx_vote",
    "Validator",
    "ValidatorSet",
    "Commit",
    "CommitSig",
    "ErrVoteInvalidSignature",
    "ErrVoteInvalidValidatorAddress",
    "ErrVoteInvalidValidatorIndex",
    "ErrVoteNil",
    "ErrVoteNonDeterministicSignature",
    "TxVoteSet",
    "MockPV",
    "PrivValidator",
    "ErroringMockPV",
]
