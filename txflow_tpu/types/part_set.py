"""Block part-sets: chunked proposal propagation.

The reference proposes blocks as bounded parts under a PartSetHeader
(total + merkle root) so a block larger than one p2p message can travel,
with parts gossiped per peer (consensus/state.go:945-962 MakePartSet;
consensus/reactor.go:465-530 gossipDataRoutine). This framework's analog
keeps the same wire economics with a flat verification scheme: the header
carries the per-part sha256 list alongside the merkle root (a 8 MB block
at 256 KiB parts is 32 hashes = 1 KiB of header), so receivers verify
each arriving part directly against its hash instead of carrying a merkle
proof per part. The root still binds the hash list, and the proposal
signature binds the assembled block via proposal.block_hash — a forged
header can only waste the assembly buffer, never commit a wrong block
(ConsensusState._set_proposal rejects on block.hash() mismatch).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto.hash import sha256
from .block import merkle_root

# Bounded well under p2p MAX_FRAME_BYTES (8 MiB) with json+hex overhead.
PART_SIZE = 256 * 1024


@dataclass
class PartSetHeader:
    total: int
    root: bytes  # merkle root over the part hashes
    hashes: list[bytes] = field(default_factory=list)  # sha256 per part

    def to_wire(self) -> dict:
        return {
            "total": self.total,
            "root": self.root.hex(),
            "hashes": [h.hex() for h in self.hashes],
        }

    @classmethod
    def from_wire(cls, d: dict) -> "PartSetHeader":
        hashes = [bytes.fromhex(h) for h in d.get("hashes", [])]
        return cls(total=int(d["total"]), root=bytes.fromhex(d["root"]), hashes=hashes)

    def validate_basic(self) -> str | None:
        if self.total <= 0 or self.total != len(self.hashes):
            return "part count / hash list mismatch"
        if merkle_root(self.hashes) != self.root:
            return "part hash list does not match root"
        return None


def make_part_set(data: bytes, part_size: int = PART_SIZE) -> tuple[PartSetHeader, list[bytes]]:
    """Split an encoded block into parts + header (MakePartSet analog)."""
    parts = [data[i : i + part_size] for i in range(0, len(data), part_size)] or [b""]
    hashes = [sha256(p) for p in parts]
    return PartSetHeader(total=len(parts), root=merkle_root(hashes), hashes=hashes), parts


class PartSetBuffer:
    """Assembly buffer for one proposal's parts (receiver side)."""

    def __init__(self, header: PartSetHeader):
        self.header = header
        self.parts: dict[int, bytes] = {}

    def add_part(self, index: int, part: bytes) -> bool:
        """True if the part was new and verified; False = dup/bad."""
        if not (0 <= index < self.header.total) or index in self.parts:
            return False
        if sha256(part) != self.header.hashes[index]:
            return False
        self.parts[index] = part
        return True

    def is_complete(self) -> bool:
        return len(self.parts) == self.header.total

    def mask(self) -> int:
        m = 0
        for i in self.parts:
            m |= 1 << i
        return m

    def assemble(self) -> bytes:
        assert self.is_complete()
        return b"".join(self.parts[i] for i in range(self.header.total))
