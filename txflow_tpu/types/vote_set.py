"""TxVoteSet: the stake-weighted quorum accumulator (reference types/vote_set.go).

This is the scalar golden model: the batched device verifier must produce
bit-identical commit decisions. Exact reference semantics preserved:

- one vote per validator address; an identical re-submission (same signature)
  is a silent duplicate (added=False, no error) — types/vote_set.go:109-112;
- a second vote from the same validator with a DIFFERENT signature is
  rejected with ErrVoteNonDeterministicSignature and never tallied
  (first-signature-wins) — types/vote_set.go:113;
- quorum: maj23 latches once sum >= total*2/3 + 1 — types/vote_set.go:158-163.

Thread-safety: a mutex guards mutation like the reference's ``mtx``; the
aggregation engine calls ``add_verified_vote`` after device batch
verification, which reproduces the decisions of ``add_vote`` exactly.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from .tx_vote import TxVote
from .validator import ValidatorSet


class ErrVoteNil(Exception):
    pass


class ErrVoteInvalidValidatorAddress(Exception):
    pass


class ErrVoteInvalidValidatorIndex(Exception):
    pass


class ErrVoteNonDeterministicSignature(Exception):
    pass


class ErrVoteInvalidSignature(Exception):
    pass


@dataclass
class CommitSig:
    """A vote included in a Commit — field-identical to TxVote (types/tx_vote.go:154-159)."""

    height: int
    tx_hash: str
    tx_key: bytes
    timestamp_ns: int
    validator_address: bytes
    signature: bytes | None

    @classmethod
    def from_vote(cls, vote: TxVote) -> "CommitSig":
        return cls(
            vote.height,
            vote.tx_hash,
            vote.tx_key,
            vote.timestamp_ns,
            vote.validator_address,
            vote.signature,
        )

    def to_vote(self) -> TxVote:
        return TxVote(
            self.height,
            self.tx_hash,
            self.tx_key,
            self.timestamp_ns,
            self.validator_address,
            self.signature,
        )


@dataclass
class Commit:
    """Evidence that a tx was committed by >2/3 stake (types/vote_set.go:263-287)."""

    tx_hash: str
    commits: list[CommitSig]

    def height(self) -> int:
        return self.commits[0].height if self.commits else 0


class TxVoteSet:
    def __init__(
        self,
        chain_id: str,
        height: int,
        tx_hash: str,
        tx_key: bytes,
        val_set: ValidatorSet,
    ):
        self.chain_id = chain_id
        self._height = height
        self.val_set = val_set
        self.tx_hash = tx_hash
        self.tx_key = tx_key
        self._mtx = threading.Lock()
        self.votes: dict[bytes, TxVote] = {}  # validator address -> vote
        self.sum = 0
        self.maj23 = False

    # ---- accessors (reference :53-78, :178-227) ----

    def height(self) -> int:
        return self._height

    def size(self) -> int:
        return self.val_set.size()

    def get_votes(self) -> list[TxVote]:
        # Copies, like the reference's by-value GetVotes — callers must not
        # be able to mutate the stored votes (first-sig-wins state).
        with self._mtx:
            return [v.copy() for v in self.votes.values()]

    def votes_snapshot(self) -> list[TxVote]:
        """Uncopied vote list for a caller that OWNS the set — the engine
        calls this only after popping the set from its in-flight map, at
        which point nothing can mutate it (first-sig-wins state is
        engine-thread-only). The commit path's per-decision deep copy
        measured ~4.4 µs (r5 profile) for zero protection."""
        with self._mtx:
            return list(self.votes.values())

    def get_by_address(self, address: bytes) -> TxVote | None:
        with self._mtx:
            return self.votes.get(address)

    def has_two_thirds_majority(self) -> bool:
        with self._mtx:
            return self.maj23

    def is_commit(self) -> bool:
        return self.has_two_thirds_majority()

    def has_two_thirds_any(self) -> bool:
        with self._mtx:
            return self.sum > self.val_set.total_voting_power() * 2 // 3

    def stake(self) -> int:
        with self._mtx:
            return self.sum

    def total_stake(self) -> int:
        # Mirrors the reference oddity: returns total*2/3, not total
        # (types/vote_set.go:214-221).
        with self._mtx:
            return self.val_set.total_voting_power() * 2 // 3

    def has_all(self) -> bool:
        with self._mtx:
            return self.sum == self.val_set.total_voting_power()

    # ---- mutation (reference :81-166) ----

    def add_vote(self, vote: TxVote | None) -> tuple[bool, Exception | None]:
        with self._mtx:
            return self._add_vote(vote)

    def _add_vote(
        self, vote: TxVote | None, check_signature: bool = True
    ) -> tuple[bool, Exception | None]:
        """One shared decision path for both the scalar and device routes:
        the batch-verified route is identical minus the signature check, so
        parity between the two can never drift."""
        if vote is None:
            return False, ErrVoteNil()
        if len(vote.validator_address) == 0:
            return False, ErrVoteInvalidValidatorAddress("empty address")
        _, val = self.val_set.get_by_address(vote.validator_address)
        if val is None:
            return False, ErrVoteInvalidValidatorIndex(
                f"cannot find validator {vote.validator_address.hex().upper()} "
                f"in valSet of size {self.val_set.size()}"
            )
        existing = self.votes.get(vote.validator_address)
        if existing is not None:
            if existing.signature == vote.signature:
                return False, None  # duplicate
            return False, ErrVoteNonDeterministicSignature(
                f"existing vote: {existing}; new vote: {vote}"
            )
        if check_signature:
            err = vote.verify(self.chain_id, val.pub_key)
            if err is not None:
                return False, ErrVoteInvalidSignature(
                    f"failed to verify vote with ChainID {self.chain_id}: {err}"
                )
        self._add_verified(vote, val.voting_power)
        return True, None

    def add_verified_vote(self, vote: TxVote) -> tuple[bool, Exception | None]:
        """Add a vote whose signature was already verified (device batch path)."""
        with self._mtx:
            return self._add_vote(vote, check_signature=False)

    def _add_verified(self, vote: TxVote, voting_power: int) -> None:
        self.votes[vote.validator_address] = vote
        self.sum += voting_power
        if self.val_set.quorum_power() <= self.sum:
            self.maj23 = True

    # ---- validator-set churn (epoch rotation / slashing) ----

    def revalidate(self, new_val_set: ValidatorSet) -> tuple[int, bool]:
        """Re-evaluate this in-flight set against a NEW validator set
        (epoch boundary crossed while the tx was below quorum). Returns
        ``(dropped, newly_quorate)``.

        Semantics, in order of precedence:

        - an already-latched certificate is IMMUTABLE: if maj23 latched
          under the old set, the set is left byte-identical (the commit
          it certifies happened under the epoch the votes were cast in)
          and (0, False) is returned;
        - votes from validators absent in the new set are discarded —
          their stake no longer exists, so it must not count toward any
          future quorum;
        - surviving votes are re-weighted to their validator's NEW power
          and ``sum`` recomputed; maj23 latches (returning True) iff the
          new set's quorum_power is now met — rotation can push a
          pending tx OVER the line when total power shrank."""
        with self._mtx:
            if self.maj23:
                return 0, False
            dropped = 0
            new_sum = 0
            for addr in list(self.votes):
                _, val = new_val_set.get_by_address(addr)
                if val is None:
                    del self.votes[addr]
                    dropped += 1
                else:
                    new_sum += val.voting_power
            self.val_set = new_val_set
            self.sum = new_sum
            if new_val_set.quorum_power() <= new_sum:
                self.maj23 = True
                return dropped, True
            return dropped, False

    # ---- commit construction (reference :242-259) ----

    def make_commit(self) -> Commit:
        with self._mtx:
            if not self.maj23:
                raise RuntimeError("cannot MakeCommit() unless tx has +2/3")
            return Commit(
                self.tx_hash,
                [CommitSig.from_vote(v) for v in self.votes.values()],
            )
